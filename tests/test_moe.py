"""MoE / expert parallelism tests (reference pattern:
test/collective/collective_global_gather.py + moe unit tests)."""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
import paddle_tpu.nn as nn
from paddle_tpu.distributed.moe import ExpertMLP, MoELayer, gshard_routing

import jax
import jax.numpy as jnp

RNG = np.random.RandomState(0)


class TestRouting:
    def test_dispatch_combine_shapes_and_capacity(self):
        t, e, c = 16, 4, 4
        logits = jnp.asarray(RNG.randn(t, e), jnp.float32)
        dispatch, combine, aux = gshard_routing(logits, e, c, topk=2)
        assert dispatch.shape == (t, e, c)
        # no slot is used twice
        slot_usage = np.asarray(dispatch).sum(0)  # [e, c]
        assert slot_usage.max() <= 1.0 + 1e-6
        # each token dispatched at most topk times
        per_token = np.asarray(dispatch).sum((1, 2))
        assert per_token.max() <= 2 + 1e-6
        # combine weights nonnegative, normalized per token (when routed)
        cw = np.asarray(combine).sum((1, 2))
        assert ((cw > 0.99) | (cw < 1e-6)).all()
        assert float(aux) > 0

    def test_top1_routing(self):
        t, e, c = 8, 2, 8
        logits = jnp.asarray(RNG.randn(t, e), jnp.float32)
        dispatch, combine, aux = gshard_routing(logits, e, c, topk=1)
        # ample capacity: every token routed exactly once
        np.testing.assert_allclose(np.asarray(dispatch).sum((1, 2)), np.ones(t))


class TestMoELayer:
    def test_forward_shape_and_aux(self):
        paddle.seed(0)
        layer = MoELayer(d_model=16, d_hidden=32, num_experts=4, topk=2)
        x = paddle.to_tensor(RNG.randn(2, 8, 16).astype(np.float32))
        out = layer(x)
        assert out.shape == [2, 8, 16]
        assert layer.aux_loss is not None and float(layer.aux_loss) > 0

    def test_single_expert_equals_dense_mlp(self):
        """1 expert + ample capacity == plain MLP (routing is identity)."""
        paddle.seed(1)
        layer = MoELayer(d_model=8, d_hidden=16, num_experts=1, topk=1, capacity_factor=4.0)
        x = paddle.to_tensor(RNG.randn(1, 4, 8).astype(np.float32))
        out = layer(x).numpy()
        w1 = layer.experts.w1.numpy()[0]
        b1 = layer.experts.b1.numpy()[0]
        w2 = layer.experts.w2.numpy()[0]
        b2 = layer.experts.b2.numpy()[0]
        flat = x.numpy().reshape(4, 8)
        import scipy.stats

        def gelu(v):
            return v * scipy.stats.norm.cdf(v)

        ref = gelu(flat @ w1 + b1) @ w2 + b2
        np.testing.assert_allclose(out.reshape(4, 8), ref, atol=1e-4, rtol=1e-4)

    def test_gradients_flow_to_gate_and_experts(self):
        paddle.seed(2)
        layer = MoELayer(d_model=8, d_hidden=16, num_experts=4, topk=2)
        x = paddle.to_tensor(RNG.randn(1, 8, 8).astype(np.float32), stop_gradient=False)
        out = layer(x)
        loss = out.sum() + 0.01 * layer.aux_loss
        loss.backward()
        assert layer.gate_weight.grad is not None
        assert layer.experts.w1.grad is not None
        assert x.grad is not None
        assert float(paddle.abs(layer.gate_weight.grad).sum()) > 0

    def test_expert_parallel_sharding(self):
        mesh = dist.ProcessMesh(np.arange(8).reshape(2, 4), ["dp", "ep"])
        layer = MoELayer(d_model=16, d_hidden=32, num_experts=8, topk=2, ep_mesh=mesh)
        # expert weights sharded over ep axis
        shard_shapes = {tuple(s.data.shape) for s in layer.experts.w1._data.addressable_shards}
        assert shard_shapes == {(2, 16, 32)}
        x = paddle.to_tensor(RNG.randn(2, 8, 16).astype(np.float32))
        out = layer(x)
        assert out.shape == [2, 8, 16]

    def test_moe_in_engine_train_step(self):
        from paddle_tpu.distributed.engine import ShardedTrainStep

        paddle.seed(3)

        class MoEModel(nn.Layer):
            def __init__(self):
                super().__init__()
                self.inp = nn.Linear(8, 16)
                self.moe = MoELayer(d_model=16, d_hidden=32, num_experts=4, topk=2)
                self.out = nn.Linear(16, 4)

            def forward(self, x):
                return self.out(self.moe(self.inp(x)))

        model = MoEModel()
        mesh = dist.ProcessMesh(np.arange(8), ["dp"])
        opt = paddle.optimizer.AdamW(1e-3, parameters=model.parameters())
        lossfn = nn.CrossEntropyLoss()
        step = ShardedTrainStep(model, lambda o, l: lossfn(o, l), opt, mesh)
        x = paddle.to_tensor(RNG.randn(16, 4, 8).astype(np.float32))
        y = paddle.to_tensor(RNG.randint(0, 4, (16, 4)).astype(np.int64))
        l0 = float(step.step(x, y))
        for _ in range(4):
            l1 = float(step.step(x, y))
        assert np.isfinite(l1) and l1 < l0


class TestMoELlama:
    """Round-4: MoE as a first-class LlamaConfig option (Mixtral-style;
    reference surface: incubate.distributed.models.moe wired into a
    decoder LM)."""

    def test_moe_llama_trains_with_aux_loss(self):
        from paddle_tpu.distributed.engine import ShardedTrainStep
        from paddle_tpu.models import (LlamaConfig, LlamaForCausalLM,
                                       moe_aux_loss, moe_pretrain_loss)

        paddle.seed(0)
        cfg = LlamaConfig.tiny(moe_num_experts=4, moe_topk=2)
        m = LlamaForCausalLM(cfg)
        # every decoder MLP is an MoE with per-expert weights
        from paddle_tpu.distributed.moe import MoELayer

        assert all(isinstance(layer.mlp, MoELayer) for layer in m.llama.layers)
        opt = paddle.optimizer.AdamW(1e-3, parameters=m.parameters())
        step = ShardedTrainStep(m, moe_pretrain_loss(m), opt,
                                dist.ProcessMesh(np.arange(1), ["dp"]),
                                dp_axis=None)
        rng = np.random.RandomState(0)
        ids = paddle.to_tensor(rng.randint(0, cfg.vocab_size, (2, 16)).astype(np.int32))
        losses = [float(step.step(ids, ids)) for _ in range(5)]
        assert losses[-1] < losses[0]
        # aux loss exists after an eager forward too
        with paddle.no_grad():
            m2 = LlamaForCausalLM(cfg)
            m2(ids)
            aux = moe_aux_loss(m2)
        assert aux is not None and np.isfinite(float(aux))

    def test_moe_llama_generates(self):
        from paddle_tpu.models import LlamaConfig, LlamaForCausalLM

        paddle.seed(1)
        cfg = LlamaConfig.tiny(moe_num_experts=4)
        m = LlamaForCausalLM(cfg)
        rng = np.random.RandomState(0)
        ids = paddle.to_tensor(rng.randint(0, cfg.vocab_size, (2, 4)).astype(np.int32))
        out = m.generate(ids, max_new_tokens=5).numpy()
        assert out.shape == (2, 9)

    def test_dense_config_unchanged(self):
        from paddle_tpu.models import LlamaConfig, LlamaForCausalLM, moe_aux_loss
        from paddle_tpu.models.llama import LlamaMLP

        m = LlamaForCausalLM(LlamaConfig.tiny())
        assert all(isinstance(layer.mlp, LlamaMLP) for layer in m.llama.layers)
        assert moe_aux_loss(m) is None


class TestDispatchModes:
    def test_gather_matches_einsum(self):
        # the O(E*C*m) gather/scatter path must reproduce the one-hot
        # einsum contraction (same routing, same drops, same weights)
        from paddle_tpu.distributed.moe import MoELayer

        paddle.seed(7)
        a = MoELayer(d_model=16, d_hidden=32, num_experts=4, topk=2,
                     dispatch_mode="einsum")
        b = MoELayer(d_model=16, d_hidden=32, num_experts=4, topk=2,
                     dispatch_mode="gather")
        b.set_state_dict(a.state_dict())
        x = paddle.to_tensor(RNG.randn(2, 12, 16).astype(np.float32))
        ya = a(x)
        yb = b(x)
        np.testing.assert_allclose(yb.numpy(), ya.numpy(), rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(float(b.aux_loss._data), float(a.aux_loss._data),
                                   rtol=1e-6)

    def test_modes_agree_in_bf16(self):
        # both modes must keep bf16 activations bf16 (einsum used to
        # promote the expert stack to f32) and agree within bf16 noise
        import jax.numpy as jnp
        from paddle_tpu.distributed.moe import MoELayer

        paddle.seed(9)
        a = MoELayer(d_model=16, d_hidden=32, num_experts=4, topk=2,
                     dispatch_mode="einsum")
        b = MoELayer(d_model=16, d_hidden=32, num_experts=4, topk=2,
                     dispatch_mode="gather")
        b.set_state_dict(a.state_dict())
        for layer in (a, b):
            layer.to(dtype="bfloat16")
        x = paddle.to_tensor(jnp.asarray(RNG.randn(2, 12, 16), jnp.bfloat16))
        ya, yb = a(x), b(x)
        assert ya._data.dtype == jnp.bfloat16
        assert yb._data.dtype == jnp.bfloat16
        np.testing.assert_allclose(yb.astype("float32").numpy(),
                                   ya.astype("float32").numpy(),
                                   rtol=0.05, atol=0.05)

    def test_gather_gradients_flow(self):
        from paddle_tpu.distributed.moe import MoELayer

        paddle.seed(8)
        layer = MoELayer(d_model=16, d_hidden=32, num_experts=4, topk=2,
                         dispatch_mode="gather")
        x = paddle.to_tensor(RNG.randn(2, 8, 16).astype(np.float32))
        out = layer(x)
        (out.sum() + 0.01 * layer.aux_loss.sum()).backward()
        assert layer.gate_weight.grad is not None
        assert float(np.abs(layer.gate_weight.grad.numpy()).sum()) > 0
        assert layer.experts.w1.grad is not None

    def test_invalid_mode_raises(self):
        from paddle_tpu.distributed.moe import MoELayer
        import pytest as _pytest

        with _pytest.raises(ValueError, match="dispatch_mode"):
            MoELayer(d_model=8, d_hidden=16, num_experts=2,
                     dispatch_mode="alltoall")


class TestEpShardedDispatch:
    def test_ep_sharded_compiled_program_is_onehot_free(self):
        """Round-5: the ep-sharded path must run the gather dispatch —
        the compiled fwd+bwd HLO contains NO [t, E, C] one-hot tensor
        (the einsum formulation's signature) and keeps the whole step
        ONE program. Reference analogue: fused MoE dispatch kernels
        (paddle/phi/kernels/fusion/, incubate fused_moe)."""
        import jax

        from paddle_tpu.core.tensor import Tensor
        from paddle_tpu.utils.functional import functional_call

        mesh = dist.ProcessMesh(np.arange(8).reshape(2, 4), ["dp", "ep"])
        layer = MoELayer(d_model=16, d_hidden=32, num_experts=8, topk=2,
                         ep_mesh=mesh)
        assert layer.dispatch_mode == "gather"
        x = RNG.randn(4, 8, 16).astype(np.float32)  # t = 32 tokens
        t = 32
        capacity = max(int(layer.capacity_factor * layer.topk * t
                           / layer.num_experts), 1)

        params = {k: v._data for k, v in layer.state_dict().items()}

        def f(params, xx):
            with paddle.no_grad():
                out = functional_call(layer, {k: Tensor(v) for k, v in
                                              params.items()}, Tensor(xx))
            return (out._data ** 2).sum()

        txt = jax.jit(jax.grad(f)).lower(params, x).compile().as_text()
        onehot = f"[{t},{layer.num_experts},{capacity}]"
        assert onehot not in txt, (
            f"one-hot dispatch tensor {onehot} found in the ep-sharded "
            "compiled program — gather path not taken")
        assert "gather(" in txt
        # and it is numerically the same layer as the einsum oracle
        layer_e = MoELayer(d_model=16, d_hidden=32, num_experts=8, topk=2,
                           dispatch_mode="einsum")
        layer_e.set_state_dict(layer.state_dict())
        got = layer(paddle.to_tensor(x)).numpy()
        ref = layer_e(paddle.to_tensor(x)).numpy()
        np.testing.assert_allclose(got, ref, rtol=2e-5, atol=2e-6)
