"""MoE / expert parallelism tests (reference pattern:
test/collective/collective_global_gather.py + moe unit tests)."""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
import paddle_tpu.nn as nn
from paddle_tpu.distributed.moe import ExpertMLP, MoELayer, gshard_routing

import jax
import jax.numpy as jnp

RNG = np.random.RandomState(0)


class TestRouting:
    def test_dispatch_combine_shapes_and_capacity(self):
        t, e, c = 16, 4, 4
        logits = jnp.asarray(RNG.randn(t, e), jnp.float32)
        dispatch, combine, aux = gshard_routing(logits, e, c, topk=2)
        assert dispatch.shape == (t, e, c)
        # no slot is used twice
        slot_usage = np.asarray(dispatch).sum(0)  # [e, c]
        assert slot_usage.max() <= 1.0 + 1e-6
        # each token dispatched at most topk times
        per_token = np.asarray(dispatch).sum((1, 2))
        assert per_token.max() <= 2 + 1e-6
        # combine weights nonnegative, normalized per token (when routed)
        cw = np.asarray(combine).sum((1, 2))
        assert ((cw > 0.99) | (cw < 1e-6)).all()
        assert float(aux) > 0

    def test_top1_routing(self):
        t, e, c = 8, 2, 8
        logits = jnp.asarray(RNG.randn(t, e), jnp.float32)
        dispatch, combine, aux = gshard_routing(logits, e, c, topk=1)
        # ample capacity: every token routed exactly once
        np.testing.assert_allclose(np.asarray(dispatch).sum((1, 2)), np.ones(t))


class TestMoELayer:
    def test_forward_shape_and_aux(self):
        paddle.seed(0)
        layer = MoELayer(d_model=16, d_hidden=32, num_experts=4, topk=2)
        x = paddle.to_tensor(RNG.randn(2, 8, 16).astype(np.float32))
        out = layer(x)
        assert out.shape == [2, 8, 16]
        assert layer.aux_loss is not None and float(layer.aux_loss) > 0

    def test_single_expert_equals_dense_mlp(self):
        """1 expert + ample capacity == plain MLP (routing is identity)."""
        paddle.seed(1)
        layer = MoELayer(d_model=8, d_hidden=16, num_experts=1, topk=1, capacity_factor=4.0)
        x = paddle.to_tensor(RNG.randn(1, 4, 8).astype(np.float32))
        out = layer(x).numpy()
        w1 = layer.experts.w1.numpy()[0]
        b1 = layer.experts.b1.numpy()[0]
        w2 = layer.experts.w2.numpy()[0]
        b2 = layer.experts.b2.numpy()[0]
        flat = x.numpy().reshape(4, 8)
        import scipy.stats

        def gelu(v):
            return v * scipy.stats.norm.cdf(v)

        ref = gelu(flat @ w1 + b1) @ w2 + b2
        np.testing.assert_allclose(out.reshape(4, 8), ref, atol=1e-4, rtol=1e-4)

    def test_gradients_flow_to_gate_and_experts(self):
        paddle.seed(2)
        layer = MoELayer(d_model=8, d_hidden=16, num_experts=4, topk=2)
        x = paddle.to_tensor(RNG.randn(1, 8, 8).astype(np.float32), stop_gradient=False)
        out = layer(x)
        loss = out.sum() + 0.01 * layer.aux_loss
        loss.backward()
        assert layer.gate_weight.grad is not None
        assert layer.experts.w1.grad is not None
        assert x.grad is not None
        assert float(paddle.abs(layer.gate_weight.grad).sum()) > 0

    def test_expert_parallel_sharding(self):
        mesh = dist.ProcessMesh(np.arange(8).reshape(2, 4), ["dp", "ep"])
        layer = MoELayer(d_model=16, d_hidden=32, num_experts=8, topk=2, ep_mesh=mesh)
        # expert weights sharded over ep axis
        shard_shapes = {tuple(s.data.shape) for s in layer.experts.w1._data.addressable_shards}
        assert shard_shapes == {(2, 16, 32)}
        x = paddle.to_tensor(RNG.randn(2, 8, 16).astype(np.float32))
        out = layer(x)
        assert out.shape == [2, 8, 16]

    def test_moe_in_engine_train_step(self):
        from paddle_tpu.distributed.engine import ShardedTrainStep

        paddle.seed(3)

        class MoEModel(nn.Layer):
            def __init__(self):
                super().__init__()
                self.inp = nn.Linear(8, 16)
                self.moe = MoELayer(d_model=16, d_hidden=32, num_experts=4, topk=2)
                self.out = nn.Linear(16, 4)

            def forward(self, x):
                return self.out(self.moe(self.inp(x)))

        model = MoEModel()
        mesh = dist.ProcessMesh(np.arange(8), ["dp"])
        opt = paddle.optimizer.AdamW(1e-3, parameters=model.parameters())
        lossfn = nn.CrossEntropyLoss()
        step = ShardedTrainStep(model, lambda o, l: lossfn(o, l), opt, mesh)
        x = paddle.to_tensor(RNG.randn(16, 4, 8).astype(np.float32))
        y = paddle.to_tensor(RNG.randint(0, 4, (16, 4)).astype(np.int64))
        l0 = float(step.step(x, y))
        for _ in range(4):
            l1 = float(step.step(x, y))
        assert np.isfinite(l1) and l1 < l0
