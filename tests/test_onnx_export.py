"""ONNX protobuf export tests.

Validation strategy: the emitted bytes are parsed with protoc-generated
bindings for a subset onnx.proto (compiled on the fly — protoc and the
protobuf runtime are in the image), so the hand-rolled wire format is
checked by an independent decoder, and initializers round-trip bit-exact.
"""

import os
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as paddle

ONNX_SUBSET_PROTO = """
syntax = "proto3";
package onnx_subset;

message AttributeProto {
  string name = 1;
  float f = 2;
  int64 i = 3;
  bytes s = 4;
  repeated float floats = 7;
  repeated int64 ints = 8;
  int32 type = 20;
}
message ValueInfoProto {
  string name = 1;
  TypeProto type = 2;
}
message TypeProto {
  message Tensor {
    int32 elem_type = 1;
    TensorShapeProto shape = 2;
  }
  Tensor tensor_type = 1;
}
message TensorShapeProto {
  message Dimension {
    int64 dim_value = 1;
    string dim_param = 2;
  }
  repeated Dimension dim = 1;
}
message TensorProto {
  repeated int64 dims = 1;
  int32 data_type = 2;
  repeated float float_data = 4;
  string name = 8;
  bytes raw_data = 9;
}
message NodeProto {
  repeated string input = 1;
  repeated string output = 2;
  string name = 3;
  string op_type = 4;
  repeated AttributeProto attribute = 5;
}
message GraphProto {
  repeated NodeProto node = 1;
  string name = 2;
  repeated TensorProto initializer = 5;
  repeated ValueInfoProto input = 11;
  repeated ValueInfoProto output = 12;
}
message OperatorSetIdProto {
  string domain = 1;
  int64 version = 2;
}
message ModelProto {
  int64 ir_version = 1;
  string producer_name = 2;
  string producer_version = 3;
  GraphProto graph = 7;
  repeated OperatorSetIdProto opset_import = 8;
}
"""


@pytest.fixture(scope="module")
def onnx_pb(tmp_path_factory):
    d = tmp_path_factory.mktemp("onnx_proto")
    proto = d / "onnx_subset.proto"
    proto.write_text(ONNX_SUBSET_PROTO)
    subprocess.run(["protoc", f"--python_out={d}", f"--proto_path={d}",
                    str(proto)], check=True)
    sys.path.insert(0, str(d))
    try:
        import onnx_subset_pb2  # noqa: E402

        yield onnx_subset_pb2
    finally:
        sys.path.remove(str(d))


def test_export_mlp_parses_and_roundtrips(onnx_pb, tmp_path):
    import paddle_tpu.nn as nn

    paddle.seed(0)

    class MLP(nn.Layer):
        def __init__(self):
            super().__init__()
            self.l1 = nn.Linear(8, 16)
            self.l2 = nn.Linear(16, 4)

        def forward(self, x):
            import paddle_tpu.nn.functional as F

            return F.softmax(self.l2(F.relu(self.l1(x))), axis=-1)

    net = MLP()
    from paddle_tpu.static import InputSpec

    path = paddle.onnx.export(net, str(tmp_path / "mlp.onnx"),
                              input_spec=[InputSpec([2, 8], "float32")])
    assert path.endswith(".onnx") and os.path.exists(path)

    m = onnx_pb.ModelProto()
    m.ParseFromString(open(path, "rb").read())
    assert m.producer_name == "paddle_tpu"
    assert m.opset_import[0].version == 12  # last opset with ReduceSum axes attr
    ops = [n.op_type for n in m.graph.node]
    assert "MatMul" in ops
    assert any(o in ops for o in ("Max", "Relu", "Where"))  # relu lowering
    assert len(m.graph.input) == 1
    assert len(m.graph.output) == 1
    in_shape = [d.dim_value for d in
                m.graph.input[0].type.tensor_type.shape.dim]
    assert in_shape == [2, 8]

    # initializers round-trip bit-exact against the layer weights
    inits = {t.name: t for t in m.graph.initializer}
    params = {k: v for k, v in net.state_dict().items()}
    raw_sizes = sorted(len(t.raw_data) for t in inits.values()
                       if t.name.startswith("param_"))
    want_sizes = sorted(int(np.prod(v.shape)) * 4 for v in params.values())
    assert raw_sizes == want_sizes
    w1 = np.asarray(net.l1.weight.numpy())
    assert any(np.frombuffer(t.raw_data, np.float32).size == w1.size
               and np.allclose(np.frombuffer(t.raw_data, np.float32)
                               .reshape(t.dims), w1)
               for t in inits.values())


def test_export_conv_model(onnx_pb, tmp_path):
    import paddle_tpu.nn as nn

    paddle.seed(0)

    class ConvNet(nn.Layer):
        def __init__(self):
            super().__init__()
            self.conv = nn.Conv2D(3, 8, 3, padding=1)

        def forward(self, x):
            import paddle_tpu.nn.functional as F

            return F.relu(self.conv(x))

    from paddle_tpu.static import InputSpec

    path = paddle.onnx.export(ConvNet(), str(tmp_path / "conv.onnx"),
                              input_spec=[InputSpec([1, 3, 8, 8], "float32")])
    m = onnx_pb.ModelProto()
    m.ParseFromString(open(path, "rb").read())
    ops = [n.op_type for n in m.graph.node]
    assert "Conv" in ops
    conv = next(n for n in m.graph.node if n.op_type == "Conv")
    attrs = {a.name: list(a.ints) for a in conv.attribute if a.ints}
    assert attrs.get("strides") == [1, 1]
    assert attrs.get("pads") == [1, 1, 1, 1]


def test_unsupported_primitive_raises_cleanly():
    import jax.numpy as jnp

    from paddle_tpu.onnx_export import OnnxExportError, export_onnx

    def weird(x):
        return jnp.fft.fft(x).real

    with pytest.raises((OnnxExportError, Exception)):
        export_onnx(weird, [jnp.zeros((4,), jnp.float32)])


def test_dynamic_batch_dim(onnx_pb, tmp_path):
    """None dims in input_spec export as symbolic dim_params (review
    regression: they used to freeze to 1)."""
    import paddle_tpu.nn as nn
    from paddle_tpu.static import InputSpec

    paddle.seed(0)
    path = paddle.onnx.export(nn.Linear(8, 4), str(tmp_path / "dyn.onnx"),
                              input_spec=[InputSpec([None, 8], "float32")])
    m = onnx_pb.ModelProto()
    m.ParseFromString(open(path, "rb").read())
    dims = m.graph.input[0].type.tensor_type.shape.dim
    assert dims[0].dim_param != "" and dims[0].dim_value == 0
    assert dims[1].dim_value == 8


def test_tuple_output_model(onnx_pb, tmp_path):
    import paddle_tpu.nn as nn
    from paddle_tpu.static import InputSpec

    paddle.seed(0)

    class TwoHead(nn.Layer):
        def __init__(self):
            super().__init__()
            self.a = nn.Linear(4, 2)
            self.b = nn.Linear(4, 3)

        def forward(self, x):
            return self.a(x), self.b(x)

    path = paddle.onnx.export(TwoHead(), str(tmp_path / "two.onnx"),
                              input_spec=[InputSpec([1, 4], "float32")])
    m = onnx_pb.ModelProto()
    m.ParseFromString(open(path, "rb").read())
    assert len(m.graph.output) == 2
