"""Top-level API parity: every name in the reference's
python/paddle/__init__.py __all__ must exist, and the new batch must be
numerically correct.
"""

import re

import numpy as np
import pytest

import paddle_tpu as paddle

RNG = np.random.RandomState(0)
REF_INIT = "/root/reference/python/paddle/__init__.py"


def test_reference_all_covered():
    src = open(REF_INIT).read()
    m = re.search(r"__all__\s*=\s*\[(.*?)\]", src, re.S)
    ref_all = re.findall(r"'([^']+)'", m.group(1))
    assert len(ref_all) > 400
    missing = [n for n in ref_all if not hasattr(paddle, n)]
    assert missing == [], f"missing from paddle_tpu: {missing}"


def test_add_n_tensordot_isin():
    a = paddle.to_tensor(np.arange(6, dtype=np.float32).reshape(2, 3))
    b = paddle.to_tensor(np.ones((2, 3), np.float32))
    np.testing.assert_allclose(paddle.add_n([a, b]).numpy(), a.numpy() + 1)
    np.testing.assert_allclose(
        paddle.tensordot(a, a, axes=[[1], [1]]).numpy(), a.numpy() @ a.numpy().T)
    assert int(paddle.isin(a, paddle.to_tensor(np.array([1.0, 5.0]))).numpy().sum()) == 2


def test_nan_to_num_and_inplace():
    x = paddle.to_tensor(np.array([np.nan, np.inf, 1.0], np.float32))
    np.testing.assert_allclose(paddle.nan_to_num(x, posinf=9).numpy(), [0, 9, 1])
    paddle.nan_to_num_(x, posinf=9)
    np.testing.assert_allclose(x.numpy(), [0, 9, 1])


def test_pdist():
    pts = np.array([[0.0, 0], [3, 4], [0, 1]], np.float32)
    np.testing.assert_allclose(paddle.pdist(paddle.to_tensor(pts)).numpy(),
                               [5, 1, np.sqrt(18)], rtol=1e-6)


def test_scatter_family():
    y = paddle.to_tensor(np.zeros((3, 3), np.float32))
    z = paddle.index_fill(y, paddle.to_tensor(np.array([0, 2])), 0, 7.0)
    assert np.allclose(z.numpy()[0], 7) and np.allclose(z.numpy()[1], 0)
    s = paddle.select_scatter(y, paddle.to_tensor(np.ones(3, np.float32)), 0, 1)
    assert np.allclose(s.numpy()[1], 1) and np.allclose(s.numpy()[0], 0)
    ss = paddle.slice_scatter(y, paddle.to_tensor(np.ones((3, 1), np.float32)),
                              [1], [0], [1], [1])
    assert np.allclose(ss.numpy()[:, 0], 1)
    d = paddle.diagonal_scatter(y, paddle.to_tensor(np.ones(3, np.float32)))
    np.testing.assert_allclose(np.diag(d.numpy()), 1.0)


def test_module_level_inplace_twins():
    t = paddle.to_tensor(np.array([2.0, 3.0], np.float32))
    paddle.sin_(t)
    np.testing.assert_allclose(t.numpy(), np.sin([2.0, 3.0]), atol=1e-6)
    u = paddle.to_tensor(np.array([4.0], np.float32))
    paddle.sqrt_(u)
    np.testing.assert_allclose(u.numpy(), [2.0])
    v = paddle.to_tensor(np.array([1.0, 2.0], np.float32))
    paddle.multiply_(v, paddle.to_tensor(np.array([3.0, 3.0], np.float32)))
    np.testing.assert_allclose(v.numpy(), [3.0, 6.0])


def test_random_inplace_families():
    paddle.seed(7)
    x = paddle.to_tensor(np.zeros((100,), np.float32))
    paddle.bernoulli_(x, p=0.3)
    frac = float(np.asarray(x.numpy()).mean())
    assert 0.1 < frac < 0.5
    paddle.log_normal_(x)
    assert (np.asarray(x.numpy()) > 0).all()
    g = paddle.standard_normal([500])
    assert abs(float(np.asarray(g.numpy()).mean())) < 0.3
    bi = paddle.binomial(paddle.to_tensor(np.full((50,), 10.0, np.float32)),
                         paddle.to_tensor(np.full((50,), 0.5, np.float32)))
    vals = np.asarray(bi.numpy())
    assert (vals >= 0).all() and (vals <= 10).all()


def test_unfold_and_framework_utils():
    u = paddle.unfold(paddle.to_tensor(np.arange(8, dtype=np.float32)), 0, 4, 2)
    assert list(u.shape) == [3, 4]
    np.testing.assert_allclose(u.numpy()[1], [2, 3, 4, 5])

    assert paddle.finfo("float32").max > 1e38
    assert paddle.iinfo("int32").max == 2**31 - 1
    assert int(paddle.rank(paddle.to_tensor(np.zeros((2, 3)))).numpy()) == 2
    np.testing.assert_allclose(paddle.shape(paddle.to_tensor(np.zeros((2, 3)))).numpy(), [2, 3])
    assert paddle.is_floating_point(paddle.to_tensor(np.zeros(1, np.float32)))
    assert paddle.is_integer(paddle.to_tensor(np.zeros(1, np.int32)))

    w = paddle.create_parameter([3, 4], "float32")
    assert not w.stop_gradient and list(w.shape) == [3, 4]

    with paddle.LazyGuard():
        pass


def test_special_gamma_family():
    from scipy import special as ss

    x = np.abs(RNG.randn(6).astype(np.float32)) + 0.5
    y = np.abs(RNG.randn(6).astype(np.float32)) + 0.5
    np.testing.assert_allclose(
        paddle.gammainc(paddle.to_tensor(x), paddle.to_tensor(y)).numpy(),
        ss.gammainc(x, y), rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(
        paddle.multigammaln(paddle.to_tensor(x + 2), 2).numpy(),
        ss.multigammaln(x + 2, 2), rtol=1e-4)


def test_flops_counts_linear():
    import paddle_tpu.nn as nn

    net = nn.Linear(8, 16)
    f = paddle.flops(net, [4, 8])
    assert f == 2 * 4 * 8 * 16


def test_histogram_tools():
    e = paddle.histogram_bin_edges(paddle.to_tensor(np.array([0.0, 1.0])), bins=4)
    np.testing.assert_allclose(e.numpy(), [0, 0.25, 0.5, 0.75, 1.0])
    h, edges = paddle.histogramdd(paddle.to_tensor(RNG.randn(30, 2).astype(np.float32)),
                                  bins=5)
    assert list(h.shape) == [5, 5] and len(edges) == 2
    assert float(np.asarray(h.numpy()).sum()) == 30


def test_random_inplace_clears_stale_tape():
    """Random overwrites must not backprop through discarded history
    (review regression)."""
    w = paddle.to_tensor(np.ones(3, np.float32), stop_gradient=False)
    y = w * 2.0
    paddle.exponential_(y)
    y.sum().backward()
    assert w.grad is None or float(np.abs(w.grad.numpy()).sum()) == 0.0


def test_p_norm_zero():
    assert float(paddle.p_norm(paddle.to_tensor(np.array([1.0, 0.0, 2.0], np.float32)), p=0)) == 2.0


def test_dtype_is_a_type():
    t = paddle.to_tensor(np.zeros(1, np.float32))
    assert isinstance(t.dtype, paddle.dtype)


def test_log_normal_default_shape():
    out = paddle.log_normal()
    assert float(out.numpy()) > 0
