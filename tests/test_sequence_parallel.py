"""Sequence/context parallelism tests: ring attention, Ulysses, Megatron-SP.

Oracle: numerical equivalence with single-device full attention
(reference pattern: hybrid-parallel loss-parity tests, SURVEY §4).
"""

import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
import paddle_tpu.nn.functional as F
from paddle_tpu.distributed.sequence_parallel import (
    gather,
    ring_attention,
    scatter,
    ulysses_attention,
)

RNG = np.random.RandomState(0)


def qkv(b=2, s=64, h=8, d=16):
    return (RNG.randn(b, s, h, d).astype(np.float32) for _ in range(3))


def sdpa(q, k, v, causal=True):
    return F.scaled_dot_product_attention(
        paddle.to_tensor(q), paddle.to_tensor(k), paddle.to_tensor(v), is_causal=causal).numpy()


class TestRingAttention:
    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_full_attention(self, causal):
        q, k, v = qkv()
        g = dist.new_group(axis_name="sp")

        def prog(q, k, v):
            return ring_attention(q, k, v, group=g, causal=causal)

        # shard seq dim (axis 1) across the ring
        spec = P(None, "sp")
        out = dist.spmd(prog, {"sp": 8}, in_specs=spec, out_specs=spec)(
            paddle.to_tensor(q), paddle.to_tensor(k), paddle.to_tensor(v))
        np.testing.assert_allclose(out.numpy(), sdpa(q, k, v, causal), atol=2e-4, rtol=1e-3)

    def test_gradients_flow(self):
        q, k, v = qkv(b=1, s=32, h=2, d=8)
        g = dist.new_group(axis_name="sp")

        def prog(q, k, v):
            return ring_attention(q, k, v, group=g, causal=True)

        spec = P(None, "sp")
        f = dist.spmd(prog, {"sp": 8}, in_specs=spec, out_specs=spec)
        tq = paddle.to_tensor(q, stop_gradient=False)
        out = f(tq, paddle.to_tensor(k), paddle.to_tensor(v))
        out.sum().backward()
        assert tq.grad is not None

        # reference gradient from plain attention
        tq2 = paddle.to_tensor(q, stop_gradient=False)
        F.scaled_dot_product_attention(tq2, paddle.to_tensor(k), paddle.to_tensor(v),
                                       is_causal=True).sum().backward()
        np.testing.assert_allclose(tq.grad.numpy(), tq2.grad.numpy(), atol=1e-3, rtol=1e-2)


class TestUlysses:
    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_full_attention(self, causal):
        q, k, v = qkv()  # h=8 divisible by sp=8
        g = dist.new_group(axis_name="sp")

        def prog(q, k, v):
            return ulysses_attention(q, k, v, group=g, causal=causal)

        spec = P(None, "sp")
        out = dist.spmd(prog, {"sp": 8}, in_specs=spec, out_specs=spec)(
            paddle.to_tensor(q), paddle.to_tensor(k), paddle.to_tensor(v))
        np.testing.assert_allclose(out.numpy(), sdpa(q, k, v, causal), atol=2e-4, rtol=1e-3)


class TestMegatronSP:
    def test_scatter_gather_roundtrip(self):
        x = RNG.randn(2, 16, 4).astype(np.float32)
        g = dist.new_group(axis_name="sp")

        def prog(x):
            local = scatter(x, group=g, axis=1)  # replicated -> seq shard
            assert local.shape[1] == 2
            return gather(local, group=g, axis=1)

        out = dist.spmd(prog, {"sp": 8}, in_specs=P(), out_specs=P())(paddle.to_tensor(x))
        np.testing.assert_allclose(out.numpy(), x)

    def test_column_row_sp_linear_parity(self):
        """seq-parallel TP block == plain two-layer matmul."""
        from paddle_tpu.distributed import fleet
        from paddle_tpu.distributed.sequence_parallel import (
            ColumnSequenceParallelLinear, RowSequenceParallelLinear)

        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": 1, "mp_degree": 8, "pp_degree": 1}
        fleet.init(is_collective=True, strategy=strategy)

        paddle.seed(0)
        col = ColumnSequenceParallelLinear(16, 32, has_bias=False, gather_output=False)
        row = RowSequenceParallelLinear(32, 16, has_bias=False,
                                        sp_group=fleet.fleet._hcg.get_model_parallel_group())
        x = RNG.randn(2, 16, 16).astype(np.float32)  # [b, s, hidden]

        mp_g = fleet.fleet._hcg.get_model_parallel_group()

        def prog(x):
            x_local = scatter(x, group=mp_g, axis=1)  # seq shard
            h = col(x_local)
            out = row(h)  # reduce-scatter back to seq shards
            return gather(out, group=mp_g, axis=1)

        out = dist.spmd(prog, {"mp": 8}, in_specs=P(), out_specs=P())(paddle.to_tensor(x))
        expected = (x @ col.inner.weight.numpy()) @ row.weight.numpy()
        np.testing.assert_allclose(out.numpy(), expected, atol=1e-4, rtol=1e-4)


class TestLongContext:
    def test_ring_attention_long_sequence(self):
        """Longer-than-memory-style check: seq 512 over 8 ranks, block 64."""
        q, k, v = qkv(b=1, s=512, h=2, d=32)
        g = dist.new_group(axis_name="sp")

        def prog(q, k, v):
            return ring_attention(q, k, v, group=g, causal=True)

        spec = P(None, "sp")
        out = dist.spmd(prog, {"sp": 8}, in_specs=spec, out_specs=spec)(
            paddle.to_tensor(q), paddle.to_tensor(k), paddle.to_tensor(v))
        np.testing.assert_allclose(out.numpy(), sdpa(q, k, v, True), atol=3e-4, rtol=1e-3)


class TestNonDivisibleLocalLength:
    def test_ring_attention_non_multiple_of_flash_block(self):
        """Regression (round-5 ADVICE, high): per-rank seq 1536 is NOT a
        multiple of the 1024 flash block; the raw min(1024, s_loc) block
        made _flash_lse's floor-divided grid skip the 512 tail rows and
        drop tail KV columns — silently wrong attention. ring_attention
        must pick a dividing block (_pick_block) like flash_attention()
        does; on the pre-fix code this comparison fails."""
        q, k, v = qkv(b=1, s=6144, h=1, d=8)  # 6144 / 4 ranks = 1536
        g = dist.new_group(axis_name="sp")

        def prog(q, k, v):
            return ring_attention(q, k, v, group=g, causal=True)

        spec = P(None, "sp")
        out = dist.spmd(prog, {"sp": 4}, in_specs=spec, out_specs=spec)(
            paddle.to_tensor(q), paddle.to_tensor(k), paddle.to_tensor(v))
        np.testing.assert_allclose(out.numpy(), sdpa(q, k, v, True),
                                   atol=3e-4, rtol=1e-3)


class TestVocabParallelEmbedding:
    def test_spmd_masked_lookup_parity(self):
        from paddle_tpu.distributed import fleet
        from paddle_tpu.distributed.fleet.mp_layers import VocabParallelEmbedding

        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": 1, "mp_degree": 8, "pp_degree": 1}
        fleet.init(is_collective=True, strategy=strategy)
        paddle.seed(1)
        emb = VocabParallelEmbedding(64, 16)
        ids = RNG.randint(0, 64, (2, 10)).astype(np.int32)

        def prog(ids):
            return emb(ids)

        out = dist.spmd(prog, {"mp": 8}, in_specs=P(), out_specs=P())(paddle.to_tensor(ids))
        expected = emb.weight.numpy()[ids]
        np.testing.assert_allclose(out.numpy(), expected, atol=1e-5)
