"""Auto SPMD shard propagation (derived Megatron placements).

Parity oracle: the reference derives op shardings from SPMD rules +
completion (phi/infermeta/spmd_rules/matmul.h:25,
auto_parallel/static/completion.py); its tests assert the completed
program matches the hand-annotated one. Here: auto_shard_layer with NO
recipe must (a) reproduce llama_shard_fn's placements decision-for-
decision on Llama, and (b) train GPT/BERT to the exact same losses as
the replicated baseline (placement changes layout, never math).
"""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu import nn
from paddle_tpu.distributed.auto_shard import auto_shard_layer, derive_placements
from paddle_tpu.distributed.mesh import Replicate, Shard


def _mesh():
    return dist.ProcessMesh(np.arange(8).reshape(2, 4), ["dp", "mp"])


class TestDerivePlacements:
    def test_llama_matches_manual_recipe(self):
        from paddle_tpu.models import LlamaConfig, LlamaForCausalLM

        paddle.seed(0)
        cfg = LlamaConfig.tiny(num_key_value_heads=4)
        model = LlamaForCausalLM(cfg)
        mesh = _mesh()
        ids = paddle.to_tensor(
            np.random.RandomState(0).randint(0, cfg.vocab_size, (2, 8)).astype(np.int32))
        dec = derive_placements(model, mesh, [ids], mp_axis="mp")

        def placement(name):
            return dec[name]["weight"][1]  # mp is mesh dim 1

        for lname, expect in {
            "q_proj": Shard(1), "k_proj": Shard(1), "v_proj": Shard(1),
            "gate_proj": Shard(1), "up_proj": Shard(1),
            "o_proj": Shard(0), "down_proj": Shard(0),
        }.items():
            hits = [n for n in dec if n.endswith(lname)]
            assert hits, f"no decision for {lname}"
            for n in hits:
                assert placement(n) == expect, (n, placement(n), expect)
        # vocab embedding rows sharded; lm_head columns sharded
        emb = [n for n in dec if n.endswith("embed_tokens")]
        assert emb and placement(emb[0]) == Shard(0)
        head = [n for n in dec if n.endswith("lm_head")]
        assert head and placement(head[0]) == Shard(1)

    def test_small_positional_embedding_stays_replicated(self):
        from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM

        paddle.seed(0)
        model = GPTForCausalLM(GPTConfig.tiny())
        mesh = _mesh()
        ids = paddle.to_tensor(
            np.random.RandomState(0).randint(0, 256, (2, 8)).astype(np.int32))
        dec = derive_placements(model, mesh, [ids])
        wpe = [n for n in dec if n.endswith("wpe")]
        assert wpe and dec[wpe[0]]["weight"][1] == Replicate()
        wte = [n for n in dec if n.endswith("wte")]
        assert wte and dec[wte[0]]["weight"][1] == Shard(0)

    def test_tied_layer_keeps_first_decision(self):
        """A shared Linear applied twice must not flip col->row via its
        own self-edge; the first decision stands."""

        class Tied(nn.Layer):
            def __init__(self):
                super().__init__()
                self.shared = nn.Linear(8, 8)
                self.mid = nn.Linear(8, 8)

            def forward(self, x):
                return self.shared(self.mid(self.shared(x)))

        paddle.seed(0)
        model = Tied()
        mesh = _mesh()
        x = paddle.to_tensor(np.random.RandomState(0).randn(2, 8).astype(np.float32))
        dec = derive_placements(model, mesh, [x])
        assert dec["shared"]["weight"][1] == Shard(1)  # first use: column
        assert dec["mid"]["weight"][1] == Shard(0)     # consumes it: row

    def test_mlp_sandwich_alternates(self):
        """A plain MLP stack must alternate col/row by dataflow, not name."""
        paddle.seed(0)
        model = nn.Sequential(nn.Linear(8, 16), nn.GELU(), nn.Linear(16, 8),
                              nn.GELU(), nn.Linear(8, 16), nn.GELU(),
                              nn.Linear(16, 8))
        mesh = _mesh()
        x = paddle.to_tensor(np.random.RandomState(0).randn(2, 8).astype(np.float32))
        dec = derive_placements(model, mesh, [x])
        pl = [dec[n]["weight"][1] for n in ("0", "2", "4", "6")]
        assert pl == [Shard(1), Shard(0), Shard(1), Shard(0)], pl


def _train_losses(model, loss_fn, opt, mesh, ids, labels, steps=3):
    from paddle_tpu.distributed.engine import ShardedTrainStep

    step = ShardedTrainStep(model, loss_fn, opt, mesh, dp_axis="dp")
    return [float(step.step(ids, labels)) for _ in range(steps)]


class TestAutoShardTrainingParity:
    @pytest.mark.parametrize("family", ["llama", "gpt", "bert"])
    def test_loss_parity_vs_replicated(self, family):
        mesh = _mesh()
        rng = np.random.RandomState(0)

        if family == "llama":
            from paddle_tpu.models import (LlamaConfig, LlamaForCausalLM,
                                           llama_pretrain_loss)

            cfg = LlamaConfig.tiny(num_key_value_heads=4)
            make = lambda: LlamaForCausalLM(cfg)
            loss_fn = llama_pretrain_loss
            V = cfg.vocab_size
        elif family == "gpt":
            from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM

            cfg = GPTConfig.tiny()
            make = lambda: GPTForCausalLM(cfg)
            ce = nn.CrossEntropyLoss()
            loss_fn = lambda logits, lab: ce(
                logits.reshape([-1, logits.shape[-1]]), lab.reshape([-1]))
            V = cfg.vocab_size
        else:
            from paddle_tpu.models.bert import BertConfig, BertForPretraining

            cfg = BertConfig.tiny()
            make = lambda: BertForPretraining(cfg)
            ce = nn.CrossEntropyLoss()
            loss_fn = lambda mlm, nsp, lab: ce(
                mlm.reshape([-1, mlm.shape[-1]]), lab.reshape([-1]))
            V = cfg.vocab_size

        ids = paddle.to_tensor(rng.randint(0, V, (4, 8)).astype(np.int32))
        labels = paddle.to_tensor(rng.randint(0, V, (4, 8)).astype(np.int64))

        paddle.seed(0)
        base = make()
        paddle.seed(0)
        auto = make()
        auto.set_state_dict(base.state_dict())

        dec = auto_shard_layer(auto, mesh, [ids], mp_axis="mp")
        assert any(
            any(isinstance(p, Shard) for p in per["weight"])
            for per in dec.values()), "auto shard derived nothing"

        opt_a = paddle.optimizer.SGD(0.1, parameters=base.parameters())
        opt_b = paddle.optimizer.SGD(0.1, parameters=auto.parameters())
        base_losses = _train_losses(base, loss_fn, opt_a, mesh, ids, labels)
        auto_losses = _train_losses(auto, loss_fn, opt_b, mesh, ids, labels)
        np.testing.assert_allclose(auto_losses, base_losses, rtol=2e-4,
                                   atol=1e-5)


class TestDecisionReport:
    """Round-4 hardening: the pass surfaces every replicated/unreached/
    out-of-scope layer instead of silently replicating (the
    _VOCAB_RATIO contract is documented and visible)."""

    def test_char_model_embedding_reported(self):
        # vocab 64 < 4*hidden 256: the heuristic replicates AND says why
        class CharModel(nn.Layer):
            def __init__(self):
                super().__init__()
                self.emb = nn.Embedding(64, 256)
                self.fc1 = nn.Linear(256, 256)
                self.fc2 = nn.Linear(256, 64)

            def forward(self, x):
                return self.fc2(self.fc1(self.emb(x)))

        paddle.seed(0)
        m = CharModel()
        ids = paddle.to_tensor(np.zeros((2, 4), np.int32))
        dec = derive_placements(m, _mesh(), [ids], mp_axis="mp")
        assert dec["emb"]["weight"][1] == Replicate()
        assert "emb" in dec.replicated
        assert "_VOCAB_RATIO" in dec.replicated["emb"]
        assert "emb" in dec.report()

    def test_out_of_scope_conv_reported(self):
        class ConvModel(nn.Layer):
            def __init__(self):
                super().__init__()
                self.conv = nn.Conv2D(3, 8, 3)
                self.fc = nn.Linear(8 * 6 * 6, 16)

            def forward(self, x):
                h = self.conv(x)
                return self.fc(h.reshape([h.shape[0], -1]))

        paddle.seed(0)
        m = ConvModel()
        x = paddle.to_tensor(np.zeros((2, 3, 8, 8), np.float32))
        dec = derive_placements(m, _mesh(), [x], mp_axis="mp")
        assert "conv" in dec.out_of_scope
        assert "out-of-scope conv" in dec.report()


class _MoEGPT(nn.Layer):
    """Tiny GPT-shaped stack whose FFN is a GShard MoE layer."""

    def __init__(self, vocab=1024, hidden=16, experts=4):
        super().__init__()
        from paddle_tpu.distributed.moe import MoELayer

        self.emb = nn.Embedding(vocab, hidden)
        self.attn_in = nn.Linear(hidden, hidden)
        self.attn_out = nn.Linear(hidden, hidden)
        self.moe = MoELayer(d_model=hidden, d_hidden=32,
                            num_experts=experts, topk=2)
        self.head = nn.Linear(hidden, vocab)

    def forward(self, ids):
        h = self.emb(ids)
        h = h + self.attn_out(paddle.nn.functional.gelu(self.attn_in(h)))
        h = h + self.moe(h)
        return self.head(h)


class TestMoEAutoShard:
    def test_expert_mlp_decisions(self):
        paddle.seed(0)
        m = _MoEGPT()
        mesh = dist.ProcessMesh(np.arange(8).reshape(2, 2, 2),
                                ["dp", "mp", "ep"])
        ids = paddle.to_tensor(np.zeros((2, 4), np.int32))
        dec = derive_placements(m, mesh, [ids], mp_axis="mp", ep_axis="ep")
        exp = dec["moe.experts"]
        # experts over ep (mesh dim 2), per-expert column/row over mp (dim 1)
        assert exp["w1"][2] == Shard(0) and exp["w1"][1] == Shard(2)
        assert exp["w2"][2] == Shard(0) and exp["w2"][1] == Shard(1)
        assert exp["b1"][2] == Shard(0) and exp["b1"][1] == Shard(1)
        assert exp["b2"][2] == Shard(0) and exp["b2"][1] == Replicate()

    def test_moe_gpt_loss_parity_vs_replicated(self):
        ce = nn.CrossEntropyLoss()

        def loss_fn(logits, labels):
            return ce(logits.reshape([-1, logits.shape[-1]]),
                      labels.reshape([-1]))

        mesh = dist.ProcessMesh(np.arange(8).reshape(2, 2, 2),
                                ["dp", "mp", "ep"])
        rng = np.random.RandomState(0)
        ids = paddle.to_tensor(rng.randint(0, 1024, (4, 8)).astype(np.int32))
        labels = paddle.to_tensor(rng.randint(0, 1024, (4, 8)).astype(np.int64))

        paddle.seed(0)
        base = _MoEGPT()
        opt_b = paddle.optimizer.SGD(0.1, parameters=base.parameters())
        base_losses = _train_losses(base, loss_fn, opt_b, mesh, ids, labels)

        paddle.seed(0)
        sharded = _MoEGPT()
        dec = auto_shard_layer(sharded, mesh, [ids], mp_axis="mp")
        assert "moe.experts" in dec
        opt_s = paddle.optimizer.SGD(0.1, parameters=sharded.parameters())
        sharded_losses = _train_losses(sharded, loss_fn, opt_s, mesh, ids,
                                       labels)
        np.testing.assert_allclose(base_losses, sharded_losses,
                                   rtol=2e-4, atol=1e-5)
