"""Model hub: list / help / load entrypoints from a hubconf.py repo.

Parity: python/paddle/hub.py (re-export of python/paddle/hapi/hub.py —
list:171, help:224, load:274; hubconf protocol: a ``hubconf.py`` at the
repo root whose public callables are the entrypoints and whose optional
``dependencies`` list names required import-checkable packages,
hapi/hub.py:149 _load_entry_from_hubconf).

TPU-runtime scope: ``source='local'`` is fully supported. The
github/gitee sources download an archive over the network
(hapi/hub.py:94 _get_cache_or_reload); this runtime has no egress, so
those sources raise with guidance to clone the repo and use local.
"""

from __future__ import annotations

import builtins
import importlib.util
import os
import sys
import uuid

__all__ = ["list", "help", "load"]

MODULE_HUBCONF = "hubconf.py"


def _check_module_exists(name: str) -> bool:
    return importlib.util.find_spec(name) is not None


def _import_module(name: str, repo_dir: str):
    path = os.path.join(repo_dir, MODULE_HUBCONF)
    if not os.path.exists(path):
        raise FileNotFoundError(f"no {MODULE_HUBCONF} found in {repo_dir!r}")
    spec = importlib.util.spec_from_file_location(name, path)
    module = importlib.util.module_from_spec(spec)
    sys.modules[name] = module
    try:
        spec.loader.exec_module(module)
    finally:
        sys.modules.pop(name, None)
    return module


def _load_hubconf(repo_dir: str, source: str, force_reload: bool):
    if source not in ("github", "gitee", "local"):
        raise ValueError(
            f'Unknown source: "{source}". Allowed values: "github" | '
            '"gitee" | "local".')
    if source in ("github", "gitee"):
        raise NotImplementedError(
            f"source={source!r} downloads a repo archive over the network; "
            "this runtime has no egress — clone the repo locally and call "
            "with source='local' (reference download path: "
            "python/paddle/hapi/hub.py:94 _get_cache_or_reload)")
    repo_dir = os.path.expanduser(repo_dir)
    m = _import_module(f"_paddle_tpu_hubconf_{uuid.uuid4().hex}", repo_dir)
    deps = getattr(m, "dependencies", None) or []
    missing = [d for d in deps if not _check_module_exists(d)]
    if missing:
        raise RuntimeError(
            f"hubconf dependencies not installed: {missing}")
    return m


def _entrypoints(m):
    return sorted(
        name for name, obj in vars(m).items()
        if callable(obj) and not name.startswith("_"))


def list(repo_dir: str, source: str = "github", force_reload: bool = False):
    """All entrypoint names exported by the repo's hubconf.py."""
    return builtins.list(_entrypoints(_load_hubconf(repo_dir, source,
                                                    force_reload)))


def help(repo_dir: str, model: str, source: str = "github",
         force_reload: bool = False):
    """The docstring of entrypoint ``model``."""
    m = _load_hubconf(repo_dir, source, force_reload)
    entry = _load_entry(m, model)
    return entry.__doc__


def load(repo_dir: str, model: str, source: str = "github",
         force_reload: bool = False, **kwargs):
    """Call entrypoint ``model`` with ``kwargs`` and return the result
    (typically a constructed, optionally weight-loaded Layer)."""
    m = _load_hubconf(repo_dir, source, force_reload)
    entry = _load_entry(m, model)
    return entry(**kwargs)


def _load_entry(m, name: str):
    entry = getattr(m, name, None)
    if entry is None or not callable(entry):
        raise RuntimeError(
            f"Cannot find callable entrypoint {name!r} in hubconf; "
            f"available: {_entrypoints(m)}")
    return entry
