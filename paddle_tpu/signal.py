"""paddle.signal equivalent — stft / istft.

Parity: python/paddle/signal.py (stft:xxx, istft — frame/overlap_add over
fft ops, reference kernels phi/kernels/cpu/stft_kernel.cc). TPU design:
framing is a strided gather, overlap-add is a scatter-add, both fused by
XLA around the batched FFT.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from .audio.functional import get_window as _get_window
from .core.tensor import Tensor
from .ops.dispatch import apply_op

__all__ = ["stft", "istft"]


def _prepare_window(n_fft: int, hop_length: Optional[int], win_length: Optional[int],
                    window):
    """Shared stft/istft window setup: defaults, string names (via
    audio.functional.get_window), Tensor unwrap, center-pad to n_fft."""
    hop = hop_length or n_fft // 4
    wl = win_length or n_fft
    if window is None:
        win = jnp.ones(wl, jnp.float32)
    elif isinstance(window, str):
        win = _get_window(window, wl)
    else:
        win = window._data if isinstance(window, Tensor) else jnp.asarray(window)
    if win.shape[0] != wl:
        raise ValueError(f"window length {win.shape[0]} != win_length {wl}")
    if wl < n_fft:
        lpad = (n_fft - wl) // 2
        win = jnp.pad(win, (lpad, n_fft - wl - lpad))
    return hop, win


def stft(x: Tensor, n_fft: int, hop_length: Optional[int] = None,
         win_length: Optional[int] = None, window: Optional[Tensor] = None,
         center: bool = True, pad_mode: str = "reflect", normalized: bool = False,
         onesided: bool = True, name=None) -> Tensor:
    """[..., T] -> complex [..., n_fft//2+1 (or n_fft), n_frames]."""
    hop, win = _prepare_window(n_fft, hop_length, win_length, window)

    def fn(x, win):
        h = x
        if center:
            pad = [(0, 0)] * (h.ndim - 1) + [(n_fft // 2, n_fft // 2)]
            h = jnp.pad(h, pad, mode="reflect" if pad_mode == "reflect" else "constant")
        T = h.shape[-1]
        n_frames = 1 + (T - n_fft) // hop
        idx = jnp.arange(n_frames)[:, None] * hop + jnp.arange(n_fft)[None, :]
        frames = h[..., idx] * win
        if onesided:
            spec = jnp.fft.rfft(frames, n=n_fft, axis=-1)
        else:
            spec = jnp.fft.fft(frames, n=n_fft, axis=-1)
        if normalized:
            spec = spec / jnp.sqrt(jnp.asarray(n_fft, spec.real.dtype))
        return jnp.swapaxes(spec, -1, -2)  # [..., freq, frames]

    return apply_op("stft", fn, x, Tensor(win))


def istft(x: Tensor, n_fft: int, hop_length: Optional[int] = None,
          win_length: Optional[int] = None, window: Optional[Tensor] = None,
          center: bool = True, normalized: bool = False, onesided: bool = True,
          length: Optional[int] = None, return_complex: bool = False, name=None) -> Tensor:
    """Inverse STFT with window-square overlap-add normalization."""
    if return_complex and onesided:
        raise ValueError("return_complex=True requires onesided=False (reference behavior)")
    hop, win = _prepare_window(n_fft, hop_length, win_length, window)

    def fn(spec, win):
        s = jnp.swapaxes(spec, -1, -2)  # [..., frames, freq]
        if normalized:
            s = s * jnp.sqrt(jnp.asarray(n_fft, jnp.float32))
        if onesided:
            frames = jnp.fft.irfft(s, n=n_fft, axis=-1)
        else:
            frames = jnp.fft.ifft(s, n=n_fft, axis=-1)
            if not return_complex:
                frames = frames.real
        frames = frames * win
        n_frames = frames.shape[-2]
        T = n_fft + hop * (n_frames - 1)
        lead = frames.shape[:-2]
        out = jnp.zeros(lead + (T,), frames.dtype)
        wsum = jnp.zeros(T, jnp.float32)
        idx = jnp.arange(n_frames)[:, None] * hop + jnp.arange(n_fft)[None, :]
        out = out.at[..., idx].add(frames)
        wsum = wsum.at[idx.reshape(-1)].add(jnp.tile(win * win, n_frames))
        out = out / jnp.where(wsum > 1e-11, wsum, 1.0)
        if center:
            out = out[..., n_fft // 2: T - n_fft // 2]
        if length is not None:
            out = out[..., :length]
        return out

    return apply_op("istft", fn, x, Tensor(win))
