"""Optimizers.

Parity: python/paddle/optimizer/ (optimizer.py Optimizer base, sgd.py,
momentum.py, adam.py, adamw.py, adagrad.py, adadelta.py, rmsprop.py,
adamax.py, lamb.py). TPU design: each parameter's update is a jitted pure
function over (param, grad, state) arrays — XLA fuses the whole update
chain; state lives as device arrays keyed per-parameter, which maps
directly onto optimizer-state sharding for ZeRO (distributed/sharding).
"""

from __future__ import annotations

import functools
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..core.autograd import no_grad
from ..core.tensor import Parameter, Tensor
from .lr import LRScheduler


class Optimizer:
    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None, grad_clip=None, name=None):
        if parameters is None:
            from ..static.graph import in_static_mode

            if not in_static_mode():
                raise ValueError("parameters must be provided in eager mode (parity: dygraph optimizer)")
            parameters = []
        self._parameter_list = list(parameters)
        self._learning_rate = learning_rate
        self._grad_clip = grad_clip
        self._decay_obj = None
        if weight_decay is None:
            self._weight_decay = 0.0
        elif isinstance(weight_decay, (int, float)):
            self._weight_decay = float(weight_decay)
        else:  # L1Decay/L2Decay-like object: keep it so grad_term applies
            self._weight_decay = float(getattr(weight_decay, "_coeff", getattr(weight_decay, "coeff", 0.0)))
            if hasattr(weight_decay, "grad_term"):
                self._decay_obj = weight_decay
        self._accumulators: Dict[str, Dict[int, jax.Array]] = {}
        self._step_count = 0

    # -- lr --
    def get_lr(self) -> float:
        if isinstance(self._learning_rate, LRScheduler):
            return self._learning_rate()
        return float(self._learning_rate)

    def set_lr(self, value: float):
        if isinstance(self._learning_rate, LRScheduler):
            raise RuntimeError("cannot set_lr when using an LRScheduler")
        self._learning_rate = float(value)

    def set_lr_scheduler(self, scheduler):
        self._learning_rate = scheduler

    # -- state --
    def _acc(self, name: str, p: Parameter, init=jnp.zeros_like) -> jax.Array:
        store = self._accumulators.setdefault(name, {})
        key = id(p)
        if key not in store:
            store[key] = init(p._data)
        return store[key]

    def _set_acc(self, name: str, p: Parameter, value):
        self._accumulators[name][id(p)] = value

    def state_dict(self):
        out = {}
        for name, store in self._accumulators.items():
            for p in self._parameter_list:
                if id(p) in store:
                    # copy: update kernels donate state buffers, so aliasing
                    # the live accumulator would invalidate the checkpoint
                    out[f"{p.name}_{name}"] = Tensor(jnp.copy(store[id(p)]))
        out["@step"] = self._step_count
        if isinstance(self._learning_rate, LRScheduler):
            out["LR_Scheduler"] = self._learning_rate.state_dict()
        return out

    def set_state_dict(self, state):
        self._step_count = int(state.get("@step", 0))
        for name, store in list(self._accumulators.items()):
            store.clear()
        # Accumulator names are inferred from the checkpoint keys (strip
        # the longest matching parameter-name prefix) instead of a fixed
        # name list, so any optimizer's state — mean_square, inf_norm,
        # step_size, … — restores into a FRESH instance that has not
        # created its accumulators yet (fault-tolerant resume path).
        params = sorted(self._parameter_list, key=lambda p: -len(p.name))
        for k, v in state.items():
            if k in ("@step", "LR_Scheduler"):
                continue
            for p in params:
                if k.startswith(p.name + "_"):
                    name = k[len(p.name) + 1:]
                    self._accumulators.setdefault(name, {})[id(p)] = jnp.copy(
                        v._data if isinstance(v, Tensor) else jnp.asarray(v))
                    break
        if "LR_Scheduler" in state and isinstance(self._learning_rate, LRScheduler):
            self._learning_rate.set_state_dict(state["LR_Scheduler"])

    def _known_accumulators(self) -> Sequence[str]:
        return list(self._accumulators.keys()) or ["moment", "moment1", "moment2", "velocity", "avg_squared"]

    # -- step --
    def _collect_params_grads(self):
        pg = []
        for p in self._parameter_list:
            if p.stop_gradient:
                continue
            g = p.grad
            pg.append((p, g))
        return pg

    @no_grad()
    def step(self):
        pg = self._collect_params_grads()
        if self._grad_clip is not None:
            pg = self._grad_clip(pg)
        self._step_count += 1
        for p, g in pg:
            if g is None:
                continue
            self._update_param(p, g._data)

    def _update_param(self, p: Parameter, g: jax.Array):
        raise NotImplementedError

    @no_grad()
    def clear_grad(self, set_to_zero: bool = False):
        for p in self._parameter_list:
            p.clear_gradient(set_to_zero)

    clear_gradients = clear_grad

    def minimize(self, loss, startup_program=None, parameters=None, no_grad_set=None):
        from ..static.graph import Variable as _StaticVariable, static_minimize

        if isinstance(loss, _StaticVariable):
            return static_minimize(self, loss)
        loss.backward()
        self.step()
        return None, None

    def _apply_decay(self, p, g):
        """Coupled decay (SGD/Momentum/Adam semantics of `weight_decay`):
        a regularizer object supplies its own gradient term (L1 -> sign)."""
        if self._decay_obj is not None:
            return g + self._decay_obj.grad_term(p._data).astype(g.dtype)
        if self._weight_decay:
            return g + self._weight_decay * p._data.astype(g.dtype)
        return g


@functools.partial(jax.jit, donate_argnums=(0,))
def _sgd_update(param, grad, lr):
    return param - lr.astype(param.dtype) * grad.astype(param.dtype)


class SGD(Optimizer):
    def _update_param(self, p, g):
        g = self._apply_decay(p, g)
        p._data = _sgd_update(p._data, g, jnp.asarray(self.get_lr(), jnp.float32))


@functools.partial(jax.jit, donate_argnums=(0, 2), static_argnums=(4, 5))
def _momentum_update(param, grad, velocity, lr, mu, use_nesterov):
    g = grad.astype(param.dtype)
    v = mu * velocity + g
    if use_nesterov:
        new_p = param - lr.astype(param.dtype) * (g + mu * v)
    else:
        new_p = param - lr.astype(param.dtype) * v
    return new_p, v


class Momentum(Optimizer):
    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None, use_nesterov=False,
                 weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._momentum = momentum
        self._use_nesterov = use_nesterov

    def _update_param(self, p, g):
        g = self._apply_decay(p, g)
        v = self._acc("velocity", p)
        p._data, v = _momentum_update(p._data, g, v, jnp.asarray(self.get_lr(), jnp.float32),
                                      self._momentum, self._use_nesterov)
        self._set_acc("velocity", p, v)


@functools.partial(jax.jit, donate_argnums=(0, 2, 3))
def _adam_update(param, grad, m, v, lr, beta1, beta2, eps, t):
    g = grad.astype(jnp.float32)
    p32 = param.astype(jnp.float32)
    m = beta1 * m + (1 - beta1) * g
    v = beta2 * v + (1 - beta2) * jnp.square(g)
    mhat = m / (1 - beta1**t)
    vhat = v / (1 - beta2**t)
    new_p = p32 - lr * mhat / (jnp.sqrt(vhat) + eps)
    return new_p.astype(param.dtype), m, v


class Adam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-08, parameters=None,
                 weight_decay=None, grad_clip=None, lazy_mode=False, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon

    def _f32_zeros(self, x):
        return jnp.zeros(x.shape, jnp.float32)

    def _update_param(self, p, g):
        g = self._apply_decay(p, g)
        m = self._acc("moment1", p, self._f32_zeros)
        v = self._acc("moment2", p, self._f32_zeros)
        p._data, m, v = _adam_update(
            p._data, g, m, v,
            jnp.asarray(self.get_lr(), jnp.float32),
            jnp.asarray(self._beta1, jnp.float32), jnp.asarray(self._beta2, jnp.float32),
            jnp.asarray(self._epsilon, jnp.float32), jnp.asarray(self._step_count, jnp.float32))
        self._set_acc("moment1", p, m)
        self._set_acc("moment2", p, v)


def _adamw_update_math(param, grad, m, v, lr, beta1, beta2, eps, t, wd, lr_ratio):
    # raw (unjitted) form: reused by the host-offload path, which wraps it
    # in its own jit with pinned_host in/out shardings (distributed/sharding.py)
    g = grad.astype(jnp.float32)
    p32 = param.astype(jnp.float32)
    p32 = p32 * (1 - lr * lr_ratio * wd)  # decoupled decay
    m = beta1 * m + (1 - beta1) * g
    v = beta2 * v + (1 - beta2) * jnp.square(g)
    mhat = m / (1 - beta1**t)
    vhat = v / (1 - beta2**t)
    new_p = p32 - lr * lr_ratio * mhat / (jnp.sqrt(vhat) + eps)
    return new_p.astype(param.dtype), m, v


_adamw_update = functools.partial(jax.jit, donate_argnums=(0, 2, 3))(
    _adamw_update_math)


class AdamW(Optimizer):
    """Parity: python/paddle/optimizer/adamw.py (decoupled weight decay,
    apply_decay_param_fun filter, lr_ratio)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-08, parameters=None,
                 weight_decay=0.01, lr_ratio=None, apply_decay_param_fun=None, grad_clip=None,
                 lazy_mode=False, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip, name)
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon
        self._wd = float(weight_decay) if isinstance(weight_decay, (int, float)) else float(getattr(weight_decay, "_coeff", 0.01))
        self._lr_ratio = lr_ratio
        self._apply_decay_param_fun = apply_decay_param_fun

    def _f32_zeros(self, x):
        return jnp.zeros(x.shape, jnp.float32)

    def _update_param(self, p, g):
        wd = self._wd
        if self._apply_decay_param_fun is not None and not self._apply_decay_param_fun(p.name):
            wd = 0.0
        lr_ratio = 1.0 if self._lr_ratio is None else float(self._lr_ratio(p))
        m = self._acc("moment1", p, self._f32_zeros)
        v = self._acc("moment2", p, self._f32_zeros)
        p._data, m, v = _adamw_update(
            p._data, g, m, v,
            jnp.asarray(self.get_lr(), jnp.float32),
            jnp.asarray(self._beta1, jnp.float32), jnp.asarray(self._beta2, jnp.float32),
            jnp.asarray(self._epsilon, jnp.float32), jnp.asarray(self._step_count, jnp.float32),
            jnp.asarray(wd, jnp.float32), jnp.asarray(lr_ratio, jnp.float32))
        self._set_acc("moment1", p, m)
        self._set_acc("moment2", p, v)


@functools.partial(jax.jit, donate_argnums=(0, 2))
def _adagrad_update(param, grad, moment, lr, eps):
    g = grad.astype(jnp.float32)
    moment = moment + jnp.square(g)
    new_p = param.astype(jnp.float32) - lr * g / (jnp.sqrt(moment) + eps)
    return new_p.astype(param.dtype), moment


class Adagrad(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-06, parameters=None, weight_decay=None,
                 grad_clip=None, initial_accumulator_value=0.0, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._epsilon = epsilon
        self._init_val = initial_accumulator_value

    def _update_param(self, p, g):
        g = self._apply_decay(p, g)
        mom = self._acc("moment", p, lambda x: jnp.full(x.shape, self._init_val, jnp.float32))
        p._data, mom = _adagrad_update(p._data, g, mom, jnp.asarray(self.get_lr(), jnp.float32),
                                       jnp.asarray(self._epsilon, jnp.float32))
        self._set_acc("moment", p, mom)


@functools.partial(jax.jit, donate_argnums=(0, 2))
def _rmsprop_update(param, grad, mean_sq, lr, rho, eps, centered, mean_g, momentum, velocity):
    g = grad.astype(jnp.float32)
    mean_sq = rho * mean_sq + (1 - rho) * jnp.square(g)
    denom = mean_sq
    mean_g = rho * mean_g + (1 - rho) * g
    denom = jnp.where(centered, mean_sq - jnp.square(mean_g), mean_sq)
    v = momentum * velocity + lr * g / jnp.sqrt(denom + eps)
    new_p = param.astype(jnp.float32) - v
    return new_p.astype(param.dtype), mean_sq, mean_g, v


class RMSProp(Optimizer):
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-06, momentum=0.0, centered=False,
                 parameters=None, weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._rho = rho
        self._epsilon = epsilon
        self._momentum = momentum
        self._centered = centered

    def _f32_zeros(self, x):
        return jnp.zeros(x.shape, jnp.float32)

    def _update_param(self, p, g):
        g = self._apply_decay(p, g)
        ms = self._acc("mean_square", p, self._f32_zeros)
        mg = self._acc("mean_grad", p, self._f32_zeros)
        v = self._acc("velocity", p, self._f32_zeros)
        p._data, ms, mg, v = _rmsprop_update(
            p._data, g, ms, jnp.asarray(self.get_lr(), jnp.float32),
            jnp.asarray(self._rho, jnp.float32), jnp.asarray(self._epsilon, jnp.float32),
            jnp.asarray(self._centered), mg, jnp.asarray(self._momentum, jnp.float32), v)
        self._set_acc("mean_square", p, ms)
        self._set_acc("mean_grad", p, mg)
        self._set_acc("velocity", p, v)


@functools.partial(jax.jit, donate_argnums=(0, 2, 3))
def _adadelta_update(param, grad, avg_sq_grad, avg_sq_update, rho, eps):
    g = grad.astype(jnp.float32)
    avg_sq_grad = rho * avg_sq_grad + (1 - rho) * jnp.square(g)
    update = jnp.sqrt(avg_sq_update + eps) / jnp.sqrt(avg_sq_grad + eps) * g
    avg_sq_update = rho * avg_sq_update + (1 - rho) * jnp.square(update)
    new_p = param.astype(jnp.float32) - update
    return new_p.astype(param.dtype), avg_sq_grad, avg_sq_update


class Adadelta(Optimizer):
    def __init__(self, learning_rate=0.001, epsilon=1e-06, rho=0.95, parameters=None,
                 weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._rho = rho
        self._epsilon = epsilon

    def _f32_zeros(self, x):
        return jnp.zeros(x.shape, jnp.float32)

    def _update_param(self, p, g):
        g = self._apply_decay(p, g)
        asg = self._acc("avg_squared_grad", p, self._f32_zeros)
        asu = self._acc("avg_squared_update", p, self._f32_zeros)
        p._data, asg, asu = _adadelta_update(p._data, g, asg, asu,
                                             jnp.asarray(self._rho, jnp.float32),
                                             jnp.asarray(self._epsilon, jnp.float32))
        self._set_acc("avg_squared_grad", p, asg)
        self._set_acc("avg_squared_update", p, asu)


@functools.partial(jax.jit, donate_argnums=(0, 2, 3))
def _adamax_update(param, grad, m, inf_norm, lr, beta1, beta2, eps, t):
    g = grad.astype(jnp.float32)
    m = beta1 * m + (1 - beta1) * g
    inf_norm = jnp.maximum(beta2 * inf_norm, jnp.abs(g))
    new_p = param.astype(jnp.float32) - (lr / (1 - beta1**t)) * m / (inf_norm + eps)
    return new_p.astype(param.dtype), m, inf_norm


class Adamax(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-08, parameters=None,
                 weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _f32_zeros(self, x):
        return jnp.zeros(x.shape, jnp.float32)

    def _update_param(self, p, g):
        g = self._apply_decay(p, g)
        m = self._acc("moment", p, self._f32_zeros)
        inf = self._acc("inf_norm", p, self._f32_zeros)
        p._data, m, inf = _adamax_update(p._data, g, m, inf,
                                         jnp.asarray(self.get_lr(), jnp.float32),
                                         jnp.asarray(self._beta1, jnp.float32),
                                         jnp.asarray(self._beta2, jnp.float32),
                                         jnp.asarray(self._epsilon, jnp.float32),
                                         jnp.asarray(self._step_count, jnp.float32))
        self._set_acc("moment", p, m)
        self._set_acc("inf_norm", p, inf)


@functools.partial(jax.jit, donate_argnums=(0, 2, 3))
def _lamb_update(param, grad, m, v, lr, beta1, beta2, eps, t, wd):
    g = grad.astype(jnp.float32)
    p32 = param.astype(jnp.float32)
    m = beta1 * m + (1 - beta1) * g
    v = beta2 * v + (1 - beta2) * jnp.square(g)
    mhat = m / (1 - beta1**t)
    vhat = v / (1 - beta2**t)
    r = mhat / (jnp.sqrt(vhat) + eps) + wd * p32
    w_norm = jnp.sqrt(jnp.sum(jnp.square(p32)))
    r_norm = jnp.sqrt(jnp.sum(jnp.square(r)))
    ratio = jnp.where((w_norm > 0) & (r_norm > 0), w_norm / r_norm, 1.0)
    new_p = p32 - lr * ratio * r
    return new_p.astype(param.dtype), m, v


class Lamb(Optimizer):
    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01, beta1=0.9, beta2=0.999,
                 epsilon=1e-06, parameters=None, grad_clip=None, exclude_from_weight_decay_fn=None,
                 name=None):
        super().__init__(learning_rate, parameters, None, grad_clip, name)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon
        self._lamb_wd = lamb_weight_decay
        self._exclude_fn = exclude_from_weight_decay_fn

    def _f32_zeros(self, x):
        return jnp.zeros(x.shape, jnp.float32)

    def _update_param(self, p, g):
        wd = self._lamb_wd
        if self._exclude_fn is not None and self._exclude_fn(p):
            wd = 0.0
        m = self._acc("moment1", p, self._f32_zeros)
        v = self._acc("moment2", p, self._f32_zeros)
        p._data, m, v = _lamb_update(p._data, g, m, v,
                                     jnp.asarray(self.get_lr(), jnp.float32),
                                     jnp.asarray(self._beta1, jnp.float32),
                                     jnp.asarray(self._beta2, jnp.float32),
                                     jnp.asarray(self._epsilon, jnp.float32),
                                     jnp.asarray(self._step_count, jnp.float32),
                                     jnp.asarray(wd, jnp.float32))
        self._set_acc("moment1", p, m)
        self._set_acc("moment2", p, v)


@functools.partial(jax.jit, donate_argnums=(0, 2, 3))
def _rprop_update(param, grad, prev_grad, step_size, lr_min, lr_max, eta_n, eta_p):
    sign = jnp.sign(grad * prev_grad)
    new_step = jnp.clip(jnp.where(sign > 0, step_size * eta_p,
                                  jnp.where(sign < 0, step_size * eta_n, step_size)),
                        lr_min, lr_max)
    g_eff = jnp.where(sign < 0, 0.0, grad)
    new_param = param - jnp.sign(g_eff).astype(param.dtype) * new_step.astype(param.dtype)
    new_prev = jnp.where(sign < 0, 0.0, grad)
    return new_param, new_prev, new_step


class Rprop(Optimizer):
    """Resilient backprop (parity: python/paddle/optimizer/rprop.py)."""

    def __init__(self, learning_rate=0.001, learning_rate_range=(1e-5, 50.0), parameters=None,
                 etas=(0.5, 1.2), grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip, name)
        self._lr_min, self._lr_max = learning_rate_range
        self._eta_n, self._eta_p = etas

    def _update_param(self, p, g):
        g = g.astype(jnp.float32)
        prev = self._acc("prev_grad", p, lambda x: jnp.zeros(x.shape, jnp.float32))
        step = self._acc("step_size", p,
                         lambda x: jnp.full(x.shape, float(self.get_lr()), jnp.float32))
        p._data, prev, step = _rprop_update(p._data, g, prev, step,
                                            jnp.float32(self._lr_min), jnp.float32(self._lr_max),
                                            jnp.float32(self._eta_n), jnp.float32(self._eta_p))
        self._set_acc("prev_grad", p, prev)
        self._set_acc("step_size", p, step)


@functools.partial(jax.jit, donate_argnums=(0, 2))
def _asgd_update(param, grad, avg, lr, t0_passed, n_avg):
    new_param = param - lr.astype(param.dtype) * grad.astype(param.dtype)
    new_avg = jnp.where(t0_passed, avg + (new_param.astype(jnp.float32) - avg) / n_avg, 
                        new_param.astype(jnp.float32))
    return new_param, new_avg


class ASGD(Optimizer):
    """Averaged SGD (parity: python/paddle/optimizer/asgd.py)."""

    def __init__(self, learning_rate=0.001, batch_num=1, parameters=None, weight_decay=None,
                 grad_clip=None, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._t0 = batch_num

    def _update_param(self, p, g):
        g = self._apply_decay(p, g.astype(jnp.float32))
        # copy: donation would otherwise see param and avg as one buffer
        avg = self._acc("averaged_param", p, lambda x: jnp.array(x, jnp.float32, copy=True))
        n_avg = max(self._step_count - self._t0, 1)
        p._data, avg = _asgd_update(p._data, g, avg, jnp.asarray(self.get_lr(), jnp.float32),
                                    jnp.asarray(self._step_count > self._t0),
                                    jnp.asarray(float(n_avg), jnp.float32))
        self._set_acc("averaged_param", p, avg)

    def averaged_parameters(self):
        # copy: the update kernel donates the accumulator buffer next step
        return {p.name: Tensor(jnp.copy(self._accumulators["averaged_param"][id(p)]))
                for p in self._parameter_list if id(p) in self._accumulators.get("averaged_param", {})}


@functools.partial(jax.jit, donate_argnums=(0, 2, 3))
def _nadam_update(param, grad, m, v, lr, beta1, beta2, eps, t, mu_prod, psi):
    g = grad.astype(jnp.float32)
    mu_t = beta1 * (1 - 0.5 * 0.96 ** (t * psi))
    mu_t1 = beta1 * (1 - 0.5 * 0.96 ** ((t + 1) * psi))
    new_mu_prod = mu_prod * mu_t
    m = beta1 * m + (1 - beta1) * g
    v = beta2 * v + (1 - beta2) * g * g
    m_hat = mu_t1 * m / (1 - new_mu_prod * mu_t1) + (1 - mu_t) * g / (1 - new_mu_prod)
    v_hat = v / (1 - beta2 ** t)
    upd = lr * m_hat / (jnp.sqrt(v_hat) + eps)
    return param - upd.astype(param.dtype), m, v, new_mu_prod


class NAdam(Optimizer):
    """Nesterov Adam (parity: python/paddle/optimizer/nadam.py)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 momentum_decay=0.004, parameters=None, weight_decay=None, grad_clip=None,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon
        self._momentum_decay = momentum_decay

    def _update_param(self, p, g):
        g = self._apply_decay(p, g.astype(jnp.float32))
        m = self._acc("momentum_decay_pow", p, lambda x: jnp.ones((), jnp.float32))
        m1 = self._acc("moment1", p, lambda x: jnp.zeros(x.shape, jnp.float32))
        m2 = self._acc("moment2", p, lambda x: jnp.zeros(x.shape, jnp.float32))
        p._data, m1, m2, mu_prod = _nadam_update(
            p._data, g, m1, m2, jnp.asarray(self.get_lr(), jnp.float32),
            jnp.float32(self._beta1), jnp.float32(self._beta2), jnp.float32(self._epsilon),
            jnp.asarray(float(self._step_count), jnp.float32), m,
            jnp.float32(self._momentum_decay))
        self._set_acc("moment1", p, m1)
        self._set_acc("moment2", p, m2)
        self._set_acc("momentum_decay_pow", p, mu_prod)


@functools.partial(jax.jit, donate_argnums=(0, 2, 3))
def _radam_update(param, grad, m, v, lr, beta1, beta2, eps, t):
    g = grad.astype(jnp.float32)
    m = beta1 * m + (1 - beta1) * g
    v = beta2 * v + (1 - beta2) * g * g
    m_hat = m / (1 - beta1 ** t)
    rho_inf = 2.0 / (1 - beta2) - 1
    rho_t = rho_inf - 2 * t * beta2 ** t / (1 - beta2 ** t)
    r = jnp.sqrt((rho_t - 4) * (rho_t - 2) * rho_inf /
                 jnp.maximum((rho_inf - 4) * (rho_inf - 2) * rho_t, 1e-8))
    v_hat = jnp.sqrt(v / (1 - beta2 ** t))
    upd = jnp.where(rho_t > 5.0, lr * r * m_hat / (v_hat + eps), lr * m_hat)
    return param - upd.astype(param.dtype), m, v


class RAdam(Optimizer):
    """Rectified Adam (parity: python/paddle/optimizer/radam.py)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 parameters=None, weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _update_param(self, p, g):
        g = self._apply_decay(p, g.astype(jnp.float32))
        m1 = self._acc("moment1", p, lambda x: jnp.zeros(x.shape, jnp.float32))
        m2 = self._acc("moment2", p, lambda x: jnp.zeros(x.shape, jnp.float32))
        p._data, m1, m2 = _radam_update(
            p._data, g, m1, m2, jnp.asarray(self.get_lr(), jnp.float32),
            jnp.float32(self._beta1), jnp.float32(self._beta2), jnp.float32(self._epsilon),
            jnp.asarray(float(self._step_count), jnp.float32))
        self._set_acc("moment1", p, m1)
        self._set_acc("moment2", p, m2)


@functools.partial(jax.jit, donate_argnums=(0, 2))
def _lars_update(param, grad, vel, lr, mu, lars_coeff, wd, eps):
    g = grad.astype(jnp.float32)
    pf = param.astype(jnp.float32)
    p_norm = jnp.linalg.norm(pf)
    g_norm = jnp.linalg.norm(g)
    local_lr = jnp.where((p_norm > 0) & (g_norm > 0),
                         lars_coeff * p_norm / (g_norm + wd * p_norm + eps), 1.0)
    v = mu * vel + lr * local_lr * (g + wd * pf)
    return (pf - v).astype(param.dtype), v


class Lars(Optimizer):
    """LARS momentum (parity: fluid lars_momentum op /
    paddle.incubate LarsMomentumOptimizer)."""

    def __init__(self, learning_rate=0.001, momentum=0.9, lars_coeff=0.001,
                 lars_weight_decay=0.0005, parameters=None, grad_clip=None,
                 exclude_from_weight_decay=None, epsilon=1e-9, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip, name)
        self._momentum = momentum
        self._lars_coeff = lars_coeff
        self._lars_wd = lars_weight_decay
        self._epsilon = epsilon
        self._exclude = list(exclude_from_weight_decay or [])

    def _update_param(self, p, g):
        wd = 0.0 if any(e in p.name for e in self._exclude) else self._lars_wd
        v = self._acc("velocity", p, lambda x: jnp.zeros(x.shape, jnp.float32))
        p._data, v = _lars_update(p._data, g, v, jnp.asarray(self.get_lr(), jnp.float32),
                                  jnp.float32(self._momentum), jnp.float32(self._lars_coeff),
                                  jnp.float32(wd), jnp.float32(self._epsilon))
        self._set_acc("velocity", p, v)


class LBFGS(Optimizer):
    """Limited-memory BFGS with closure-based step (parity:
    python/paddle/optimizer/lbfgs.py — full-batch two-loop recursion with
    strong-Wolfe or fixed-step line search)."""

    def __init__(self, learning_rate=1.0, max_iter=20, max_eval=None, tolerance_grad=1e-7,
                 tolerance_change=1e-9, history_size=100, line_search_fn=None,
                 parameters=None, weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._max_iter = max_iter
        self._tol_grad = tolerance_grad
        self._tol_change = tolerance_change
        self._history = history_size
        self._line_search = line_search_fn
        self._s: list = []
        self._y: list = []
        self._prev_flat = None
        self._prev_grad = None

    def _flatten(self, tensors):
        return jnp.concatenate([jnp.ravel(t.astype(jnp.float32)) for t in tensors])

    def _unflatten_to_params(self, flat):
        off = 0
        for p in self._parameter_list:
            n = int(np.prod(p.shape)) if p.shape else 1
            p._data = flat[off: off + n].reshape(p.shape).astype(p._data.dtype)
            off += n

    def _eval(self, closure):
        """Evaluate closure; return (loss, flat params, flat grads) with
        weight decay and grad clip applied (reference parity)."""
        loss = closure()
        params = self._parameter_list
        pg = [(p, p.grad) for p in params]
        if self._grad_clip is not None:
            pg = self._grad_clip([(p, g) for p, g in pg if g is not None])
            grads_by_id = {id(p): g for p, g in pg}
            pg = [(p, grads_by_id.get(id(p))) for p in params]
        flat = self._flatten([p._data for p in params])
        grad = self._flatten([
            self._apply_decay(p, (g._data if g is not None else jnp.zeros(p.shape)).astype(jnp.float32))
            for p, g in pg])
        return loss, flat, grad

    def step(self, closure=None):
        if closure is None:
            raise ValueError("LBFGS.step requires a closure returning the loss")
        loss, flat, grad = self._eval(closure)
        first_loss = loss
        for _ in range(self._max_iter):
            if float(jnp.abs(grad).max()) <= self._tol_grad:
                break
            if self._prev_flat is not None:
                s = flat - self._prev_flat
                y = grad - self._prev_grad
                if float(s @ y) > 1e-10:
                    self._s.append(s)
                    self._y.append(y)
                    if len(self._s) > self._history:
                        self._s.pop(0)
                        self._y.pop(0)
            # two-loop recursion
            q = grad
            alphas = []
            for s, y in zip(reversed(self._s), reversed(self._y)):
                rho = 1.0 / (s @ y)
                a = rho * (s @ q)
                alphas.append((a, rho, s, y))
                q = q - a * y
            if self._s:
                s, y = self._s[-1], self._y[-1]
                q = q * (s @ y) / jnp.maximum(y @ y, 1e-10)
            for a, rho, s, y in reversed(alphas):
                b = rho * (y @ q)
                q = q + (a - b) * s
            direction = -q
            lr = float(self.get_lr())
            if self._line_search == "strong_wolfe":
                lr = self._backtrack(closure, float(loss.numpy()), flat, grad, direction, lr)
            self._prev_flat = flat
            self._prev_grad = grad
            delta = lr * direction
            self._unflatten_to_params(flat + delta)
            if float(jnp.abs(delta).max()) <= self._tol_change:
                break
            new_loss, flat, grad = self._eval(closure)
            if abs(float(new_loss.numpy()) - float(loss.numpy())) <= self._tol_change:
                loss = new_loss
                break
            loss = new_loss
        self._step_count += 1
        return first_loss

    def _backtrack(self, closure, base, flat, grad, direction, lr, c1=1e-4, shrink=0.5, iters=10):
        """Armijo backtracking; reuses the already-computed base loss."""
        slope = float(grad @ direction)
        for _ in range(iters):
            self._unflatten_to_params(flat + lr * direction)
            trial = float(closure().numpy())
            if trial <= base + c1 * lr * slope:
                break
            lr *= shrink
        self._unflatten_to_params(flat)
        return lr
