"""Functional (pure) optimizer kernels for whole-program training steps.

The eager optimizers (optimizer.py) mutate per-parameter state host-side;
for pjit/GSPMD training the entire step must be one compiled program, so
these pure init/update pairs mirror the same update rules over pytrees.
The split mirrors the reference's dual structure: eager optimizer ops vs
static-graph optimizer passes (reference: python/paddle/optimizer/
optimizer.py _append_optimize_op dygraph-vs-static branches).

State layout note: state pytrees mirror the param pytree, so ZeRO-style
optimizer-state sharding = sharding the state pytree over the 'dp'/
'sharding' mesh axis (reference semantics: DygraphShardingOptimizer).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp


class FunctionalOptimizer(NamedTuple):
    init: Callable  # params -> state
    update: Callable  # (grads, state, params, lr) -> (new_params, new_state)


def _tree_f32_zeros(params):
    return jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32), params)


def _path_name(path):
    """Parameter name from tree-path entries (DictKey.key for dict trees;
    keystr-ish fallback for others) — shared by the decay-mask lookups."""
    return ".".join(str(getattr(k, "key", getattr(k, "idx", k)))
                    for k in path)


def sgd(weight_decay: float = 0.0) -> FunctionalOptimizer:
    def init(params):
        return {}

    def update(grads, state, params, lr):
        def upd(p, g):
            if g is None:
                return p
            g = g.astype(p.dtype)
            if weight_decay:
                g = g + weight_decay * p
            return p - lr.astype(p.dtype) * g

        return jax.tree.map(upd, params, grads), state

    return FunctionalOptimizer(init, update)


def momentum(mu: float = 0.9, weight_decay: float = 0.0, use_nesterov: bool = False) -> FunctionalOptimizer:
    def init(params):
        return {"velocity": _tree_f32_zeros(params)}

    def update(grads, state, params, lr):
        p_flat, treedef = jax.tree.flatten(params)
        g_flat = treedef.flatten_up_to(grads)
        v_flat = treedef.flatten_up_to(state["velocity"])
        new_p, new_v = [], []
        for p, g, v in zip(p_flat, g_flat, v_flat):
            if g is None:
                new_p.append(p)
                new_v.append(v)
                continue
            g32 = g.astype(jnp.float32)
            if weight_decay:
                g32 = g32 + weight_decay * p.astype(jnp.float32)
            v_n = mu * v + g32
            step = (g32 + mu * v_n) if use_nesterov else v_n
            new_p.append((p.astype(jnp.float32) - lr * step).astype(p.dtype))
            new_v.append(v_n)
        return treedef.unflatten(new_p), {"velocity": treedef.unflatten(new_v)}

    return FunctionalOptimizer(init, update)


def adamw(beta1: float = 0.9, beta2: float = 0.999, epsilon: float = 1e-8,
          weight_decay: float = 0.01, decay_mask_fn: Optional[Callable] = None) -> FunctionalOptimizer:
    """AdamW with fp32 master state (bf16 params supported). decay_mask_fn:
    param-name predicate (parity: apply_decay_param_fun)."""

    def init(params):
        return {
            "m": _tree_f32_zeros(params),
            "v": _tree_f32_zeros(params),
            "t": jnp.zeros((), jnp.float32),
        }

    def update(grads, state, params, lr):
        t = state["t"] + 1.0
        p_flat_path, treedef = jax.tree_util.tree_flatten_with_path(params)
        g_flat = treedef.flatten_up_to(grads)
        m_flat = treedef.flatten_up_to(state["m"])
        v_flat = treedef.flatten_up_to(state["v"])
        new_p, new_m, new_v = [], [], []
        for (path, p), g, m, v in zip(p_flat_path, g_flat, m_flat, v_flat):
            if g is None:
                new_p.append(p)
                new_m.append(m)
                new_v.append(v)
                continue
            g32 = g.astype(jnp.float32)
            p32 = p.astype(jnp.float32)
            wd = weight_decay
            if decay_mask_fn is not None and not decay_mask_fn(_path_name(path)):
                wd = 0.0
            p32 = p32 * (1.0 - lr * wd)
            m_n = beta1 * m + (1 - beta1) * g32
            v_n = beta2 * v + (1 - beta2) * jnp.square(g32)
            mhat = m_n / (1 - beta1**t)
            vhat = v_n / (1 - beta2**t)
            new_p.append((p32 - lr * mhat / (jnp.sqrt(vhat) + epsilon)).astype(p.dtype))
            new_m.append(m_n)
            new_v.append(v_n)
        return treedef.unflatten(new_p), {"m": treedef.unflatten(new_m),
                                          "v": treedef.unflatten(new_v), "t": t}

    return FunctionalOptimizer(init, update)


def adam(beta1: float = 0.9, beta2: float = 0.999, epsilon: float = 1e-8,
         weight_decay: float = 0.0) -> FunctionalOptimizer:
    base = adamw(beta1, beta2, epsilon, weight_decay=0.0)

    def update(grads, state, params, lr):
        if weight_decay:
            grads = jax.tree.map(
                lambda g, p: None if g is None else g + weight_decay * p.astype(g.dtype), grads, params)
        return base.update(grads, state, params, lr)

    return FunctionalOptimizer(base.init, update)


def clip_by_global_norm(grads, clip_norm: float):
    """Pure global-norm clip over a grad pytree (parity:
    ClipGradByGlobalNorm inside compiled steps)."""
    leaves = [g for g in jax.tree.leaves(grads) if g is not None]
    total = sum(jnp.sum(jnp.square(g.astype(jnp.float32))) for g in leaves)
    gnorm = jnp.sqrt(total)
    scale = clip_norm / jnp.maximum(gnorm, clip_norm)
    return jax.tree.map(lambda g: None if g is None else (g.astype(jnp.float32) * scale).astype(g.dtype), grads), gnorm


def adamw_flat(beta1: float = 0.9, beta2: float = 0.999, epsilon: float = 1e-8,
               weight_decay: float = 0.01,
               decay_mask_fn: Optional[Callable] = None) -> FunctionalOptimizer:
    """AdamW with the update FUSED across parameters: leaves sharing a
    (shape, dtype, wd) signature update as ONE stacked launch, and their
    m/v state lives stacked — a transformer's 12x-repeated layer weights
    collapse from ~111 tiny launch chains into ~10 vectorized ones.

    Rationale (v5e profile of the 134M bench step): the per-leaf update
    ran as ~111 sequential `subtract_convert_fusion` launches costing
    22.9 ms of a 128 ms step (18%) — launch latency, not math (HBM-bound
    floor is ~3.5 ms). Same-shape jnp.stack lowers to a single concat
    kernel (a mixed-size flat concat degenerates into a
    dynamic-update-slice chain — measured slower than the baseline).

    Numerics are identical to `adamw` (same math, same per-leaf wd mask;
    elementwise ops don't care about packing). The reference analogue is
    the fused multi_tensor adam path (distributed_fused_lamb /
    multi_tensor_apply)."""

    def _groups(params):
        """Leaf indices grouped by (shape, dtype, wd)."""
        p_flat_path, treedef = jax.tree_util.tree_flatten_with_path(params)
        groups = {}
        for i, (path, p) in enumerate(p_flat_path):
            wd = weight_decay if (decay_mask_fn is None
                                  or decay_mask_fn(_path_name(path))) else 0.0
            groups.setdefault((tuple(p.shape), str(p.dtype), wd),
                              []).append(i)
        return treedef, list(groups.items())

    def update(grads, state, params, lr):
        treedef, groups = _groups(params)
        p_leaves = treedef.flatten_up_to(params)
        g_leaves = treedef.flatten_up_to(grads)
        if any(g is None for g in g_leaves):
            raise ValueError("adamw_flat requires a gradient for every "
                             "parameter; use adamw for partial updates")
        t = state["t"] + 1.0
        new_p = [None] * len(p_leaves)
        new_m, new_v = dict(state["m"]), dict(state["v"])
        for gi, ((shape, dt, wd), idxs) in enumerate(groups):
            pg = jnp.stack([p_leaves[i] for i in idxs]).astype(jnp.float32)
            gg = jnp.stack([g_leaves[i] for i in idxs]).astype(jnp.float32)
            m = beta1 * state["m"][gi] + (1 - beta1) * gg
            v = beta2 * state["v"][gi] + (1 - beta2) * jnp.square(gg)
            mhat = m / (1 - beta1 ** t)
            vhat = v / (1 - beta2 ** t)
            out = (pg * (1.0 - lr * wd)
                   - lr * mhat / (jnp.sqrt(vhat) + epsilon)).astype(dt)
            for k, i in enumerate(idxs):
                new_p[i] = out[k]
            new_m[gi], new_v[gi] = m, v
        return treedef.unflatten(new_p), {"m": new_m, "v": new_v, "t": t}

    def init(params):
        _, groups = _groups(params)
        return {
            "m": {gi: jnp.zeros((len(idxs),) + tuple(shape), jnp.float32)
                  for gi, ((shape, _dt, _wd), idxs) in enumerate(groups)},
            "v": {gi: jnp.zeros((len(idxs),) + tuple(shape), jnp.float32)
                  for gi, ((shape, _dt, _wd), idxs) in enumerate(groups)},
            "t": jnp.zeros((), jnp.float32),
        }

    return FunctionalOptimizer(init, update)


def from_eager(opt, fused: bool = False) -> FunctionalOptimizer:
    """Map an eager Optimizer instance to its functional twin.

    fused=True picks the flat cross-parameter AdamW (single launch chain;
    ~18% of the 134M bench step was per-leaf update launches). Only valid
    when the optimizer state does NOT need per-parameter placement (ZeRO
    state sharding keys placements by parameter)."""
    from . import optimizer as eager

    if isinstance(opt, eager.AdamW):
        fn = opt._apply_decay_param_fun
        if fused:
            return adamw_flat(opt._beta1, opt._beta2, opt._epsilon, opt._wd,
                              decay_mask_fn=fn)
        return adamw(opt._beta1, opt._beta2, opt._epsilon, opt._wd,
                     decay_mask_fn=fn)
    if fused:
        raise NotImplementedError(
            f"fused=True is implemented for AdamW only (got "
            f"{type(opt).__name__}) — silently falling back would "
            "misreport any A/B the caller runs")
    if isinstance(opt, eager.Adam):
        return adam(opt._beta1, opt._beta2, opt._epsilon, opt._weight_decay)
    if isinstance(opt, eager.Momentum):
        return momentum(opt._momentum, opt._weight_decay, opt._use_nesterov)
    if isinstance(opt, eager.SGD):
        return sgd(opt._weight_decay)
    raise NotImplementedError(f"no functional twin for {type(opt).__name__}")
