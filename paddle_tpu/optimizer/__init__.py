from . import lr
from .optimizer import (
    SGD, Adadelta, Adagrad, Adam, Adamax, AdamW, Lamb, Momentum, Optimizer, RMSProp,
)

__all__ = ["Optimizer", "SGD", "Momentum", "Adam", "AdamW", "Adagrad", "Adadelta",
           "Adamax", "RMSProp", "Lamb", "lr"]
