from . import lr
from .optimizer import (
    ASGD, LBFGS, SGD, Adadelta, Adagrad, Adam, Adamax, AdamW, Lamb, Lars, Momentum,
    NAdam, Optimizer, RAdam, RMSProp, Rprop,
)

__all__ = ["Optimizer", "SGD", "Momentum", "Adam", "AdamW", "Adagrad", "Adadelta",
           "Adamax", "RMSProp", "Lamb", "LBFGS", "Rprop", "ASGD", "NAdam", "RAdam",
           "Lars", "lr"]
