"""Opt-in stdlib HTTP front end for the serving engine — the same
zero-dependency ``http.server`` pattern as the observability scrape
endpoint (``observability.exporters.start_http_server``).

Token-level API (the framework has no tokenizer): prompts and
completions are lists of token ids.

- ``POST /generate`` body
  ``{"prompt": [ids], "max_new_tokens": 16, "do_sample": false,
     "temperature": 1.0, "top_k": 0, "top_p": 1.0, "eos_token_id": null,
     "seed": 0, "spec_k": null, "deadline_s": null, "stream": false}``
  (``spec_k`` is the per-request speculative override on draft-model
  engines: 0 opts out, null takes the engine default — outputs are
  identical either way, only throughput moves)
  -> ``{"request_id", "status", "prompt_len", "tokens", "ttft_s",
        "tpot_s", "latency_s", "spec_drafted", "spec_accepted"}``;
  with ``"stream": true`` the response
  is newline-delimited JSON, one ``{"token": id}`` line per token as it
  lands, then a final ``{"done": true, "status": ...}`` line.
- ``GET /healthz``  -> liveness + the serving gauges
  (slots busy/total, queue depth) as JSON.
- ``GET /stats``    -> ``engine.stats()`` (incl. the streaming latency
  digests — TTFT/TPOT/queue-wait/prefill-chunk p50/p95/p99 — and the
  goodput gauge).
- ``GET /trace``    -> the request-lifecycle trace as Chrome-trace
  (catapult) JSON — save it and load in chrome://tracing / Perfetto;
  ``?trace=<request_id>`` filters to one request's timeline.
- ``GET /debug/requests`` -> the live per-request state table (queued /
  running / recent-finished, with phase, KV blocks, waits, latencies).
- ``GET /debug/memory`` -> the HBM ledger: live device bytes attributed
  to subsystems (KV pools, model weights, executable temp/output sizes
  from the captured memory analyses), headroom vs ``bytes_limit``
  (``"unsupported"`` where PJRT reports nothing), plus the device peak
  table and the per-executable roofline ledger.

Backpressure maps to ``429``, invalid requests to ``400``.
Opt-in only: nothing starts this server implicitly.
"""

from __future__ import annotations

import json
import threading
import time

from ..observability import tracing as _tracing
from .scheduler import QueueFullError

__all__ = ["start_serving_http_server", "stop_serving_http_server"]

_server = None
_server_thread = None
_server_lock = threading.Lock()


def _request_record(req) -> dict:
    return {
        "request_id": req.id,
        "status": req.status,
        "prompt_len": int(req.prompt.shape[0]),
        "tokens": list(req.output_tokens),
        "ttft_s": req.ttft_s,
        "tpot_s": req.tpot_s,
        "latency_s": (req.finish_ts - req.arrival_ts
                      if req.finish_ts else None),
        "spec_drafted": req.spec_drafted,
        "spec_accepted": req.spec_accepted,
        "error": req.error,
    }


def start_serving_http_server(engine, port: int = 0, addr: str = "127.0.0.1",
                              request_timeout_s: float = 300.0) -> int:
    """Serve the engine over HTTP on a daemon thread; returns the bound
    port (``port=0`` picks a free one). Starts the engine's background
    loop if it isn't running (handlers block on ``Request.result``)."""
    global _server, _server_thread
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    engine.start()

    class _Handler(BaseHTTPRequestHandler):
        def _json(self, code: int, payload: dict):
            body = json.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            path = self.path.split("?")[0]
            if path == "/healthz":
                healthy = engine.healthy
                payload = {
                    "status": "ok" if healthy else "unhealthy",
                    "ts": time.time(),
                    "slots_busy": engine.busy_slots(),
                    "slots_total": engine.config.max_slots,
                    "queue_depth": engine.scheduler.depth,
                    "crashed": engine.crashed,
                }
                kv = getattr(engine, "kv_block_stats", lambda: None)()
                if kv is not None:  # paged engines: pool pressure at a
                    payload["kv_blocks_in_use"] = kv["in_use"]   # glance
                    payload["kv_blocks_total"] = kv["usable"]
                    payload["kv_blocks_shared"] = kv["shared"]
                    payload["kv_block_utilization"] = round(
                        kv["utilization"], 4)
                self._json(200 if healthy else 503, payload)
            elif path == "/stats":
                self._json(200, engine.stats())
            elif path == "/trace":
                # catapult JSON for chrome://tracing; ?trace=<id>
                # filters to one request's lanes
                trace = None
                query = self.path.partition("?")[2]
                for kv in query.split("&"):
                    k, _, v = kv.partition("=")
                    if k == "trace" and v:
                        try:
                            trace = int(v)
                        except ValueError:
                            trace = v
                self._json(200, _tracing.chrome_trace(trace))
            elif path == "/debug/requests":
                self._json(200, engine.debug_requests())
            elif path == "/debug/memory":
                from ..observability import perf as _perf

                self._json(200, {
                    "ts": time.time(),
                    "hbm": _perf.hbm_ledger(),
                    "peaks": _perf.peak_specs(),
                    "ledger": _perf.ledger(),
                })
            else:
                self._json(404, {"error": f"no such path {path!r}"})

        def do_POST(self):
            if self.path.split("?")[0] != "/generate":
                self._json(404, {"error": "POST /generate only"})
                return
            try:
                length = int(self.headers.get("Content-Length", 0))
                body = json.loads(self.rfile.read(length) or b"{}")
                prompt = body.pop("prompt")
                stream = bool(body.pop("stream", False))
                deadline_s = body.pop("deadline_s", None)
                if not isinstance(prompt, (list, tuple)) or not prompt:
                    raise ValueError("prompt must be a non-empty list of "
                                     "token ids")
            except (ValueError, KeyError, json.JSONDecodeError) as e:
                self._json(400, {"error": f"bad request: {e}"})
                return
            try:
                req = engine.submit(prompt, deadline_s=deadline_s, **body)
            except QueueFullError as e:
                self._json(429, {"error": str(e)})
                return
            except (TypeError, ValueError) as e:
                self._json(400, {"error": f"bad request: {e}"})
                return
            if not stream:
                try:
                    req.result(timeout=request_timeout_s)
                except TimeoutError:
                    req.cancel()
                    req.result(timeout=10.0)
                self._json(200, _request_record(req))
                return
            # streaming: newline-delimited JSON; no Content-Length, the
            # connection close marks the end (HTTP/1.0 framing)
            self.send_response(200)
            self.send_header("Content-Type", "application/jsonl")
            self.end_headers()
            try:
                for tok in req.stream(timeout=request_timeout_s):
                    self.wfile.write(
                        (json.dumps({"token": int(tok)}) + "\n").encode())
                    self.wfile.flush()
            except (TimeoutError, BrokenPipeError, ConnectionResetError):
                req.cancel()
            done = dict(_request_record(req))
            done["done"] = True
            try:
                self.wfile.write((json.dumps(done) + "\n").encode())
            except (BrokenPipeError, ConnectionResetError):
                pass

        def log_message(self, *args):  # no per-request stderr chatter
            pass

    with _server_lock:
        if _server is not None:
            return _server.server_address[1]
        _server = ThreadingHTTPServer((addr, port), _Handler)
        _server_thread = threading.Thread(target=_server.serve_forever,
                                          name="paddle-tpu-serving-http",
                                          daemon=True)
        _server_thread.start()
        return _server.server_address[1]


def stop_serving_http_server():
    global _server, _server_thread
    with _server_lock:
        if _server is not None:
            _server.shutdown()
            _server.server_close()
            _server = None
            _server_thread = None
