"""Opt-in stdlib HTTP front end for the serving engine — the same
zero-dependency ``http.server`` pattern as the observability scrape
endpoint (``observability.exporters.start_http_server``).

Token-level API (the framework has no tokenizer): prompts and
completions are lists of token ids.

- ``POST /generate`` body
  ``{"prompt": [ids], "max_new_tokens": 16, "do_sample": false,
     "temperature": 1.0, "top_k": 0, "top_p": 1.0, "eos_token_id": null,
     "seed": 0, "spec_k": null, "priority": "interactive",
     "deadline_s": null, "stream": false}``
  (``spec_k`` is the per-request speculative override on draft-model
  engines: 0 opts out, null takes the engine default — outputs are
  identical either way, only throughput moves)
  -> ``{"request_id", "status", "prompt_len", "tokens", "ttft_s",
        "tpot_s", "latency_s", "spec_drafted", "spec_accepted"}``;
  with ``"stream": true`` the response
  is newline-delimited JSON, one ``{"token": id}`` line per token as it
  lands, then a final ``{"done": true, "status": ...}`` line.
- ``GET /healthz``  -> ``engine.health()``: 200 only while admitting.
  The 503 states are DISTINCT — ``crashed`` / ``draining`` / ``stopped``
  / ``saturated`` each with their own payload, and saturated responses
  carry a ``Retry-After`` header derived from the queue-wait digest (a
  backed-up replica is no longer indistinguishable from a dead one).
- ``GET /stats``    -> ``engine.stats()`` (incl. the streaming latency
  digests — TTFT/TPOT/queue-wait/prefill-chunk p50/p95/p99 — and the
  goodput gauge).
- ``GET /trace``    -> the request-lifecycle trace as Chrome-trace
  (catapult) JSON — save it and load in chrome://tracing / Perfetto;
  ``?trace=<request_id>`` filters to one request's timeline (the
  router passes its propagated attempt trace id here to fetch an
  attempt's replica-side events for the merged fleet trace).
- ``GET /metrics``  -> Prometheus text exposition of this replica's
  registry — the scrape target of the router's metric federation.

``POST /generate`` honors a W3C-traceparent-style header
(``00-<32hex>-<16hex>-<2hex>``): a valid header makes the request's
span tree record under the propagated trace id so the router can join
it into one fleet trace; malformed or absent headers are ignored (fresh
local trace) — never a 400/500.
- ``GET /debug/requests`` -> the live per-request state table (queued /
  running / recent-finished, with phase, KV blocks, waits, latencies).
- ``GET /debug/memory`` -> the HBM ledger: live device bytes attributed
  to subsystems (KV pools, model weights, executable temp/output sizes
  from the captured memory analyses), headroom vs ``bytes_limit``
  (``"unsupported"`` where PJRT reports nothing), plus the device peak
  table and the per-executable roofline ledger.
- ``POST /drain``   -> graceful shutdown: stop admitting, finish
  in-flight requests (body ``{"timeout_s": ...}`` bounds the wait;
  stragglers are FAILED explicitly), then 200 ``{"drained": bool}``.
  Subsequent ``/healthz`` reports ``draining``/``stopped``.

Backpressure maps to ``429`` (+ ``Retry-After``), invalid requests to
``400``, draining/stopped engines to ``503``. Opt-in only: nothing
starts this server implicitly.

``ServingHTTPServer`` is the instance API — one per engine, any number
per process (a multi-replica router fronts several). The module-level
``start_serving_http_server``/``stop_serving_http_server`` pair keeps
the original one-server-per-process convenience surface.
"""

from __future__ import annotations

import json
import math
import threading
import time

from ..observability import fleet as _fleet
from ..observability import tracing as _tracing
from .engine import EngineStoppedError
from .scheduler import QueueFullError
from .supervisor import PoisonedRequestError

__all__ = ["ServingHTTPServer", "start_serving_http_server",
           "stop_serving_http_server"]

_default_server = None
_server_lock = threading.Lock()


def _request_record(req) -> dict:
    return {
        "request_id": req.id,
        "status": req.status,
        "prompt_len": int(req.prompt.shape[0]),
        "tokens": list(req.output_tokens),
        "ttft_s": req.ttft_s,
        "tpot_s": req.tpot_s,
        "latency_s": (req.finish_ts - req.arrival_ts
                      if req.finish_ts else None),
        "spec_drafted": req.spec_drafted,
        "spec_accepted": req.spec_accepted,
        "error": req.error,
    }


def retry_after_header(payload: dict) -> dict:
    """``Retry-After`` (integer seconds, >= 1 per RFC 9110) from a
    payload's ``retry_after_s`` hint, or no header when there is none."""
    ra = payload.get("retry_after_s")
    if ra is None:
        return {}
    return {"Retry-After": str(max(1, math.ceil(float(ra))))}


class ServingHTTPServer:
    """One engine's HTTP front end on a daemon thread. ``port=0`` binds
    a free port (read it back from ``.port``); ``stop()`` shuts the
    server down (the engine itself is stopped separately — or via
    ``POST /drain``)."""

    def __init__(self, engine, port: int = 0, addr: str = "127.0.0.1",
                 request_timeout_s: float = 300.0):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        engine.start()
        self.engine = engine

        class _Handler(BaseHTTPRequestHandler):
            def _json(self, code: int, payload: dict, headers=None):
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                path = self.path.split("?")[0]
                if path == "/healthz":
                    code, payload = engine.health()
                    self._json(code, payload,
                               headers=retry_after_header(payload))
                elif path == "/stats":
                    self._json(200, engine.stats())
                elif path == "/trace":
                    # catapult JSON for chrome://tracing; ?trace=<id>
                    # filters to one request's lanes
                    trace = None
                    query = self.path.partition("?")[2]
                    for kv in query.split("&"):
                        k, _, v = kv.partition("=")
                        if k == "trace" and v:
                            try:
                                trace = int(v)
                            except ValueError:
                                trace = v
                    self._json(200, _tracing.chrome_trace(trace))
                elif path == "/metrics":
                    # Prometheus exposition for this replica — the
                    # router's federation aggregator scrapes it
                    from ..observability import exporters as _exp

                    body = _exp.prometheus_text().encode()
                    self.send_response(200)
                    self.send_header(
                        "Content-Type",
                        "text/plain; version=0.0.4; charset=utf-8")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                elif path == "/debug/requests":
                    self._json(200, engine.debug_requests())
                elif path == "/debug/memory":
                    from ..observability import perf as _perf

                    self._json(200, {
                        "ts": time.time(),
                        "hbm": _perf.hbm_ledger(),
                        "peaks": _perf.peak_specs(),
                        "ledger": _perf.ledger(),
                    })
                else:
                    self._json(404, {"error": f"no such path {path!r}"})

            def do_POST(self):
                path = self.path.split("?")[0]
                if path == "/drain":
                    try:
                        length = int(self.headers.get("Content-Length", 0))
                        body = json.loads(self.rfile.read(length) or b"{}")
                        timeout_s = body.get("timeout_s")
                    except (ValueError, json.JSONDecodeError) as e:
                        self._json(400, {"error": f"bad request: {e}"})
                        return
                    drained = engine.drain(timeout_s=timeout_s)
                    self._json(200, {"drained": bool(drained),
                                     "status": engine.health()[1]["status"]})
                    return
                if path != "/generate":
                    self._json(404, {"error": "POST /generate or /drain"})
                    return
                try:
                    length = int(self.headers.get("Content-Length", 0))
                    body = json.loads(self.rfile.read(length) or b"{}")
                    prompt = body.pop("prompt")
                    stream = bool(body.pop("stream", False))
                    deadline_s = body.pop("deadline_s", None)
                    if not isinstance(prompt, (list, tuple)) or not prompt:
                        raise ValueError("prompt must be a non-empty list "
                                         "of token ids")
                except (ValueError, KeyError, json.JSONDecodeError) as e:
                    self._json(400, {"error": f"bad request: {e}"})
                    return
                # fleet trace propagation: a VALID traceparent header
                # makes the replica-side request adopt the propagated
                # trace id (the Request is constructed on this handler
                # thread inside submit, under the context). Anything
                # malformed parses to None — a fresh local trace, never
                # a 400/500; a hostile header must not cost the caller
                # their request.
                prop = _fleet.parse_traceparent(
                    self.headers.get(_fleet.TRACEPARENT_HEADER))
                try:
                    if prop is not None:
                        with _tracing.trace_context(prop):
                            req = engine.submit(prompt,
                                                deadline_s=deadline_s,
                                                **body)
                    else:
                        req = engine.submit(prompt, deadline_s=deadline_s,
                                            **body)
                except QueueFullError as e:
                    # backpressure carries the same digest-derived
                    # Retry-After hint the saturated /healthz payload
                    # does; a deadline-infeasible rejection carries the
                    # queue-wait estimate the deadline lost to instead
                    from . import metrics as _sm

                    ra = getattr(e, "retry_after_s", None)
                    if ra is None:
                        ra = _sm.queue_wait_retry_after()
                    self._json(429, {"error": str(e), "retry_after_s": ra},
                               headers=retry_after_header(
                                   {"retry_after_s": ra}))
                    return
                except EngineStoppedError as e:
                    self._json(503, {"error": str(e),
                                     "status": engine.health()[1]["status"]})
                    return
                except PoisonedRequestError as e:
                    # quarantined fingerprint (supervised engines): an
                    # ACTIONABLE 400 — the body says why, names the
                    # fingerprint, and tells the caller not to retry.
                    # Must precede the generic ValueError arm (it IS a
                    # ValueError — deliberately, so unsupervised
                    # surfaces still treat it as a plain bad request).
                    self._json(400, {"error": str(e),
                                     "quarantined": True,
                                     "fingerprint": e.fingerprint,
                                     "retriable": False})
                    return
                except (TypeError, ValueError) as e:
                    self._json(400, {"error": f"bad request: {e}"})
                    return
                if not stream:
                    try:
                        req.result(timeout=request_timeout_s)
                    except TimeoutError:
                        req.cancel()
                        req.result(timeout=10.0)
                    self._json(200, _request_record(req))
                    return
                # streaming: newline-delimited JSON; no Content-Length,
                # the connection close marks the end (HTTP/1.0 framing)
                self.send_response(200)
                self.send_header("Content-Type", "application/jsonl")
                self.end_headers()
                try:
                    for tok in req.stream(timeout=request_timeout_s):
                        self.wfile.write(
                            (json.dumps({"token": int(tok)}) + "\n").encode())
                        self.wfile.flush()
                except (TimeoutError, BrokenPipeError, ConnectionResetError):
                    req.cancel()
                done = dict(_request_record(req))
                done["done"] = True
                try:
                    self.wfile.write((json.dumps(done) + "\n").encode())
                except (BrokenPipeError, ConnectionResetError):
                    pass

            def log_message(self, *args):  # no per-request stderr chatter
                pass

        self._server = ThreadingHTTPServer((addr, port), _Handler)
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            name=f"paddle-tpu-serving-http:{self.port}", daemon=True)
        self._thread.start()

    def stop(self):
        self._server.shutdown()
        self._server.server_close()


def start_serving_http_server(engine, port: int = 0, addr: str = "127.0.0.1",
                              request_timeout_s: float = 300.0) -> int:
    """Serve the engine over HTTP on a daemon thread; returns the bound
    port (``port=0`` picks a free one). Starts the engine's background
    loop if it isn't running (handlers block on ``Request.result``).
    One default server per process — build ``ServingHTTPServer``
    instances directly to front several engines."""
    global _default_server
    with _server_lock:
        if _default_server is not None:
            return _default_server.port
        _default_server = ServingHTTPServer(
            engine, port=port, addr=addr,
            request_timeout_s=request_timeout_s)
        return _default_server.port


def stop_serving_http_server():
    global _default_server
    with _server_lock:
        if _default_server is not None:
            _default_server.stop()
            _default_server = None
