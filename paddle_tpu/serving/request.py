"""Serving requests: sampling params, lifecycle states, and the handle
callers hold while the engine decodes.

A ``Request`` is both the scheduler's queue entry and the caller-facing
handle: ``result()`` blocks until the request finishes, ``stream()``
iterates tokens as the decode step lands them (per-token queue push from
the engine thread), ``cancel()`` asks the scheduler/engine to drop it.
Reference analogue: the per-request state objects of iteration-level
schedulers (Orca's request control block, vLLM's SequenceGroup) — here
deliberately minimal because the TPU-side state is just "which slot, at
which position".
"""

from __future__ import annotations

import hashlib
import itertools
import queue
import threading
import time
from dataclasses import dataclass
from typing import Callable, List, Optional

import numpy as np

from ..observability import tracing as _tracing

__all__ = ["SamplingParams", "Request", "RequestStatus",
           "PRIORITY_CLASSES", "request_fingerprint"]

# priority classes, LOWEST first — the shed order under queue pressure
# (DAGOR-style: batch work is shed before interactive work ever waits).
# The default is "interactive" so single-class workloads see exactly
# the pre-priority FCFS behavior: shedding only ever triggers when a
# STRICTLY lower class is present to shed.
PRIORITY_CLASSES = ("batch", "interactive")


def request_fingerprint(prompt, params: "SamplingParams") -> str:
    """Deterministic identity of a request's WORK: a short hex digest
    over the prompt tokens and every decode knob that reaches the
    compiled step. Two submissions of the same prompt+params — across
    retries, replicas, or engine restarts — share a fingerprint, which
    is what lets the poison-request quarantine recognize a
    deterministically-crashing request no matter which replica admits
    it. Priority and deadline are deliberately EXCLUDED: they change
    scheduling, not the work, and a poison request resubmitted at a
    different priority is still poison."""
    h = hashlib.sha256()
    h.update(np.asarray(prompt, np.int32).tobytes())
    h.update(repr((params.max_new_tokens, params.do_sample,
                   params.temperature, params.top_k, params.top_p,
                   params.eos_token_id, params.seed,
                   params.spec_k)).encode())
    return h.hexdigest()[:16]


class RequestStatus:
    """String states of the request lifecycle (no Enum: these land in
    JSON artifacts and HTTP responses as-is)."""

    QUEUED = "queued"
    RUNNING = "running"        # owns a slot; prefilled; decoding
    COMPLETED = "completed"    # EOS or max_new_tokens
    CANCELLED = "cancelled"
    EXPIRED = "expired"        # deadline passed before completion
    REJECTED = "rejected"      # backpressure: queue was full
    FAILED = "failed"          # prefill/step raised (engine survives)

    FINAL = (COMPLETED, CANCELLED, EXPIRED, REJECTED, FAILED)


@dataclass
class SamplingParams:
    """Per-request decode knobs — the same surface as
    ``generation.generate`` so outputs are comparable request-for-request
    (greedy by default; temperature/top-k/top-p when ``do_sample``).

    ``spec_k`` is the per-request speculative-decoding override on a
    draft-model engine: ``None`` takes the engine's configured k, ``0``
    opts this request out of speculation entirely (it rides the verify
    bundle as a plain one-token step), and ``1..engine_k`` shrinks the
    draft window. Values above the engine's k clamp down to it (the
    compiled bundle width is the engine's). Outputs are identical at
    every setting — speculation only changes how many tokens a round
    advances."""

    max_new_tokens: int = 32
    do_sample: bool = False
    temperature: float = 1.0
    top_k: int = 0
    top_p: float = 1.0
    eos_token_id: Optional[int] = None
    seed: int = 0
    spec_k: Optional[int] = None
    # priority CLASS, not a numeric weight: "interactive" (default) or
    # "batch". Under queue pressure the scheduler sheds the lowest
    # class first, and the router's brownout ladder degrades batch
    # work (shed -> token cap -> spec cap) before interactive work
    # feels anything. Priority never changes outputs — only admission.
    priority: str = "interactive"

    def __post_init__(self):
        if self.priority not in PRIORITY_CLASSES:
            raise ValueError(
                f"unknown priority class {self.priority!r}: expected one "
                f"of {PRIORITY_CLASSES} (lowest-shed-first order)")

    @property
    def priority_rank(self) -> int:
        """Position in the shed order (0 = shed first)."""
        return PRIORITY_CLASSES.index(self.priority)


_ids = itertools.count()
_STOP = object()  # stream sentinel


class Request:
    """One serving request: prompt tokens in, generated tokens out.

    Created by ``ServingEngine.submit``; also the handle the caller
    keeps. Thread-safe: the engine thread pushes tokens and flips
    status, caller threads read/wait/cancel.
    """

    def __init__(self, prompt, params: SamplingParams,
                 deadline_s: Optional[float] = None,
                 on_token: Optional[Callable[["Request", int], None]] = None):
        self.id = next(_ids)
        # trace identity: a propagated cross-process trace id (the
        # router's attempt id, carried by the traceparent header or the
        # thread-local trace_context at submit) when one is active on
        # the constructing thread, else the local request id — so a
        # replica-side span tree joins the fleet trace when there is
        # one and stays self-contained when there isn't
        _ctx = _tracing.current_trace()
        self.trace = _ctx if _ctx is not None else self.id
        self.prompt = prompt  # np.int32 [L]
        self.params = params
        self.arrival_ts = time.perf_counter()
        self.deadline_ts = (self.arrival_ts + deadline_s
                            if deadline_s is not None else None)
        self.on_token = on_token

        self.status = RequestStatus.QUEUED
        self.output_tokens: List[int] = []
        self.error: Optional[str] = None
        self.slot: Optional[int] = None

        self.prefill_done_ts: Optional[float] = None
        self.first_token_ts: Optional[float] = None
        self.last_token_ts: Optional[float] = None
        self.finish_ts: Optional[float] = None
        # queue-wait accounting: reset by requeue (preemption/backoff) so
        # the digest measures each wait, not lifetime-minus-decode
        self.queued_since_ts: float = self.arrival_ts
        self.admitted_ts: Optional[float] = None
        self.queue_wait_total_s: float = 0.0  # summed over re-admissions
        self.preempt_count = 0
        # speculative-lane accounting (engine thread): draft tokens
        # proposed for / accepted by this request's verify rounds
        self.spec_drafted = 0
        self.spec_accepted = 0

        self.cancel_requested = False
        # request-lifecycle trace: one root span for the whole life plus
        # named child spans the engine opens/closes (queued, prefill,
        # decode); finish() closes whatever is still open so every
        # terminal path — including scheduler-side cancel/expire — leaves
        # a complete, nesting-consistent trace
        ts0 = int(self.arrival_ts * 1e9)
        self._root_span = _tracing.begin_span(
            "request", cat="request", trace=self.trace,
            args={"prompt_len": int(prompt.shape[0]),
                  "max_new_tokens": params.max_new_tokens,
                  "do_sample": params.do_sample}, ts_ns=ts0)
        self._open_spans = {}
        self._tr_begin("queued", ts_ns=ts0)
        # paged-engine preemption state: (tokens_to_prefill, prng_key,
        # n_reselected) set when the request is requeued for recompute —
        # the generated tokens fold into the next prefill and the final
        # select's re-derived token is skipped, never re-delivered
        self._resume = None
        # supervisor quarantine state: the lazily-computed work
        # fingerprint (identity across retries/replicas/restarts) and
        # the solo-probe flag — a crash SUSPECT the supervisor requeues
        # is re-admitted in isolation so a repeat crash implicates it
        # definitively instead of smearing suspicion over co-runners
        self._fingerprint: Optional[str] = None
        self.quarantine_probe = False
        self._done = threading.Event()
        self._stream_q: "queue.Queue" = queue.Queue()

    @property
    def fingerprint(self) -> str:
        fp = self._fingerprint
        if fp is None:
            fp = self._fingerprint = request_fingerprint(self.prompt,
                                                         self.params)
        return fp

    @property
    def priority(self) -> str:
        return self.params.priority

    # -- tracing -------------------------------------------------------------
    def _tr_begin(self, name: str, ts_ns: Optional[int] = None, **args):
        """Open a named lifecycle span (engine thread). Idempotent per
        name: re-beginning an open span is a no-op."""
        if name not in self._open_spans:
            self._open_spans[name] = _tracing.begin_span(
                name, cat="request", trace=self.trace, args=args or None,
                ts_ns=ts_ns)

    def _tr_end(self, name: str, **args):
        sp = self._open_spans.pop(name, None)
        if sp is not None:
            _tracing.end_span(sp, args=args or None)

    def _tr_event(self, name: str, ts_ns: Optional[int] = None, **args):
        _tracing.instant(name, cat="request", trace=self.trace,
                         args=args or None, ts_ns=ts_ns)

    # -- engine side ---------------------------------------------------------
    def push_token(self, token: int, now: float):
        """Engine thread: deliver one generated token."""
        self.output_tokens.append(token)
        if self.first_token_ts is None:
            self.first_token_ts = now
        self.last_token_ts = now
        self._stream_q.put(token)
        if self.on_token is not None:
            try:
                self.on_token(self, token)
            except Exception:
                pass  # a consumer callback must never kill the decode loop

    def finish(self, status: str, error: Optional[str] = None):
        """Engine/scheduler thread: terminal transition (idempotent)."""
        if self.status in RequestStatus.FINAL:
            return
        self.status = status
        self.error = error
        self.finish_ts = time.perf_counter()
        # close the trace: whatever lifecycle span is still open ends
        # here, the terminal status lands as an instant, and the root
        # span closes last so children stay inside it
        end_ns = int(self.finish_ts * 1e9)
        for name in list(self._open_spans):
            sp = self._open_spans.pop(name)
            _tracing.end_span(sp, ts_ns=end_ns)
        self._tr_event(status, ts_ns=end_ns,
                       generated=len(self.output_tokens),
                       **({"error": error} if error else {}))
        _tracing.end_span(self._root_span, ts_ns=end_ns,
                          args={"status": status})
        self._stream_q.put(_STOP)
        self._done.set()

    # -- caller side ---------------------------------------------------------
    @property
    def done(self) -> bool:
        return self._done.is_set()

    def cancel(self):
        """Ask for cancellation; the engine frees the slot at the next
        step boundary (queued requests are dropped at admission)."""
        self.cancel_requested = True

    def result(self, timeout: Optional[float] = None) -> List[int]:
        """Block until the request reaches a terminal state; returns the
        generated tokens (possibly partial for cancelled/expired
        requests). Raises TimeoutError if it doesn't finish in time."""
        if not self._done.wait(timeout):
            raise TimeoutError(
                f"request {self.id} not finished within {timeout}s "
                f"(status={self.status})")
        return list(self.output_tokens)

    def stream(self, timeout: Optional[float] = None):
        """Yield generated token ids as the engine lands them. Ends when
        the request reaches a terminal state. ``timeout`` bounds the wait
        for EACH token (TimeoutError on a stall)."""
        while True:
            item = self._stream_q.get(timeout=timeout)
            if item is _STOP:
                return
            yield item

    def full_tokens(self) -> List[int]:
        """prompt + generated, as one list (the ``generate()`` shape
        minus the post-EOS padding)."""
        return list(self.prompt.tolist()) + list(self.output_tokens)

    # -- latency metrics -----------------------------------------------------
    @property
    def ttft_s(self) -> Optional[float]:
        """Time to first token (arrival -> first delivered token)."""
        if self.first_token_ts is None:
            return None
        return self.first_token_ts - self.arrival_ts

    @property
    def tpot_s(self) -> Optional[float]:
        """Mean time per output token AFTER the first (decode cadence)."""
        if self.first_token_ts is None or self.last_token_ts is None:
            return None
        n = len(self.output_tokens) - 1
        if n <= 0:
            return None
        return (self.last_token_ts - self.first_token_ts) / n

    def debug_row(self) -> dict:
        """One row of the ``/debug/requests`` live state table."""
        now = time.perf_counter()
        return {
            "request_id": self.id,
            "trace": self.trace,
            "status": self.status,
            "priority": self.params.priority,
            "slot": self.slot,
            "prompt_len": int(self.prompt.shape[0]),
            "generated": len(self.output_tokens),
            "max_new_tokens": self.params.max_new_tokens,
            "age_s": round(now - self.arrival_ts, 4),
            "queue_wait_s": round(self.queue_wait_total_s, 4)
                if self.admitted_ts is not None else None,
            "ttft_s": self.ttft_s,
            "tpot_s": self.tpot_s,
            "preemptions": self.preempt_count,
            "spec_k": self.params.spec_k,
            "spec_drafted": self.spec_drafted,
            "spec_accepted": self.spec_accepted,
            "spec_accept_rate": (round(self.spec_accepted
                                       / self.spec_drafted, 4)
                                 if self.spec_drafted else None),
            "deadline_in_s": (round(self.deadline_ts - now, 4)
                              if self.deadline_ts is not None
                              and self.finish_ts is None else None),
            "latency_s": (round(self.finish_ts - self.arrival_ts, 4)
                          if self.finish_ts is not None else None),
            "error": self.error,
        }

    def __repr__(self):
        return (f"Request(id={self.id}, status={self.status}, "
                f"prompt_len={len(self.prompt)}, "
                f"generated={len(self.output_tokens)})")
