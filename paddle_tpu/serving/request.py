"""Serving requests: sampling params, lifecycle states, and the handle
callers hold while the engine decodes.

A ``Request`` is both the scheduler's queue entry and the caller-facing
handle: ``result()`` blocks until the request finishes, ``stream()``
iterates tokens as the decode step lands them (per-token queue push from
the engine thread), ``cancel()`` asks the scheduler/engine to drop it.
Reference analogue: the per-request state objects of iteration-level
schedulers (Orca's request control block, vLLM's SequenceGroup) — here
deliberately minimal because the TPU-side state is just "which slot, at
which position".
"""

from __future__ import annotations

import itertools
import queue
import threading
import time
from dataclasses import dataclass
from typing import Callable, List, Optional

__all__ = ["SamplingParams", "Request", "RequestStatus"]


class RequestStatus:
    """String states of the request lifecycle (no Enum: these land in
    JSON artifacts and HTTP responses as-is)."""

    QUEUED = "queued"
    RUNNING = "running"        # owns a slot; prefilled; decoding
    COMPLETED = "completed"    # EOS or max_new_tokens
    CANCELLED = "cancelled"
    EXPIRED = "expired"        # deadline passed before completion
    REJECTED = "rejected"      # backpressure: queue was full
    FAILED = "failed"          # prefill/step raised (engine survives)

    FINAL = (COMPLETED, CANCELLED, EXPIRED, REJECTED, FAILED)


@dataclass
class SamplingParams:
    """Per-request decode knobs — the same surface as
    ``generation.generate`` so outputs are comparable request-for-request
    (greedy by default; temperature/top-k/top-p when ``do_sample``)."""

    max_new_tokens: int = 32
    do_sample: bool = False
    temperature: float = 1.0
    top_k: int = 0
    top_p: float = 1.0
    eos_token_id: Optional[int] = None
    seed: int = 0


_ids = itertools.count()
_STOP = object()  # stream sentinel


class Request:
    """One serving request: prompt tokens in, generated tokens out.

    Created by ``ServingEngine.submit``; also the handle the caller
    keeps. Thread-safe: the engine thread pushes tokens and flips
    status, caller threads read/wait/cancel.
    """

    def __init__(self, prompt, params: SamplingParams,
                 deadline_s: Optional[float] = None,
                 on_token: Optional[Callable[["Request", int], None]] = None):
        self.id = next(_ids)
        self.prompt = prompt  # np.int32 [L]
        self.params = params
        self.arrival_ts = time.perf_counter()
        self.deadline_ts = (self.arrival_ts + deadline_s
                            if deadline_s is not None else None)
        self.on_token = on_token

        self.status = RequestStatus.QUEUED
        self.output_tokens: List[int] = []
        self.error: Optional[str] = None
        self.slot: Optional[int] = None

        self.prefill_done_ts: Optional[float] = None
        self.first_token_ts: Optional[float] = None
        self.last_token_ts: Optional[float] = None
        self.finish_ts: Optional[float] = None

        self.cancel_requested = False
        # paged-engine preemption state: (tokens_to_prefill, prng_key,
        # n_reselected) set when the request is requeued for recompute —
        # the generated tokens fold into the next prefill and the final
        # select's re-derived token is skipped, never re-delivered
        self._resume = None
        self._done = threading.Event()
        self._stream_q: "queue.Queue" = queue.Queue()

    # -- engine side ---------------------------------------------------------
    def push_token(self, token: int, now: float):
        """Engine thread: deliver one generated token."""
        self.output_tokens.append(token)
        if self.first_token_ts is None:
            self.first_token_ts = now
        self.last_token_ts = now
        self._stream_q.put(token)
        if self.on_token is not None:
            try:
                self.on_token(self, token)
            except Exception:
                pass  # a consumer callback must never kill the decode loop

    def finish(self, status: str, error: Optional[str] = None):
        """Engine/scheduler thread: terminal transition (idempotent)."""
        if self.status in RequestStatus.FINAL:
            return
        self.status = status
        self.error = error
        self.finish_ts = time.perf_counter()
        self._stream_q.put(_STOP)
        self._done.set()

    # -- caller side ---------------------------------------------------------
    @property
    def done(self) -> bool:
        return self._done.is_set()

    def cancel(self):
        """Ask for cancellation; the engine frees the slot at the next
        step boundary (queued requests are dropped at admission)."""
        self.cancel_requested = True

    def result(self, timeout: Optional[float] = None) -> List[int]:
        """Block until the request reaches a terminal state; returns the
        generated tokens (possibly partial for cancelled/expired
        requests). Raises TimeoutError if it doesn't finish in time."""
        if not self._done.wait(timeout):
            raise TimeoutError(
                f"request {self.id} not finished within {timeout}s "
                f"(status={self.status})")
        return list(self.output_tokens)

    def stream(self, timeout: Optional[float] = None):
        """Yield generated token ids as the engine lands them. Ends when
        the request reaches a terminal state. ``timeout`` bounds the wait
        for EACH token (TimeoutError on a stall)."""
        while True:
            item = self._stream_q.get(timeout=timeout)
            if item is _STOP:
                return
            yield item

    def full_tokens(self) -> List[int]:
        """prompt + generated, as one list (the ``generate()`` shape
        minus the post-EOS padding)."""
        return list(self.prompt.tolist()) + list(self.output_tokens)

    # -- latency metrics -----------------------------------------------------
    @property
    def ttft_s(self) -> Optional[float]:
        """Time to first token (arrival -> first delivered token)."""
        if self.first_token_ts is None:
            return None
        return self.first_token_ts - self.arrival_ts

    @property
    def tpot_s(self) -> Optional[float]:
        """Mean time per output token AFTER the first (decode cadence)."""
        if self.first_token_ts is None or self.last_token_ts is None:
            return None
        n = len(self.output_tokens) - 1
        if n <= 0:
            return None
        return (self.last_token_ts - self.first_token_ts) / n

    def __repr__(self):
        return (f"Request(id={self.id}, status={self.status}, "
                f"prompt_len={len(self.prompt)}, "
                f"generated={len(self.output_tokens)})")
