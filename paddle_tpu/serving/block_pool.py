"""Host-side KV block allocator + prefix cache for the paged serving
engine.

The paged KV design (PagedAttention / vLLM allocation model, RadixAttention
prefix reuse, translated to this repo's static-shape substrate): device
HBM holds ONE fixed pool of ``num_blocks`` KV blocks of ``block_size``
tokens each (per layer, [num_blocks, block_size, kv_heads, head_dim]);
every slot's logical cache is a small int32 block table indexing the
pool. All allocation POLICY lives here on the host — the device only
ever sees block tables as traced arrays, so occupancy/sharing patterns
never retrace the decode step.

- ``BlockPool``: free-list allocator with per-block reference counts.
  Block 0 is permanently reserved as the *dump* block: inactive slot
  rows in the pool-wide decode step still execute their (static-shape)
  cache write, and routing those writes at physical block 0 keeps them
  from ever dirtying a live block. A block with refcount > 1 is SHARED
  (prefix cache and/or several requests); writers must copy-on-write
  fork it first (`ServingEngine._ensure_writable`).
- ``PrefixCache``: exact-prefix reuse map ``prompt[:end] -> block id``
  with LRU eviction. A request whose prompt starts with an already-
  prefilled prefix adopts those blocks by reference instead of
  recomputing them — a shared system prompt is prefilled once, ever.
  Partial (non-block-aligned) tails are cached too; the first divergent
  write into one triggers the COW fork, which is what makes sharing
  safe.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import List, Sequence, Tuple

import numpy as np

from . import metrics as _sm

__all__ = ["BlockPool", "PrefixCache", "PoolExhaustedError",
           "BlockPoolError", "DUMP_BLOCK"]

# physical block 0: the write sink for inactive/padded rows. Never
# allocated, never freed, never cached.
DUMP_BLOCK = 0


class PoolExhaustedError(RuntimeError):
    """No free KV blocks. Callers evict the prefix cache / preempt a
    running request and retry, or surface admission backpressure."""


class BlockPoolError(RuntimeError):
    """Allocator invariant violation (double free, bad block id) — a
    bug in the caller, never load-dependent."""


class BlockPool:
    """Ref-counted free-list allocator over ``num_blocks`` KV blocks.

    Thread-safe (one lock; every operation is O(1) or O(n_requested)).
    Allocation is all-or-nothing: ``alloc(n)`` either returns ``n``
    block ids or raises ``PoolExhaustedError`` leaving the pool
    untouched. The free list is LIFO so tests and replays are
    deterministic.
    """

    # pt-analysis lock discipline: every mutable piece of allocator
    # state is touched only under self._lock (methods below either take
    # it or are '# holds-lock' helpers whose callers do)
    GUARDED_BY = {
        "_free": "_lock",
        "_ref": "_lock",
        "alloc_total": "_lock",
        "free_total": "_lock",
        "cow_forks": "_lock",
        "high_watermark": "_lock",
    }

    def __init__(self, num_blocks: int, block_size: int):
        if num_blocks < 2:
            raise ValueError(
                f"num_blocks must be >= 2 (block 0 is the reserved dump "
                f"block), got {num_blocks}")
        if block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {block_size}")
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        self._lock = threading.Lock()
        # LIFO free list; low ids first out for deterministic layouts
        self._free: List[int] = list(range(num_blocks - 1, 0, -1))
        self._ref = np.zeros(num_blocks, np.int64)
        self._ref[DUMP_BLOCK] = 1  # pinned forever
        self.alloc_total = 0
        self.free_total = 0
        self.cow_forks = 0   # engine reports forks via note_cow_fork()
        self.high_watermark = 0
        with self._lock:
            self._set_gauges()

    # -- core ops ------------------------------------------------------------
    def alloc(self, n: int = 1) -> List[int]:
        """Take ``n`` fresh blocks (refcount 1 each). All-or-nothing."""
        if n < 0:
            raise ValueError(f"alloc({n})")
        with self._lock:
            if n > len(self._free):
                raise PoolExhaustedError(
                    f"KV block pool exhausted: need {n} block(s), "
                    f"{len(self._free)} free of {self.usable_blocks} usable "
                    f"(block_size={self.block_size})")
            ids = [self._free.pop() for _ in range(n)]
            for b in ids:
                self._ref[b] = 1
            self.alloc_total += n
            self.high_watermark = max(self.high_watermark,
                                      self._used_unlocked())
            self._set_gauges()
            return ids

    def incref(self, block_id: int) -> None:
        """Adopt a shared reference to a live block."""
        with self._lock:
            self._check_live(block_id)
            self._ref[block_id] += 1
            self._set_gauges()

    def decref(self, block_id: int) -> bool:
        """Drop one reference; returns True when the block was freed."""
        with self._lock:
            self._check_live(block_id)
            self._ref[block_id] -= 1
            if self._ref[block_id] == 0:
                self._free.append(block_id)
                self.free_total += 1
                self._set_gauges()
                return True
            self._set_gauges()
            return False

    def ref(self, block_id: int) -> int:
        with self._lock:
            if not (0 <= block_id < self.num_blocks):
                raise BlockPoolError(f"bad block id {block_id}")
            return int(self._ref[block_id])

    def _check_live(self, block_id: int):  # holds-lock: _lock
        if not (0 < block_id < self.num_blocks):
            raise BlockPoolError(
                f"bad block id {block_id} (usable ids are "
                f"1..{self.num_blocks - 1}; 0 is the reserved dump block)")
        if self._ref[block_id] <= 0:
            raise BlockPoolError(
                f"block {block_id} is not allocated (double free / "
                f"use-after-free)")

    def note_cow_fork(self) -> None:
        """Engine-side fork accounting (the fork itself is the engine's
        jitted copy; only the counter lives behind the pool lock)."""
        with self._lock:
            self.cow_forks += 1

    # -- accounting ----------------------------------------------------------
    # The public properties take the lock (they are read from the HTTP
    # stats/health threads while the engine allocates); the *_unlocked
    # helpers are for use inside an operation that already holds it.
    def _free_unlocked(self) -> int:  # holds-lock: _lock
        return len(self._free)

    def _used_unlocked(self) -> int:  # holds-lock: _lock
        return self.usable_blocks - len(self._free)

    def _shared_unlocked(self) -> int:  # holds-lock: _lock
        return int((self._ref[1:] > 1).sum())

    @property
    def usable_blocks(self) -> int:
        return self.num_blocks - 1  # minus the dump block (immutable)

    @property
    def free_blocks(self) -> int:
        with self._lock:
            return self._free_unlocked()

    @property
    def used_blocks(self) -> int:
        with self._lock:
            return self._used_unlocked()

    @property
    def shared_blocks(self) -> int:
        """Blocks referenced by more than one owner (COW-protected)."""
        with self._lock:
            return self._shared_unlocked()

    def stats(self) -> dict:
        """Fragmentation/utilization accounting for /stats and tests —
        one lock hold, so the snapshot is internally consistent."""
        with self._lock:
            used = self._used_unlocked()
            return {
                "num_blocks": self.num_blocks,
                "block_size": self.block_size,
                "usable": self.usable_blocks,
                "in_use": used,
                "free": self._free_unlocked(),
                "shared": self._shared_unlocked(),
                "utilization": used / max(1, self.usable_blocks),
                "high_watermark": self.high_watermark,
                "alloc_total": self.alloc_total,
                "free_total": self.free_total,
                "cow_forks": self.cow_forks,
            }

    def _set_gauges(self):  # holds-lock: _lock
        _sm.kv_blocks_total.set(self.usable_blocks)
        _sm.kv_blocks_in_use.set(self._used_unlocked())
        _sm.kv_blocks_shared.set(self._shared_unlocked())


class PrefixCache:
    """Exact token-prefix -> KV block map with LRU eviction.

    One entry per cached block: the key is the request prompt's bytes up
    to and including the tokens that block covers, so a hit guarantees
    both the block's own tokens AND its entire left context match —
    K/V entries are position- and context-dependent, a content-only
    match would be wrong. The cache holds its own reference on every
    registered block; eviction (LRU, only blocks nobody else references)
    releases it back to the pool.
    """

    GUARDED_BY = {"_map": "_lock", "hits": "_lock", "misses": "_lock"}

    def __init__(self, pool: BlockPool):
        self.pool = pool
        # key -> (block_id, covered_end); ordered for LRU (oldest first)
        self._map: "OrderedDict[bytes, Tuple[int, int]]" = OrderedDict()
        self._lock = threading.Lock()
        self.hits = 0       # block-level hit/miss tallies (also metrics)
        self.misses = 0
        # Eviction hook: called as on_evict(key, block_id, covered_end)
        # BEFORE the block is released, returning "demoted" when it
        # copied the KV somewhere (the hierarchical host tier rides
        # this, serving/kv_tier.py) or "dropped" to free outright. None
        # (the default) keeps the legacy drop-on-evict behavior. A
        # raising hook counts as "dropped": eviction must reclaim
        # blocks even when the tier misbehaves.
        self.on_evict = None

    @staticmethod
    def _key(tokens: np.ndarray, end: int) -> bytes:
        return np.ascontiguousarray(tokens[:end], dtype=np.int32).tobytes()

    def __len__(self) -> int:
        with self._lock:
            return len(self._map)

    def note(self, hit_blocks: int, miss_blocks: int) -> None:
        """Admission-side hit/miss accounting (the engine calls this
        once per admission; keeping the tallies behind the cache lock
        means a /stats scrape never reads a torn update)."""
        with self._lock:
            self.hits += hit_blocks
            self.misses += miss_blocks

    def match(self, tokens: np.ndarray, limit: int) -> Tuple[int, List[int]]:
        """Longest reusable prefix of ``tokens`` covering at most
        ``limit`` tokens (callers pass ``len(prompt) - 1`` so at least
        the last prompt token is always recomputed for its logits).
        Increfs every matched block on behalf of the caller; returns
        ``(n_tokens_covered, block_ids)``."""
        bs = self.pool.block_size
        matched: List[int] = []
        covered = 0
        with self._lock:
            while covered < limit:
                hit = None
                # longest cached span first: the full next block, then
                # every shorter partial tail down to one extra token
                top = min(covered + bs, limit)
                for end in range(top, covered, -1):
                    ent = self._map.get(self._key(tokens, end))
                    if ent is not None:
                        hit = (end, ent[0])
                        break
                if hit is None:
                    break
                end, bid = hit
                self.pool.incref(bid)
                self._map.move_to_end(self._key(tokens, end))
                matched.append(bid)
                covered = end
                if end % bs:
                    break  # a partial block is always the last reusable one
        return covered, matched

    def insert(self, tokens: np.ndarray, length: int,
               block_ids: Sequence[int]) -> int:
        """Register the blocks covering ``tokens[:length]`` after a
        prefill completes. Already-present keys are left alone (the
        first writer wins; no duplicate references). Returns the number
        of NEW entries."""
        bs = self.pool.block_size
        added = 0
        with self._lock:
            for i, bid in enumerate(block_ids):
                end = min((i + 1) * bs, length)
                if end <= i * bs:
                    break
                key = self._key(tokens, end)
                if key in self._map:
                    self._map.move_to_end(key)
                    continue
                self.pool.incref(bid)
                self._map[key] = (bid, end)
                added += 1
        return added

    def evict(self, n: int) -> int:
        """Free up to ``n`` blocks by dropping LRU entries whose block
        nobody else references (cache-only blocks). Returns how many
        blocks were actually freed."""
        freed = 0
        with self._lock:
            for key in list(self._map.keys()):
                if freed >= n:
                    break
                bid, end = self._map[key]
                if self.pool.ref(bid) == 1:  # cache holds the only ref
                    del self._map[key]
                    outcome = "dropped"
                    if self.on_evict is not None:
                        # the block is still live (our ref) — the hook
                        # may copy it device->host before the decref
                        # below hands it back to the pool
                        try:
                            if self.on_evict(key, bid, end) == "demoted":
                                outcome = "demoted"
                        except Exception:  # noqa: BLE001 — see __init__
                            pass
                    self.pool.decref(bid)
                    freed += 1
                    _sm.prefix_cache_evictions.labels(outcome).inc()
        return freed

    def forget(self, block_id: int) -> None:
        """Drop every entry pointing at ``block_id`` (engine-side
        invalidation; releases the cache's reference)."""
        with self._lock:
            for key in [k for k, (b, _) in self._map.items()
                        if b == block_id]:
                del self._map[key]
                self.pool.decref(block_id)

    def entries(self) -> List[Tuple[bytes, int, int]]:
        """Consistent ``(key, block_id, covered_end)`` snapshot in LRU
        order (oldest first) — the drain-time tier flush walks this to
        persist every still-cached prefix."""
        with self._lock:
            return [(k, bid, end) for k, (bid, end) in self._map.items()]

    def stats(self) -> dict:
        with self._lock:
            return {"entries": len(self._map), "hits": self.hits,
                    "misses": self.misses}
