"""Multi-replica serving router: spread requests over N ``ServingEngine``
replicas with health-gated failover, deadline-aware retries, tail-latency
hedging, and graceful drain.

One engine process is a single point of failure: today a decode-loop
crash fails every in-flight request with a 503 and no recovery. This is
the layer production serving stacks put ABOVE iteration-level
scheduling (Orca governs *inside* one engine; a vLLM-class deployment
routes *across* engines), and it is where serving fault tolerance
actually lives:

- **Load-aware admission**: each request goes to the replica with the
  lowest load score — router-attributed in-flight attempts, queue depth
  and KV-pool utilization from the replica's ``/stats``, and the p95
  TTFT digest (the PR-7 latency digests exist precisely for this
  decision). Stats are polled with a staleness bound and a timeout; a
  replica whose ``/stats`` hangs keeps serving on its last-known score
  (a slow stats endpoint is not a dead replica).
- **Health gating**: replicas are probed on ``/healthz``. ``K``
  consecutive probe failures (error / timeout / malformed payload /
  ``crashed`` / ``stalled``) eject the replica from rotation; an
  ejected replica is re-admitted only after passing a WARMUP probe
  (``status == "ok"`` and ``warmed_up`` — a replacement engine that
  hasn't AOT-compiled its executables would pay its compiles out of the
  first user's deadline). ``saturated`` and ``draining`` are NOT
  failures: a backed-up replica gets a ``retry_after_s`` backoff, a
  draining one just stops receiving new work.
- **Deadline-aware retry**: a request whose attempt dies with its
  replica (crash, abort, ejection mid-flight) is retried on another
  replica with capped exponential backoff + seeded jitter. Retries are
  idempotent because prefill restarts from the prompt and the engine's
  PRNG chain is seed-deterministic: the new replica re-derives exactly
  the tokens the dead one already delivered, and the relay drops the
  replayed prefix — the caller sees each token once and the final
  output is bit-identical to a single-engine run. Retries respect the
  remaining deadline (a retry that cannot beat the deadline fails as
  EXPIRED immediately), never fire for cancelled requests, and are
  bounded per-request (``max_retries_per_request``) and globally (the
  amplification cap: extra attempts <= cap * requests + floor — a
  crash storm cannot melt the surviving replicas with retry traffic).
- **Hedging** (opt-in): when a request's first token is slower than the
  digest-derived threshold (``hedge_ttft_factor`` x the replica's p95
  TTFT), a second replica races it; the first to deliver a token wins
  and the loser is cancelled. Outputs are identical either way (same
  seed => same tokens), so hedging only moves tail latency.
- **Graceful drain**: ``drain(name)`` stops admitting to a replica and
  lets its in-flight requests finish (``engine.stop()`` drains by
  default now) while the router routes new traffic elsewhere —
  vs. the fail-all crash path. ``router_http`` wires SIGTERM to
  ``drain_all`` through the fault-tolerance preemption listener.

- **Fleet observability plane** (``observability/fleet.py``, gated by
  ``RouterConfig.fleet_observability``): every attempt carries a
  deterministic propagated trace id (traceparent header over HTTP,
  thread-local ``trace_context`` in-process) so the replica-side span
  tree joins the router's trace — ``merged_trace(request_id)`` fetches
  each attempt's events back and renders ONE multi-swimlane catapult
  file; replica ``/metrics`` are scraped on the stats cadence into a
  federation aggregator (``federated_metrics_text()``, relabeled
  ``replica=<name>`` + ``replica="fleet"`` roll-ups); terminal
  requests feed multi-window SLO burn rates (``slo_report()``); and
  per-replica TPOT deviation (robust MAD) flags stragglers in
  ``/replicas`` — optionally penalized in the admission score.

- **Quarantine propagation + brownout** (the self-healing plane): a
  replica supervisor (``serving/supervisor.py``) that quarantines a
  poison request publishes the fingerprint in its ``/stats`` block;
  the router merges every replica's blacklist on its normal stats
  cadence AND learns from the retry path (an attempt failing with the
  ``PoisonedRequestError`` marker is terminal, never retried — the
  poison must not crash-loop its way across the fleet). And when the
  fleet SLO burns on BOTH windows, a ``BrownoutController`` steps the
  router through the degradation ladder: shed batch-class submits,
  disable hedging, clamp batch decode length, cap speculation — with
  hysteresis on recovery so one good minute doesn't re-admit the
  overload.

The router talks to replicas through a small client protocol —
``healthz() / stats() / submit() / cancel() / drain()`` (plus the
optional fleet extensions ``metrics_text() / trace_events()``) — with two
implementations: ``LocalReplica`` (in-process engine, what the tests
and the single-host topology use) and ``HTTPReplica`` (an engine behind
``serving.http`` in another process). ``chaos.py`` wraps the same
protocol to inject faults; ``tests/test_router.py`` asserts the
invariants under them: no request silently lost, greedy outputs
bit-identical to a single-engine run, zero retraces on surviving
replicas, retry amplification bounded.
"""

from __future__ import annotations

import itertools
import json
import queue
import random
import threading
import time
import urllib.error
import urllib.request
import weakref
from dataclasses import dataclass, field, replace as _dc_replace
from typing import Dict, List, Optional

import numpy as np

from ..observability import exporters as _exporters
from ..observability import fleet as _fleet
from ..observability import tracing as _trace
from . import metrics as _sm
from .engine import EngineStoppedError, ServingEngine
from .request import RequestStatus, SamplingParams, request_fingerprint
from .scheduler import QueueFullError
from .supervisor import (EngineSupervisor, POISON_MARKER,
                         PoisonedRequestError)

__all__ = ["Router", "RouterConfig", "RouterRequest", "ReplicaState",
           "LocalReplica", "HTTPReplica", "NoReplicaError"]

_router_req_ids = itertools.count()
_STOP = object()


class NoReplicaError(RuntimeError):
    """No replica can admit the request right now (all ejected,
    draining, or saturated). Carries ``retry_after_s`` when the cause
    is saturation (shed load upstream and come back)."""

    def __init__(self, msg: str, retry_after_s: Optional[float] = None):
        super().__init__(msg)
        self.retry_after_s = retry_after_s


class ReplicaState:
    """Router-side replica lifecycle (strings: these land in /stats
    JSON as-is)."""

    HEALTHY = "healthy"    # in rotation
    EJECTED = "ejected"    # failed K consecutive probes; awaiting warmup
    DRAINING = "draining"  # no new admissions; in-flight finishing
    STOPPED = "stopped"    # drained / removed


def _call_with_timeout(fn, timeout_s: float):
    """Run ``fn()`` on a daemon thread, bounded by ``timeout_s``. The
    probe/stats calls must never wedge the router on a hung replica —
    a timed-out worker thread is abandoned (daemon) rather than
    joined forever."""
    box: list = []
    done = threading.Event()

    def _run():
        try:
            box.append(("ok", fn()))
        except Exception as e:  # noqa: BLE001 — surfaced to the caller
            box.append(("err", e))
        done.set()

    t = threading.Thread(target=_run, daemon=True,
                         name="paddle-tpu-router-probe")
    t.start()
    if not done.wait(timeout_s):
        raise TimeoutError(f"replica call exceeded {timeout_s}s")
    kind, val = box[0]
    if kind == "err":
        raise val
    return val


# ---------------------------------------------------------------------------
# replica clients
# ---------------------------------------------------------------------------

class LocalReplica:
    """In-process replica: the ``ServingEngine`` driven directly. The
    single-host topology (and the chaos suite's substrate) — same
    decision surface as the HTTP client: ``healthz()`` returns exactly
    the ``/healthz`` payload, ``stats()`` exactly ``/stats``."""

    def __init__(self, engine: ServingEngine, name: Optional[str] = None):
        self.engine = engine
        self.name = name

    def healthz(self) -> dict:
        return self.engine.health()[1]

    def stats(self) -> dict:
        return self.engine.stats()

    def submit(self, prompt, deadline_s=None, on_token=None, params=None,
               trace_id=None):
        if trace_id is not None:
            # fleet trace propagation, in-process flavor: the Request is
            # constructed on this thread inside engine.submit and adopts
            # the context — same join the traceparent header buys HTTP
            with _trace.trace_context(trace_id):
                return self.engine.submit(prompt, deadline_s=deadline_s,
                                          on_token=on_token, params=params)
        return self.engine.submit(prompt, deadline_s=deadline_s,
                                  on_token=on_token, params=params)

    def cancel(self, handle):
        self.engine.cancel(handle)

    def metrics_text(self) -> str:
        """This replica's Prometheus exposition (the federation scrape
        target). In-process replicas share one registry, so every
        LocalReplica of a process returns the same text — the federated
        roll-ups then multiply shared series by the replica count;
        real isolation needs the HTTP topology (one process each)."""
        return _exporters.prometheus_text()

    def trace_events(self, trace_id) -> dict:
        """Chrome-trace JSON for one propagated trace id — the
        replica-side half of a router attempt's merged fleet trace.
        Works even after this replica's engine crashed: the tracing
        ring is in-process state, not engine state."""
        return _trace.chrome_trace(trace_id)

    def warmup(self) -> dict:
        return self.engine.warmup()

    def start(self):
        self.engine.start()

    def drain(self, timeout_s: Optional[float] = None):
        self.engine.stop(drain_timeout_s=timeout_s)


class _HTTPAttempt:
    """Request-handle shim over a streaming ``POST /generate``: a
    daemon thread reads the NDJSON token lines and mirrors the
    ``Request`` surface the router's await loop uses (``done`` /
    ``status`` / ``output_tokens`` / ``error`` / ``result()``)."""

    def __init__(self, url: str, body: dict, on_token, timeout_s: float,
                 headers: Optional[Dict[str, str]] = None):
        self.output_tokens: List[int] = []
        self.status = RequestStatus.RUNNING
        self.error: Optional[str] = None
        self._done = threading.Event()
        self._on_token = on_token
        self._resp = None
        self._cancelled = False
        req = urllib.request.Request(
            url, data=json.dumps(dict(body, stream=True)).encode(),
            headers={"Content-Type": "application/json", **(headers or {})})
        self._thread = threading.Thread(
            target=self._consume, args=(req, timeout_s), daemon=True,
            name="paddle-tpu-router-http-attempt")
        self._thread.start()

    @property
    def done(self) -> bool:
        return self._done.is_set()

    def _finish(self, status, error=None):
        if not self._done.is_set():
            self.status = status
            self.error = error
            self._done.set()

    def _consume(self, req, timeout_s):
        try:
            self._resp = urllib.request.urlopen(req, timeout=timeout_s)
            for line in self._resp:
                rec = json.loads(line)
                if "token" in rec:
                    self.output_tokens.append(int(rec["token"]))
                    if self._on_token is not None:
                        try:
                            self._on_token(self, rec["token"])
                        except Exception:  # noqa: BLE001 — consumer bug
                            pass
                elif rec.get("done"):
                    self._finish(rec.get("status", RequestStatus.FAILED),
                                 rec.get("error"))
                    return
            self._finish(RequestStatus.FAILED, "stream ended without a "
                                               "done record")
        except urllib.error.HTTPError as e:
            # a non-200 carries a JSON error body (429 backpressure,
            # 400 bad-request/quarantine): surface the SERVER's message
            # — repr(e) would swallow it, and the router's poison
            # marker check reads this string
            try:
                err = json.loads(e.read()).get("error") or repr(e)
            except Exception:  # noqa: BLE001 — body unreadable
                err = repr(e)
            self._finish(RequestStatus.FAILED, err)
        except Exception as e:  # noqa: BLE001 — connection-level failure
            if self._cancelled:
                self._finish(RequestStatus.CANCELLED)
            else:
                self._finish(RequestStatus.FAILED, repr(e))

    def cancel(self):
        self._cancelled = True
        resp = self._resp
        if resp is not None:
            try:
                resp.close()  # server handler sees the broken pipe
            except Exception:  # noqa: BLE001
                pass

    def result(self, timeout: Optional[float] = None) -> List[int]:
        if not self._done.wait(timeout):
            raise TimeoutError("HTTP attempt not finished")
        return list(self.output_tokens)


class HTTPReplica:
    """A replica behind ``serving.http`` (``ServingHTTPServer``) in
    another process — or another port of this one. Probes hit
    ``GET /healthz`` (503 payloads are read, not treated as transport
    errors: a saturated/draining replica is alive), submissions stream
    ``POST /generate``, drain posts ``/drain``."""

    def __init__(self, base_url: str, name: Optional[str] = None,
                 timeout_s: float = 5.0, request_timeout_s: float = 300.0):
        self.base_url = base_url.rstrip("/")
        self.name = name
        self.timeout_s = timeout_s
        self.request_timeout_s = request_timeout_s

    def _get(self, path: str) -> dict:
        try:
            with urllib.request.urlopen(self.base_url + path,
                                        timeout=self.timeout_s) as resp:
                return json.loads(resp.read())
        except urllib.error.HTTPError as e:
            return json.loads(e.read())  # 503 payloads carry the status

    def healthz(self) -> dict:
        return self._get("/healthz")

    def stats(self) -> dict:
        return self._get("/stats")

    def metrics_text(self) -> str:
        """Raw ``GET /metrics`` text (Prometheus exposition — not
        JSON-decoded like ``_get``)."""
        with urllib.request.urlopen(self.base_url + "/metrics",
                                    timeout=self.timeout_s) as resp:
            return resp.read().decode("utf-8")

    def trace_events(self, trace_id) -> dict:
        """``GET /trace?trace=<propagated id>`` — the id is hex+dash,
        URL-safe as-is, and non-integer so the replica serves it as a
        string trace lane."""
        return self._get(f"/trace?trace={trace_id}")

    def submit(self, prompt, deadline_s=None, on_token=None, params=None,
               trace_id=None):
        p = params or SamplingParams()
        body = {"prompt": [int(t) for t in np.asarray(prompt).reshape(-1)],
                "max_new_tokens": p.max_new_tokens,
                "do_sample": p.do_sample, "temperature": p.temperature,
                "top_k": p.top_k, "top_p": p.top_p,
                "eos_token_id": p.eos_token_id, "seed": p.seed,
                "spec_k": p.spec_k, "priority": p.priority,
                "deadline_s": deadline_s}
        headers = {}
        if trace_id is not None:
            tp = _fleet.traceparent_of(trace_id)
            if tp is not None:
                headers[_fleet.TRACEPARENT_HEADER] = tp
        return _HTTPAttempt(self.base_url + "/generate", body, on_token,
                            self.request_timeout_s, headers=headers)

    def cancel(self, handle):
        handle.cancel()

    def drain(self, timeout_s: Optional[float] = None):
        req = urllib.request.Request(
            self.base_url + "/drain",
            data=json.dumps({"timeout_s": timeout_s}).encode(),
            headers={"Content-Type": "application/json"})
        wait = (timeout_s + self.timeout_s) if timeout_s is not None \
            else self.request_timeout_s
        with urllib.request.urlopen(req, timeout=wait) as resp:
            return json.loads(resp.read())


# ---------------------------------------------------------------------------
# configuration
# ---------------------------------------------------------------------------

@dataclass
class RouterConfig:
    """Router knobs. Defaults are sized for the in-process test/bench
    topology; a real deployment mostly raises the timeouts."""

    # health gating
    probe_failures_to_eject: int = 3   # K consecutive failures -> eject
    probe_interval_s: float = 0.2      # background prober cadence
    probe_timeout_s: float = 1.0
    readmit_probes: int = 1            # consecutive ok probes to re-admit
    # load-aware admission
    stats_refresh_s: float = 0.25      # staleness bound on cached /stats
    stats_timeout_s: float = 1.0
    w_inflight: float = 1.0            # score weights (lower score wins)
    w_queue: float = 1.0
    w_util: float = 1.0
    w_ttft: float = 0.5
    # supervisor-aware shedding: scales the replica's restart pressure
    # (supervisor restarts_in_window / max_restarts, from /stats) so a
    # chronically-restarting replica sheds load BEFORE its crash-loop
    # breaker trips and the prober has to eject it (0.0 = off)
    w_restart: float = 0.5
    # deadline-aware retry
    max_retries_per_request: int = 2
    retry_backoff_base_s: float = 0.02
    retry_backoff_max_s: float = 0.5
    retry_jitter: float = 0.25         # +- fraction of the delay
    retry_amplification_cap: float = 0.5   # extra attempts <= cap*requests
    retry_amplification_floor: int = 4     # ... + floor (small-N slack)
    # tail-latency hedging
    hedge: bool = False
    hedge_ttft_factor: float = 4.0     # threshold = factor * replica p95
    hedge_min_wait_s: float = 0.25
    # routing-loop bounds
    unroutable_timeout_s: float = 5.0  # no admitting replica for this long
    drain_timeout_s: Optional[float] = 30.0
    auto_warmup: bool = True           # warm local replicas at registration
    seed: int = 0                      # retry-jitter PRNG (deterministic)
    # fleet observability plane (observability/fleet.py): the master
    # switch gates trace propagation, /metrics federation scrapes, SLO
    # observation, and straggler detection — the bench A/B lever
    fleet_observability: bool = True
    slo: Optional["_fleet.SLOConfig"] = None  # None -> SLOConfig()
    straggler_detection: bool = True
    straggler_mad_threshold: float = 3.5  # Iglewicz-Hoaglin convention
    straggler_min_replicas: int = 3    # below this the median is the fleet
    # admission-score penalty added while a replica is flagged straggler
    # (0.0 = detect-and-report only, never shed load)
    straggler_penalty: float = 0.0
    recent_requests: int = 256         # merged-trace lookup registry cap
    # SLO-driven brownout (rides the fleet plane: needs the SLOTracker's
    # burn rates for input, so fleet_observability off disables it too).
    # Escalation is driven from the probe loop; the ladder's actions
    # fire at submit/attempt/hedge time.
    brownout: bool = True
    brownout_recover_reports: int = 3  # healthy streak to de-escalate
    brownout_min_dwell_s: float = 2.0  # min residence per level
    brownout_batch_max_new_tokens: int = 16  # cap_batch_tokens clamp
    brownout_spec_k_cap: int = 0       # shrink_spec clamp (0 = plain)

    def __post_init__(self):
        if self.probe_failures_to_eject < 1:
            raise ValueError("probe_failures_to_eject must be >= 1: a "
                             "replica cannot be ejected on zero evidence")
        if self.max_retries_per_request < 0:
            raise ValueError("max_retries_per_request must be >= 0")
        if self.retry_amplification_cap < 0:
            raise ValueError("retry_amplification_cap must be >= 0")
        if self.straggler_mad_threshold <= 0:
            raise ValueError("straggler_mad_threshold must be > 0")
        if self.straggler_penalty < 0:
            raise ValueError("straggler_penalty must be >= 0 (a negative "
                             "penalty would ATTRACT load to stragglers)")
        if self.w_restart < 0:
            raise ValueError("w_restart must be >= 0 (a negative weight "
                             "would ATTRACT load to crash-looping replicas)")
        if self.recent_requests < 1:
            raise ValueError("recent_requests must be >= 1")
        if self.brownout_batch_max_new_tokens < 1:
            raise ValueError("brownout_batch_max_new_tokens must be >= 1 "
                             "(a zero-token cap silently discards work; "
                             "use shedding for that)")
        if self.brownout_spec_k_cap < 0:
            raise ValueError("brownout_spec_k_cap must be >= 0")


@dataclass
class _Load:
    """Last-known load snapshot of one replica (from /stats)."""

    ts: float = 0.0
    queue_depth: int = 0
    max_queue_depth: int = 1
    slots_busy: int = 0
    slots: int = 1
    util: float = 0.0
    ttft_p95: Optional[float] = None
    tpot_p50: Optional[float] = None   # straggler-detection input
    kv_tier: Optional[dict] = None     # hierarchical-KV tier state, for
    stale: bool = False                # cache-aware routing to read
    # supervisor restart pressure: restarts_in_window / max_restarts
    # (1.0 = one crash from the breaker) + quarantined-prompt count
    restart_pressure: float = 0.0
    quarantined_count: int = 0


class _Replica:
    """Router-side handle: client + health state + load cache."""

    def __init__(self, name: str, client):
        self.name = name
        self.client = client
        self.state = ReplicaState.HEALTHY
        self.consecutive_probe_failures = 0
        self.ok_streak = 0
        self.inflight = 0
        self.saturated_until = 0.0
        self.load = _Load()
        self.attempts = 0
        self.probe_failures = 0
        self.submit_failures = 0
        self.stats_errors = 0
        self.ejections = 0
        self.last_probe: Optional[dict] = None
        self.straggler = False         # robust-MAD TPOT outlier flag

    def row(self) -> dict:
        return {
            "name": self.name, "state": self.state,
            "inflight": self.inflight, "attempts": self.attempts,
            "consecutive_probe_failures": self.consecutive_probe_failures,
            "probe_failures": self.probe_failures,
            "submit_failures": self.submit_failures,
            "stats_errors": self.stats_errors,
            "ejections": self.ejections,
            "saturated": self.saturated_until > time.perf_counter(),
            "straggler": self.straggler,
            "load": {
                "queue_depth": self.load.queue_depth,
                "slots_busy": self.load.slots_busy,
                "slots": self.load.slots,
                "util": round(self.load.util, 4),
                "ttft_p95": self.load.ttft_p95,
                "tpot_p50": self.load.tpot_p50,
                "kv_tier": self.load.kv_tier,
                "stale": self.load.stale,
                "restart_pressure": round(self.load.restart_pressure, 4),
                "quarantined_count": self.load.quarantined_count,
            },
        }


# ---------------------------------------------------------------------------
# the caller-facing handle
# ---------------------------------------------------------------------------

class RouterRequest:
    """One routed request: survives replica failover. The caller-facing
    surface mirrors ``Request`` (``result()`` / ``stream()`` /
    ``cancel()`` / TTFT/TPOT), but tokens arrive through the router's
    relay, which guarantees EXACTLY-ONCE delivery across retries and
    hedges: a retried attempt re-derives the already-delivered prefix
    (deterministic PRNG chain) and the relay drops it; a superseded
    attempt's callbacks are dropped entirely — ``on_token`` never fires
    for a replica the request failed away from."""

    def __init__(self, prompt, params: SamplingParams,
                 deadline_s: Optional[float], on_token):
        self.id = next(_router_req_ids)
        self.prompt = np.asarray(prompt, dtype=np.int32).reshape(-1)
        self.params = params
        # same identity the replica supervisors quarantine by: when an
        # attempt dies with the poison marker, THIS is the fingerprint
        # the router blacklists — no parsing of error strings needed
        self.fingerprint = request_fingerprint(self.prompt, params)
        self.arrival_ts = time.perf_counter()
        self.deadline_ts = (self.arrival_ts + deadline_s
                            if deadline_s is not None else None)
        self.on_token = on_token

        self.status = RequestStatus.QUEUED
        self.error: Optional[str] = None
        self.output_tokens: List[int] = []
        self.replica: Optional[str] = None   # current/winning replica
        self.attempts: List[dict] = []       # routing history
        self.retries = 0
        self.hedged = False
        self.first_token_ts: Optional[float] = None
        self.last_token_ts: Optional[float] = None
        self.finish_ts: Optional[float] = None
        self.cancel_requested = False

        self._lock = threading.Lock()
        self._done = threading.Event()
        self._stream_q: "queue.Queue" = queue.Queue()
        # attempt generations: the relay delivers only tokens of the
        # CURRENT generation, and only past the already-delivered count
        self._gen_iter = itertools.count(1)
        self._current_gen: Optional[int] = None
        self._hedge_gen: Optional[int] = None
        self._gen_counts: Dict[int, int] = {}
        self._root = _trace.begin_span(
            "router.request", cat="router", trace=f"router/{self.id}",
            args={"prompt_len": int(self.prompt.shape[0]),
                  "max_new_tokens": params.max_new_tokens})
        # fleet plane: one router.attempt span per submitted attempt
        # (distinct per retry/hedge), closed at whichever resolution
        # site fires first — finish() sweeps any survivors so the trace
        # is always nesting-complete; _observer (the router's SLO hook)
        # runs once at the terminal transition
        self._attempt_spans: Dict[int, object] = {}
        self._observer = None

    # -- deadline ------------------------------------------------------------
    def remaining_s(self) -> Optional[float]:
        if self.deadline_ts is None:
            return None
        return self.deadline_ts - time.perf_counter()

    # -- relay (engine threads) ----------------------------------------------
    def _on_attempt_token(self, gen: int, replica: str, token: int):
        deliver = False
        with self._lock:
            self._gen_counts[gen] = self._gen_counts.get(gen, 0) + 1
            idx = self._gen_counts[gen] - 1
            if gen == self._hedge_gen and not self.output_tokens \
                    and self._current_gen != gen:
                # hedge race: first token wins the request
                self._current_gen = gen
            if gen == self._current_gen and not self._done.is_set() \
                    and idx >= len(self.output_tokens):
                now = time.perf_counter()
                self.output_tokens.append(int(token))
                if self.first_token_ts is None:
                    self.first_token_ts = now
                self.last_token_ts = now
                self.replica = replica
                deliver = True
        if deliver:
            self._stream_q.put(int(token))
            if self.on_token is not None:
                try:
                    self.on_token(self, int(token))
                except Exception:  # noqa: BLE001 — consumer callback bug
                    pass

    def _set_current(self, gen: Optional[int]):
        with self._lock:
            self._current_gen = gen

    def _next_gen(self) -> int:
        return next(self._gen_iter)

    # -- fleet attempt spans -------------------------------------------------
    def _begin_attempt(self, gen: int, replica: str, hedge: bool,
                       trace_id: Optional[str]):
        sp = _trace.begin_span(
            "router.attempt", cat="router", trace=f"router/{self.id}",
            args={"gen": gen, "replica": replica, "hedge": hedge,
                  **({"trace_id": trace_id} if trace_id else {})})
        with self._lock:
            self._attempt_spans[gen] = sp

    def _end_attempt(self, gen: int, outcome: str):
        with self._lock:
            sp = self._attempt_spans.pop(gen, None)
        if sp is not None:
            _trace.end_span(sp, args={"outcome": outcome})

    # -- terminal ------------------------------------------------------------
    def finish(self, status: str, error: Optional[str] = None):
        with self._lock:
            if self.status in RequestStatus.FINAL:
                return
            self.status = status
            self.error = error
            self.finish_ts = time.perf_counter()
        _sm.router_requests_total.labels(status).inc()
        _trace.instant(status, cat="router", trace=f"router/{self.id}",
                       args={"generated": len(self.output_tokens),
                             **({"error": error} if error else {})})
        # close any attempt span still open (e.g. an in-flight attempt
        # at cancel/expire) before the root so children stay inside it
        for gen in list(self._attempt_spans):
            self._end_attempt(gen, status)
        _trace.end_span(self._root, args={"status": status,
                                          "retries": self.retries})
        if self._observer is not None:
            try:
                self._observer(self)
            except Exception:  # noqa: BLE001 — SLO accounting must never
                pass           # block a terminal transition
        self._stream_q.put(_STOP)
        self._done.set()

    # -- caller side ---------------------------------------------------------
    @property
    def done(self) -> bool:
        return self._done.is_set()

    def cancel(self):
        self.cancel_requested = True

    def result(self, timeout: Optional[float] = None) -> List[int]:
        if not self._done.wait(timeout):
            raise TimeoutError(
                f"router request {self.id} not finished within {timeout}s "
                f"(status={self.status})")
        return list(self.output_tokens)

    def stream(self, timeout: Optional[float] = None):
        while True:
            item = self._stream_q.get(timeout=timeout)
            if item is _STOP:
                return
            yield item

    @property
    def ttft_s(self) -> Optional[float]:
        if self.first_token_ts is None:
            return None
        return self.first_token_ts - self.arrival_ts

    @property
    def tpot_s(self) -> Optional[float]:
        if self.first_token_ts is None or self.last_token_ts is None:
            return None
        n = len(self.output_tokens) - 1
        if n <= 0:
            return None
        return (self.last_token_ts - self.first_token_ts) / n

    def debug_row(self) -> dict:
        return {
            "request_id": self.id, "status": self.status,
            "replica": self.replica,
            "generated": len(self.output_tokens),
            "retries": self.retries, "hedged": self.hedged,
            "attempts": list(self.attempts),
            "ttft_s": self.ttft_s, "tpot_s": self.tpot_s,
            "error": self.error,
        }


# ---------------------------------------------------------------------------
# the router
# ---------------------------------------------------------------------------

class Router:
    """See the module docstring. Construct over replica clients (or
    bare ``ServingEngine``s, wrapped into ``LocalReplica``), then
    ``submit()`` — each request is driven by its own daemon thread
    through route -> attempt -> (retry/hedge) -> terminal. ``start()``
    runs the background prober; tests drive ``probe_once()`` manually
    for determinism."""

    def __init__(self, replicas, config: Optional[RouterConfig] = None,
                 **overrides):
        if config is None:
            config = RouterConfig(**overrides)
        elif overrides:
            raise ValueError("pass RouterConfig OR keyword overrides, "
                             "not both")
        self.config = config
        self._lock = threading.Lock()
        self._replicas: Dict[str, _Replica] = {}
        self._rng = random.Random(config.seed)
        self._rng_lock = threading.Lock()
        self._rr_counter = itertools.count()
        self._requests = 0
        self._extra_attempts = 0   # retries + hedges (amplification)
        self._outcomes: Dict[str, int] = {}
        self._drivers: List[threading.Thread] = []
        self._running = False
        self._prober: Optional[threading.Thread] = None
        # bounded id -> RouterRequest registry: the merged-trace lookup
        # (GET /trace?request=<id> on router_http) needs the attempt
        # history after the caller's handle is gone
        self._recent: "Dict[int, RouterRequest]" = {}
        self.fleet_enabled = config.fleet_observability
        self._aggregator = _fleet.FleetMetricsAggregator()
        self._slo = _fleet.SLOTracker(config.slo or _fleet.SLOConfig())
        self._stragglers_flagged = 0
        # fingerprint -> where the quarantine was learned (replica name
        # or "retry"); merged from replica /stats and the retry path
        self._quarantined: Dict[str, str] = {}
        self._brownout = (
            _fleet.BrownoutController(
                recover_reports=config.brownout_recover_reports,
                min_dwell_s=config.brownout_min_dwell_s)
            if (config.brownout and config.fleet_observability) else None)
        for i, rep in enumerate(replicas):
            self.add_replica(rep, name=getattr(rep, "name", None) or f"r{i}")
        ref = weakref.ref(self)
        _trace.register_state_provider(
            "serving_router",
            lambda ref=ref: (ref().stats() if ref() is not None else None))
        _trace.register_state_provider(
            "serving_fleet",
            lambda ref=ref: (ref()._fleet_state()
                             if ref() is not None else None))

    # -- replica registry ----------------------------------------------------
    def add_replica(self, client, name: Optional[str] = None):
        """Register a replica (a client, or a bare engine). Local
        replicas are warmed up at registration (``auto_warmup``) and
        their background loop is started — a replica that enters
        rotation cold would pay its executable compiles out of the
        first routed request's deadline."""
        if isinstance(client, (ServingEngine, EngineSupervisor)):
            # a supervisor exposes the full engine surface, so the same
            # LocalReplica shim serves both: the router sees warm
            # restarts as a brief "restarting" 503, not a new replica
            client = LocalReplica(client)
        name = name or getattr(client, "name", None) \
            or f"r{len(self._replicas)}"
        client.name = name
        if self.config.auto_warmup and hasattr(client, "warmup"):
            try:
                warmed = bool(client.healthz().get("warmed_up"))
            except Exception:  # noqa: BLE001 — probe decides later
                warmed = True
            if not warmed:
                client.warmup()
        if hasattr(client, "start"):
            client.start()
        rep = _Replica(name, client)
        with self._lock:
            if name in self._replicas:
                raise ValueError(f"duplicate replica name {name!r}")
            self._replicas[name] = rep
        _sm.router_replica_healthy.labels(name).set(1)
        _trace.instant("replica_added", cat="router", args={"replica": name})
        return rep

    def remove_replica(self, name: str):
        with self._lock:
            rep = self._replicas.pop(name, None)
        if rep is not None:
            rep.state = ReplicaState.STOPPED
            _sm.router_replica_healthy.labels(name).set(0)
            self._aggregator.forget(name)

    def replicas(self) -> List[dict]:
        with self._lock:
            return [r.row() for r in self._replicas.values()]

    def _rep_list(self) -> List[_Replica]:
        with self._lock:
            return list(self._replicas.values())

    # -- health probing ------------------------------------------------------
    def probe_once(self):
        """One probe round over every replica (the background prober's
        body; tests call it directly for determinism). Straggler
        detection rides the probe cadence — deterministic for tests,
        and the flags update even when no traffic is flowing."""
        for rep in self._rep_list():
            if rep.state in (ReplicaState.DRAINING, ReplicaState.STOPPED):
                continue
            self._probe(rep)
        self.update_stragglers()
        if self._brownout is not None:
            # brownout rides the probe cadence: deterministic for tests
            # (probe_once() -> exactly one control tick), and the
            # min-dwell hysteresis keeps the 0.2s cadence from racing
            # the ladder up
            self._brownout.update(self._slo.report())

    def _probe(self, rep: _Replica):
        cfg = self.config
        try:
            payload = _call_with_timeout(rep.client.healthz,
                                         cfg.probe_timeout_s)
        except TimeoutError:
            return self._probe_failed(rep, "timeout")
        except Exception:  # noqa: BLE001 — any transport/client error
            return self._probe_failed(rep, "error")
        if not isinstance(payload, dict) \
                or not isinstance(payload.get("status"), str):
            return self._probe_failed(rep, "malformed")
        rep.last_probe = payload
        status = payload["status"]
        if status == "ok":
            return self._probe_ok(rep, payload)
        if status == "saturated":
            # alive, just backed up: not a failure, but back off
            rep.saturated_until = time.perf_counter() + float(
                payload.get("retry_after_s") or 1.0)
            return self._probe_ok(rep, payload)
        if status == "restarting":
            # a supervised replica mid warm-restart: alive, coming back
            # with a warmed engine in well under a probe-ejection window
            # — back off briefly rather than burn an ejection strike
            # (if the restart FAILS the breaker flips the payload to
            # "crashed" + restarts_exhausted and ejection proceeds)
            rep.saturated_until = time.perf_counter() + 0.1
            rep.consecutive_probe_failures = 0
            return None
        if status in ("draining", "stopped"):
            # the replica is going away on its own terms
            if rep.state != ReplicaState.STOPPED:
                rep.state = (ReplicaState.DRAINING if status == "draining"
                             else ReplicaState.STOPPED)
                _sm.router_replica_healthy.labels(rep.name).set(0)
            return None
        if status in ("crashed", "stalled"):
            return self._probe_failed(rep, status)
        return self._probe_failed(rep, "malformed")

    def _probe_failed(self, rep: _Replica, reason: str):
        rep.probe_failures += 1
        rep.consecutive_probe_failures += 1
        rep.ok_streak = 0
        _sm.router_probe_failures_total.labels(reason).inc()
        if rep.state == ReplicaState.HEALTHY \
                and rep.consecutive_probe_failures \
                >= self.config.probe_failures_to_eject:
            rep.state = ReplicaState.EJECTED
            rep.ejections += 1
            _sm.router_ejections_total.inc()
            _sm.router_replica_healthy.labels(rep.name).set(0)
            _trace.instant("replica_ejected", cat="router",
                           args={"replica": rep.name, "reason": reason})

    def _probe_ok(self, rep: _Replica, payload: dict):
        rep.consecutive_probe_failures = 0
        if rep.state != ReplicaState.EJECTED:
            return
        # readmission is gated on the WARMUP probe: an engine that
        # reports ok but hasn't AOT-compiled would pay its compiles out
        # of the first routed request's deadline
        if not payload.get("warmed_up", True):
            rep.ok_streak = 0
            return
        rep.ok_streak += 1
        if rep.ok_streak >= self.config.readmit_probes:
            rep.state = ReplicaState.HEALTHY
            rep.ok_streak = 0
            _sm.router_readmissions_total.inc()
            _sm.router_replica_healthy.labels(rep.name).set(1)
            _trace.instant("replica_readmitted", cat="router",
                           args={"replica": rep.name})

    # -- load-aware pick -----------------------------------------------------
    def _refresh_load(self, rep: _Replica, now: float):
        if now - rep.load.ts <= self.config.stats_refresh_s:
            return
        rep.load.ts = now  # claim the refresh window even on failure
        try:
            st = _call_with_timeout(rep.client.stats,
                                    self.config.stats_timeout_s)
        except Exception:  # noqa: BLE001 — slow/broken stats != dead
            rep.stats_errors += 1
            rep.load.stale = True
            return
        try:
            ld = rep.load
            ld.queue_depth = int(st.get("queue_depth", 0))
            ld.max_queue_depth = max(1, int(st.get("max_queue_depth", 1)))
            ld.slots_busy = int(st.get("slots_busy", 0))
            ld.slots = max(1, int(st.get("slots", 1)))
            kv = st.get("kv_blocks") or {}
            ld.util = float(kv.get("utilization",
                                   ld.slots_busy / ld.slots))
            digests = st.get("latency_digests") or {}
            dig = digests.get("ttft_s") or {}
            ld.ttft_p95 = dig.get("p95")
            ld.tpot_p50 = (digests.get("tpot_s") or {}).get("p50")
            ld.kv_tier = st.get("kv_tier")
            ld.stale = False
            # quarantine propagation: the supervisor's /stats block is
            # the fleet-wide gossip channel — one replica's verdict
            # blacklists the fingerprint at THIS router for every
            # replica, on the normal stats cadence (no new endpoint)
            sup = st.get("supervisor")
            if isinstance(sup, dict):
                for fp in sup.get("quarantined") or ():
                    self._learn_quarantine(str(fp), rep.name)
                # restart pressure: how close this replica sits to its
                # crash-loop breaker — fraction of the windowed restart
                # budget already burned. Scored via w_restart so the
                # fleet sheds load off a flapping replica proactively
                # instead of waiting for restarts_exhausted ejection.
                budget = max(1, int(sup.get("max_restarts", 1) or 1))
                ld.restart_pressure = min(
                    1.0, int(sup.get("restarts_in_window", 0)) / budget)
                ld.quarantined_count = len(sup.get("quarantined") or ())
            else:
                ld.restart_pressure = 0.0
                ld.quarantined_count = 0
        except (TypeError, ValueError):
            rep.stats_errors += 1
            rep.load.stale = True
        # federation rides the same staleness-bounded cadence: the
        # metrics scrape never adds a second timer or failure mode
        if self.fleet_enabled:
            self._scrape_metrics(rep, now)

    def _scrape_metrics(self, rep: _Replica, now: float):
        """Scrape one replica's /metrics into the federation aggregator
        — timeout-guarded like /stats, staleness-bounded by the same
        refresh knob. A hung or failing scrape marks the replica's
        series stale (last-known values keep serving); it NEVER ejects:
        only /healthz probes decide rotation."""
        fn = getattr(rep.client, "metrics_text", None)
        if fn is None:  # chaos fakes / minimal clients: nothing to scrape
            return
        if not self._aggregator.should_scrape(rep.name, now,
                                              self.config.stats_refresh_s):
            return
        try:
            text = _call_with_timeout(fn, self.config.stats_timeout_s)
            self._aggregator.update(rep.name, text, now)
        except Exception:  # noqa: BLE001 — slow/broken scrape != dead
            self._aggregator.mark_stale(rep.name)

    def _score(self, rep: _Replica, ttft_norm: float) -> float:
        cfg = self.config
        ld = rep.load
        return (cfg.w_inflight * rep.inflight / ld.slots
                + cfg.w_queue * ld.queue_depth / ld.max_queue_depth
                + cfg.w_util * ld.util
                + cfg.w_ttft * ttft_norm
                + cfg.w_restart * ld.restart_pressure
                + (cfg.straggler_penalty if rep.straggler else 0.0))

    def _pick(self, exclude=()) -> tuple:
        """(replica, reason): the lowest-score admitting replica, or
        (None, why-not)."""
        now = time.perf_counter()
        cands = []
        saturated = False
        for rep in self._rep_list():
            if rep.state != ReplicaState.HEALTHY or rep.name in exclude:
                continue
            if rep.saturated_until > now:
                saturated = True
                continue
            self._refresh_load(rep, now)
            cands.append(rep)
        if not cands:
            return None, ("saturated" if saturated else "no_healthy_replica")
        p95s = [r.load.ttft_p95 for r in cands if r.load.ttft_p95]
        max_p95 = max(p95s) if p95s else None

        def key(rep):
            tn = (rep.load.ttft_p95 / max_p95
                  if max_p95 and rep.load.ttft_p95 else 0.0)
            return (self._score(rep, tn), rep.inflight,
                    next(self._rr_counter))

        return min(cands, key=key), "ok"

    # -- submission ----------------------------------------------------------
    def submit(self, prompt, deadline_s: Optional[float] = None,
               on_token=None, params: Optional[SamplingParams] = None,
               **sampling) -> RouterRequest:
        """Route one request; returns its handle immediately (a daemon
        driver thread owns the route/retry/hedge loop). The same
        surface as ``ServingEngine.submit`` — outputs for a given
        prompt + seed are bit-identical to a single engine's, whatever
        failover happened along the way."""
        if params is None:
            params = SamplingParams(**sampling)
        elif sampling:
            raise ValueError("pass params OR sampling kwargs, not both")
        fp = request_fingerprint(
            np.asarray(prompt, dtype=np.int32).reshape(-1), params)
        with self._lock:
            poisoned = fp in self._quarantined
        if poisoned:
            _sm.router_poison_blocked_total.labels("submit").inc()
            raise PoisonedRequestError(
                f"{POISON_MARKER}: request fingerprint {fp} is "
                f"quarantined fleet-wide (it crashed serving engines "
                f"until its restart budget ran out) — do not resubmit",
                fingerprint=fp)
        if self._brownout is not None and self._brownout.shed_batch \
                and params.priority == "batch":
            _sm.requests_shed_total.labels("batch").inc()
            raise QueueFullError(
                f"brownout level {self._brownout.level_name!r}: "
                f"batch-class work is shed while the fleet SLO is "
                f"burning — retry later or resubmit as interactive")
        with self._lock:
            have_any = any(r.state != ReplicaState.STOPPED
                           for r in self._replicas.values())
        if not have_any:
            raise NoReplicaError(
                "router has no live replicas (none registered, or all "
                "drained/stopped) — add_replica() a warmed engine first")
        rr = RouterRequest(prompt, params, deadline_s, on_token)
        if self.fleet_enabled:
            rr._observer = self._observe_slo
        with self._lock:
            self._requests += 1
            self._recent[rr.id] = rr
            while len(self._recent) > self.config.recent_requests:
                self._recent.pop(next(iter(self._recent)))
        t = threading.Thread(target=self._drive, args=(rr,), daemon=True,
                             name=f"paddle-tpu-router-req-{rr.id}")
        t.start()
        return rr

    def _learn_quarantine(self, fp: str, source: str):
        """Blacklist a fingerprint router-wide (idempotent)."""
        with self._lock:
            if fp in self._quarantined:
                return
            self._quarantined[fp] = source
        _sm.router_poison_blocked_total.labels("learned").inc()
        _trace.instant("quarantine_learned", cat="router",
                       args={"fingerprint": fp, "source": source})

    def _observe_slo(self, rr: RouterRequest):
        """SLO observation at a request's terminal transition (the
        ``RouterRequest._observer`` hook). COMPLETED means completed
        within any deadline — EXPIRED is its own terminal state — so
        COMPLETED is exactly the goodput-good event."""
        self._slo.observe(rr.status, rr.ttft_s,
                          met_deadline=(rr.status
                                        == RequestStatus.COMPLETED))

    # -- the per-request driver ----------------------------------------------
    def _drive(self, rr: RouterRequest):
        cfg = self.config
        exclude: Dict[str, float] = {}
        unroutable_since: Optional[float] = None
        while True:
            if rr.cancel_requested:
                return rr.finish(RequestStatus.CANCELLED)
            rem = rr.remaining_s()
            if rem is not None and rem <= 0:
                return rr.finish(RequestStatus.EXPIRED,
                                 error="deadline passed while routing")
            rep, why = self._pick(exclude)
            if rep is None:
                _sm.router_unroutable_total.inc()
                if unroutable_since is None:
                    unroutable_since = time.perf_counter()
                elif time.perf_counter() - unroutable_since \
                        > cfg.unroutable_timeout_s:
                    return rr.finish(
                        RequestStatus.FAILED,
                        error=f"no admitting replica for "
                              f"{cfg.unroutable_timeout_s}s ({why}) — "
                              f"all replicas ejected, draining, or "
                              f"saturated")
                exclude.clear()  # reconsider everyone next round
                time.sleep(0.02)
                continue
            gen, handle, record = self._submit_attempt(rr, rep, hedge=False)
            if handle is None:
                if rr.done:
                    return  # fatal (bad request): finished inside
                # a refused submit does NOT reset the unroutable clock:
                # a fleet of replicas that all refuse must time out, not
                # loop forever between pick and refusal
                if unroutable_since is None:
                    unroutable_since = time.perf_counter()
                exclude[rep.name] = time.perf_counter()
                continue
            unroutable_since = None
            outcome = self._await(rr, rep, gen, handle, record)
            if outcome in ("done", "cancelled", "expired"):
                return
            # retriable: the attempt died with its replica
            exclude[rep.name] = time.perf_counter()
            if rr.cancel_requested:
                return rr.finish(RequestStatus.CANCELLED)
            ok, why_not = self._may_retry(rr)
            if not ok:
                return rr.finish(
                    RequestStatus.FAILED,
                    error=f"attempt on replica {rep.name!r} failed and "
                          f"{why_not}; last error: {record.get('error')}")
            with self._lock:
                self._extra_attempts += 1
            rr.retries += 1
            _sm.router_retries_total.inc()
            _trace.instant("retry", cat="router", trace=f"router/{rr.id}",
                           args={"n": rr.retries, "from": rep.name})
            if not self._retry_backoff(rr):
                return  # finished EXPIRED inside

    def _submit_attempt(self, rr: RouterRequest, rep: _Replica,
                        hedge: bool) -> tuple:
        """(gen, handle, attempt_record); handle None = not submitted
        (rejected/refused, record says why — or ``rr`` finished for a
        caller error no replica can fix)."""
        with self._lock:
            poisoned = rr.fingerprint in self._quarantined
        if poisoned:
            # quarantined between submission and this (re)try: the
            # retry path must not carry the poison to a fresh replica
            _sm.router_poison_blocked_total.labels("retry").inc()
            rr.finish(RequestStatus.FAILED,
                      error=f"{POISON_MARKER}: request fingerprint "
                            f"{rr.fingerprint} was quarantined while "
                            f"in flight — not retried")
            return 0, None, {"replica": rep.name, "outcome": "poisoned",
                             "hedge": hedge, "error": None,
                             "trace_id": None}
        gen = rr._next_gen()
        if hedge:
            with rr._lock:
                rr._hedge_gen = gen
        else:
            rr._set_current(gen)

        def _relay(_inner, tok, rr=rr, gen=gen, name=rep.name):
            rr._on_attempt_token(gen, name, tok)

        rem = rr.remaining_s()
        params = self._attempt_params(rr)
        # fleet trace propagation: each attempt (retry/hedge included)
        # gets a DISTINCT deterministic trace id — the replica-side span
        # tree records under it and the merged catapult file shows one
        # swimlane per attempt
        tid = (_fleet.attempt_trace_id(rr.id, gen)
               if self.fleet_enabled else None)
        record = {"replica": rep.name, "outcome": "submitted",
                  "hedge": hedge, "error": None, "trace_id": tid}
        rr.attempts.append(record)
        try:
            if tid is not None:
                try:
                    handle = rep.client.submit(
                        rr.prompt, deadline_s=rem, on_token=_relay,
                        params=params, trace_id=tid)
                except TypeError:
                    # pre-fleet client (no trace_id kwarg): submit
                    # without propagation rather than failing the
                    # request over an observability feature
                    record["trace_id"] = tid = None
                    handle = rep.client.submit(
                        rr.prompt, deadline_s=rem, on_token=_relay,
                        params=params)
            else:
                handle = rep.client.submit(rr.prompt, deadline_s=rem,
                                           on_token=_relay,
                                           params=params)
        except PoisonedRequestError as e:
            # the replica's supervisor already blacklisted this
            # fingerprint (its /stats hadn't been merged yet): learn it
            # and fail terminally — a poison verdict is never retried
            self._learn_quarantine(e.fingerprint or rr.fingerprint,
                                   rep.name)
            _sm.router_poison_blocked_total.labels("retry").inc()
            record.update(outcome="poisoned", error=repr(e))
            rr.finish(RequestStatus.FAILED, error=str(e))
            return gen, None, record
        except QueueFullError as e:
            rep.saturated_until = time.perf_counter() + \
                _sm.queue_wait_retry_after()
            record.update(outcome="rejected", error=str(e))
            return gen, None, record
        except (EngineStoppedError, RuntimeError) as e:
            # crashed / draining / stopped replica: routing failure,
            # probes will eject it — try elsewhere now
            rep.submit_failures += 1
            record.update(outcome="refused", error=repr(e))
            return gen, None, record
        except (TypeError, ValueError) as e:
            # caller error (bad prompt/params): no replica can help
            record.update(outcome="bad_request", error=repr(e))
            rr.finish(RequestStatus.FAILED, error=f"bad request: {e}")
            return gen, None, record
        rep.attempts += 1
        rep.inflight += 1
        _sm.router_attempts_total.inc()
        _sm.router_replica_inflight.labels(rep.name).set(rep.inflight)
        rr.status = RequestStatus.RUNNING
        rr._begin_attempt(gen, rep.name, hedge, tid)
        _trace.instant("routed", cat="router", trace=f"router/{rr.id}",
                       args={"replica": rep.name, "hedge": hedge})
        return gen, handle, record

    def _attempt_params(self, rr: RouterRequest) -> SamplingParams:
        """The params one attempt actually submits: under brownout,
        batch-class work gets its decode length clamped (level >=
        ``cap_batch_tokens``) and everyone's speculation width capped
        (level >= ``shrink_spec``) — explicit, per-attempt degradation
        that never mutates the caller's ``rr.params``."""
        bo = self._brownout
        if bo is None:
            return rr.params
        p = rr.params
        changes = {}
        if bo.cap_batch_tokens and p.priority == "batch" \
                and p.max_new_tokens > \
                self.config.brownout_batch_max_new_tokens:
            changes["max_new_tokens"] = \
                self.config.brownout_batch_max_new_tokens
        if bo.shrink_spec and p.spec_k > self.config.brownout_spec_k_cap:
            changes["spec_k"] = self.config.brownout_spec_k_cap
        return _dc_replace(p, **changes) if changes else p

    def _release_attempt(self, rep: _Replica):
        rep.inflight = max(0, rep.inflight - 1)
        _sm.router_replica_inflight.labels(rep.name).set(rep.inflight)

    def _abandon(self, rr: RouterRequest, item, reason: str):
        """Detach + cancel an attempt the request is moving away from:
        its relay generation is no longer current, so even if the
        replica keeps decoding (a hung step that later resumes), its
        ``on_token`` pushes are dropped — the caller never sees a
        token from a replica the request failed away from."""
        rep, gen, handle, record = item
        try:
            rep.client.cancel(handle)
        except Exception:  # noqa: BLE001 — dead replica: nothing to cancel
            pass
        record["outcome"] = reason
        rr._end_attempt(gen, reason)
        self._release_attempt(rep)

    def _await(self, rr: RouterRequest, rep: _Replica, gen: int,
               handle, record: dict) -> str:
        """Wait out one attempt; returns "done" | "cancelled" |
        "expired" | "retriable". Handles hedging: the watch set grows
        to two attempts and the first token decides the winner."""
        cfg = self.config
        att_t0 = time.perf_counter()
        watch = [(rep, gen, handle, record)]
        hedged_here = False
        while True:
            # terminal checks the replicas can't make for us
            if rr.cancel_requested:
                for item in watch:
                    self._abandon(rr, item, "cancelled")
                rr.finish(RequestStatus.CANCELLED)
                return "cancelled"
            rem = rr.remaining_s()
            if rem is not None and rem <= -0.05:
                # the replica enforces the same deadline; the slack only
                # covers a replica too wedged to expire it itself
                for item in watch:
                    self._abandon(rr, item, "expired")
                rr.finish(RequestStatus.EXPIRED,
                          error="deadline passed during decode")
                return "expired"
            # finished attempts
            for item in list(watch):
                r, g, h, rec = item
                if not h.done:
                    continue
                watch.remove(item)
                self._release_attempt(r)
                rr._end_attempt(g, h.status)
                with rr._lock:
                    is_current = (g == rr._current_gen)
                if not is_current:
                    # superseded (lost hedge / abandoned): bookkeeping
                    # only — its tokens were dropped by the relay
                    rec["outcome"] = ("hedge_lost"
                                      if h.status == RequestStatus.COMPLETED
                                      else "stale_" + h.status)
                    rec["error"] = h.error
                    continue
                if h.status == RequestStatus.COMPLETED:
                    rec["outcome"] = "completed"
                    for other in watch:  # hedge loser still running
                        self._abandon(rr, other, "hedge_lost")
                    rr.replica = r.name
                    rr.finish(RequestStatus.COMPLETED)
                    return "done"
                if h.status == RequestStatus.EXPIRED:
                    rec["outcome"] = "expired"
                    for other in watch:
                        self._abandon(rr, other, "expired")
                    rr.finish(RequestStatus.EXPIRED,
                              error=h.error or "deadline passed")
                    return "expired"
                if h.status == RequestStatus.CANCELLED \
                        and rr.cancel_requested:
                    rec["outcome"] = "cancelled"
                    rr.finish(RequestStatus.CANCELLED)
                    return "cancelled"
                if h.error and POISON_MARKER in str(h.error):
                    # the replica's supervisor quarantined this request
                    # MID-FLIGHT (it was implicated in its last allowed
                    # crash). The marker rides the terminal error string
                    # — which survives the HTTP NDJSON done-record — so
                    # the verdict propagates on the retry path too:
                    # terminal here, blacklisted everywhere.
                    rec["outcome"] = "poisoned"
                    rec["error"] = h.error
                    self._learn_quarantine(rr.fingerprint, r.name)
                    _sm.router_poison_blocked_total.labels("retry").inc()
                    for other in watch:
                        self._abandon(rr, other, "poisoned")
                    rr.finish(RequestStatus.FAILED, error=h.error)
                    return "done"
                # FAILED / REJECTED / engine-side cancel we didn't ask
                # for: the attempt died with its replica -> retriable
                rec["outcome"] = "failed"
                rec["error"] = h.error
                if watch:
                    # a hedge is still racing: promote it to current
                    r2, g2, _h2, _rec2 = watch[0]
                    rr._set_current(g2)
                    rep = r2
                    continue
                return "retriable"
            if not watch:
                return "retriable"
            # replica ejected/stopped under a live attempt (hang or
            # crash the probe saw first): abandon and fail over
            for item in list(watch):
                r, g, h, rec = item
                if r.state in (ReplicaState.EJECTED, ReplicaState.STOPPED):
                    watch.remove(item)
                    with rr._lock:
                        lost_current = (g == rr._current_gen)
                        if lost_current:
                            rr._current_gen = None
                    self._abandon(rr, item, "replica_lost")
                    rec["error"] = f"replica {r.name!r} {r.state} with " \
                                   f"the attempt in flight"
                    if lost_current and watch:
                        r2, g2, _h2, _rec2 = watch[0]
                        rr._set_current(g2)
                        rep = r2
            if not watch:
                return "retriable"
            # hedging: first token slower than the digest-derived
            # threshold -> race a second replica (suppressed from
            # brownout level "no_hedge" up: a hedge is a deliberate
            # duplicate, the first capacity to reclaim under overload)
            if cfg.hedge and not hedged_here and not rr.output_tokens \
                    and len(watch) == 1 \
                    and not (self._brownout is not None
                             and self._brownout.hedge_disabled):
                p95 = watch[0][0].load.ttft_p95
                threshold = max(cfg.hedge_min_wait_s,
                                cfg.hedge_ttft_factor * p95 if p95 else 0.0)
                if time.perf_counter() - att_t0 > threshold:
                    hedged_here = True
                    cand, _why = self._pick(exclude=(watch[0][0].name,))
                    if cand is not None:
                        g2, h2, rec2 = self._submit_attempt(
                            rr, cand, hedge=True)
                        if h2 is not None:
                            rr.hedged = True
                            with self._lock:
                                self._extra_attempts += 1
                            _sm.router_hedges_total.inc()
                            _trace.instant(
                                "hedged", cat="router",
                                trace=f"router/{rr.id}",
                                args={"to": cand.name,
                                      "from": watch[0][0].name})
                            watch.append((cand, g2, h2, rec2))
            # once a hedge race is decided (first token), cancel the
            # loser immediately instead of letting it decode to the end
            if len(watch) > 1 and rr.output_tokens:
                with rr._lock:
                    cur = rr._current_gen
                for item in list(watch):
                    if item[1] != cur:
                        watch.remove(item)
                        self._abandon(rr, item, "hedge_lost")
            # block on the primary's completion event when it has one
            # (push wake-up); fall back to a short poll slice
            ev = getattr(watch[0][2], "_done", None)
            if ev is not None:
                ev.wait(0.01)
            else:
                time.sleep(0.005)

    # -- retry policy --------------------------------------------------------
    def _may_retry(self, rr: RouterRequest) -> tuple:
        cfg = self.config
        if rr.cancel_requested:
            return False, "the request was cancelled (cancelled requests " \
                          "are never retried)"
        if rr.retries >= cfg.max_retries_per_request:
            return False, (f"its retry budget is exhausted "
                           f"({cfg.max_retries_per_request} retries)")
        with self._lock:
            cap = (cfg.retry_amplification_cap * max(1, self._requests)
                   + cfg.retry_amplification_floor)
            if self._extra_attempts + 1 > cap:
                return False, (
                    f"the global retry-amplification cap is exhausted "
                    f"({self._extra_attempts} extra attempts vs cap "
                    f"{cap:.1f} = {cfg.retry_amplification_cap} x "
                    f"{self._requests} requests + "
                    f"{cfg.retry_amplification_floor}) — a failure storm "
                    f"must shed load, not multiply it")
        return True, ""

    def _retry_backoff(self, rr: RouterRequest) -> bool:
        """Capped exponential backoff with seeded jitter, bounded by
        the remaining deadline. Returns False (after finishing the
        request EXPIRED) when the deadline cannot survive the wait."""
        cfg = self.config
        delay = min(cfg.retry_backoff_base_s * (2 ** (rr.retries - 1)),
                    cfg.retry_backoff_max_s)
        with self._rng_lock:
            delay *= 1.0 + cfg.retry_jitter * self._rng.uniform(-1.0, 1.0)
        delay = max(delay, 0.0)
        rem = rr.remaining_s()
        if rem is not None and rem <= delay:
            rr.finish(RequestStatus.EXPIRED,
                      error=f"deadline would pass during retry backoff "
                            f"({delay:.3f}s wait, {max(rem, 0):.3f}s left)")
            return False
        end = time.perf_counter() + delay
        while time.perf_counter() < end:
            if rr.cancel_requested:
                rr.finish(RequestStatus.CANCELLED)
                return False
            time.sleep(min(0.01, max(end - time.perf_counter(), 0)))
        return True

    # -- fleet observability plane -------------------------------------------
    def update_stragglers(self):
        """Recompute per-replica straggler flags: robust modified
        z-score (MAD) of each healthy replica's TPOT p50 against the
        fleet, one-sided (only SLOW outliers are stragglers — an
        unusually fast replica is a gift, not a fault). Flag
        transitions emit a trace instant and bump the counter;
        detection never ejects — at most it adds the configured
        admission-score penalty."""
        cfg = self.config
        if not (self.fleet_enabled and cfg.straggler_detection):
            return
        now = time.perf_counter()
        healthy = [r for r in self._rep_list()
                   if r.state == ReplicaState.HEALTHY]
        for rep in healthy:
            self._refresh_load(rep, now)
        sampled = [r for r in healthy if r.load.tpot_p50 is not None]
        if len(sampled) < cfg.straggler_min_replicas:
            for rep in healthy:
                self._set_straggler(rep, False)
            return
        zs = _fleet.mad_zscores([r.load.tpot_p50 for r in sampled])
        flagged = {r.name for r, z in zip(sampled, zs)
                   if z > cfg.straggler_mad_threshold}
        for rep in healthy:
            self._set_straggler(rep, rep.name in flagged)

    def _set_straggler(self, rep: _Replica, flag: bool):
        if flag and not rep.straggler:
            self._stragglers_flagged += 1
            _sm.router_stragglers_total.inc()
            _trace.instant("replica_straggler", cat="router",
                           args={"replica": rep.name,
                                 "tpot_p50": rep.load.tpot_p50})
        elif rep.straggler and not flag:
            _trace.instant("replica_recovered", cat="router",
                           args={"replica": rep.name})
        rep.straggler = flag
        _sm.router_replica_straggler.labels(rep.name).set(1 if flag else 0)

    def federated_metrics_text(self) -> str:
        """The fleet's federated Prometheus exposition (router
        ``GET /metrics``): every replica's series under a
        ``replica=<name>`` label plus ``replica="fleet"`` roll-ups.
        Refreshes due scrapes first (staleness-bounded, timeout-
        guarded) so the endpoint works with no traffic flowing."""
        if self.fleet_enabled:
            now = time.perf_counter()
            for rep in self._rep_list():
                if rep.state == ReplicaState.STOPPED:
                    continue
                self._scrape_metrics(rep, now)
        return self._aggregator.render()

    def slo_report(self) -> dict:
        """The fleet SLO verdict (router ``GET /slo``): per-objective
        multi-window burn rates and ok/breach flags, plus the brownout
        ladder state the verdict drives."""
        out = self._slo.report()
        if self._brownout is not None:
            out["brownout"] = self._brownout.report()
        return out

    def merged_trace(self, request_id: int) -> Optional[dict]:
        """ONE catapult file for one routed request: the router's own
        lane plus each attempt's replica-side span tree, fetched by the
        attempt's propagated trace id and merged side by side — a
        crash-failover request renders attempt 1 on the dead replica
        and attempt 2 on the survivor. None for an unknown/evicted id.
        Attempt fetches are timeout-guarded; an unreachable replica
        costs its lane, not the merge."""
        with self._lock:
            rr = self._recent.get(request_id)
        if rr is None:
            return None
        parts = [(f"router request {request_id}",
                  _trace.chrome_trace(f"router/{request_id}"))]
        for i, att in enumerate(list(rr.attempts), 1):
            tid = att.get("trace_id")
            if not tid:
                continue
            with self._lock:
                rep = self._replicas.get(att.get("replica"))
            fn = getattr(rep.client, "trace_events", None) \
                if rep is not None else None
            if fn is None:
                continue
            try:
                events = _call_with_timeout(
                    lambda fn=fn, tid=tid: fn(tid),
                    self.config.stats_timeout_s)
            except Exception:  # noqa: BLE001 — lane lost, merge survives
                continue
            if not (events or {}).get("traceEvents"):
                continue  # refused/rejected attempt: nothing replica-side
            parts.append(
                (f"attempt {i} [{att.get('replica')}]"
                 f"{' (hedge)' if att.get('hedge') else ''}", events))
        return _fleet.merge_catapult(parts)

    def _fleet_state(self) -> Optional[dict]:
        """Flight-recorder state provider: the fleet plane's view in
        crash dumps / ``observability.snapshot()``."""
        if not self.fleet_enabled:
            return None
        return {
            "slo": self._slo.report(),
            "federation": self._aggregator.stats(),
            "stragglers": {r.name: r.straggler
                           for r in self._rep_list()},
            "stragglers_flagged": self._stragglers_flagged,
            "brownout": (self._brownout.report()
                         if self._brownout is not None else None),
            "quarantined": sorted(self._quarantined),
        }

    # -- drain / lifecycle ---------------------------------------------------
    def drain(self, name: str, timeout_s: Optional[float] = None,
              wait: bool = True):
        """Gracefully take a replica out of rotation: stop routing to
        it immediately, let its in-flight requests finish (the
        engine-side drain), then mark it stopped. New traffic keeps
        flowing to the other replicas the whole time."""
        with self._lock:
            rep = self._replicas.get(name)
        if rep is None:
            raise KeyError(f"no replica named {name!r}")
        rep.state = ReplicaState.DRAINING
        _sm.router_replica_healthy.labels(name).set(0)
        _sm.router_drains_total.inc()
        _trace.instant("replica_draining", cat="router",
                       args={"replica": name})
        timeout_s = timeout_s if timeout_s is not None \
            else self.config.drain_timeout_s

        def _do():
            try:
                rep.client.drain(timeout_s)
            except Exception:  # noqa: BLE001 — a dead replica is drained
                pass
            rep.state = ReplicaState.STOPPED

        if wait:
            _do()
        else:
            threading.Thread(target=_do, daemon=True,
                             name=f"paddle-tpu-router-drain-{name}").start()

    def drain_all(self, timeout_s: Optional[float] = None):
        """Drain every replica concurrently (the SIGTERM path)."""
        names = [r.name for r in self._rep_list()
                 if r.state in (ReplicaState.HEALTHY, ReplicaState.EJECTED)]
        threads = [threading.Thread(target=self.drain,
                                    args=(n, timeout_s), daemon=True)
                   for n in names]
        for t in threads:
            t.start()
        for t in threads:
            t.join()

    def start(self):
        """Run the background prober (health gating without manual
        ``probe_once()`` calls)."""
        with self._lock:
            if self._running:
                return self
            self._running = True
        self._prober = threading.Thread(target=self._probe_loop,
                                        name="paddle-tpu-router-prober",
                                        daemon=True)
        self._prober.start()
        return self

    def _probe_loop(self):
        while self._running:
            self.probe_once()
            time.sleep(self.config.probe_interval_s)

    def stop(self, drain: bool = False,
             timeout_s: Optional[float] = None):
        """Stop the prober; ``drain=True`` also drains every replica
        (graceful full shutdown)."""
        self._running = False
        if self._prober is not None:
            self._prober.join(timeout=max(1.0,
                                          self.config.probe_interval_s * 4))
            self._prober = None
        if drain:
            self.drain_all(timeout_s)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()
        return False

    # -- introspection -------------------------------------------------------
    def stats(self) -> dict:
        with self._lock:
            requests = self._requests
            extra = self._extra_attempts
            quarantined = dict(self._quarantined)
        return {
            "replicas": self.replicas(),
            "requests": requests,
            "extra_attempts": extra,
            "amplification": round(1.0 + extra / requests, 4)
            if requests else None,
            "quarantine": {"fingerprints": sorted(quarantined),
                           "sources": quarantined},
            "brownout": (self._brownout.report()
                         if self._brownout is not None else None),
            "fleet": {
                "enabled": self.fleet_enabled,
                "federation": self._aggregator.stats(),
                "stragglers_flagged": self._stragglers_flagged,
                "slo_observed": self._slo.observed,
            },
            "config": {
                "probe_failures_to_eject":
                    self.config.probe_failures_to_eject,
                "max_retries_per_request":
                    self.config.max_retries_per_request,
                "retry_amplification_cap":
                    self.config.retry_amplification_cap,
                "hedge": self.config.hedge,
                "straggler_penalty": self.config.straggler_penalty,
                "brownout": self.config.brownout,
            },
        }
