"""Serving metrics, registered at import so a scrape of the
observability HTTP endpoint shows serving state (queue depth, slot
occupancy, TTFT/TPOT) without anyone having to take a snapshot first.

Names follow the ``paddle_tpu_serving_*`` prefix; all instruments live
in the shared observability registry (lock-free writer hot path), so
``observability.prometheus_text()`` / ``/metrics`` pick them up
automatically.
"""

from __future__ import annotations

from ..observability import metrics as _m

__all__ = [
    "requests_total", "tokens_total", "queue_depth", "slots_busy",
    "slot_occupancy", "steps_total", "step_seconds", "prefill_seconds",
    "ttft_seconds", "tpot_seconds", "engine_crashes_total",
]

requests_total = _m.counter(
    "paddle_tpu_serving_requests_total",
    "serving requests by terminal outcome", ("outcome",))
tokens_total = _m.counter(
    "paddle_tpu_serving_tokens_total",
    "tokens through the serving engine (prompt = prefilled, "
    "generated = decoded)", ("kind",))
queue_depth = _m.gauge(
    "paddle_tpu_serving_queue_depth",
    "requests waiting for a decode slot")
slots_busy = _m.gauge(
    "paddle_tpu_serving_slots_busy",
    "decode slots currently running a request")
slot_occupancy = _m.gauge(
    "paddle_tpu_serving_slot_occupancy",
    "busy fraction of the decode slot pool (0..1)")
steps_total = _m.counter(
    "paddle_tpu_serving_steps_total",
    "batched decode steps executed")
engine_crashes_total = _m.counter(
    "paddle_tpu_serving_engine_crashes_total",
    "decode-loop crashes outside the per-request guards (every queued "
    "and running request is failed, /healthz flips unhealthy)")
engine_unhealthy = _m.gauge(
    "paddle_tpu_serving_engine_unhealthy",
    "1 while the most recent serving engine is crash-dead; constructing "
    "a fresh engine resets it (drives /healthz 503s)")
step_seconds = _m.histogram(
    "paddle_tpu_serving_step_seconds",
    "wall time of one batched decode step",
    buckets=(0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
             0.5, 1.0, 2.5, 5.0))
prefill_seconds = _m.histogram(
    "paddle_tpu_serving_prefill_seconds",
    "wall time of one bucketed prefill (+ cache splice)",
    buckets=(0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
             1.0, 2.5, 5.0, 10.0, 30.0))
ttft_seconds = _m.histogram(
    "paddle_tpu_serving_ttft_seconds",
    "time to first token (request arrival -> first token delivered)",
    buckets=(0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
             10.0, 30.0, 60.0))
tpot_seconds = _m.histogram(
    "paddle_tpu_serving_tpot_seconds",
    "per-token decode latency (time between consecutive tokens of one "
    "request)",
    buckets=(0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
             0.5, 1.0, 2.5))
