"""Serving metrics, registered at import so a scrape of the
observability HTTP endpoint shows serving state (queue depth, slot
occupancy, TTFT/TPOT) without anyone having to take a snapshot first.

Names follow the ``paddle_tpu_serving_*`` prefix; all instruments live
in the shared observability registry (lock-free writer hot path), so
``observability.prometheus_text()`` / ``/metrics`` pick them up
automatically.
"""

from __future__ import annotations

from ..observability import metrics as _m
# the roofline gauges live in observability.perf (they cover training
# entries too); re-exported here so the serving surface registers and
# names every gauge its /stats + /debug/memory endpoints publish
from ..observability.perf import hbm_bw_util_gauge, mfu_gauge

__all__ = [
    "mfu_gauge", "hbm_bw_util_gauge",
    "requests_total", "tokens_total", "queue_depth", "slots_busy",
    "slot_occupancy", "steps_total", "step_seconds", "prefill_seconds",
    "ttft_seconds", "tpot_seconds", "engine_crashes_total",
    "kv_blocks_total", "kv_blocks_in_use", "kv_blocks_shared",
    "prefix_cache_hits", "prefix_cache_misses", "prefix_cache_evictions",
    "cow_forks_total", "preemptions_total", "prefill_chunks_total",
    "kv_bytes_per_token",
    "kv_tier_demoted_blocks", "kv_tier_readmitted_blocks",
    "kv_tier_readmitted_tokens", "kv_tier_spills", "kv_tier_disk_loads",
    "kv_tier_disk_skipped", "kv_tier_host_blocks", "kv_tier_host_bytes",
    "kv_tier_disk_entries",
    "ttft_summary", "tpot_summary", "queue_wait_seconds",
    "prefill_chunk_seconds", "goodput_tokens_per_second",
    "latency_digests", "spec_drafted_tokens", "spec_accepted_tokens",
    "spec_rejected_tokens", "spec_accept_len", "spec_accept_depth",
    "spec_tree_nodes_drafted", "spec_tree_nodes_accepted",
    "queue_wait_retry_after",
    "queue_wait_p50",
    "requests_shed_total", "deadline_rejected_total",
    "supervisor_restarts_total", "supervisor_requeued_total",
    "requests_quarantined_total",
    "router_requests_total", "router_attempts_total",
    "router_retries_total", "router_hedges_total",
    "router_probe_failures_total", "router_ejections_total",
    "router_readmissions_total", "router_drains_total",
    "router_replica_healthy", "router_replica_inflight",
    "router_unroutable_total",
    "router_stragglers_total", "router_replica_straggler",
    "router_poison_blocked_total",
]

requests_total = _m.counter(
    "paddle_tpu_serving_requests_total",
    "serving requests by terminal outcome", ("outcome",))
tokens_total = _m.counter(
    "paddle_tpu_serving_tokens_total",
    "tokens through the serving engine (prompt = prefilled, "
    "generated = decoded)", ("kind",))
queue_depth = _m.gauge(
    "paddle_tpu_serving_queue_depth",
    "requests waiting for a decode slot")
slots_busy = _m.gauge(
    "paddle_tpu_serving_slots_busy",
    "decode slots currently running a request")
slot_occupancy = _m.gauge(
    "paddle_tpu_serving_slot_occupancy",
    "busy fraction of the decode slot pool (0..1)")
steps_total = _m.counter(
    "paddle_tpu_serving_steps_total",
    "batched decode steps executed")
engine_crashes_total = _m.counter(
    "paddle_tpu_serving_engine_crashes_total",
    "decode-loop crashes outside the per-request guards (every queued "
    "and running request is failed, /healthz flips unhealthy)")
# -- self-healing supervision (serving/supervisor.py) ----------------------
supervisor_restarts_total = _m.counter(
    "paddle_tpu_serving_supervisor_restarts_total",
    "warm engine restarts the supervisor performed after a decode-loop "
    "crash (fresh pools + warmup() zero-compile boot; innocent "
    "requests requeued, crash suspects re-admitted as solo probes)")
supervisor_requeued_total = _m.counter(
    "paddle_tpu_serving_supervisor_requeued_total",
    "requests carried across a supervised engine restart instead of "
    "failed, by where the crash caught them ('queued' = waiting for a "
    "slot, untouched by the crashing step; 'running' = active in the "
    "crashing step, requeued under the seed-deterministic PRNG replay "
    "so the resumed output stays bit-identical)", ("kind",))
requests_quarantined_total = _m.counter(
    "paddle_tpu_serving_quarantined_total",
    "requests failed terminally as poison: their fingerprint was "
    "implicated in the quarantine budget's worth of distinct engine "
    "crashes, and no replica will re-admit it")
# -- priority-aware overload control (DAGOR-style shedding) ----------------
requests_shed_total = _m.counter(
    "paddle_tpu_serving_requests_shed_total",
    "queued requests shed (REJECTED) to admit a higher-priority class "
    "under queue pressure, by the shed request's class", ("cls",))
deadline_rejected_total = _m.counter(
    "paddle_tpu_serving_deadline_rejected_total",
    "requests rejected at admission because their deadline could not "
    "beat the live queue-wait p50 (429 + Retry-After: failing fast "
    "beats queueing work that is already dead), by class", ("cls",))
engine_unhealthy = _m.gauge(
    "paddle_tpu_serving_engine_unhealthy",
    "1 while the most recent serving engine is crash-dead; constructing "
    "a fresh engine resets it (drives /healthz 503s)")
# -- paged KV cache (block pool + prefix sharing) --------------------------
kv_blocks_total = _m.gauge(
    "paddle_tpu_kv_blocks_total",
    "usable KV blocks in the device pool (excludes the reserved dump "
    "block)")
kv_blocks_in_use = _m.gauge(
    "paddle_tpu_kv_blocks_in_use",
    "KV blocks currently allocated (request-owned or prefix-cached)")
kv_blocks_shared = _m.gauge(
    "paddle_tpu_kv_blocks_shared",
    "KV blocks with more than one reference (COW-protected prefix "
    "sharing)")
prefix_cache_hits = _m.counter(
    "paddle_tpu_prefix_cache_hits_total",
    "prompt KV blocks adopted from the prefix cache instead of "
    "prefilled")
prefix_cache_misses = _m.counter(
    "paddle_tpu_prefix_cache_misses_total",
    "prompt KV blocks that had to be prefilled (no cached prefix)")
prefix_cache_evictions = _m.counter(
    "paddle_tpu_prefix_cache_evictions_total",
    "prefix-cache entries evicted (LRU) to reclaim pool blocks, by what "
    "happened to the KV: 'demoted' = copied down to the host tier, "
    "'dropped' = freed outright (no tier, or the cost model said "
    "recompute is cheaper)", ("outcome",))
cow_forks_total = _m.counter(
    "paddle_tpu_serving_cow_forks_total",
    "copy-on-write forks: first divergent write into a shared KV block")
preemptions_total = _m.counter(
    "paddle_tpu_serving_preemptions_total",
    "running requests preempted (blocks reclaimed, requeued for "
    "recompute) under KV-pool pressure")
prefill_chunks_total = _m.counter(
    "paddle_tpu_serving_prefill_chunks_total",
    "fixed-size prefill chunks executed (chunked-prefill admission)")
# -- hierarchical KV tiers (serving/kv_tier.py: host RAM + disk) -----------
kv_tier_demoted_blocks = _m.counter(
    "paddle_tpu_kv_tier_demoted_blocks_total",
    "KV blocks demoted device->host instead of freed, by trigger "
    "('evict' = prefix-cache LRU victim, 'preempt' = preempted "
    "request's private blocks, 'flush' = drain-time persistence "
    "sweep, 'promote' = disk entry pulled back into host RAM)",
    ("reason",))
kv_tier_readmitted_blocks = _m.counter(
    "paddle_tpu_kv_tier_readmitted_blocks_total",
    "demoted KV blocks spliced host->HBM at admission instead of "
    "recomputed, by source tier", ("src",))
kv_tier_readmitted_tokens = _m.counter(
    "paddle_tpu_kv_tier_readmitted_tokens_total",
    "prompt tokens whose prefill was skipped because their block was "
    "re-admitted from a lower tier (the recompute work the hierarchy "
    "saved)")
kv_tier_spills = _m.counter(
    "paddle_tpu_kv_tier_spills_total",
    "tier entries committed to the persistent disk store (host-LRU "
    "spill victims + drain-time flush; each one an atomic-commit "
    "write)")
kv_tier_disk_loads = _m.counter(
    "paddle_tpu_kv_tier_disk_loads_total",
    "tier entries loaded (deep-verified) from the persistent disk "
    "store")
kv_tier_disk_skipped = _m.counter(
    "paddle_tpu_kv_tier_disk_skipped_total",
    "persisted spill entries refused at scan or load: 'corrupt' = "
    "uncommitted / digest-mismatch (kill-mid-spill debris), "
    "'incompatible' = written by a different engine configuration "
    "(fingerprint mismatch)", ("reason",))
kv_tier_host_blocks = _m.gauge(
    "paddle_tpu_kv_tier_host_blocks",
    "KV blocks currently resident in the host-RAM tier")
kv_tier_host_bytes = _m.gauge(
    "paddle_tpu_kv_tier_host_bytes",
    "host RAM the resident tier entries occupy (values + quant scales "
    "+ draft-model rows, at quantized width)")
kv_tier_disk_entries = _m.gauge(
    "paddle_tpu_kv_tier_disk_entries",
    "committed entries in the persistent disk tier")
# -- quantized KV (int8/fp8 block pools) -----------------------------------
kv_bytes_per_token = _m.gauge(
    "paddle_tpu_kv_bytes_per_token",
    "HBM bytes one cached token costs across all layers (K+V values "
    "plus, for quantized formats, the per-token-per-head f32 absmax "
    "scales) — set per engine at construction; the capacity math "
    "bf16_bytes / fmt_bytes is the pool-size multiplier a fixed HBM "
    "budget buys", ("format",))
# -- speculative decoding (draft-model engines) ----------------------------
spec_drafted_tokens = _m.counter(
    "paddle_tpu_serving_spec_drafted_tokens_total",
    "draft tokens proposed to speculative verify rounds")
spec_accepted_tokens = _m.counter(
    "paddle_tpu_serving_spec_accepted_tokens_total",
    "draft tokens accepted by the target model (each one a decode step "
    "the pool did not have to run)")
spec_rejected_tokens = _m.counter(
    "paddle_tpu_serving_spec_rejected_tokens_total",
    "draft tokens rejected at verify (the round still emits the "
    "target's own token, so rejection costs draft work, never output)")
# tree lane (ServingConfig.spec_tree): node accounting is distinct from
# the token counters above — a tree drafts width-1 NODES per round but
# can accept at most depth of them (one root-to-leaf path), so node
# accept RATE is structurally low even when every path matches; the
# depth histogram is the tuning surface (shift width toward the depths
# that actually accept)
spec_tree_nodes_drafted = _m.counter(
    "paddle_tpu_serving_spec_tree_nodes_drafted_total",
    "draft tree nodes proposed to tree-speculative verify rounds "
    "(tree width - 1 per live row per round)")
spec_tree_nodes_accepted = _m.counter(
    "paddle_tpu_serving_spec_tree_nodes_accepted_total",
    "draft tree nodes on accepted root-to-leaf paths (each one a decode "
    "step the pool did not have to run)")
spec_accept_depth = _m.histogram(
    "paddle_tpu_serving_spec_accept_depth",
    "accepted path depth per tree-speculative verify round (0 = only "
    "the root's own target token emitted, d = a depth-d draft path "
    "fully matched)",
    buckets=(0, 1, 2, 3, 4, 6, 8, 12, 16))

step_seconds = _m.histogram(
    "paddle_tpu_serving_step_seconds",
    "wall time of one batched decode step",
    buckets=(0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
             0.5, 1.0, 2.5, 5.0))
prefill_seconds = _m.histogram(
    "paddle_tpu_serving_prefill_seconds",
    "wall time of one bucketed prefill (+ cache splice)",
    buckets=(0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
             1.0, 2.5, 5.0, 10.0, 30.0))
ttft_seconds = _m.histogram(
    "paddle_tpu_serving_ttft_seconds",
    "time to first token (request arrival -> first token delivered)",
    buckets=(0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
             10.0, 30.0, 60.0))
tpot_seconds = _m.histogram(
    "paddle_tpu_serving_tpot_seconds",
    "per-token decode latency (time between consecutive tokens of one "
    "request)",
    buckets=(0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
             0.5, 1.0, 2.5))

# -- streaming latency digests (summaries: exact p50/p95/p99 over a
# sliding sample window — the tails the fixed histogram buckets above
# quantize away; surfaced on /stats and in observability.snapshot()) ----
ttft_summary = _m.summary(
    "paddle_tpu_serving_ttft_summary_seconds",
    "time to first token, streaming p50/p95/p99 over the recent window")
tpot_summary = _m.summary(
    "paddle_tpu_serving_tpot_summary_seconds",
    "inter-token decode latency, streaming p50/p95/p99 over the recent "
    "window")
queue_wait_seconds = _m.summary(
    "paddle_tpu_serving_queue_wait_seconds",
    "time a request waited for a decode slot (submission-or-requeue -> "
    "admission), streaming p50/p95/p99")
prefill_chunk_seconds = _m.summary(
    "paddle_tpu_serving_prefill_chunk_seconds",
    "host wall time of one chunked-prefill dispatch, streaming "
    "p50/p95/p99")
spec_accept_len = _m.summary(
    "paddle_tpu_serving_spec_accept_len_summary",
    "accepted draft tokens per speculative verify round (0..k), "
    "streaming p50/p95/p99 — the live accept-length distribution the "
    "spec_k knob should be tuned against")
goodput_tokens_per_second = _m.gauge(
    "paddle_tpu_serving_goodput_tokens_per_second",
    "deadline-met throughput: tokens of requests that COMPLETED within "
    "their deadline (or had none), per second over the recent window — "
    "the number a load-aware router balances on (tokens delivered past "
    "a deadline are work, not goodput)")

# -- multi-replica router (serving/router.py) ------------------------------
router_requests_total = _m.counter(
    "paddle_tpu_router_requests_total",
    "router requests by terminal outcome", ("outcome",))
router_attempts_total = _m.counter(
    "paddle_tpu_router_attempts_total",
    "replica submissions the router made (first attempts + retries + "
    "hedges) — attempts/requests is the amplification factor the retry "
    "cap bounds")
router_retries_total = _m.counter(
    "paddle_tpu_router_retries_total",
    "requests re-submitted to another replica after their attempt died "
    "with the replica (crash/eject/stop)")
router_hedges_total = _m.counter(
    "paddle_tpu_router_hedges_total",
    "tail-latency hedges: a second replica was raced because TTFT "
    "exceeded the digest-derived threshold")
router_probe_failures_total = _m.counter(
    "paddle_tpu_router_probe_failures_total",
    "health-probe failures by reason (error/timeout/malformed/crashed)",
    ("reason",))
router_ejections_total = _m.counter(
    "paddle_tpu_router_ejections_total",
    "replicas ejected from rotation after K consecutive probe failures")
router_readmissions_total = _m.counter(
    "paddle_tpu_router_readmissions_total",
    "ejected replicas re-admitted after passing the warmup probe")
router_drains_total = _m.counter(
    "paddle_tpu_router_drains_total",
    "graceful replica drains initiated through the router")
router_unroutable_total = _m.counter(
    "paddle_tpu_router_unroutable_total",
    "requests that found no admitting replica (all ejected/draining/"
    "saturated) at some point in their routing loop")
router_replica_healthy = _m.gauge(
    "paddle_tpu_router_replica_healthy",
    "1 while the replica is in rotation (0 = ejected/draining/stopped)",
    ("replica",))
router_replica_inflight = _m.gauge(
    "paddle_tpu_router_replica_inflight",
    "router-attributed in-flight attempts per replica", ("replica",))
router_stragglers_total = _m.counter(
    "paddle_tpu_router_stragglers_total",
    "straggler flag transitions: a replica's TPOT p50 crossed the "
    "robust-MAD deviation threshold vs the fleet median (detection, "
    "not ejection — the replica stays in rotation)")
router_replica_straggler = _m.gauge(
    "paddle_tpu_router_replica_straggler",
    "1 while the replica's decode cadence is a robust-MAD outlier vs "
    "the fleet median (optionally fed into the admission score via "
    "RouterConfig.straggler_penalty)", ("replica",))
router_poison_blocked_total = _m.counter(
    "paddle_tpu_router_poison_blocked_total",
    "router-side poison verdicts: submissions refused for a quarantined "
    "fingerprint plus attempts failed terminally on a replica's "
    "PoisonedRequestError (either way, the poison never reaches "
    "another engine)", ("site",))

_DIGESTS = {
    "ttft_s": ttft_summary,
    "tpot_s": tpot_summary,
    "queue_wait_s": queue_wait_seconds,
    "prefill_chunk_s": prefill_chunk_seconds,
}


def queue_wait_retry_after(default: float = 1.0) -> float:
    """Retry-After hint for saturated/backpressure responses: the
    queue-wait digest's p50 is the best live estimate of when a slot
    frees up (falls back to ``default`` before any sample lands)."""
    quantiles, _total, count = queue_wait_seconds._d().snapshot()
    if not count:
        return default
    p50 = quantiles.get(0.5)
    if p50 is None:
        return default
    return max(round(float(p50), 3), 0.05)


def queue_wait_p50(min_count: int = 8) -> "float | None":
    """The queue-wait digest's live p50, or ``None`` before the digest
    has ``min_count`` samples — the deadline-feasibility estimate the
    scheduler rejects against. The warm-up guard matters: rejecting on
    one early outlier would turn a cold start into a 429 storm."""
    quantiles, _total, count = queue_wait_seconds._d().snapshot()
    if count < min_count:
        return None
    p50 = quantiles.get(0.5)
    return None if p50 is None else float(p50)


def latency_digests() -> dict:
    """Percentile snapshot of every serving latency digest — the
    ``/stats`` ``latency_digests`` block and the CI trace summary."""
    out = {}
    for name, s in _DIGESTS.items():
        quantiles, total, count = s._d().snapshot()
        out[name] = {f"p{round(q * 100)}": v for q, v in quantiles.items()}
        out[name]["count"] = count
        out[name]["mean"] = (total / count) if count else None
    return out
