"""HTTP front end for the multi-replica router — the process boundary
of the serving fleet. Same zero-dependency stdlib pattern as
``serving.http`` (which fronts ONE engine; this fronts the router that
fans out over many).

- ``POST /generate`` — the ``serving.http`` request surface, routed:
  body/response identical (plus ``"replica"``, ``"retries"``,
  ``"hedged"`` in the record), streaming via ``"stream": true``.
  Failover/retry/hedging happen underneath; the client sees each token
  once. ``503`` + ``Retry-After`` when no replica can admit
  (saturation), ``400`` for bad requests. A QUARANTINED fingerprint
  (a poison request that crashed supervised engines until its budget
  ran out) gets an actionable ``400`` — ``{"quarantined": true,
  "fingerprint": ..., "retriable": false}`` — whether refused at
  submit or convicted mid-flight; batch-class work shed under SLO
  brownout gets ``429`` + ``Retry-After``.
- ``GET /healthz`` — fleet health: 200 while at least one replica is in
  rotation; 503 payload distinguishes ``draining`` (shutdown in
  progress) from ``unavailable`` (everything ejected). Per-replica
  states ride along.
- ``GET /stats`` — ``router.stats()`` (replica table, amplification,
  outcome counts).
- ``GET /replicas`` — just the replica table (incl. the ``straggler``
  flag per replica).
- ``GET /metrics`` — the FEDERATED Prometheus exposition: every
  replica's series relabeled ``replica=<name>`` plus ``replica="fleet"``
  roll-ups (summed counters/histograms, count-weighted merged summary
  digests, fleet goodput). Scrapes are staleness-bounded and
  timeout-guarded; a hung replica serves last-known series flagged by
  ``paddle_tpu_fleet_scrape_stale``.
- ``GET /slo`` — the fleet SLO verdict: per-objective (availability /
  goodput / ttft_p95) multi-window burn rates with ok/breach flags.
- ``GET /trace?request=<id>`` — ONE merged catapult file for a routed
  request: the router's lane + each attempt's replica-side swimlane
  (fetched by the propagated trace id), 404 for unknown/evicted ids.
- ``POST /drain`` — body ``{"replica": name}`` drains one replica,
  ``{}`` drains ALL (graceful fleet shutdown); non-blocking, poll
  ``/replicas``.

SIGTERM → graceful drain: ``install_sigterm_drain(router)`` registers a
fault-tolerance preemption listener (``fault_tolerance.preemption``),
so the signal stops admission, finishes in-flight requests on every
replica, and leaves the router reporting ``draining``/``stopped`` —
instead of the old behavior (process death fails every in-flight
request with no recovery).
"""

from __future__ import annotations

import json
import threading
import time

from .http import retry_after_header
from .request import RequestStatus
from .router import NoReplicaError, ReplicaState, Router
from .scheduler import QueueFullError
from .supervisor import POISON_MARKER, PoisonedRequestError

__all__ = ["RouterHTTPServer", "install_sigterm_drain",
           "uninstall_sigterm_drain"]


def _record(rr) -> dict:
    return {
        "request_id": rr.id,
        "status": rr.status,
        "prompt_len": int(rr.prompt.shape[0]),
        "tokens": list(rr.output_tokens),
        "ttft_s": rr.ttft_s,
        "tpot_s": rr.tpot_s,
        "latency_s": (rr.finish_ts - rr.arrival_ts
                      if rr.finish_ts else None),
        "replica": rr.replica,
        "retries": rr.retries,
        "hedged": rr.hedged,
        "error": rr.error,
    }


def router_health(router: Router) -> tuple:
    """(http_status, payload): fleet-level health — 200 while anyone is
    admitting."""
    rows = router.replicas()
    states = [r["state"] for r in rows]
    payload = {"ts": time.time(), "replicas": rows,
               "healthy_replicas": states.count(ReplicaState.HEALTHY)}
    if payload["healthy_replicas"] > 0:
        payload["status"] = "ok"
        return 200, payload
    if states and all(s in (ReplicaState.DRAINING, ReplicaState.STOPPED)
                      for s in states):
        payload["status"] = "draining" \
            if ReplicaState.DRAINING in states else "stopped"
    else:
        payload["status"] = "unavailable"
    return 503, payload


class RouterHTTPServer:
    """The router served over HTTP on a daemon thread; ``port=0`` binds
    a free port (``.port``). ``sigterm_drain=True`` additionally wires
    SIGTERM/SIGINT to a graceful fleet drain."""

    def __init__(self, router: Router, port: int = 0,
                 addr: str = "127.0.0.1", request_timeout_s: float = 300.0,
                 sigterm_drain: bool = False):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        self.router = router
        router.start()  # background prober: health gating needs no caller
        if sigterm_drain:
            install_sigterm_drain(router)

        class _Handler(BaseHTTPRequestHandler):
            def _json(self, code: int, payload: dict, headers=None):
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                path = self.path.split("?")[0]
                if path == "/healthz":
                    code, payload = router_health(router)
                    self._json(code, payload)
                elif path == "/stats":
                    self._json(200, router.stats())
                elif path == "/replicas":
                    self._json(200, {"replicas": router.replicas()})
                elif path == "/metrics":
                    body = router.federated_metrics_text().encode()
                    self.send_response(200)
                    self.send_header(
                        "Content-Type",
                        "text/plain; version=0.0.4; charset=utf-8")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                elif path == "/slo":
                    self._json(200, router.slo_report())
                elif path == "/trace":
                    req_id = None
                    for kv in self.path.partition("?")[2].split("&"):
                        k, _, v = kv.partition("=")
                        if k == "request" and v:
                            try:
                                req_id = int(v)
                            except ValueError:
                                pass
                    if req_id is None:
                        self._json(400, {"error": "GET /trace?request=<id>"})
                        return
                    merged = router.merged_trace(req_id)
                    if merged is None:
                        self._json(404, {"error": f"no routed request "
                                                  f"{req_id} in the recent "
                                                  f"registry"})
                        return
                    self._json(200, merged)
                else:
                    self._json(404, {"error": f"no such path {path!r}"})

            def do_POST(self):
                path = self.path.split("?")[0]
                if path == "/drain":
                    try:
                        length = int(self.headers.get("Content-Length", 0))
                        body = json.loads(self.rfile.read(length) or b"{}")
                    except (ValueError, json.JSONDecodeError) as e:
                        self._json(400, {"error": f"bad request: {e}"})
                        return
                    name = body.get("replica")
                    try:
                        if name is None:
                            threading.Thread(
                                target=router.drain_all,
                                args=(body.get("timeout_s"),),
                                daemon=True).start()
                        else:
                            router.drain(name, body.get("timeout_s"),
                                         wait=False)
                    except KeyError as e:
                        self._json(404, {"error": str(e)})
                        return
                    self._json(200, {"draining": name or "all"})
                    return
                if path != "/generate":
                    self._json(404, {"error": "POST /generate or /drain"})
                    return
                try:
                    length = int(self.headers.get("Content-Length", 0))
                    body = json.loads(self.rfile.read(length) or b"{}")
                    prompt = body.pop("prompt")
                    stream = bool(body.pop("stream", False))
                    deadline_s = body.pop("deadline_s", None)
                    if not isinstance(prompt, (list, tuple)) or not prompt:
                        raise ValueError("prompt must be a non-empty list "
                                         "of token ids")
                except (ValueError, KeyError, json.JSONDecodeError) as e:
                    self._json(400, {"error": f"bad request: {e}"})
                    return
                try:
                    rr = router.submit(prompt, deadline_s=deadline_s,
                                       **body)
                except NoReplicaError as e:
                    self._json(503, {"error": str(e)},
                               headers=retry_after_header(
                                   {"retry_after_s": e.retry_after_s or 1}))
                    return
                except PoisonedRequestError as e:
                    # fleet-wide quarantine verdict: an actionable 400 —
                    # the body names the fingerprint and says never to
                    # resubmit (a 429/503 would invite the retry that
                    # crash-loops fleets)
                    self._json(400, {"error": str(e),
                                     "quarantined": True,
                                     "fingerprint": e.fingerprint,
                                     "retriable": False})
                    return
                except QueueFullError as e:
                    # brownout shed (batch class under SLO burn): 429 +
                    # Retry-After — deferrable work comes back later
                    ra = getattr(e, "retry_after_s", None) or 1
                    self._json(429, {"error": str(e), "retry_after_s": ra},
                               headers=retry_after_header(
                                   {"retry_after_s": ra}))
                    return
                except (TypeError, ValueError) as e:
                    self._json(400, {"error": f"bad request: {e}"})
                    return
                if not stream:
                    try:
                        rr.result(timeout=request_timeout_s)
                    except TimeoutError:
                        rr.cancel()
                        try:
                            rr.result(timeout=10.0)
                        except TimeoutError:
                            pass
                    rec = _record(rr)
                    if rr.status == RequestStatus.FAILED and rr.error \
                            and "no admitting replica" in rr.error:
                        self._json(503, rec, headers=retry_after_header(
                            {"retry_after_s": 1}))
                        return
                    if rr.status == RequestStatus.FAILED and rr.error \
                            and POISON_MARKER in rr.error:
                        # quarantined MID-FLIGHT (the request was
                        # implicated in its last allowed crash): same
                        # actionable 400 as the submit-time refusal
                        rec["quarantined"] = True
                        rec["fingerprint"] = rr.fingerprint
                        rec["retriable"] = False
                        self._json(400, rec)
                        return
                    self._json(200, rec)
                    return
                self.send_response(200)
                self.send_header("Content-Type", "application/jsonl")
                self.end_headers()
                try:
                    for tok in rr.stream(timeout=request_timeout_s):
                        self.wfile.write(
                            (json.dumps({"token": int(tok)}) + "\n").encode())
                        self.wfile.flush()
                except (TimeoutError, BrokenPipeError, ConnectionResetError):
                    rr.cancel()
                done = dict(_record(rr))
                done["done"] = True
                try:
                    self.wfile.write((json.dumps(done) + "\n").encode())
                except (BrokenPipeError, ConnectionResetError):
                    pass

            def log_message(self, *args):
                pass

        self._server = ThreadingHTTPServer((addr, port), _Handler)
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            name=f"paddle-tpu-router-http:{self.port}", daemon=True)
        self._thread.start()

    def stop(self):
        self._server.shutdown()
        self._server.server_close()


# -- SIGTERM -> graceful drain ----------------------------------------------

_drain_listeners = {}


def install_sigterm_drain(router: Router,
                          timeout_s=None) -> None:
    """Wire SIGTERM/SIGINT (and programmatic
    ``fault_tolerance.request_preemption()``) to a graceful fleet
    drain: stop admitting, finish in-flight requests on every replica,
    then stop. The drain runs off the signal-handler thread — the
    handler only flips the flag."""
    from ..fault_tolerance.preemption import (add_preemption_listener,
                                              install_preemption_handler)

    if router in _drain_listeners:
        return

    def _on_preempt(reason: str, router=router, timeout_s=timeout_s):
        threading.Thread(target=router.drain_all, args=(timeout_s,),
                         name="paddle-tpu-router-sigterm-drain",
                         daemon=True).start()

    install_preemption_handler()
    add_preemption_listener(_on_preempt)
    _drain_listeners[router] = _on_preempt


def uninstall_sigterm_drain(router: Router) -> None:
    from ..fault_tolerance.preemption import remove_preemption_listener

    fn = _drain_listeners.pop(router, None)
    if fn is not None:
        remove_preemption_listener(fn)
