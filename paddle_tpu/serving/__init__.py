"""paddle_tpu.serving — continuous-batching inference engine over
slot-based static KV caches.

The north-star workload is "serve heavy traffic from millions of
users"; ``generation.generate`` is one request at a time, whole-batch
lockstep. This package is the request-level layer above the same
static-shape decode substrate:

- ``engine``:    ``ServingEngine`` — a fixed pool of decode slots over
                 pre-allocated [B, max_len, h, d] KV buffers; bucketed
                 padded prefill, ``dynamic_update_slice`` cache splice,
                 ONE jitted decode step for the whole pool (per-slot
                 positions/sampling params/PRNG keys as traced arrays),
                 slots freed on EOS/max-tokens and refilled immediately.
- ``scheduler``: FCFS admission, max-queue-depth backpressure
                 (``QueueFullError``), deadlines, cancellation.
- ``request``:   ``Request`` handles — blocking ``result()``, streaming
                 ``stream()`` iterator, per-token callbacks.
- ``metrics``:   requests/tokens counters, queue-depth + slot-occupancy
                 gauges, TTFT/TPOT histograms in the shared
                 observability registry (registered at import so
                 scrapes always show serving state).
- ``http``:      opt-in stdlib HTTP front end
                 (``start_serving_http_server``).

Quick start::

    from paddle_tpu import serving
    eng = serving.ServingEngine(model, max_slots=8, max_len=512)
    eng.start()                      # background loop (or drive step())
    req = eng.submit(prompt_ids, max_new_tokens=64, eos_token_id=2)
    for tok in req.stream():         # tokens as the decode lands them
        ...
"""

from __future__ import annotations

from . import metrics  # registers the serving gauges at import
from .engine import ServingConfig, ServingEngine
from .http import start_serving_http_server, stop_serving_http_server
from .request import Request, RequestStatus, SamplingParams
from .scheduler import QueueFullError, Scheduler

__all__ = [
    "ServingConfig", "ServingEngine", "SamplingParams", "Request",
    "RequestStatus", "Scheduler", "QueueFullError",
    "start_serving_http_server", "stop_serving_http_server",
]
