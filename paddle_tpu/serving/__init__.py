"""paddle_tpu.serving — continuous-batching inference engine over a
paged (block-pool) KV cache.

The north-star workload is "serve heavy traffic from millions of
users"; ``generation.generate`` is one request at a time, whole-batch
lockstep. This package is the request-level layer above the same
static-shape decode substrate:

- ``engine``:     ``ServingEngine`` — a fixed pool of decode slots whose
                  KV lives in a shared pool of device blocks addressed
                  through per-slot traced block tables (capacity bounded
                  by tokens in flight, not slots * worst-case length);
                  chunked prefill, ref-counted copy-on-write prefix
                  sharing, preemption-by-recompute under pool pressure,
                  ONE jitted decode step for the whole pool (per-slot
                  positions/params/keys/block tables as traced arrays),
                  slots freed on EOS/max-tokens and refilled
                  immediately. ``kv_mode="contiguous"`` keeps the
                  pre-paging per-slot-buffer engine as the A/B baseline.
                  ``draft_model=`` adds SPECULATIVE DECODING: a small
                  draft proposes ``spec_k`` tokens per slot, the target
                  scores the whole bundle in one paged flash-decode
                  call, and each slot advances by its own accept length
                  through the block tables — outputs stay bit-identical
                  to plain decode (greedy and sampled), speculation only
                  moves throughput.
- ``block_pool``: host-side KV block allocator (free list + refcounts,
                  exhaustion/double-free errors, fragmentation stats)
                  and the exact-prefix LRU cache behind prefix sharing
                  (eviction-callback hook feeding the tier below).
- ``kv_tier``:    hierarchical KV under the block pool
                  (``ServingConfig(kv_tier=True)``): prefix-cache
                  eviction victims and preempted requests' blocks
                  demote device->host instead of being freed, a
                  returning prefix re-admits via one jitted host->HBM
                  splice instead of prefill chunks (cost model:
                  transfer bytes vs the perf ledger's measured
                  recompute rate), and an optional disk tier
                  (``kv_tier_path``) persists the prefix cache across
                  engine restarts with atomic-commit crash safety.
- ``scheduler``:  FCFS admission, max-queue-depth backpressure
                  (``QueueFullError``), deadlines, cancellation,
                  front-of-queue requeue for preempted requests.
- ``request``:    ``Request`` handles — blocking ``result()``, streaming
                  ``stream()`` iterator, per-token callbacks.
- ``metrics``:    requests/tokens counters, queue-depth + slot-occupancy
                  + KV-block gauges, prefix-cache/COW/preemption
                  counters, TTFT/TPOT histograms in the shared
                  observability registry (registered at import so
                  scrapes always show serving state).
- ``http``:       opt-in stdlib HTTP front end (``ServingHTTPServer`` /
                  ``start_serving_http_server``) with split /healthz 503
                  states (crashed/draining/saturated/stalled) and
                  digest-derived Retry-After.
- ``router``:     multi-replica layer: ``Router`` spreads requests over
                  N engine replicas (``LocalReplica``/``HTTPReplica``)
                  with load-aware admission, health-gated failover
                  (probe ejection + warmup-gated readmission),
                  deadline-aware retries whose failover outputs are
                  bit-identical to a single engine, optional TTFT
                  hedging, and graceful drain.
- ``router_http``: the router's HTTP front end (``RouterHTTPServer``)
                  + SIGTERM -> fleet drain.
- ``supervisor``: self-healing layer over one engine
                  (``EngineSupervisor``): warm in-process restart after
                  a decode-loop crash (fresh pools, zero-compile
                  warmup, innocent queued+running requests requeued on
                  the seed-deterministic replay — same handles, same
                  bytes), a crash-loop breaker, and poison-request
                  quarantine (``PoisonedRequestError``) whose
                  fingerprint blacklist the router propagates
                  fleet-wide via /stats and the retry path.
- ``chaos``:      deterministic fault injection (``ChaosEngine``,
                  ``ChaosReplica``, restart-surviving
                  ``SupervisedChaos`` with fingerprint-targeted poison
                  faults) powering the router chaos suite.

Quick start::

    from paddle_tpu import serving
    eng = serving.ServingEngine(model, max_slots=8, max_len=512)
    eng.start()                      # background loop (or drive step())
    req = eng.submit(prompt_ids, max_new_tokens=64, eos_token_id=2)
    for tok in req.stream():         # tokens as the decode lands them
        ...
"""

from __future__ import annotations

from . import metrics  # registers the serving gauges at import
from .block_pool import (BlockPool, BlockPoolError, PoolExhaustedError,
                         PrefixCache)
from .chaos import ChaosEngine, ChaosError, ChaosReplica, SupervisedChaos
from .engine import (EngineDrainingError, EngineStoppedError, ServingConfig,
                     ServingEngine)
from .http import (ServingHTTPServer, start_serving_http_server,
                   stop_serving_http_server)
from .kv_tier import DiskPrefixStore, KVTier, TierCostModel
from .request import (PRIORITY_CLASSES, Request, RequestStatus,
                      SamplingParams, request_fingerprint)
from .router import (HTTPReplica, LocalReplica, NoReplicaError, ReplicaState,
                     Router, RouterConfig, RouterRequest)
from .router_http import (RouterHTTPServer, install_sigterm_drain,
                          uninstall_sigterm_drain)
from .scheduler import DeadlineInfeasibleError, QueueFullError, Scheduler
from .supervisor import EngineSupervisor, PoisonedRequestError

__all__ = [
    "ServingConfig", "ServingEngine", "SamplingParams", "Request",
    "RequestStatus", "Scheduler", "QueueFullError",
    "DeadlineInfeasibleError", "PRIORITY_CLASSES", "request_fingerprint",
    "EngineSupervisor", "PoisonedRequestError",
    "EngineStoppedError", "EngineDrainingError",
    "BlockPool", "PrefixCache", "PoolExhaustedError", "BlockPoolError",
    "KVTier", "TierCostModel", "DiskPrefixStore",
    "ServingHTTPServer", "start_serving_http_server",
    "stop_serving_http_server",
    "Router", "RouterConfig", "RouterRequest", "ReplicaState",
    "LocalReplica", "HTTPReplica", "NoReplicaError",
    "RouterHTTPServer", "install_sigterm_drain", "uninstall_sigterm_drain",
    "ChaosEngine", "ChaosReplica", "ChaosError", "SupervisedChaos",
]
