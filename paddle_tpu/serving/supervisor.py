"""Self-healing supervision for the serving engine: warm restart after
a decode-loop crash, with innocent requests carried across the restart
and deterministically-crashing "poison" requests quarantined.

The PR-4 crash path is honest but brutal: a decode-loop death fails
EVERY queued and running request on the replica, and only a fresh
engine recovers. That is the right floor for an unsupervised engine —
``result()`` callers must never hang — but it turns one bad step into
a replica-wide outage, and a request that deterministically crashes
the step (a "poison" request) then rides the router's retry path to
the next replica and crash-loops the whole fleet. ``EngineSupervisor``
closes both holes in-process:

- **Warm restart.** The supervisor installs the engine's crash hook
  (``_crash_hook``), which runs inside ``_on_loop_crash`` after the
  flight dump but BEFORE ``_fail_inflight`` — the only window in which
  capture is possible, because ``Request.finish`` is idempotent and
  irreversible. The hook detaches every queued request (never touched
  by the crashing step) and every running request (rebuilt onto the
  seed-deterministic PRNG replay used by preemption, so the resumed
  decode is bit-identical), then a restart thread builds a FRESH
  engine from the same model/config, ``warmup()``s it (the zero-
  compile boot: a fresh engine's first compiles are warmup entries,
  not retraces), requeues the survivors at the queue front in FCFS
  order, and swaps it in. Callers holding ``Request`` handles notice
  nothing but a latency blip: same objects, same streams, same bytes.

- **Crash-loop breaker.** Restarts are budgeted: more than
  ``max_restarts`` inside ``restart_window_s`` means the crash is not
  transient — the supervisor stays crashed, fails anything pending
  with an explicit error, and ``/healthz`` reports ``crashed`` with
  ``restarts_exhausted`` so the router ejects the replica exactly as
  it would an unsupervised corpse.

- **Poison quarantine.** The requests RUNNING in the crashing step are
  suspects. Suspects are requeued flagged ``quarantine_probe``: the
  engine admits a probe only into an idle pool, alone, so a repeat
  crash implicates exactly one fingerprint instead of smearing
  suspicion over innocent co-runners. A fingerprint implicated in
  ``quarantine_crashes`` distinct crashes fails terminally with a
  ``PoisonedRequestError`` message, lands on the supervisor-wide
  blacklist, and is refused at ``submit()`` from then on. The router
  learns the blacklist from ``/stats`` (its normal load-refresh
  cadence) and from the error marker on the retry path, so no replica
  fleet-wide re-admits the fingerprint: one poison request costs at
  most ``quarantine_crashes`` restarts across the whole fleet.

The supervisor exposes the ENGINE surface (``submit`` / ``cancel`` /
``health`` / ``stats`` / ``warmup`` / ``start`` / ``stop`` / ``drain``
+ attribute delegation for everything else), so it drops in wherever a
``ServingEngine`` goes: ``LocalReplica(EngineSupervisor(...))`` under
a router, or ``ServingHTTPServer(EngineSupervisor(...))`` behind HTTP.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Optional

import numpy as np

from ..observability import tracing as _tracing
from . import metrics as _sm
from .engine import ServingEngine
from .request import (Request, RequestStatus, SamplingParams,
                      request_fingerprint)

__all__ = ["EngineSupervisor", "PoisonedRequestError", "POISON_MARKER"]

# the marker every quarantine surface carries: the terminal Request
# error string, the HTTP error body, and the router's retry path all
# match on it, so "is this failure poison?" is one substring test that
# survives serialization across the replica boundary
POISON_MARKER = "PoisonedRequestError"


class PoisonedRequestError(ValueError):
    """The request's fingerprint is quarantined: it was implicated in
    the quarantine budget's worth of distinct engine crashes, and no
    replica will re-admit it. Subclasses ``ValueError`` deliberately —
    every existing bad-request surface (HTTP 400, the router's
    terminal ``bad_request`` taxonomy) already treats it as
    non-retriable, which is exactly the quarantine contract: retrying
    poison is how fleets crash-loop."""

    def __init__(self, msg: str, fingerprint: Optional[str] = None):
        super().__init__(msg)
        self.fingerprint = fingerprint


class EngineSupervisor:
    """Wraps a ``ServingEngine`` with warm restart, a crash-loop
    breaker, and poison-request quarantine. Construction mirrors
    ``ServingEngine``: pass a ``ServingConfig`` or field overrides.

    >>> sup = EngineSupervisor(model, max_slots=4, max_len=128)
    >>> sup.warmup(); sup.start()
    >>> req = sup.submit(prompt, max_new_tokens=32)   # engine surface
    """

    GUARDED_BY = {
        "_engine": "_lock", "_pending": "_lock", "_implicated": "_lock",
        "_quarantined": "_lock", "_restart_ts": "_lock",
        "_restarting": "_lock", "_broken": "_lock", "_crashes": "_lock",
        "_restarts": "_lock", "_started": "_lock",
        "_last_restart_s": "_lock",
    }

    def __init__(self, model, config=None, draft_model=None,
                 max_restarts: int = 3, restart_window_s: float = 60.0,
                 quarantine_crashes: int = 2,
                 restart_grace_s: float = 30.0,
                 warmup_on_restart: bool = True, **overrides):
        if max_restarts < 1:
            raise ValueError("max_restarts must be >= 1: a supervisor "
                             "that never restarts is just an engine")
        if quarantine_crashes < 1:
            raise ValueError("quarantine_crashes must be >= 1")
        self._model = model
        self._draft_model = draft_model
        self.max_restarts = int(max_restarts)
        self.restart_window_s = float(restart_window_s)
        self.quarantine_crashes = int(quarantine_crashes)
        self.restart_grace_s = float(restart_grace_s)
        self.warmup_on_restart = bool(warmup_on_restart)

        self._lock = threading.RLock()
        self._pending: list = []          # captured, awaiting requeue
        self._implicated: dict = {}       # fingerprint -> distinct crashes
        self._quarantined: dict = {}      # fingerprint -> quarantine info
        self._restart_ts: deque = deque() # breaker window
        self._rebuild_hooks: list = []    # called with each fresh engine
        self._restarting = False
        self._broken = False
        self._crashes = 0
        self._restarts = 0
        self._started = False
        self._last_restart_s: Optional[float] = None
        self._engine_ready = threading.Event()
        self._engine_ready.set()

        self._engine = self._build(config=config, **overrides)
        self._config = self._engine.config  # rebuilds reuse the resolved one

    # -- engine lifecycle ----------------------------------------------------
    def _build(self, config=None, **overrides) -> ServingEngine:
        eng = ServingEngine(self._model, config=config,
                            draft_model=self._draft_model, **overrides)
        eng._crash_hook = self._on_engine_crash
        return eng

    @property
    def engine(self) -> ServingEngine:
        """The CURRENT engine (swapped atomically on restart)."""
        with self._lock:
            return self._engine

    def add_rebuild_hook(self, fn):
        """Register ``fn(new_engine)``, called on every warm restart
        with the freshly built (not yet warmed) engine — how chaos
        faults and instrumentation survive the engine swap."""
        self._rebuild_hooks.append(fn)
        return self

    # -- the crash path ------------------------------------------------------
    def _on_engine_crash(self, engine: ServingEngine, exc: BaseException):
        """The engine's ``_crash_hook``: runs on the dying serve-loop
        thread, step lock held, flight dump taken, requests not yet
        failed. Detaches survivors, updates the quarantine ledger, and
        (budget permitting) kicks off the restart thread. Anything NOT
        detached here is failed by ``_fail_inflight`` right after —
        the unsupervised semantics are the fallback, never silence."""
        err = repr(exc)
        with self._lock:
            if self._broken or self._stopped_flag():
                return  # no engine is coming back; let the crash path fail
            if engine is not self._engine:
                return  # a stale, already-replaced engine died again
            self._crashes += 1
            running, queued = engine._export_inflight()
            survivors = []
            for req in running:
                fp = req.fingerprint
                n = self._implicated.get(fp, 0) + 1
                self._implicated[fp] = n
                if n >= self.quarantine_crashes:
                    self._quarantine(fp, req, err)
                else:
                    req.quarantine_probe = True  # re-admitted solo
                    survivors.append(req)
            _sm.supervisor_requeued_total.labels("running").inc(
                len(survivors))
            _sm.supervisor_requeued_total.labels("queued").inc(len(queued))
            # breaker: restarts inside the sliding window, incl. this one
            now = time.perf_counter()
            self._restart_ts.append(now)
            while self._restart_ts and \
                    now - self._restart_ts[0] > self.restart_window_s:
                self._restart_ts.popleft()
            if len(self._restart_ts) > self.max_restarts:
                self._broken = True
                _tracing.instant(
                    "supervisor_breaker_open", cat="supervisor",
                    trace="supervisor",
                    args={"restarts": self._restarts,
                          "window_s": self.restart_window_s,
                          "error": err})
                for req in survivors + queued:
                    req.finish(
                        RequestStatus.FAILED,
                        error=f"engine crash-loop: restart budget "
                              f"exhausted ({self.max_restarts} restarts "
                              f"in {self.restart_window_s}s); last "
                              f"crash: {err}")
                return
            # survivors ride to the fresh engine: running first (they
            # hold the oldest FCFS positions), then the queued tail
            self._pending = survivors + queued
            self._restarting = True
            self._engine_ready.clear()
            crashes = self._crashes
        _tracing.instant(
            "supervisor_restart_begin", cat="supervisor",
            trace="supervisor",
            args={"crash": crashes, "error": err,
                  "captured_running": len(survivors),
                  "captured_queued": len(queued)})
        threading.Thread(target=self._rebuild, args=(engine,),
                         name="paddle-tpu-supervisor", daemon=True).start()

    # holds-lock: _lock
    def _quarantine(self, fp: str, req: Request, err: str):
        """Terminal verdict (caller holds the lock): blacklist the
        fingerprint and fail the request with the poison marker."""
        self._quarantined[fp] = {
            "fingerprint": fp,
            "crashes": self._implicated.get(fp, 0),
            "last_error": err,
            "request_id": req.id,
            "ts": time.time(),
        }
        _sm.requests_quarantined_total.inc()
        req._tr_event("quarantined", fingerprint=fp)
        req.finish(RequestStatus.FAILED, error=self.poison_error(fp))

    # holds-lock: _lock
    def poison_error(self, fp: str) -> str:
        """The actionable quarantine error (carries ``POISON_MARKER``;
        callers hold the lock — ``_implicated`` is read under it)."""
        n = self._implicated.get(fp, self.quarantine_crashes)
        return (f"{POISON_MARKER}: request fingerprint {fp} was "
                f"implicated in {n} engine crash(es) (quarantine budget "
                f"{self.quarantine_crashes}) and is quarantined "
                f"fleet-wide — do not resubmit this request")

    def _rebuild(self, dead: ServingEngine):
        """The restart thread: fresh engine, zero-compile warmup,
        survivors requeued at the front, atomic swap, loop restarted."""
        t0 = time.perf_counter()
        try:
            eng = self._build(config=self._config)
            for hook in list(self._rebuild_hooks):
                try:
                    hook(eng)
                except Exception:  # noqa: BLE001 — a broken hook must not
                    pass           # turn a warm restart into an outage
            if self.warmup_on_restart:
                eng.warmup()
        except Exception as e:  # noqa: BLE001 — rebuild failed: stay crashed
            with self._lock:
                self._broken = True
                pending, self._pending = self._pending, []
                self._restarting = False
            for req in pending:
                req.finish(RequestStatus.FAILED,
                           error=f"supervised restart failed: {e!r}")
            self._engine_ready.set()
            return
        with self._lock:
            pending, self._pending = self._pending, []
            started = self._started
        # queue front in FCFS order: requeue() is appendleft, so walk
        # the survivors newest-first
        for req in reversed(pending):
            if req.status in RequestStatus.FINAL:
                continue  # cancelled/finished while the engine was down
            eng.scheduler.requeue(req)
        with self._lock:
            self._engine = eng
            self._restarts += 1
            restarts = self._restarts
            self._restarting = False
            self._last_restart_s = time.perf_counter() - t0
        _sm.supervisor_restarts_total.inc()
        _tracing.instant(
            "supervisor_restart_done", cat="supervisor", trace="supervisor",
            args={"restart": restarts,
                  "wall_s": round(time.perf_counter() - t0, 3),
                  "requeued": len(pending)})
        if started:
            eng.start()
        self._engine_ready.set()

    def _stopped_flag(self) -> bool:
        with self._lock:
            eng = self._engine
        return eng.stopped or eng.draining

    # -- the engine surface --------------------------------------------------
    def submit(self, prompt, deadline_s: Optional[float] = None,
               on_token=None, params: Optional[SamplingParams] = None,
               **sampling) -> Request:
        """``ServingEngine.submit`` plus the quarantine gate: a
        blacklisted fingerprint is refused with ``PoisonedRequestError``
        before it can touch the engine. During a warm restart the
        submit blocks (up to ``restart_grace_s``) for the fresh engine
        instead of bouncing — the restart is a latency blip, not an
        error burst."""
        if params is None:
            params = SamplingParams(**sampling)
        elif sampling:
            raise ValueError("pass params OR sampling kwargs, not both")
        prompt = np.asarray(prompt, dtype=np.int32).reshape(-1)
        fp = request_fingerprint(prompt, params)
        with self._lock:
            if fp in self._quarantined:
                raise PoisonedRequestError(self.poison_error(fp),
                                           fingerprint=fp)
            restarting = self._restarting
        if restarting:
            self._engine_ready.wait(self.restart_grace_s)
        return self.engine.submit(prompt, deadline_s=deadline_s,
                                  on_token=on_token, params=params)

    def cancel(self, req: Request) -> bool:
        return self.engine.cancel(req)

    def warmup(self) -> dict:
        return self.engine.warmup()

    def start(self):
        with self._lock:
            self._started = True
        self.engine.start()
        return self

    def stop(self, abort: bool = False,
             drain_timeout_s: Optional[float] = 30.0):
        with self._lock:
            self._started = False
        self.engine.stop(abort=abort, drain_timeout_s=drain_timeout_s)

    def drain(self, timeout_s: Optional[float] = None) -> bool:
        return self.engine.drain(timeout_s=timeout_s)

    def run_until_idle(self, max_steps: int = 1_000_000) -> int:
        """Synchronous drive, restart-aware: keeps stepping the CURRENT
        engine until queue and slots are empty — across warm restarts
        (where ``engine`` is swapped under it) and through the restart
        window itself."""
        n = 0
        deadline = time.perf_counter() + self.restart_grace_s
        while n < max_steps:
            self._engine_ready.wait(self.restart_grace_s)
            eng = self.engine
            if eng.crashed is not None:
                if self.broken or time.perf_counter() > deadline:
                    break
                time.sleep(0.002)
                continue
            if not (eng.scheduler.depth or eng.busy_slots()):
                break
            deadline = time.perf_counter() + self.restart_grace_s
            try:
                if not eng.step():
                    time.sleep(0.001)
            except Exception as e:  # noqa: BLE001 — mirror _serve_loop:
                # the crash path captures survivors + kicks the restart
                eng._on_loop_crash(e)
            n += 1
        return n

    # -- introspection -------------------------------------------------------
    @property
    def restarts(self) -> int:
        with self._lock:
            return self._restarts

    @property
    def restarting(self) -> bool:
        with self._lock:
            return self._restarting

    @property
    def broken(self) -> bool:
        with self._lock:
            return self._broken

    @property
    def quarantined(self) -> list:
        """Blacklisted fingerprints (sorted) — the ``/stats`` block the
        router merges fleet-wide on its load-refresh cadence."""
        with self._lock:
            return sorted(self._quarantined)

    def is_quarantined(self, fingerprint: str) -> bool:
        with self._lock:
            return fingerprint in self._quarantined

    def supervisor_stats(self) -> dict:
        with self._lock:
            return {
                "crashes": self._crashes,
                "restarts": self._restarts,
                "restarting": self._restarting,
                "broken": self._broken,
                "max_restarts": self.max_restarts,
                "restart_window_s": self.restart_window_s,
                "restarts_in_window": len(self._restart_ts),
                "last_restart_s": (round(self._last_restart_s, 3)
                                   if self._last_restart_s is not None
                                   else None),
                "quarantine_crashes": self.quarantine_crashes,
                "quarantined": sorted(self._quarantined),
                "quarantine": [dict(v) for v in
                               self._quarantined.values()],
                "implicated": dict(self._implicated),
            }

    def health(self) -> tuple:
        """The engine's ``/healthz`` surface plus the supervisor block.
        During a warm restart the payload reports ``restarting`` (503:
        route elsewhere, probes may back off but the replica is coming
        back); a tripped breaker reports the engine's own ``crashed``
        with ``restarts_exhausted`` so the router ejects it for good."""
        with self._lock:
            restarting, broken = self._restarting, self._broken
        if restarting:
            return 503, {"ts": time.time(), "status": "restarting",
                         "supervisor": self.supervisor_stats()}
        code, payload = self.engine.health()
        payload["supervisor"] = self.supervisor_stats()
        if broken:
            payload["restarts_exhausted"] = True
        return code, payload

    def stats(self) -> dict:
        out = self.engine.stats()
        out["supervisor"] = self.supervisor_stats()
        return out

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()
        return False

    def __getattr__(self, name):
        # everything else (scheduler, config, paged, warmed_up,
        # debug_requests, run_until_idle-adjacent state...) delegates
        # to the CURRENT engine, so the supervisor drops in anywhere a
        # ServingEngine goes
        if name.startswith("_") or name == "engine":
            raise AttributeError(name)
        return getattr(self.engine, name)
