"""Continuous-batching serving engine over a paged (or contiguous) KV
cache.

The TPU-native translation of iteration-level scheduling (Orca) +
PagedAttention-class KV management (vLLM) + RadixAttention-style prefix
reuse, built on this repo's static-shape decode substrate:

- ``kv_mode="paged"`` (default): device HBM holds ONE fixed pool of KV
  blocks (per layer, [num_blocks, block_size, kv_heads, d]); each slot's
  cache is an int32 block table into the pool. Capacity is bounded by
  TOKENS IN FLIGHT instead of slots * worst-case length — a short
  request strands at most ``block_size - 1`` token slots, not
  ``max_len - L``. On top of the pool:

  * **prefix sharing**: a prompt whose prefix was already prefilled
    (same tokens, same positions — e.g. a shared system prompt) adopts
    those blocks by reference from the host-side prefix cache instead of
    recomputing them; ref-counted copy-on-write forks a shared block on
    the first divergent write, so sharing is invisible to outputs.
  * **chunked prefill**: prompts are admitted in fixed-size chunks
    (ONE ``serving.prefill_chunk`` executable replaces every per-bucket
    prefill program) interleaved with decode steps, so a long prompt
    never head-of-line-blocks running requests for its whole length.
  * **preemption by recompute**: under pool pressure the latest-admitted
    request is preempted — its blocks freed, the request requeued at the
    queue front with its generated tokens folded into the prefill and
    its PRNG chain replayed, so the resumed decode is bit-identical and
    nothing is ever re-delivered.

- ``kv_mode="contiguous"``: the pre-paging design — per-slot
  [B, max_len, h, d] buffers, bucketed padded prefill + cache splice —
  kept as the A/B baseline (``benchmarks/bench_paged_kv.py``).

- ``draft_model=`` (paged only): SPECULATIVE DECODING. Decode is
  KV-bandwidth-bound, so idle FLOPs verify ``spec_k`` draft tokens per
  slot per round: ONE jitted draft program (k cached draft-model
  forwards over draft KV pools that share the target's block tables),
  then ONE jitted verify scoring the whole [B, k+1] bundle with the
  target through ``paged_flash_decode_attention``'s q_len > 1 path.
  Acceptance is the Leviathan/Chen rule under a common-noise coupling:
  draft and target select with the SAME per-position PRNG subkey, so
  accept-with-prob-min(1, p/q) collapses to exact token match and the
  emitted sequence is BIT-IDENTICAL to non-speculative decode — greedy
  and sampled — while the chain still advances one split per emitted
  token (preemption replay untouched). Rejected draft KV rolls back BY
  POSITION (the next bundle overwrites it before any in-length query
  can attend it); variable per-slot accept length is a per-row position
  bump through the block tables. Requests opt out (or shrink k) via
  ``SamplingParams.spec_k``; opted-out rows ride the verify bundle at
  width 1 as plain decode steps, so mixed pools share the same two
  executables — each compiles exactly once.

Both modes drive ONE jitted pool-wide decode step per iteration:
per-slot positions / sampling params / PRNG keys / active mask — and in
paged mode the block tables — are traced arrays, so mixed
occupancy/length/sharing patterns share a single step executable that
compiles exactly once (recompile-monitor-asserted across request waves).

Per-request outputs are bit-identical to ``generation.generate`` with
the same sampling seed/params in BOTH modes: the slot key chain
reproduces generate's ``key, sub = split(key)`` walk, ``select_tokens``
is row-wise equal to the config-static ``_select_token``, and the paged
read path gathers the exact same K/V values the contiguous cache holds
(garbage beyond a row's length is an exact no-op under the additive
causal mask, just like the contiguous cache's zeros).

Observability: the ``paddle_tpu_serving_*`` instruments plus the paged
``paddle_tpu_kv_blocks_{total,in_use,shared}`` gauges and
``paddle_tpu_prefix_cache_{hits,misses}_total`` counters; compiles are
attributed to ``serving.step`` / ``serving.prefill_chunk`` /
``serving.cow`` (paged) or ``serving.prefill[bucket]`` (contiguous) —
a ``serving.step`` retrace after warmup is a bug and the monitor flags
it.
"""

from __future__ import annotations

import functools
import os
import threading
import time
import weakref
from collections import deque
from dataclasses import dataclass, field
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..generation import (make_cached_runner, make_kv_caches,
                          make_paged_kv_pools, select_tokens,
                          spec_accept_length, split_key_levels, split_keys)
from ..observability import recompile as _recompile
from ..observability import tracing as _trace
from ..observability.recompile import entrypoint as _entrypoint
from . import metrics as _sm
from .block_pool import (DUMP_BLOCK, BlockPool, PoolExhaustedError,
                         PrefixCache)
from .kv_tier import DiskPrefixStore, KVTier, TierCostModel
from .request import Request, RequestStatus, SamplingParams
from .scheduler import Scheduler

__all__ = ["ServingConfig", "ServingEngine", "EngineStoppedError",
           "EngineDrainingError"]


class EngineStoppedError(RuntimeError):
    """``submit()`` after ``stop()``: the engine no longer admits work.
    Raised instead of silently enqueueing into a loop that will never
    run again (the old behavior hung the caller's ``result()``
    forever)."""


class EngineDrainingError(EngineStoppedError):
    """``submit()`` during drain: in-flight requests are finishing but
    no new work is admitted. A router should route the request to
    another replica; a direct caller should back off and retry once the
    replacement replica is up."""


def _default_buckets(max_len: int) -> tuple:
    """Powers of two from 16 up to (and always including) max_len."""
    out = []
    b = 16
    while b < max_len:
        out.append(b)
        b *= 2
    out.append(max_len)
    return tuple(out)


@dataclass
class ServingConfig:
    """Engine knobs.

    - ``max_slots``: the decode batch B — slots in flight at once.
    - ``max_len``: per-slot KV capacity; every request needs
      prompt_len + max_new_tokens <= max_len.
    - ``kv_mode``: ``"paged"`` (block-pool KV, prefix sharing, chunked
      prefill — the default) or ``"contiguous"`` (per-slot buffers,
      bucketed prefill — the A/B baseline).
    - ``block_size``: tokens per KV block (paged). Must divide
      ``max_len`` — the per-slot block table covers max_len in whole
      blocks.
    - ``num_blocks``: pool size INCLUDING the reserved dump block.
      Default ``max_slots * (max_len / block_size) + 1`` (worst case —
      paging can never run out); size it below that to oversubscribe
      slots against a fixed HBM budget (preemption keeps it safe).
    - ``prefill_chunk``: tokens per prefill chunk (paged): one fixed
      [1, prefill_chunk] executable replaces every prefill bucket, and
      long prompts are admitted chunk-by-chunk between decode steps.
    - ``prefix_caching``: reuse previously prefilled prompt prefixes
      (ref-counted, COW-protected). Disable for strictly independent
      workloads.
    - ``prefill_buckets``: (contiguous mode) padded prompt lengths; each
      bucket costs one prefill + one splice compile. Defaults to powers
      of two up to max_len.
    - ``max_queue_depth``: admission backpressure bound
      (``QueueFullError`` beyond it).
    - ``pad_token_id``: right-pad filler for padded prefill — any valid
      token id works (padded positions are causally invisible, and paged
      mode routes their writes to the dump block).
    - ``spec_k``: draft tokens per speculative round when the engine is
      built with a ``draft_model`` (the verify bundle is ``spec_k + 1``
      query positions through the paged kernel). Requests opt out (or
      shrink their k) per-request via ``SamplingParams.spec_k``; ignored
      without a draft model.
    - ``spec_tree``: per-level branching factors (e.g. ``[4, 2, 2]``)
      upgrading the speculative lane from a single draft chain to a
      token TREE: the draft proposes every branch, ONE verify scores
      the whole flattened tree (root + all nodes) through the paged
      kernel's ancestor-masked bundle path, and the deepest fully-
      matching root-to-leaf path is committed. Mutually exclusive with
      a non-default ``spec_k`` — one engine runs one lane. The node
      count (``spec_tree_width``) must fit the kernel's query window
      (``MAX_PAGED_Q_LEN``). ``SamplingParams.spec_k`` still applies
      per-request, clamping the tree DEPTH (0 = plain decode rows
      riding the bundle at width 1). Outputs stay bit-identical to
      non-speculative decode, greedy and sampled.
    - ``kv_format``: KV block storage (paged only) — ``"bf16"`` keeps
      the model compute dtype (default); ``"int8"``/``"fp8"`` store the
      pool narrow with per-token-per-head absmax scale pools riding the
      same blocks: writes quantize in the scatter epilogue, the paged
      flash-decode kernel dequantizes in its prologue (XLA fallback at
      the gather), roughly doubling the tokens a fixed KV HBM budget
      holds. COW forks, prefix sharing, preemption-resume, and the
      spec-decode lane all operate on quantized blocks unchanged. fp8
      uses the e4m3 jnp dtype where available; int8 is the portable
      floor.
    - ``tp``: tensor-parallel degree — shard ONE model (and its KV
      pools, on the kv-heads axis) across ``tp`` devices via the
      ``distributed/partition.py`` rule tables; every executable runs
      under jit with explicit shardings over the TP mesh. Outputs are
      bit-identical to the tp=1 engine (greedy and sampled, spec and
      preemption lanes included); requires ``kv_mode="paged"`` and a
      model whose heads/kv-heads/intermediate/vocab divide by tp.
    - ``kv_tier``: hierarchical KV (``serving/kv_tier.py``) — prefix-
      cache eviction victims and preempted requests' blocks DEMOTE to a
      host-RAM tier (device->host at quantized width) instead of being
      freed, and a returning prefix re-admits via one jitted host->HBM
      block splice instead of prefill chunks. Defaults from the
      ``PADDLE_TPU_KV_TIER`` env var ("1" enables); requires paged mode
      with prefix caching. Outputs stay bit-identical tier-on vs
      tier-off. ``kv_tier_host_blocks`` caps host residency (LRU);
      ``kv_tier_path`` (env ``PADDLE_TPU_KV_TIER_PATH``) adds the
      crash-safe disk tier below host, making cached prefixes persist
      across engine restarts; ``kv_tier_host_gbps`` (env
      ``PADDLE_TPU_KV_TIER_HOST_GBPS``) and ``kv_tier_safety`` feed the
      demote-vs-drop / readmit-vs-recompute cost model.
    """

    max_slots: int = 4
    max_len: int = 256
    prefill_buckets: Sequence[int] = ()
    max_queue_depth: int = 64
    pad_token_id: int = 0
    kv_mode: str = "paged"
    block_size: int = 16
    num_blocks: Optional[int] = None
    prefill_chunk: int = 32
    prefix_caching: bool = True
    spec_k: int = 4
    spec_tree: Optional[Sequence[int]] = None
    kv_format: str = "bf16"
    # tensor parallelism: shard ONE model over `tp` chips (Megatron
    # layout via distributed/partition.py rule tables; KV pools shard on
    # the kv-heads axis). Host-side scheduling/paging is tp-agnostic —
    # one allocator/prefix-cache/block-table drives every shard — and
    # outputs stay bit-identical to the tp=1 engine. Divisibility
    # against the model's heads/vocab is validated at engine build.
    tp: int = 1
    # background loop liveness: with work pending and no step boundary
    # for this long, /healthz flips to "stalled" (503) so a router's
    # probes can eject a HUNG replica — a wedged device dispatch looks
    # exactly like this, and without the detector it is invisible (the
    # loop thread is stuck, but every state read still says "ok")
    stall_timeout_s: float = 10.0
    # hierarchical KV tiers (host RAM + optional persistent disk under
    # the block pool); None resolves from the environment in
    # __post_init__ so a deployment can flip the tier on without code
    kv_tier: Optional[bool] = None
    kv_tier_host_blocks: int = 256
    kv_tier_path: Optional[str] = None
    kv_tier_host_gbps: Optional[float] = None
    kv_tier_safety: float = 1.5

    def __post_init__(self):
        if self.kv_mode not in ("paged", "contiguous"):
            raise ValueError(
                f"kv_mode must be 'paged' or 'contiguous', got "
                f"{self.kv_mode!r}")
        from ..quantization.intx import KV_FORMATS, format_dtype

        if self.kv_format not in KV_FORMATS:
            raise ValueError(
                f"kv_format must be one of {KV_FORMATS}, got "
                f"{self.kv_format!r}")
        if self.kv_format != "bf16":
            format_dtype(self.kv_format)  # actionable fp8-missing error
            if self.kv_mode != "paged":
                raise ValueError(
                    f"kv_format={self.kv_format!r} requires "
                    f"kv_mode='paged': quantized KV lives in the block "
                    f"pool (per-block scale companions, dequant in the "
                    f"paged kernel prologue) — switch kv_mode to 'paged' "
                    f"or drop kv_format (the contiguous engine is the "
                    f"bf16 A/B baseline)")
        from ..pallas_kernels.decode_attention import (
            MAX_PAGED_Q_LEN, MAX_SPEC_K, spec_tree_width)

        if not 0 <= int(self.spec_k) <= MAX_SPEC_K:
            raise ValueError(
                f"spec_k ({self.spec_k}) must be in [0, {MAX_SPEC_K}]: the "
                f"speculative verify scores spec_k + 1 bundle positions in "
                f"one paged flash-decode call, whose query window is "
                f"MAX_PAGED_Q_LEN = {MAX_SPEC_K + 1} — shrink spec_k (draft "
                f"win saturates long before that) or raise MAX_PAGED_Q_LEN "
                f"with the kernel's block budget in mind")
        if self.spec_tree is not None:
            factors = tuple(int(f) for f in self.spec_tree)
            if not factors or any(f < 1 for f in factors):
                raise ValueError(
                    f"spec_tree must be a non-empty sequence of branching "
                    f"factors >= 1 per draft level (e.g. [4, 2, 2]), got "
                    f"{self.spec_tree!r}")
            if int(self.spec_k) != 4:
                raise ValueError(
                    f"spec_tree ({list(factors)}) and a non-default spec_k "
                    f"({self.spec_k}) are mutually exclusive: one engine "
                    f"runs ONE speculative lane — the chain (spec_k drafts "
                    f"per round) or the tree (branching factors per level). "
                    f"Drop spec_k (per-request depth clamps still ride "
                    f"SamplingParams.spec_k) or drop spec_tree")
            wnodes = spec_tree_width(factors)
            if wnodes > MAX_PAGED_Q_LEN:
                raise ValueError(
                    f"spec_tree {list(factors)} flattens to {wnodes} nodes, "
                    f"but the verify bundle scores every node in one paged "
                    f"flash-decode call whose query window is "
                    f"MAX_PAGED_Q_LEN = {MAX_PAGED_Q_LEN} — shrink the "
                    f"branching factors or the depth (accept depth "
                    f"saturates long before that) or raise MAX_PAGED_Q_LEN "
                    f"with the kernel's block budget in mind")
            self.spec_tree = factors
        if int(self.tp) < 1:
            raise ValueError(f"tp ({self.tp}) must be >= 1")
        if int(self.tp) > 1 and self.kv_mode != "paged":
            raise ValueError(
                f"tp={self.tp} requires kv_mode='paged': tensor-parallel "
                f"serving shards the block pools on the kv-heads axis — "
                f"switch kv_mode to 'paged' (the contiguous engine is the "
                f"single-chip A/B baseline)")
        if self.kv_mode == "paged":
            if self.block_size < 1 or self.max_len % self.block_size:
                raise ValueError(
                    f"block_size ({self.block_size}) must divide max_len "
                    f"({self.max_len}): the per-slot block table covers "
                    f"max_len in whole KV blocks — pick a block_size that "
                    f"divides max_len (e.g. 16) or round max_len up to a "
                    f"multiple of block_size")
            if self.prefill_chunk < 1:
                raise ValueError(
                    f"prefill_chunk must be >= 1, got {self.prefill_chunk}")
            if self.num_blocks is not None and self.num_blocks < 2:
                raise ValueError(
                    f"num_blocks ({self.num_blocks}) must be >= 2: block 0 "
                    f"is the reserved dump block, so at least one usable "
                    f"block is needed")
        # hierarchical KV: env-resolved defaults, then validation
        if self.kv_tier is None:
            self.kv_tier = os.environ.get("PADDLE_TPU_KV_TIER", "") \
                not in ("", "0", "false", "False")
        self.kv_tier = bool(self.kv_tier)
        if self.kv_tier_path is None:
            self.kv_tier_path = \
                os.environ.get("PADDLE_TPU_KV_TIER_PATH") or None
        if self.kv_tier_host_gbps is None:
            self.kv_tier_host_gbps = float(
                os.environ.get("PADDLE_TPU_KV_TIER_HOST_GBPS", "12.0"))
        if self.kv_tier:
            if self.kv_mode != "paged":
                raise ValueError(
                    "kv_tier=True requires kv_mode='paged': the host/disk "
                    "tiers hold demoted POOL BLOCKS and re-admit them "
                    "through the block tables — switch kv_mode to 'paged' "
                    "or drop kv_tier")
            if not self.prefix_caching:
                raise ValueError(
                    "kv_tier=True requires prefix_caching=True: tier "
                    "entries are keyed by the prefix cache's exact-token "
                    "keys and re-admission extends prefix matches — "
                    "enable prefix_caching or drop kv_tier")
            if self.kv_tier_host_blocks < 1:
                raise ValueError(
                    f"kv_tier_host_blocks ({self.kv_tier_host_blocks}) "
                    f"must be >= 1")
            if self.kv_tier_host_gbps <= 0 or self.kv_tier_safety <= 0:
                raise ValueError(
                    f"kv_tier_host_gbps ({self.kv_tier_host_gbps}) and "
                    f"kv_tier_safety ({self.kv_tier_safety}) must be > 0")

    def validate_draft(self, model_config, draft_config):
        """Speculative-lane compatibility checks between the target and
        draft models (called by the engine when ``draft_model`` is
        given; lives here so the error surface sits with the other
        config validation)."""
        if self.kv_mode != "paged":
            raise ValueError(
                "speculative decoding requires kv_mode='paged': the "
                "verify bundle and rollback-by-position ride the block "
                "tables — drop draft_model or switch kv_mode to 'paged'")
        if self.spec_k < 1:
            raise ValueError(
                f"spec_k ({self.spec_k}) must be >= 1 when a draft_model "
                f"is given — with 0 draft tokens per round the draft "
                f"model is dead weight; drop draft_model instead")
        if draft_config.vocab_size != model_config.vocab_size:
            raise ValueError(
                f"draft/target vocab mismatch: draft vocab_size "
                f"({draft_config.vocab_size}) != target vocab_size "
                f"({model_config.vocab_size}) — speculative verify "
                f"compares draft TOKEN IDS against target selections, so "
                f"both models must share one tokenizer/vocab (e.g. build "
                f"the draft with generation.truncated_draft)")
        if self.max_len > draft_config.max_position_embeddings:
            raise ValueError(
                f"max_len ({self.max_len}) exceeds the DRAFT model's "
                f"max_position_embeddings "
                f"({draft_config.max_position_embeddings}); the draft "
                f"decodes the same positions the target does — shrink "
                f"max_len or use a draft with a longer position table")

    def buckets(self) -> tuple:
        bs = tuple(sorted({int(b) for b in self.prefill_buckets
                           if int(b) <= self.max_len}))
        if not bs:
            return _default_buckets(self.max_len)
        if bs[-1] != self.max_len:
            bs = bs + (self.max_len,)
        return bs

    def blocks_per_slot(self) -> int:
        return self.max_len // self.block_size

    def default_num_blocks(self) -> int:
        return self.max_slots * self.blocks_per_slot() + 1


@dataclass
class _PrefillJob:
    """Host-side progress of one chunked prefill: which tokens remain,
    the request's PRNG key (split ONCE, at the final chunk — generate's
    chain), and whether the final select's token was already delivered
    (preemption resume regenerates the last delivered token)."""

    req: Request
    tokens: np.ndarray           # prompt (+ replayed generation on resume)
    total: int
    done: int                    # tokens already in the cache (prefix hits
    key: "jax.Array"             # + completed chunks)
    skip: int                    # 1 on resume: final select re-derives an
    t0: float = field(default_factory=time.perf_counter)  # already-sent token


class ServingEngine:
    """Request-level serving over one decoder model (llama / gpt — any
    model speaking the generation.py static-cache protocol).

    Drive it synchronously (``submit`` + ``step``/``run_until_idle`` —
    deterministic, what the tests do) or as a background thread
    (``start``/``stop``; ``submit`` then wakes the loop and callers wait
    on ``Request.result()`` / iterate ``Request.stream()``).
    """

    def __init__(self, model, config: Optional[ServingConfig] = None,
                 draft_model=None, **overrides):
        if config is None:
            config = ServingConfig(**overrides)
        elif overrides:
            raise ValueError("pass ServingConfig OR keyword overrides, not both")
        self.config = config
        self.model = model
        mcfg = model.config
        if config.max_len > mcfg.max_position_embeddings:
            raise ValueError(
                f"max_len ({config.max_len}) exceeds the model's "
                f"max_position_embeddings ({mcfg.max_position_embeddings})")
        self.paged = config.kv_mode == "paged"
        self.draft_model = draft_model
        self.spec = draft_model is not None
        if self.spec:
            config.validate_draft(mcfg, draft_model.config)
            self._spec_tree = (tuple(config.spec_tree)
                               if config.spec_tree is not None else None)
            if self._spec_tree is not None:
                from ..generation import spec_tree_plan
                self._tree = spec_tree_plan(self._spec_tree)
                # per-request SamplingParams.spec_k clamps the tree
                # DEPTH on the tree lane, so _spec_k doubles as the
                # depth bound and sizes the accept histogram (a round
                # accepts 0..depth draft nodes, one per path level)
                self._spec_k = int(self._tree["depth"])
            else:
                self._tree = None
                self._spec_k = int(config.spec_k)
            from ..pallas_kernels.decode_attention import \
                spec_verify_eligibility
            ok, reason = spec_verify_eligibility(
                self._spec_k,
                next(iter(model.parameters()))._data.dtype,
                spec_tree=self._spec_tree)
            # expected verify-bundle path, recorded once per engine: the
            # kernel serves q_len = spec_k + 1 (chain) or w-node (tree)
            # bundles, or the XLA gather fallback does (reason-counted
            # either way, under the spec_ / spec_tree_ prefix)
            self._spec_verify_kernel = ok
            _trace.instant("spec_verify_path", cat="engine",
                           args={"kernel": ok, "reason": reason,
                                 "k": self._spec_k,
                                 "tree": (list(self._spec_tree)
                                          if self._spec_tree else None)})
        B = int(config.max_slots)
        self.scheduler = Scheduler(config.max_queue_depth)

        self._dtype = next(iter(model.parameters()))._data.dtype
        params = {k: v._data for k, v in model.named_parameters_dict().items()}
        buffers = {k: v._data for k, v in model.named_buffers_dict().items()}
        self._pb = {**params, **buffers}
        self._mcfg = mcfg
        if self.spec:
            self._dcfg = draft_model.config
            self._ddtype = next(iter(draft_model.parameters()))._data.dtype
            self._dpb = {
                **{k: v._data
                   for k, v in draft_model.named_parameters_dict().items()},
                **{k: v._data
                   for k, v in draft_model.named_buffers_dict().items()}}
            self._spec_drafted = 0
            self._spec_accepted = 0
            self._spec_rounds = 0
            # engine-local accept-length histogram (0..k accepted per
            # round — on the tree lane k is the DEPTH, one accepted node
            # per path level): /stats percentiles come from THIS
            # engine's rounds; the registry Summary stays the fleet-wide
            # scrape surface
            self._accept_hist = [0] * (self._spec_k + 1)

        # per-slot decode state (last token, position, PRNG chain,
        # sampling params) lives on DEVICE across steps — the decode loop
        # transfers ONE [B] token vector per iteration (plus, in paged
        # mode, the tiny int32 block table); admission updates a slot's
        # state rows inside the jitted chunk/splice program.
        self._state = {
            "tokens": jnp.zeros(B, jnp.int32),     # last token per slot
            "pos": jnp.zeros(B, jnp.int32),        # next cache write index
            "keys": jnp.zeros((B, 2), jnp.uint32),  # per-slot PRNG chain
            "ds": jnp.zeros(B, bool),
            "temp": jnp.ones(B, jnp.float32),
            "tk": jnp.zeros(B, jnp.int32),
            "tp": jnp.ones(B, jnp.float32),
        }
        # tensor parallelism: rule-shard the params over the TP mesh and
        # pin the per-slot state replicated — the executables then run
        # under jit with explicit in/out shardings (see _init_paged), so
        # GSPMD inserts the Megatron collectives and the host-side
        # scheduler/paging logic below never notices the mesh.
        self._tp = int(config.tp)
        self._tp_mesh = None
        self._tp_pb_sh = self._tp_dpb_sh = None
        if self._tp > 1:
            from ..distributed import partition as _partition
            _partition.validate_tp(mcfg, self._tp)
            self._tp_mesh = _partition.tp_mesh(self._tp)
            self._pb, self._tp_pb_sh = _partition.shard_params(
                self._pb, self._tp_mesh,
                _partition.partition_rules_for(model))
            if self.spec:
                _partition.validate_tp(self._dcfg, self._tp,
                                       what="draft model")
                self._dpb, self._tp_dpb_sh = _partition.shard_params(
                    self._dpb, self._tp_mesh,
                    _partition.partition_rules_for(draft_model))
            rep = _partition.replicated(self._tp_mesh)
            self._state = {k: jax.device_put(v, rep)
                           for k, v in self._state.items()}

        self._slot_req: List[Optional[Request]] = [None] * B
        self._slot_sampling = [False] * B  # host mirror for the step cond
        self._decoding = [False] * B       # past prefill, in the step batch
        self._slot_seq = [0] * B           # admission order (victim pick)
        self._admit_seq = 0

        self._steps = 0
        self._occupancy_integral = 0
        self._outcomes = {}
        self._preempt_count = 0
        self._last_progress_ts = time.perf_counter()  # stall detector
        self._step_lock = threading.RLock()
        self._wake = threading.Condition()
        self._running = False
        self._thread: Optional[threading.Thread] = None
        self._crashed: Optional[str] = None  # repr of the fatal loop error
        self._draining = False   # no new admissions; in-flight finishing
        self._stopped = False    # terminal: drained (or aborted) + loop down
        self._warmed_up = False  # warmup() ran: executables AOT-compiled
        # supervisor crash-capture hook: called by _on_loop_crash (step
        # lock held, flight dump already taken, requests NOT yet failed)
        # so a supervisor can detach queued+running requests for requeue
        # on a rebuilt engine before _fail_inflight reaches them
        self._crash_hook = None
        _sm.engine_unhealthy.set(0)  # a fresh engine is the healthy one

        # /debug/requests keeps the tail of finished requests next to the
        # live ones; goodput is deadline-met tokens over a sliding window
        self._recent: deque = deque(maxlen=256)
        self._goodput_window: deque = deque()  # (finish_ts, tokens)
        self._goodput_span_s = 30.0
        # flight-recorder state provider: a crash dump carries this
        # engine's full stats() (pool accounting, per-slot phases, queue
        # depth) — weakref'd so a dead engine drops out of dumps
        ref = weakref.ref(self)
        _trace.register_state_provider(
            "serving_engine",
            lambda ref=ref: (ref().stats() if ref() is not None else None))

        run = make_cached_runner(model)
        self._run = run

        self._tier: Optional[KVTier] = None  # set by _init_paged(kv_tier)
        if self.paged:
            self._init_paged(B, run)
        else:
            self._init_contiguous(B, run)
        self._register_memory_components()

    def _register_memory_components(self):
        """HBM-ledger attribution (``observability.perf.hbm_ledger``):
        the engine owns the KV pools and holds the model weights — the
        two footprints an OOM forensics dump most needs named. Weakref'd
        like the flight-recorder state provider; a dead engine drops
        out instead of pinning its pools."""
        from ..observability import perf as _perf

        ref = weakref.ref(self)

        def _pool_bytes(attr, ref=ref):
            eng = ref()
            pools = getattr(eng, attr, None) if eng is not None else None
            if pools is None:
                return None
            total = int(sum(arr.nbytes for c in pools for arr in c.values()))
            out = {"bytes": total, "kv_format": eng.config.kv_format,
                   "bytes_per_token": eng._kv_bytes_per_token
                   if eng.paged else None}
            if eng.paged:
                out["blocks"] = eng._nblocks
            if eng._tp > 1:
                # jax .nbytes is the GLOBAL logical size; the pools
                # shard on the kv-heads axis, so each chip holds 1/tp
                out["tp"] = eng._tp
                out["bytes_per_device"] = total // eng._tp
            return out

        def _weight_bytes(ref=ref):
            eng = ref()
            if eng is None:
                return None
            n = int(sum(v.nbytes for v in eng._pb.values()))
            if eng.spec:
                n += int(sum(v.nbytes for v in eng._dpb.values()))
            out = {"bytes": n}
            if eng._tp > 1:
                # Megatron-sharded matmul weights split 1/tp; norms/rope
                # replicate — report the exact per-device residency from
                # the arrays' own shardings, not a naive division
                per_dev = 0
                for pb in ((eng._pb, eng._dpb) if eng.spec else (eng._pb,)):
                    for v in pb.values():
                        try:
                            shard = v.sharding.shard_shape(v.shape)
                            per_dev += int(np.prod(shard, dtype=np.int64)
                                           * v.dtype.itemsize)
                        except Exception:
                            per_dev += int(v.nbytes)
                out["tp"] = eng._tp
                out["bytes_per_device"] = per_dev
            return out

        if self.paged:
            _perf.register_memory_component(
                "serving_kv_pool", functools.partial(_pool_bytes, "_pools"))
            if self.spec:
                _perf.register_memory_component(
                    "serving_draft_kv_pool",
                    functools.partial(_pool_bytes, "_dpools"))
        else:
            _perf.register_memory_component(
                "serving_kv_cache", functools.partial(_pool_bytes, "_caches"))
        _perf.register_memory_component("serving_model_weights",
                                        _weight_bytes)

    # -- executables: paged --------------------------------------------------
    def _init_paged(self, B: int, run):
        config = self.config
        mcfg = self._mcfg
        bs = config.block_size
        nb = config.blocks_per_slot()
        self._nblocks = int(config.num_blocks or config.default_num_blocks())
        self.pool = BlockPool(self._nblocks, bs)
        self.prefix_cache = PrefixCache(self.pool) if config.prefix_caching \
            else None
        self._pools = make_paged_kv_pools(mcfg, self._nblocks, bs,
                                          self._dtype, config.kv_format)
        tpm = self._tp_mesh
        if tpm is not None:
            from ..distributed import partition as _partition
            self._pools, self._tp_pool_sh = _partition.shard_kv_pools(
                self._pools, tpm)
        # the executables below round-trip the pool dicts generically so
        # quantized pools (extra ks/vs scale arrays) ride every program
        # — chunk, step, COW, draft, verify — without a second variant
        pool_keys = tuple(self._pools[0].keys())
        self._pool_keys = pool_keys
        from ..generation import kv_cache_bytes_per_token
        self._kv_bytes_per_token = kv_cache_bytes_per_token(
            mcfg, config.kv_format, self._dtype)
        _sm.kv_bytes_per_token.labels(config.kv_format).set(
            self._kv_bytes_per_token)
        self._bt = np.zeros((B, nb), np.int32)           # host block tables
        self._slot_blocks: List[List[int]] = [[] for _ in range(B)]
        self._slot_len = [0] * B                         # host mirror of pos
        self._jobs: List[Optional[_PrefillJob]] = [None] * B
        # this engine's closures are NEW executables — their first
        # compiles are warmup, not retraces of a previous engine's
        warm = ["serving.step", "serving.prefill_chunk", "serving.cow"]
        if self.spec:
            warm += ["serving.spec_draft", "serving.spec_verify"]
        if config.kv_tier:
            warm += ["serving.kv_demote", "serving.kv_splice"]
        _recompile.reset_warmup(*warm)
        if self.spec:
            # the draft model's KV pools mirror the target's block
            # structure and are addressed by the SAME per-slot block
            # tables, so one host-side allocator/prefix-cache/COW
            # bookkeeping drives both models' caches
            self._dpools = make_paged_kv_pools(
                self._dcfg, self._nblocks, bs, self._ddtype,
                config.kv_format)
            if tpm is not None:
                self._dpools, self._tp_dpool_sh = _partition.shard_kv_pools(
                    self._dpools, tpm)
            self._drun = make_cached_runner(self.draft_model)

        C = int(config.prefill_chunk)

        # executable wrapper: plain jit at tp=1; at tp>1 jit with
        # EXPLICIT in/out shardings — round-tripped trees (pools, state)
        # keep identical layouts on both sides so the compiled signature
        # is a fixpoint and the one-compile invariant survives sharding
        # — plus the trace-time tp context the Pallas decode dispatch
        # consults (a pallas_call cannot be GSPMD-partitioned; under
        # tp>1 attention takes the XLA gather path, which shards
        # cleanly on the kv-heads axis).
        if tpm is None:
            rep = pb_sh = pool_sh = state_sh = None

            def _wrap(fn, donate, in_s, out_s):
                return jax.jit(fn, donate_argnums=donate)
        else:
            rep = _partition.replicated(tpm)
            pb_sh = self._tp_pb_sh
            pool_sh = self._tp_pool_sh
            state_sh = {k: rep for k in self._state}

            def _wrap(fn, donate, in_s, out_s):
                return _partition.tp_jit(
                    fn, tp=self._tp, mesh=tpm, in_shardings=in_s,
                    out_shardings=out_s, donate_argnums=donate)
        self._tp_rep = rep
        self._tp_state_sh = state_sh
        self._tp_wrap = _wrap

        def _chunk(pb, pools, state, bt_row, ids, pos0, valid, slot, is_last,
                   last_idx, key, ds, temp, tk, tp):
            """ONE fixed-shape prefill chunk: forward ``ids`` [1, C] at
            offset ``pos0`` through the paged caches (writes scatter
            through the slot's block table; pad tokens beyond ``valid``
            land in the dump block), then the final-token select with
            generate's exact key chain. State rows for ``slot`` are set
            only when ``is_last`` (traced — chunk count never retraces);
            the select itself is computed every chunk and simply unused
            until then."""
            caches = [dict(c, bt=bt_row, valid=valid[None])
                      for c in pools]
            logits, newc = run(pb, ids, caches, pos0)
            last = jax.lax.dynamic_slice_in_dim(logits, last_idx, 1,
                                                axis=1)[:, 0]
            key2, sub = jax.random.split(key)
            token = jax.lax.cond(
                ds[0],
                lambda: select_tokens(last, sub[None], ds, temp, tk, tp),
                lambda: jnp.argmax(last, axis=-1).astype(jnp.int32))
            state = dict(state)

            def _sel(new, old):
                return jnp.where(is_last, new, old)

            state["tokens"] = state["tokens"].at[slot].set(
                _sel(token[0], state["tokens"][slot]))
            state["pos"] = state["pos"].at[slot].set(
                _sel(pos0 + valid, state["pos"][slot]))
            state["keys"] = state["keys"].at[slot].set(
                _sel(key2, state["keys"][slot]))
            state["ds"] = state["ds"].at[slot].set(_sel(ds[0], state["ds"][slot]))
            state["temp"] = state["temp"].at[slot].set(
                _sel(temp[0], state["temp"][slot]))
            state["tk"] = state["tk"].at[slot].set(_sel(tk[0], state["tk"][slot]))
            state["tp"] = state["tp"].at[slot].set(_sel(tp[0], state["tp"][slot]))
            pools_out = [{kk: c[kk] for kk in pool_keys} for c in newc]
            return token, pools_out, state

        _chunk = _wrap(_chunk, (1, 2),
                       (pb_sh, pool_sh, state_sh) + (rep,) * 12,
                       (rep, pool_sh, state_sh))

        def _step(pb, pools, state, bt, any_sampling, active):
            """ONE decode iteration for the whole slot pool, reading and
            writing KV through the traced block tables ``bt`` [B, nb]
            (inactive rows are zeroed by the host -> their static-shape
            writes land in the dump block). Everything else matches the
            contiguous step: traced per-slot positions/params/keys,
            ``any_sampling`` cond skipping the sampler for pure-argmax
            pools, free rows pinned to pos 0. Compiles exactly once —
            occupancy, length mix, and SHARING patterns are all data."""
            caches = [dict(c, bt=bt) for c in pools]
            logits, newc = run(pb, state["tokens"][:, None], caches,
                               state["pos"])
            last = logits[:, 0]
            new_keys, subs = split_keys(state["keys"])
            nxt = jax.lax.cond(
                any_sampling,
                lambda: select_tokens(last, subs, state["ds"], state["temp"],
                                      state["tk"], state["tp"]),
                lambda: jnp.argmax(last, axis=-1).astype(jnp.int32))
            state = dict(state)
            state["tokens"] = nxt
            state["pos"] = jnp.where(
                active,
                jnp.minimum(state["pos"] + 1, jnp.int32(config.max_len - 1)),
                jnp.int32(0))
            state["keys"] = new_keys
            pools_out = [{kk: c[kk] for kk in pool_keys} for c in newc]
            return nxt, pools_out, state

        _step = _wrap(_step, (1, 2),
                      (pb_sh, pool_sh, state_sh, rep, rep, rep),
                      (rep, pool_sh, state_sh))

        def _cow(pools, src, dst):
            """Copy-on-write fork: duplicate physical block ``src`` into
            ``dst`` across every layer's K and V pool (one dispatch;
            src/dst are traced so every fork shares the executable)."""
            out = []
            for c in pools:
                out.append({kk: c[kk].at[dst].set(c[kk][src])
                            for kk in c})
            return out

        _cow = _wrap(_cow, (0,), (pool_sh, rep, rep), pool_sh)

        self._chunk_fn = _chunk
        self._step_fn = _step
        self._cow_fn = _cow
        self._chunk_size = C
        # retrace warnings for the engine entries cite these defs
        _recompile.register_entry_location("serving.step", _step)
        _recompile.register_entry_location("serving.prefill_chunk", _chunk)
        _recompile.register_entry_location("serving.cow", _cow)
        if config.kv_tier:
            self._init_kv_tier(pool_keys, _wrap, rep, pool_sh)
        if self.spec:
            self._init_spec(B, run)
        if self._tp > 1:
            # per-shard perf-ledger rows: the sharded executables'
            # cost_analysis is captured from the PARTITIONED module, so
            # flops/bytes/MFU are already per-device — the mesh tag makes
            # that explicit in /stats and the roofline ledger
            from ..observability import perf as _perf
            for e in warm:
                _perf.note_entry_mesh(e, {"tp": self._tp})

    # -- hierarchical KV: host/disk tiers under the pool ---------------------
    def _init_kv_tier(self, pool_keys, _wrap, rep, pool_sh):
        """Two more one-compile executables plus the host-side tier
        state machine (``serving/kv_tier.py``):

        - ``serving.kv_demote``: gather ONE block's rows out of every
          pool (target + draft + int8/fp8 scale companions) — the
          device half of a device->host demotion. ``src`` is traced, so
          every demotion shares the executable.
        - ``serving.kv_splice``: scatter a demoted block's payload back
          into pool block ``dst`` (donated pools, traced ``dst``) — the
          re-admission that replaces that block's prefill chunks.

        Both run under jit with the same explicit-sharding wrapper as
        the other executables at tp>1 (payloads replicate; the pool
        sides keep the kv-heads sharding), so the zero-retrace
        invariant holds with tiering ON.
        """
        config = self.config
        spec = self.spec
        dpool_sh = self._tp_dpool_sh if (spec and rep is not None) else None

        if spec:
            def _kv_extract(pools, dpools, src):
                return ([{kk: c[kk][src] for kk in pool_keys}
                         for c in pools],
                        [{kk: c[kk][src] for kk in c} for c in dpools])

            def _kv_splice(pools, dpools, pay, dpay, dst):
                return ([{kk: c[kk].at[dst].set(pay[li][kk])
                          for kk in c} for li, c in enumerate(pools)],
                        [{kk: c[kk].at[dst].set(dpay[li][kk])
                          for kk in c} for li, c in enumerate(dpools)])

            ex_t = [{kk: rep for kk in pool_keys} for _ in self._pools]
            ex_d = [{kk: rep for kk in c} for c in self._dpools]
            _kv_extract = _wrap(_kv_extract, (),
                                (pool_sh, dpool_sh, rep), (ex_t, ex_d))
            _kv_splice = _wrap(_kv_splice, (0, 1),
                               (pool_sh, dpool_sh, ex_t, ex_d, rep),
                               (pool_sh, dpool_sh))
        else:
            def _kv_extract(pools, src):
                return [{kk: c[kk][src] for kk in pool_keys}
                        for c in pools]

            def _kv_splice(pools, pay, dst):
                return [{kk: c[kk].at[dst].set(pay[li][kk]) for kk in c}
                        for li, c in enumerate(pools)]

            ex_t = [{kk: rep for kk in pool_keys} for _ in self._pools]
            _kv_extract = _wrap(_kv_extract, (), (pool_sh, rep), ex_t)
            _kv_splice = _wrap(_kv_splice, (0,), (pool_sh, ex_t, rep),
                               pool_sh)
        self._kv_extract_fn = _kv_extract
        self._kv_splice_fn = _kv_splice
        _recompile.register_entry_location("serving.kv_demote", _kv_extract)
        _recompile.register_entry_location("serving.kv_splice", _kv_splice)

        # host bytes one demoted block costs (per-block rows across all
        # pools at quantized width) — the cost model's transfer size
        blk = sum(
            int(np.prod(c[kk].shape[1:], dtype=np.int64))
            * c[kk].dtype.itemsize
            for c in self._pools for kk in c)
        if spec:
            blk += sum(
                int(np.prod(c[kk].shape[1:], dtype=np.int64))
                * c[kk].dtype.itemsize
                for c in self._dpools for kk in c)
        self._tier_block_bytes = int(blk)

        def _prefill_rate():
            from ..observability import perf as _perf
            row = _perf.ledger_entry("serving.prefill_chunk")
            return row.get("items_per_s") if row else None

        cost = TierCostModel(host_gbps=config.kv_tier_host_gbps,
                             safety=config.kv_tier_safety,
                             prefill_rate_fn=_prefill_rate)
        disk = None
        if config.kv_tier_path:
            # re-admitting a foreign engine's bytes would be silent
            # corruption — the fingerprint pins everything that shapes
            # a block's payload or its interpretation
            disk = DiskPrefixStore(config.kv_tier_path, fingerprint={
                "kv_format": config.kv_format,
                "block_size": config.block_size,
                "bytes_per_token": self._kv_bytes_per_token,
                "dtype": str(np.dtype(self._dtype)),
                "spec": spec,
                "layers": int(self._mcfg.num_hidden_layers),
            })
        self._tier = KVTier(host_blocks=config.kv_tier_host_blocks,
                            block_size=config.block_size, cost=cost,
                            disk=disk)
        self.prefix_cache.on_evict = self._on_prefix_evict

    def _tier_extract(self, bid: int) -> dict:
        """Device->host copy of block ``bid``'s rows across every pool,
        as the tier's flat ``{"<layer>/<pool-key>": ndarray}`` payload
        (draft-model rows under ``d<layer>/``)."""
        t0 = time.perf_counter_ns()
        src = jnp.asarray(bid, jnp.int32)
        with _entrypoint("serving.kv_demote"):
            if self.spec:
                t, d = self._kv_extract_fn(self._pools, self._dpools, src)
            else:
                t, d = self._kv_extract_fn(self._pools, src), None
        payload = {}
        for li, c in enumerate(jax.device_get(t)):
            for kk, arr in c.items():
                payload[f"{li}/{kk}"] = np.asarray(arr)
        if d is not None:
            for li, c in enumerate(jax.device_get(d)):
                for kk, arr in c.items():
                    payload[f"d{li}/{kk}"] = np.asarray(arr)
        t1 = time.perf_counter_ns()
        _trace.complete("kv_demote", "engine", None, t0, t1 - t0,
                        {"block": bid})
        return payload

    def _tier_splice(self, bid: int, payload: dict):
        """Scatter a demoted payload back into pool block ``bid`` (the
        host->HBM re-admission; one jitted dispatch)."""
        t0 = time.perf_counter_ns()
        dst = jnp.asarray(bid, jnp.int32)
        pay = [{kk: jnp.asarray(payload[f"{li}/{kk}"])
                for kk in self._pool_keys}
               for li in range(len(self._pools))]
        with _entrypoint("serving.kv_splice"):
            if self.spec:
                dkeys = tuple(self._dpools[0].keys())
                dpay = [{kk: jnp.asarray(payload[f"d{li}/{kk}"])
                         for kk in dkeys}
                        for li in range(len(self._dpools))]
                self._pools, self._dpools = self._kv_splice_fn(
                    self._pools, self._dpools, pay, dpay, dst)
            else:
                self._pools = self._kv_splice_fn(self._pools, pay, dst)
        t1 = time.perf_counter_ns()
        _trace.complete("kv_splice", "engine", None, t0, t1 - t0,
                        {"block": bid})

    def _on_prefix_evict(self, key: bytes, bid: int, end: int) -> str:
        """PrefixCache eviction hook: copy the victim block down to the
        host tier when the cost model says the transfer beats the
        recompute it saves; the cache frees the device block either
        way."""
        tier = self._tier
        if tier is None:
            return "dropped"
        if not tier.cost.should_demote(tier.tokens_in_block(end),
                                       self._tier_block_bytes):
            return "dropped"
        tier.put(key, end, self._tier_extract(bid), reason="evict")
        return "demoted"

    def _demote_slot_blocks(self, slot: int, tokens: np.ndarray,
                            covered: int):
        """Preemption-side demotion: the victim slot's PRIVATE blocks
        (nobody else references them — shared ones survive in the
        prefix cache) demote to the host tier before ``_clear_slot``
        frees them, so the preempted request's resume prefill re-admits
        instead of recomputing."""
        tier = self._tier
        if tier is None or covered <= 0:
            return
        bs = self.config.block_size
        for i, bid in enumerate(self._slot_blocks[slot]):
            end = min((i + 1) * bs, covered)
            if end <= i * bs:
                break
            if self.pool.ref(bid) != 1:
                continue
            key = tier.key_of(tokens, end)
            if tier.has(key):
                continue
            if not tier.cost.should_demote(tier.tokens_in_block(end),
                                           self._tier_block_bytes):
                continue
            tier.put(key, end, self._tier_extract(bid), reason="preempt")

    def _flush_tier(self):
        """Drain-time persistence sweep (the restart contract): every
        still-cached prefix demotes to the host tier, then the whole
        host tier commits to the disk store. Best-effort — shutdown
        must never wedge on a full disk."""
        tier = self._tier
        if tier is None or tier.disk is None or self.prefix_cache is None:
            return
        try:
            for key, bid, end in self.prefix_cache.entries():
                if not tier.has(key):
                    tier.put(key, end, self._tier_extract(bid),
                             reason="flush")
            n = tier.flush()
            _trace.instant("kv_tier_flush", cat="engine",
                           args={"committed": n})
        except Exception as e:  # noqa: BLE001 — see docstring
            import warnings
            warnings.warn(f"kv_tier: drain-time flush failed "
                          f"(persistence skipped): {e!r}")

    # -- executables: speculative lane (paged only) --------------------------
    def _init_spec(self, B: int, run):
        """Draft + verify executables over the shared block tables.

        Two programs replace the plain decode step: ``spec_draft`` runs
        k cached draft-model forwards (q_len 1) proposing one token
        each, ``spec_verify`` scores the whole [B, k+1] bundle with the
        target in ONE paged flash-decode call and accepts the longest
        draft prefix matching the target's own selections. Every
        per-row quantity (positions, block tables, live bundle width
        ``spec_valid``, accept length) is traced data, so both compile
        exactly once whatever the accept-length pattern.

        PRNG contract: the draft proposes with the SAME chain subkeys
        the verify selects with (common-noise coupling), and the verify
        commits the chain at level ``n_emit`` — one split per EMITTED
        token, exactly the non-speculative chain, so outputs are
        bit-identical to plain decode (greedy AND sampled) and
        preemption's replay-by-token-count machinery works untouched.

        KV rollback is BY POSITION: rejected draft/target writes stay in
        the pool past the committed length; the next round's bundle
        lands on top of them before any in-length query can attend them
        (the same contract the contiguous cache's garbage rides on)."""
        config = self.config
        k = self._spec_k
        drun = self._drun
        pool_keys = self._pool_keys
        _wrap = self._tp_wrap
        rep = self._tp_rep
        pb_sh, dpb_sh = self._tp_pb_sh, self._tp_dpb_sh
        pool_sh = getattr(self, "_tp_pool_sh", None)
        dpool_sh = getattr(self, "_tp_dpool_sh", None)
        state_sh = self._tp_state_sh

        def _draft(dpb, dpools, state, bt, spec_valid, any_sampling):
            """k cached draft forwards proposing the bundle's draft
            tokens. ``spec_valid`` [B] is each row's live bundle width:
            draft writes beyond it are routed to the dump block (rows
            opted out of speculation still get their last token's draft
            KV at width 1, keeping the draft cache consistent for
            free)."""
            _, subs = split_key_levels(state["keys"], k)
            tok = state["tokens"]
            pos = state["pos"]
            drafts = []
            cur = dpools
            for j in range(k):
                caches = [dict(c, bt=bt,
                               valid=jnp.maximum(spec_valid - j, 0))
                          for c in cur]
                logits, newdc = drun(dpb, tok[:, None], caches, pos + j)
                last = logits[:, 0]
                sub_j = subs[:, j]
                tok = jax.lax.cond(
                    any_sampling,
                    lambda l=last, s=sub_j: select_tokens(
                        l, s, state["ds"], state["temp"], state["tk"],
                        state["tp"]),
                    lambda l=last: jnp.argmax(l, axis=-1).astype(jnp.int32))
                drafts.append(tok)
                cur = [{kk: c[kk] for kk in pool_keys} for c in newdc]
            # one write-only forward for the LAST draft token: on a
            # full accept the sequence advances past pos+k, and d_k's
            # draft KV was only ever an output — without this write the
            # next round's draft attends a hole there and falls off the
            # chain (accept rate halves; outputs are unaffected since
            # verify is target-authoritative). Dump-routed unless the
            # row's bundle really spans k+1 positions.
            caches = [dict(c, bt=bt, valid=jnp.maximum(spec_valid - k, 0))
                      for c in cur]
            _, newdc = drun(dpb, tok[:, None], caches, pos + k)
            cur = [{kk: c[kk] for kk in pool_keys} for c in newdc]
            return jnp.stack(drafts, axis=1), cur

        _draft = _wrap(_draft, (1,),
                       (dpb_sh, dpool_sh, state_sh, rep, rep, rep),
                       (rep, dpool_sh))

        def _verify(pb, pools, state, bt, drafts, spec_valid, any_sampling,
                    active):
            """ONE target forward over the [B, k+1] bundle (the paged
            kernel's q_len > 1 path), candidate selection for every
            position with that position's chain subkey, accept-length
            commit. Rows with ``spec_valid`` 1 ride as plain decode
            steps (their drafts are ignored), width-0 rows are inert —
            mixed spec/non-spec pools share this one executable."""
            bundle = jnp.concatenate([state["tokens"][:, None], drafts],
                                     axis=1)
            caches = [dict(c, bt=bt, valid=spec_valid) for c in pools]
            logits, newc = run(pb, bundle, caches, state["pos"])
            levels, subs = split_key_levels(state["keys"], k + 1)
            V = logits.shape[-1]
            flat = logits.reshape(B * (k + 1), V)

            def _rep(x):
                return jnp.broadcast_to(
                    x[:, None], (B, k + 1)).reshape(B * (k + 1))

            cand = jax.lax.cond(
                any_sampling,
                lambda: select_tokens(
                    flat, subs.reshape(B * (k + 1), 2), _rep(state["ds"]),
                    _rep(state["temp"]), _rep(state["tk"]),
                    _rep(state["tp"])),
                lambda: jnp.argmax(flat, axis=-1).astype(jnp.int32)
            ).reshape(B, k + 1)
            n_emit = spec_accept_length(drafts, cand, spec_valid)
            new_keys = jnp.take_along_axis(
                levels, n_emit[:, None, None], axis=1)[:, 0]
            last = jnp.take_along_axis(
                cand, jnp.maximum(n_emit - 1, 0)[:, None], axis=1)[:, 0]
            state = dict(state)
            state["tokens"] = jnp.where(n_emit > 0, last, state["tokens"])
            state["pos"] = jnp.where(
                active,
                jnp.minimum(state["pos"] + n_emit,
                            jnp.int32(config.max_len - 1)),
                jnp.int32(0))
            state["keys"] = new_keys
            pools_out = [{kk: c[kk] for kk in pool_keys} for c in newc]
            return cand, n_emit, pools_out, state

        _verify = _wrap(_verify, (1, 2),
                        (pb_sh, pool_sh, state_sh, rep, rep, rep, rep, rep),
                        (rep, rep, pool_sh, state_sh))

        if self._spec_tree is not None:
            # tree lane (ServingConfig.spec_tree): the chain pair above
            # is replaced before anything traces it — same entry names,
            # so warmup, recompile accounting, and the dispatch sites
            # stay lane-agnostic. The tree verify additionally owns the
            # draft pools (the accepted path's KV commits by position in
            # BOTH models' caches).
            _draft, _verify = self._build_tree_spec(B, run)

        def _chunk_spec(pb, dpb, pools, dpools, state, bt_row, ids, pos0,
                        valid, slot, is_last, last_idx, key, ds, temp, tk,
                        tp):
            """The prefill chunk with the draft model riding along: both
            models' paged caches take the chunk's writes through the one
            block table, so prefix-cached blocks carry BOTH models' KV
            and preemption-resume re-prefills both. Select/state logic
            is the plain chunk's, verbatim."""
            caches = [dict(c, bt=bt_row, valid=valid[None])
                      for c in pools]
            dcaches = [dict(c, bt=bt_row, valid=valid[None])
                       for c in dpools]
            logits, newc = run(pb, ids, caches, pos0)
            _, newdc = drun(dpb, ids, dcaches, pos0)
            last = jax.lax.dynamic_slice_in_dim(logits, last_idx, 1,
                                                axis=1)[:, 0]
            key2, sub = jax.random.split(key)
            token = jax.lax.cond(
                ds[0],
                lambda: select_tokens(last, sub[None], ds, temp, tk, tp),
                lambda: jnp.argmax(last, axis=-1).astype(jnp.int32))
            state = dict(state)

            def _sel(new, old):
                return jnp.where(is_last, new, old)

            state["tokens"] = state["tokens"].at[slot].set(
                _sel(token[0], state["tokens"][slot]))
            state["pos"] = state["pos"].at[slot].set(
                _sel(pos0 + valid, state["pos"][slot]))
            state["keys"] = state["keys"].at[slot].set(
                _sel(key2, state["keys"][slot]))
            state["ds"] = state["ds"].at[slot].set(_sel(ds[0], state["ds"][slot]))
            state["temp"] = state["temp"].at[slot].set(
                _sel(temp[0], state["temp"][slot]))
            state["tk"] = state["tk"].at[slot].set(_sel(tk[0], state["tk"][slot]))
            state["tp"] = state["tp"].at[slot].set(_sel(tp[0], state["tp"][slot]))
            pools_out = [{kk: c[kk] for kk in pool_keys} for c in newc]
            dpools_out = [{kk: c[kk] for kk in pool_keys} for c in newdc]
            return token, pools_out, dpools_out, state

        _chunk_spec = _wrap(
            _chunk_spec, (2, 3, 4),
            (pb_sh, dpb_sh, pool_sh, dpool_sh, state_sh) + (rep,) * 12,
            (rep, pool_sh, dpool_sh, state_sh))

        def _cow_spec(pools, dpools, src, dst):
            """COW fork across BOTH models' pools (same block ids)."""
            out, dout = [], []
            for c in pools:
                out.append({kk: c[kk].at[dst].set(c[kk][src])
                            for kk in c})
            for c in dpools:
                dout.append({kk: c[kk].at[dst].set(c[kk][src])
                             for kk in c})
            return out, dout

        _cow_spec = _wrap(_cow_spec, (0, 1),
                          (pool_sh, dpool_sh, rep, rep),
                          (pool_sh, dpool_sh))

        self._draft_fn = _draft
        self._verify_fn = _verify
        self._chunk_spec_fn = _chunk_spec
        self._cow_spec_fn = _cow_spec
        wd = int(self._tree["nodes"]) - 1 if self._spec_tree is not None \
            else k
        self._zero_drafts = jnp.zeros((B, wd), jnp.int32)
        _recompile.register_entry_location("serving.spec_draft", _draft)
        _recompile.register_entry_location("serving.spec_verify", _verify)
        _recompile.register_entry_location("serving.prefill_chunk",
                                           _chunk_spec)
        _recompile.register_entry_location("serving.cow", _cow_spec)

    def _build_tree_spec(self, B: int, run):
        """TREE-speculative draft + verify executables
        (``ServingConfig.spec_tree``; Medusa/SpecInfer-class token-tree
        verification on this repo's paged substrate).

        ``spec_draft`` grows the token tree level by level, each forward
        re-feeding the WHOLE tree-so-far under the square ancestor mask
        (past-KV masking is untouched, so a rectangular new-nodes-only
        query is not expressible; earlier nodes' KV rewrites
        bit-identically). Branch 0 of every node proposes with the exact
        chain subkey for its depth — the non-speculative sampler's own
        draw — and branches r > 0 diversify via ``fold_in`` on the
        child's BFS index. ``spec_verify`` scores all w flattened nodes
        in ONE paged flash-decode call (the [B, w, w] ancestor mask
        rides the cache dicts the way per-slot sampling params ride the
        state), walks the deepest root-to-leaf path whose every node
        matches the target's selection for its parent, and commits that
        path's KV BY POSITION in both models' pools — a gather/scatter
        through the block tables where non-committed slots route back
        onto themselves (same-value no-op writes). Node i's cache slot
        is pos + i; its RoPE/positional index is pos + depth(i), carried
        by the ``tree_depth`` vector.

        PRNG contract: identical to the chain lane — all depth-t nodes
        verify with chain subkey ``subs[:, t]``, the chain commits at
        level ``n_emit`` (one split per EMITTED token), so outputs are
        bit-identical to non-speculative decode (greedy AND sampled) and
        preemption replay / failover requeue machinery never notices the
        tree. Every per-row quantity (positions, block tables, live
        BFS-prefix width ``spec_valid``, accept depth) is traced data:
        both programs compile exactly once; width-1 rows ride the bundle
        as plain decode steps."""
        config = self.config
        drun = self._drun
        pool_keys = self._pool_keys
        _wrap = self._tp_wrap
        rep = self._tp_rep
        pb_sh, dpb_sh = self._tp_pb_sh, self._tp_dpb_sh
        pool_sh = getattr(self, "_tp_pool_sh", None)
        dpool_sh = getattr(self, "_tp_dpool_sh", None)
        state_sh = self._tp_state_sh
        plan = self._tree
        D, w = int(plan["depth"]), int(plan["nodes"])
        off = [int(o) for o in plan["offsets"]]
        factors = plan["factors"]
        parent = jnp.asarray(plan["parent"])
        depth_vec = jnp.asarray(plan["depth_vec"])
        anc_idx = jnp.asarray(plan["anc_idx"])
        anc = jnp.asarray(plan["anc"])
        bs = config.block_size

        def _rep_bw(x, m):
            return jnp.broadcast_to(x[:, None], (B, m)).reshape(B * m)

        def _tree_caches(pools, bt, valid, n):
            tm = jnp.broadcast_to(anc[:n, :n][None], (B, n, n))
            return [dict(c, bt=bt, valid=valid, tree_mask=tm,
                         tree_depth=depth_vec[:n]) for c in pools]

        def _draft(dpb, dpools, state, bt, spec_valid, any_sampling):
            """D level forwards + one write-only full-width forward.
            ``spec_valid`` [B] is each row's live node width (a BFS
            prefix): writes beyond it route to the dump block, so rows
            opted down to plain decode still get their root token's
            draft KV at width 1 (draft cache stays consistent for
            free)."""
            _, subs = split_key_levels(state["keys"], D + 1)
            tok_tree = jnp.zeros((B, w), jnp.int32).at[:, 0].set(
                state["tokens"])
            pos = state["pos"]
            cur = dpools
            for t in range(D):
                n = off[t + 1]
                caches = _tree_caches(
                    cur, bt, jnp.minimum(spec_valid, jnp.int32(n)), n)
                logits, newdc = drun(dpb, tok_tree[:, :n], caches, pos)
                cur = [{kk: c[kk] for kk in pool_keys} for c in newdc]
                lvl = logits[:, off[t]:n]            # [B, w_t, V]
                f = factors[t]
                w_next = off[t + 2] - off[t + 1]
                # greedy: branch 0 = argmax EXPLICITLY (bit-parity with
                # the verify selection under any top_k tie-break),
                # branches r>0 = the r-th ranked token
                tk = jax.lax.top_k(lvl, f)[1].astype(jnp.int32)
                tk = tk.at[:, :, 0].set(
                    jnp.argmax(lvl, axis=-1).astype(jnp.int32))
                greedy = tk.reshape(B, w_next)

                def _samp(lvl=lvl, t=t, f=f, w_next=w_next, greedy=greedy):
                    V = lvl.shape[-1]
                    base = subs[:, t]                # the chain subkey
                    gidx = off[t + 1] + jnp.arange(w_next,
                                                   dtype=jnp.uint32)
                    folded = jax.vmap(lambda kk: jax.vmap(
                        lambda g: jax.random.fold_in(kk, g))(gidx))(base)
                    use_base = (jnp.arange(w_next) % f) == 0
                    keys_lvl = jnp.where(
                        use_base[None, :, None],
                        jnp.broadcast_to(base[:, None], (B, w_next, 2)),
                        folded)
                    sampled = select_tokens(
                        jnp.repeat(lvl, f, axis=1).reshape(B * w_next, V),
                        keys_lvl.reshape(B * w_next, 2),
                        _rep_bw(state["ds"], w_next),
                        _rep_bw(state["temp"], w_next),
                        _rep_bw(state["tk"], w_next),
                        _rep_bw(state["tp"], w_next)).reshape(B, w_next)
                    return jnp.where(state["ds"][:, None], sampled, greedy)

                children = jax.lax.cond(any_sampling, _samp,
                                        lambda g=greedy: g)
                tok_tree = tok_tree.at[:, off[t + 1]:off[t + 2]].set(
                    children)
            # write-only forward at full width: leaf KV, so a deep
            # accept never leaves the next round's draft attending a
            # hole (outputs are unaffected either way — the verify is
            # target-authoritative)
            caches = _tree_caches(cur, bt, spec_valid, w)
            _, newdc = drun(dpb, tok_tree, caches, pos)
            cur = [{kk: c[kk] for kk in pool_keys} for c in newdc]
            return tok_tree[:, 1:], cur

        _draft = _wrap(_draft, (1,),
                       (dpb_sh, dpool_sh, state_sh, rep, rep, rep),
                       (rep, dpool_sh))

        def _kv_path_move(pools, bt, src_tok, dst_tok):
            """Commit-walk scatter: flat pool index = physical block
            (via the row's table) * block_size + offset; every path
            slot's payload is gathered BEFORE any write lands, and
            duplicate destinations only ever carry identical values
            (non-committed entries route onto their own source)."""
            nb_cols = bt.shape[1]
            sblk = jnp.clip(src_tok // bs, 0, nb_cols - 1)
            dblk = jnp.clip(dst_tok // bs, 0, nb_cols - 1)
            fsrc = (jnp.take_along_axis(bt, sblk, axis=1) * bs
                    + src_tok % bs).reshape(-1)
            fdst = (jnp.take_along_axis(bt, dblk, axis=1) * bs
                    + dst_tok % bs).reshape(-1)
            out = []
            for c in pools:
                nc = {}
                for kk in c:
                    p = c[kk]
                    fl = p.reshape((p.shape[0] * p.shape[1],)
                                   + p.shape[2:])
                    fl = fl.at[fdst].set(fl[fsrc])
                    nc[kk] = fl.reshape(p.shape)
                out.append(nc)
            return out

        def _verify(pb, pools, dpools, state, bt, drafts, spec_valid,
                    any_sampling, active):
            """ONE target forward over the [B, w] flattened tree, per-
            node candidate selection with the node's DEPTH subkey, the
            deepest-path accept walk, and the by-position KV commit in
            both pools."""
            bundle = jnp.concatenate([state["tokens"][:, None], drafts],
                                     axis=1)
            caches = _tree_caches(pools, bt, spec_valid, w)
            logits, newc = run(pb, bundle, caches, state["pos"])
            levels, subs = split_key_levels(state["keys"], D + 1)
            node_keys = jnp.take(subs, depth_vec, axis=1)   # [B, w, 2]
            V = logits.shape[-1]
            flat = logits.reshape(B * w, V)
            cand = jax.lax.cond(
                any_sampling,
                lambda: select_tokens(
                    flat, node_keys.reshape(B * w, 2),
                    _rep_bw(state["ds"], w), _rep_bw(state["temp"], w),
                    _rep_bw(state["tk"], w), _rep_bw(state["tp"], w)),
                lambda: jnp.argmax(flat, axis=-1).astype(jnp.int32)
            ).reshape(B, w)
            # a node survives iff its token matches the target's
            # selection for its PARENT and every ancestor survives
            # (D parent-AND sweeps); the BFS-prefix width gates rows
            match = jnp.concatenate(
                [jnp.ones((B, 1), bool),
                 bundle[:, 1:] == jnp.take(cand, parent[1:], axis=1)],
                axis=1)
            acc = match & (jnp.arange(w)[None, :] < spec_valid[:, None])
            for _ in range(D):
                acc = acc & jnp.take(acc, parent, axis=1)
            score = jnp.where(acc, depth_vec[None, :] + 1, 0)
            best = jnp.argmax(score, axis=1)
            n_emit = jnp.take_along_axis(score, best[:, None],
                                         axis=1)[:, 0]
            path = jnp.take(anc_idx, best, axis=0)          # [B, D+1]
            emitted = jnp.take_along_axis(cand, path, axis=1)
            new_keys = jnp.take_along_axis(
                levels, n_emit[:, None, None], axis=1)[:, 0]
            last = jnp.take_along_axis(cand, best[:, None], axis=1)[:, 0]
            pos = state["pos"]
            # commit slot pos+t <- slot pos+path[t] for 1 <= t < n_emit
            # in BOTH pools; everything else routes onto itself
            tt = jnp.arange(D + 1)[None, :]
            src_tok = pos[:, None] + path
            dst_tok = pos[:, None] + tt
            commit = (tt < n_emit[:, None]) & (tt >= 1)
            dst_tok = jnp.where(commit, dst_tok, src_tok)
            pools_out = _kv_path_move(
                [{kk: c[kk] for kk in pool_keys} for c in newc],
                bt, src_tok, dst_tok)
            dpools_out = _kv_path_move(dpools, bt, src_tok, dst_tok)
            state = dict(state)
            state["tokens"] = jnp.where(n_emit > 0, last,
                                        state["tokens"])
            state["pos"] = jnp.where(
                active,
                jnp.minimum(pos + n_emit,
                            jnp.int32(config.max_len - 1)),
                jnp.int32(0))
            state["keys"] = new_keys
            return emitted, n_emit, pools_out, dpools_out, state

        _verify = _wrap(_verify, (1, 2, 3),
                        (pb_sh, pool_sh, dpool_sh, state_sh,
                         rep, rep, rep, rep, rep),
                        (rep, rep, pool_sh, dpool_sh, state_sh))
        return _draft, _verify

    # -- executables: contiguous (the pre-paging engine, A/B baseline) -------
    def _init_contiguous(self, B: int, run):
        config = self.config
        mcfg = self._mcfg
        self._buckets = config.buckets()
        _recompile.reset_warmup(
            "serving.step", *(f"serving.prefill[{b}]" for b in self._buckets))
        self._caches = make_kv_caches(mcfg, B, config.max_len, self._dtype)

        @jax.jit
        def _prefill(pb, ids, last_idx, key, do_sample, temp, top_k, top_p):
            """Bucketed prefill: one forward over the right-padded
            prompt into fresh [1, Lb] caches, then the FIRST token
            select with generate's exact key chain
            (key, sub = split(key); select(last_logits, sub))."""
            Lb = ids.shape[1]
            caches = make_kv_caches(mcfg, 1, Lb, self._dtype)
            logits, caches = run(pb, ids, caches, 0)
            last = jax.lax.dynamic_slice_in_dim(logits, last_idx, 1, axis=1)[:, 0]
            key, sub = jax.random.split(key)
            token = jax.lax.cond(
                do_sample[0],
                lambda: select_tokens(last, sub[None], do_sample, temp,
                                      top_k, top_p),
                lambda: jnp.argmax(last, axis=-1).astype(jnp.int32))
            return token, key, caches

        @functools.partial(jax.jit, donate_argnums=(0, 1))
        def _splice(caches, state, pcaches, slot, token, pos0, key,
                    ds, temp, tk, tp):
            """Admission: copy a prefilled [1, Lb, h, d] cache into slot
            ``slot`` of the pool (rows [slot, 0:Lb]) via
            ``dynamic_update_slice`` AND set that slot's rows of the
            device-resident decode state — one dispatch, no recompile,
            nothing round-trips through the host."""
            out = []
            for c, p in zip(caches, pcaches):
                out.append({
                    "k": jax.lax.dynamic_update_slice(
                        c["k"], p["k"].astype(c["k"].dtype), (slot, 0, 0, 0)),
                    "v": jax.lax.dynamic_update_slice(
                        c["v"], p["v"].astype(c["v"].dtype), (slot, 0, 0, 0)),
                })
            state = dict(state)
            state["tokens"] = state["tokens"].at[slot].set(token)
            state["pos"] = state["pos"].at[slot].set(pos0)
            state["keys"] = state["keys"].at[slot].set(key)
            state["ds"] = state["ds"].at[slot].set(ds)
            state["temp"] = state["temp"].at[slot].set(temp)
            state["tk"] = state["tk"].at[slot].set(tk)
            state["tp"] = state["tp"].at[slot].set(tp)
            return out, state

        @functools.partial(jax.jit, donate_argnums=(1, 2))
        def _step(pb, caches, state, any_sampling, active):
            """ONE decode iteration for the whole slot pool (contiguous
            caches): per-slot positions drive per-row RoPE/cache-write/
            mask; per-slot params + keys drive the batched sampler.
            Compiles once; free slots ride along pinned to pos 0."""
            logits, caches = run(pb, state["tokens"][:, None], caches,
                                 state["pos"])
            last = logits[:, 0]
            new_keys, subs = split_keys(state["keys"])
            nxt = jax.lax.cond(
                any_sampling,
                lambda: select_tokens(last, subs, state["ds"], state["temp"],
                                      state["tk"], state["tp"]),
                lambda: jnp.argmax(last, axis=-1).astype(jnp.int32))
            state = dict(state)
            state["tokens"] = nxt
            state["pos"] = jnp.where(
                active,
                jnp.minimum(state["pos"] + 1, jnp.int32(config.max_len - 1)),
                jnp.int32(0))
            state["keys"] = new_keys
            return nxt, caches, state

        self._prefill_fn = _prefill
        self._splice_fn = _splice
        self._step_fn = _step
        _recompile.register_entry_location("serving.step", _step)
        for b in self._buckets:
            _recompile.register_entry_location(f"serving.prefill[{b}]",
                                               _prefill)

    # -- warmup: AOT-compile every executable before taking traffic ----------
    def warmup(self) -> dict:
        """Compile every executable this engine will dispatch — the
        pool-wide decode step (or the spec draft+verify pair), the
        ``[1, C]`` prefill chunk, and the COW fork (contiguous mode:
        every prefill bucket + splice + step) — by running each once
        with inert inputs: zeroed block tables route every write to the
        reserved dump block, ``valid``/``active`` masks are all-off, and
        ``is_last`` is False, so no slot state a future request relies
        on is touched (free rows' tokens/keys are scratch that admission
        rewrites anyway).

        A replica that warms up before registering with the router
        serves its FIRST request with zero compiles — the recompile
        monitor asserts it (the warmup runs inside
        ``recompile.warmup_scope`` so a second in-process replica's
        expected compiles never count as retraces of the first's
        entries). Requires an idle engine; idempotent. Returns
        ``{"entries": [...], "compiles": n, "wall_s": t}``."""
        t0 = time.perf_counter()
        before = _recompile.total_compiles()
        with self._step_lock:
            if self.busy_slots() or self.scheduler.depth:
                raise RuntimeError(
                    "warmup() requires an idle engine: it dispatches "
                    "every executable with inert (dump-block-routed) "
                    "inputs — warm up before submitting traffic")
            with _recompile.warmup_scope():
                if self.paged:
                    entries = self._warmup_paged()
                else:
                    entries = self._warmup_contiguous()
            self._warmed_up = True
        return {"entries": entries,
                "compiles": _recompile.total_compiles() - before,
                "wall_s": round(time.perf_counter() - t0, 4)}

    def _warmup_paged(self) -> list:
        B = self.config.max_slots
        nb = self._bt.shape[1]
        bt1 = jnp.zeros((1, nb), jnp.int32)
        btB = jnp.zeros((B, nb), jnp.int32)
        off = jnp.zeros(B, bool)
        zero_i = jnp.asarray(0, jnp.int32)
        chunk_args = (
            bt1, jnp.zeros((1, self._chunk_size), jnp.int32),
            zero_i, zero_i, zero_i, jnp.asarray(False), zero_i,
            jax.random.PRNGKey(0), jnp.asarray([False]),
            jnp.asarray([1.0], jnp.float32), jnp.asarray([0], jnp.int32),
            jnp.asarray([1.0], jnp.float32))
        entries = ["serving.prefill_chunk", "serving.cow"]
        with _entrypoint("serving.prefill_chunk"):
            if self.spec:
                _, self._pools, self._dpools, self._state = \
                    self._chunk_spec_fn(self._pb, self._dpb, self._pools,
                                        self._dpools, self._state,
                                        *chunk_args)
            else:
                _, self._pools, self._state = self._chunk_fn(
                    self._pb, self._pools, self._state, *chunk_args)
        if self.spec:
            # a spec engine never traces the plain step — its decode
            # round is the draft+verify pair
            entries += ["serving.spec_draft", "serving.spec_verify"]
            sv0 = jnp.zeros(B, jnp.int32)
            with _entrypoint("serving.spec_draft"):
                _, self._dpools = self._draft_fn(
                    self._dpb, self._dpools, self._state, btB, sv0,
                    jnp.asarray(False))
            with _entrypoint("serving.spec_verify"):
                if self._spec_tree is not None:
                    _, _, self._pools, self._dpools, self._state = \
                        self._verify_fn(
                            self._pb, self._pools, self._dpools,
                            self._state, btB, self._zero_drafts, sv0,
                            jnp.asarray(False), off)
                else:
                    _, _, self._pools, self._state = self._verify_fn(
                        self._pb, self._pools, self._state, btB,
                        self._zero_drafts, sv0, jnp.asarray(False), off)
        else:
            entries.append("serving.step")
            with _entrypoint("serving.step"):
                _, self._pools, self._state = self._step_fn(
                    self._pb, self._pools, self._state, btB,
                    jnp.asarray(False), off)
        with _entrypoint("serving.cow"):
            if self.spec:
                self._pools, self._dpools = self._cow_spec_fn(
                    self._pools, self._dpools, zero_i, zero_i)
            else:
                self._pools = self._cow_fn(self._pools, zero_i, zero_i)
        if self._tier is not None:
            # inert tier round trip: extract the dump block's rows and
            # splice the same payload back into it (dump content is
            # never meaningfully read) — compiles both tier executables
            entries += ["serving.kv_demote", "serving.kv_splice"]
            self._tier_splice(DUMP_BLOCK, self._tier_extract(DUMP_BLOCK))
        return entries

    def _warmup_contiguous(self) -> list:
        B = self.config.max_slots
        entries = ["serving.step"]
        for b in self._buckets:
            entries.append(f"serving.prefill[{b}]")
            with _entrypoint(f"serving.prefill[{b}]"):
                token, key, pcaches = self._prefill_fn(
                    self._pb,
                    jnp.full((1, b), self.config.pad_token_id, jnp.int32),
                    jnp.asarray(0, jnp.int32), jax.random.PRNGKey(0),
                    jnp.asarray([False]), jnp.asarray([1.0], jnp.float32),
                    jnp.asarray([0], jnp.int32),
                    jnp.asarray([1.0], jnp.float32))
                # pos0 = 0: the step pins free rows to position 0, so
                # the scratch splice into (free) slot 0 is invisible
                self._caches, self._state = self._splice_fn(
                    self._caches, self._state, pcaches,
                    jnp.asarray(0, jnp.int32), token[0],
                    jnp.asarray(0, jnp.int32), key, jnp.asarray(False),
                    jnp.asarray(1.0, jnp.float32),
                    jnp.asarray(0, jnp.int32),
                    jnp.asarray(1.0, jnp.float32))
        with _entrypoint("serving.step"):
            _, self._caches, self._state = self._step_fn(
                self._pb, self._caches, self._state, jnp.asarray(False),
                jnp.zeros(B, bool))
        return entries

    # -- submission ----------------------------------------------------------
    def submit(self, prompt, deadline_s: Optional[float] = None,
               on_token=None, params: Optional[SamplingParams] = None,
               **sampling) -> Request:
        """Enqueue one request; returns its handle immediately.

        ``prompt`` is a 1-D sequence of token ids; ``sampling`` takes
        the ``SamplingParams`` fields (``max_new_tokens``, ``do_sample``,
        ``temperature``, ``top_k``, ``top_p``, ``eos_token_id``,
        ``seed``), or pass a prebuilt ``params``. Raises ``ValueError``
        for requests that cannot fit a slot and ``QueueFullError`` under
        backpressure."""
        if self._crashed is not None:
            raise RuntimeError(
                f"serving engine has crashed ({self._crashed}); create a "
                f"fresh engine — this one's decode state is gone")
        if self._stopped:
            raise EngineStoppedError(
                "serving engine is stopped; submit() refused — build a "
                "fresh engine (and warmup() it before taking traffic)")
        if self._draining:
            raise EngineDrainingError(
                "serving engine is draining: in-flight requests are "
                "finishing but no new work is admitted — route this "
                "request to another replica")
        if params is None:
            params = SamplingParams(**sampling)
        elif sampling:
            raise ValueError("pass params OR sampling kwargs, not both")
        prompt = np.asarray(prompt, dtype=np.int32).reshape(-1)
        L = int(prompt.shape[0])
        if L < 1:
            raise ValueError("empty prompt")
        if params.max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if L + params.max_new_tokens > self.config.max_len:
            raise ValueError(
                f"prompt ({L}) + max_new_tokens ({params.max_new_tokens}) "
                f"exceeds the slot KV capacity max_len="
                f"{self.config.max_len}")
        if self.paged:
            bs = self.config.block_size
            worst = -(-(L + params.max_new_tokens - 1) // bs)
            if worst > self.pool.usable_blocks:
                raise ValueError(
                    f"prompt ({L}) + max_new_tokens "
                    f"({params.max_new_tokens}) needs up to {worst} KV "
                    f"blocks of {bs} tokens, but the pool only has "
                    f"{self.pool.usable_blocks} usable blocks — raise "
                    f"num_blocks or shrink the request")
        req = Request(prompt, params, deadline_s=deadline_s, on_token=on_token)
        self.scheduler.submit(req)  # may raise QueueFullError
        with self._wake:
            self._wake.notify_all()
        return req

    def cancel(self, req: Request) -> bool:
        return self.scheduler.cancel(req)

    # -- slot bookkeeping ----------------------------------------------------
    def _bucket(self, L: int) -> int:
        for b in self._buckets:
            if b >= L:
                return b
        raise ValueError(f"prompt length {L} exceeds max bucket "
                         f"{self._buckets[-1]}")

    def busy_slots(self) -> int:
        return sum(r is not None for r in self._slot_req)

    def _update_occupancy_gauges(self):
        busy = self.busy_slots()
        _sm.slots_busy.set(busy)
        _sm.slot_occupancy.set(busy / max(1, self.config.max_slots))

    def _clear_slot(self, slot: int):
        """Reset every host-side trace of a slot's occupant (shared by
        free and preempt paths)."""
        self._slot_req[slot] = None
        self._slot_sampling[slot] = False
        self._decoding[slot] = False
        if self.paged:
            self._jobs[slot] = None
            for b in self._slot_blocks[slot]:
                self.pool.decref(b)
            self._slot_blocks[slot] = []
            self._bt[slot, :] = 0
            self._slot_len[slot] = 0

    def _note_admission(self, req: Request, now: float,
                        resumed: bool = False):
        """Queue-wait digest + trace transitions shared by both engines:
        the ``queued`` span ends, ``admitted`` (and ``resume`` for a
        preempted request) lands, and the wait feeds the p50/p95/p99
        digest."""
        wait = max(now - req.queued_since_ts, 0.0)
        req.queue_wait_total_s += wait
        req.admitted_ts = now
        _sm.queue_wait_seconds.observe(wait)
        req._tr_end("queued", wait_s=round(wait, 6))
        if resumed:
            req._tr_event("resume", generated=len(req.output_tokens))
        req._tr_event("admitted", slot=req.slot)
        req._tr_begin("prefill")

    def _note_goodput(self, req: Request, now: float):
        """Completed within deadline (or no deadline): its tokens count
        toward the goodput gauge over the sliding window."""
        if req.deadline_ts is not None and now > req.deadline_ts:
            return
        w = self._goodput_window
        w.append((now, len(req.output_tokens)))
        horizon = now - self._goodput_span_s
        while w and w[0][0] < horizon:
            w.popleft()
        span = max(now - w[0][0], 1e-9) if len(w) > 1 \
            else self._goodput_span_s
        _sm.goodput_tokens_per_second.set(
            sum(n for _, n in w) / max(span, 1e-9))

    def _free_slot(self, slot: int, status: str, outcome: str,
                   error: Optional[str] = None):
        req = self._slot_req[slot]
        self._clear_slot(slot)
        if req is not None:
            req.finish(status, error=error)
            _sm.requests_total.labels(outcome).inc()
            self._outcomes[outcome] = self._outcomes.get(outcome, 0) + 1
            self._recent.append(req)
            if outcome == "completed":
                self._note_goodput(req, req.finish_ts)
        self._update_occupancy_gauges()

    def _finish_or_keep(self, slot: int, req: Request, token: int,
                        now: float) -> bool:
        """Terminal checks after a delivered token; True when freed."""
        p = req.params
        if req.cancel_requested:
            self._free_slot(slot, RequestStatus.CANCELLED, "cancelled")
            return True
        if req.deadline_ts is not None and now > req.deadline_ts:
            self._free_slot(slot, RequestStatus.EXPIRED, "expired",
                            error="deadline passed during decode")
            return True
        if p.eos_token_id is not None and token == p.eos_token_id:
            self._free_slot(slot, RequestStatus.COMPLETED, "completed")
            return True
        if len(req.output_tokens) >= p.max_new_tokens:
            self._free_slot(slot, RequestStatus.COMPLETED, "completed")
            return True
        return False

    # -- paged: pool pressure (eviction -> preemption) -----------------------
    def _reclaim_alloc(self, n: int, requester: int,
                       allow_preempt: bool = True) -> List[int]:
        """Allocate ``n`` blocks, reclaiming under pressure: first evict
        prefix-cache entries nobody references, then (decode/COW paths
        only) preempt the latest-admitted OTHER request. Admission never
        preempts — a request that cannot be admitted without violence
        waits at the queue front instead (no admission/preemption
        thrash)."""
        while True:
            try:
                return self.pool.alloc(n)
            except PoolExhaustedError:
                deficit = max(1, n - self.pool.free_blocks)
                if self.prefix_cache is not None \
                        and self.prefix_cache.evict(deficit) > 0:
                    continue
                victim = self._pick_victim(exclude=requester) \
                    if allow_preempt else None
                if victim is None:
                    raise
                self._preempt(victim)

    def _pick_victim(self, exclude: int) -> Optional[int]:
        """Latest-admitted busy slot (other than ``exclude``) whose
        release would actually free at least one block. Oldest requests
        are never victimized first, so the head of the line always makes
        progress and preemption terminates."""
        best, best_seq = None, -1
        for slot in range(self.config.max_slots):
            if slot == exclude or self._slot_req[slot] is None:
                continue
            if not any(self.pool.ref(b) == 1 for b in self._slot_blocks[slot]):
                continue  # all shared: releasing frees nothing
            if self._slot_seq[slot] > best_seq:
                best, best_seq = slot, self._slot_seq[slot]
        return best

    def _build_resume(self, slot: int):
        """Seed-deterministic resume state for the slot's occupant (the
        recipe both preemption and supervised restart replay): mid-
        prefill restarts the same chunk job; mid-decode folds the
        generated tokens into the next prefill with the PRNG chain
        split back to the right link, and the one token the resumed
        prefill's final select re-derives is skipped, never
        re-delivered. The resumed decode is bit-identical — on THIS
        engine after a preemption or on a fresh one after a crash.
        Returns ``(tokens, recompute_len)`` for the caller's block
        bookkeeping (``(None, 0)`` when nothing ran yet: a fresh
        prefill replays everything)."""
        req = self._slot_req[slot]
        job = self._jobs[slot] if self.paged else None
        if job is not None:
            # mid-prefill: nothing delivered yet; restart the same job
            req._resume = (job.tokens, job.key, job.skip)
            return job.tokens, job.done
        g = len(req.output_tokens)
        if g == 0:
            # claimed but never prefilled (crash between admission
            # bookkeeping and the first chunk): full replay
            req._resume = None
            return None, 0
        key = jax.random.PRNGKey(req.params.seed)
        for _ in range(g - 1):
            key, _ = jax.random.split(key)
        tokens = np.concatenate(
            [req.prompt,
             np.asarray(req.output_tokens[:g - 1], np.int32)])
        req._resume = (tokens, key, 1)
        return tokens, (self._slot_len[slot] if self.paged else len(tokens))

    def _preempt(self, slot: int):
        """Preemption by recompute: release the slot's blocks and push
        the request back to the QUEUE FRONT with its generated tokens
        folded into the next prefill and its PRNG chain replayed — the
        resumed decode is bit-identical, and the one token the resumed
        prefill's select re-derives is skipped, never re-delivered."""
        req = self._slot_req[slot]
        tokens, recompute_len = self._build_resume(slot)
        if tokens is not None:
            # the resume prefill recomputes exactly tokens[:recompute_
            # len]; demoting the private blocks now lets it re-admit
            # them through the tier instead of re-running the chunks
            self._demote_slot_blocks(slot, tokens, recompute_len)
        req.slot = None
        req.preempt_count += 1
        # whichever lifecycle span is open (prefill or decode) ends at
        # the preemption boundary; requeue() opens the next queued span
        req._tr_end("prefill")
        req._tr_end("decode")
        req._tr_event("preempted", slot=slot,
                      generated=len(req.output_tokens))
        self._clear_slot(slot)
        self.scheduler.requeue(req)
        self._preempt_count += 1
        _sm.preemptions_total.inc()
        self._update_occupancy_gauges()

    def _ensure_writable(self, slot: int, block_idx: int):
        """COW: the first write into a SHARED block forks it — allocate
        a fresh block, copy the shared content (one jitted dispatch),
        repoint the slot's table, drop the shared reference."""
        bid = self._slot_blocks[slot][block_idx]
        if self.pool.ref(bid) <= 1:
            return
        new_id = self._reclaim_alloc(1, slot)[0]
        with _entrypoint("serving.cow"):
            if self.spec:
                self._pools, self._dpools = self._cow_spec_fn(
                    self._pools, self._dpools,
                    jnp.asarray(bid, jnp.int32),
                    jnp.asarray(new_id, jnp.int32))
            else:
                self._pools = self._cow_fn(self._pools,
                                           jnp.asarray(bid, jnp.int32),
                                           jnp.asarray(new_id, jnp.int32))
        self.pool.decref(bid)
        self._slot_blocks[slot][block_idx] = new_id
        self._bt[slot, block_idx] = new_id
        self.pool.note_cow_fork()
        _sm.cow_forks_total.inc()
        req = self._slot_req[slot]
        if req is not None:
            req._tr_event("cow_fork", block=block_idx, src=bid, dst=new_id)

    # -- paged: admission + chunked prefill ----------------------------------
    def _begin_prefill(self, req: Request, slot: int):
        """Claim the slot: match the prompt against the prefix cache,
        allocate the remaining prompt blocks, and queue the chunk job.
        No model work happens here — chunks run interleaved with decode
        steps in ``step()``."""
        resume = req._resume
        if resume is not None:
            tokens, key, skip = resume
        else:
            tokens, key, skip = req.prompt, \
                jax.random.PRNGKey(req.params.seed), 0
        tokens = np.asarray(tokens, np.int32).reshape(-1)
        total = int(tokens.shape[0])
        bs = self.config.block_size
        n_blocks = -(-total // bs)
        matched_tok, mblocks = 0, []
        if self.prefix_cache is not None:
            matched_tok, mblocks = self.prefix_cache.match(tokens, total - 1)
        # hierarchical KV re-admission: extend the prefix-cache match
        # through the host/disk tiers — each hit allocates a fresh
        # block and SPLICES the demoted payload back instead of running
        # that block's prefill chunks. A partial tier entry is always
        # the last extension.
        covered, tier_blocks, tier_tok = matched_tok, [], 0
        if self._tier is not None and covered % bs and mblocks:
            # partial-tail upgrade: the cache match ended mid-block, but
            # the tier may hold a LONGER demoted copy of that same
            # block (a preempted request's COW fork demotes keyed at
            # the boundary). Swap the partial cache block for a spliced
            # tier block — this is what re-aligns coverage so the
            # aligned loop below can keep extending through the
            # preempted request's decode blocks. The entry must end
            # inside the SAME block: a longer key's payload would be
            # the next block, not a replacement for this one.
            ceil = ((covered // bs) + 1) * bs
            ent = self._tier.match_next(tokens, covered,
                                        min(ceil, total - 1))
            if ent is not None and self._tier.cost.should_readmit(
                    ent[0] - covered, self._tier_block_bytes):
                end, payload, src = ent
                try:
                    nid = self._reclaim_alloc(1, slot,
                                              allow_preempt=False)[0]
                except PoolExhaustedError:
                    nid = None
                if nid is not None:
                    self._tier_splice(nid, payload)
                    self.pool.decref(mblocks.pop())  # drop partial tail
                    matched_tok = (matched_tok // bs) * bs
                    tier_blocks.append(nid)
                    tier_tok += end - covered
                    _sm.kv_tier_readmitted_blocks.labels(src).inc()
                    covered = end
        if self._tier is not None and covered % bs == 0:
            while covered < total - 1:
                ent = self._tier.match_next(tokens, covered, total - 1)
                if ent is None:
                    break
                end, payload, src = ent
                if not self._tier.cost.should_readmit(
                        end - covered, self._tier_block_bytes):
                    break
                try:
                    nid = self._reclaim_alloc(1, slot,
                                              allow_preempt=False)[0]
                except PoolExhaustedError:
                    break  # re-admit what fits; prefill does the rest
                self._tier_splice(nid, payload)
                tier_blocks.append(nid)
                tier_tok += end - covered
                _sm.kv_tier_readmitted_blocks.labels(src).inc()
                covered = end
                if end % bs:
                    break
        try:
            fresh = self._reclaim_alloc(
                n_blocks - len(mblocks) - len(tier_blocks), slot,
                allow_preempt=False)
        except PoolExhaustedError:
            # admission retries later — the resume state MUST survive
            # this attempt, or a requeued preempted request would
            # restart as fresh and re-deliver its tokens
            for b in mblocks + tier_blocks:
                self.pool.decref(b)
            raise
        req._resume = None  # consumed only once admission is certain
        if self.prefix_cache is not None:
            self.prefix_cache.note(len(mblocks), n_blocks - len(mblocks))
            _sm.prefix_cache_hits.inc(len(mblocks))
            _sm.prefix_cache_misses.inc(n_blocks - len(mblocks))
            if matched_tok:
                _sm.tokens_total.labels("prompt_cached").inc(matched_tok)
            if mblocks:
                req._tr_event("prefix_cache_hit", blocks=len(mblocks),
                              tokens=matched_tok)
            else:
                req._tr_event("prefix_cache_miss", blocks=n_blocks)
        if tier_tok:
            self._tier.note_readmit(len(tier_blocks), tier_tok)
            _sm.kv_tier_readmitted_tokens.inc(tier_tok)
            _sm.tokens_total.labels("prompt_tier").inc(tier_tok)
            req._tr_event("kv_tier_readmit", blocks=len(tier_blocks),
                          tokens=tier_tok)
        blocks = mblocks + tier_blocks + fresh
        self._slot_blocks[slot] = blocks
        self._bt[slot, :] = 0
        self._bt[slot, :len(blocks)] = blocks
        self._slot_len[slot] = 0
        self._decoding[slot] = False
        self._slot_req[slot] = req
        self._admit_seq += 1
        self._slot_seq[slot] = self._admit_seq
        req.slot = slot
        req.status = RequestStatus.RUNNING
        self._note_admission(req, time.perf_counter(),
                             resumed=resume is not None)
        self._jobs[slot] = _PrefillJob(req=req, tokens=tokens, total=total,
                                       done=covered, key=key, skip=skip)
        self._update_occupancy_gauges()

    def _advance_prefill(self, slot: int):
        """Run ONE fixed-size prefill chunk for the slot. The final
        chunk also selects the first token (generate's key chain) and
        flips the slot into the decode batch; its already-prefilled
        prompt blocks are registered with the prefix cache BEFORE any
        decode write can dirty them (COW keeps them pristine)."""
        job = self._jobs[slot]
        req = job.req
        if req.cancel_requested:
            self._free_slot(slot, RequestStatus.CANCELLED, "cancelled")
            return
        if req.deadline_ts is not None \
                and time.perf_counter() > req.deadline_ts:
            # the deadline can expire BETWEEN admission and the first
            # (or any) prefill chunk — free the blocks now instead of
            # burning chunk dispatches on a request nobody will read
            self._free_slot(slot, RequestStatus.EXPIRED, "expired",
                            error="deadline passed during prefill")
            return
        C = self._chunk_size
        bs = self.config.block_size
        start = job.done
        end = min(start + C, job.total)
        is_last = end == job.total
        for bi in range(start // bs, (end - 1) // bs + 1):
            self._ensure_writable(slot, bi)
        ids = np.full((1, C), self.config.pad_token_id, np.int32)
        ids[0, :end - start] = job.tokens[start:end]
        p = req.params
        tc0 = time.perf_counter_ns()
        # the request is the active trace during its chunk, so an XLA
        # compile fired here (the one serving.prefill_chunk warmup, or a
        # would-be-retrace bug) lands in this request's timeline
        with _trace.trace_context(req.trace), \
                _entrypoint("serving.prefill_chunk"):
            chunk_args = (
                jnp.asarray(self._bt[slot:slot + 1]),
                jnp.asarray(ids), jnp.asarray(start, jnp.int32),
                jnp.asarray(end - start, jnp.int32),
                jnp.asarray(slot, jnp.int32), jnp.asarray(is_last),
                jnp.asarray(job.total - 1 - start, jnp.int32), job.key,
                jnp.asarray([p.do_sample]),
                jnp.asarray([p.temperature], jnp.float32),
                jnp.asarray([p.top_k], jnp.int32),
                jnp.asarray([p.top_p], jnp.float32))
            if self.spec:
                token, self._pools, self._dpools, self._state = \
                    self._chunk_spec_fn(self._pb, self._dpb, self._pools,
                                        self._dpools, self._state,
                                        *chunk_args)
            else:
                token, self._pools, self._state = self._chunk_fn(
                    self._pb, self._pools, self._state, *chunk_args)
        tc1 = time.perf_counter_ns()
        _trace.complete("prefill_chunk", "request", req.trace, tc0, tc1 - tc0,
                        {"slot": slot, "start": start, "end": end,
                         "last": is_last})
        _sm.prefill_chunk_seconds.observe((tc1 - tc0) / 1e9)
        job.done = end
        _sm.prefill_chunks_total.inc()
        _sm.tokens_total.labels("prompt").inc(end - start)
        from ..observability import perf as _perf
        _perf.note_entry_items("serving.prefill_chunk", end - start)
        if not is_last:
            return
        if self.prefix_cache is not None:
            n_reg = min(int(req.prompt.shape[0]), job.total)
            self.prefix_cache.insert(
                job.tokens, n_reg,
                self._slot_blocks[slot][:-(-n_reg // bs)])
        tok0 = int(np.asarray(token)[0])
        now = time.perf_counter()
        _sm.prefill_seconds.observe(now - job.t0)
        self._jobs[slot] = None
        self._decoding[slot] = True
        self._slot_len[slot] = job.total
        self._slot_sampling[slot] = bool(p.do_sample)
        req.prefill_done_ts = now
        req._tr_end("prefill", tokens=job.total)
        req._tr_begin("decode")
        if job.skip:
            return  # resumed: tok0 re-derives the last delivered token
        req.push_token(tok0, now)
        req._tr_event("first_token")
        _sm.ttft_seconds.observe(req.ttft_s)
        _sm.ttft_summary.observe(req.ttft_s)
        _sm.tokens_total.labels("generated").inc()
        self._finish_or_keep(slot, req, tok0, now)
        self._update_occupancy_gauges()

    # -- contiguous: admission / prefill -------------------------------------
    def _prefill_into_slot(self, req: Request, slot: int):
        p = req.params
        L = int(req.prompt.shape[0])
        Lb = self._bucket(L)
        ids = np.full((1, Lb), self.config.pad_token_id, np.int32)
        ids[0, :L] = req.prompt
        t0 = time.perf_counter()
        req.slot = slot
        self._note_admission(req, t0)
        with _trace.trace_context(req.trace), \
                _entrypoint(f"serving.prefill[{Lb}]"):
            token, key, pcaches = self._prefill_fn(
                self._pb, jnp.asarray(ids), jnp.asarray(L - 1, jnp.int32),
                jax.random.PRNGKey(p.seed),
                jnp.asarray([p.do_sample]),
                jnp.asarray([p.temperature], jnp.float32),
                jnp.asarray([p.top_k], jnp.int32),
                jnp.asarray([p.top_p], jnp.float32))
            # prefill outputs stay on device: the splice wires them into
            # the pool caches + the slot's decode-state rows directly
            self._caches, self._state = self._splice_fn(
                self._caches, self._state, pcaches,
                jnp.asarray(slot, jnp.int32), token[0],
                jnp.asarray(L, jnp.int32), key,
                jnp.asarray(p.do_sample),
                jnp.asarray(p.temperature, jnp.float32),
                jnp.asarray(p.top_k, jnp.int32),
                jnp.asarray(p.top_p, jnp.float32))
        tok0 = int(np.asarray(token)[0])
        now = time.perf_counter()
        _sm.prefill_seconds.observe(now - t0)
        _sm.tokens_total.labels("prompt").inc(L)
        _sm.tokens_total.labels("generated").inc()

        self._slot_req[slot] = req
        self._slot_sampling[slot] = bool(p.do_sample)
        self._decoding[slot] = True
        req.slot = slot
        req.status = RequestStatus.RUNNING
        req.prefill_done_ts = now
        req._tr_end("prefill", tokens=L)
        req._tr_begin("decode")

        req.push_token(tok0, now)
        req._tr_event("first_token")
        _sm.ttft_seconds.observe(req.ttft_s)
        _sm.ttft_summary.observe(req.ttft_s)
        self._finish_or_keep(slot, req, tok0, now)
        self._update_occupancy_gauges()

    def _admit(self):
        """Fill every free slot FCFS from the queue; runs at the top of
        each iteration so a slot freed by EOS is refilled before the
        next decode step. Paged admission only claims blocks and queues
        the chunk job; contiguous admission runs the whole bucketed
        prefill inline (the pre-paging behavior)."""
        # quarantine-probe isolation: a crash SUSPECT the supervisor
        # requeued runs ALONE — admitted only into an idle pool, with
        # nothing admitted beside it. A repeat crash then implicates
        # exactly one request instead of smearing suspicion over
        # innocent co-runners (which is what would let a single poison
        # request quarantine its whole cohort).
        if any(r is not None and r.quarantine_probe for r in self._slot_req):
            return
        for slot in range(self.config.max_slots):
            while self._slot_req[slot] is None:
                req = self.scheduler.pop_ready()
                if req is None:
                    return
                if req.quarantine_probe and self.busy_slots():
                    # the probe waits at the queue front for an idle
                    # pool (admission-backoff requeue: same wait
                    # window), and blocks everything behind it — brief,
                    # bounded by the in-flight requests' decode
                    self.scheduler.requeue(req)
                    return
                try:
                    if self.paged:
                        self._begin_prefill(req, slot)
                    else:
                        self._prefill_into_slot(req, slot)
                except PoolExhaustedError:
                    # not enough free blocks even after cache eviction:
                    # FCFS holds — the request waits at the queue front
                    # until decode completions release blocks
                    self.scheduler.requeue(req)
                    return
                except Exception as e:  # noqa: BLE001 — engine must survive
                    self._clear_slot(slot)
                    req.finish(RequestStatus.FAILED, error=repr(e))
                    _sm.requests_total.labels("failed").inc()
                    self._outcomes["failed"] = self._outcomes.get("failed", 0) + 1
                else:
                    if req.quarantine_probe:
                        return  # solo: nothing is admitted beside it

    # -- the iteration -------------------------------------------------------
    def step(self) -> bool:
        """One engine iteration: admit into free slots, advance every
        in-flight chunked prefill by one chunk (paged), then (if any
        slot is decoding) run the single jitted decode step for the
        whole pool and deliver/retire per-slot tokens. Returns True when
        any work happened.

        A ``PoolExhaustedError`` escaping the iteration (every in-loop
        exhaustion is normally absorbed by eviction/preemption — an
        escape means the reclaim logic is stuck) snapshots the flight
        recorder before propagating: the dump carries the pool/slot
        state that produced the wedge."""
        try:
            return self._step_impl()
        except PoolExhaustedError as e:
            # a pool-exhaustion escape IS an allocation failure: the dump
            # carries the OOM forensics payload (HBM ledger + top
            # temp-byte executables) on top of the usual state snapshot
            from ..observability import perf as _perf

            try:
                extra = {"error": repr(e), **_perf.oom_report()}
            except Exception:  # noqa: BLE001 — dump must not crash twice
                extra = {"error": repr(e)}
            _trace.flight_dump("pool_exhausted", extra=extra)
            raise

    def _step_impl(self) -> bool:
        with self._step_lock:
            self._last_progress_ts = time.perf_counter()
            self._admit()
            worked = False
            if self.paged:
                for slot in range(self.config.max_slots):
                    if self._jobs[slot] is None:
                        continue
                    worked = True
                    try:
                        self._advance_prefill(slot)
                    except PoolExhaustedError:
                        self._preempt(slot)  # retried from the queue front
                    except Exception as e:  # noqa: BLE001
                        self._free_slot(slot, RequestStatus.FAILED, "failed",
                                        error=repr(e))

            active = [i for i, r in enumerate(self._slot_req)
                      if r is not None and self._decoding[i]]
            # cancellation between steps: drop flagged slots without
            # paying another decode step for them
            for i in list(active):
                if self._slot_req[i].cancel_requested:
                    self._free_slot(i, RequestStatus.CANCELLED, "cancelled")
                    active.remove(i)
            if not active:
                self._update_occupancy_gauges()
                return worked

            if self.paged:
                # every active row writes this step's K/V at its current
                # length — or, speculatively, at its whole verify-bundle
                # window [len, len + spec_len): cross a block boundary
                # -> allocate; write into a shared (prefix-cached) block
                # -> COW fork. Allocation pressure preempts the
                # latest-admitted request, which can shrink `active`.
                bs = self.config.block_size
                for i in list(active):
                    if self._slot_req[i] is None or not self._decoding[i]:
                        continue  # preempted by an earlier row's reclaim
                    # _row_spec_len is a pure function of host state that
                    # does not change between here and the dispatch, so
                    # the bundle can never write past this coverage
                    m = self._row_spec_len(i) if self.spec else 1
                    first_bi = self._slot_len[i] // bs
                    last_bi = (self._slot_len[i] + m - 1) // bs
                    try:
                        for bi in range(first_bi, last_bi + 1):
                            if bi >= len(self._slot_blocks[i]):
                                nid = self._reclaim_alloc(1, i)[0]
                                self._slot_blocks[i].append(nid)
                                self._bt[i, bi] = nid
                            else:
                                self._ensure_writable(i, bi)
                    except PoolExhaustedError:
                        self._preempt(i)
                active = [i for i in active
                          if self._slot_req[i] is not None
                          and self._decoding[i]]
                if not active:
                    self._update_occupancy_gauges()
                    return True

            t0 = time.perf_counter()
            any_sampling = any(self._slot_sampling[i] for i in active)
            active_mask = np.zeros(self.config.max_slots, bool)
            active_mask[active] = True
            if self.spec:
                return self._spec_step(active, active_mask, any_sampling, t0)
            with _entrypoint("serving.step"):
                if self.paged:
                    bt_step = self._bt.copy()
                    bt_step[~active_mask] = 0  # inactive rows -> dump block
                    toks, self._pools, self._state = self._step_fn(
                        self._pb, self._pools, self._state,
                        jnp.asarray(bt_step), jnp.asarray(any_sampling),
                        jnp.asarray(active_mask))
                else:
                    toks, self._caches, self._state = self._step_fn(
                        self._pb, self._caches, self._state,
                        jnp.asarray(any_sampling), jnp.asarray(active_mask))
            toks_np = np.asarray(toks)  # the step's ONE device->host sync
            now = time.perf_counter()
            _sm.steps_total.inc()
            _sm.step_seconds.observe(now - t0)
            # the engine-lane step span reuses the timestamps already
            # taken for the histogram: zero extra clock reads on the
            # decode hot path
            _trace.complete("serving.step", "engine", "engine",
                            int(t0 * 1e9), int((now - t0) * 1e9),
                            {"active": len(active), "step": self._steps})
            self._steps += 1
            self._occupancy_integral += len(active)
            from ..observability import perf as _perf
            _perf.note_entry_items("serving.step", len(active))

            for i in active:
                req = self._slot_req[i]
                if self.paged:
                    self._slot_len[i] = min(self._slot_len[i] + 1,
                                            self.config.max_len - 1)
                t = int(toks_np[i])
                prev = req.last_token_ts
                req.push_token(t, now)
                _sm.tokens_total.labels("generated").inc()
                if prev is not None:
                    _sm.tpot_seconds.observe(now - prev)
                    _sm.tpot_summary.observe(now - prev)
                self._finish_or_keep(i, req, t, now)
            return True

    # -- the speculative iteration -------------------------------------------
    def _row_spec_len(self, slot: int) -> int:
        """Live bundle width for one decoding slot this round: 1 + the
        row's draft count, clamped by the request's own ``spec_k``
        (opt-out = 0 -> width 1 = a plain decode step riding the
        bundle), its remaining token budget (drafting past
        ``max_new_tokens`` is pure waste), and the slot's KV capacity
        (the bundle writes ``width`` positions through the table)."""
        req = self._slot_req[slot]
        p = req.params
        k_req = self._spec_k if p.spec_k is None \
            else max(0, min(int(p.spec_k), self._spec_k))
        remaining = p.max_new_tokens - len(req.output_tokens)
        room = self.config.max_len - self._slot_len[slot]
        if self._spec_tree is not None:
            # tree lane: k_req clamps the DEPTH; the bundle width is
            # the BFS node count of the clamped tree (an accepted path
            # emits at most depth+1 tokens, so depth caps at
            # remaining-1), then clips to the slot's KV room — any
            # BFS prefix is a valid (ragged) tree
            depth_cap = max(0, min(k_req, remaining - 1))
            width = int(self._tree["offsets"][depth_cap + 1])
            return max(1, min(width, room))
        return max(1, min(k_req + 1, remaining, room))

    def _spec_step(self, active, active_mask, any_sampling, t0: float) -> bool:
        """One speculative iteration for the whole pool: ONE jitted
        draft program (k draft-model forwards), ONE jitted verify
        (target scores the k+1-wide bundle through the paged kernel,
        accepts the longest matching prefix, bumps each row's position
        by its own accept length through the block tables). The draft
        program is skipped — host-side, no recompile — when no live row
        wants more than a plain step this round."""
        B = self.config.max_slots
        k = self._spec_k
        spec_valid = np.zeros(B, np.int32)
        for i in active:
            spec_valid[i] = self._row_spec_len(i)
        bt_step = self._bt.copy()
        bt_step[~active_mask] = 0
        bt_j = jnp.asarray(bt_step)
        sv_j = jnp.asarray(spec_valid)
        as_j = jnp.asarray(any_sampling)
        tree = self._spec_tree is not None
        need_draft = bool((spec_valid > 1).any())
        if need_draft:
            td0 = time.perf_counter()
            with _entrypoint("serving.spec_draft"):
                drafts, self._dpools = self._draft_fn(
                    self._dpb, self._dpools, self._state, bt_j, sv_j, as_j)
            td1 = time.perf_counter()
            _trace.complete("serving.spec_draft", "engine", "engine",
                            int(td0 * 1e9), int((td1 - td0) * 1e9),
                            {"active": len(active), "k": k,
                             **({"tree": list(self._spec_tree),
                                 "nodes": int(self._tree["nodes"])}
                                if tree else {})})
        else:
            drafts = self._zero_drafts
        tv0 = time.perf_counter()
        with _entrypoint("serving.spec_verify"):
            if tree:
                cand, n_emit, self._pools, self._dpools, self._state = \
                    self._verify_fn(
                        self._pb, self._pools, self._dpools, self._state,
                        bt_j, drafts, sv_j, as_j,
                        jnp.asarray(active_mask))
            else:
                cand, n_emit, self._pools, self._state = self._verify_fn(
                    self._pb, self._pools, self._state, bt_j, drafts,
                    sv_j, as_j, jnp.asarray(active_mask))
        cand_np = np.asarray(cand)   # the round's device->host sync
        n_np = np.asarray(n_emit)
        now = time.perf_counter()
        _sm.steps_total.inc()
        _sm.step_seconds.observe(now - t0)
        _trace.complete("serving.spec_verify", "engine", "engine",
                        int(tv0 * 1e9), int((now - tv0) * 1e9),
                        {"active": len(active), "step": self._steps,
                         **({"tree": list(self._spec_tree)}
                            if tree else {})})
        self._steps += 1
        self._occupancy_integral += len(active)
        self._spec_rounds += 1
        from ..observability import perf as _perf
        if need_draft:
            _perf.note_entry_items("serving.spec_draft",
                                   int((spec_valid - 1).clip(0).sum()))
        _perf.note_entry_items("serving.spec_verify",
                               int(n_np[active].sum()))

        for i in active:
            req = self._slot_req[i]
            n = int(n_np[i])
            drafted = int(spec_valid[i]) - 1
            accepted = n - 1
            if drafted > 0:
                self._spec_drafted += drafted
                self._spec_accepted += accepted
                req.spec_drafted += drafted
                req.spec_accepted += accepted
                _sm.spec_drafted_tokens.inc(drafted)
                _sm.spec_accepted_tokens.inc(accepted)
                _sm.spec_rejected_tokens.inc(drafted - accepted)
                _sm.spec_accept_len.observe(accepted)
                if tree:
                    # node accounting + the per-depth accept histogram
                    # (on the tree lane `accepted` IS the accepted path
                    # depth: one draft node per committed level)
                    _sm.spec_tree_nodes_drafted.inc(drafted)
                    _sm.spec_tree_nodes_accepted.inc(accepted)
                    _sm.spec_accept_depth.observe(accepted)
                self._accept_hist[accepted] += 1
                # accepted-k instant on the request's PR-7 trace lane
                req._tr_event("spec_accept", drafted=drafted,
                              accepted=accepted, emitted=n)
            self._slot_len[i] = min(self._slot_len[i] + n,
                                    self.config.max_len - 1)
            prev = req.last_token_ts
            interval = (now - prev) if prev is not None else None
            for j in range(n):
                t = int(cand_np[i, j])
                req.push_token(t, now)
                _sm.tokens_total.labels("generated").inc()
                if interval is not None:
                    # the round's wall time amortized over its tokens —
                    # the honest per-token cadence of a multi-token step
                    _sm.tpot_seconds.observe(interval / n)
                    _sm.tpot_summary.observe(interval / n)
                if self._finish_or_keep(i, req, t, now):
                    break
        return True

    def run_until_idle(self, max_steps: int = 1_000_000) -> int:
        """Drive ``step()`` until queue and slots are empty (the
        synchronous serving loop); returns iterations executed."""
        n = 0
        while n < max_steps and (self.scheduler.depth or self.busy_slots()):
            if not self.step():
                break
            n += 1
        # admission may have drained the queue into terminal states
        # without any decode work; one more pass clears stragglers
        self._admit()
        return n

    # -- background loop -----------------------------------------------------
    def start(self):
        """Run the serving loop on a daemon thread (the HTTP front end
        and ``Request.result()`` consumers use this mode)."""
        if self._stopped:
            raise EngineStoppedError(
                "stopped engines don't restart: the drain already "
                "refused new work — build a fresh engine (warmup() it "
                "before taking traffic)")
        with self._wake:
            if self._running:
                return self
            self._running = True
            self._thread = threading.Thread(
                target=self._serve_loop, name="paddle-tpu-serving", daemon=True)
            self._thread.start()
        return self

    def _serve_loop(self):
        # the per-request try in _admit guards prefill failures; anything
        # escaping step() itself (a poisoned pool program, OOM, a bug) is
        # fatal to the WHOLE pool — without this guard the thread died
        # silently and every result() caller hung forever
        try:
            while self._running:
                if not self.step():
                    with self._wake:
                        if self._running and not self.scheduler.depth \
                                and not self.busy_slots():
                            self._wake.wait(0.05)
        except BaseException as e:  # noqa: BLE001 — loop-level crash
            self._on_loop_crash(e)

    def _on_loop_crash(self, exc: BaseException):
        """Decode-loop death: fail EVERY running and queued request with
        the exception (so ``result()``/``stream()`` callers return
        instead of hanging), flip health to unhealthy, and count it."""
        err = repr(exc)
        with self._step_lock:
            self._crashed = err
            self._running = False
            _sm.engine_crashes_total.inc()
            _sm.engine_unhealthy.set(1)
            # post-mortem first, while the slot/queue state still shows
            # what the engine was doing when it died (the dump's state
            # provider reads stats() — before the requests are failed).
            # A death that looks like a device allocation failure gets
            # the OOM forensics dump instead: same flight recorder, but
            # the extra names the top temp-byte executable — the OOM
            # names its culprit instead of dying with an XLA backtrace.
            from ..observability import perf as _perf

            if _perf.is_oom_error(exc):
                _perf.dump_oom(exc)
            else:
                _trace.flight_dump("engine_crash", extra={"error": err})
            # supervised engines: the supervisor's capture hook runs
            # AFTER the post-mortem (the dump shows the true in-flight
            # state) and BEFORE _fail_inflight (finish() is idempotent
            # and irreversible — anything the hook does not detach is
            # failed below, exactly the unsupervised semantics)
            hook = self._crash_hook
            if hook is not None:
                try:
                    hook(self, exc)
                except Exception:  # noqa: BLE001 — the crash path must
                    pass           # survive a broken supervisor
            self._fail_inflight(f"engine loop crashed: {err}")
        with self._wake:
            self._wake.notify_all()

    def _fail_inflight(self, error: str):
        """Fail every running slot and queued request with ``error`` so
        their ``result()``/``stream()`` callers return instead of
        hanging (crash / abort / drain-timeout paths; caller holds the
        step lock)."""
        for slot in range(self.config.max_slots):
            if self._slot_req[slot] is not None:
                self._free_slot(slot, RequestStatus.FAILED, "failed",
                                error=error)
        while True:  # drain the queue; pop_ready finishes
            req = self.scheduler.pop_ready()  # cancelled/expired itself
            if req is None:
                break
            req.finish(RequestStatus.FAILED, error=error)
            _sm.requests_total.labels("failed").inc()
            self._outcomes["failed"] = self._outcomes.get("failed", 0) + 1

    def _export_inflight(self) -> tuple:
        """Detach every running and queued request WITHOUT finishing
        them — the supervised-restart capture (caller holds the step
        lock, normally from inside ``_crash_hook``). Returns
        ``(running, queued)`` in FCFS admission order. This engine is
        presumed dead: no pool bookkeeping happens (the pools die with
        the engine); only host-side request state is rebuilt, via the
        same ``_build_resume`` recipe preemption uses, so a FRESH
        engine resumes each running request bit-identically. Queued
        requests were never touched by the crashing step and carry no
        resume state at all. On a contiguous engine (no resume support
        in its prefill path) only fresh running requests are detached —
        ones with delivered tokens stay and fail as before rather than
        re-deliver duplicates."""
        running = []
        order = sorted(
            (slot for slot in range(self.config.max_slots)
             if self._slot_req[slot] is not None),
            key=lambda s: self._slot_seq[s])
        for slot in order:
            req = self._slot_req[slot]
            if not self.paged and req.output_tokens:
                continue  # contiguous decode cannot replay; fail it
            self._build_resume(slot)
            req.slot = None
            req._tr_end("prefill")
            req._tr_end("decode")
            req._tr_event("captured", slot=slot,
                          generated=len(req.output_tokens))
            self._slot_req[slot] = None
            self._decoding[slot] = False
            if self.paged:
                self._jobs[slot] = None
            running.append(req)
        return running, self.scheduler.detach_all()

    @property
    def crashed(self) -> Optional[str]:
        return self._crashed

    @property
    def healthy(self) -> bool:
        return self._crashed is None

    @property
    def draining(self) -> bool:
        return self._draining and not self._stopped

    @property
    def stopped(self) -> bool:
        return self._stopped

    @property
    def warmed_up(self) -> bool:
        return self._warmed_up

    def drain(self, timeout_s: Optional[float] = None) -> bool:
        """Stop admitting new requests and let the in-flight ones finish
        (the graceful half of ``stop()``; a router calls this before
        taking a replica out of rotation). ``submit()`` raises
        ``EngineDrainingError`` from the moment this is called. Returns
        True when every in-flight request reached a terminal state on
        its own; on ``timeout_s`` expiry the stragglers are FAILED with
        an explicit drain-timeout error (never silently dropped) and
        False is returned. Idempotent; a crashed engine is already
        drained (everything was failed by the crash path)."""
        with self._wake:
            self._draining = True
            self._wake.notify_all()
        deadline = (time.perf_counter() + timeout_s
                    if timeout_s is not None else None)
        while self.scheduler.depth or self.busy_slots():
            if self._crashed is not None:
                return False  # crash path failed everything already
            if deadline is not None and time.perf_counter() > deadline:
                with self._step_lock:
                    self._fail_inflight(
                        f"drain timed out after {timeout_s}s; request "
                        f"aborted at engine stop — retry on another "
                        f"replica")
                return False
            if self._thread is None:
                # sync engine (nobody runs the loop): drive it inline —
                # draining blocks submits, so the backlog is finite
                self.run_until_idle()
            else:
                time.sleep(0.005)
        return True

    def stop(self, abort: bool = False,
             drain_timeout_s: Optional[float] = 30.0):
        """Stop serving. DRAINS by default: new submits are refused
        (``EngineDrainingError`` now, ``EngineStoppedError`` once
        stopped), in-flight requests finish (or are explicitly FAILED
        at ``drain_timeout_s``), then the loop stops. ``abort=True``
        keeps the old fail-fast shutdown, minus its silent data loss:
        every queued and running request is FAILED immediately with an
        actionable error instead of being abandoned with ``result()``
        hanging forever."""
        with self._wake:
            self._draining = True
        if abort:
            with self._step_lock:
                self._fail_inflight(
                    "engine stopped (abort=True); request aborted "
                    "mid-flight — resubmit to another replica")
        elif self._crashed is None:
            self.drain(timeout_s=drain_timeout_s)
        if self.paged and self._crashed is None:
            # persist the prefix cache across the restart (disk tier)
            # BEFORE the terminal flip: the engine is drained, so the
            # pool blocks are stable under the step lock
            with self._step_lock:
                self._flush_tier()
        self._stopped = True
        self._running = False
        with self._wake:
            self._wake.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()
        return False

    # -- introspection -------------------------------------------------------
    @property
    def mean_occupancy(self) -> Optional[float]:
        if not self._steps:
            return None
        return self._occupancy_integral / (self._steps * self.config.max_slots)

    def spec_stats(self) -> dict:
        """Speculative-lane accounting for ``/stats`` and the flight
        recorder: engine-lifetime drafted/accepted/rejected totals, the
        pool-wide accept rate, and the accept-length digest."""
        if not self.spec:
            return {"enabled": False}
        count = sum(self._accept_hist)
        total = sum(i * n for i, n in enumerate(self._accept_hist))

        def _pct(p):
            # exact percentile over the engine's own rounds (the hist is
            # tiny: one bucket per accept length 0..k)
            target = p * count
            seen = 0
            for i, n in enumerate(self._accept_hist):
                seen += n
                if seen >= target:
                    return float(i)
            return float(len(self._accept_hist) - 1)

        out = {
            "enabled": True,
            "mode": "tree" if self._spec_tree is not None else "chain",
            "k": self._spec_k,
            "verify_kernel": self._spec_verify_kernel,
            "rounds": self._spec_rounds,
            "drafted_tokens": self._spec_drafted,
            "accepted_tokens": self._spec_accepted,
            "rejected_tokens": self._spec_drafted - self._spec_accepted,
            "accept_rate": (self._spec_accepted / self._spec_drafted
                            if self._spec_drafted else None),
            "queue_spec_opted_out": self.scheduler.depth_spec_opted_out(),
            "accept_len": {
                **({f"p{round(p * 100)}": _pct(p)
                    for p in (0.5, 0.95, 0.99)} if count else {}),
                "hist": list(self._accept_hist),
                "mean": (total / count) if count else None,
                "count": count},
        }
        if self._spec_tree is not None:
            # tree lane: the drafted/accepted totals above count NODES
            # (the whole flattened tree verifies; most siblings lose by
            # construction), so accept_rate is structurally low — the
            # per-round accepted PATH depth is the useful signal
            out["tree"] = {
                "factors": list(self._spec_tree),
                "depth": int(self._tree["depth"]),
                "nodes": int(self._tree["nodes"]),
                "drafted_nodes": self._spec_drafted,
                "accepted_nodes": self._spec_accepted,
                # +1: the root token always commits alongside the path
                "mean_accepted_path_len":
                    (total / count) + 1.0 if count else None,
            }
        return out

    def kv_block_stats(self) -> Optional[dict]:
        """Pool utilization + internal fragmentation (allocated token
        slots the slots' sequences do not fill) — paged mode only.
        Carries the quantization accounting: the storage format, bytes
        per cached token (values + scales, all layers), and the
        capacity multiplier vs a bf16 pool of the same HBM budget."""
        if not self.paged:
            return None
        from ..generation import kv_cache_bytes_per_token

        stats = self.pool.stats()
        bs = self.config.block_size
        frag = 0
        for slot in range(self.config.max_slots):
            if self._slot_req[slot] is None:
                continue
            used = self._jobs[slot].done if self._jobs[slot] is not None \
                else self._slot_len[slot]
            frag += len(self._slot_blocks[slot]) * bs - used
        stats["internal_fragmentation_tokens"] = frag
        stats["kv_format"] = self.config.kv_format
        stats["bytes_per_token"] = self._kv_bytes_per_token
        stats["effective_capacity_tokens"] = self.pool.usable_blocks * bs
        bf16 = kv_cache_bytes_per_token(self._mcfg, "bf16", self._dtype)
        stats["capacity_vs_bf16"] = round(
            bf16 / max(1, self._kv_bytes_per_token), 3)
        return stats

    def debug_requests(self) -> dict:
        """The live per-request state table (``GET /debug/requests``):
        every queued and running request plus the recent-finished tail,
        each as a ``Request.debug_row`` (+ slot-phase and KV-block
        accounting for running ones)."""
        queued = [r.debug_row() for r in self.scheduler.snapshot()]
        running = []
        for slot, r in enumerate(self._slot_req):
            if r is None:
                continue
            row = r.debug_row()
            if self.paged:
                job = self._jobs[slot]
                row["phase"] = "prefill" if job is not None else "decode"
                row["tokens_in_cache"] = (job.done if job is not None
                                          else self._slot_len[slot])
                row["kv_blocks"] = len(self._slot_blocks[slot])
            else:
                row["phase"] = "decode"
            running.append(row)
        recent = [r.debug_row() for r in list(self._recent)]
        return {"ts": time.time(), "queued": queued, "running": running,
                "recent": recent}

    def health(self) -> tuple:
        """``(http_status, payload)`` for ``/healthz`` — and the probe
        surface a router's health-gating reads. The 503 states are
        DISTINCT (a saturated replica used to be indistinguishable from
        a dead one):

        - ``ok`` (200): admitting traffic.
        - ``crashed`` (503): the decode loop died; every request was
          failed; only a fresh engine recovers. ``crashed`` carries the
          error repr.
        - ``draining`` (503): no new admissions, in-flight requests
          finishing (graceful shutdown in progress) — route elsewhere,
          don't retry here.
        - ``stopped`` (503): drain complete, loop down.
        - ``saturated`` (503): alive but the admission queue is full;
          ``retry_after_s`` (derived from the queue-wait digest's p50)
          says when a slot is likely to free — back off, don't eject.
        - ``stalled`` (503): the background loop has work pending but
          hasn't reached a step boundary for ``stall_timeout_s`` — a
          hung device dispatch; probes should treat it like a crash.
        """
        payload = {
            "ts": time.time(),
            "slots_busy": self.busy_slots(),
            "slots_total": self.config.max_slots,
            "queue_depth": self.scheduler.depth,
            "max_queue_depth": self.scheduler.max_queue_depth,
            "warmed_up": self._warmed_up,
            "crashed": self._crashed,
        }
        if self.paged:
            kv = self.kv_block_stats()
            payload["kv_blocks_in_use"] = kv["in_use"]
            payload["kv_blocks_total"] = kv["usable"]
            payload["kv_blocks_shared"] = kv["shared"]
            payload["kv_block_utilization"] = round(kv["utilization"], 4)
        if self._crashed is not None:
            payload["status"] = "crashed"
            return 503, payload
        if self._stopped:
            payload["status"] = "stopped"
            return 503, payload
        if self._draining:
            payload["status"] = "draining"
            payload["in_flight"] = (payload["slots_busy"]
                                    + payload["queue_depth"])
            return 503, payload
        stalled_s = time.perf_counter() - self._last_progress_ts
        if self._running and stalled_s > self.config.stall_timeout_s \
                and (payload["slots_busy"] or payload["queue_depth"]):
            payload["status"] = "stalled"
            payload["stalled_s"] = round(stalled_s, 3)
            return 503, payload
        if payload["queue_depth"] >= self.scheduler.max_queue_depth:
            payload["status"] = "saturated"
            payload["retry_after_s"] = _sm.queue_wait_retry_after()
            return 503, payload
        payload["status"] = "ok"
        return 200, payload

    def stats(self) -> dict:
        out = {
            "kv_mode": self.config.kv_mode,
            "slots": self.config.max_slots,
            "slots_busy": self.busy_slots(),
            "queue_depth": self.scheduler.depth,
            "max_len": self.config.max_len,
            "steps": self._steps,
            "mean_occupancy": self.mean_occupancy,
            "outcomes": dict(self._outcomes),
            "running": self._running,
            "healthy": self.healthy,
            "crashed": self._crashed,
            "draining": self.draining,
            "stopped": self._stopped,
            "warmed_up": self._warmed_up,
            "max_queue_depth": self.scheduler.max_queue_depth,
            "latency_digests": _sm.latency_digests(),
            "goodput_tokens_per_s": _sm.goodput_tokens_per_second.value(),
            "preemptions": self._preempt_count,
            "tp": self._tp,
        }
        # the performance ledger for this engine's executables: per-entry
        # flops/bytes/intensity/roofline + MFU when peaks are known (the
        # /stats block the acceptance criteria read)
        from ..observability import perf as _perf
        out["perf"] = {"ledger": _perf.ledger(prefix="serving."),
                       "peaks": _perf.peak_specs()}
        out["spec"] = self.spec_stats()
        if self.paged:
            out["block_size"] = self.config.block_size
            out["prefill_chunk"] = self.config.prefill_chunk
            out["kv_format"] = self.config.kv_format
            out["kv_blocks"] = self.kv_block_stats()
            out["prefix_cache"] = (self.prefix_cache.stats()
                                   if self.prefix_cache is not None else None)
            out["kv_tier"] = (self._tier.stats()
                              if self._tier is not None else None)
            out["requests"] = [
                {"request_id": r.id, "slot": slot,
                 "tokens_in_cache": (self._jobs[slot].done
                                     if self._jobs[slot] is not None
                                     else self._slot_len[slot]),
                 "kv_blocks": len(self._slot_blocks[slot]),
                 "phase": ("prefill" if self._jobs[slot] is not None
                           else "decode")}
                for slot, r in enumerate(self._slot_req) if r is not None]
        else:
            out["prefill_buckets"] = list(self._buckets)
        return out
