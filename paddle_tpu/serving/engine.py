"""Continuous-batching serving engine over slot-based static KV caches.

The TPU-native translation of iteration-level scheduling (Orca) +
paged/managed KV serving (vLLM), built on this repo's static-shape
decode substrate instead of paging:

- a fixed pool of ``max_slots`` decode SLOTS over pre-allocated
  [B, max_len, h, d] KV buffers (one pytree for the whole pool);
- admission prefills one request at a BUCKETED prompt length (a small
  set of padded-prefill executables — right-padded, plain causal mask:
  padded keys sit at positions the causal mask never exposes) and
  splices the per-layer [1, Lb, h, d] prefill cache into the slot with
  ``dynamic_update_slice``;
- decode drives ONE jitted step for the whole slot pool every
  iteration: per-slot positions ([B] vector — each slot at its own
  sequence offset), per-slot sampling params and PRNG keys carried as
  traced arrays so mixed greedy/sampled requests share the single step
  program. The step executable compiles exactly once and then runs at
  whatever occupancy admission sustains. Free slots ride along as
  garbage rows with their positions PINNED to 0 (a traced [B] active
  mask — occupancy patterns never retrace), so the flash-decode
  kernel's per-row length masking prices a dead slot at one KV block;
- slots free on EOS / max-tokens / cancellation / deadline and are
  refilled by the next iteration's admission pass.

Per-request outputs are bit-identical to ``generation.generate`` with
the same sampling seed/params: the slot key chain reproduces generate's
``key, sub = split(key)`` walk and ``select_tokens`` row-wise equals the
config-static ``_select_token`` (tests/test_serving.py holds this as an
oracle).

Observability: requests/tokens counters, queue-depth + slot-occupancy
gauges, TTFT/TPOT histograms (serving/metrics.py), and every compile is
attributed to the ``serving.step`` / ``serving.prefill[Lb]`` recompile-
monitor entries — a retrace on ``serving.step`` after warmup is a bug
and the monitor will flag it.
"""

from __future__ import annotations

import functools
import threading
import time
from dataclasses import dataclass
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..generation import (make_cached_runner, make_kv_caches, select_tokens,
                          split_keys)
from ..observability import recompile as _recompile
from ..observability.recompile import entrypoint as _entrypoint
from . import metrics as _sm
from .request import Request, RequestStatus, SamplingParams
from .scheduler import Scheduler

__all__ = ["ServingConfig", "ServingEngine"]


def _default_buckets(max_len: int) -> tuple:
    """Powers of two from 16 up to (and always including) max_len."""
    out = []
    b = 16
    while b < max_len:
        out.append(b)
        b *= 2
    out.append(max_len)
    return tuple(out)


@dataclass
class ServingConfig:
    """Engine knobs.

    - ``max_slots``: the decode batch B — slots in flight at once.
    - ``max_len``: per-slot KV capacity; every request needs
      prompt_len + max_new_tokens <= max_len.
    - ``prefill_buckets``: padded prompt lengths; each bucket costs one
      prefill + one splice compile, so keep the set small. Defaults to
      powers of two up to max_len.
    - ``max_queue_depth``: admission backpressure bound
      (``QueueFullError`` beyond it).
    - ``pad_token_id``: right-pad filler for bucketed prefill — any
      valid token id works (padded positions are causally invisible).
    """

    max_slots: int = 4
    max_len: int = 256
    prefill_buckets: Sequence[int] = ()
    max_queue_depth: int = 64
    pad_token_id: int = 0

    def buckets(self) -> tuple:
        bs = tuple(sorted({int(b) for b in self.prefill_buckets
                           if int(b) <= self.max_len}))
        if not bs:
            return _default_buckets(self.max_len)
        if bs[-1] != self.max_len:
            bs = bs + (self.max_len,)
        return bs


class ServingEngine:
    """Request-level serving over one decoder model (llama / gpt — any
    model speaking the generation.py static-cache protocol).

    Drive it synchronously (``submit`` + ``step``/``run_until_idle`` —
    deterministic, what the tests do) or as a background thread
    (``start``/``stop``; ``submit`` then wakes the loop and callers wait
    on ``Request.result()`` / iterate ``Request.stream()``).
    """

    def __init__(self, model, config: Optional[ServingConfig] = None, **overrides):
        if config is None:
            config = ServingConfig(**overrides)
        elif overrides:
            raise ValueError("pass ServingConfig OR keyword overrides, not both")
        self.config = config
        self.model = model
        mcfg = model.config
        if config.max_len > mcfg.max_position_embeddings:
            raise ValueError(
                f"max_len ({config.max_len}) exceeds the model's "
                f"max_position_embeddings ({mcfg.max_position_embeddings})")
        self._buckets = config.buckets()
        # this engine's step/prefill closures are NEW executables — their
        # first compiles are warmup, not retraces of a previous engine's
        _recompile.reset_warmup(
            "serving.step", *(f"serving.prefill[{b}]" for b in self._buckets))
        B = int(config.max_slots)
        self.scheduler = Scheduler(config.max_queue_depth)

        self._dtype = next(iter(model.parameters()))._data.dtype
        params = {k: v._data for k, v in model.named_parameters_dict().items()}
        buffers = {k: v._data for k, v in model.named_buffers_dict().items()}
        self._pb = {**params, **buffers}
        self._mcfg = mcfg

        # slot pool state. The KV pool AND the per-slot decode state
        # (last token, position, PRNG chain, sampling params) live on
        # DEVICE across steps — the decode loop transfers ONE [B] token
        # vector per iteration and nothing else; admission updates a
        # slot's state rows inside the (jitted) splice program.
        self._caches = make_kv_caches(mcfg, B, config.max_len, self._dtype)
        self._state = {
            "tokens": jnp.zeros(B, jnp.int32),     # last token per slot
            "pos": jnp.zeros(B, jnp.int32),        # next cache write index
            "keys": jnp.zeros((B, 2), jnp.uint32),  # per-slot PRNG chain
            "ds": jnp.zeros(B, bool),
            "temp": jnp.ones(B, jnp.float32),
            "tk": jnp.zeros(B, jnp.int32),
            "tp": jnp.ones(B, jnp.float32),
        }
        self._slot_req: List[Optional[Request]] = [None] * B
        self._slot_sampling = [False] * B  # host mirror for the step cond

        self._steps = 0
        self._occupancy_integral = 0
        self._outcomes = {}
        self._step_lock = threading.RLock()
        self._wake = threading.Condition()
        self._running = False
        self._thread: Optional[threading.Thread] = None
        self._crashed: Optional[str] = None  # repr of the fatal loop error
        _sm.engine_unhealthy.set(0)  # a fresh engine is the healthy one

        run = make_cached_runner(model)

        @jax.jit
        def _prefill(pb, ids, last_idx, key, do_sample, temp, top_k, top_p):
            """Bucketed prefill: one forward over the right-padded
            prompt into fresh [1, Lb] caches, then the FIRST token
            select with generate's exact key chain
            (key, sub = split(key); select(last_logits, sub))."""
            Lb = ids.shape[1]
            caches = make_kv_caches(mcfg, 1, Lb, self._dtype)
            logits, caches = run(pb, ids, caches, 0)
            last = jax.lax.dynamic_slice_in_dim(logits, last_idx, 1, axis=1)[:, 0]
            key, sub = jax.random.split(key)
            token = jax.lax.cond(
                do_sample[0],
                lambda: select_tokens(last, sub[None], do_sample, temp,
                                      top_k, top_p),
                lambda: jnp.argmax(last, axis=-1).astype(jnp.int32))
            return token, key, caches

        @functools.partial(jax.jit, donate_argnums=(0, 1))
        def _splice(caches, state, pcaches, slot, token, pos0, key,
                    ds, temp, tk, tp):
            """Admission: copy a prefilled [1, Lb, h, d] cache into slot
            ``slot`` of the pool (rows [slot, 0:Lb]) via
            ``dynamic_update_slice`` AND set that slot's rows of the
            device-resident decode state — one dispatch, no recompile,
            nothing round-trips through the host."""
            out = []
            for c, p in zip(caches, pcaches):
                out.append({
                    "k": jax.lax.dynamic_update_slice(
                        c["k"], p["k"].astype(c["k"].dtype), (slot, 0, 0, 0)),
                    "v": jax.lax.dynamic_update_slice(
                        c["v"], p["v"].astype(c["v"].dtype), (slot, 0, 0, 0)),
                })
            state = dict(state)
            state["tokens"] = state["tokens"].at[slot].set(token)
            state["pos"] = state["pos"].at[slot].set(pos0)
            state["keys"] = state["keys"].at[slot].set(key)
            state["ds"] = state["ds"].at[slot].set(ds)
            state["temp"] = state["temp"].at[slot].set(temp)
            state["tk"] = state["tk"].at[slot].set(tk)
            state["tp"] = state["tp"].at[slot].set(tp)
            return out, state

        @functools.partial(jax.jit, donate_argnums=(1, 2))
        def _step(pb, caches, state, any_sampling, active):
            """ONE decode iteration for the whole slot pool: per-slot
            positions (vector ``state["pos"]``) drive per-row RoPE/
            cache-write/mask; per-slot params + keys drive the batched
            sampler. Compiles once — every shape here is fixed by the
            pool (``active`` is a traced [B] bool, so occupancy patterns
            never retrace). When NO active slot samples (``any_sampling``,
            a host-tracked traced scalar — stale params on freed slots
            can't force the branch), a runtime ``lax.cond`` skips the
            sampling branch (its full-vocab sort is the most expensive
            op in the step) for a pure-argmax step — exact, since
            ``select_tokens`` is row-wise greedy for ds=False rows.
            Free slots keep decoding garbage rows; their tokens are
            never delivered and admission resets their state. Their
            positions are PINNED to 0 (not advanced), so the per-row
            length masking in the flash-decode kernel prices a dead slot
            at one KV block — a mostly-empty pool costs proportional to
            occupancy, not max_len."""
            logits, caches = run(pb, state["tokens"][:, None], caches,
                                 state["pos"])
            last = logits[:, 0]
            new_keys, subs = split_keys(state["keys"])
            nxt = jax.lax.cond(
                any_sampling,
                lambda: select_tokens(last, subs, state["ds"], state["temp"],
                                      state["tk"], state["tp"]),
                lambda: jnp.argmax(last, axis=-1).astype(jnp.int32))
            state = dict(state)
            state["tokens"] = nxt
            # active rows advance (clamped so late cache writes stay in
            # bounds); free rows pin at 0 until admission resets them
            state["pos"] = jnp.where(
                active,
                jnp.minimum(state["pos"] + 1, jnp.int32(config.max_len - 1)),
                jnp.int32(0))
            state["keys"] = new_keys
            return nxt, caches, state

        self._prefill_fn = _prefill
        self._splice_fn = _splice
        self._step_fn = _step

    # -- submission ----------------------------------------------------------
    def submit(self, prompt, deadline_s: Optional[float] = None,
               on_token=None, params: Optional[SamplingParams] = None,
               **sampling) -> Request:
        """Enqueue one request; returns its handle immediately.

        ``prompt`` is a 1-D sequence of token ids; ``sampling`` takes
        the ``SamplingParams`` fields (``max_new_tokens``, ``do_sample``,
        ``temperature``, ``top_k``, ``top_p``, ``eos_token_id``,
        ``seed``), or pass a prebuilt ``params``. Raises ``ValueError``
        for requests that cannot fit a slot and ``QueueFullError`` under
        backpressure."""
        if self._crashed is not None:
            raise RuntimeError(
                f"serving engine has crashed ({self._crashed}); create a "
                f"fresh engine — this one's decode state is gone")
        if params is None:
            params = SamplingParams(**sampling)
        elif sampling:
            raise ValueError("pass params OR sampling kwargs, not both")
        prompt = np.asarray(prompt, dtype=np.int32).reshape(-1)
        L = int(prompt.shape[0])
        if L < 1:
            raise ValueError("empty prompt")
        if params.max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if L + params.max_new_tokens > self.config.max_len:
            raise ValueError(
                f"prompt ({L}) + max_new_tokens ({params.max_new_tokens}) "
                f"exceeds the slot KV capacity max_len="
                f"{self.config.max_len}")
        req = Request(prompt, params, deadline_s=deadline_s, on_token=on_token)
        self.scheduler.submit(req)  # may raise QueueFullError
        with self._wake:
            self._wake.notify_all()
        return req

    def cancel(self, req: Request) -> bool:
        return self.scheduler.cancel(req)

    # -- slot bookkeeping ----------------------------------------------------
    def _bucket(self, L: int) -> int:
        for b in self._buckets:
            if b >= L:
                return b
        raise ValueError(f"prompt length {L} exceeds max bucket "
                         f"{self._buckets[-1]}")

    def busy_slots(self) -> int:
        return sum(r is not None for r in self._slot_req)

    def _update_occupancy_gauges(self):
        busy = self.busy_slots()
        _sm.slots_busy.set(busy)
        _sm.slot_occupancy.set(busy / max(1, self.config.max_slots))

    def _free_slot(self, slot: int, status: str, outcome: str,
                   error: Optional[str] = None):
        req = self._slot_req[slot]
        self._slot_req[slot] = None
        self._slot_sampling[slot] = False
        if req is not None:
            req.finish(status, error=error)
            _sm.requests_total.labels(outcome).inc()
            self._outcomes[outcome] = self._outcomes.get(outcome, 0) + 1
        self._update_occupancy_gauges()

    def _finish_or_keep(self, slot: int, req: Request, token: int,
                        now: float) -> bool:
        """Terminal checks after a delivered token; True when freed."""
        p = req.params
        if req.cancel_requested:
            self._free_slot(slot, RequestStatus.CANCELLED, "cancelled")
            return True
        if req.deadline_ts is not None and now > req.deadline_ts:
            self._free_slot(slot, RequestStatus.EXPIRED, "expired",
                            error="deadline passed during decode")
            return True
        if p.eos_token_id is not None and token == p.eos_token_id:
            self._free_slot(slot, RequestStatus.COMPLETED, "completed")
            return True
        if len(req.output_tokens) >= p.max_new_tokens:
            self._free_slot(slot, RequestStatus.COMPLETED, "completed")
            return True
        return False

    # -- admission / prefill -------------------------------------------------
    def _prefill_into_slot(self, req: Request, slot: int):
        p = req.params
        L = int(req.prompt.shape[0])
        Lb = self._bucket(L)
        ids = np.full((1, Lb), self.config.pad_token_id, np.int32)
        ids[0, :L] = req.prompt
        t0 = time.perf_counter()
        with _entrypoint(f"serving.prefill[{Lb}]"):
            token, key, pcaches = self._prefill_fn(
                self._pb, jnp.asarray(ids), jnp.asarray(L - 1, jnp.int32),
                jax.random.PRNGKey(p.seed),
                jnp.asarray([p.do_sample]),
                jnp.asarray([p.temperature], jnp.float32),
                jnp.asarray([p.top_k], jnp.int32),
                jnp.asarray([p.top_p], jnp.float32))
            # prefill outputs stay on device: the splice wires them into
            # the pool caches + the slot's decode-state rows directly
            self._caches, self._state = self._splice_fn(
                self._caches, self._state, pcaches,
                jnp.asarray(slot, jnp.int32), token[0],
                jnp.asarray(L, jnp.int32), key,
                jnp.asarray(p.do_sample),
                jnp.asarray(p.temperature, jnp.float32),
                jnp.asarray(p.top_k, jnp.int32),
                jnp.asarray(p.top_p, jnp.float32))
        tok0 = int(np.asarray(token)[0])
        now = time.perf_counter()
        _sm.prefill_seconds.observe(now - t0)
        _sm.tokens_total.labels("prompt").inc(L)
        _sm.tokens_total.labels("generated").inc()

        self._slot_req[slot] = req
        self._slot_sampling[slot] = bool(p.do_sample)
        req.slot = slot
        req.status = RequestStatus.RUNNING
        req.prefill_done_ts = now

        req.push_token(tok0, now)
        _sm.ttft_seconds.observe(req.ttft_s)
        self._finish_or_keep(slot, req, tok0, now)
        self._update_occupancy_gauges()

    def _admit(self):
        """Fill every free slot FCFS from the queue (prefill + splice);
        runs at the top of each iteration so a slot freed by EOS is
        refilled before the next decode step."""
        for slot in range(self.config.max_slots):
            while self._slot_req[slot] is None:
                req = self.scheduler.pop_ready()
                if req is None:
                    return
                try:
                    self._prefill_into_slot(req, slot)
                except Exception as e:  # noqa: BLE001 — engine must survive
                    self._slot_req[slot] = None
                    req.finish(RequestStatus.FAILED, error=repr(e))
                    _sm.requests_total.labels("failed").inc()
                    self._outcomes["failed"] = self._outcomes.get("failed", 0) + 1

    # -- the iteration -------------------------------------------------------
    def step(self) -> bool:
        """One engine iteration: admit into free slots, then (if any
        slot is busy) run the single jitted decode step for the whole
        pool and deliver/retire per-slot tokens. Returns True when any
        work happened."""
        with self._step_lock:
            self._admit()
            active = [i for i, r in enumerate(self._slot_req) if r is not None]
            # cancellation between steps: drop flagged slots without
            # paying another decode step for them
            for i in list(active):
                if self._slot_req[i].cancel_requested:
                    self._free_slot(i, RequestStatus.CANCELLED, "cancelled")
                    active.remove(i)
            if not active:
                self._update_occupancy_gauges()
                return False

            t0 = time.perf_counter()
            any_sampling = any(self._slot_sampling[i] for i in active)
            active_mask = np.zeros(self.config.max_slots, bool)
            active_mask[active] = True
            with _entrypoint("serving.step"):
                toks, self._caches, self._state = self._step_fn(
                    self._pb, self._caches, self._state,
                    jnp.asarray(any_sampling), jnp.asarray(active_mask))
            toks_np = np.asarray(toks)  # the step's ONE device->host sync
            now = time.perf_counter()
            _sm.steps_total.inc()
            _sm.step_seconds.observe(now - t0)
            self._steps += 1
            self._occupancy_integral += len(active)

            for i in active:
                req = self._slot_req[i]
                t = int(toks_np[i])
                prev = req.last_token_ts
                req.push_token(t, now)
                _sm.tokens_total.labels("generated").inc()
                if prev is not None:
                    _sm.tpot_seconds.observe(now - prev)
                self._finish_or_keep(i, req, t, now)
            return True

    def run_until_idle(self, max_steps: int = 1_000_000) -> int:
        """Drive ``step()`` until queue and slots are empty (the
        synchronous serving loop); returns iterations executed."""
        n = 0
        while n < max_steps and (self.scheduler.depth or self.busy_slots()):
            if not self.step():
                break
            n += 1
        # admission may have drained the queue into terminal states
        # without any decode work; one more pass clears stragglers
        self._admit()
        return n

    # -- background loop -----------------------------------------------------
    def start(self):
        """Run the serving loop on a daemon thread (the HTTP front end
        and ``Request.result()`` consumers use this mode)."""
        with self._wake:
            if self._running:
                return self
            self._running = True
            self._thread = threading.Thread(
                target=self._serve_loop, name="paddle-tpu-serving", daemon=True)
            self._thread.start()
        return self

    def _serve_loop(self):
        # the per-request try in _admit guards prefill failures; anything
        # escaping step() itself (a poisoned pool program, OOM, a bug) is
        # fatal to the WHOLE pool — without this guard the thread died
        # silently and every result() caller hung forever
        try:
            while self._running:
                if not self.step():
                    with self._wake:
                        if self._running and not self.scheduler.depth \
                                and not self.busy_slots():
                            self._wake.wait(0.05)
        except BaseException as e:  # noqa: BLE001 — loop-level crash
            self._on_loop_crash(e)

    def _on_loop_crash(self, exc: BaseException):
        """Decode-loop death: fail EVERY running and queued request with
        the exception (so ``result()``/``stream()`` callers return
        instead of hanging), flip health to unhealthy, and count it."""
        err = repr(exc)
        with self._step_lock:
            self._crashed = err
            self._running = False
            _sm.engine_crashes_total.inc()
            _sm.engine_unhealthy.set(1)
            for slot in range(self.config.max_slots):
                if self._slot_req[slot] is not None:
                    self._free_slot(slot, RequestStatus.FAILED, "failed",
                                    error=f"engine loop crashed: {err}")
            while True:  # drain the queue; pop_ready finishes
                req = self.scheduler.pop_ready()  # cancelled/expired itself
                if req is None:
                    break
                req.finish(RequestStatus.FAILED,
                           error=f"engine loop crashed: {err}")
                _sm.requests_total.labels("failed").inc()
                self._outcomes["failed"] = self._outcomes.get("failed", 0) + 1
        with self._wake:
            self._wake.notify_all()

    @property
    def crashed(self) -> Optional[str]:
        return self._crashed

    @property
    def healthy(self) -> bool:
        return self._crashed is None

    def stop(self):
        self._running = False
        with self._wake:
            self._wake.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()
        return False

    # -- introspection -------------------------------------------------------
    @property
    def mean_occupancy(self) -> Optional[float]:
        if not self._steps:
            return None
        return self._occupancy_integral / (self._steps * self.config.max_slots)

    def stats(self) -> dict:
        return {
            "slots": self.config.max_slots,
            "slots_busy": self.busy_slots(),
            "queue_depth": self.scheduler.depth,
            "max_len": self.config.max_len,
            "prefill_buckets": list(self._buckets),
            "steps": self._steps,
            "mean_occupancy": self.mean_occupancy,
            "outcomes": dict(self._outcomes),
            "running": self._running,
            "healthy": self.healthy,
            "crashed": self._crashed,
        }
