"""Hierarchical KV cache tiers below the device block pool: a host-RAM
block tier and a crash-safe persistent (disk) prefix store.

HBM used to be the ONLY KV tier, so pool pressure meant preemption-by-
recompute and every ``PrefixCache`` eviction threw shared work away.
CachedAttention (USENIX ATC '24) and Mooncake (Qin et al. 2024) show
that a host/disk hierarchy turns multi-turn and many-tenant workloads
from recompute-bound into transfer-bound — the trade this module costs
out with the perf ledger's measured prefill rate:

- **Host tier** (``KVTier``): an LRU map of demoted KV blocks in host
  RAM, keyed by the SAME exact-token-prefix bytes ``PrefixCache`` uses
  (``prompt[:end].tobytes()`` — so a hit guarantees the block's tokens
  AND its entire left context match). Cold blocks arrive from the
  engine's demote paths (prefix-cache eviction victims, preempted
  requests' private blocks, drain-time flush); a returning prefix
  *re-admits* via one jitted host→HBM block splice instead of a prefill
  chunk. Payloads are the block's raw pool rows at quantized width —
  for int8/fp8 pools the narrow values AND their f32 scale companions
  ride together, and for spec engines the draft model's rows do too —
  so a device→host→device round trip is bit-exact and re-admission
  preserves output parity.
- **Cost model** (``TierCostModel``): demote-vs-drop and
  readmit-vs-recompute decided from recompute-tokens × the ledger's
  measured prefill tokens/s vs transfer bytes / host-link bandwidth.
  Until the ledger has a measured rate the model defaults to
  demote/readmit (block-granularity transfers are orders of magnitude
  cheaper than recompute on every measured configuration — the
  CachedAttention finding), but the decision is recounted once real
  rates land.
- **Disk tier** (``DiskPrefixStore``): host-LRU spill victims and the
  drain-time flush persist under ``kv_tier_path`` using the checkpoint
  atomic-commit machinery (``distributed/checkpoint/atomic.py``):
  every entry is written to a ``.tmp-*`` scratch dir, fsynced, given a
  sha256-digest ``COMMITTED`` marker, and ``os.replace``-renamed into
  place — a kill at ANY byte of a spill leaves only an ignorable
  orphan, never a half-visible entry. Restart scans re-admit ONLY
  committed entries; digest mismatches and foreign configurations are
  skipped with a counted warning.

The module is host-side only (numpy + files): the ENGINE owns the two
jitted device programs (``serving.kv_demote`` extract /
``serving.kv_splice`` re-admit) and calls down with materialized
payloads, which keeps this state machine unit-testable without a
device and keeps the one-compile invariant where it is enforced.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import warnings
from collections import OrderedDict
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from ..distributed.checkpoint import atomic as _atomic
from . import metrics as _sm

__all__ = ["KVTier", "TierCostModel", "DiskPrefixStore",
           "payload_nbytes"]


def payload_nbytes(payload: Dict[str, np.ndarray]) -> int:
    """Host bytes one demoted block costs (values + quant scales +
    draft-model rows — everything that must move to re-admit it)."""
    return int(sum(a.nbytes for a in payload.values()))


def _resolve_dtype(name: str) -> np.dtype:
    """``np.dtype`` from its persisted name, including the ml_dtypes
    extension types numpy can't parse (``bfloat16``, ``float8_*``)."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes  # ships with jax

        return np.dtype(getattr(ml_dtypes, name))


class TierCostModel:
    """Demote-vs-drop / readmit-vs-recompute from measured rates.

    Recomputing ``t`` tokens costs ``t / prefill_rate`` seconds (the
    perf ledger's measured ``serving.prefill_chunk`` items/s); moving
    ``b`` bytes over the host link costs ``b / bandwidth``. A tier
    operation is worth it when the transfer (scaled by ``safety``, the
    dispatch-overhead fudge) beats the recompute it saves. Before any
    rate is measured the model says yes — at block granularity the
    transfer is ~100x cheaper than recompute on every configuration we
    measured, so the conservative default is to keep the work.
    """

    def __init__(self, host_gbps: float = 12.0, safety: float = 1.5,
                 prefill_rate_fn: Optional[Callable[[], Optional[float]]]
                 = None):
        if host_gbps <= 0:
            raise ValueError(f"host_gbps must be > 0, got {host_gbps}")
        if safety <= 0:
            raise ValueError(f"safety must be > 0, got {safety}")
        self.host_bytes_per_s = float(host_gbps) * 1e9
        self.safety = float(safety)
        self._prefill_rate_fn = prefill_rate_fn
        self.decisions = {"demote": 0, "drop": 0, "readmit": 0,
                          "recompute": 0}

    def prefill_tokens_per_s(self) -> Optional[float]:
        if self._prefill_rate_fn is None:
            return None
        try:
            rate = self._prefill_rate_fn()
        except Exception:  # noqa: BLE001 — a ledger hiccup never decides
            return None
        return float(rate) if rate and rate > 0 else None

    def transfer_s(self, n_bytes: int) -> float:
        return n_bytes / self.host_bytes_per_s

    def recompute_s(self, tokens: int) -> Optional[float]:
        rate = self.prefill_tokens_per_s()
        return tokens / rate if rate else None

    def _worth_it(self, tokens: int, n_bytes: int) -> bool:
        recompute = self.recompute_s(tokens)
        if recompute is None:
            return True  # unmeasured: keep the work (see class doc)
        return self.transfer_s(n_bytes) * self.safety < recompute

    def should_demote(self, tokens: int, n_bytes: int) -> bool:
        ok = self._worth_it(tokens, n_bytes)
        self.decisions["demote" if ok else "drop"] += 1
        return ok

    def should_readmit(self, tokens: int, n_bytes: int) -> bool:
        ok = self._worth_it(tokens, n_bytes)
        self.decisions["readmit" if ok else "recompute"] += 1
        return ok

    def snapshot(self) -> dict:
        return {"host_gbps": self.host_bytes_per_s / 1e9,
                "safety": self.safety,
                "prefill_tokens_per_s": self.prefill_tokens_per_s(),
                "decisions": dict(self.decisions)}


class DiskPrefixStore:
    """Crash-safe persistent prefix entries under one directory.

    One committed subdirectory per entry (``e_<sha256(key)[:32]>``)
    holding ``key.bin`` (the exact prefix-key bytes), ``meta.json``
    (covered end, array specs, and the engine configuration
    fingerprint), and one raw ``a<i>.bin`` per payload array. Writes go
    through :func:`atomic.atomic_write` — digest marker + fsync +
    atomic rename — so a SIGKILL at any stage of a spill leaves only a
    ``.tmp-*`` orphan the startup sweep deletes. Reads deep-verify the
    digests and skip (with a counted warning) anything corrupt,
    uncommitted, or written by a different engine configuration.
    """

    # pt-analysis lock discipline: the in-memory index and tallies are
    # only touched under self._lock; the filesystem protocol itself is
    # process-atomic (commit = one rename)
    GUARDED_BY = {
        "_index": "_lock",
        "loads": "_lock",
        "spills": "_lock",
        "corrupt_skipped": "_lock",
        "incompatible_skipped": "_lock",
    }

    def __init__(self, path: str, fingerprint: dict):
        self.path = os.path.abspath(path)
        self.fingerprint = dict(fingerprint)
        os.makedirs(self.path, exist_ok=True)
        self._lock = threading.Lock()
        # key bytes -> (covered_end, entry dir name); committed-only
        self._index: Dict[bytes, Tuple[int, str]] = {}
        self.loads = 0
        self.spills = 0
        self.corrupt_skipped = 0
        self.incompatible_skipped = 0
        _atomic.cleanup_stale_tmp(self.path)
        self._scan()

    @staticmethod
    def _entry_dir(key: bytes) -> str:
        return "e_" + hashlib.sha256(key).hexdigest()[:32]

    def _scan(self):
        """Build the index from COMMITTED entries only: a dir without a
        valid marker (kill mid-spill) or with a foreign fingerprint is
        skipped — counted, warned, never trusted."""
        with self._lock:
            for name in sorted(os.listdir(self.path)):
                if not name.startswith("e_") or ".tmp-" in name \
                        or ".old-" in name:
                    continue
                p = os.path.join(self.path, name)
                if not os.path.isdir(p):
                    continue
                try:
                    _atomic.read_marker(p)  # committed? (deep at load)
                    with open(os.path.join(p, "meta.json")) as fh:
                        meta = json.load(fh)
                    if meta.get("fingerprint") != self.fingerprint:
                        self.incompatible_skipped += 1
                        _sm.kv_tier_disk_skipped.labels(
                            "incompatible").inc()
                        continue
                    with open(os.path.join(p, "key.bin"), "rb") as fh:
                        key = fh.read()
                    self._index[key] = (int(meta["end"]), name)
                except (_atomic.CheckpointCorruptError, OSError,
                        ValueError, KeyError) as e:
                    self.corrupt_skipped += 1
                    _sm.kv_tier_disk_skipped.labels("corrupt").inc()
                    warnings.warn(
                        f"kv_tier: skipping uncommitted/corrupt spill "
                        f"entry {p!r}: {e}")
            _sm.kv_tier_disk_entries.set(len(self._index))

    def __len__(self) -> int:
        with self._lock:
            return len(self._index)

    def end_for(self, key: bytes) -> Optional[int]:
        with self._lock:
            ent = self._index.get(key)
            return ent[0] if ent is not None else None

    def put(self, key: bytes, end: int,
            payload: Dict[str, np.ndarray]) -> bool:
        """Atomically persist one entry; idempotent (an already-
        committed key is left alone — its content is identical by the
        exact-prefix keying). Returns True when a commit happened."""
        with self._lock:
            if key in self._index:
                return False
        final = os.path.join(self.path, self._entry_dir(key))
        names = sorted(payload.keys())
        meta = {"end": int(end), "fingerprint": self.fingerprint,
                "arrays": [{"name": n, "file": f"a{i}.bin",
                            "dtype": str(payload[n].dtype.name),
                            "shape": list(payload[n].shape)}
                           for i, n in enumerate(names)]}
        with _atomic.atomic_write(final, extra_marker={"end": int(end)}) \
                as tmp:
            with open(os.path.join(tmp, "key.bin"), "wb") as fh:
                fh.write(key)
            with open(os.path.join(tmp, "meta.json"), "w") as fh:
                json.dump(meta, fh, indent=1)
            for i, n in enumerate(names):
                with open(os.path.join(tmp, f"a{i}.bin"), "wb") as fh:
                    fh.write(np.ascontiguousarray(payload[n]).tobytes())
        with self._lock:
            self._index[key] = (int(end), self._entry_dir(key))
            self.spills += 1
            _sm.kv_tier_spills.inc()
            _sm.kv_tier_disk_entries.set(len(self._index))
        return True

    def get(self, key: bytes) \
            -> Optional[Tuple[int, Dict[str, np.ndarray]]]:
        """Load one committed entry (deep digest verification). A
        corrupt entry is dropped from the index with a counted warning
        and None is returned — the caller falls back to recompute."""
        with self._lock:
            ent = self._index.get(key)
        if ent is None:
            return None
        end, name = ent
        p = os.path.join(self.path, name)
        try:
            _atomic.verify_checkpoint(p, deep=True)
            with open(os.path.join(p, "meta.json")) as fh:
                meta = json.load(fh)
            with open(os.path.join(p, "key.bin"), "rb") as fh:
                if fh.read() != key:
                    raise ValueError("key bytes mismatch (hash collision "
                                     "or foreign entry)")
            payload: Dict[str, np.ndarray] = {}
            for spec in meta["arrays"]:
                with open(os.path.join(p, spec["file"]), "rb") as fh:
                    buf = fh.read()
                arr = np.frombuffer(buf, dtype=_resolve_dtype(
                    spec["dtype"])).reshape(spec["shape"])
                payload[spec["name"]] = arr
            with self._lock:
                self.loads += 1
                _sm.kv_tier_disk_loads.inc()
            return int(meta["end"]), payload
        except (_atomic.CheckpointCorruptError, OSError, ValueError,
                KeyError) as e:
            with self._lock:
                self._index.pop(key, None)
                self.corrupt_skipped += 1
                _sm.kv_tier_disk_skipped.labels("corrupt").inc()
                _sm.kv_tier_disk_entries.set(len(self._index))
            warnings.warn(
                f"kv_tier: corrupt spill entry {p!r} skipped "
                f"(falling back to prefill recompute): {e}")
            return None

    def stats(self) -> dict:
        with self._lock:
            return {"path": self.path, "entries": len(self._index),
                    "spills": self.spills, "loads": self.loads,
                    "corrupt_skipped": self.corrupt_skipped,
                    "incompatible_skipped": self.incompatible_skipped}


class KVTier:
    """The host-RAM block tier + its disk spill, one state machine.

    Entries are ``key -> (covered_end, payload)`` in LRU order, at most
    ``host_blocks`` of them resident (each entry is exactly one KV
    block). Inserts beyond capacity evict the LRU entry: it spills to
    the disk store when one is configured and the cost model approves,
    else it is dropped. Lookups promote disk hits back into the host
    map so a hot prefix pays the file read once.

    Key space is ``PrefixCache``'s: the int32 token prefix's raw bytes,
    so tier hits compose with (and extend past) prefix-cache hits
    during admission without any translation.
    """

    GUARDED_BY = {
        "_host": "_lock",
        "_host_bytes": "_lock",
        "demoted": "_lock",
        "dropped": "_lock",
        "readmitted_blocks": "_lock",
        "readmitted_tokens": "_lock",
    }

    def __init__(self, *, host_blocks: int, block_size: int,
                 cost: TierCostModel,
                 disk: Optional[DiskPrefixStore] = None):
        if host_blocks < 1:
            raise ValueError(f"host_blocks must be >= 1, got {host_blocks}")
        self.host_blocks = int(host_blocks)
        self.block_size = int(block_size)
        self.cost = cost
        self.disk = disk
        self._lock = threading.Lock()
        # key -> (end, payload); ordered for LRU (oldest first)
        self._host: "OrderedDict[bytes, Tuple[int, Dict[str, np.ndarray]]]" \
            = OrderedDict()
        self._host_bytes = 0
        self.demoted = 0
        self.dropped = 0
        self.readmitted_blocks = 0
        self.readmitted_tokens = 0

    @staticmethod
    def key_of(tokens: np.ndarray, end: int) -> bytes:
        """The shared prefix-key convention (``PrefixCache._key``)."""
        return np.ascontiguousarray(tokens[:end], dtype=np.int32).tobytes()

    def tokens_in_block(self, end: int) -> int:
        """Tokens the entry's (last) block actually covers — what a
        re-admission saves from the prefill."""
        return end - ((end - 1) // self.block_size) * self.block_size

    def __len__(self) -> int:
        with self._lock:
            return len(self._host)

    def has(self, key: bytes) -> bool:
        with self._lock:
            if key in self._host:
                return True
        return self.disk is not None and self.disk.end_for(key) is not None

    # -- demotion --------------------------------------------------------
    def put(self, key: bytes, end: int, payload: Dict[str, np.ndarray],
            reason: str = "evict") -> None:
        """Admit one demoted block into the host tier (LRU refresh if
        already present). ``reason`` labels the demotion counter
        (``evict`` / ``preempt`` / ``flush``)."""
        spill = []
        with self._lock:
            if key in self._host:
                self._host.move_to_end(key)
                self._host[key] = (int(end), payload)
                return
            self._host[key] = (int(end), payload)
            self._host_bytes += payload_nbytes(payload)
            self.demoted += 1
            while len(self._host) > self.host_blocks:
                vkey, (vend, vpayload) = self._host.popitem(last=False)
                self._host_bytes -= payload_nbytes(vpayload)
                spill.append((vkey, vend, vpayload))
            self._set_gauges()
        _sm.kv_tier_demoted_blocks.labels(reason).inc()
        for vkey, vend, vpayload in spill:
            self._spill_or_drop(vkey, vend, vpayload)

    def _spill_or_drop(self, key: bytes, end: int,
                       payload: Dict[str, np.ndarray]) -> None:
        if self.disk is not None and self.cost.should_demote(
                self.tokens_in_block(end), payload_nbytes(payload)):
            self.disk.put(key, end, payload)
        else:
            with self._lock:
                self.dropped += 1

    # -- re-admission ----------------------------------------------------
    def lookup(self, key: bytes) \
            -> Optional[Tuple[int, Dict[str, np.ndarray], str]]:
        """``(end, payload, source)`` — host hit (LRU refresh) or disk
        load (promoted into the host map so a hot prefix pays the file
        read once); None on miss."""
        with self._lock:
            ent = self._host.get(key)
            if ent is not None:
                self._host.move_to_end(key)
                return ent[0], ent[1], "host"
        if self.disk is None:
            return None
        ent = self.disk.get(key)
        if ent is None:
            return None
        self.put(key, ent[0], ent[1], reason="promote")
        return ent[0], ent[1], "disk"

    def match_next(self, tokens: np.ndarray, covered: int, limit: int) \
            -> Optional[Tuple[int, Dict[str, np.ndarray], str]]:
        """The longest tier entry extending coverage past ``covered``
        (at most one block, at most ``limit`` tokens) — the same
        longest-span-first walk ``PrefixCache.match`` does, continued
        into the lower tiers."""
        top = min(covered + self.block_size, limit)
        for end in range(top, covered, -1):
            ent = self.lookup(self.key_of(tokens, end))
            if ent is not None:
                return ent
        return None

    def note_readmit(self, blocks: int, tokens: int) -> None:
        with self._lock:
            self.readmitted_blocks += blocks
            self.readmitted_tokens += tokens

    # -- flush / stats ---------------------------------------------------
    def flush(self) -> int:
        """Persist every host-resident entry to the disk store (drain/
        stop path — the persistence contract across engine restarts).
        Returns the number of entries newly committed."""
        if self.disk is None:
            return 0
        with self._lock:
            entries = list(self._host.items())
        n = 0
        for key, (end, payload) in entries:
            if self.disk.put(key, end, payload):
                n += 1
        return n

    def _set_gauges(self):  # holds-lock: _lock
        _sm.kv_tier_host_blocks.set(len(self._host))
        _sm.kv_tier_host_bytes.set(self._host_bytes)

    def stats(self) -> dict:
        with self._lock:
            out = {
                "host_entries": len(self._host),
                "host_capacity": self.host_blocks,
                "host_bytes": self._host_bytes,
                "demoted_blocks": self.demoted,
                "dropped_blocks": self.dropped,
                "readmitted_blocks": self.readmitted_blocks,
                "readmitted_tokens": self.readmitted_tokens,
            }
        out["cost_model"] = self.cost.snapshot()
        out["disk"] = self.disk.stats() if self.disk is not None else None
        return out
