"""Admission control for the serving engine: FCFS queue with bounded
depth (backpressure), per-request deadlines, and cancellation.

Iteration-level scheduling (Orca) splits serving into two loops: the
ADMISSION decision (this module — which request gets the next free slot)
and the ITERATION itself (engine.py — one decode step for every running
slot). FCFS within a priority class is deliberately the whole policy
here: the TPU-side design makes admission cheap enough (bucketed
prefill + cache splice, no recompile) that fancier policies are a
drop-in swap of ``pop_ready``.

Overload control (the DAGOR shape — Zhou et al., SoCC'18): when the
queue is FULL and a higher-priority request arrives, the newest
lowest-class queued request is SHED (rejected with an explicit error)
to make room — batch work absorbs the pressure before interactive work
ever bounces. And a request whose deadline cannot beat the live
queue-wait p50 is rejected AT ADMISSION (429 + Retry-After) instead of
queued: work that will expire in the queue is load with zero goodput.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Optional

from . import metrics as _sm
from .request import Request, RequestStatus

__all__ = ["Scheduler", "QueueFullError", "DeadlineInfeasibleError"]


class QueueFullError(RuntimeError):
    """Backpressure: the admission queue is at max depth. Callers should
    shed load or retry later — the engine NEVER buffers unboundedly."""


class DeadlineInfeasibleError(QueueFullError):
    """Admission-time rejection: the request's deadline cannot beat the
    live queue-wait estimate, so queueing it would only produce an
    EXPIRED request later. Subclasses ``QueueFullError`` so every
    existing backpressure surface (HTTP 429 + Retry-After, the
    router's saturated-backoff path) handles it for free;
    ``retry_after_s`` carries the wait estimate the deadline lost to."""

    def __init__(self, msg: str, retry_after_s: Optional[float] = None):
        super().__init__(msg)
        self.retry_after_s = retry_after_s


class Scheduler:
    GUARDED_BY = {"_q": "_lock"}

    def __init__(self, max_queue_depth: int = 64):
        self.max_queue_depth = int(max_queue_depth)
        self._q: deque = deque()
        self._lock = threading.Lock()

    def __len__(self) -> int:
        with self._lock:
            return len(self._q)

    @property
    def depth(self) -> int:
        with self._lock:
            return len(self._q)

    def submit(self, req: Request):
        """FCFS enqueue with priority-aware overload control.

        Raises ``QueueFullError`` (and marks the request REJECTED) when
        the queue is at max depth and holds nothing of a strictly lower
        priority class — the clear-rejection contract: the caller knows
        immediately, nothing is dropped silently. When a LOWER class is
        queued, the newest such request is shed instead (it is the one
        that has invested the least wait) and the arrival is admitted.
        Raises ``DeadlineInfeasibleError`` when the queue is non-empty
        and the request's remaining deadline cannot beat the live
        queue-wait p50 — failing fast at admission beats queueing work
        that will expire before a slot frees."""
        with self._lock:
            if req.deadline_ts is not None and self._q:
                est = _sm.queue_wait_p50()
                remaining = req.deadline_ts - time.perf_counter()
                if est is not None and remaining <= est:
                    req.finish(
                        RequestStatus.REJECTED,
                        error=f"deadline infeasible: {remaining:.3f}s "
                              f"remain but the queue-wait p50 is "
                              f"{est:.3f}s")
                    _sm.requests_total.labels("rejected").inc()
                    _sm.deadline_rejected_total.labels(req.priority).inc()
                    raise DeadlineInfeasibleError(
                        f"deadline cannot beat the queue: {remaining:.3f}s "
                        f"remain, queue-wait p50 is {est:.3f}s — retry "
                        f"with a later deadline or back off",
                        retry_after_s=round(est, 3))
            if len(self._q) >= self.max_queue_depth:
                victim = None
                rank = req.params.priority_rank
                for cand in reversed(self._q):  # newest lowest class
                    if cand.params.priority_rank < rank and \
                            (victim is None or cand.params.priority_rank
                             < victim.params.priority_rank):
                        victim = cand
                        if victim.params.priority_rank == 0:
                            break
                if victim is None:
                    req.finish(RequestStatus.REJECTED,
                               error=f"queue full "
                                     f"(depth {self.max_queue_depth})")
                    _sm.requests_total.labels("rejected").inc()
                    raise QueueFullError(
                        f"serving queue is full ({self.max_queue_depth} "
                        f"requests waiting); retry later or raise "
                        f"max_queue_depth")
                self._q.remove(victim)
                victim.finish(
                    RequestStatus.REJECTED,
                    error=f"shed under queue pressure: class "
                          f"{victim.priority} yielded its place to an "
                          f"arriving {req.priority} request — retry "
                          f"later")
                _sm.requests_total.labels("rejected").inc()
                _sm.requests_shed_total.labels(victim.priority).inc()
            req.status = RequestStatus.QUEUED
            self._q.append(req)
            _sm.queue_depth.set(len(self._q))

    def requeue(self, req: Request):
        """Push a request back to the FRONT of the queue (paged-engine
        preemption / admission backoff): it keeps its FCFS position and
        is retried before anything newer. Deliberately exempt from the
        depth bound — the request was already admitted once; bouncing it
        with a rejection now would turn pool pressure into data loss."""
        with self._lock:
            if req.status != RequestStatus.QUEUED:
                # preemption: a fresh queue-wait window + a fresh
                # `queued` span, so the trace shows each wait separately
                # (queued → preempted → requeued/queued → resume). An
                # admission-BACKOFF requeue (popped, no free blocks, put
                # straight back) keeps the running wait window — the
                # request has been waiting the whole time.
                req.queued_since_ts = time.perf_counter()
                req._tr_event("requeued")
            req._tr_begin("queued")
            req.status = RequestStatus.QUEUED
            self._q.appendleft(req)
            _sm.queue_depth.set(len(self._q))

    def snapshot(self) -> list:
        """Queued requests, FCFS order (the /debug/requests live
        table's waiting section)."""
        with self._lock:
            return list(self._q)

    def detach_all(self) -> list:
        """Remove and return every queued request WITHOUT finishing
        them (FCFS order) — the supervisor's crash-capture hook. A
        queued request was never touched by the crashing step; handing
        it to a rebuilt engine instead of failing it is the whole
        point of supervised restart (``Request.finish`` is idempotent
        and irreversible, so capture must happen BEFORE the crash
        path's ``_fail_inflight`` can reach the queue)."""
        with self._lock:
            out = list(self._q)
            self._q.clear()
            _sm.queue_depth.set(0)
            return out

    def depth_spec_opted_out(self) -> int:
        """Queued requests that opted OUT of speculation
        (``SamplingParams.spec_k == 0``). A draft-model engine whose
        queue is mostly opt-outs is paying verify-bundle width for
        plain decode — ``/stats`` surfaces this so the operator can see
        the mismatch between the engine's spec config and the actual
        admission mix."""
        with self._lock:
            return sum(1 for r in self._q if r.params.spec_k == 0)

    def cancel(self, req: Request) -> bool:
        """Cancel a request. Queued: removed immediately. Running: flag
        it; the engine frees the slot at the next step boundary. Returns
        True when the request was still live."""
        req.cancel_requested = True
        with self._lock:
            if req in self._q:
                self._q.remove(req)
                _sm.queue_depth.set(len(self._q))
                req.finish(RequestStatus.CANCELLED)
                _sm.requests_total.labels("cancelled").inc()
                return True
        return req.status not in RequestStatus.FINAL

    def pop_ready(self, now: Optional[float] = None) -> Optional[Request]:
        """Next admissible request (FCFS), transparently finishing
        cancelled/expired entries it skips over."""
        if now is None:
            now = time.perf_counter()
        with self._lock:
            while self._q:
                req = self._q.popleft()
                _sm.queue_depth.set(len(self._q))
                if req.cancel_requested:
                    req.finish(RequestStatus.CANCELLED)
                    _sm.requests_total.labels("cancelled").inc()
                    continue
                if req.deadline_ts is not None and now > req.deadline_ts:
                    req.finish(RequestStatus.EXPIRED,
                               error="deadline passed while queued")
                    _sm.requests_total.labels("expired").inc()
                    continue
                return req
            return None
