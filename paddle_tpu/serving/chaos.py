"""Deterministic, seedable fault injection for the serving stack.

The router's reliability claims are only worth what the chaos suite
proves: ``tests/test_router.py`` drives every failure mode below
against REAL engines and asserts the invariants (no request silently
lost, greedy failover outputs bit-identical to a single-engine run,
zero retraces on surviving replicas, retry amplification bounded).
Faults are triggered by CALL COUNTS, not wall clocks, so a chaos run
replays identically; the only randomness is the opt-in Bernoulli storm
mode, driven by a private ``random.Random(seed)``.

Two injection points, matching the two surfaces the router touches:

- ``ChaosEngine`` wraps a live ``ServingEngine``'s ``step`` (instance
  attribute — the class is untouched) to kill, slow, or hang the decode
  loop mid-flight. A ``crash`` escapes ``step()`` into the engine's
  real ``_serve_loop`` crash path: the flight recorder dumps, every
  in-flight request fails with the injected error, ``/healthz`` flips
  to ``crashed`` — exactly the production failure the router must
  survive.
- ``ChaosReplica`` wraps a replica CLIENT (``LocalReplica`` /
  ``HTTPReplica``) to corrupt the router's control plane: ``/stats``
  timeouts, malformed or erroring ``/healthz`` probes, and
  ``PoolExhausted``/``QueueFull`` submit storms.

Poison requests — the supervisor's chaos counterpart — are injected by
REQUEST IDENTITY, not call count: ``poison_fingerprint`` crashes any
step in which a request with the armed fingerprint is running, however
many times that request is admitted, on whichever engine generation.
That is exactly the deterministic-crash shape quarantine exists for,
and it is what makes the fault survive a warm restart (a call-count
fault would fire once and be gone; the poison re-fires every time the
supervisor's probe re-admits the suspect). ``SupervisedChaos`` keeps
the fault armed ACROSS restarts by re-wrapping each rebuilt engine via
the supervisor's rebuild hook, with one shared ``injected`` ledger so
a test can assert the total crash count fleet-wide.

All injectors keep counters of everything they injected, so tests
assert the fault actually fired (a chaos test that silently injected
nothing proves nothing).
"""

from __future__ import annotations

import random
import threading
import time
from typing import Optional

from .block_pool import PoolExhaustedError
from .scheduler import QueueFullError

__all__ = ["ChaosError", "ChaosEngine", "ChaosReplica",
           "SupervisedChaos"]


class ChaosError(RuntimeError):
    """Marker for injected faults — assertions can tell a chaos kill
    from a genuine bug."""


class ChaosEngine:
    """Fault injector over one engine's step loop.

    >>> monkey = ChaosEngine(engine).crash_after_steps(5)
    >>> ...            # the 6th step raises ChaosError inside the loop
    >>> monkey.restore()

    Faults are one-shot unless re-armed; step counting starts at
    injection time. ``restore()`` puts the original bound method back
    (a crashed engine stays crashed — that is the point)."""

    def __init__(self, engine, seed: int = 0):
        self.engine = engine
        self.rng = random.Random(seed)
        self._orig_step = engine.step
        self._lock = threading.Lock()
        self._steps_seen = 0
        self._crash_at: Optional[int] = None
        self._crash_msg = "chaos: injected replica crash mid-decode"
        self._crash_p = 0.0
        self._slow_at: Optional[int] = None
        self._slow_for = 0
        self._slow_s = 0.0
        self._hang_at: Optional[int] = None
        self._hang_event = threading.Event()
        self._poison_fp: Optional[str] = None
        self._poison_left: Optional[int] = None
        self._poison_msg = "chaos: poisoned request crashed the step"
        self.injected = {"crash": 0, "slow": 0, "hang": 0, "poison": 0}
        engine.step = self._step

    # -- arming --------------------------------------------------------------
    def crash_after_steps(self, n: int, msg: Optional[str] = None):
        """Raise ``ChaosError`` out of step ``n+1`` (counted from now):
        the decode loop dies mid-flight through the engine's real crash
        path."""
        with self._lock:
            self._crash_at = self._steps_seen + int(n)
            if msg:
                self._crash_msg = msg
        return self

    def crash_storm(self, p: float):
        """Bernoulli(p) crash chance per step (seeded — deterministic
        for a given seed and step sequence)."""
        with self._lock:
            self._crash_p = float(p)
        return self

    def slow_steps(self, delay_s: float, after: int = 0, for_steps: int = 1):
        """Stretch ``for_steps`` steps (starting ``after`` steps from
        now) by ``delay_s`` each — the degraded-but-alive replica."""
        with self._lock:
            self._slow_at = self._steps_seen + int(after)
            self._slow_for = int(for_steps)
            self._slow_s = float(delay_s)
        return self

    def poison_fingerprint(self, fingerprint: str,
                           crashes: Optional[int] = None,
                           msg: Optional[str] = None):
        """Crash every step in which a request with this fingerprint is
        RUNNING — the deterministic poison request. Unlike the count
        faults this one is not one-shot: it re-fires each time the
        request is (re-)admitted, which is the shape quarantine must
        defeat. ``crashes`` bounds the total firings (None =
        unbounded); the quarantine contract says the supervisor stops
        re-admitting the fingerprint before any sane bound is hit."""
        with self._lock:
            self._poison_fp = str(fingerprint)
            self._poison_left = None if crashes is None else int(crashes)
            if msg:
                self._poison_msg = msg
        return self

    def hang_after_steps(self, n: int):
        """Block the loop inside step ``n+1`` until ``release()`` — the
        hung replica: /healthz stays reachable (and eventually reports
        ``stalled``), the loop thread is wedged."""
        with self._lock:
            self._hang_at = self._steps_seen + int(n)
            self._hang_event.clear()
        return self

    def release(self):
        """Un-hang a hung step (the wedge clears; the loop resumes)."""
        self._hang_event.set()
        return self

    def restore(self):
        self.engine.step = self._orig_step
        self._hang_event.set()
        return self

    # -- the wrapped step ----------------------------------------------------
    def _step(self) -> bool:
        with self._lock:
            n = self._steps_seen
            self._steps_seen += 1
            crash = (self._crash_at is not None and n >= self._crash_at) \
                or (self._crash_p > 0.0
                    and self.rng.random() < self._crash_p)
            slow = (self._slow_at is not None and self._slow_at <= n
                    < self._slow_at + self._slow_for)
            hang = self._hang_at is not None and n >= self._hang_at
            poison = False
            if self._poison_fp is not None and \
                    (self._poison_left is None or self._poison_left > 0):
                # identity fault: fires iff the poisoned request is in
                # a slot RIGHT NOW (same thread as the step — the slot
                # table is stable here)
                for r in self.engine._slot_req:
                    if r is not None and r.fingerprint == self._poison_fp:
                        poison = True
                        if self._poison_left is not None:
                            self._poison_left -= 1
                        break
        if poison:
            self.injected["poison"] += 1
            raise ChaosError(self._poison_msg)
        if hang:
            self.injected["hang"] += 1
            with self._lock:
                self._hang_at = None  # one-shot
            self._hang_event.wait()
        if crash:
            self.injected["crash"] += 1
            with self._lock:
                self._crash_at = None
                self._crash_p = 0.0
            raise ChaosError(self._crash_msg)
        if slow:
            self.injected["slow"] += 1
            time.sleep(self._slow_s)
        return self._orig_step()


class ChaosReplica:
    """Control-plane fault injector: wraps a replica client, passing
    everything through except the armed faults. Stackable with
    ``ChaosEngine`` (data plane) on the same replica."""

    def __init__(self, inner, seed: int = 0):
        self.inner = inner
        self.name = getattr(inner, "name", None)
        self.rng = random.Random(seed)
        self._lock = threading.Lock()
        self._stats_fail = 0       # remaining stats faults
        self._stats_mode = "timeout"
        self._stats_hang_s = 5.0
        self._probe_fail = 0       # remaining healthz faults
        self._probe_mode = "error"
        self._malformed_payload = "IM FINE"  # not a dict: malformed
        self._reject_submits = 0
        self._reject_exc = "pool"
        self.injected = {"stats": 0, "probe": 0, "submit": 0}

    # -- arming --------------------------------------------------------------
    def fail_stats(self, n: int, mode: str = "timeout",
                   hang_s: float = 5.0):
        """Next ``n`` ``stats()`` calls fail: ``"timeout"`` blocks for
        ``hang_s`` (the router's stats timeout must cut it loose),
        ``"error"`` raises."""
        with self._lock:
            self._stats_fail = int(n)
            self._stats_mode = mode
            self._stats_hang_s = float(hang_s)
        return self

    def fail_probes(self, n: int, mode: str = "error", payload=None):
        """Next ``n`` ``healthz()`` calls fail: ``"error"`` raises,
        ``"timeout"`` blocks, ``"malformed"`` returns a non-payload
        (default a bare string — the probe validator must reject it,
        not crash on it)."""
        with self._lock:
            self._probe_fail = int(n)
            self._probe_mode = mode
            if payload is not None:
                self._malformed_payload = payload
        return self

    def reject_submits(self, n: int, exc: str = "pool"):
        """Next ``n`` ``submit()`` calls raise — ``"pool"`` =
        ``PoolExhaustedError`` (the PoolExhausted storm), ``"queue"`` =
        ``QueueFullError`` (backpressure)."""
        with self._lock:
            self._reject_submits = int(n)
            self._reject_exc = exc
        return self

    # -- the wrapped client --------------------------------------------------
    def healthz(self):
        with self._lock:
            fail, mode = self._probe_fail, self._probe_mode
            if fail > 0:
                self._probe_fail -= 1
        if fail > 0:
            self.injected["probe"] += 1
            if mode == "timeout":
                time.sleep(self._stats_hang_s)
                raise TimeoutError("chaos: probe hung")
            if mode == "malformed":
                return self._malformed_payload
            raise ChaosError("chaos: probe endpoint exploded")
        return self.inner.healthz()

    def stats(self):
        with self._lock:
            fail, mode = self._stats_fail, self._stats_mode
            if fail > 0:
                self._stats_fail -= 1
        if fail > 0:
            self.injected["stats"] += 1
            if mode == "timeout":
                time.sleep(self._stats_hang_s)
                raise TimeoutError("chaos: stats hung")
            raise ChaosError("chaos: stats endpoint exploded")
        return self.inner.stats()

    def submit(self, prompt, deadline_s=None, on_token=None, params=None,
               trace_id=None):
        with self._lock:
            fail, exc = self._reject_submits, self._reject_exc
            if fail > 0:
                self._reject_submits -= 1
        if fail > 0:
            self.injected["submit"] += 1
            if exc == "queue":
                raise QueueFullError("chaos: queue full")
            raise PoolExhaustedError("chaos: pool exhausted")
        if trace_id is not None:
            # fleet trace propagation passes through chaos untouched —
            # the merged failover trace is exactly what the chaos
            # suite's crash lanes need to be debuggable
            return self.inner.submit(prompt, deadline_s=deadline_s,
                                     on_token=on_token, params=params,
                                     trace_id=trace_id)
        return self.inner.submit(prompt, deadline_s=deadline_s,
                                 on_token=on_token, params=params)

    def __getattr__(self, name):
        # fleet extensions (metrics_text / trace_events) and any future
        # optional protocol methods delegate to the inner client — and
        # stay ABSENT when the inner client lacks them, so the router's
        # hasattr gating sees the truth through the chaos wrapper
        if name in ("metrics_text", "trace_events"):
            return getattr(self.inner, name)
        raise AttributeError(name)

    def cancel(self, handle):
        return self.inner.cancel(handle)

    def drain(self, timeout_s=None):
        return self.inner.drain(timeout_s)

    def warmup(self):
        return self.inner.warmup()

    def start(self):
        if hasattr(self.inner, "start"):
            self.inner.start()


class SupervisedChaos:
    """Chaos that SURVIVES warm restarts.

    A plain ``ChaosEngine`` dies with its engine: the supervisor's
    rebuild swaps in a fresh ``ServingEngine`` whose ``step`` is
    unwrapped, so any fault armed on the old engine silently stops
    firing — and a poison-quarantine test that silently stops injecting
    proves nothing. This wrapper registers a rebuild hook on the
    supervisor and re-wraps every engine generation with a fresh
    ``ChaosEngine``, re-armed by the caller's ``arm`` closure and
    writing into ONE shared ``injected`` ledger, so the test's "the
    poison fired exactly N times fleet-wide" assertion spans restarts.

    >>> chaos = SupervisedChaos(sup, arm=lambda m:
    ...     m.poison_fingerprint(fp))
    >>> ...  # crash, restart, crash again: chaos.injected["poison"] == 2
    """

    def __init__(self, supervisor, arm=None, seed: int = 0):
        self.supervisor = supervisor
        self.seed = seed
        self._arm = arm
        self.injected = {"crash": 0, "slow": 0, "hang": 0, "poison": 0}
        self.monkeys: list = []
        supervisor.add_rebuild_hook(self._attach)
        self._attach(supervisor.engine)

    def _attach(self, engine):
        m = ChaosEngine(engine, seed=self.seed)
        m.injected = self.injected  # one ledger across generations
        if self._arm is not None:
            self._arm(m)
        self.monkeys.append(m)
        return m

    @property
    def current(self) -> ChaosEngine:
        """The monkey on the supervisor's CURRENT engine generation."""
        return self.monkeys[-1]

    def restore(self):
        for m in self.monkeys:
            m.restore()
        return self
