"""Static-graph inference model save/load.

Parity: python/paddle/static/io.py (save_inference_model /
load_inference_model). The artifact format is shared with
``paddle_tpu.jit.save`` — a serialized StableHLO program + params — so one
predictor (paddle_tpu.inference) serves both entry points, the way the
reference serves ``.pdmodel``/``.pdiparams`` from both jit.save and
static save_inference_model.
"""

from __future__ import annotations

import json
from typing import List, Sequence

import jax
import numpy as np
from jax import export as jexport

from ..core.tensor import Tensor
from .graph import Executor, Program, Variable, _replay, default_main_program
from .input_spec import InputSpec, avals_from_specs

_MODEL_SUFFIX = ".pdmodel"
_PARAMS_SUFFIX = ".pdiparams"
_META_SUFFIX = ".pdmeta"


def save_inference_model(path_prefix: str, feed_vars: Sequence[Variable],
                         fetch_vars: Sequence[Variable], executor: Executor = None,
                         program: Program = None, **kwargs) -> None:
    feed_vars = list(feed_vars)
    fetch_vars = list(fetch_vars)
    prog = program or feed_vars[0]._prog
    nodes = list(prog._nodes)
    feed_vids = [v._vid for v in feed_vars]
    fetch_vids = [v._vid for v in fetch_vars]
    param_vids = list(prog._params.keys())
    params = {prog._params[vid].name: np.asarray(prog._params[vid]._data) for vid in param_vids}
    name_by_vid = {vid: prog._params[vid].name for vid in param_vids}

    def runner(params, buffers, *feed_vals):
        del buffers
        env = {}
        for vid, val in zip(feed_vids, feed_vals):
            env[vid] = val
        for vid in param_vids:
            env[vid] = params[name_by_vid[vid]]
        _replay(nodes, env)
        return tuple(env[v] for v in fetch_vids)

    specs = []
    for v in feed_vars:
        declared = v._declared_shape if v._declared_shape is not None else tuple(v.shape)
        specs.append(InputSpec(list(declared), str(v.dtype), name=v.name))
    avals = avals_from_specs(specs)
    param_sds = {k: jax.ShapeDtypeStruct(v.shape, v.dtype) for k, v in params.items()}
    exported = jexport.export(jax.jit(runner))(param_sds, {}, *avals)

    with open(path_prefix + _MODEL_SUFFIX, "wb") as f:
        f.write(exported.serialize())
    with open(path_prefix + _PARAMS_SUFFIX, "wb") as f:
        np.savez(f, **{("p:" + k): v for k, v in params.items()})
    with open(path_prefix + _META_SUFFIX, "w") as f:
        json.dump({"input_specs": [s.to_dict() for s in specs],
                   "params": sorted(params.keys()), "buffers": [],
                   "fetch_names": [v.name for v in fetch_vars],
                   "format": "paddle_tpu.static.v1"}, f)


def load_inference_model(path_prefix: str, executor: Executor = None, **kwargs) -> List:
    """Returns [program, feed_target_names, fetch_targets] like the
    reference; ``program`` is a TranslatedLayer the Executor can run."""
    from ..jit.save_load import load as jit_load

    layer = jit_load(path_prefix)
    feed_names = [s.name for s in layer.input_specs]
    fetch_names = layer._meta.get("fetch_names", [])
    return [layer, feed_names, fetch_names]
