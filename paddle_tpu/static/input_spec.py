"""InputSpec — declarative input signature for to_static / jit.save.

Parity: python/paddle/static/input_spec.py (reference InputSpec). TPU design:
an InputSpec maps 1:1 onto a jax.ShapeDtypeStruct; unknown dims (None / -1)
become jax.export symbolic dimensions so saved programs stay
shape-polymorphic the way the reference's ProgramDesc is.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np


def _canon_dtype(dtype) -> jnp.dtype:
    if dtype is None:
        return jnp.dtype("float32")
    if isinstance(dtype, str):
        return jnp.dtype(dtype)
    return jnp.dtype(dtype)


class InputSpec:
    """Describes the shape/dtype/name of one program input."""

    def __init__(self, shape: Sequence[Optional[int]], dtype: Any = "float32",
                 name: Optional[str] = None, stop_gradient: bool = True):
        self.shape = tuple(None if (d is None or (isinstance(d, int) and d < 0)) else int(d)
                           for d in shape)
        self.dtype = _canon_dtype(dtype)
        self.name = name
        self.stop_gradient = stop_gradient

    @classmethod
    def from_tensor(cls, tensor, name: Optional[str] = None) -> "InputSpec":
        return cls(tuple(tensor.shape), str(np.dtype(tensor.dtype)) if not isinstance(tensor.dtype, jnp.dtype) else str(tensor.dtype), name or getattr(tensor, "name", None))

    @classmethod
    def from_numpy(cls, ndarray: np.ndarray, name: Optional[str] = None) -> "InputSpec":
        return cls(ndarray.shape, str(ndarray.dtype), name)

    def batch(self, batch_size: Optional[int] = None) -> "InputSpec":
        return InputSpec((batch_size,) + self.shape, str(self.dtype), self.name)

    def unbatch(self) -> "InputSpec":
        if len(self.shape) == 0:
            raise ValueError("Cannot unbatch a 0-d InputSpec.")
        return InputSpec(self.shape[1:], str(self.dtype), self.name)

    def __repr__(self):
        return f"InputSpec(shape={self.shape}, dtype={self.dtype}, name={self.name})"

    def __eq__(self, other):
        return (isinstance(other, InputSpec) and self.shape == other.shape
                and self.dtype == other.dtype and self.name == other.name)

    def __hash__(self):
        return hash((self.shape, str(self.dtype), self.name))

    def to_dict(self) -> dict:
        return {"shape": [d for d in self.shape], "dtype": str(self.dtype), "name": self.name}

    @classmethod
    def from_dict(cls, d: dict) -> "InputSpec":
        return cls(d["shape"], d["dtype"], d.get("name"))


def avals_from_specs(specs: Sequence[InputSpec], scope=None):
    """InputSpecs → jax ShapeDtypeStructs; None dims → symbolic dims (one
    shared SymbolicScope so constraints relate across inputs)."""
    from jax import export as jexport

    if scope is None:
        scope = jexport.SymbolicScope()
    avals = []
    for si, s in enumerate(specs):
        dims = []
        for di, d in enumerate(s.shape):
            if d is None:
                (sym,) = jexport.symbolic_shape(f"_s{si}_{di}", scope=scope)
                dims.append(sym)
            else:
                dims.append(d)
        avals.append(jax.ShapeDtypeStruct(tuple(dims), s.dtype))
    return avals
