"""paddle.static equivalent — static-graph build/run API over XLA.

Parity: python/paddle/static/ (Program/Executor/data/InputSpec/
save_inference_model) and the executor stack beneath it
(paddle/fluid/framework/new_executor/standalone_executor.cc:37).
"""

from . import nn_static as nn
from .graph import (
    Executor,
    Program,
    Variable,
    append_backward,
    create_global_var,
    create_parameter,
    data,
    default_main_program,
    default_startup_program,
    disable_static,
    enable_static,
    global_scope,
    gradients,
    in_static_mode,
    program_guard,
    scope_guard,
    static_minimize,
)
from .input_spec import InputSpec
from .io import load_inference_model, save_inference_model

# Paddle exposes these under paddle.static as well
CompiledProgram = Program

__all__ = [
    "InputSpec", "Program", "CompiledProgram", "Executor", "Variable", "nn",
    "data", "program_guard", "default_main_program", "default_startup_program",
    "enable_static", "disable_static", "in_static_mode", "gradients",
    "append_backward", "create_parameter", "create_global_var", "global_scope",
    "scope_guard", "save_inference_model", "load_inference_model",
]
