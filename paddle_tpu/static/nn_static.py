"""paddle.static.nn — layer builders for static-graph mode.

Parity: python/paddle/static/nn/common.py (fc, conv2d, batch_norm, ...).
Each builder declares parameters via ``create_parameter`` and emits ops
through the normal functional API (which the static record hook captures).
"""

from __future__ import annotations

from typing import Optional, Sequence

from .. import nn
from ..nn import functional as F
from .graph import create_parameter


def fc(x, size: int, num_flatten_dims: int = 1, weight_attr=None, bias_attr=None,
       activation: Optional[str] = None, name=None):
    """Fully-connected layer (parity: paddle.static.nn.fc)."""
    if weight_attr is not None:
        raise NotImplementedError(
            "static.nn.fc weight_attr (custom initializer/regularizer) is "
            "not implemented; initialize via paddle.seed + nn.initializer "
            "defaults, or build the graph from nn.Linear")
    declared = getattr(x, "_declared_shape", None) or tuple(x.shape)
    in_dim = 1
    for d in x.shape[num_flatten_dims:]:
        in_dim *= int(d)
    if tuple(x.shape[num_flatten_dims:]) != (in_dim,):
        if num_flatten_dims == 1:
            lead = [-1]
        else:
            # at most one dynamic lead dim is expressible in a reshape
            lead = [-1 if d is None else int(d) for d in declared[:num_flatten_dims]]
        x = x.reshape(lead + [in_dim])
    w = create_parameter([in_dim, size], str(x.dtype), name=None)
    out = x.matmul(w)
    if bias_attr is not False:
        b = create_parameter([size], str(x.dtype), is_bias=True)
        out = out + b
    if activation:
        out = getattr(F, activation)(out)
    return out


def conv2d(input, num_filters: int, filter_size, stride=1, padding=0, dilation=1,
           groups: int = 1, param_attr=None, bias_attr=None, act: Optional[str] = None,
           data_format: str = "NCHW", name=None):
    if param_attr is not None:
        raise NotImplementedError(
            "static.nn.conv2d param_attr is not implemented; use the "
            "default initializers or nn.Conv2D")
    ks = (filter_size, filter_size) if isinstance(filter_size, int) else tuple(filter_size)
    cin = int(input.shape[1] if data_format == "NCHW" else input.shape[-1])
    w = create_parameter([num_filters, cin // groups, ks[0], ks[1]], str(input.dtype))
    out = F.conv2d(input, w, None, stride=stride, padding=padding, dilation=dilation,
                   groups=groups, data_format=data_format)
    if bias_attr is not False:
        b = create_parameter([num_filters], str(input.dtype), is_bias=True)
        shape = [1, num_filters, 1, 1] if data_format == "NCHW" else [1, 1, 1, num_filters]
        out = out + b.reshape(shape)
    if act:
        out = getattr(F, act)(out)
    return out


def batch_norm(input, act=None, momentum: float = 0.9, epsilon: float = 1e-5,
               param_attr=None, bias_attr=None, data_layout: str = "NCHW",
               is_test: bool = False, name=None):
    """Inference-form BN built from recorded ops (running stats are
    non-trainable globals, so static_minimize never updates them —
    ``momentum`` would only matter for that absent update; ``is_test``
    is therefore the only supported behavior either way)."""
    if param_attr is not None or bias_attr is not None:
        raise NotImplementedError(
            "static.nn.batch_norm param_attr/bias_attr are not "
            "implemented; use default initializers or nn.BatchNorm2D")
    from .graph import create_global_var

    c = int(input.shape[1] if data_layout == "NCHW" else input.shape[-1])
    from ..nn import initializer as init_mod

    scale = create_parameter([c], str(input.dtype), default_initializer=init_mod.Constant(1.0))
    bias = create_parameter([c], str(input.dtype), is_bias=True)
    mean = create_global_var([c], 0.0, str(input.dtype))
    var = create_global_var([c], 1.0, str(input.dtype))
    ndim = len(input.shape)
    if data_layout == "NCHW":
        bshape = [1, c] + [1] * (ndim - 2)
    else:
        bshape = [1] * (ndim - 1) + [c]
    inv = (var.reshape(bshape) + epsilon).rsqrt()
    out = (input - mean.reshape(bshape)) * inv * scale.reshape(bshape) + bias.reshape(bshape)
    if act:
        out = getattr(F, act)(out)
    return out


def embedding(input, size: Sequence[int], is_sparse: bool = False, padding_idx=None,
              param_attr=None, dtype="float32"):
    if param_attr is not None:
        raise NotImplementedError(
            "static.nn.embedding param_attr is not implemented; use "
            "default initializers or nn.Embedding")
    w = create_parameter(list(size), dtype)
    return F.embedding(input, w, padding_idx=padding_idx)
