"""Static-graph mode: Program / Variable / Executor.

Parity: the reference's static graph stack — Program/Block/OpDesc
(paddle/fluid/framework/ framework.proto, python/paddle/static/),
StandaloneExecutor + PirInterpreter (paddle/fluid/framework/new_executor/
standalone_executor.cc:37, pir_interpreter.cc:1504).

TPU design: the "graph" is a record of pure jax op closures captured at
Python build time through the same ``apply_op`` dispatch the eager mode
uses (the record hook below is the analogue of tracing into PIR instead of
executing). ``Executor.run`` replays the recorded nodes as one pure
function over the feed/parameter environment and hands the whole thing to
``jax.jit`` — so the "interpreter" is XLA itself: one compiled executable
per (program version, fetch set, feed shapes), which is exactly the
whole-graph fast path the reference's interpreter approximates with
instruction scheduling.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core import dtype as dtypes
from ..core.tensor import Parameter, Tensor
from ..ops import dispatch as _dispatch

__all__ = [
    "Variable", "Program", "Executor", "program_guard", "data",
    "default_main_program", "default_startup_program", "enable_static",
    "disable_static", "in_static_mode", "gradients", "append_backward",
    "create_parameter", "create_global_var", "scope_guard", "global_scope",
]


class Variable(Tensor):
    """Symbolic tensor in a Program (parity: python/paddle/base/framework.py
    Variable). ``_data`` holds a ShapeDtypeStruct aval, never a value."""

    __slots__ = ("_prog", "_vid", "_kind", "_declared_shape")

    def __init__(self, aval, prog: "Program", kind: str, name: Optional[str] = None,
                 stop_gradient: bool = True):
        # bypass Tensor.__init__'s jnp.asarray: set fields directly
        self._data = aval
        self.stop_gradient = stop_gradient
        self._grad_data = None
        self._grad_node = None
        self._out_slot = 0
        Tensor._next_id[0] += 1
        self.name = name or f"var_{Tensor._next_id[0]}"
        self.persistable = False
        self._hooks = []
        self.placements = None
        self.process_mesh = None
        self._prog = prog
        self._kind = kind  # 'feed' | 'op' | 'param'
        self._declared_shape = None  # feed vars: user shape with None dims
        self._vid = prog._new_vid(self)

    @property
    def shape(self):
        return tuple(self._data.shape)

    @property
    def dtype(self):
        return self._data.dtype

    def numpy(self):
        raise RuntimeError(
            f"Variable '{self.name}' has no value in static-graph mode; "
            "fetch it through Executor.run(fetch_list=[...]).")

    def __repr__(self):
        return f"Variable(name={self.name}, shape={self.shape}, dtype={self.dtype}, kind={self._kind})"


class _Node:
    __slots__ = ("op", "fn", "inputs", "out_vids", "kind", "extra")

    def __init__(self, op: str, fn, inputs: List[Tuple[str, Any]], out_vids: List[int],
                 kind: str = "op", extra=None):
        self.op = op
        self.fn = fn
        self.inputs = inputs          # list of ('var', vid) | ('const', jax array)
        self.out_vids = out_vids
        self.kind = kind              # 'op' | 'grad' | 'assign_param'
        self.extra = extra


class Program:
    """An ordered record of op nodes (parity: ProgramDesc / pir::Program)."""

    def __init__(self):
        self._nodes: List[_Node] = []
        self._vars: Dict[int, Variable] = {}
        self._feeds: Dict[str, int] = {}          # feed name -> vid
        self._params: Dict[int, Parameter] = {}   # vid -> eager storage
        self._next = [0]
        self._version = 0
        self._cache: Dict[tuple, Any] = {}
        self.random_seed = 0

    def _new_vid(self, var: Variable) -> int:
        vid = self._next[0]
        self._next[0] += 1
        self._vars[vid] = var
        return vid

    def _invalidate(self):
        self._version += 1
        self._cache.clear()

    # -- introspection (parity: Program.list_vars / global_block) --
    def list_vars(self):
        return list(self._vars.values())

    def all_parameters(self):
        return [self._vars[vid] for vid in self._params]

    def block(self, i=0):
        return self

    def global_block(self):
        return self

    @property
    def ops(self):
        return self._nodes

    def clone(self, for_test: bool = False) -> "Program":
        p = Program()
        if for_test:
            # prune backward + optimizer-update nodes (parity: clone(for_test=True)
            # pruning the backward block)
            p._nodes = [n for n in self._nodes
                        if n.kind != "grad" and n.op != "optimizer_update"]
        else:
            p._nodes = list(self._nodes)
            if "_writebacks" in self.__dict__:
                p.__dict__["_writebacks"] = list(self.__dict__["_writebacks"])
            if "_opt_states" in self.__dict__:
                p.__dict__["_opt_states"] = self.__dict__["_opt_states"]  # shared state
            if "_lr_refresh" in self.__dict__:
                p.__dict__["_lr_refresh"] = list(self.__dict__["_lr_refresh"])
        p._vars = dict(self._vars)
        p._feeds = dict(self._feeds)
        p._params = dict(self._params)
        p._next = [self._next[0]]
        return p

    def __repr__(self):
        return f"Program(nodes={len(self._nodes)}, feeds={list(self._feeds)}, params={len(self._params)})"


_tls = threading.local()


def _state():
    if not hasattr(_tls, "main"):
        _tls.main = Program()
        _tls.startup = Program()
        _tls.static = False
    return _tls


def default_main_program() -> Program:
    return _state().main


def default_startup_program() -> Program:
    return _state().startup


class program_guard:
    def __init__(self, main_program: Program, startup_program: Optional[Program] = None):
        self._main = main_program
        self._startup = startup_program or Program()

    def __enter__(self):
        s = _state()
        self._saved = (s.main, s.startup)
        s.main, s.startup = self._main, self._startup
        return self

    def __exit__(self, *exc):
        s = _state()
        s.main, s.startup = self._saved
        return False


def in_static_mode() -> bool:
    return _state().static


def enable_static(*args, **kwargs):
    _state().static = True
    _dispatch._static_hook = _record_hook


def disable_static(*args, **kwargs):
    _state().static = False
    _dispatch._static_hook = None


# ---------------------------------------------------------------- recording

def _aval_of(x) -> jax.ShapeDtypeStruct:
    if isinstance(x, Variable):
        return x._data
    d = x._data if isinstance(x, Tensor) else x
    return jax.ShapeDtypeStruct(tuple(d.shape), d.dtype)


def _record_hook(name: str, fn, tensors: Sequence[Tensor], nouts=None):
    """Installed as ops.dispatch._static_hook while static mode is on.
    Returns NotImplemented for all-concrete inputs (constant folding — the
    op just executes eagerly, like the reference executing CPU ops at
    build time for shape computation)."""
    if not any(isinstance(t, Variable) for t in tensors):
        return NotImplemented
    prog = None
    for t in tensors:
        if isinstance(t, Variable):
            prog = t._prog
            break
    inputs: List[Tuple[str, Any]] = []
    avals = []
    for t in tensors:
        if isinstance(t, Variable):
            if t._prog is not prog:
                raise ValueError("cannot mix Variables from different Programs in one op")
            inputs.append(("var", t._vid))
        else:
            inputs.append(("const", t._data))
        avals.append(_aval_of(t))
    out_aval = jax.eval_shape(fn, *avals)
    multi = isinstance(out_aval, (tuple, list))
    out_avals = list(out_aval) if multi else [out_aval]
    outs = [Variable(a, prog, "op") for a in out_avals]
    prog._nodes.append(_Node(name, fn, inputs, [o._vid for o in outs]))
    prog._invalidate()
    return outs if multi else outs[0]


def data(name: str, shape: Sequence[Optional[int]], dtype="float32", lod_level=0) -> Variable:
    """Declare a feed input (parity: paddle.static.data)."""
    prog = default_main_program()
    dt = dtypes.convert_dtype(dtype)
    declared = tuple(None if (d is None or (isinstance(d, int) and d < 0)) else int(d) for d in shape)
    concrete = tuple(1 if d is None else d for d in declared)
    v = Variable(jax.ShapeDtypeStruct(concrete, dt), prog, "feed", name=name)
    v._declared_shape = declared
    prog._feeds[name] = v._vid
    prog._invalidate()
    return v


def create_parameter(shape, dtype="float32", name=None, attr=None, is_bias=False,
                     default_initializer=None) -> Variable:
    """Declare a trainable parameter with eager storage (parity:
    paddle.static.create_parameter; storage plays the Scope's role)."""
    from ..nn import initializer as init_mod

    prog = default_main_program()
    dt = dtypes.convert_dtype(dtype)
    if default_initializer is None:
        default_initializer = (init_mod.Constant(0.0) if is_bias
                               else init_mod.XavierNormal())
    storage = Parameter(default_initializer(tuple(shape), dt), trainable=True, name=name)
    v = Variable(jax.ShapeDtypeStruct(tuple(shape), dt), prog, "param",
                 name=storage.name, stop_gradient=False)
    prog._params[v._vid] = storage
    prog._invalidate()
    return v


def create_global_var(shape, value, dtype="float32", persistable=False, name=None) -> Variable:
    prog = default_main_program()
    dt = dtypes.convert_dtype(dtype)
    storage = Parameter(jnp.full(tuple(shape), value, dt), trainable=False, name=name)
    v = Variable(jax.ShapeDtypeStruct(tuple(shape), dt), prog, "param", name=storage.name)
    prog._params[v._vid] = storage
    prog._invalidate()
    return v


# ---------------------------------------------------------------- replay

def _replay(nodes: List[_Node], env: Dict[int, Any], skip_vids=frozenset(),
            stop_grad_vids=frozenset()):
    """Evaluate recorded nodes over env (vid -> traced array)."""
    for node in nodes:
        if node.kind == "grad":
            _replay_grad(node, env)
            continue
        args = [env[ref] if kind == "var" else ref for kind, ref in node.inputs]
        out = node.fn(*args)
        outs = list(out) if isinstance(out, (tuple, list)) else [out]
        for vid, o in zip(node.out_vids, outs):
            if vid not in skip_vids:
                env[vid] = jax.lax.stop_gradient(o) if vid in stop_grad_vids else o


def _replay_grad(node: _Node, env: Dict[int, Any]):
    """grad node: d(targets)/d(inputs) by re-running the recorded prefix
    under jax.vjp with the input vids as free variables."""
    prefix, target_vids, input_vids, cot_vids, no_grad_vids = node.extra
    base = dict(env)
    ng = frozenset(no_grad_vids)

    def g(*in_vals):
        e = dict(base)
        for vid, val in zip(input_vids, in_vals):
            e[vid] = val
        _replay(prefix, e, skip_vids=frozenset(input_vids), stop_grad_vids=ng)
        return tuple(e[t] for t in target_vids)

    primals = tuple(env[v] for v in input_vids)
    outs, vjp = jax.vjp(g, *primals)
    if cot_vids:
        cots = tuple(jnp.ones(o.shape, o.dtype) if v is None else env[v]
                     for v, o in zip(cot_vids, outs))
    else:
        cots = tuple(jnp.ones(o.shape, o.dtype) for o in outs)
    grads = vjp(cots)
    for vid, gval in zip(node.out_vids, grads):
        env[vid] = gval


def gradients(targets, inputs, target_gradients=None, no_grad_set=None) -> List[Variable]:
    """Static backward (parity: paddle.static.gradients /
    paddle.base.backward.gradients). Appends one grad meta-node whose replay
    runs jax.vjp over the captured forward prefix."""
    targets = targets if isinstance(targets, (list, tuple)) else [targets]
    inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    prog = targets[0]._prog
    prefix = list(prog._nodes)
    target_vids = [t._vid for t in targets]
    input_vids = [i._vid for i in inputs]
    if target_gradients is not None:
        tgs = target_gradients if isinstance(target_gradients, (list, tuple)) else [target_gradients]
        if len(tgs) != len(targets):
            raise ValueError("target_gradients must match targets in length")
        cot_vids = []
        for tg, t in zip(tgs, targets):
            if tg is None:  # None -> default ones cotangent for that target
                cot_vids.append(None)
            elif isinstance(tg, Variable):
                cot_vids.append(tg._vid)
            else:  # concrete Tensor/array cotangent: intern as a constant var
                arr = tg._data if isinstance(tg, Tensor) else jnp.asarray(tg)
                cv = Variable(jax.ShapeDtypeStruct(arr.shape, arr.dtype), prog, "param")
                prog._params[cv._vid] = Parameter(arr, trainable=False, name=cv.name)
                cot_vids.append(cv._vid)
    else:
        cot_vids = []
    no_grad_vids = [v._vid for v in (no_grad_set or [])]
    outs = [Variable(i._data, prog, "op", name=f"{i.name}@GRAD") for i in inputs]
    prog._nodes.append(_Node("gradients", None,
                             [("var", v) for v in input_vids + [c for c in cot_vids if c is not None]],
                             [o._vid for o in outs], kind="grad",
                             extra=(prefix, target_vids, input_vids, cot_vids, no_grad_vids)))
    prog._invalidate()
    return outs


def append_backward(loss, parameter_list=None, no_grad_set=None) -> List[Tuple[Variable, Variable]]:
    """Parity: paddle.static.append_backward — returns (param, grad) pairs."""
    prog = loss._prog
    if parameter_list is None:
        params = [prog._vars[vid] for vid in prog._params
                  if not prog._vars[vid].stop_gradient]
    else:
        params = list(parameter_list)
    grads = gradients([loss], params)
    return list(zip(params, grads))


def static_minimize(optimizer, loss: Variable):
    """Append grad + update nodes implementing optimizer.minimize for static
    mode, using the optimizer's functional update rule. Parameter storage
    (and accumulator state) is updated post-run by the Executor."""
    from ..optimizer.functional import from_eager

    prog = loss._prog
    pairs = append_backward(loss)
    if not pairs:
        return None, []
    fopt = from_eager(optimizer)
    pvars = [p for p, _ in pairs]
    gvars = [g for _, g in pairs]
    storages = [prog._params[p._vid] for p in pvars]
    key = f"@opt_state_{id(optimizer)}"
    state_store = prog.__dict__.setdefault("_opt_states", {})
    if key not in state_store:
        state_store[key] = fopt.init({s.name: s._data for s in storages})

    # lr enters the graph as a refreshed param input so LRScheduler.step()/
    # set_lr() between runs take effect without retracing
    lr_storage = Parameter(jnp.asarray(optimizer.get_lr(), jnp.float32),
                           trainable=False, name=f"@lr_{id(optimizer)}")
    lr_var = Variable(jax.ShapeDtypeStruct((), jnp.float32), prog, "param",
                      name=lr_storage.name)
    prog._params[lr_var._vid] = lr_storage
    prog.__dict__.setdefault("_lr_refresh", []).append((lr_storage, optimizer))

    def upd_fn(lr, *pg_vals):
        n = len(pvars)
        p_vals, g_vals = pg_vals[:n], pg_vals[n:]
        params = {s.name: v for s, v in zip(storages, p_vals)}
        grads = {s.name: v for s, v in zip(storages, g_vals)}
        new_params, new_state = fopt.update(grads, state_store[key], params, lr)
        flat_state = jax.tree.leaves(new_state)
        return tuple(new_params[s.name] for s in storages) + tuple(flat_state)

    n_state = len(jax.tree.leaves(state_store[key]))
    out_avals = ([jax.ShapeDtypeStruct(p.shape, p.dtype) for p in pvars]
                 + [jax.ShapeDtypeStruct(l.shape, l.dtype) for l in jax.tree.leaves(state_store[key])])
    outs = [Variable(a, prog, "op") for a in out_avals]
    node = _Node("optimizer_update", upd_fn,
                 [("var", lr_var._vid)] + [("var", p._vid) for p in pvars]
                 + [("var", g._vid) for g in gvars],
                 [o._vid for o in outs], kind="op",
                 extra=None)
    prog._nodes.append(node)
    # remember write-back plan: (param storages, their out vids, state key, state out vids)
    prog.__dict__.setdefault("_writebacks", []).append(
        (storages, [o._vid for o in outs[:len(pvars)]], key,
         [o._vid for o in outs[len(pvars):]], n_state))
    prog._invalidate()
    return None, pairs


# ---------------------------------------------------------------- executor

class _Scope:
    def find_var(self, name):
        return None


_global_scope = _Scope()


def global_scope():
    return _global_scope


class scope_guard:
    def __init__(self, scope):
        self._scope = scope

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


class Executor:
    """Parity: paddle.static.Executor over StandaloneExecutor
    (standalone_executor.cc:37). run() = jit-compiled whole-program replay."""

    def __init__(self, place=None):
        self.place = place

    def run(self, program: Optional[Program] = None, feed: Optional[Dict[str, Any]] = None,
            fetch_list: Optional[Sequence] = None, return_numpy: bool = True, **kwargs):
        from ..jit.save_load import TranslatedLayer

        if isinstance(program, TranslatedLayer):
            feed = feed or {}
            names = [s.name for s in program.input_specs]
            out = program(*[feed[n] for n in names])
            outs = out if isinstance(out, (tuple, list)) else [out]
            fetch_names = program._meta.get("fetch_names") or [f"fetch_{i}" for i in range(len(outs))]
            by_name = dict(zip(fetch_names, outs))
            if fetch_list:
                wanted = [f if isinstance(f, str) else getattr(f, "name", f) for f in fetch_list]
                outs = [by_name[w] for w in wanted]
            return [np.asarray(o._data) if return_numpy else o for o in outs]

        prog = program or default_main_program()
        feed = feed or {}
        fetch_list = list(fetch_list or [])
        fetch_vids = tuple(v._vid for v in fetch_list)
        if not prog._nodes and not fetch_list:
            return []  # startup program: params were initialized eagerly

        for lr_storage, opt in prog.__dict__.get("_lr_refresh", []):
            lr_storage._data = jnp.asarray(opt.get_lr(), jnp.float32)

        writebacks = prog.__dict__.get("_writebacks", [])
        opt_states = prog.__dict__.get("_opt_states", {})
        feed_names = tuple(sorted(prog._feeds.keys() & feed.keys()))
        param_vids = tuple(prog._params.keys())
        wb_param_vids = tuple(vid for wb in writebacks for vid in wb[1])
        wb_state_vids = tuple(vid for wb in writebacks for vid in wb[3])

        ckey = (prog._version, fetch_vids, feed_names)
        runner = prog._cache.get(ckey)
        if runner is None:
            nodes = prog._nodes

            def run_fn(feed_vals, param_vals, state_leaves):
                env: Dict[int, Any] = {}
                for nm, v in zip(feed_names, feed_vals):
                    env[prog._feeds[nm]] = v
                for vid, v in zip(param_vids, param_vals):
                    env[vid] = v
                # rebind optimizer state snapshots for this step
                it = iter(state_leaves)
                for k in sorted(opt_states.keys()):
                    treedef = jax.tree.structure(opt_states[k])
                    opt_states[k] = jax.tree.unflatten(
                        treedef, [next(it) for _ in range(treedef.num_leaves)])
                _replay(nodes, env)
                fetches = tuple(env[v] for v in fetch_vids)
                wb_p = tuple(env[v] for v in wb_param_vids)
                wb_s = tuple(env[v] for v in wb_state_vids)
                return fetches, wb_p, wb_s

            runner = jax.jit(run_fn)
            prog._cache[ckey] = runner

        feed_vals = tuple(jnp.asarray(feed[nm]) for nm in feed_names)
        param_vals = tuple(prog._params[vid]._data for vid in param_vids)
        state_leaves = tuple(l for k in sorted(opt_states.keys())
                             for l in jax.tree.leaves(opt_states[k]))
        fetches, wb_p, wb_s = runner(feed_vals, param_vals, state_leaves)

        # write back updated params + optimizer state (the Scope mutation step)
        i = 0
        for storages, out_vids, skey, svids, n_state in writebacks:
            for s, vid in zip(storages, out_vids):
                s._data = wb_p[i]
                i += 1
        j = 0
        for storages, out_vids, skey, svids, n_state in writebacks:
            leaves = list(wb_s[j:j + n_state])
            j += n_state
            treedef = jax.tree.structure(opt_states[skey])
            opt_states[skey] = jax.tree.unflatten(treedef, leaves)

        if return_numpy:
            return [np.asarray(f) for f in fetches]
        return [Tensor(f) for f in fetches]

    def close(self):
        pass
