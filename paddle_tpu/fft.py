"""paddle.fft — discrete Fourier transform surface.

Parity: python/paddle/fft.py (36 functions: c2c/r2c/c2r 1-D/2-D/n-D
transforms + helpers, norm modes 'forward'|'backward'|'ortho';
kernels paddle/phi/kernels/*/fft_*). TPU design: jnp.fft → XLA FFT HLO
(differentiable; batched over leading dims).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .core.tensor import Tensor
from .ops.dispatch import apply_op, ensure_tensor

_fft_native = [None]  # None = undetected; True = device FFT HLO works


def _device_fft_supported() -> bool:
    """Static detection only: executing an FFT on a backend without the
    lowering (e.g. the axon dev tunnel) poisons the PJRT client, so never
    probe by running one. XLA:CPU/GPU/TPU all implement the FFT HLO; only
    experimental plugin backends (axon) lack it."""
    if _fft_native[0] is None:
        import os

        plugin = os.environ.get("JAX_PLATFORMS", "")
        _fft_native[0] = plugin in ("", "cpu", "gpu", "tpu", "cuda", "rocm") \
            or jax.default_backend() == "cpu"
    return _fft_native[0]


def _with_cpu_fallback(jfn):
    """Run the transform on the host CPU backend when the accelerator has no
    FFT lowering — the reference's backend-fallback model (its fft kernels
    are pocketfft/cufft, CPU/GPU only; kernel_factory falls back to CPU).
    device_put in/out keeps the op differentiable through the tape."""

    def fn(a, **kw):
        if _device_fft_supported():
            return jfn(a, **kw)
        cpu = jax.devices("cpu")[0]
        # default_device(cpu): internal constants (norm scaling) must also be
        # created/promoted on the host — complex dtypes may not exist on the
        # plugin device at all
        with jax.default_device(cpu):
            out = jfn(jax.device_put(a, cpu), **kw)
        if jnp.issubdtype(out.dtype, jnp.complexfloating):
            return out  # complex results stay host-committed
        return jax.device_put(out, jax.devices()[0])

    return fn

__all__ = [
    "fft", "ifft", "rfft", "irfft", "hfft", "ihfft",
    "fft2", "ifft2", "rfft2", "irfft2", "hfft2", "ihfft2",
    "fftn", "ifftn", "rfftn", "irfftn", "hfftn", "ihfftn",
    "fftfreq", "rfftfreq", "fftshift", "ifftshift",
]

_NORMS = {"forward", "backward", "ortho", None}


def _norm(norm):
    if norm not in _NORMS:
        raise ValueError(
            f"Unexpected norm: {norm!r}. Norm should be forward, backward or ortho")
    return norm or "backward"


def _wrap1(name, jfn, complex_in=False):
    def op(x, n=None, axis=-1, norm="backward", name=None):
        t = ensure_tensor(x)
        nm = _norm(norm)
        f = _with_cpu_fallback(jfn)
        return apply_op(op.__name__, lambda a: f(a, n=n, axis=axis, norm=nm), t)

    op.__name__ = name
    return op


def _wrap2(name, jfn):
    def op(x, s=None, axes=(-2, -1), norm="backward", name=None):
        t = ensure_tensor(x)
        nm = _norm(norm)
        f = _with_cpu_fallback(jfn)
        return apply_op(op.__name__, lambda a: f(a, s=s, axes=tuple(axes), norm=nm), t)

    op.__name__ = name
    return op


def _wrapn(name, jfn):
    def op(x, s=None, axes=None, norm="backward", name=None):
        t = ensure_tensor(x)
        nm = _norm(norm)
        ax = tuple(axes) if axes is not None else None
        f = _with_cpu_fallback(jfn)
        return apply_op(op.__name__, lambda a: f(a, s=s, axes=ax, norm=nm), t)

    op.__name__ = name
    return op


fft = _wrap1("fft", jnp.fft.fft)
ifft = _wrap1("ifft", jnp.fft.ifft)
rfft = _wrap1("rfft", jnp.fft.rfft)
irfft = _wrap1("irfft", jnp.fft.irfft)
hfft = _wrap1("hfft", jnp.fft.hfft)
ihfft = _wrap1("ihfft", jnp.fft.ihfft)

fft2 = _wrap2("fft2", jnp.fft.fft2)
ifft2 = _wrap2("ifft2", jnp.fft.ifft2)
rfft2 = _wrap2("rfft2", jnp.fft.rfft2)
irfft2 = _wrap2("irfft2", jnp.fft.irfft2)


def hfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return hfftn(x, s=s, axes=axes, norm=norm)


def ihfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return ihfftn(x, s=s, axes=axes, norm=norm)


fftn = _wrapn("fftn", jnp.fft.fftn)
ifftn = _wrapn("ifftn", jnp.fft.ifftn)
rfftn = _wrapn("rfftn", jnp.fft.rfftn)
irfftn = _wrapn("irfftn", jnp.fft.irfftn)


def hfftn(x, s=None, axes=None, norm="backward", name=None):
    """n-D Hermitian FFT: c2c forward over inner axes, c2r (hfft) over the
    last axis — the reference's fft_c2c + fft_c2r composition."""
    t = ensure_tensor(x)
    nm = _norm(norm)

    def f(a):
        native = _device_fft_supported()

        def run(a):
            ax = tuple(axes) if axes is not None else tuple(range(a.ndim))
            for i, axi in enumerate(ax[:-1]):
                a = jnp.fft.fft(a, n=None if s is None else s[i], axis=axi, norm=nm)
            return jnp.fft.hfft(a, n=None if s is None else s[-1], axis=ax[-1], norm=nm)

        if native:
            return run(a)
        cpu = jax.devices("cpu")[0]
        with jax.default_device(cpu):
            out = run(jax.device_put(a, cpu))
        return jax.device_put(out, jax.devices()[0])  # hfft output is real

    return apply_op("hfftn", f, t)


def ihfftn(x, s=None, axes=None, norm="backward", name=None):
    """Inverse of hfftn: r2c (ihfft) over the last axis, then c2c inverse
    over the inner axes."""
    t = ensure_tensor(x)
    nm = _norm(norm)

    def f(a):
        native = _device_fft_supported()

        def run(a):
            ax = tuple(axes) if axes is not None else tuple(range(a.ndim))
            a = jnp.fft.ihfft(a, n=None if s is None else s[-1], axis=ax[-1], norm=nm)
            for i, axi in enumerate(ax[:-1]):
                a = jnp.fft.ifft(a, n=None if s is None else s[i], axis=axi, norm=nm)
            return a

        if native:
            return run(a)
        cpu = jax.devices("cpu")[0]
        with jax.default_device(cpu):
            return run(jax.device_put(a, cpu))  # complex: stays host-committed

    return apply_op("ihfftn", f, t)


def fftfreq(n, d=1.0, dtype=None, name=None) -> Tensor:
    return Tensor(jnp.fft.fftfreq(n, d=d).astype(dtype or "float32"))


def rfftfreq(n, d=1.0, dtype=None, name=None) -> Tensor:
    return Tensor(jnp.fft.rfftfreq(n, d=d).astype(dtype or "float32"))


def fftshift(x, axes=None, name=None) -> Tensor:
    return apply_op("fftshift", lambda a: jnp.fft.fftshift(a, axes=axes),
                    ensure_tensor(x))


def ifftshift(x, axes=None, name=None) -> Tensor:
    return apply_op("ifftshift", lambda a: jnp.fft.ifftshift(a, axes=axes),
                    ensure_tensor(x))
