"""paddle_tpu: a TPU-native deep-learning framework with PaddlePaddle's
capability surface.

Built from scratch on JAX/XLA/PJRT (compute) + Pallas (hot kernels) +
GSPMD/shard_map (parallelism). The reference implementation being matched
(not ported) is PaddlePaddle (see /root/repo/SURVEY.md for the blueprint);
docstrings cite reference files for capability parity checks.
"""

from __future__ import annotations

from .core import dtype as _dtype_mod
from .core.dtype import (
    bfloat16,
    bool,
    complex64,
    complex128,
    float16,
    float32,
    float64,
    float8_e4m3fn,
    float8_e5m2,
    get_default_dtype,
    int8,
    int16,
    int32,
    int64,
    set_default_dtype,
    uint8,
)
from .core.flags import get_flags, set_flags
from .core.tensor import Parameter, Tensor
from .core import autograd as _autograd
from .core.autograd import enable_grad, is_grad_enabled, no_grad, set_grad_enabled
from .core.autograd import grad
from .ops import *  # noqa: F401,F403 — the full op namespace (paddle.* functional surface)
from .ops import dispatch as _dispatch
from .core import device
from .core.device import CPUPlace, CUDAPlace, TPUPlace, get_device, is_compiled_with_cuda, set_device

from . import amp, autograd, distribution, fft, hub, io, jit, linalg, metric, nn, optimizer, profiler, vision
from . import observability
from . import hapi
from .hapi import Model, callbacks, summary
from .core import memory
from .core.memory import max_memory_allocated, memory_allocated
from . import distributed
from .framework import io_utils as _io_utils
from .framework.io_utils import load, save
from .framework.random_utils import get_cuda_rng_state, set_cuda_rng_state

from . import static
from .static import disable_static, enable_static
from . import inference
from . import sparse
from . import incubate
from . import quantization
from . import audio
from . import text
from . import signal
from . import onnx
from . import regularizer
from . import generation
from . import serving
from . import fault_tolerance

# top-level aliases for reference __all__ parity
# paddle.dtype is a TYPE in the reference (framework dtype class);
# Tensor.dtype returns numpy dtype instances, so np.dtype is the match
from numpy import dtype as dtype
from .distributed.parallel import DataParallel
from .nn.param_attr import ParamAttr
from .jit.api import to_static as _jit_to_static  # noqa: F401 (paddle.jit.to_static path)

__version__ = "0.1.0"
