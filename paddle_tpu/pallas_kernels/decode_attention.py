"""Flash-decode: GQA-native split-K Pallas attention for the decode hot path.

The serving/generation decode step runs single-query attention (q_len
small, typically 1) against the static [B, max_len, kv_heads, d] KV
caches. The plain XLA path scores the ENTIRE padded cache and — for GQA
models — first materializes the repeat_kv-expanded [B, max_len, heads, d]
K/V in HBM, multiplying the dominant HBM stream by heads/kv_heads.
This kernel is the TPU-native fix (reference analogue: the decode branch
of phi/kernels/gpu/flash_attn_kernel.cu and the flash-decoding split-K
formulation):

- split-K over the cache length: grid (B, kv_heads, num_kv_blocks);
  every KV block computes an online-softmax PARTIAL (running max, sum,
  unnormalized accumulator) and a small XLA combine merges them — short
  batches still expose B * kv_heads * num_blocks parallel cells, and on
  TPU the first two grid dims are declared "parallel" for megacore.
- GQA-native: each grid cell loads its [block_k, d] K/V block ONCE and
  serves the kv head's whole [group * q_len, d] query bundle through a
  single MXU matmul — repeat_kv never materializes, so KV bytes drop by
  the group factor (4x for Llama-70B-style heads/kv_heads ratios).
- per-row length masking: the engine's per-slot [B] position vector is
  scalar-prefetched; each row's kv-block loop is bounded by its own
  length, blocks wholly beyond ``pos + q_len`` are skipped (the K/V
  BlockSpec index map re-points them at the row's last needed block,
  which Pallas recognizes as a revisit and does not re-fetch), and the
  boundary block masks ``kpos <= qpos`` element-wise. A mostly-empty
  cache therefore costs proportional to occupancy, not max_len; dead
  slots (the serving engine pins freed slots to pos 0) touch one block.
- bf16 (or fp32) streams with fp32 statistics and accumulation
  (preferred_element_type on both matmuls, stats never leave fp32).

Layout contract matches generation.make_kv_caches: q [B, q_len, heads,
d], caches [B, max_len, kv_heads, d], query head j reads kv head
j // (heads // kv_heads) (the repeat_kv mapping).

Dispatch: llama/gpt decode paths call ``decode_dispatch`` (env
``PADDLE_TPU_FLASH_DECODE``; default on for TPU backends, opt-in on CPU
where Pallas interprets) and fall back to XLA with reason counters —
``paddle_tpu_flash_decode_{hits,fallbacks}_total`` — mirroring the
fused-conv instrumentation pattern.
"""

from __future__ import annotations

import math
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # TPU-only module; CPU tests run in interpret mode
    from jax.experimental.pallas import tpu as pltpu

    _HAS_TPU_PALLAS = True
except ImportError:  # pragma: no cover
    pltpu = None
    _HAS_TPU_PALLAS = False

from ..observability.metrics import _ENABLED as _obs_on
from ..observability.metrics import counter as _obs_counter
from ._blocks import pick_block
from .flash_attention import NEG_INF, _dot_prec, _interpret

__all__ = ["flash_decode_attention", "flash_decode_enabled",
           "decode_dispatch", "MAX_DECODE_Q_LEN",
           "paged_flash_decode_attention", "paged_decode_dispatch",
           "MAX_PAGED_Q_LEN", "MAX_SPEC_K", "spec_verify_eligibility",
           "spec_tree_width"]

_FLASH_DECODE_ENV = "PADDLE_TPU_FLASH_DECODE"

# the kernel is built for the short-query decode window; longer chunks
# (prefill) belong to flash_attention's q-blocked grid
MAX_DECODE_Q_LEN = 8

# the paged variant also serves chunked-prefill bundles (one fixed chunk
# shape replaces every per-bucket prefill executable) and speculative
# verify bundles (q_len = spec_k + 1), so its query window is the
# chunk/bundle, not the decode step
MAX_PAGED_Q_LEN = 256

# largest per-round draft count the serving engine accepts: the verify
# bundle must fit the paged kernel's query window (ServingConfig
# validates spec_k against this so an oversized k fails at construction
# with an actionable error instead of silently falling back)
MAX_SPEC_K = MAX_PAGED_Q_LEN - 1

# Dispatch outcome counters (PR-2 fused-conv pattern): the decode
# dispatch is a python-side decision with automatic XLA fallback, so a
# config regression that silently disables the kernel family would be
# invisible without them. Under jit they fire once per TRACE.
_fd_hits = _obs_counter(
    "paddle_tpu_flash_decode_hits_total",
    "decode steps dispatched to the Pallas flash-decode kernel",
    ("model",))
_fd_fallbacks = _obs_counter(
    "paddle_tpu_flash_decode_fallbacks_total",
    "decode steps on the XLA fallback path",
    ("reason",))


def flash_decode_enabled() -> bool:
    """Env-gated: PADDLE_TPU_FLASH_DECODE=1/0 forces it; default on for
    TPU backends (where the kernel is compiled) and off on CPU (where
    Pallas runs in the slow interpreter — tests opt in explicitly)."""
    v = os.environ.get(_FLASH_DECODE_ENV)
    if v is not None:
        return v != "0"
    return jax.default_backend() == "tpu"


def _tp_sharded() -> bool:
    """True while tracing inside a tensor-parallel executable
    (``distributed.partition.tp_context``). ``pallas_call`` cannot be
    partitioned by GSPMD, so a kernel hit inside a tp>1 ``shard_map``-
    free jit would force XLA to gather the full sharded KV onto every
    device; declining here keeps the kv-head-sharded gather fallback."""
    from ..distributed.partition import tp_active

    return tp_active() > 1


def decode_dispatch(model: str, *, q_len: int, has_mask: bool,
                    dtype, quantized: bool = False) -> bool:
    """The decode-path dispatch decision for one attention layer call:
    True -> run ``flash_decode_attention``; False -> XLA fallback, with
    the reason counted. Called from the static-cache branch of the
    llama/gpt attention forwards (python-side, so under jit this costs
    nothing after the first trace).

    ``quantized``: the cache is an int8/fp8 store — hits count under a
    ``<model>_quant`` label and fallbacks under ``quant_<reason>``, so a
    config regression that silently pushes the quantized lane onto the
    XLA dequant-gather fallback is visible in the metrics."""
    reason = None
    if not flash_decode_enabled():
        reason = "disabled"
    elif not _HAS_TPU_PALLAS:  # pragma: no cover — jax without pallas.tpu
        reason = "no_tpu_pallas"
    elif _tp_sharded():
        # pallas_call can't be partitioned by GSPMD; the XLA gather
        # fallback shards cleanly on the kv-heads axis instead
        reason = "tp_sharded"
    elif has_mask:
        # caller brought its own attention mask (ragged left-padded
        # prompts): the kernel's masking is position-derived only
        reason = "external_mask"
    elif q_len > MAX_DECODE_Q_LEN:
        reason = "q_len"
    elif str(dtype) not in ("float32", "bfloat16"):
        reason = "dtype"
    else:
        from ..core.autograd import is_grad_enabled

        if is_grad_enabled():
            # forward-only kernel (decode is inference); taping it would
            # fail at vjp derivation
            reason = "grad_mode"
    if reason is None:
        if _obs_on[0]:
            _fd_hits.labels(model + ("_quant" if quantized else "")).inc()
        return True
    if _obs_on[0]:
        _fd_fallbacks.labels(("quant_" if quantized else "") + reason).inc()
    return False


def paged_decode_dispatch(model: str, *, q_len: int, has_mask: bool,
                          dtype, quantized: bool = False) -> bool:
    """Dispatch decision for the PAGED decode/chunk-prefill path: True
    -> ``paged_flash_decode_attention`` (block-table gather inside the
    kernel's index map); False -> the XLA gather fallback
    (``gather_paged_kv`` + grouped SDPA — ``gather_paged_kv_dequant``
    for quantized pools), with the reason counted under a ``paged_``
    prefix (``paged_quant_`` when the pool is quantized). Same gates as
    ``decode_dispatch`` except the query window covers the prefill
    chunk (``MAX_PAGED_Q_LEN``)."""
    reason = None
    if not flash_decode_enabled():
        reason = "disabled"
    elif not _HAS_TPU_PALLAS:  # pragma: no cover — jax without pallas.tpu
        reason = "no_tpu_pallas"
    elif _tp_sharded():
        reason = "tp_sharded"
    elif has_mask:
        reason = "external_mask"
    elif q_len > MAX_PAGED_Q_LEN:
        reason = "q_len"
    elif str(dtype) not in ("float32", "bfloat16"):
        reason = "dtype"
    else:
        from ..core.autograd import is_grad_enabled

        if is_grad_enabled():
            reason = "grad_mode"
    if reason is None:
        if _obs_on[0]:
            _fd_hits.labels(
                model + "_paged" + ("_quant" if quantized else "")).inc()
        return True
    if _obs_on[0]:
        _fd_fallbacks.labels(
            ("paged_quant_" if quantized else "paged_") + reason).inc()
    return False


def spec_tree_width(spec_tree) -> int:
    """Node count of a draft token tree with per-depth branching factors
    ``spec_tree`` (root + every level): ``[4, 2, 2]`` -> 1 + 4 + 8 + 16
    = 29. This is the verify bundle's q_len — the quantity the kernel's
    query window bounds."""
    w = wl = 1
    for f in spec_tree:
        wl *= int(f)
        w += wl
    return w


def spec_verify_eligibility(spec_k: int, dtype, spec_tree=None):
    """Will a speculative verify bundle (q_len = spec_k + 1 for a chain,
    the flattened node count for a ``spec_tree``) take the paged
    flash-decode kernel, and if not, why? Called ONCE per engine at
    construction — the per-layer dispatch still decides each trace via
    ``paged_decode_dispatch``; this is the engine-level preflight that
    records the expected path (and its fallback reason, under the
    ``spec_`` / ``spec_tree_`` prefix) so a config that silently pushes
    every verify onto the XLA gather fallback is visible in the metrics
    before any traffic arrives."""
    if spec_tree is not None:
        prefix, width = "spec_tree_", spec_tree_width(spec_tree)
    else:
        prefix, width = "spec_", spec_k + 1
    reason = None
    if not flash_decode_enabled():
        reason = "disabled"
    elif not _HAS_TPU_PALLAS:  # pragma: no cover
        reason = "no_tpu_pallas"
    elif width > MAX_PAGED_Q_LEN:
        reason = "q_len"
    elif str(dtype) not in ("float32", "bfloat16"):
        reason = "dtype"
    if reason is None:
        return True, None
    if _obs_on[0]:
        _fd_fallbacks.labels(prefix + reason).inc()
    return False, reason


_COMPILER_PARAMS = None


def _compiler_kwargs():
    """Megacore partitioning on chip: batch and kv-head grid dims are
    embarrassingly parallel (every cell writes its own partial), only
    the kv-block dim needs sequential order (the revisit-skip on the
    K/V index map). Interpret mode takes no compiler params."""
    if not _HAS_TPU_PALLAS or _interpret():
        return {}
    global _COMPILER_PARAMS
    if _COMPILER_PARAMS is None:
        params_cls = (getattr(pltpu, "CompilerParams", None)
                      or getattr(pltpu, "TPUCompilerParams", None))
        if params_cls is None:  # pragma: no cover
            raise RuntimeError(
                "paddle_tpu flash decode needs pallas TPU compiler params "
                f"(neither CompilerParams nor TPUCompilerParams on "
                f"jax=={jax.__version__})")
        _COMPILER_PARAMS = params_cls(
            dimension_semantics=("parallel", "parallel", "arbitrary"))
    return {"compiler_params": _COMPILER_PARAMS}


def _cell_partial(q, k, v, length, start, o_ref, m_ref, l_ref, *,
                  block_k: int, sm_scale: float, q_len: int, group: int,
                  mask=None):
    """The block's online-softmax partial for the whole query bundle —
    shared by the plain and dequantizing kernel variants so the math can
    never drift between them (quantized vs bf16 parity oracles depend on
    identical masking/summation order).

    ``mask`` (None or [q_len, q_len] f32, 1.0 = visible): the row's
    in-bundle ancestor mask for tree-speculative verify. None keeps the
    causal bundle (kpos <= qpos) bitwise — a causal ancestor mask input
    reproduces it exactly, so the chain lane never pays the extra
    operand. Past-KV masking (everything before the bundle) is untouched
    either way: all of it is ancestry by construction."""
    gq, d = q.shape
    sc = jnp.dot(q, k.T, preferred_element_type=jnp.float32,
                 precision=_dot_prec(q.dtype)) * sm_scale
    kpos = start + jax.lax.broadcasted_iota(jnp.int32, (gq, block_k), 1)
    if mask is None:
        # query row r sits at absolute position pos + r // group; masking
        # kpos <= qpos covers BOTH the right-pad beyond the row's length
        # and causality inside the q_len window
        qpos = (length - q_len) \
            + jax.lax.broadcasted_iota(jnp.int32, (gq, block_k), 0) // group
        vis = kpos <= qpos
    else:
        # bundle node j lives at cache position (length - q_len) + j; a
        # dynamic per-column gather of mask[:, j] is not expressible in
        # the cell, so build the column one-hot [q_len, block_k] and
        # read the tile through one small MXU matmul. Columns outside
        # the bundle window match no one-hot row and fall to the past-KV
        # term (kpos < length - q_len), which also bounds the right-pad:
        # kpos >= length matches nothing and stays masked.
        mask_g = jnp.broadcast_to(
            mask[:, None, :], (q_len, group, q_len)).reshape(gq, q_len)
        j_col = (start - (length - q_len)) \
            + jax.lax.broadcasted_iota(jnp.int32, (q_len, block_k), 1)
        onehot = (jax.lax.broadcasted_iota(
            jnp.int32, (q_len, block_k), 0) == j_col).astype(jnp.float32)
        anc = jnp.dot(mask_g, onehot, preferred_element_type=jnp.float32)
        vis = (kpos < length - q_len) | (anc > 0.5)
    sc = jnp.where(vis, sc, NEG_INF)
    m = sc.max(axis=-1)                # [gq] f32
    p = jnp.exp(sc - m[:, None])
    l = p.sum(axis=-1)
    acc = jnp.dot(p.astype(v.dtype), v,
                  preferred_element_type=jnp.float32,
                  precision=_dot_prec(q.dtype))
    o_ref[0, 0, 0] = acc
    m_ref[0, 0, 0] = m[:, None]
    l_ref[0, 0, 0] = l[:, None]


def _cell_skip(o_ref, m_ref, l_ref, gq: int, d: int):
    # skipped blocks still own their partial slots; the finite
    # NEG_INF sentinel makes them exact zeros in the combine
    # (exp(NEG_INF - m_total) underflows to 0, l contributes 0)
    o_ref[0, 0, 0] = jnp.zeros((gq, d), jnp.float32)
    m_ref[0, 0, 0] = jnp.full((gq, 1), NEG_INF, jnp.float32)
    l_ref[0, 0, 0] = jnp.zeros((gq, 1), jnp.float32)


def _decode_kernel(lens_ref, q_ref, k_ref, v_ref, o_ref, m_ref, l_ref, *,
                   block_k: int, sm_scale: float, q_len: int, group: int,
                   mask_ref=None):
    """One (batch row, kv head, kv block) cell: the block's online-
    softmax partial for the whole query bundle.

    Refs (blocked):
      q [1, q_len, 1, group, d]   — the kv head's query bundle
      k/v [1, block_k, 1, d]      — one cache block of this kv head
      mask [1, q_len, q_len] f32  — optional in-bundle ancestor mask
      o [1, 1, 1, gq, d] f32      — unnormalized accumulator partial
      m/l [1, 1, 1, gq, 1] f32    — running max / sum partials
    """
    b = pl.program_id(0)
    s = pl.program_id(2)
    length = lens_ref[b]          # row's valid kv length = pos + q_len
    start = s * block_k
    gq = q_len * group
    d = q_ref.shape[-1]

    @pl.when(start < length)
    def _compute():
        q = q_ref[0, :, 0].reshape(gq, d)  # rows r = i*group + g
        k = k_ref[0, :, 0, :]              # [block_k, d]
        v = v_ref[0, :, 0, :]
        mask = None if mask_ref is None else mask_ref[0]
        _cell_partial(q, k, v, length, start, o_ref, m_ref, l_ref,
                      block_k=block_k, sm_scale=sm_scale, q_len=q_len,
                      group=group, mask=mask)

    @pl.when(start >= length)
    def _skip():
        _cell_skip(o_ref, m_ref, l_ref, gq, d)


def _decode_kernel_quant(lens_ref, q_ref, k_ref, v_ref, ks_ref, vs_ref,
                         o_ref, m_ref, l_ref, *, block_k: int,
                         sm_scale: float, q_len: int, group: int,
                         bound: float, mask_ref=None):
    """The quantized-cache cell: identical to ``_decode_kernel`` plus a
    DEQUANT PROLOGUE — the int8/fp8 K/V block and its per-token absmax
    scale column ([1, block_k, 1] f32) are widened to the query dtype in
    VMEM before the MXU matmuls, so the HBM stream is the narrow one.
    ``q * s / bound`` in that exact order matches
    ``quantization.intx.unpack_absmax`` bitwise, keeping the kernel and
    the XLA gather fallback interchangeable."""
    b = pl.program_id(0)
    s = pl.program_id(2)
    length = lens_ref[b]
    start = s * block_k
    gq = q_len * group
    d = q_ref.shape[-1]

    @pl.when(start < length)
    def _compute():
        q = q_ref[0, :, 0].reshape(gq, d)
        # dequant prologue: [block_k, d] narrow values * [block_k, 1]
        # absmax scales, widened in VMEM — nothing else in the cell
        # changes
        ks = ks_ref[0, :, 0]
        vs = vs_ref[0, :, 0]
        k = (k_ref[0, :, 0, :].astype(jnp.float32)
             * ks[:, None] / bound).astype(q.dtype)
        v = (v_ref[0, :, 0, :].astype(jnp.float32)
             * vs[:, None] / bound).astype(q.dtype)
        mask = None if mask_ref is None else mask_ref[0]
        _cell_partial(q, k, v, length, start, o_ref, m_ref, l_ref,
                      block_k=block_k, sm_scale=sm_scale, q_len=q_len,
                      group=group, mask=mask)

    @pl.when(start >= length)
    def _skip():
        _cell_skip(o_ref, m_ref, l_ref, gq, d)


def _flash_decode(q5, kc, vc, lens, *, sm_scale: float, block_k: int,
                  k_scale=None, v_scale=None):
    """q5 [B, q_len, KV, group, d], caches [B, max_len, KV, d],
    lens [B] int32 -> [B, KV, gq, d] f32 (unnormalized layout rows
    r = i*group + g, already combined and normalized).

    ``k_scale``/``v_scale`` ([B, max_len, KV] f32, both or neither):
    the caches hold int8/fp8 and each grid cell dequantizes its block in
    the kernel prologue (same grid, same index maps — the scale column
    rides the K/V re-point-and-skip logic)."""
    from ..quantization.intx import format_bound

    B, q_len, KV, group, d = q5.shape
    max_len = kc.shape[1]
    bk = pick_block(max_len, block_k)
    nb = max_len // bk
    gq = q_len * group
    quant = k_scale is not None

    def _idx_q(b, h, s, lens):
        return (b, 0, h, 0, 0)

    def _idx_kv(b, h, s, lens):
        # blocks beyond the row's last needed block re-point AT the last
        # needed one: Pallas sees a repeated index and skips the fetch,
        # so right-pad past pos (and dead slots pinned to pos 0) cost no
        # HBM traffic beyond one block
        last = jnp.maximum(pl.cdiv(lens[b], bk) - 1, 0)
        return (b, jnp.minimum(s, last), h, 0)

    def _idx_scale(b, h, s, lens):
        last = jnp.maximum(pl.cdiv(lens[b], bk) - 1, 0)
        return (b, jnp.minimum(s, last), h)

    def _idx_out(b, h, s, lens):
        return (b, h, s, 0, 0)

    def _idx_stat(b, h, s, lens):
        return (b, h, s, 0, 0)

    in_specs = [
        pl.BlockSpec((1, q_len, 1, group, d), _idx_q),
        pl.BlockSpec((1, bk, 1, d), _idx_kv),
        pl.BlockSpec((1, bk, 1, d), _idx_kv),
    ]
    if quant:
        in_specs += [pl.BlockSpec((1, bk, 1), _idx_scale),
                     pl.BlockSpec((1, bk, 1), _idx_scale)]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(B, KV, nb),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, 1, 1, gq, d), _idx_out),
            pl.BlockSpec((1, 1, 1, gq, 1), _idx_stat),
            pl.BlockSpec((1, 1, 1, gq, 1), _idx_stat),
        ],
    )

    if quant:
        bound = format_bound(
            "int8" if kc.dtype == jnp.int8 else "fp8")

        def kern(lens_ref, q_ref, k_ref, v_ref, ks_ref, vs_ref, o_ref,
                 m_ref, l_ref):
            _decode_kernel_quant(lens_ref, q_ref, k_ref, v_ref, ks_ref,
                                 vs_ref, o_ref, m_ref, l_ref, block_k=bk,
                                 sm_scale=sm_scale, q_len=q_len,
                                 group=group, bound=bound)

        operands = (lens.astype(jnp.int32), q5, kc, vc,
                    k_scale.astype(jnp.float32), v_scale.astype(jnp.float32))
    else:
        def kern(lens_ref, q_ref, k_ref, v_ref, o_ref, m_ref, l_ref):
            _decode_kernel(lens_ref, q_ref, k_ref, v_ref, o_ref, m_ref,
                           l_ref, block_k=bk, sm_scale=sm_scale,
                           q_len=q_len, group=group)

        operands = (lens.astype(jnp.int32), q5, kc, vc)

    o_p, m_p, l_p = pl.pallas_call(
        kern,
        grid_spec=grid_spec,
        out_shape=(jax.ShapeDtypeStruct((B, KV, nb, gq, d), jnp.float32),
                   jax.ShapeDtypeStruct((B, KV, nb, gq, 1), jnp.float32),
                   jax.ShapeDtypeStruct((B, KV, nb, gq, 1), jnp.float32)),
        interpret=_interpret(),
        **_compiler_kwargs(),
    )(*operands)

    # split-K combine (tiny: nb * gq * d floats per row/head): classic
    # log-sum-exp merge of the blocks' partials. Skipped blocks carry
    # (m=NEG_INF, l=0, acc=0) and contribute exact zeros; a fully-masked
    # row (dead slot) ends with l_total=0 and returns zeros.
    m_tot = m_p.max(axis=2)                        # [B, KV, gq, 1]
    alpha = jnp.exp(m_p - m_tot[:, :, None])       # [B, KV, nb, gq, 1]
    l_tot = (l_p * alpha).sum(axis=2)
    acc = (o_p * alpha).sum(axis=2)
    return acc / jnp.maximum(l_tot, 1e-30)


def _unwrap(x):
    from ..core.tensor import Tensor

    if x is None:
        return None
    return x._data if isinstance(x, Tensor) else jnp.asarray(x)


def flash_decode_attention(q, k_cache, v_cache, positions, sm_scale=None,
                           block_k: int = 256, k_scale=None, v_scale=None):
    """Flash-decode attention over the static KV caches.

    q: [B, q_len, heads, d] (q_len <= MAX_DECODE_Q_LEN); k_cache/v_cache:
    [B, max_len, kv_heads, d] with this step's tokens ALREADY written at
    [pos, pos + q_len) (the update_static_kv_cache protocol);
    ``positions``: per-row [B] int32 vector or scalar — query i of row b
    sits at absolute position positions[b] + i and attends cache
    positions <= it. Returns [B, q_len, heads, d] in q's dtype.

    heads must be a multiple of kv_heads; query head j reads kv head
    j // (heads // kv_heads) (the repeat_kv mapping) without ever
    materializing the expansion.

    QUANTIZED caches: pass the per-token-per-head absmax scales
    ``k_scale``/``v_scale`` ([B, max_len, kv_heads] f32, the
    ``make_kv_caches(kv_format=...)`` companions) and int8/fp8 caches —
    each grid cell dequantizes its block in the kernel prologue, so the
    HBM stream is the narrow one and nothing else changes.
    """
    from ..core.tensor import Tensor
    from ..ops.dispatch import apply_op

    is_tensor = isinstance(q, Tensor)
    pos_arr = positions._data if isinstance(positions, Tensor) else positions
    ks_arr, vs_arr = _unwrap(k_scale), _unwrap(v_scale)
    if (ks_arr is None) != (vs_arr is None):
        raise ValueError("pass both k_scale and v_scale or neither")

    def _f(qa, ka, va):
        B, q_len, H, d = qa.shape
        KV = ka.shape[2]
        if H % KV:
            raise ValueError(f"heads ({H}) not a multiple of kv_heads ({KV})")
        group = H // KV
        scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(d)
        pos = jnp.asarray(pos_arr, jnp.int32)
        if pos.ndim == 0:
            pos = jnp.broadcast_to(pos, (B,))
        lens = jnp.minimum(pos + q_len, ka.shape[1])
        q5 = qa.reshape(B, q_len, KV, group, d)
        o = _flash_decode(q5, ka, va, lens, sm_scale=scale, block_k=block_k,
                          k_scale=ks_arr, v_scale=vs_arr)
        # [B, KV, q_len*group, d] rows r = i*group + g -> [B, q_len, H, d]
        o = o.reshape(B, KV, q_len, group, d)
        o = jnp.transpose(o, (0, 2, 1, 3, 4)).reshape(B, q_len, H, d)
        return o.astype(qa.dtype)

    if is_tensor:
        return apply_op("flash_decode_attention", _f, q, k_cache, v_cache)
    return _f(jnp.asarray(q), jnp.asarray(k_cache), jnp.asarray(v_cache))


def _paged_flash_decode(q5, kp, vp, bt, lens, *, sm_scale: float,
                        k_scale=None, v_scale=None, ancestor_mask=None):
    """q5 [B, q_len, KV, group, d], pools [num_blocks, bs, KV, d],
    bt [B, nb] int32, lens [B] int32 -> [B, KV, gq, d] f32 (combined and
    normalized). Identical math to ``_flash_decode`` — the only change
    is the K/V index map, which resolves the grid's logical kv-block
    through the scalar-prefetched block table into a physical pool
    block. Out-of-range blocks re-point at the row's LAST needed logical
    block (the same Pallas revisit-skip as the contiguous kernel), so a
    short row costs its own length, not the table width.

    ``k_scale``/``v_scale`` ([num_blocks, bs, KV] f32): quantized pools
    — the scale column rides the same table-indirected index map and the
    cell dequantizes its block in the prologue.

    ``ancestor_mask`` ([B, q_len, q_len] f32, 1.0 = visible): per-row
    in-bundle visibility for tree-speculative verify; every cell of row
    b reads the same [q_len, q_len] block (index map pins (b, 0, 0)).
    None compiles the causal bundle exactly as before."""
    from ..quantization.intx import format_bound

    B, q_len, KV, group, d = q5.shape
    bs = kp.shape[1]
    nb = bt.shape[1]
    gq = q_len * group
    quant = k_scale is not None
    tree = ancestor_mask is not None

    def _idx_q(b, h, s, lens, bt):
        return (b, 0, h, 0, 0)

    def _idx_kv(b, h, s, lens, bt):
        last = jnp.maximum(pl.cdiv(lens[b], bs) - 1, 0)
        return (bt[b, jnp.minimum(s, last)], 0, h, 0)

    def _idx_scale(b, h, s, lens, bt):
        last = jnp.maximum(pl.cdiv(lens[b], bs) - 1, 0)
        return (bt[b, jnp.minimum(s, last)], 0, h)

    def _idx_mask(b, h, s, lens, bt):
        return (b, 0, 0)

    def _idx_out(b, h, s, lens, bt):
        return (b, h, s, 0, 0)

    in_specs = [
        pl.BlockSpec((1, q_len, 1, group, d), _idx_q),
        pl.BlockSpec((1, bs, 1, d), _idx_kv),
        pl.BlockSpec((1, bs, 1, d), _idx_kv),
    ]
    if quant:
        in_specs += [pl.BlockSpec((1, bs, 1), _idx_scale),
                     pl.BlockSpec((1, bs, 1), _idx_scale)]
    if tree:
        in_specs += [pl.BlockSpec((1, q_len, q_len), _idx_mask)]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, KV, nb),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, 1, 1, gq, d), _idx_out),
            pl.BlockSpec((1, 1, 1, gq, 1), _idx_out),
            pl.BlockSpec((1, 1, 1, gq, 1), _idx_out),
        ],
    )

    operands = (lens.astype(jnp.int32), bt.astype(jnp.int32), q5, kp, vp)
    if quant:
        bound = format_bound("int8" if kp.dtype == jnp.int8 else "fp8")
        operands += (k_scale.astype(jnp.float32),
                     v_scale.astype(jnp.float32))
        if tree:
            def _kern(lens_ref, bt_ref, q_ref, k_ref, v_ref, ks_ref,
                      vs_ref, am_ref, o_ref, m_ref, l_ref):
                del bt_ref
                _decode_kernel_quant(lens_ref, q_ref, k_ref, v_ref, ks_ref,
                                     vs_ref, o_ref, m_ref, l_ref,
                                     block_k=bs, sm_scale=sm_scale,
                                     q_len=q_len, group=group, bound=bound,
                                     mask_ref=am_ref)
        else:
            def _kern(lens_ref, bt_ref, q_ref, k_ref, v_ref, ks_ref,
                      vs_ref, o_ref, m_ref, l_ref):
                del bt_ref
                _decode_kernel_quant(lens_ref, q_ref, k_ref, v_ref, ks_ref,
                                     vs_ref, o_ref, m_ref, l_ref,
                                     block_k=bs, sm_scale=sm_scale,
                                     q_len=q_len, group=group, bound=bound)
    else:
        if tree:
            def _kern(lens_ref, bt_ref, q_ref, k_ref, v_ref, am_ref,
                      o_ref, m_ref, l_ref):
                del bt_ref
                _decode_kernel(lens_ref, q_ref, k_ref, v_ref, o_ref,
                               m_ref, l_ref, block_k=bs,
                               sm_scale=sm_scale, q_len=q_len,
                               group=group, mask_ref=am_ref)
        else:
            def _kern(lens_ref, bt_ref, q_ref, k_ref, v_ref, o_ref, m_ref,
                      l_ref):
                # bt_ref is consumed by the index maps; the cell body
                # itself is the contiguous kernel verbatim (same
                # lens-bounded masking)
                del bt_ref
                _decode_kernel(lens_ref, q_ref, k_ref, v_ref, o_ref,
                               m_ref, l_ref, block_k=bs,
                               sm_scale=sm_scale, q_len=q_len,
                               group=group)
    if tree:
        operands += (ancestor_mask.astype(jnp.float32),)

    o_p, m_p, l_p = pl.pallas_call(
        _kern,
        grid_spec=grid_spec,
        out_shape=(jax.ShapeDtypeStruct((B, KV, nb, gq, d), jnp.float32),
                   jax.ShapeDtypeStruct((B, KV, nb, gq, 1), jnp.float32),
                   jax.ShapeDtypeStruct((B, KV, nb, gq, 1), jnp.float32)),
        interpret=_interpret(),
        **_compiler_kwargs(),
    )(*operands)

    m_tot = m_p.max(axis=2)
    alpha = jnp.exp(m_p - m_tot[:, :, None])
    l_tot = (l_p * alpha).sum(axis=2)
    acc = (o_p * alpha).sum(axis=2)
    return acc / jnp.maximum(l_tot, 1e-30)


def paged_flash_decode_attention(q, k_pool, v_pool, block_table, positions,
                                 sm_scale=None, k_scale=None, v_scale=None,
                                 ancestor_mask=None):
    """Flash-decode attention over PAGED KV pools.

    q: [B, q_len, heads, d] (q_len <= MAX_PAGED_Q_LEN — the serving
    decode step OR one chunked-prefill bundle); k_pool/v_pool:
    [num_blocks, block_size, kv_heads, d] shared pools with this step's
    tokens ALREADY scattered at their table-resolved positions
    (``generation.paged_kv_cache_write``); ``block_table``: [B, nb]
    int32 — row b's logical block j lives in physical pool block
    ``block_table[b, j]``; ``positions``: per-row [B] int32 vector or
    scalar, same contract as ``flash_decode_attention``. Returns
    [B, q_len, heads, d] in q's dtype.

    QUANTIZED pools: pass the [num_blocks, block_size, kv_heads] f32
    absmax scale pools as ``k_scale``/``v_scale``
    (``make_paged_kv_pools(kv_format=...)``'s ``ks``/``vs``) — dequant
    happens in the kernel prologue, per block, behind the same
    table-indirected index map.

    TREE-SPECULATIVE bundles: ``ancestor_mask`` [B, q_len, q_len] bool
    (True = bundle node i may attend bundle node j) replaces ONLY the
    in-bundle causal mask — every query still attends all of its row's
    past KV (every committed position is an ancestor of every tree
    node). A causal lower-triangular mask reproduces the default path
    bitwise.
    """
    from ..core.tensor import Tensor
    from ..ops.dispatch import apply_op

    is_tensor = isinstance(q, Tensor)
    pos_arr = positions._data if isinstance(positions, Tensor) else positions
    bt_arr = block_table._data if isinstance(block_table, Tensor) \
        else block_table
    ks_arr, vs_arr = _unwrap(k_scale), _unwrap(v_scale)
    am_arr = _unwrap(ancestor_mask)
    if (ks_arr is None) != (vs_arr is None):
        raise ValueError("pass both k_scale and v_scale or neither")

    def _f(qa, ka, va):
        B, q_len, H, d = qa.shape
        KV = ka.shape[2]
        if H % KV:
            raise ValueError(f"heads ({H}) not a multiple of kv_heads ({KV})")
        group = H // KV
        scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(d)
        bt = jnp.asarray(bt_arr, jnp.int32)
        if bt.ndim != 2 or bt.shape[0] != B:
            raise ValueError(
                f"block_table must be [B={B}, nb], got {bt.shape}")
        pos = jnp.asarray(pos_arr, jnp.int32)
        if pos.ndim == 0:
            pos = jnp.broadcast_to(pos, (B,))
        max_len = bt.shape[1] * ka.shape[1]
        lens = jnp.minimum(pos + q_len, max_len)
        if am_arr is not None and tuple(am_arr.shape) != (B, q_len, q_len):
            raise ValueError(
                f"ancestor_mask must be [B={B}, q_len={q_len}, "
                f"q_len={q_len}], got {tuple(am_arr.shape)}")
        q5 = qa.reshape(B, q_len, KV, group, d)
        o = _paged_flash_decode(q5, ka, va, bt, lens, sm_scale=scale,
                                k_scale=ks_arr, v_scale=vs_arr,
                                ancestor_mask=am_arr)
        o = o.reshape(B, KV, q_len, group, d)
        o = jnp.transpose(o, (0, 2, 1, 3, 4)).reshape(B, q_len, H, d)
        return o.astype(qa.dtype)

    if is_tensor:
        return apply_op("paged_flash_decode_attention", _f, q, k_pool, v_pool)
    return _f(jnp.asarray(q), jnp.asarray(k_pool), jnp.asarray(v_pool))
