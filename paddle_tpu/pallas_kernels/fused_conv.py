"""Fused conv2d + BatchNorm + ReLU as Pallas TPU kernels.

Reference analogue: the fused conv+BN+ReLU epilogue paths in
paddle/phi/kernels/fusion/ and the cuDNN-backed conv epilogues in
phi/kernels/gpu/conv_kernel.cu. Why a hand-written kernel at all:
the round-5 xprof byte audit (benchmarks/resnet_byte_audit.json,
COVERAGE.md) showed every op of the ResNet-50 train step HBM-bound at
~660-680 GB/s with the convs already at MXU peak — XLA's fusion floor
moves each conv output once for the write, once more for the BN stat
reduce, and twice for the normalize+relu. SURVEY §7.1 reserves Pallas
for exactly this case ("where XLA fusion is insufficient").

Kernel design (NHWC, stride-1 convs — ResNet's hot shapes):

- The conv is computed as tap matmuls over the FLATTENED spatial form
  ``x2 = x.reshape(N*H*W, C)``: for kernel tap (di, dj) the
  contribution to output row ``m`` is
  ``x2[m + (di-1)*W + (dj-1)] @ w[di, dj]`` — a plain [rows, C] x
  [C, K] MXU matmul per tap (1 tap for 1x1, 9 for 3x3) accumulated into
  an fp32 VMEM scratch. Rows whose tap would cross an image edge (top/
  bottom row, left/right column — which in the flat layout would read
  the previous/next row or image) are zero-masked from an iota over the
  flat index, so no padded copy of the activation ever exists.
- A grid block is a whole number of images (block = nb*H*W rows), so
  every non-masked tap read stays inside the block: halo exchange is
  unnecessary by construction.
- The epilogue runs on the fp32 accumulator BEFORE the tile leaves
  VMEM:
  * inference: ``y = relu(acc * scale + shift)`` with the BN stats
    folded into per-channel scale/shift — conv+BN+ReLU is one HBM
    write.
  * training: the kernel writes the conv output once PLUS per-block
    channel partials (sum, sum-of-squares, reduced from the fp32
    accumulator) — the BN statistics pass costs zero extra HBM reads.
    The normalize+scale+shift(+relu) stays in XLA, which fuses it to
    one read+write, and the custom VJP reuses the existing
    ``nn/functional.py`` ``_bn_train_bwd`` formulation so autograd and
    the ``batch_norm`` path compose.

Backward: conv gradients are the standard transposed convolutions —
XLA's codegen for those is already at MXU peak (byte audit), so the
custom VJPs derive them with ``jax.vjp`` over the reference
``lax.conv_general_dilated`` expression rather than re-deriving kernel
code that could only tie.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # TPU-only module; CPU tests run in interpret mode
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

from .flash_attention import _compiler_kwargs, _dot_prec, _interpret

__all__ = ["fused_conv_bn_eval", "fused_conv_bn_train", "conv_stats",
           "conv_stats_pre", "bn_apply", "conv_qualifies"]


def conv_qualifies(kernel, stride, padding, dilation, groups) -> bool:
    """The shapes this kernel family covers: dense stride-1 NHWC 3x3
    (pad 1) and 1x1 (pad 0) convs — ResNet's FLOP bulk. Everything else
    falls back to the XLA path at the dispatch hook."""
    if groups != 1 or tuple(dilation) != (1, 1) or tuple(stride) != (1, 1):
        return False
    k, p = tuple(kernel), tuple(padding)
    return (k == (3, 3) and p == (1, 1)) or (k == (1, 1) and p == (0, 0))


def _pick_images_per_block(n: int, hw: int, c: int, k: int, itemsize: int) -> int:
    """Images per grid block: block rows = nb*H*W, so a valid tap read
    never leaves its block. Aim for ~2-4k rows (MXU-efficient M) while
    keeping the x tile + fp32 accumulator + y tile within a few MB of
    VMEM; nb must divide N (the greedy step-down always terminates at 1)."""
    bytes_per_row = c * itemsize + k * (4 + itemsize)
    target = max(1, min(4096, (6 << 20) // max(1, bytes_per_row)) // hw)
    nb = max(1, min(n, target))
    while n % nb:
        nb -= 1
    return nb


def _taps(kh: int, kw: int, w: int):
    """(di, dj, flat-row offset) per kernel tap, pad = (k-1)//2."""
    return [(di, dj, (di - (kh - 1) // 2) * w + (dj - (kw - 1) // 2))
            for di in range(kh) for dj in range(kw)]


def _conv_acc(x_ref, w_ref, acc_ref, *, taps, hw: int, h: int, w: int,
              kh: int, kw: int, pre=None, xn_ref=None):
    """Accumulate the conv of one [BM, C] block (BM a multiple of hw)
    into the fp32 scratch ``acc_ref`` and return its value.

    ``pre``: optional (scale_ref, shift_ref, relu_in) prologue — the
    PREVIOUS BatchNorm's normalize(+ReLU) applied to the x tile in VMEM
    before the tap matmuls, so the normalized activation never exists
    in HBM (chain fusion: this kernel reads the upstream conv's RAW
    output). For 3x3 the normalized block is staged once in ``xn_ref``
    so every tap slices it."""
    prec = _dot_prec(x_ref.dtype)

    def _prologue(xs):
        ps_ref, pb_ref, relu_in = pre
        xf = xs.astype(jnp.float32) * ps_ref[:] + pb_ref[:]
        if relu_in:
            xf = jnp.maximum(xf, 0.0)
        return xf.astype(xs.dtype)

    if kh == kw == 1:
        x = x_ref[:]
        if pre is not None:
            x = _prologue(x)
        acc_ref[:] = jnp.dot(x, w_ref[0],
                             preferred_element_type=jnp.float32,
                             precision=prec)
        return acc_ref[:]

    bm = x_ref.shape[0]
    if pre is not None:
        xn_ref[:] = _prologue(x_ref[:])
        x_ref = xn_ref
    acc_ref[:] = jnp.zeros_like(acc_ref)
    for t, (di, dj, off) in enumerate(taps):
        src = max(0, off)       # first x row this tap can read
        dst = max(0, -off)      # output row it contributes to
        ln = bm - abs(off)
        # validity of destination rows dst..dst+ln against the IMAGE
        # edges (periodic in the flat index, so block position is moot)
        d = dst + jax.lax.broadcasted_iota(jnp.int32, (ln, 1), 0)
        i = (d % hw) // w
        j = d % w
        valid = None
        if di == 0:
            valid = i >= 1
        elif di == kh - 1:
            valid = i <= h - 2
        if dj == 0:
            cnd = j >= 1
            valid = cnd if valid is None else (valid & cnd)
        elif dj == kw - 1:
            cnd = j <= w - 2
            valid = cnd if valid is None else (valid & cnd)
        xs = x_ref[src:src + ln]
        if valid is not None:
            xs = jnp.where(valid, xs, jnp.zeros_like(xs))
        acc_ref[dst:dst + ln] += jnp.dot(xs, w_ref[t],
                                         preferred_element_type=jnp.float32,
                                         precision=prec)
    return acc_ref[:]


def _epilogue_kernel(x_ref, w_ref, scale_ref, shift_ref, o_ref, acc_ref, *,
                     taps, hw, h, w, kh, kw, relu):
    acc = _conv_acc(x_ref, w_ref, acc_ref, taps=taps, hw=hw, h=h, w=w,
                    kh=kh, kw=kw)
    y = acc * scale_ref[:] + shift_ref[:]  # [1, K] blocks broadcast over rows
    if relu:
        y = jnp.maximum(y, 0.0)
    o_ref[:] = y.astype(o_ref.dtype)


def _stats_kernel(x_ref, w_ref, o_ref, s1_ref, s2_ref, acc_ref, *,
                  taps, hw, h, w, kh, kw, pre=None, xn_ref=None):
    acc = _conv_acc(x_ref, w_ref, acc_ref, taps=taps, hw=hw, h=h, w=w,
                    kh=kh, kw=kw, pre=pre, xn_ref=xn_ref)
    o_ref[:] = acc.astype(o_ref.dtype)
    # channel partials straight off the fp32 accumulator: the BN stat
    # pass never re-reads the conv output from HBM (and is MORE accurate
    # than reducing the rounded bf16 output — cf. the single-pass-stats
    # note at _bn_train_fwd in nn/functional.py)
    s1_ref[:] = jnp.sum(acc, axis=0, keepdims=True)
    s2_ref[:] = jnp.sum(acc * acc, axis=0, keepdims=True)


def _prep(x, w):
    n, h, w_sp, c = x.shape
    k, c_w, kh, kw = w.shape
    if c_w != c:
        raise ValueError(f"fused conv: weight in_channels {c_w} != input {c}")
    hw = h * w_sp
    # OIHW -> [taps, C, K]: tap-major planes for the kernel's matmul loop
    w_t = jnp.transpose(w, (2, 3, 1, 0)).reshape(kh * kw, c, k)
    x2 = x.reshape(n * hw, c)  # contiguous: free reshape, no HBM copy
    bm = _pick_images_per_block(n, hw, c, k, x.dtype.itemsize) * hw
    return x2, w_t, (n, h, w_sp, c, k, hw, kh, kw, bm)


def _pallas_epilogue(x, w, scale, shift, relu):
    x2, w_t, (n, h, w_sp, c, k, hw, kh, kw, bm) = _prep(x, w)
    m = x2.shape[0]
    kern = functools.partial(_epilogue_kernel, taps=_taps(kh, kw, w_sp),
                             hw=hw, h=h, w=w_sp, kh=kh, kw=kw, relu=relu)
    y = pl.pallas_call(
        kern,
        out_shape=jax.ShapeDtypeStruct((m, k), x.dtype),
        # pt-analysis: disable=pallas-block-divide -- bm = nb * hw where
        # _pick_images_per_block steps nb down until it divides N, so bm
        # always divides m = N * hw (invariant lives in _prep)
        grid=(m // bm,),
        in_specs=[
            pl.BlockSpec((bm, c), lambda i: (i, 0)),
            pl.BlockSpec((kh * kw, c, k), lambda i: (0, 0, 0)),
            pl.BlockSpec((1, k), lambda i: (0, 0)),
            pl.BlockSpec((1, k), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((bm, k), lambda i: (i, 0)),
        scratch_shapes=[pltpu.VMEM((bm, k), jnp.float32)],
        interpret=_interpret(),
        **_compiler_kwargs(),
    )(x2, w_t, scale.reshape(1, k).astype(jnp.float32),
      shift.reshape(1, k).astype(jnp.float32))
    return y.reshape(n, h, w_sp, k)


def _pallas_stats(x, w, pre=None):
    """Conv + channel-partial sums; ``pre``: optional
    (scale[C], shift[C], relu_in) prologue normalize of x in VMEM."""
    x2, w_t, (n, h, w_sp, c, k, hw, kh, kw, bm) = _prep(x, w)
    m = x2.shape[0]
    # pt-analysis: disable=pallas-block-divide -- bm = nb * hw where
    # _pick_images_per_block steps nb down until it divides N, so bm
    # always divides m = N * hw (invariant lives in _prep)
    g = m // bm
    in_specs = [
        pl.BlockSpec((bm, c), lambda i: (i, 0)),
        pl.BlockSpec((kh * kw, c, k), lambda i: (0, 0, 0)),
    ]
    args = [x2, w_t]
    scratch = [pltpu.VMEM((bm, k), jnp.float32)]
    if pre is None:
        kern = functools.partial(_stats_kernel, taps=_taps(kh, kw, w_sp),
                                 hw=hw, h=h, w=w_sp, kh=kh, kw=kw)
    else:
        ps, pb, relu_in = pre
        in_specs += [pl.BlockSpec((1, c), lambda i: (0, 0)),
                     pl.BlockSpec((1, c), lambda i: (0, 0))]
        args += [ps.reshape(1, c).astype(jnp.float32),
                 pb.reshape(1, c).astype(jnp.float32)]
        if kh != 1:
            scratch.append(pltpu.VMEM((bm, c), x.dtype))

        def kern(x_ref, w_ref, ps_ref, pb_ref, o_ref, s1_ref, s2_ref,
                 acc_ref, *xn):
            _stats_kernel(x_ref, w_ref, o_ref, s1_ref, s2_ref, acc_ref,
                          taps=_taps(kh, kw, w_sp), hw=hw, h=h, w=w_sp,
                          kh=kh, kw=kw, pre=(ps_ref, pb_ref, relu_in),
                          xn_ref=xn[0] if xn else None)

    out, s1, s2 = pl.pallas_call(
        kern,
        out_shape=(jax.ShapeDtypeStruct((m, k), x.dtype),
                   jax.ShapeDtypeStruct((g, k), jnp.float32),
                   jax.ShapeDtypeStruct((g, k), jnp.float32)),
        grid=(g,),
        in_specs=in_specs,
        out_specs=(pl.BlockSpec((bm, k), lambda i: (i, 0)),
                   pl.BlockSpec((1, k), lambda i: (i, 0)),
                   pl.BlockSpec((1, k), lambda i: (i, 0))),
        scratch_shapes=scratch,
        interpret=_interpret(),
        **_compiler_kwargs(),
    )(*args)
    return out.reshape(n, h, w_sp, k), jnp.sum(s1, 0), jnp.sum(s2, 0)


def _xla_conv(x, w):
    pad = ((1, 1), (1, 1)) if w.shape[2] == 3 else ((0, 0), (0, 0))
    return jax.lax.conv_general_dilated(
        x, w, (1, 1), pad, dimension_numbers=("NHWC", "OIHW", "NHWC"))


# ---------------------------------------------------------------------------
# inference: conv + folded BN scale/shift (+ReLU) in one kernel
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def fused_conv_bn_eval(x, w, scale, shift, relu=False):
    """``relu(conv2d(x, w) * scale + shift)`` with the epilogue applied
    in VMEM. x: [N, H, W, C]; w: OIHW (3x3 pad-1 or 1x1 pad-0, stride
    1); scale/shift: [K] (BN running stats pre-folded by the caller).
    Conv+BN+ReLU costs one HBM read of x and one write of y."""
    return _pallas_epilogue(x, w, scale, shift, relu)


def _eval_ref(x, w, scale, shift, relu):
    y = _xla_conv(x, w) * scale + shift
    return jnp.maximum(y, 0.0).astype(x.dtype) if relu else y.astype(x.dtype)


def _eval_fwd(x, w, scale, shift, relu):
    return fused_conv_bn_eval(x, w, scale, shift, relu), (x, w, scale, shift)


def _eval_bwd(relu, res, dy):
    # rare path (grads through frozen-stats BN): the XLA composition's
    # vjp IS the fused forward's derivative
    x, w, scale, shift = res
    _, vjp = jax.vjp(lambda *a: _eval_ref(*a, relu), x, w, scale, shift)
    return vjp(dy)


fused_conv_bn_eval.defvjp(_eval_fwd, _eval_bwd)


# ---------------------------------------------------------------------------
# training: three composable custom-vjp pieces.
#
#   conv_stats(x, w)                 -> (conv_out, mean, var)
#   conv_stats_pre(co_p, m_p, v_p, gp, bp, w, relu_in, eps_p)
#                                    -> (conv_out, mean, var)
#       — same, but the input is the UPSTREAM conv's raw output and the
#       upstream BN's normalize(+ReLU) runs as a VMEM prologue, so the
#       normalized activation never touches HBM (chain fusion).
#   bn_apply(co, m, v, gamma, beta)  -> y
#       — the normalize the model actually consumes; its VJP is the
#       existing _bn_train_bwd formulation from nn/functional.py.
#
# Gradient contract: bn_apply's dco is the FULL batch-norm backward
# (it folds the stats' dependence on co), so bn_apply returns ZERO
# cotangents for m/v; the m/v outputs of conv_stats* carry gradients
# only for their OTHER consumer — the next unit's prologue — which
# conv_stats*'s vjp (jax.vjp over the XLA reference composition)
# handles exactly. No term is dropped, none is double-counted.
# ---------------------------------------------------------------------------


def _moments_ref(co):
    cof = co.astype(jnp.float32)
    m = jnp.mean(cof, axis=(0, 1, 2))
    v = jnp.maximum(jnp.mean(cof * cof, axis=(0, 1, 2)) - m * m, 0.0)
    return m, v


def _stats_from_partials(x, s1, s2):
    cnt = x.shape[0] * x.shape[1] * x.shape[2]
    m = s1 / cnt
    v = jnp.maximum(s2 / cnt - m * m, 0.0)  # single-pass stats, fp32 acc
    return m, v


@jax.custom_vjp
def conv_stats(x, w):
    """Pallas conv whose epilogue also emits the output's channel mean/
    var — the BN statistics pass costs zero extra HBM reads."""
    co, s1, s2 = _pallas_stats(x, w)
    m, v = _stats_from_partials(x, s1, s2)
    return co, m, v


def _conv_stats_ref(x, w):
    co = _xla_conv(x, w)
    return (co,) + _moments_ref(co)


def _conv_stats_fwd(x, w):
    return conv_stats(x, w), (x, w)


def _conv_stats_bwd(res, cts):
    x, w = res
    _, vjp = jax.vjp(_conv_stats_ref, x, w)
    return vjp(cts)


conv_stats.defvjp(_conv_stats_fwd, _conv_stats_bwd)


def _fold_bn(m, v, gamma, beta, eps):
    scale = gamma.astype(jnp.float32) * jax.lax.rsqrt(v.astype(jnp.float32) + eps)
    return scale, beta.astype(jnp.float32) - m.astype(jnp.float32) * scale


@functools.partial(jax.custom_vjp, nondiff_argnums=(6, 7))
def conv_stats_pre(co_p, m_p, v_p, gp, bp, w, relu_in=True, eps_p=1e-5):
    """conv_stats over ``normalize(co_p; m_p, v_p, gp, bp)`` (+ReLU),
    with the normalize applied as a VMEM prologue: the upstream BN's
    output never materializes in HBM — this kernel reads the upstream
    conv's RAW output instead."""
    ps, pb = _fold_bn(m_p, v_p, gp, bp, eps_p)
    co, s1, s2 = _pallas_stats(co_p, w, pre=(ps, pb, relu_in))
    m, v = _stats_from_partials(co_p, s1, s2)
    return co, m, v


def _conv_stats_pre_ref(co_p, m_p, v_p, gp, bp, w, relu_in, eps_p):
    ps, pb = _fold_bn(m_p, v_p, gp, bp, eps_p)
    xn = co_p.astype(jnp.float32) * ps + pb
    if relu_in:
        xn = jnp.maximum(xn, 0.0)
    co = _xla_conv(xn.astype(co_p.dtype), w)
    return (co,) + _moments_ref(co)


def _conv_stats_pre_fwd(co_p, m_p, v_p, gp, bp, w, relu_in, eps_p):
    return (conv_stats_pre(co_p, m_p, v_p, gp, bp, w, relu_in, eps_p),
            (co_p, m_p, v_p, gp, bp, w))


def _conv_stats_pre_bwd(relu_in, eps_p, res, cts):
    _, vjp = jax.vjp(
        lambda *a: _conv_stats_pre_ref(*a, relu_in, eps_p), *res)
    return vjp(cts)


conv_stats_pre.defvjp(_conv_stats_pre_fwd, _conv_stats_pre_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5,))
def bn_apply(co, m, v, gamma, beta, epsilon=1e-5):
    """BN normalize of a conv output given its (already computed) batch
    stats. VJP = the existing _bn_train_bwd formulation (dco carries the
    full stats chain; m/v get zero cotangents — see the contract above)."""
    r = jax.lax.rsqrt(v.astype(jnp.float32) + epsilon)
    g = r * gamma.astype(jnp.float32)
    shift = beta.astype(jnp.float32) - m.astype(jnp.float32) * g
    return (co.astype(jnp.float32) * g + shift).astype(co.dtype)


def _bn_apply_fwd(co, m, v, gamma, beta, epsilon):
    y = bn_apply(co, m, v, gamma, beta, epsilon)
    r = jax.lax.rsqrt(v.astype(jnp.float32) + epsilon)
    return y, (co, m, r, gamma, beta)


def _bn_apply_bwd(epsilon, res, dy):
    co, m, r, gamma, beta = res
    from ..nn.functional import _bn_train_bwd  # lazy: avoids import cycle

    k = co.shape[-1]
    bshape = (1, 1, 1, k)
    dco, dgamma, dbeta = _bn_train_bwd(
        (0, 1, 2), epsilon,
        (co, m.astype(jnp.float32).reshape(bshape), r.reshape(bshape),
         gamma.reshape(bshape), beta.reshape(bshape)), dy)
    zeros = jnp.zeros_like(m)  # m and v share shape/dtype
    return (dco.astype(co.dtype), zeros, zeros,
            dgamma.reshape(k).astype(gamma.dtype),
            dbeta.reshape(k).astype(beta.dtype))


bn_apply.defvjp(_bn_apply_fwd, _bn_apply_bwd)


def fused_conv_bn_train(x, w, gamma, beta, epsilon=1e-5):
    """Convenience composition: (y, batch_mean, batch_var) for one
    unchained conv+BN unit (tests and the microbench use this)."""
    co, m, v = conv_stats(x, w)
    return bn_apply(co, m, v, gamma, beta, epsilon), m, v
