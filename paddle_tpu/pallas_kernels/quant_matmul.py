"""Weight-only quantized matmul with dequant fused into the Pallas
prologue.

The decode step of a served model is weight-streaming-bound: every token
re-reads the full q/k/v/o + MLP + lm_head weights from HBM. Storing them
int8 (or fp8 e4m3 where the dtype exists) halves that stream — IF the
dequant never materializes a full-width weight copy. The XLA path
(nn/quant.py ``weight_only_linear``) relies on fusion + an
optimization_barrier to get that; this kernel makes it structural:

- grid (m_blocks, n_blocks, k_blocks), k innermost/sequential;
- each cell's PROLOGUE loads one [bn, bk] int8/fp8 weight block and
  widens it to the activation dtype IN VMEM (the narrow values are what
  crossed HBM), then one MXU matmul accumulates into a f32 [bm, bn]
  output block;
- the final k step applies the per-output-channel scale to the
  accumulator — mathematically identical to scaling the weights
  (the scale is per output column), one multiply per output element
  instead of one per weight element.

Scale convention matches ``nn.quant.weight_quantize``: ``scale`` is the
DEQUANT MULTIPLIER (absmax / 127 for int8, absmax / 448 for fp8), so
``w ≈ q * scale[:, None]``.

Dispatch: ``weight_only_linear`` consults ``quant_matmul_dispatch``
(env ``PADDLE_TPU_QUANT_WEIGHTS``; default on for TPU, opt-in on CPU
where Pallas interprets) and falls back to the fused XLA form with the
reason counted — ``paddle_tpu_quant_matmul_{hits,fallbacks}_total`` —
the fused-conv/flash-decode instrumentation pattern.
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # TPU-only module; CPU tests run in interpret mode
    from jax.experimental.pallas import tpu as pltpu

    _HAS_TPU_PALLAS = True
except ImportError:  # pragma: no cover
    pltpu = None
    _HAS_TPU_PALLAS = False

from ..observability.metrics import _ENABLED as _obs_on
from ..observability.metrics import counter as _obs_counter
from ._blocks import pick_block
from .flash_attention import _dot_prec, _interpret

__all__ = ["quant_matmul", "quant_matmul_enabled", "quant_matmul_dispatch"]

_QUANT_WEIGHTS_ENV = "PADDLE_TPU_QUANT_WEIGHTS"

_qm_hits = _obs_counter(
    "paddle_tpu_quant_matmul_hits_total",
    "matmuls dispatched to the Pallas weight-dequant kernel",
    ("fmt",))
_qm_fallbacks = _obs_counter(
    "paddle_tpu_quant_matmul_fallbacks_total",
    "weight-only matmuls on the XLA dequant-fusion fallback path",
    ("reason",))


def quant_matmul_enabled() -> bool:
    """Env-gated: PADDLE_TPU_QUANT_WEIGHTS=1/0 forces it; default on for
    TPU backends (where the kernel is compiled) and off on CPU (where
    Pallas runs in the slow interpreter — tests opt in explicitly)."""
    v = os.environ.get(_QUANT_WEIGHTS_ENV)
    if v is not None:
        return v != "0"
    return jax.default_backend() == "tpu"


def quant_matmul_dispatch(*, dtype, fmt: str) -> bool:
    """True -> run the Pallas ``quant_matmul``; False -> the XLA
    dequant-fusion fallback, reason counted. Python-side, so under jit
    this costs nothing after the first trace."""
    reason = None
    if not quant_matmul_enabled():
        reason = "disabled"
    elif not _HAS_TPU_PALLAS:  # pragma: no cover — jax without pallas.tpu
        reason = "no_tpu_pallas"
    elif str(dtype) not in ("float32", "bfloat16"):
        reason = "dtype"
    else:
        from ..core.autograd import is_grad_enabled

        if is_grad_enabled():
            # forward-only kernel (quantized weights are a serving
            # artifact; QAT trains through the fake-quant STE path)
            reason = "grad_mode"
    if reason is None:
        if _obs_on[0]:
            _qm_hits.labels(fmt).inc()
        return True
    if _obs_on[0]:
        _qm_fallbacks.labels(reason).inc()
    return False


_COMPILER_PARAMS = None


def _compiler_kwargs():
    """m/n grid dims are embarrassingly parallel; the k dim accumulates
    into the revisited output block and must stay sequential."""
    if not _HAS_TPU_PALLAS or _interpret():
        return {}
    global _COMPILER_PARAMS
    if _COMPILER_PARAMS is None:
        params_cls = (getattr(pltpu, "CompilerParams", None)
                      or getattr(pltpu, "TPUCompilerParams", None))
        if params_cls is None:  # pragma: no cover
            raise RuntimeError(
                "paddle_tpu quant matmul needs pallas TPU compiler params "
                f"(neither CompilerParams nor TPUCompilerParams on "
                f"jax=={jax.__version__})")
        _COMPILER_PARAMS = params_cls(
            dimension_semantics=("parallel", "parallel", "arbitrary"))
    return {"compiler_params": _COMPILER_PARAMS}


def _qmm_kernel(x_ref, w_ref, s_ref, o_ref, *, nk: int):
    """One (m block, n block, k step) cell.

    Refs (blocked):
      x [bm, bk]        — activation block
      w [bn, bk] int8/fp8 — weight block, NARROW over HBM
      s [1, bn] f32     — per-output-channel dequant multipliers
      o [bm, bn] f32    — accumulator, revisited across the k steps
    """
    @pl.when(pl.program_id(2) == 0)
    def _zero():
        o_ref[...] = jnp.zeros_like(o_ref)

    x = x_ref[...]
    # dequant prologue: widen the narrow weight block to the activation
    # dtype in VMEM; the per-channel scale moves to the accumulator
    # epilogue below (identical math, n multiplies instead of n*k)
    w = w_ref[...].astype(x.dtype)
    o_ref[...] += jnp.dot(x, w.T, preferred_element_type=jnp.float32,
                          precision=_dot_prec(x.dtype))

    @pl.when(pl.program_id(2) == nk - 1)
    def _scale():
        o_ref[...] = o_ref[...] * s_ref[...]


def quant_matmul(x, qweight, scale, block_m: int = 128,
                 block_n: int = 256, block_k: int = 512):
    """``x [..., K] @ dequant(qweight [N, K]).T`` -> [..., N] in x's
    dtype, dequant fused into the weight-load prologue. ``scale`` [N]
    f32 is the per-output-channel dequant multiplier
    (``nn.quant.weight_quantize``'s convention)."""
    from ..core.tensor import Tensor
    from ..ops.dispatch import apply_op

    is_tensor = isinstance(x, Tensor)

    def _f(xa, qa, sa):
        lead = xa.shape[:-1]
        K = xa.shape[-1]
        N = qa.shape[0]
        if qa.shape[1] != K:
            raise ValueError(
                f"qweight must be [N, K={K}], got {qa.shape}")
        xm = xa.reshape(-1, K)
        m = xm.shape[0]
        bm = pick_block(m, block_m)
        bn = pick_block(N, block_n)
        bk = pick_block(K, block_k)
        nk = K // bk
        s2 = sa.reshape(1, N).astype(jnp.float32)

        def _idx_x(i, j, k):
            return (i, k)

        def _idx_w(i, j, k):
            return (j, k)

        def _idx_s(i, j, k):
            return (0, j)

        def _idx_o(i, j, k):
            return (i, j)

        def kern(x_ref, w_ref, s_ref, o_ref):
            _qmm_kernel(x_ref, w_ref, s_ref, o_ref, nk=nk)

        out = pl.pallas_call(
            kern,
            grid=(m // bm, N // bn, nk),
            in_specs=[
                pl.BlockSpec((bm, bk), _idx_x),
                pl.BlockSpec((bn, bk), _idx_w),
                pl.BlockSpec((1, bn), _idx_s),
            ],
            out_specs=pl.BlockSpec((bm, bn), _idx_o),
            out_shape=jax.ShapeDtypeStruct((m, N), jnp.float32),
            interpret=_interpret(),
            **_compiler_kwargs(),
        )(xm, qa, s2)
        return out.reshape(lead + (N,)).astype(xa.dtype)

    if is_tensor:
        return apply_op("quant_matmul", _f, x, qweight, scale)
    return _f(jnp.asarray(x), jnp.asarray(qweight), jnp.asarray(scale))
