"""Shared block-size selection for the Pallas attention kernels.

One chooser for flash_attention.py (prefill/training) and
decode_attention.py (flash decode) so the PR-1 non-divisible-length
fix-up cannot drift between kernels: the wanted block is clamped to the
dimension and halved until it divides it exactly (Pallas grids here
assume exact tiling; the final fallback of 1 always divides).
"""

from __future__ import annotations


def pick_block(s: int, want: int) -> int:
    """Largest power-of-two-ish divisor of ``s`` at most ``want``.

    Starts from ``min(want, s)`` and halves until the candidate divides
    ``s``. For the usual power-of-two sequence lengths this returns
    ``want`` (or ``s`` when shorter); for awkward lengths (the ring hop
    sizes PR 1 hit, odd KV capacities) it degrades gracefully instead of
    producing a grid that drops the tail.
    """
    b = min(want, s)
    while s % b and b > 1:
        b //= 2
    return b
