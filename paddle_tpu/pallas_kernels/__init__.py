"""Hand-written Pallas TPU kernels for the ops XLA fusion can't cover.

Reference analogue: paddle/phi/kernels/fusion/ (fused attention/transformer
CUDA kernels) and the dynloaded flash-attention library
(phi/kernels/gpu/flash_attn_kernel.cu).
"""

from .decode_attention import (flash_decode_attention,
                               paged_flash_decode_attention)
from .flash_attention import flash_attention
from .fused_conv import fused_conv_bn_eval, fused_conv_bn_train
from .quant_matmul import quant_matmul
