"""Flash attention as Pallas TPU kernels (forward + blockwise backward).

Reference analogue: phi/kernels/gpu/flash_attn_kernel.cu and
phi/kernels/gpu/flash_attn_grad_kernel.cu (FlashAttention-2 via dynloaded
libflashattn, fwd/bwd/varlen). TPU-native design:

- forward: online-softmax over k-blocks held in VMEM, q-blocks on the
  grid; stores the per-row logsumexp (LSE) for the backward.
- backward: two tiled kernels, exactly the FlashAttention-2 recipe —
  a dK/dV kernel (grid over k-blocks, loop over q-blocks) and a dQ
  kernel (grid over q-blocks, loop over k-blocks), both recomputing
  p = exp(s - lse) blockwise so no O(s²) tensor is ever materialized.
  delta = rowsum(dO * O) is a cheap fused XLA precompute.
- causal blocks beyond the diagonal are skipped entirely (both passes).
- varlen: packed sequences expressed as segment ids (cu_seqlens ->
  segments), masked in-kernel — the TPU equivalent of the reference's
  flash_attn_varlen path.

Matmuls keep the input dtype (bf16 on the MXU fast path) with fp32
accumulation via preferred_element_type; softmax/statistics run in fp32.

Layout: [batch, seq, heads, head_dim] (Paddle convention); internally
blocked as [b*h, s, d].
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # TPU-only module; CPU tests run in interpret mode
    from jax.experimental.pallas import tpu as pltpu

    _HAS_TPU_PALLAS = True
except ImportError:  # pragma: no cover
    pltpu = None
    _HAS_TPU_PALLAS = False

_VMEM_PARAMS = None


def _vmem_params():
    # Raise Mosaic's 16 MB default scoped-VMEM cap: the backward kernels
    # hold full-sequence q/do (dK/dV pass) and k/v (dQ pass) refs, which
    # at seq >= 8192 exceed 16 MB while the chip has 128 MB VMEM. Looked
    # up lazily at first kernel launch so that a renamed class on a future
    # jax only breaks the TPU compile path, not `import paddle_tpu`
    # (interpret/CPU mode never needs the cap). A constructor failure must
    # still SURFACE here: silently dropping the cap would break the
    # documented seq-8192 support. Older jax spells it TPUCompilerParams.
    global _VMEM_PARAMS
    if _VMEM_PARAMS is None:
        params_cls = (getattr(pltpu, "CompilerParams", None)
                      or getattr(pltpu, "TPUCompilerParams", None))
        if params_cls is None:
            raise RuntimeError(
                "paddle_tpu flash attention needs pallas TPU compiler params "
                "(jax.experimental.pallas.tpu.CompilerParams or "
                "TPUCompilerParams) to raise the scoped-VMEM cap for "
                "seq>=8192 support; this jax version exposes neither. "
                f"jax=={jax.__version__}")
        _VMEM_PARAMS = params_cls(vmem_limit_bytes=100 * 1024 * 1024)
    return _VMEM_PARAMS


def _compiler_kwargs():
    if not _HAS_TPU_PALLAS or _interpret():
        return {}
    return {"compiler_params": _vmem_params()}

NEG_INF = -1e30


def _dot_prec(dt):
    """Kernel dot precision: f32 operands inherit the global setting
    (the TPU test lane forces 'highest' for oracle comparisons), while
    half-precision operands pin DEFAULT — Mosaic rejects an fp32-precision
    contraction on bf16 vectors ("Bad lhs type"), and bf16-operand/
    f32-accumulate IS this kernel's contract."""
    return None if dt == jnp.float32 else jax.lax.Precision.DEFAULT


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------


def _fwd_kernel(q_ref, k_ref, v_ref, segc_ref, segr_ref, o_ref, lse_ref, *,
                block_k: int, sm_scale: float, causal: bool, q_block: int,
                seq_len: int, varlen: bool):
    qi = pl.program_id(1)
    q = q_ref[0]  # [block_q, d] — input dtype feeds the MXU
    bq = q.shape[0]

    m = jnp.full((bq,), NEG_INF, jnp.float32)
    l = jnp.zeros((bq,), jnp.float32)
    acc = jnp.zeros((bq, q_ref.shape[-1]), jnp.float32)
    if varlen:
        seg_q = segc_ref[0]  # (block_q, 1)

    num_kb = seq_len // block_k
    if causal:
        # only k-blocks up to the diagonal contribute
        last_kb = jnp.minimum(num_kb, ((qi + 1) * q_block + block_k - 1) // block_k)
    else:
        last_kb = num_kb

    def body(kb, carry):
        m, l, acc = carry
        k = k_ref[0, pl.ds(kb * block_k, block_k), :]
        v = v_ref[0, pl.ds(kb * block_k, block_k), :]
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32,
        precision=_dot_prec(q.dtype)) * sm_scale
        mask = None
        if causal:
            qpos = qi * q_block + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 0)
            kpos = kb * block_k + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 1)
            mask = qpos >= kpos
        if varlen:
            seg_k = _seg_row_slice(segr_ref, kb, block_k)  # (1, bk)
            same = seg_q == seg_k
            mask = same if mask is None else (mask & same)
        if mask is not None:
            s = jnp.where(mask, s, NEG_INF)
        m_new = jnp.maximum(m, s.max(axis=-1))
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + p.sum(axis=-1)
        acc_new = acc * alpha[:, None] + jnp.dot(
            p.astype(v.dtype), v, preferred_element_type=jnp.float32,
        precision=_dot_prec(q.dtype))
        return m_new, l_new, acc_new

    m, l, acc = jax.lax.fori_loop(0, last_kb, body, (m, l, acc))
    l_safe = jnp.maximum(l, 1e-30)
    o_ref[0] = (acc / l_safe[:, None]).astype(o_ref.dtype)
    lse_ref[0] = (m + jnp.log(l_safe))[:, None]



def _seg_row_slice(segr_ref, start_block: int, block: int):
    """Slice a (1, 1, s) segment-row ref along lanes. Mosaic requires the
    lane offset to be provably a multiple of 128, hence the hint — varlen
    callers must use 128-multiple blocks (enforced in _check_varlen_blocks)."""
    off = pl.multiple_of(start_block * block, 128)
    return segr_ref[0, :, pl.ds(off, block)]  # (1, block)


def _check_varlen_blocks(s: int, block_q: int, block_k: int):
    if _interpret():
        return  # CPU interpret mode has no lane-tiling constraint
    if block_q % 128 or block_k % 128 or s % 128:
        raise ValueError(
            f"varlen flash attention on TPU requires seq ({s}) and blocks "
            f"(q={block_q}, k={block_k}) to be multiples of 128; pad the "
            "packed stream (flash_attn_varlen does this automatically)")


def _varlen_specs(seg, s: int, *, col_block=None):
    """(extra_specs, extra_args) for the two segment-id orientations:
    column [bh, s, 1] for q rows (optionally blocked per q-block) and
    row [bh, 1, s] for k columns."""
    if col_block is None:
        col = pl.BlockSpec((1, s, 1), lambda b, i: (b, 0, 0))
    else:
        col = pl.BlockSpec((1, col_block, 1), lambda b, i: (b, i, 0))
    row = pl.BlockSpec((1, 1, s), lambda b, i: (b, 0, 0))
    return [col, row], [seg[:, :, None], seg[:, None, :]]


def _flash_fwd(q, k, v, seg, *, causal: bool, sm_scale: float, block_q: int,
               block_k: int):
    bh, s, d = q.shape
    # clamp AND make the tiling exact: a block that does not divide s
    # would silently drop the tail rows of the (bh, s // block) grid.
    # The public entries already pick_block, but the invariant belongs
    # where the grid is built (pt-analysis pallas-block-divide).
    block_q = _pick_block(s, block_q)
    block_k = _pick_block(s, block_k)
    varlen = seg is not None
    grid = (bh, s // block_q)
    in_specs = [
        pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
        pl.BlockSpec((1, s, d), lambda b, i: (b, 0, 0)),
        pl.BlockSpec((1, s, d), lambda b, i: (b, 0, 0)),
    ]
    args = [q, k, v]
    if varlen:
        _check_varlen_blocks(s, block_q, block_k)
        sp, ar = _varlen_specs(seg, s, col_block=block_q)
        in_specs += sp
        args += ar

    def kern(q_ref, k_ref, v_ref, *rest):
        if varlen:
            segc_ref, segr_ref, o_ref, lse_ref = rest
        else:
            (o_ref, lse_ref) = rest
            segc_ref = segr_ref = None
        _fwd_kernel(q_ref, k_ref, v_ref, segc_ref, segr_ref, o_ref, lse_ref,
                    block_k=block_k, sm_scale=sm_scale, causal=causal,
                    q_block=block_q, seq_len=s, varlen=varlen)
    out, lse = pl.pallas_call(
        kern,
        out_shape=(jax.ShapeDtypeStruct((bh, s, d), q.dtype),
                   jax.ShapeDtypeStruct((bh, s, 1), jnp.float32)),
        grid=grid,
        in_specs=in_specs,
        out_specs=(pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
                   pl.BlockSpec((1, block_q, 1), lambda b, i: (b, i, 0))),
        interpret=_interpret(),
        **_compiler_kwargs(),
    )(*args)
    return out, lse[..., 0]


# ---------------------------------------------------------------------------
# backward: dK/dV kernel — grid over k-blocks, loop over q-blocks
# ---------------------------------------------------------------------------


def _bwd_dkdv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                     segc_ref, segr_ref, dk_ref, dv_ref, *, block_q: int,
                     sm_scale: float, causal: bool, k_block: int,
                     seq_len: int, varlen: bool):
    ki = pl.program_id(1)
    k = k_ref[0]  # [block_k, d]
    v = v_ref[0]
    bk = k.shape[0]
    d = k.shape[-1]

    dk = jnp.zeros((bk, d), jnp.float32)
    dv = jnp.zeros((bk, d), jnp.float32)
    if varlen:
        seg_k = _seg_row_slice(segr_ref, ki, k_block)  # (1, bk)

    num_qb = seq_len // block_q
    # causal: q-blocks strictly before the diagonal see no keys of this block
    first_qb = (ki * k_block) // block_q if causal else 0

    def body(qb, carry):
        dk, dv = carry
        q = q_ref[0, pl.ds(qb * block_q, block_q), :]
        do = do_ref[0, pl.ds(qb * block_q, block_q), :]
        lse = lse_ref[0, pl.ds(qb * block_q, block_q), :]      # (bq, 1)
        delta = delta_ref[0, pl.ds(qb * block_q, block_q), :]  # (bq, 1)
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32,
        precision=_dot_prec(q.dtype)) * sm_scale
        mask = None
        if causal:
            qpos = qb * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, bk), 0)
            kpos = ki * k_block + jax.lax.broadcasted_iota(jnp.int32, (block_q, bk), 1)
            mask = qpos >= kpos
        if varlen:
            seg_q = segc_ref[0, pl.ds(qb * block_q, block_q), :]  # (bq, 1)
            same = seg_q == seg_k
            mask = same if mask is None else (mask & same)
        if mask is not None:
            s = jnp.where(mask, s, NEG_INF)
        p = jnp.exp(s - lse)  # normalized probabilities
        dv = dv + jnp.dot(p.astype(do.dtype).T, do,
                          preferred_element_type=jnp.float32,
        precision=_dot_prec(q.dtype))
        dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32,
        precision=_dot_prec(q.dtype))
        ds = p * (dp - delta) * sm_scale
        dk = dk + jnp.dot(ds.astype(q.dtype).T, q,
                          preferred_element_type=jnp.float32,
        precision=_dot_prec(q.dtype))
        return dk, dv

    dk, dv = jax.lax.fori_loop(first_qb, num_qb, body, (dk, dv))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


# ---------------------------------------------------------------------------
# backward: dQ kernel — grid over q-blocks, loop over k-blocks
# ---------------------------------------------------------------------------


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                   segc_ref, segr_ref, dq_ref, *, block_k: int,
                   sm_scale: float, causal: bool, q_block: int,
                   seq_len: int, varlen: bool):
    qi = pl.program_id(1)
    q = q_ref[0]  # [block_q, d]
    do = do_ref[0]
    lse = lse_ref[0]      # (bq, 1)
    delta = delta_ref[0]  # (bq, 1)
    bq = q.shape[0]
    d = q.shape[-1]

    dq = jnp.zeros((bq, d), jnp.float32)
    if varlen:
        seg_q = segc_ref[0]  # (bq, 1)

    num_kb = seq_len // block_k
    if causal:
        last_kb = jnp.minimum(num_kb, ((qi + 1) * q_block + block_k - 1) // block_k)
    else:
        last_kb = num_kb

    def body(kb, dq):
        k = k_ref[0, pl.ds(kb * block_k, block_k), :]
        v = v_ref[0, pl.ds(kb * block_k, block_k), :]
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32,
        precision=_dot_prec(q.dtype)) * sm_scale
        mask = None
        if causal:
            qpos = qi * q_block + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 0)
            kpos = kb * block_k + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 1)
            mask = qpos >= kpos
        if varlen:
            seg_k = _seg_row_slice(segr_ref, kb, block_k)  # (1, bk)
            same = seg_q == seg_k
            mask = same if mask is None else (mask & same)
        if mask is not None:
            s = jnp.where(mask, s, NEG_INF)
        p = jnp.exp(s - lse)
        dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32,
        precision=_dot_prec(q.dtype))
        ds = p * (dp - delta) * sm_scale
        dq = dq + jnp.dot(ds.astype(k.dtype), k,
                          preferred_element_type=jnp.float32,
        precision=_dot_prec(q.dtype))
        return dq

    dq = jax.lax.fori_loop(0, last_kb, body, dq)
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _flash_bwd(q, k, v, seg, out, lse, do, *, causal: bool, sm_scale: float,
               block_q: int, block_k: int, dlse=None):
    bh, s, d = q.shape
    # same exact-tiling contract as _flash_fwd (and the same inputs pick
    # the same blocks, so fwd/bwd tile identically)
    block_q = _pick_block(s, block_q)
    block_k = _pick_block(s, block_k)
    varlen = seg is not None
    # delta = rowsum(dO * O): phrased as a dot so XLA accumulates bf16
    # products in f32 WITHOUT materializing f32 copies of dO and O (the
    # astype form emitted two [bh,s,d] f32 converts + layout copies,
    # ~4 ms/step on the 12-layer bench points)
    delta = jnp.einsum("bsd,bsd->bs", do, out,
                       preferred_element_type=jnp.float32,
        precision=_dot_prec(q.dtype))[..., None]
    if dlse is not None:
        # lse cotangent (flash-with-lse path): ds = p*(dp - delta + dlse)
        delta = delta - dlse.astype(jnp.float32)[..., None]
    lse = lse[..., None]  # [bh, s, 1] — TPU-tileable stat columns

    # dK/dV pass
    in_specs = [
        pl.BlockSpec((1, s, d), lambda b, i: (b, 0, 0)),        # q
        pl.BlockSpec((1, block_k, d), lambda b, i: (b, i, 0)),  # k
        pl.BlockSpec((1, block_k, d), lambda b, i: (b, i, 0)),  # v
        pl.BlockSpec((1, s, d), lambda b, i: (b, 0, 0)),        # do
        pl.BlockSpec((1, s, 1), lambda b, i: (b, 0, 0)),        # lse
        pl.BlockSpec((1, s, 1), lambda b, i: (b, 0, 0)),        # delta
    ]
    args = [q, k, v, do, lse, delta]
    if varlen:
        _check_varlen_blocks(s, block_q, block_k)
        sp, ar = _varlen_specs(seg, s)
        in_specs += sp
        args += ar

    def kern_dkdv(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, *rest):
        if varlen:
            segc_ref, segr_ref, dk_ref, dv_ref = rest
        else:
            dk_ref, dv_ref = rest
            segc_ref = segr_ref = None
        _bwd_dkdv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                         segc_ref, segr_ref, dk_ref, dv_ref, block_q=block_q,
                         sm_scale=sm_scale, causal=causal, k_block=block_k,
                         seq_len=s, varlen=varlen)

    dk, dv = pl.pallas_call(
        kern_dkdv,
        out_shape=(jax.ShapeDtypeStruct((bh, s, d), k.dtype),
                   jax.ShapeDtypeStruct((bh, s, d), v.dtype)),
        grid=(bh, s // block_k),
        in_specs=in_specs,
        out_specs=(pl.BlockSpec((1, block_k, d), lambda b, i: (b, i, 0)),
                   pl.BlockSpec((1, block_k, d), lambda b, i: (b, i, 0))),
        interpret=_interpret(),
        **_compiler_kwargs(),
    )(*args)

    # dQ pass
    in_specs = [
        pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),  # q
        pl.BlockSpec((1, s, d), lambda b, i: (b, 0, 0)),        # k
        pl.BlockSpec((1, s, d), lambda b, i: (b, 0, 0)),        # v
        pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),  # do
        pl.BlockSpec((1, block_q, 1), lambda b, i: (b, i, 0)),  # lse
        pl.BlockSpec((1, block_q, 1), lambda b, i: (b, i, 0)),  # delta
    ]
    args = [q, k, v, do, lse, delta]
    if varlen:
        sp, ar = _varlen_specs(seg, s, col_block=block_q)
        in_specs += sp
        args += ar

    def kern_dq(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, *rest):
        if varlen:
            segc_ref, segr_ref, dq_ref = rest
        else:
            (dq_ref,) = rest
            segc_ref = segr_ref = None
        _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                       segc_ref, segr_ref, dq_ref, block_k=block_k,
                       sm_scale=sm_scale, causal=causal, q_block=block_q,
                       seq_len=s, varlen=varlen)

    dq = pl.pallas_call(
        kern_dq,
        out_shape=jax.ShapeDtypeStruct((bh, s, d), q.dtype),
        grid=(bh, s // block_q),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
        interpret=_interpret(),
        **_compiler_kwargs(),
    )(*args)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# custom VJP plumbing
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def _flash(q, k, v, seg, causal, sm_scale, block_q, block_k):
    out, _ = _flash_fwd(q, k, v, seg, causal=causal, sm_scale=sm_scale,
                        block_q=block_q, block_k=block_k)
    return out


def _flash_vjp_fwd(q, k, v, seg, causal, sm_scale, block_q, block_k):
    out, lse = _flash_fwd(q, k, v, seg, causal=causal, sm_scale=sm_scale,
                          block_q=block_q, block_k=block_k)
    return out, (q, k, v, seg, out, lse)


def _flash_vjp_bwd(causal, sm_scale, block_q, block_k, res, do):
    q, k, v, seg, out, lse = res
    dq, dk, dv = _flash_bwd(q, k, v, seg, out, lse, do, causal=causal,
                            sm_scale=sm_scale, block_q=block_q,
                            block_k=block_k)
    return dq, dk, dv, None


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def _flash_lse(q, k, v, seg, causal, sm_scale, block_q, block_k):
    """Flash attention that also RETURNS the log-sum-exp rows.

    For block-parallel formulations (ring attention) that merge several
    kernels' normalized partials: out = sum_i out_i * exp(lse_i - lse).
    The lse output is differentiable: d lse_r / d s_rk = p_rk, so its
    cotangent folds into the standard backward as delta_r - dlse_r
    (delta = rowsum(dO*O)) — same kernels, one extra subtraction."""
    return _flash_fwd(q, k, v, seg, causal=causal, sm_scale=sm_scale,
                      block_q=block_q, block_k=block_k)


def _flash_lse_vjp_fwd(q, k, v, seg, causal, sm_scale, block_q, block_k):
    out, lse = _flash_fwd(q, k, v, seg, causal=causal, sm_scale=sm_scale,
                          block_q=block_q, block_k=block_k)
    return (out, lse), (q, k, v, seg, out, lse)


def _flash_lse_vjp_bwd(causal, sm_scale, block_q, block_k, res, cts):
    do, dlse = cts
    q, k, v, seg, out, lse = res
    dq, dk, dv = _flash_bwd(q, k, v, seg, out, lse, do, causal=causal,
                            sm_scale=sm_scale, block_q=block_q,
                            block_k=block_k, dlse=dlse)
    return dq, dk, dv, None


_flash_lse.defvjp(_flash_lse_vjp_fwd, _flash_lse_vjp_bwd)


# Shared with decode_attention.py (pallas_kernels/_blocks.py) so the
# non-divisible-length fix-up can't drift between the kernels; the
# `_pick_block` name stays importable (distributed/sequence_parallel.py).
from ._blocks import pick_block as _pick_block  # noqa: E402


def flash_attention(q, k, v, causal: bool = True, sm_scale=None,
                    block_q: int = 1024, block_k: int = 1024, segment_ids=None):
    """Flash attention on [b, s, h, d] Tensors or arrays. Returns same layout.

    Default 1024x1024 blocks: round-5 chip re-sweep on v5e — vs the
    512x512 round-3 optimum, the end-to-end train step gains +2.1% at
    seq 1024, +1.6% at 4096, and +4.0% at 8192 (fewer grid launches,
    better MXU occupancy per block; VMEM still fits at head_dim <= 128).
    Blocks are clamped to the sequence length.
    Sequences to at least 16384 train on one chip (the raised Mosaic VMEM
    cap covers the backward's full-sequence refs; measured 42.2k tok/s at
    16k, batch 2, no remat — the bench's seq16384 point); beyond that,
    shard the sequence across chips with ring attention / Ulysses
    (distributed/sequence_parallel.py — ring runs THIS kernel per hop
    via _flash_lse and merges partials by log-sum-exp).

    segment_ids: optional [b, s] int32 — packed-sequence (varlen) masking;
    attention only within equal segment ids.

    Parity: paddle.nn.functional.flash_attention.flash_attention
    (python/paddle/nn/functional/flash_attention.py); backward parity:
    phi/kernels/gpu/flash_attn_grad_kernel.cu.
    """
    from ..core.tensor import Tensor
    from ..ops.dispatch import apply_op

    is_tensor = isinstance(q, Tensor)
    seg_arr = None
    if segment_ids is not None:
        seg_arr = segment_ids._data if isinstance(segment_ids, Tensor) else jnp.asarray(segment_ids)
        seg_arr = seg_arr.astype(jnp.int32)

    def _f(qa, ka, va):
        b, s, h, d = qa.shape
        scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(d)
        qm = jnp.moveaxis(qa, 2, 1).reshape(b * h, s, d)
        km = jnp.moveaxis(ka, 2, 1).reshape(b * h, s, d)
        vm = jnp.moveaxis(va, 2, 1).reshape(b * h, s, d)
        seg = None
        if seg_arr is not None:
            seg = jnp.repeat(seg_arr[:, None, :], h, axis=1).reshape(b * h, s)
        # The 512x512 default's VMEM budget assumes head_dim <= 128; wider
        # heads scale the per-block q/k/v refs linearly, so halve the block
        # cap to stay inside the (raised) scoped-VMEM limit.
        want_q, want_k = block_q, block_k
        if d > 128:
            want_q = min(want_q, 256)
            want_k = min(want_k, 256)
        bq = _pick_block(s, want_q)
        bk = _pick_block(s, want_k)
        if seg is not None and not _interpret():
            # varlen lane slices need 128-multiple blocks on TPU
            bq = max(bq, 128)
            bk = max(bk, 128)
        out = _flash(qm, km, vm, seg, causal, scale, bq, bk)
        return jnp.moveaxis(out.reshape(b, h, s, d), 1, 2)

    if is_tensor:
        return apply_op("flash_attention", _f, q, k, v)
    return _f(q, k, v)


def flash_attn_varlen(q, k, v, cu_seqlens, causal: bool = True, sm_scale=None,
                      block_q: int = 512, block_k: int = 512):
    """Varlen flash attention over packed sequences.

    q/k/v: [total_tokens, h, d] — sequences packed back to back;
    cu_seqlens: [n_seq + 1] int32 cumulative lengths (reference:
    flash_attn_unpadded, phi/kernels/gpu/flash_attn_kernel.cu varlen path).
    """
    from ..core.tensor import Tensor
    from ..ops.dispatch import apply_op

    def _arr(x):
        return x._data if isinstance(x, Tensor) else jnp.asarray(x)

    cu = _arr(cu_seqlens).astype(jnp.int32)
    is_tensor = any(isinstance(t, Tensor) for t in (q, k, v))
    if is_tensor:  # normalize mixed Tensor/array inputs for apply_op
        q, k, v = (t if isinstance(t, Tensor) else Tensor(jnp.asarray(t))
                   for t in (q, k, v))

    def _f(qa, ka, va):
        total = qa.shape[0]
        # token i belongs to segment j iff cu[j] <= i < cu[j+1]
        pos = jnp.arange(total, dtype=jnp.int32)
        seg = jnp.searchsorted(cu[1:], pos, side="right").astype(jnp.int32)
        # pad the packed stream to a 128 multiple (TPU lane tiling); padding
        # gets segment id -1 so no real token attends to it, and its rows are
        # sliced off below (their cotangents are zero in the backward)
        pad = (-total) % 128
        if pad and not _interpret():
            zeros = lambda a: jnp.zeros((pad,) + a.shape[1:], a.dtype)
            qa = jnp.concatenate([qa, zeros(qa)])
            ka = jnp.concatenate([ka, zeros(ka)])
            va = jnp.concatenate([va, zeros(va)])
            seg = jnp.concatenate([seg, jnp.full((pad,), -1, jnp.int32)])
        # in-segment causal positions: flash's causal mask is on absolute
        # positions, which is correct for packed sequences as long as the
        # segment mask also applies (cross-segment attention is masked out).
        out = flash_attention(qa[None], ka[None], va[None], causal=causal,
                              sm_scale=sm_scale, block_q=block_q,
                              block_k=block_k, segment_ids=seg[None])
        return out[0, :total]

    if is_tensor:
        # route through dispatch so the tape sees one grad node (parity with
        # flash_attention above; the review-caught alternative silently
        # detached packed-sequence training from autograd)
        return apply_op("flash_attn_varlen", _f, q, k, v)
    return _f(_arr(q), _arr(k), _arr(v))
