"""Flash attention as a Pallas TPU kernel.

Reference analogue: phi/kernels/gpu/flash_attn_kernel.cu (FlashAttention-2
via dynloaded libflashattn). TPU-native design: blockwise online-softmax
attention with q-blocks on the grid and a fori_loop over k-blocks held in
VMEM; the causal variant skips fully-masked k-blocks. The custom VJP
recomputes attention blockwise (flash backward) so no O(s²) tensor is ever
materialized — this is the long-context workhorse that XLA's fused SDPA
can't provide at large s.

Layout: [batch, seq, heads, head_dim] (Paddle convention); internally
blocked as [b*h, s, d].
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # TPU-only module; CPU tests run in interpret mode
    from jax.experimental.pallas import tpu as pltpu

    _HAS_TPU_PALLAS = True
except Exception:  # pragma: no cover
    pltpu = None
    _HAS_TPU_PALLAS = False

NEG_INF = -1e30


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, *, block_k: int, sm_scale: float, causal: bool,
                q_block: int, seq_len: int):
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * sm_scale  # [block_q, d]
    bq = q.shape[0]

    m = jnp.full((bq,), NEG_INF, jnp.float32)
    l = jnp.zeros((bq,), jnp.float32)
    acc = jnp.zeros((bq, q_ref.shape[-1]), jnp.float32)

    num_kb = seq_len // block_k
    if causal:
        # only k-blocks up to the diagonal contribute
        last_kb = jnp.minimum(num_kb, ((qi + 1) * q_block + block_k - 1) // block_k)
    else:
        last_kb = num_kb

    def body(kb, carry):
        m, l, acc = carry
        # slice through the ref (Pallas TPU requires pl.ds on refs, not
        # dynamic_slice on loaded values)
        k = k_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        s = q @ k.T  # [bq, bk]
        if causal:
            qpos = qi * q_block + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 0)
            kpos = kb * block_k + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 1)
            s = jnp.where(qpos >= kpos, s, NEG_INF)
        m_new = jnp.maximum(m, s.max(axis=-1))
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + p.sum(axis=-1)
        acc_new = acc * alpha[:, None] + p @ v
        return m_new, l_new, acc_new

    m, l, acc = jax.lax.fori_loop(0, last_kb, body, (m, l, acc))
    o_ref[0] = (acc / jnp.maximum(l, 1e-30)[:, None]).astype(o_ref.dtype)


def _flash_fwd(q, k, v, *, causal: bool, sm_scale: float, block_q: int, block_k: int):
    bh, s, d = q.shape
    block_q = min(block_q, s)
    block_k = min(block_k, s)
    grid = (bh, s // block_q)
    out = pl.pallas_call(
        functools.partial(_fwd_kernel, block_k=block_k, sm_scale=sm_scale, causal=causal,
                          q_block=block_q, seq_len=s),
        out_shape=jax.ShapeDtypeStruct((bh, s, d), q.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, s, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, s, d), lambda b, i: (b, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
        interpret=_interpret(),
    )(q, k, v)
    return out


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash(q, k, v, causal, sm_scale, block_q, block_k):
    return _flash_fwd(q, k, v, causal=causal, sm_scale=sm_scale, block_q=block_q, block_k=block_k)


def _flash_vjp_fwd(q, k, v, causal, sm_scale, block_q, block_k):
    out = _flash_fwd(q, k, v, causal=causal, sm_scale=sm_scale, block_q=block_q, block_k=block_k)
    return out, (q, k, v, out)


def _flash_vjp_bwd(causal, sm_scale, block_q, block_k, res, do):
    """Blockwise recomputation backward (flash-attention backward pass) in
    plain jnp — XLA fuses/tiles this well; a dedicated Pallas backward
    kernel can replace it without API change."""
    q, k, v, out = res
    qf = q.astype(jnp.float32) * sm_scale
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    dof = do.astype(jnp.float32)
    s = jnp.einsum("bqd,bkd->bqk", qf, kf)
    if causal:
        sq, sk = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((sq, sk), bool))
        s = jnp.where(mask, s, NEG_INF)
    m = s.max(-1, keepdims=True)
    p = jnp.exp(s - m)
    l = p.sum(-1, keepdims=True)
    p = p / jnp.maximum(l, 1e-30)
    dv = jnp.einsum("bqk,bqd->bkd", p, dof)
    dp = jnp.einsum("bqd,bkd->bqk", dof, vf)
    delta = (dof * out.astype(jnp.float32)).sum(-1, keepdims=True)
    ds = p * (dp - delta)
    dq = jnp.einsum("bqk,bkd->bqd", ds, kf) * sm_scale
    dk = jnp.einsum("bqk,bqd->bkd", ds, qf)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def flash_attention(q, k, v, causal: bool = True, sm_scale=None, block_q: int = 128,
                    block_k: int = 128):
    """Flash attention on [b, s, h, d] Tensors or arrays. Returns same layout.

    Parity: paddle.nn.functional.flash_attention.flash_attention
    (python/paddle/nn/functional/flash_attention.py).
    """
    from ..core.tensor import Tensor
    from ..ops.dispatch import apply_op

    is_tensor = isinstance(q, Tensor)

    def _f(qa, ka, va):
        b, s, h, d = qa.shape
        scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(d)
        qm = jnp.moveaxis(qa, 2, 1).reshape(b * h, s, d)
        km = jnp.moveaxis(ka, 2, 1).reshape(b * h, s, d)
        vm = jnp.moveaxis(va, 2, 1).reshape(b * h, s, d)
        bq = block_q
        while s % bq and bq > 1:
            bq //= 2
        bk = block_k
        while s % bk and bk > 1:
            bk //= 2
        out = _flash(qm, km, vm, causal, scale, bq, bk)
        return jnp.moveaxis(out.reshape(b, h, s, d), 1, 2)

    if is_tensor:
        return apply_op("flash_attention", _f, q, k, v)
    return _f(q, k, v)
