"""BERT (encoder) model — fine-tune/DP milestone model (SURVEY §7.2 step 5)."""

from __future__ import annotations

from dataclasses import dataclass

import jax.numpy as jnp

from .. import nn
from ..core.tensor import Tensor
from ..nn import functional as F


@dataclass
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    layer_norm_eps: float = 1e-12
    dropout: float = 0.1

    @staticmethod
    def base(**overrides):
        cfg = BertConfig()
        for k, v in overrides.items():
            setattr(cfg, k, v)
        return cfg

    @staticmethod
    def tiny(**overrides):
        cfg = BertConfig(vocab_size=256, hidden_size=64, num_hidden_layers=2,
                         num_attention_heads=4, intermediate_size=128,
                         max_position_embeddings=128, dropout=0.0)
        for k, v in overrides.items():
            setattr(cfg, k, v)
        return cfg


class BertEmbeddings(nn.Layer):
    def __init__(self, config: BertConfig):
        super().__init__()
        self.word_embeddings = nn.Embedding(config.vocab_size, config.hidden_size)
        self.position_embeddings = nn.Embedding(config.max_position_embeddings, config.hidden_size)
        self.token_type_embeddings = nn.Embedding(config.type_vocab_size, config.hidden_size)
        self.layer_norm = nn.LayerNorm(config.hidden_size, epsilon=config.layer_norm_eps)
        self.dropout = nn.Dropout(config.dropout)

    def forward(self, input_ids, token_type_ids=None):
        from ..ops.creation import arange, zeros_like

        b, s = input_ids.shape
        pos = arange(0, s, dtype="int32")
        if token_type_ids is None:
            token_type_ids = zeros_like(input_ids)
        x = (self.word_embeddings(input_ids) + self.position_embeddings(pos)
             + self.token_type_embeddings(token_type_ids))
        return self.dropout(self.layer_norm(x))


class BertModel(nn.Layer):
    def __init__(self, config: BertConfig):
        super().__init__()
        self.config = config
        self.embeddings = BertEmbeddings(config)
        enc_layer = nn.TransformerEncoderLayer(
            config.hidden_size, config.num_attention_heads, config.intermediate_size,
            dropout=config.dropout, activation="gelu", layer_norm_eps=config.layer_norm_eps)
        self.encoder = nn.TransformerEncoder(enc_layer, config.num_hidden_layers)
        self.pooler = nn.Linear(config.hidden_size, config.hidden_size)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None):
        x = self.embeddings(input_ids, token_type_ids)
        if attention_mask is not None:
            # [b, s] 1/0 -> additive [b, 1, 1, s]
            from ..ops.manipulation import unsqueeze

            m = unsqueeze(attention_mask.astype("float32"), [1, 2])
            attention_mask = (m - 1.0) * 1e9
        x = self.encoder(x, src_mask=attention_mask)
        pooled = F.tanh(self.pooler(x[:, 0]))
        return x, pooled

    @classmethod
    def from_huggingface(cls, hf_model):
        """Build a BertModel from a transformers BertModel — the encoder
        counterpart of the Llama/GPT-2 interop doors. HF BERT is post-LN
        with exact (erf) GELU, matching nn.TransformerEncoderLayer's
        defaults; torch Linear weights [out, in] transpose to our
        [in, out]. Converts the BASE model (sequence + pooled outputs);
        task heads differ structurally across ecosystems and are left to
        the caller."""
        h = hf_model.config
        if getattr(h, "hidden_act", "gelu") != "gelu":
            raise NotImplementedError(
                f"hidden_act={h.hidden_act!r}: this encoder uses exact GELU")
        if getattr(h, "position_embedding_type", "absolute") != "absolute":
            raise NotImplementedError(
                "only absolute position embeddings are supported")
        if getattr(h, "is_decoder", False) or getattr(h, "add_cross_attention", False):
            raise NotImplementedError(
                "decoder-configured BERT (causal self-attention / cross-"
                "attention) does not map onto this bidirectional encoder")
        if "pooler.dense.weight" not in hf_model.state_dict():
            raise NotImplementedError(
                "checkpoint has no pooler (add_pooling_layer=False); this "
                "model always carries one — load a pooled variant")
        config = BertConfig(
            vocab_size=h.vocab_size, hidden_size=h.hidden_size,
            num_hidden_layers=h.num_hidden_layers,
            num_attention_heads=h.num_attention_heads,
            intermediate_size=h.intermediate_size,
            max_position_embeddings=h.max_position_embeddings,
            type_vocab_size=h.type_vocab_size,
            layer_norm_eps=h.layer_norm_eps, dropout=0.0)
        model = cls(config)

        def lin(prefix):  # torch Linear -> (weight.T, bias)
            return (to_np(sd[prefix + ".weight"]).T, to_np(sd[prefix + ".bias"]))

        def to_np(v):
            return v.detach().cpu().numpy()

        sd = hf_model.state_dict()
        emb = "embeddings."
        out = {
            "embeddings.word_embeddings.weight": to_np(sd[emb + "word_embeddings.weight"]),
            "embeddings.position_embeddings.weight": to_np(sd[emb + "position_embeddings.weight"]),
            "embeddings.token_type_embeddings.weight": to_np(sd[emb + "token_type_embeddings.weight"]),
            "embeddings.layer_norm.weight": to_np(sd[emb + "LayerNorm.weight"]),
            "embeddings.layer_norm.bias": to_np(sd[emb + "LayerNorm.bias"]),
        }
        out["pooler.weight"], out["pooler.bias"] = lin("pooler.dense")
        for i in range(config.num_hidden_layers):
            src, dst = f"encoder.layer.{i}.", f"encoder.layers.{i}."
            for hf_name, our_name in (
                    ("attention.self.query", "self_attn.q_proj"),
                    ("attention.self.key", "self_attn.k_proj"),
                    ("attention.self.value", "self_attn.v_proj"),
                    ("attention.output.dense", "self_attn.out_proj"),
                    ("intermediate.dense", "linear1"),
                    ("output.dense", "linear2")):
                w, bias = lin(src + hf_name)
                out[dst + our_name + ".weight"] = w
                out[dst + our_name + ".bias"] = bias
            for hf_name, our_name in (("attention.output.LayerNorm", "norm1"),
                                      ("output.LayerNorm", "norm2")):
                out[dst + our_name + ".weight"] = to_np(sd[src + hf_name + ".weight"])
                out[dst + our_name + ".bias"] = to_np(sd[src + hf_name + ".bias"])

        from .interop import load_converted_state

        load_converted_state(model, out)
        model.eval()
        return model


class BertForPretraining(nn.Layer):
    def __init__(self, config: BertConfig):
        super().__init__()
        self.bert = BertModel(config)
        self.mlm_head = nn.Linear(config.hidden_size, config.vocab_size)
        self.nsp_head = nn.Linear(config.hidden_size, 2)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None):
        seq, pooled = self.bert(input_ids, token_type_ids, attention_mask)
        return self.mlm_head(seq), self.nsp_head(pooled)


class BertForSequenceClassification(nn.Layer):
    def __init__(self, config: BertConfig, num_classes: int = 2):
        super().__init__()
        self.bert = BertModel(config)
        self.dropout = nn.Dropout(config.dropout)
        self.classifier = nn.Linear(config.hidden_size, num_classes)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None):
        _, pooled = self.bert(input_ids, token_type_ids, attention_mask)
        return self.classifier(self.dropout(pooled))
