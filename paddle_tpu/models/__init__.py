"""Model families (large-model kit; reference analogue: the PaddleNLP-facing
capability surface built on fleet + fused kernels)."""

from .llama import (
    LlamaConfig,
    LlamaForCausalLM,
    LlamaModel,
    llama_pretrain_loss,
    llama_shard_fn,
    moe_aux_loss,
    moe_pretrain_loss,
)
from .gpt import GPTConfig, GPTForCausalLM
from .bert import BertConfig, BertForPretraining, BertModel
