"""Llama model family — the flagship pretraining model.

Capability parity target: the reference trains Llama/GPT through
PaddleNLP on fleet hybrid parallelism (SURVEY §3.4); the in-framework
pieces it relies on are fused attention kernels
(phi/kernels/gpu/flash_attn_kernel.cu), TP layers (mpu/mp_layers.py),
and SPMD rules (phi/infermeta/spmd_rules/flash_attention.cc). This module
is the TPU-native model built directly on those equivalents:
- attention: nn.functional.scaled_dot_product_attention (XLA-fused) or
  the Pallas flash kernel for long sequences;
- TP/SP/DP: parameters carry mesh placements via ``llama_shard_fn``
  (Megatron layout: qkv/gate column-sharded, o/down row-sharded,
  embeddings vocab-sharded), activations get sequence-dim constraints —
  GSPMD materializes the same collectives fleet would issue;
- rotary embeddings, RMSNorm, SwiGLU as fusable jnp chains.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Optional

import jax
import jax.numpy as jnp

from .. import nn
from ..core.tensor import Tensor
from ..nn import functional as F
from ..ops import dispatch as _dispatch
from ..ops.dispatch import apply_op


@dataclass
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 11008
    num_hidden_layers: int = 32
    num_attention_heads: int = 32
    num_key_value_heads: int = 32
    max_position_embeddings: int = 4096
    rms_norm_eps: float = 1e-6
    rope_theta: float = 10000.0
    tie_word_embeddings: bool = False
    use_flash_attention: bool = False  # Pallas kernel (long-seq path)
    # single [h, (q+2kv)*d] / [h, 2*ffn] matmuls instead of 3/2 separate
    # ones sharing the input (reference: PaddleNLP fuse_attention_qkv /
    # fused_linear config). Opt-in: on v5e at the 134M bench point both
    # measured SLOWER than the unfused layout (qkv 124.7k vs 127.8k
    # tok/s, mlp 127.0k) — XLA already amortizes the shared input read,
    # and the post-matmul slices cost more than the fusion saves; kept
    # for weight-layout parity with fused-checkpoint ecosystems
    fuse_attention_qkv: bool = False
    fuse_mlp: bool = False
    # Mixtral-style MoE decoder: >0 replaces every MLP with a GShard MoE
    # (distributed/moe.py MoELayer) — the in-model door to the reference's
    # incubate MoE surface. Experts are built replicated here; shard them
    # over an 'ep' axis with distributed.auto_shard (ExpertMLP pairing
    # rule) or shard_tensor on experts.w*/b*, and set
    # moe_dispatch_mode='einsum' so GSPMD turns dispatch/combine into
    # all-to-alls (default None: MoELayer picks gather, the fast
    # single-granule path)
    moe_num_experts: int = 0
    moe_topk: int = 2
    moe_capacity_factor: float = 1.25
    moe_dispatch_mode: Optional[str] = None
    dtype: str = "float32"

    @staticmethod
    def llama2_7b(**overrides):
        cfg = LlamaConfig()
        for k, v in overrides.items():
            setattr(cfg, k, v)
        return cfg

    @staticmethod
    def tiny(**overrides):
        cfg = LlamaConfig(vocab_size=256, hidden_size=64, intermediate_size=128,
                          num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
                          max_position_embeddings=128)
        for k, v in overrides.items():
            setattr(cfg, k, v)
        return cfg


def _rope_tables(head_dim: int, max_pos: int, theta: float):
    inv_freq = 1.0 / (theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim))
    t = jnp.arange(max_pos, dtype=jnp.float32)
    freqs = jnp.outer(t, inv_freq)  # [max_pos, head_dim/2]
    return jnp.cos(freqs), jnp.sin(freqs)


def apply_rotary_pos_emb(q: Tensor, k: Tensor, cos_tab, sin_tab, position_offset: int = 0):
    """Rotary embedding on [b, s, h, d] tensors (reference:
    incubate fused_rope / PaddleNLP rope; half-split convention).
    ``position_offset`` may be a per-row [b] vector (serving decode:
    every slot sits at its own position) — the tables are then gathered
    per row instead of sliced once."""

    def _rope(x, cos, sin):
        s = x.shape[1]
        if isinstance(position_offset, int):
            c = cos[position_offset:position_offset + s]
            si = sin[position_offset:position_offset + s]
        elif getattr(position_offset, "ndim", 0) == 2:
            # explicit [b, s] position grid (spec-tree bundles: node i
            # occupies cache slot pos+i but its ROTARY position is
            # pos+depth(i) — siblings share a position)
            c = cos[position_offset]   # [b, s, d/2]
            si = sin[position_offset]
        elif getattr(position_offset, "ndim", 0) == 1:
            # per-row offsets [b]: gather [b, s] position rows
            idx = position_offset[:, None] + jnp.arange(s)
            c = cos[idx]   # [b, s, d/2]
            si = sin[idx]
        else:  # traced offset (jitted decode step)
            c = jax.lax.dynamic_slice_in_dim(cos, position_offset, s, 0)
            si = jax.lax.dynamic_slice_in_dim(sin, position_offset, s, 0)
        # apply the rotation in the activation dtype: the tables are
        # COMPUTED in fp32 (angle precision lives there), but a bf16
        # activation rounds the product to bf16 anyway, so casting the
        # table first costs <=1 ulp while keeping the whole rope fwd AND
        # its transpose in bf16 — fp32 tables made XLA materialize fp32
        # [b,h,s,d] copies in the backward (~10 ms/step on the MoE bench)
        if c.ndim == 3:  # per-row [b, s, d/2]
            c = c[:, :, None, :].astype(x.dtype)
            si = si[:, :, None, :].astype(x.dtype)
        else:
            c = c[None, :, None, :].astype(x.dtype)
            si = si[None, :, None, :].astype(x.dtype)
        x1, x2 = jnp.split(x, 2, axis=-1)
        return jnp.concatenate([
            x1 * c - x2 * si,
            x2 * c + x1 * si,
        ], axis=-1)

    qo = apply_op("rope", lambda x: _rope(x, cos_tab, sin_tab), q)
    ko = apply_op("rope", lambda x: _rope(x, cos_tab, sin_tab), k)
    return qo, ko


def repeat_kv(x, rep: int):
    """GQA head expansion: [b, s, kv_heads, d] -> [b, s, kv_heads*rep, d]
    (reference PaddleNLP repeat_kv; each kv head serves ``rep`` query
    heads)."""
    from ..ops.dispatch import apply_op, ensure_tensor

    return apply_op("repeat_kv", lambda a: jnp.repeat(a, rep, axis=2),
                    ensure_tensor(x))


class LlamaAttention(nn.Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        self.hidden_size = config.hidden_size
        self.num_heads = config.num_attention_heads
        self.num_kv_heads = config.num_key_value_heads
        self.head_dim = config.hidden_size // config.num_attention_heads
        if config.fuse_attention_qkv:
            self.qkv_proj = nn.Linear(
                self.hidden_size,
                (self.num_heads + 2 * self.num_kv_heads) * self.head_dim,
                bias_attr=False)
        else:
            self.q_proj = nn.Linear(self.hidden_size, self.num_heads * self.head_dim, bias_attr=False)
            self.k_proj = nn.Linear(self.hidden_size, self.num_kv_heads * self.head_dim, bias_attr=False)
            self.v_proj = nn.Linear(self.hidden_size, self.num_kv_heads * self.head_dim, bias_attr=False)
        self.o_proj = nn.Linear(self.num_heads * self.head_dim, self.hidden_size, bias_attr=False)

    def forward(self, hidden_states, cos_tab, sin_tab, attn_mask=None, kv_cache=None, position_offset=0):
        b, s, _ = hidden_states.shape
        if self.config.fuse_attention_qkv:
            qkv = self.qkv_proj(hidden_states)
            qd = self.num_heads * self.head_dim
            kvd = self.num_kv_heads * self.head_dim
            q = qkv[:, :, :qd].reshape([b, s, self.num_heads, self.head_dim])
            k = qkv[:, :, qd:qd + kvd].reshape([b, s, self.num_kv_heads, self.head_dim])
            v = qkv[:, :, qd + kvd:].reshape([b, s, self.num_kv_heads, self.head_dim])
        else:
            q = self.q_proj(hidden_states).reshape([b, s, self.num_heads, self.head_dim])
            k = self.k_proj(hidden_states).reshape([b, s, self.num_kv_heads, self.head_dim])
            v = self.v_proj(hidden_states).reshape([b, s, self.num_kv_heads, self.head_dim])
        # under a tp>1 trace, pin [b, s, heads, d] activations to the
        # heads axis so GSPMD keeps column-parallel outputs where the
        # q/k/v weight shards put them (no-op at tp=1)
        from ..distributed.partition import maybe_constrain_heads

        q, k, v = (maybe_constrain_heads(q), maybe_constrain_heads(k),
                   maybe_constrain_heads(v))
        # spec-tree bundle companions riding the cache dict: the
        # [b, s, s] ancestor mask and the [s] node-depth vector that
        # decouples each node's rotary position from its cache slot
        tree_mask = tree_depth = None
        if isinstance(kv_cache, dict):
            tree_mask = kv_cache.get("tree_mask")
            tree_depth = kv_cache.get("tree_depth")
        rope_pos = position_offset
        if tree_depth is not None:
            td = tree_depth._data if isinstance(tree_depth, Tensor) \
                else jnp.asarray(tree_depth)
            po = position_offset._data \
                if isinstance(position_offset, Tensor) \
                else jnp.asarray(position_offset, jnp.int32)
            if po.ndim == 0:
                po = jnp.broadcast_to(po, (b,))
            rope_pos = po[:, None] + td[None, :].astype(jnp.int32)
        q, k = apply_rotary_pos_emb(q, k, cos_tab, sin_tab, rope_pos)

        static_cache = isinstance(kv_cache, dict)
        # paged static cache: the dict carries a "bt" block table and
        # [num_blocks, block_size, h, d] pools (the serving engine's
        # paged KV pool) instead of contiguous [b, max_len, h, d] rows
        paged_cache = static_cache and "bt" in kv_cache
        # quantized cache: int8/fp8 storage + "ks"/"vs" absmax scale
        # companions; the flash-decode kernels dequantize in their
        # prologue, the XLA fallbacks at the gather
        quant_cache = static_cache and "ks" in kv_cache
        # flash prefill: at offset 0 causal attention over the prompt
        # alone equals the masked-dense attention over the padded cache
        # (positions >= s are masked out anyway) — keep the step k/v for
        # the Pallas kernel and skip the [s, max_len] mask entirely.
        # Long-prompt serving stays flash-fast; the per-token decode path
        # (s == 1) is unchanged. Paged caches never take it: with prefix
        # sharing the chunk MUST read earlier blocks through the table.
        flash_prefill = (static_cache and not paged_cache
                         and self.config.use_flash_attention
                         and attn_mask is None
                         and isinstance(position_offset, int)
                         and position_offset == 0 and s > 1)
        # flash decode: the static-cache decode step (s small) runs the
        # Pallas flash-decode kernel over the cache, GQA-native and
        # per-row length-masked — no repeat_kv, no [s, max_len] mask
        use_flash_decode = False
        if static_cache and not flash_prefill:
            from ..pallas_kernels.decode_attention import (
                decode_dispatch, paged_decode_dispatch)

            dispatch = paged_decode_dispatch if paged_cache else decode_dispatch
            # the PAGED kernel scores tree bundles natively (ancestor
            # mask input); the contiguous kernel has no mask input, so a
            # tree bundle there counts as an external mask and declines
            ext_mask = attn_mask is not None or (
                tree_mask is not None and not paged_cache)
            use_flash_decode = dispatch(
                "llama", q_len=s, has_mask=ext_mask,
                dtype=q.dtype, quantized=quant_cache)
        if static_cache:
            # pre-allocated buffers updated in place at position_offset
            # (jit-friendly decode path; the reference's cache_kv
            # semantics with TPU-native dynamic_update_slice — or a
            # block-table scatter for paged pools)
            from ..generation import update_static_kv_cache

            step_k, step_v = k, v
            k, v, new_cache, mask = update_static_kv_cache(
                kv_cache, k, v, position_offset,
                build_mask=(attn_mask is None and not flash_prefill
                            and not use_flash_decode),
                gather=not use_flash_decode)
            if flash_prefill:
                k, v = step_k, step_v
            elif attn_mask is None and not use_flash_decode:
                attn_mask = mask
        elif kv_cache is not None:
            pk, pv = kv_cache
            from ..ops.manipulation import concat

            k = concat([pk, k], axis=1)
            v = concat([pv, v], axis=1)
            new_cache = (k, v)
        else:
            new_cache = None

        if use_flash_decode:
            from ..pallas_kernels.decode_attention import (
                flash_decode_attention, paged_flash_decode_attention)

            if paged_cache:
                out = paged_flash_decode_attention(
                    q, new_cache["k"], new_cache["v"], new_cache["bt"],
                    position_offset, k_scale=new_cache.get("ks"),
                    v_scale=new_cache.get("vs"),
                    ancestor_mask=tree_mask)
            else:
                out = flash_decode_attention(
                    q, k, v, position_offset,
                    k_scale=new_cache.get("ks"),
                    v_scale=new_cache.get("vs"))
        else:
            # GQA: the static-cache (decode/cached-prefill) fallback uses
            # the grouped contraction — k/v stay [b, max_len, kv, d], no
            # HBM expansion; the training/uncached paths keep repeat_kv
            # (the Pallas prefill kernel wants expanded heads)
            gqa = self.num_kv_heads != self.num_heads
            grouped_fallback = gqa and static_cache and not flash_prefill
            if gqa and not grouped_fallback:
                rep = self.num_heads // self.num_kv_heads
                k = repeat_kv(k, rep)
                v = repeat_kv(v, rep)

            if self.config.use_flash_attention and attn_mask is None \
                    and (not static_cache or flash_prefill):
                from ..pallas_kernels.flash_attention import flash_attention

                if flash_prefill and s % 128:
                    # pad the prompt to the kernel's 128 grid: padded queries
                    # are sliced off below, and causal masking means no REAL
                    # query (row < s) ever attends a padded key (row >= s)
                    pad = ((0, 0), (0, 128 - s % 128), (0, 0), (0, 0))
                    qp, kp, vp = (Tensor(jnp.pad(t._data, pad)) for t in (q, k, v))
                    out = flash_attention(qp, kp, vp, causal=True)[:, :s]
                else:
                    out = flash_attention(q, k, v, causal=True)
            elif grouped_fallback:
                out = F.grouped_query_sdpa(q, k, v, attn_mask=attn_mask)
            else:
                out = F.scaled_dot_product_attention(
                    q, k, v, attn_mask=attn_mask,
                    is_causal=attn_mask is None)
        out = out.reshape([b, s, self.num_heads * self.head_dim])
        out = self.o_proj(out)
        if kv_cache is not None:
            return out, new_cache
        return out


class LlamaMLP(nn.Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self._fused = config.fuse_mlp
        self._ffn = config.intermediate_size
        if self._fused:
            self.gate_up_proj = nn.Linear(
                config.hidden_size, 2 * config.intermediate_size, bias_attr=False)
        else:
            self.gate_proj = nn.Linear(config.hidden_size, config.intermediate_size, bias_attr=False)
            self.up_proj = nn.Linear(config.hidden_size, config.intermediate_size, bias_attr=False)
        self.down_proj = nn.Linear(config.intermediate_size, config.hidden_size, bias_attr=False)

    def forward(self, x):
        if self._fused:
            gu = self.gate_up_proj(x)
            gate, up = gu[:, :, :self._ffn], gu[:, :, self._ffn:]
            return self.down_proj(F.silu(gate) * up)
        return self.down_proj(F.silu(self.gate_proj(x)) * self.up_proj(x))


class LlamaDecoderLayer(nn.Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.self_attn = LlamaAttention(config)
        if config.moe_num_experts > 0:
            from ..distributed.moe import MoELayer

            self.mlp = MoELayer(
                d_model=config.hidden_size,
                d_hidden=config.intermediate_size,
                num_experts=config.moe_num_experts,
                topk=config.moe_topk,
                capacity_factor=config.moe_capacity_factor,
                activation="silu",
                dispatch_mode=config.moe_dispatch_mode)
        else:
            self.mlp = LlamaMLP(config)
        self.input_layernorm = nn.RMSNorm(config.hidden_size, epsilon=config.rms_norm_eps)
        self.post_attention_layernorm = nn.RMSNorm(config.hidden_size, epsilon=config.rms_norm_eps)

    def forward(self, hidden_states, cos_tab, sin_tab, attn_mask=None, kv_cache=None,
                position_offset=0):
        residual = hidden_states
        hidden_states = self.input_layernorm(hidden_states)
        new_cache = None
        if kv_cache is not None:
            hidden_states, new_cache = self.self_attn(hidden_states, cos_tab, sin_tab,
                                                      attn_mask, kv_cache, position_offset)
        else:
            hidden_states = self.self_attn(hidden_states, cos_tab, sin_tab, attn_mask)
        hidden_states = residual + hidden_states
        residual = hidden_states
        hidden_states = self.post_attention_layernorm(hidden_states)
        hidden_states = self.mlp(hidden_states)
        out = residual + hidden_states
        if kv_cache is not None:
            return out, new_cache
        return out


class LlamaModel(nn.Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        self.embed_tokens = nn.Embedding(config.vocab_size, config.hidden_size)
        self.layers = nn.LayerList([LlamaDecoderLayer(config) for _ in range(config.num_hidden_layers)])
        self.norm = nn.RMSNorm(config.hidden_size, epsilon=config.rms_norm_eps)
        head_dim = config.hidden_size // config.num_attention_heads
        cos_tab, sin_tab = _rope_tables(head_dim, config.max_position_embeddings, config.rope_theta)
        self.register_buffer("rope_cos", Tensor(cos_tab), persistable=False)
        self.register_buffer("rope_sin", Tensor(sin_tab), persistable=False)

    def forward(self, input_ids, attn_mask=None, kv_caches=None, position_offset=0):
        h = self.embed_tokens(input_ids)
        cos_tab, sin_tab = self.rope_cos._data, self.rope_sin._data
        if kv_caches is not None:
            new_caches = []
            for layer, cache in zip(self.layers, kv_caches, strict=True):
                h, nc = layer(h, cos_tab, sin_tab, attn_mask, cache, position_offset)
                new_caches.append(nc)
            return self.norm(h), new_caches
        for layer in self.layers:
            h = layer(h, cos_tab, sin_tab, attn_mask)
        return self.norm(h)


class LlamaForCausalLM(nn.Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        self.llama = LlamaModel(config)
        if config.tie_word_embeddings:
            self.lm_head = None
        else:
            self.lm_head = nn.Linear(config.hidden_size, config.vocab_size, bias_attr=False)

    def forward(self, input_ids, attn_mask=None, kv_caches=None, position_offset=0):
        if kv_caches is not None:
            h, new_caches = self.llama(input_ids, attn_mask, kv_caches, position_offset)
        else:
            h = self.llama(input_ids, attn_mask)
        if self.lm_head is None:
            from ..ops.math import matmul

            logits = matmul(h, self.llama.embed_tokens.weight, transpose_y=True)
        else:
            logits = self.lm_head(h)
        if kv_caches is not None:
            return logits, new_caches
        return logits

    def generate(self, input_ids, max_new_tokens: int = 32, **kwargs):
        from ..generation import generate

        return generate(self, input_ids, max_new_tokens=max_new_tokens, **kwargs)

    @classmethod
    def from_huggingface(cls, hf_model_or_state_dict, config: "LlamaConfig | None" = None):
        """Build a LlamaForCausalLM from a HuggingFace transformers Llama
        model (or its state_dict) — the interop door for users bringing
        reference-ecosystem checkpoints (PaddleNLP's Llama loads the same
        HF layout). Accepts the torch module itself or any mapping of
        parameter name -> array-like; weights are converted with
        ``convert_hf_llama_state_dict``."""
        sd = hf_model_or_state_dict
        if hasattr(sd, "state_dict"):
            # scaled-RoPE checkpoints (Llama-3.1 'llama3', 'linear', ...)
            # would load silently with wrong tables — refuse regardless of
            # whether the caller supplies a config. (A bare state_dict
            # carries no config: the caller vouches for default RoPE.)
            if hasattr(sd, "config"):
                scaling = getattr(sd.config, "rope_scaling", None)
                if scaling and scaling.get("rope_type", scaling.get("type")) \
                        not in (None, "default"):
                    raise NotImplementedError(
                        f"rope_scaling={scaling!r} is not supported; only the "
                        "default RoPE tables are derived from the config")
            if config is None and hasattr(sd, "config"):
                h = sd.config
                config = LlamaConfig(
                    vocab_size=h.vocab_size, hidden_size=h.hidden_size,
                    intermediate_size=h.intermediate_size,
                    num_hidden_layers=h.num_hidden_layers,
                    num_attention_heads=h.num_attention_heads,
                    num_key_value_heads=getattr(h, "num_key_value_heads",
                                                h.num_attention_heads),
                    max_position_embeddings=h.max_position_embeddings,
                    rms_norm_eps=h.rms_norm_eps,
                    rope_theta=getattr(h, "rope_theta", 10000.0),
                    tie_word_embeddings=getattr(h, "tie_word_embeddings", False))
            sd = sd.state_dict()
        if config is None:
            raise ValueError("config is required when passing a bare state_dict")
        if config.fuse_attention_qkv or config.fuse_mlp:
            raise NotImplementedError(
                "from_huggingface targets the unfused layout; load unfused, "
                "then concatenate into a fused twin if needed")
        model = cls(config)
        converted = convert_hf_llama_state_dict(sd)
        from .interop import load_converted_state

        # leftover weights (e.g. attention_bias / mlp_bias checkpoints)
        # would be silently dropped — wrong logits with no error; the
        # tied lm_head duplicate is the only benign one
        return load_converted_state(
            model, converted,
            allow_leftover=("lm_head.weight",) if config.tie_word_embeddings
            else ())


def convert_hf_llama_state_dict(sd) -> dict:
    """HF Llama parameter layout -> ours: ``model.`` prefix becomes
    ``llama.``, torch Linear weights [out, in] transpose to [in, out]
    (embedding and norm weights keep their layout), lm_head [vocab, h]
    transposes to [h, vocab]. Values are returned as numpy arrays."""
    import numpy as np

    def to_np(v):
        if hasattr(v, "detach"):  # torch tensor
            v = v.detach().cpu().numpy()
        return np.asarray(v)

    out = {}
    for name, v in sd.items():
        if name.endswith("rotary_emb.inv_freq"):
            continue  # we derive RoPE tables from the config
        arr = to_np(v)
        new = name
        if new.startswith("model."):
            new = "llama." + new[len("model."):]
        is_linear_w = new.endswith("_proj.weight") or new == "lm_head.weight"
        if is_linear_w and arr.ndim == 2:
            arr = arr.T
        out[new] = arr
    return out


def moe_aux_loss(model) -> Optional[Tensor]:
    """Sum of per-layer MoE load-balancing losses from the LAST forward
    (each MoELayer stashes ``aux_loss`` — traced values inside a traced
    step, so read this in the same loss closure; reference:
    moe_layer.py gate.get_loss). None for dense models."""
    total = None
    for layer in model.sublayers(include_self=True):
        aux = getattr(layer, "aux_loss", None)
        if aux is not None:
            total = aux if total is None else total + aux
    if total is None:
        return None
    return total if isinstance(total, Tensor) else Tensor(total)


def moe_pretrain_loss(model, aux_coeff: float = 0.01):
    """loss_fn factory for ShardedTrainStep on an MoE Llama: next-token
    CE + aux_coeff * load-balance loss (reference training recipes add
    the gate loss the same way)."""

    def loss_fn(logits, labels):
        loss = llama_pretrain_loss(logits, labels)
        aux = moe_aux_loss(model)
        if aux is not None:
            loss = loss + aux_coeff * aux
        return loss

    return loss_fn


def llama_pretrain_loss(logits: Tensor, labels: Tensor) -> Tensor:
    """Shifted next-token cross entropy (labels may equal input_ids;
    ignore_index=-100): position t predicts labels[t+1].

    Fused form (custom vjp): loss = logsumexp(logits) - logits[label]
    with labels shifted left and the last position ignore-masked. The
    forward streams the fp32 LSE without materializing an fp32 logits
    copy, and the backward computes d logits = (softmax - onehot) * mask
    / n directly in the logits dtype — the only big residual is the
    logits tensor itself (the autodiff'd form would save an fp32 exp
    buffer: 2 GB at seq 4096, an OOM on one chip). Measured +1.5%
    end-to-end on the 134M bench over the generic one-hot cross_entropy.
    Reference analogue: the fused softmax-CE kernels
    (c_softmax_with_cross_entropy / phi cross_entropy_with_softmax)."""
    from ..ops.dispatch import apply_op

    b, s, v = logits.shape
    lab = labels._data
    if lab.ndim == 3 and lab.shape[-1] == 1:  # (b, s, 1) label convention
        lab = lab[..., 0]

    def _f(lg):
        lab_s = jnp.concatenate(
            [lab[:, 1:], jnp.full((b, 1), -100, lab.dtype)], 1)
        return _fused_shift_ce(lg, lab_s)

    return apply_op("cross_entropy", _f, logits)


@jax.custom_vjp
def _fused_shift_ce(lg, lab_s):
    loss, _ = _fused_shift_ce_fwd(lg, lab_s)
    return loss


def _lse_stream(lg):
    """Row LSE with fp32 accumulation but NO fp32 copy of lg: the
    sub→convert→exp→reduce chain fuses into the reduction loop."""
    m = jnp.max(lg, axis=-1)
    z = jnp.sum(jnp.exp((lg - m[..., None]).astype(jnp.float32)), axis=-1)
    return m.astype(jnp.float32) + jnp.log(z)


def _fused_shift_ce_fwd(lg, lab_s):
    v = lg.shape[-1]
    lse = _lse_stream(lg)
    picked = jnp.take_along_axis(
        lg, jnp.clip(lab_s, 0, v - 1)[..., None].astype(jnp.int32),
        -1)[..., 0]
    mask = lab_s != -100
    n = jnp.maximum(mask.sum(), 1)
    loss = ((lse - picked.astype(jnp.float32)) * mask).sum() / n
    return loss, (lg, lab_s, lse, n)


def _fused_shift_ce_bwd(res, g):
    lg, lab_s, lse, n = res
    v = lg.shape[-1]
    mask = (lab_s != -100)[..., None]
    # softmax recomputed in the LOGITS dtype (bf16): exp(lg - lse)
    p = jnp.exp(lg - lse[..., None].astype(lg.dtype))
    onehot = jax.nn.one_hot(jnp.clip(lab_s, 0, v - 1), v, dtype=lg.dtype)
    scale = (g / n).astype(lg.dtype)
    dlg = (p - onehot) * mask * scale
    return dlg.astype(lg.dtype), None


_fused_shift_ce.defvjp(_fused_shift_ce_fwd, _fused_shift_ce_bwd)


# ---------------------------------------------------------------------------
# Sharding recipe (Megatron layout over a ProcessMesh)
# ---------------------------------------------------------------------------


def llama_shard_fn(mesh, mp_axis: str = "mp"):
    """Returns a shard_fn for distributed.shard_layer: Megatron TP layout.

    Parity: the reference's Llama TP config (ColumnParallelLinear on
    q/k/v/gate/up, RowParallelLinear on o/down, VocabParallelEmbedding) —
    expressed as placements; GSPMD inserts the collectives.
    """
    from ..distributed.api import shard_tensor
    from ..distributed.mesh import Replicate, Shard

    if mp_axis not in mesh.dim_names:
        return lambda name, layer, m: None
    mp_idx = mesh.dim_names.index(mp_axis)

    def placements_for(param_name: str, layer_name: str):
        pl = [Replicate()] * mesh.ndim
        # fused qkv_proj/gate_up_proj column-shard too (matched by the
        # v_proj/up_proj substrings): the concatenated out dim splits per
        # partition; the post-matmul q/k/v (gate/up) slices cross shard
        # boundaries, which GSPMD reshards correctly (use the unfused
        # layout when TP matmul-local slicing matters)
        col = any(k in layer_name for k in ("q_proj", "k_proj", "v_proj", "gate_proj", "up_proj"))
        row = any(k in layer_name for k in ("o_proj", "down_proj"))
        vocab = "embed_tokens" in layer_name or "lm_head" in layer_name
        if col and param_name == "weight":
            pl[mp_idx] = Shard(1)
        elif row and param_name == "weight":
            pl[mp_idx] = Shard(0)
        elif vocab and param_name == "weight":
            # embed: shard vocab rows; lm_head weight [hidden, vocab]: shard cols
            pl[mp_idx] = Shard(1) if "lm_head" in layer_name else Shard(0)
        return pl

    def shard_fn(name, sublayer, m):
        for pname, p in list(sublayer._parameters.items()):
            if p is None:
                continue
            sublayer._parameters[pname] = shard_tensor(p, mesh, placements_for(pname, name))

    return shard_fn
