"""GPT-2/3-style model (learned positions, pre-LN, GELU MLP).

Capability target: the reference's GPT-3 hybrid-parallel path
(SURVEY §7.2 milestone 4: GPT-3 1.3B TP+PP).
"""

from __future__ import annotations

from dataclasses import dataclass

import jax.numpy as jnp

from .. import nn
from ..core.tensor import Tensor
from ..nn import functional as F


@dataclass
class GPTConfig:
    vocab_size: int = 50304
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 1024
    layer_norm_eps: float = 1e-5
    dropout: float = 0.0

    @property
    def num_key_value_heads(self):
        # no GQA in the GPT family; generation.py sizes KV caches off this
        return self.num_attention_heads

    @staticmethod
    def gpt3_1p3b(**overrides):
        cfg = GPTConfig(hidden_size=2048, num_hidden_layers=24, num_attention_heads=16,
                        intermediate_size=8192, max_position_embeddings=2048)
        for k, v in overrides.items():
            setattr(cfg, k, v)
        return cfg

    @staticmethod
    def tiny(**overrides):
        cfg = GPTConfig(vocab_size=256, hidden_size=64, num_hidden_layers=2,
                        num_attention_heads=4, intermediate_size=128, max_position_embeddings=128)
        for k, v in overrides.items():
            setattr(cfg, k, v)
        return cfg


class GPTBlock(nn.Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.ln_1 = nn.LayerNorm(config.hidden_size, epsilon=config.layer_norm_eps)
        self.attn = nn.MultiHeadAttention(config.hidden_size, config.num_attention_heads,
                                          dropout=config.dropout)
        self.ln_2 = nn.LayerNorm(config.hidden_size, epsilon=config.layer_norm_eps)
        self.fc_in = nn.Linear(config.hidden_size, config.intermediate_size)
        self.fc_out = nn.Linear(config.intermediate_size, config.hidden_size)

    def forward(self, x, attn_mask=None, kv_cache=None, position_offset=0):
        h = self.ln_1(x)
        b, s, _ = h.shape
        nh = self.attn.num_heads
        hd = self.attn.head_dim
        q = self.attn.q_proj(h).reshape([b, s, nh, hd])
        k = self.attn.k_proj(h).reshape([b, s, nh, hd])
        v = self.attn.v_proj(h).reshape([b, s, nh, hd])
        # under a tp>1 trace, pin [b, s, heads, d] activations to the
        # heads axis so GSPMD keeps column-parallel outputs where the
        # q/k/v weight shards put them (no-op at tp=1)
        from ..distributed.partition import maybe_constrain_heads

        q, k, v = (maybe_constrain_heads(q), maybe_constrain_heads(k),
                   maybe_constrain_heads(v))
        new_cache = None
        use_flash_decode = False
        paged_cache = isinstance(kv_cache, dict) and "bt" in kv_cache
        if isinstance(kv_cache, dict):
            # pre-allocated [b, max_len, h, d] buffers updated in place
            # (the generation.py static-cache protocol, as in llama.py;
            # "bt"-carrying dicts are paged pools + block tables); the
            # decode step (s small, no external mask) dispatches to the
            # Pallas flash-decode kernel — same gate as llama
            from ..generation import update_static_kv_cache
            from ..pallas_kernels.decode_attention import (
                decode_dispatch, paged_decode_dispatch)

            dispatch = paged_decode_dispatch if paged_cache else decode_dispatch
            # spec-tree bundles: the PAGED kernel takes the ancestor
            # mask natively; the contiguous kernel has no mask input so
            # a tree bundle there declines like an external mask
            tree_mask = kv_cache.get("tree_mask")
            ext_mask = attn_mask is not None or (
                tree_mask is not None and not paged_cache)
            use_flash_decode = dispatch(
                "gpt", q_len=s, has_mask=ext_mask,
                dtype=q.dtype, quantized="ks" in kv_cache)
            k, v, new_cache, mask = update_static_kv_cache(
                kv_cache, k, v, position_offset,
                build_mask=attn_mask is None and not use_flash_decode,
                gather=not use_flash_decode)
            if attn_mask is None and not use_flash_decode:
                attn_mask = mask
        elif kv_cache is not None:
            raise TypeError(
                f"GPT kv_cache must be the generation.py static-cache dict, "
                f"got {type(kv_cache).__name__}")
        if use_flash_decode:
            from ..pallas_kernels.decode_attention import (
                flash_decode_attention, paged_flash_decode_attention)

            if paged_cache:
                a = paged_flash_decode_attention(
                    q, new_cache["k"], new_cache["v"], new_cache["bt"],
                    position_offset, k_scale=new_cache.get("ks"),
                    v_scale=new_cache.get("vs"),
                    ancestor_mask=new_cache.get("tree_mask"))
            else:
                a = flash_decode_attention(
                    q, k, v, position_offset,
                    k_scale=new_cache.get("ks"),
                    v_scale=new_cache.get("vs"))
        else:
            a = F.scaled_dot_product_attention(
                q, k, v, attn_mask=attn_mask,
                is_causal=attn_mask is None and kv_cache is None)
        x = x + self.attn.out_proj(a.reshape([b, s, nh * hd]))
        x = x + self.fc_out(F.gelu(self.fc_in(self.ln_2(x)), approximate=True))
        if kv_cache is not None:
            return x, new_cache
        return x


class GPTModel(nn.Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        self.wte = nn.Embedding(config.vocab_size, config.hidden_size)
        self.wpe = nn.Embedding(config.max_position_embeddings, config.hidden_size)
        self.h = nn.LayerList([GPTBlock(config) for _ in range(config.num_hidden_layers)])
        self.ln_f = nn.LayerNorm(config.hidden_size, epsilon=config.layer_norm_eps)

    def forward(self, input_ids, attn_mask=None, kv_caches=None, position_offset=0):
        b, s = input_ids.shape
        # position_offset may be traced (jitted decode step): index wpe
        # with a dynamic starting position; a per-row [b] vector (serving
        # decode: each slot at its own position) gathers [b, s] rows
        td = None
        if kv_caches is not None and isinstance(kv_caches[0], dict):
            # spec-tree bundle: node i's LEARNED position is
            # pos + depth(i), decoupled from its cache slot pos + i
            td = kv_caches[0].get("tree_depth")
        if td is not None:
            tdv = td._data if isinstance(td, Tensor) else jnp.asarray(td)
            po = position_offset._data \
                if isinstance(position_offset, Tensor) \
                else jnp.asarray(position_offset, jnp.int32)
            if po.ndim == 0:
                po = jnp.broadcast_to(po, (b,))
            pos = po[:, None] + tdv[None, :].astype(jnp.int32)
        elif getattr(position_offset, "ndim", 0) == 1:
            pos = position_offset[:, None] + jnp.arange(s, dtype=jnp.int32)[None, :]
        else:
            pos = position_offset + jnp.arange(s, dtype=jnp.int32)
        x = self.wte(input_ids) + self.wpe(Tensor(pos))
        if kv_caches is not None:
            new_caches = []
            for block, cache in zip(self.h, kv_caches, strict=True):
                x, nc = block(x, attn_mask, cache, position_offset)
                new_caches.append(nc)
            return self.ln_f(x), new_caches
        for block in self.h:
            x = block(x, attn_mask)
        return self.ln_f(x)


class GPTForCausalLM(nn.Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        self.gpt = GPTModel(config)
        self.lm_head = nn.Linear(config.hidden_size, config.vocab_size, bias_attr=False)

    def forward(self, input_ids, attn_mask=None, kv_caches=None, position_offset=0):
        if kv_caches is not None:
            h, new_caches = self.gpt(input_ids, attn_mask, kv_caches, position_offset)
            return self.lm_head(h), new_caches
        return self.lm_head(self.gpt(input_ids, attn_mask))

    def generate(self, input_ids, max_new_tokens: int = 32, **kwargs):
        from ..generation import generate

        return generate(self, input_ids, max_new_tokens=max_new_tokens, **kwargs)

    @classmethod
    def from_huggingface(cls, hf_model):
        """Build a GPTForCausalLM from a transformers GPT2LMHeadModel —
        the GPT-2 counterpart of the Llama interop door. HF GPT-2 stores
        Conv1D weights in [in, out] (our nn.Linear layout — no
        transpose); the fused c_attn [h, 3h] splits into q/k/v; lm_head
        is tied to wte (we materialize the transpose into our untied
        head)."""
        h = hf_model.config
        if getattr(h, "activation_function", "gelu_new") not in (
                "gelu_new", "gelu_pytorch_tanh"):
            raise NotImplementedError(
                f"activation_function={h.activation_function!r}: this model "
                "uses the tanh-approximate GELU only")
        # attention-math knobs carry no weights, so the shape checks
        # can't catch them — refuse rather than silently mis-load
        if getattr(h, "scale_attn_by_inverse_layer_idx", False) \
                or not getattr(h, "scale_attn_weights", True) \
                or getattr(h, "add_cross_attention", False):
            raise NotImplementedError(
                "non-default attention scaling / cross-attention configs are "
                "not reproduced by this model's fixed 1/sqrt(head_dim) SDPA")
        config = GPTConfig(
            vocab_size=h.vocab_size, hidden_size=h.n_embd,
            num_hidden_layers=h.n_layer, num_attention_heads=h.n_head,
            intermediate_size=h.n_inner or 4 * h.n_embd,
            max_position_embeddings=h.n_positions,
            layer_norm_eps=h.layer_norm_epsilon)
        model = cls(config)

        def to_np(v):
            return v.detach().cpu().numpy()

        sd = hf_model.state_dict()
        out = {
            "gpt.wte.weight": to_np(sd["transformer.wte.weight"]),
            "gpt.wpe.weight": to_np(sd["transformer.wpe.weight"]),
            "gpt.ln_f.weight": to_np(sd["transformer.ln_f.weight"]),
            "gpt.ln_f.bias": to_np(sd["transformer.ln_f.bias"]),
            # present in the state_dict tied or untied; using it (not
            # wte.T) keeps untied checkpoints correct
            "lm_head.weight": to_np(sd["lm_head.weight"]).T,
        }
        hs = config.hidden_size
        for i in range(config.num_hidden_layers):
            src, dst = f"transformer.h.{i}.", f"gpt.h.{i}."
            for ln in ("ln_1", "ln_2"):
                out[dst + ln + ".weight"] = to_np(sd[src + ln + ".weight"])
                out[dst + ln + ".bias"] = to_np(sd[src + ln + ".bias"])
            ca_w = to_np(sd[src + "attn.c_attn.weight"])  # [h, 3h]
            ca_b = to_np(sd[src + "attn.c_attn.bias"])  # [3h]
            for j, name in enumerate(("q_proj", "k_proj", "v_proj")):
                out[dst + f"attn.{name}.weight"] = ca_w[:, j * hs:(j + 1) * hs]
                out[dst + f"attn.{name}.bias"] = ca_b[j * hs:(j + 1) * hs]
            out[dst + "attn.out_proj.weight"] = to_np(sd[src + "attn.c_proj.weight"])
            out[dst + "attn.out_proj.bias"] = to_np(sd[src + "attn.c_proj.bias"])
            out[dst + "fc_in.weight"] = to_np(sd[src + "mlp.c_fc.weight"])
            out[dst + "fc_in.bias"] = to_np(sd[src + "mlp.c_fc.bias"])
            out[dst + "fc_out.weight"] = to_np(sd[src + "mlp.c_proj.weight"])
            out[dst + "fc_out.bias"] = to_np(sd[src + "mlp.c_proj.bias"])

        from .interop import load_converted_state

        return load_converted_state(model, out)
