"""GPT-2/3-style model (learned positions, pre-LN, GELU MLP).

Capability target: the reference's GPT-3 hybrid-parallel path
(SURVEY §7.2 milestone 4: GPT-3 1.3B TP+PP).
"""

from __future__ import annotations

from dataclasses import dataclass

import jax.numpy as jnp

from .. import nn
from ..core.tensor import Tensor
from ..nn import functional as F


@dataclass
class GPTConfig:
    vocab_size: int = 50304
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 1024
    layer_norm_eps: float = 1e-5
    dropout: float = 0.0

    @staticmethod
    def gpt3_1p3b(**overrides):
        cfg = GPTConfig(hidden_size=2048, num_hidden_layers=24, num_attention_heads=16,
                        intermediate_size=8192, max_position_embeddings=2048)
        for k, v in overrides.items():
            setattr(cfg, k, v)
        return cfg

    @staticmethod
    def tiny(**overrides):
        cfg = GPTConfig(vocab_size=256, hidden_size=64, num_hidden_layers=2,
                        num_attention_heads=4, intermediate_size=128, max_position_embeddings=128)
        for k, v in overrides.items():
            setattr(cfg, k, v)
        return cfg


class GPTBlock(nn.Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.ln_1 = nn.LayerNorm(config.hidden_size, epsilon=config.layer_norm_eps)
        self.attn = nn.MultiHeadAttention(config.hidden_size, config.num_attention_heads,
                                          dropout=config.dropout)
        self.ln_2 = nn.LayerNorm(config.hidden_size, epsilon=config.layer_norm_eps)
        self.fc_in = nn.Linear(config.hidden_size, config.intermediate_size)
        self.fc_out = nn.Linear(config.intermediate_size, config.hidden_size)

    def forward(self, x, attn_mask=None):
        h = self.ln_1(x)
        b, s, _ = h.shape
        nh = self.attn.num_heads
        hd = self.attn.head_dim
        q = self.attn.q_proj(h).reshape([b, s, nh, hd])
        k = self.attn.k_proj(h).reshape([b, s, nh, hd])
        v = self.attn.v_proj(h).reshape([b, s, nh, hd])
        a = F.scaled_dot_product_attention(q, k, v, attn_mask=attn_mask, is_causal=attn_mask is None)
        x = x + self.attn.out_proj(a.reshape([b, s, nh * hd]))
        x = x + self.fc_out(F.gelu(self.fc_in(self.ln_2(x)), approximate=True))
        return x


class GPTModel(nn.Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        self.wte = nn.Embedding(config.vocab_size, config.hidden_size)
        self.wpe = nn.Embedding(config.max_position_embeddings, config.hidden_size)
        self.h = nn.LayerList([GPTBlock(config) for _ in range(config.num_hidden_layers)])
        self.ln_f = nn.LayerNorm(config.hidden_size, epsilon=config.layer_norm_eps)

    def forward(self, input_ids, attn_mask=None):
        from ..ops.creation import arange

        b, s = input_ids.shape
        pos = arange(0, s, dtype="int32")
        x = self.wte(input_ids) + self.wpe(pos)
        for block in self.h:
            x = block(x, attn_mask)
        return self.ln_f(x)


class GPTForCausalLM(nn.Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        self.gpt = GPTModel(config)
        self.lm_head = nn.Linear(config.hidden_size, config.vocab_size, bias_attr=False)

    def forward(self, input_ids, attn_mask=None):
        return self.lm_head(self.gpt(input_ids, attn_mask))

    def generate(self, input_ids, max_new_tokens: int = 32, **kwargs):
        from ..generation import generate_uncached

        return generate_uncached(self, input_ids, max_new_tokens=max_new_tokens, **kwargs)
