"""Shared checkpoint-conversion loader for the HF interop doors
(llama/gpt/bert from_huggingface)."""

from __future__ import annotations

import json
import os

import jax.numpy as jnp

from ..core.tensor import Tensor


def load_hf_state_dict(path: str) -> dict:
    """Read a HuggingFace checkpoint DIRECTORY into a flat name->array
    dict without torch: single or sharded ``*.safetensors`` (index json
    honored). The interop doors accept the result as their bare
    state_dict input — so converting a downloaded checkpoint needs no
    torch and no model instantiation."""
    from safetensors import safe_open

    if os.path.isfile(path):
        files = [path]
    else:
        idx = os.path.join(path, "model.safetensors.index.json")
        if os.path.exists(idx):
            with open(idx) as f:
                weight_map = json.load(f)["weight_map"]
            files = sorted({os.path.join(path, v) for v in weight_map.values()})
        else:
            single = os.path.join(path, "model.safetensors")
            if not os.path.exists(single):
                raise FileNotFoundError(
                    f"no model.safetensors[.index.json] under {path!r}")
            files = [single]
    out = {}
    for f in files:
        with safe_open(f, framework="np") as sf:
            for name in sf.keys():
                out[name] = sf.get_tensor(name)
    return out


def load_converted_state(model, converted: dict, *, allow_leftover=()):
    """Validate-and-load a converted state dict into ``model``.

    Raises on missing parameters, on leftover weights the model cannot
    consume (silent weight dropping = silently wrong outputs), and on
    shape mismatches. ``allow_leftover``: names that are benign
    duplicates (e.g. a tied lm_head)."""
    params = model.named_parameters_dict()
    missing = set(params) - set(converted)
    if missing:
        raise ValueError(f"HF state_dict missing parameters: {sorted(missing)[:5]}")
    leftover = set(converted) - set(params) - set(allow_leftover)
    if leftover:
        raise ValueError(
            f"HF state_dict has weights this model cannot consume: "
            f"{sorted(leftover)[:5]}")
    for name, p in params.items():
        w = converted[name]
        if tuple(w.shape) != tuple(p.shape):
            raise ValueError(
                f"{name}: HF shape {tuple(w.shape)} vs model {tuple(p.shape)}")
        p.set_value(Tensor(jnp.asarray(w, dtype=p._data.dtype)))
    return model
