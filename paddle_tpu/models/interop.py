"""Shared checkpoint-conversion loader for the HF interop doors
(llama/gpt/bert from_huggingface)."""

from __future__ import annotations

import jax.numpy as jnp

from ..core.tensor import Tensor


def load_converted_state(model, converted: dict, *, allow_leftover=()):
    """Validate-and-load a converted state dict into ``model``.

    Raises on missing parameters, on leftover weights the model cannot
    consume (silent weight dropping = silently wrong outputs), and on
    shape mismatches. ``allow_leftover``: names that are benign
    duplicates (e.g. a tied lm_head)."""
    params = model.named_parameters_dict()
    missing = set(params) - set(converted)
    if missing:
        raise ValueError(f"HF state_dict missing parameters: {sorted(missing)[:5]}")
    leftover = set(converted) - set(params) - set(allow_leftover)
    if leftover:
        raise ValueError(
            f"HF state_dict has weights this model cannot consume: "
            f"{sorted(leftover)[:5]}")
    for name, p in params.items():
        w = converted[name]
        if tuple(w.shape) != tuple(p.shape):
            raise ValueError(
                f"{name}: HF shape {tuple(w.shape)} vs model {tuple(p.shape)}")
        p.set_value(Tensor(jnp.asarray(w, dtype=p._data.dtype)))
    return model
