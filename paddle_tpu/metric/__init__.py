"""Metrics (parity: python/paddle/metric/metrics.py — Metric base,
Accuracy, Precision, Recall, Auc)."""

from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor


def _np(x):
    return np.asarray(x._data) if isinstance(x, Tensor) else np.asarray(x)


class Metric:
    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        raise NotImplementedError

    def compute(self, *args):
        return args


class Accuracy(Metric):
    def __init__(self, topk=(1,), name=None):
        self.topk = (topk,) if isinstance(topk, int) else tuple(topk)
        self.maxk = max(self.topk)
        self._name = name or "acc"
        self.reset()

    def reset(self):
        self.total = [0.0] * len(self.topk)
        self.count = [0] * len(self.topk)

    def compute(self, pred, label, *args):
        pred_np = _np(pred)
        label_np = _np(label)
        if label_np.ndim == pred_np.ndim and label_np.shape[-1] == 1:
            label_np = label_np[..., 0]
        top = np.argsort(-pred_np, axis=-1)[..., : self.maxk]
        correct = top == label_np[..., None]
        return Tensor(correct.astype(np.float32))

    def update(self, correct, *args):
        c = _np(correct)
        out = []
        for i, k in enumerate(self.topk):
            num = c[..., :k].sum()
            self.total[i] += float(num)
            self.count[i] += int(c.shape[0])
            out.append(float(num) / max(c.shape[0], 1))
        return out[0] if len(out) == 1 else out

    def accumulate(self):
        res = [t / max(c, 1) for t, c in zip(self.total, self.count)]
        return res[0] if len(res) == 1 else res

    def name(self):
        return self._name


class Precision(Metric):
    def __init__(self, name=None):
        self._name = name or "precision"
        self.reset()

    def reset(self):
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        p = (_np(preds) > 0.5).astype(np.int64).reshape(-1)
        l = _np(labels).astype(np.int64).reshape(-1)
        self.tp += int(((p == 1) & (l == 1)).sum())
        self.fp += int(((p == 1) & (l == 0)).sum())

    def accumulate(self):
        d = self.tp + self.fp
        return self.tp / d if d else 0.0

    def name(self):
        return self._name


class Recall(Metric):
    def __init__(self, name=None):
        self._name = name or "recall"
        self.reset()

    def reset(self):
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        p = (_np(preds) > 0.5).astype(np.int64).reshape(-1)
        l = _np(labels).astype(np.int64).reshape(-1)
        self.tp += int(((p == 1) & (l == 1)).sum())
        self.fn += int(((p == 0) & (l == 1)).sum())

    def accumulate(self):
        d = self.tp + self.fn
        return self.tp / d if d else 0.0

    def name(self):
        return self._name


class Auc(Metric):
    def __init__(self, curve="ROC", num_thresholds=4095, name=None):
        self.num_thresholds = num_thresholds
        self._name = name or "auc"
        self.reset()

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds + 1)
        self._stat_neg = np.zeros(self.num_thresholds + 1)

    def update(self, preds, labels):
        p = _np(preds)
        if p.ndim == 2:
            p = p[:, 1]
        l = _np(labels).reshape(-1)
        bins = np.clip((p * self.num_thresholds).astype(np.int64), 0, self.num_thresholds)
        for b, y in zip(bins, l):
            if y:
                self._stat_pos[b] += 1
            else:
                self._stat_neg[b] += 1

    def accumulate(self):
        tot_pos = 0.0
        tot_neg = 0.0
        auc = 0.0
        for i in range(self.num_thresholds, -1, -1):
            new_pos = tot_pos + self._stat_pos[i]
            new_neg = tot_neg + self._stat_neg[i]
            auc += (new_pos + tot_pos) * self._stat_neg[i] / 2.0
            tot_pos, tot_neg = new_pos, new_neg
        denom = tot_pos * tot_neg
        return float(auc / denom) if denom else 0.0

    def name(self):
        return self._name


def accuracy(input, label, k=1, correct=None, total=None, name=None):
    pred_np = _np(input)
    label_np = _np(label)
    if label_np.ndim == pred_np.ndim and label_np.shape[-1] == 1:
        label_np = label_np[..., 0]
    top = np.argsort(-pred_np, axis=-1)[..., :k]
    c = (top == label_np[..., None]).any(-1).mean()
    import jax.numpy as jnp

    return Tensor(jnp.asarray(np.float32(c)))
