"""paddle.linalg — linear-algebra operator surface.

Parity: python/paddle/linalg.py re-exporting python/paddle/tensor/linalg.py
(cholesky, svd, qr, lu, eigh, norm family, solve family, …; kernels under
paddle/phi/kernels/*/{svd,qr,cholesky,...}_kernel).

TPU design: everything lowers to XLA's native decompositions via
jax.numpy.linalg / jax.scipy.linalg (MXU-friendly blocked algorithms,
differentiable through jax.vjp so the tape gets gradients for free).
`eig`/`eigvals` (general non-symmetric) have no TPU lowering — they run
as host callbacks like the reference's CPU-only eig kernel
(paddle/phi/kernels/cpu/eig_kernel.cc).
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .core.tensor import Tensor
from .ops.dispatch import apply_op, ensure_tensor

__all__ = [
    "cholesky", "cholesky_inverse", "cholesky_solve", "cond", "corrcoef",
    "cov", "det", "eig", "eigh", "eigvals", "eigvalsh", "householder_product",
    "inv", "lstsq", "lu", "lu_unpack", "matrix_exp", "matrix_norm",
    "matrix_power", "matrix_rank", "multi_dot", "norm", "pinv", "qr",
    "slogdet", "solve", "svd", "svd_lowrank", "pca_lowrank",
    "triangular_solve", "vector_norm", "ormqr",
]


def cholesky(x, upper: bool = False, name=None) -> Tensor:
    def f(a):
        L = jnp.linalg.cholesky(a)
        return jnp.swapaxes(L, -1, -2).conj() if upper else L

    return apply_op("cholesky", f, ensure_tensor(x))


def cholesky_solve(x, y, upper: bool = False, name=None) -> Tensor:
    """Solve A X = B given the Cholesky factor ``y`` of A (paddle argument
    order: x = B, y = factor)."""

    def f(b, factor):
        return jax.scipy.linalg.cho_solve((factor, not upper), b)

    return apply_op("cholesky_solve", f, ensure_tensor(x), ensure_tensor(y))


def cholesky_inverse(x, upper: bool = False, name=None) -> Tensor:
    def f(factor):
        eye = jnp.eye(factor.shape[-1], dtype=factor.dtype)
        return jax.scipy.linalg.cho_solve((factor, not upper), eye)

    return apply_op("cholesky_inverse", f, ensure_tensor(x))


def inv(x, name=None) -> Tensor:
    return apply_op("inv", jnp.linalg.inv, ensure_tensor(x))


inverse = inv


def det(x, name=None) -> Tensor:
    return apply_op("det", jnp.linalg.det, ensure_tensor(x))


def slogdet(x, name=None):
    t = ensure_tensor(x)

    def f(a):
        sign, logabs = jnp.linalg.slogdet(a)
        return sign, logabs

    return apply_op("slogdet", f, t)


def solve(x, y, name=None) -> Tensor:
    def f(a, b):
        # paddle solves a @ out = b with 1-D b treated as a column
        if b.ndim == a.ndim - 1:
            return jnp.linalg.solve(a, b[..., None])[..., 0]
        return jnp.linalg.solve(a, b)

    return apply_op("solve", f, ensure_tensor(x), ensure_tensor(y))


def triangular_solve(x, y, upper: bool = True, transpose: bool = False,
                     unitriangular: bool = False, name=None) -> Tensor:
    def f(a, b):
        return jax.lax.linalg.triangular_solve(
            a, b, left_side=True, lower=not upper, transpose_a=transpose,
            unit_diagonal=unitriangular)

    return apply_op("triangular_solve", f, ensure_tensor(x), ensure_tensor(y))


def svd(x, full_matrices: bool = False, name=None):
    return apply_op(
        "svd", lambda a: jnp.linalg.svd(a, full_matrices=full_matrices),
        ensure_tensor(x))


def qr(x, mode: str = "reduced", name=None):
    t = ensure_tensor(x)
    if mode == "r":
        return apply_op("qr", lambda a: jnp.linalg.qr(a, mode="r"), t)
    return apply_op("qr", lambda a: tuple(jnp.linalg.qr(a, mode=mode)), t)


def lu(x, pivot: bool = True, get_infos: bool = False, name=None):
    """Packed LU with 1-based pivots (reference lu_op semantics)."""
    if not pivot:
        raise NotImplementedError(
            "lu(pivot=False) (no partial pivoting) has no LAPACK/XLA "
            "lowering; use the default pivoted factorization")
    t = ensure_tensor(x)

    def f(a):
        lu_mat, piv = jax.scipy.linalg.lu_factor(a)
        return lu_mat, (piv + 1).astype(jnp.int32)

    lu_mat, piv = apply_op("lu", f, t)
    if get_infos:
        info = Tensor(jnp.zeros(t.shape[:-2] or (1,), jnp.int32))
        return lu_mat, piv, info
    return lu_mat, piv


def lu_unpack(x, y, unpack_ludata: bool = True, unpack_pivots: bool = True, name=None):
    """(LU packed, pivots) -> P, L, U (reference lu_unpack)."""
    xt, yt = ensure_tensor(x), ensure_tensor(y)

    def f(lu_mat, piv):
        m, n = lu_mat.shape[-2], lu_mat.shape[-1]
        k = min(m, n)
        L = jnp.tril(lu_mat[..., :, :k], -1) + jnp.eye(m, k, dtype=lu_mat.dtype)
        U = jnp.triu(lu_mat[..., :k, :])
        # pivots (1-based sequential row swaps) -> permutation matrix
        def perm_from_pivots(p):
            perm = jnp.arange(m)

            def body(i, perm):
                j = p[i] - 1
                pi, pj = perm[i], perm[j]
                perm = perm.at[i].set(pj).at[j].set(pi)
                return perm

            perm = jax.lax.fori_loop(0, p.shape[0], body, perm)
            return jnp.eye(m, dtype=lu_mat.dtype)[perm].T

        if piv.ndim == 1:
            P = perm_from_pivots(piv)
        else:
            P = jnp.vectorize(perm_from_pivots, signature="(k)->(m,m)")(piv)
        return P, L, U

    P, L, U = apply_op("lu_unpack", f, xt, yt)
    # reference flags: unpack_ludata=False suppresses L/U, unpack_pivots=
    # False suppresses P (None placeholders keep the 3-tuple shape)
    return (P if unpack_pivots else None,
            L if unpack_ludata else None,
            U if unpack_ludata else None)


def eigh(x, UPLO: str = "L", name=None):
    return apply_op(
        "eigh", lambda a: jnp.linalg.eigh(a, symmetrize_input=False,
                                          UPLO=UPLO), ensure_tensor(x))


def eigvalsh(x, UPLO: str = "L", name=None) -> Tensor:
    return apply_op("eigvalsh", lambda a: jnp.linalg.eigvalsh(a, UPLO=UPLO),
                    ensure_tensor(x))


def _np_eig(a):
    w, v = np.linalg.eig(a)
    return w.astype(np.complex64 if a.dtype in (np.float32, np.complex64)
                    else np.complex128), \
           v.astype(np.complex64 if a.dtype in (np.float32, np.complex64)
                    else np.complex128)


def eig(x, name=None):
    """General eigendecomposition — host callback (no TPU lowering exists;
    reference also restricts eig to CPU, phi/kernels/cpu/eig_kernel.cc)."""
    t = ensure_tensor(x)
    a = t._data
    cdt = jnp.complex64 if a.dtype in (jnp.float32, jnp.complex64) else jnp.complex128
    out_shapes = (jax.ShapeDtypeStruct(a.shape[:-1], cdt),
                  jax.ShapeDtypeStruct(a.shape, cdt))
    w, v = jax.pure_callback(_np_eig, out_shapes, a, vmap_method="sequential")
    return Tensor(w), Tensor(v)


def eigvals(x, name=None) -> Tensor:
    w, _ = eig(x)
    return w


def matrix_power(x, n: int, name=None) -> Tensor:
    return apply_op("matrix_power", lambda a: jnp.linalg.matrix_power(a, n),
                    ensure_tensor(x))


def matrix_exp(x, name=None) -> Tensor:
    return apply_op("matrix_exp", jax.scipy.linalg.expm, ensure_tensor(x))


def matrix_rank(x, tol=None, hermitian: bool = False, rtol=None, atol=None, name=None) -> Tensor:
    t = ensure_tensor(x)

    def f(a):
        s = (jnp.abs(jnp.linalg.eigvalsh(a)) if hermitian
             else jnp.linalg.svd(a, compute_uv=False))
        smax = jnp.max(s, axis=-1, keepdims=True)
        if tol is not None:
            thr = jnp.asarray(tol, s.dtype)
            thr = jnp.broadcast_to(thr, smax.shape) if jnp.ndim(thr) == 0 else thr[..., None]
        elif rtol is not None or atol is not None:
            r = jnp.asarray(0.0 if rtol is None else rtol, s.dtype)
            a_ = jnp.asarray(0.0 if atol is None else atol, s.dtype)
            thr = jnp.maximum(a_, r * smax)
        else:
            eps = jnp.finfo(s.dtype).eps
            thr = smax * max(a.shape[-2], a.shape[-1]) * eps
        return jnp.sum(s > thr, axis=-1).astype(jnp.int64)

    return apply_op("matrix_rank", f, t)


def multi_dot(x: Sequence, name=None) -> Tensor:
    ts = [ensure_tensor(t) for t in x]
    return apply_op("multi_dot", lambda *arrs: jnp.linalg.multi_dot(arrs), *ts)


def pinv(x, rcond=1e-15, hermitian: bool = False, name=None) -> Tensor:
    return apply_op(
        "pinv", lambda a: jnp.linalg.pinv(a, rtol=rcond, hermitian=hermitian),
        ensure_tensor(x))


def lstsq(x, y, rcond=None, driver=None, name=None):
    """Returns (solution, residuals, rank, singular_values) like the
    reference lstsq_op."""
    if driver not in (None, "gels", "gelsd"):
        raise NotImplementedError(
            f"lstsq driver {driver!r}: only the default SVD-backed path "
            "('gelsd'-equivalent) exists on XLA")
    xt, yt = ensure_tensor(x), ensure_tensor(y)

    def f(a, b):
        b2 = b[..., None] if b.ndim == a.ndim - 1 else b
        sol, res, rank, sv = jnp.linalg.lstsq(a, b2, rcond=rcond)
        if b.ndim == a.ndim - 1:
            sol = sol[..., 0]
        return sol, res, rank.astype(jnp.int32), sv

    return apply_op("lstsq", f, xt, yt)


def householder_product(x, tau, name=None) -> Tensor:
    """Accumulate Q from Householder reflectors (geqrf layout)."""
    xt, tt = ensure_tensor(x), ensure_tensor(tau)

    def f(a, t):
        m, n = a.shape[-2], a.shape[-1]
        k = t.shape[-1]

        def one(a2, t2):
            Q = jnp.eye(m, dtype=a2.dtype)

            def body(i, Q):
                v = jnp.where(jnp.arange(m) < i, 0.0, a2[:, i]).at[i].set(1.0)
                H = jnp.eye(m, dtype=a2.dtype) - t2[i] * jnp.outer(v, v.conj())
                return Q @ H

            Q = jax.lax.fori_loop(0, k, body, Q)
            return Q[:, :n]

        if a.ndim == 2:
            return one(a, t)
        batch = a.shape[:-2]
        a_f = a.reshape((-1, m, n))
        t_f = t.reshape((-1, k))
        out = jax.vmap(one)(a_f, t_f)
        return out.reshape(batch + (m, n))

    return apply_op("householder_product", f, xt, tt)


def ormqr(x, tau, other, left: bool = True, transpose: bool = False, name=None) -> Tensor:
    """Multiply ``other`` by Q from a geqrf factorization (reference ormqr)."""
    q = householder_product(x, tau)
    qd = q._data

    def f(qm, c):
        qm2 = jnp.swapaxes(qm, -1, -2).conj() if transpose else qm
        return qm2 @ c if left else c @ qm2

    return apply_op("ormqr", f, Tensor(qd), ensure_tensor(other))


# ---------------------------------------------------------------------------
# Norms / statistics
# ---------------------------------------------------------------------------


def vector_norm(x, p=2.0, axis=None, keepdim: bool = False, name=None) -> Tensor:
    t = ensure_tensor(x)

    def f(a):
        ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
        if p == float("inf"):
            return jnp.max(jnp.abs(a), axis=ax, keepdims=keepdim)
        if p == float("-inf"):
            return jnp.min(jnp.abs(a), axis=ax, keepdims=keepdim)
        if p == 0:
            return jnp.sum((a != 0).astype(a.dtype), axis=ax, keepdims=keepdim)
        return jnp.sum(jnp.abs(a) ** p, axis=ax, keepdims=keepdim) ** (1.0 / p)

    return apply_op("vector_norm", f, t)


def matrix_norm(x, p="fro", axis=(-2, -1), keepdim: bool = False, name=None) -> Tensor:
    t = ensure_tensor(x)

    def f(a):
        return jnp.linalg.norm(a, ord=p, axis=tuple(axis), keepdims=keepdim)

    return apply_op("matrix_norm", f, t)


def norm(x, p=None, axis=None, keepdim: bool = False, name=None) -> Tensor:
    """paddle.linalg.norm dispatch: fro/nuc/p-vector/p-matrix by axis arity."""
    t = ensure_tensor(x)
    if isinstance(p, str):  # 'fro' / 'nuc' imply a matrix norm over trailing dims
        return matrix_norm(t, p, axis if axis is not None else (-2, -1), keepdim)
    if axis is None and p is None:
        flat = t.reshape([-1]) if t.ndim != 1 else t
        return vector_norm(flat, 2.0, None, keepdim)
    if axis is None:
        return vector_norm(t.reshape([-1]) if t.ndim != 1 else t, p, None, keepdim)
    if isinstance(axis, (tuple, list)) and len(axis) == 2:
        return matrix_norm(t, "fro" if p is None else p, axis, keepdim)
    return vector_norm(t, 2.0 if p is None else p, axis, keepdim)


def cond(x, p=None, name=None) -> Tensor:
    return apply_op("cond",
                    lambda a: jnp.linalg.cond(a, p=p), ensure_tensor(x))


def cov(x, rowvar: bool = True, ddof: bool = True, fweights=None,
        aweights=None, name=None) -> Tensor:
    t = ensure_tensor(x)
    fw = None if fweights is None else ensure_tensor(fweights)._data
    aw = None if aweights is None else ensure_tensor(aweights)._data

    def f(a):
        return jnp.cov(a, rowvar=rowvar, ddof=1 if ddof else 0,
                       fweights=fw, aweights=aw)

    return apply_op("cov", f, t)


def corrcoef(x, rowvar: bool = True, name=None) -> Tensor:
    return apply_op("corrcoef", lambda a: jnp.corrcoef(a, rowvar=rowvar),
                    ensure_tensor(x))


# ---------------------------------------------------------------------------
# Low-rank (randomized) decompositions — reference pca_lowrank/svd_lowrank
# ---------------------------------------------------------------------------


def svd_lowrank(x, q: Optional[int] = 6, niter: int = 2, M=None, name=None):
    t = ensure_tensor(x)
    from .ops.random import split_key

    key = split_key()
    Md = None if M is None else ensure_tensor(M)._data

    def f(a):
        a2 = a - Md if Md is not None else a
        m, n = a2.shape[-2], a2.shape[-1]
        k = min(q or 6, m, n)
        omega = jax.random.normal(key, a2.shape[:-2] + (n, k), a2.dtype)
        Y = a2 @ omega
        Q, _ = jnp.linalg.qr(Y)
        for _ in range(niter):
            Z = jnp.swapaxes(a2, -1, -2) @ Q
            Qz, _ = jnp.linalg.qr(Z)
            Y = a2 @ Qz
            Q, _ = jnp.linalg.qr(Y)
        B = jnp.swapaxes(Q, -1, -2) @ a2
        Ub, s, Vh = jnp.linalg.svd(B, full_matrices=False)
        U = Q @ Ub
        return U, s, jnp.swapaxes(Vh, -1, -2)

    return apply_op("svd_lowrank", f, t)


def pca_lowrank(x, q: Optional[int] = None, center: bool = True, niter: int = 2, name=None):
    t = ensure_tensor(x)
    if q is None:
        q = min(6, t.shape[-2], t.shape[-1])
    if center:
        mean = t._data.mean(axis=-2, keepdims=True)
        return svd_lowrank(Tensor(t._data - mean), q=q, niter=niter)
    return svd_lowrank(t, q=q, niter=niter)
