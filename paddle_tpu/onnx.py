"""paddle.onnx equivalent — model export entry point.

Parity: python/paddle/onnx/export.py (paddle.onnx.export, which delegates
to paddle2onnx). Export writes the framework's portable program artifact
(serialized StableHLO via jit.save — an open interchange format consumable
by ONNX-MLIR/IREE toolchains). Callers that require true .onnx protobuf
output pass ``require_onnx=True`` and get an explicit NotImplementedError
until a StableHLO->ONNX translation lands.
"""

from __future__ import annotations

import os
from typing import Optional, Sequence

__all__ = ["export"]


def export(layer, path: str, input_spec: Optional[Sequence] = None,
           opset_version: int = 9, **configs) -> str:
    """Export ``layer`` for external serving. Writes the StableHLO program
    artifact at ``path`` (+ .pdmodel/.pdiparams/.pdmeta) and returns the
    written prefix; raises if true ONNX protobuf output is requested but
    unavailable."""
    from .jit.save_load import save as jit_save

    prefix = path[:-5] if path.endswith(".onnx") else path
    jit_save(layer, prefix, input_spec=input_spec)
    if path.endswith(".onnx") or configs.get("require_onnx"):
        # true protobuf export: trace the layer and map jax primitives to
        # ONNX nodes (onnx_export.py); params become initializers
        import jax.numpy as jnp

        from .core.tensor import Tensor
        from .onnx_export import export_onnx
        from .utils.functional import functional_call

        params = {k: v._data for k, v in layer.state_dict().items()}

        import jax as _jax

        def fwd(params, *xs):
            out = functional_call(layer, params, *[Tensor(x) for x in xs])
            return _jax.tree.map(lambda t: t._data if isinstance(t, Tensor) else t,
                                 out, is_leaf=lambda t: isinstance(t, Tensor))

        if input_spec is None:
            raise ValueError("onnx export requires input_spec with shapes")
        examples, decl_shapes = [], []
        for spec in input_spec:
            shape = [1 if (s is None or s == -1) else int(s) for s in spec.shape]
            decl_shapes.append(list(spec.shape))
            examples.append(jnp.zeros(shape, getattr(spec, "dtype", jnp.float32)))
        model_bytes = export_onnx(fwd, examples, params=params,
                                  input_shapes=decl_shapes)
        onnx_path = prefix + ".onnx"
        with open(onnx_path, "wb") as f:
            f.write(model_bytes)
        return onnx_path
    return prefix
