"""paddle.onnx equivalent — model export entry point.

Parity: python/paddle/onnx/export.py (paddle.onnx.export, which delegates
to paddle2onnx). Export writes the framework's portable program artifact
(serialized StableHLO via jit.save — an open interchange format consumable
by ONNX-MLIR/IREE toolchains). Callers that require true .onnx protobuf
output pass ``require_onnx=True`` and get an explicit NotImplementedError
until a StableHLO->ONNX translation lands.
"""

from __future__ import annotations

import os
from typing import Optional, Sequence

__all__ = ["export"]


def export(layer, path: str, input_spec: Optional[Sequence] = None,
           opset_version: int = 9, **configs) -> str:
    """Export ``layer`` for external serving. Writes the StableHLO program
    artifact at ``path`` (+ .pdmodel/.pdiparams/.pdmeta) and returns the
    written prefix; raises if true ONNX protobuf output is requested but
    unavailable."""
    from .jit.save_load import save as jit_save

    prefix = path[:-5] if path.endswith(".onnx") else path
    jit_save(layer, prefix, input_spec=input_spec)
    if configs.get("require_onnx"):
        # only an explicit request for protobuf output errors; the default
        # contract is the portable StableHLO artifact
        raise NotImplementedError(
            "StableHLO->ONNX graph translation is not implemented; consume the "
            f"serialized program at {prefix}.pdmodel instead")
    return prefix
