"""paddle.save / paddle.load equivalent.

Parity: python/paddle/framework/io.py:773 save, :1020 load — pickled
nested state structures with tensors serialized by value. Tensors are
stored as raw bytes + dtype/shape metadata (host transfer at save; device
upload at load), matching the reference's DenseTensor serialization
semantics. Extended dtypes (bfloat16, fp8) round-trip via ml_dtypes.
"""

from __future__ import annotations

import os
import pickle
from typing import Any

import jax.numpy as jnp
import ml_dtypes
import numpy as np

from ..core.tensor import Parameter, Tensor

_PROTO = 4

_EXT_DTYPES = {
    "bfloat16": ml_dtypes.bfloat16,
    "float8_e4m3fn": ml_dtypes.float8_e4m3fn,
    "float8_e5m2": ml_dtypes.float8_e5m2,
}


def _np_dtype(name: str):
    if name in _EXT_DTYPES:
        return np.dtype(_EXT_DTYPES[name])
    return np.dtype(name)


class _TensorPayload:
    """Pickle-stable tensor wrapper (raw bytes + metadata)."""

    def __init__(self, array, trainable: bool = False, name=None):
        a = np.asarray(array)
        self.dtype_name = a.dtype.name
        self.shape = a.shape
        self.buf = a.tobytes()
        self.trainable = trainable
        self.name = name

    def to_numpy(self) -> np.ndarray:
        return np.frombuffer(self.buf, dtype=_np_dtype(self.dtype_name)).reshape(self.shape)


def _pack(obj):
    if isinstance(obj, Parameter):
        return _TensorPayload(np.asarray(obj._data), obj.trainable, obj.name)
    if isinstance(obj, Tensor):
        return _TensorPayload(np.asarray(obj._data), False, obj.name)
    if isinstance(obj, dict):
        return {k: _pack(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = type(obj)
        return t(_pack(v) for v in obj)
    return obj


def _unpack(obj, return_numpy=False):
    if isinstance(obj, _TensorPayload):
        arr = obj.to_numpy()
        if return_numpy:
            return arr
        return Tensor(jnp.asarray(arr), name=obj.name)
    if isinstance(obj, dict):
        return {k: _unpack(v, return_numpy) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = type(obj)
        return t(_unpack(v, return_numpy) for v in obj)
    return obj


def save(obj: Any, path: str, protocol: int = _PROTO, **configs):
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "wb") as f:
        pickle.dump(_pack(obj), f, protocol=protocol)


def load(path: str, return_numpy: bool = False, **configs) -> Any:
    with open(path, "rb") as f:
        obj = pickle.load(f)
    return _unpack(obj, return_numpy=return_numpy)
