from . import io_utils, random_utils
from .io_utils import load, save
