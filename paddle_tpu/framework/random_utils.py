"""RNG state API parity (paddle.get_cuda_rng_state etc.)."""

from __future__ import annotations

from ..ops.random import get_rng_state, seed, set_rng_state


def get_cuda_rng_state():
    return get_rng_state()


def set_cuda_rng_state(state):
    set_rng_state(state)
