"""Automatic SParsity (ASP) — n:m structured sparsity workflow.

Parity: python/paddle/incubate/asp/ (prune_model, decorate,
calculate_density, check_sparsity, set/reset_excluded_layers; mask algos
utils.py get_mask_1d/get_mask_2d_best). TPU note: n:m masks keep the
matmul shapes static — XLA treats masked weights as dense bf16, so ASP
here is a training-workflow feature (mask maintenance across optimizer
steps) exactly like the reference's pre-Ampere CPU path.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import jax.numpy as jnp
import numpy as np

from .. import nn
from ..core.tensor import Tensor

__all__ = ["calculate_density", "check_sparsity", "get_mask_1d", "get_mask_2d_best",
           "get_mask_2d_greedy", "prune_model", "decorate", "set_excluded_layers",
           "reset_excluded_layers"]

_excluded: Dict[int, List[str]] = {}
# id(param) -> (weakref to param, mask): the weakref guards against both
# leak-forever growth and id() reuse applying a dead model's mask
_masks: Dict[int, tuple] = {}


def _set_mask(p, mask: np.ndarray):
    import weakref

    _masks[id(p)] = (weakref.ref(p), mask)


def _get_mask(p) -> Optional[np.ndarray]:
    entry = _masks.get(id(p))
    if entry is None:
        return None
    ref, mask = entry
    if ref() is not p:  # param died and id was reused
        del _masks[id(p)]
        return None
    return mask


def calculate_density(x) -> float:
    arr = np.asarray(x._data if isinstance(x, Tensor) else x)
    return float(np.count_nonzero(arr)) / max(arr.size, 1)


def get_mask_1d(mat: np.ndarray, n: int = 2, m: int = 4) -> np.ndarray:
    """Keep the n largest-|w| entries of every m-length group along the last
    dim (parity: asp/utils.py get_mask_1d)."""
    shape = mat.shape
    flat = np.abs(mat.reshape(-1, m))
    order = np.argsort(-flat, axis=1)
    mask = np.zeros_like(flat, dtype=bool)
    np.put_along_axis(mask, order[:, :n], True, axis=1)
    return mask.reshape(shape)


_VALID_2D_PATTERNS: Dict[tuple, np.ndarray] = {}


def _valid_2d_patterns(n: int, m: int) -> np.ndarray:
    """All m×m 0/1 blocks with every row AND column summing to n (parity:
    asp/utils.py compute_valid_2d_patterns). 90 patterns for 2:4."""
    key = (n, m)
    if key not in _VALID_2D_PATTERNS:
        import itertools

        rows = [np.array([1 if i in c else 0 for i in range(m)])
                for c in itertools.combinations(range(m), n)]
        pats = [np.stack(combo) for combo in itertools.product(rows, repeat=m)
                if (np.stack(combo).sum(0) == n).all()]
        _VALID_2D_PATTERNS[key] = np.stack(pats).astype(bool)
    return _VALID_2D_PATTERNS[key]


def get_mask_2d_greedy(mat: np.ndarray, n: int = 2, m: int = 4) -> np.ndarray:
    """Greedy n:m mask over m×m blocks: repeatedly keep the largest |w|
    whose row and column quotas (< n) are both open (parity: asp/utils.py
    get_mask_2d_greedy — near-linear, works for any m)."""
    if mat.ndim < 2 or mat.shape[-1] % m or mat.shape[-2] % m:
        raise ValueError(f"get_mask_2d_greedy needs trailing dims divisible by {m}")
    lead = mat.shape[:-2]
    R, C = mat.shape[-2], mat.shape[-1]
    a = np.abs(mat.reshape(-1, R // m, m, C // m, m).transpose(0, 1, 3, 2, 4)).reshape(-1, m, m)
    masks = np.zeros_like(a, dtype=bool)
    for b in range(a.shape[0]):
        order = np.argsort(-a[b].ravel())
        rows = np.zeros(m, np.int64)
        cols = np.zeros(m, np.int64)
        taken = 0
        for idx in order:
            r, c = divmod(int(idx), m)
            if rows[r] < n and cols[c] < n:
                masks[b, r, c] = True
                rows[r] += 1
                cols[c] += 1
                taken += 1
                if taken == n * m:
                    break
    mask = masks.reshape(-1, R // m, C // m, m, m).transpose(0, 1, 3, 2, 4)
    return mask.reshape(mat.shape)


def get_mask_2d_best(mat: np.ndarray, n: int = 2, m: int = 4) -> np.ndarray:
    """Exhaustive n:m mask over m×m blocks satisfying n:m along BOTH dims,
    maximizing retained |w| (parity: asp/utils.py get_mask_2d_best).
    Pattern enumeration is C(m,n)^m, so only small groups are exact; larger
    m falls back to the greedy variant."""
    if mat.ndim < 2 or mat.shape[-1] % m or mat.shape[-2] % m:
        raise ValueError(f"get_mask_2d_best needs trailing dims divisible by {m}")
    if m > 4:
        return get_mask_2d_greedy(mat, n, m)
    pats = _valid_2d_patterns(n, m)           # [P, m, m]
    lead = mat.shape[:-2]
    R, C = mat.shape[-2], mat.shape[-1]
    a = np.abs(mat.reshape(-1, R // m, m, C // m, m).transpose(0, 1, 3, 2, 4))
    blocks = a.reshape(-1, m, m)              # [B, m, m]
    scores = np.einsum("bij,pij->bp", blocks, pats)
    best = pats[np.argmax(scores, axis=1)]    # [B, m, m]
    mask = best.reshape(-1, R // m, C // m, m, m).transpose(0, 1, 3, 2, 4)
    return mask.reshape(mat.shape)


def check_sparsity(mat, n: int = 2, m: int = 4) -> bool:
    arr = np.asarray(mat._data if isinstance(mat, Tensor) else mat)
    if arr.size % m:
        return False
    groups = (arr.reshape(-1, m) != 0).sum(axis=1)
    return bool((groups <= n).all())


def set_excluded_layers(model, layer_names: List[str]):
    _excluded[id(model)] = list(layer_names)


def reset_excluded_layers(model=None):
    if model is None:
        _excluded.clear()
    else:
        _excluded.pop(id(model), None)


def _prunable(model, m: int = 4):
    excluded = set(_excluded.get(id(model), []))
    for name, layer in model.named_sublayers():
        if name in excluded:
            continue
        if isinstance(layer, (nn.Linear, nn.Conv2D)) and hasattr(layer, "weight"):
            w = layer.weight
            if int(w.shape[-1]) % m == 0:  # per-row n:m groups must not span rows
                yield name, layer


def prune_model(model, n: int = 2, m: int = 4, mask_algo: str = "mask_1d",
                with_mask: bool = True):
    """Compute and apply n:m masks to all prunable weights; masks are
    remembered so `decorate`d optimizers re-apply them after each step."""
    algo = {"mask_1d": get_mask_1d, "mask_2d_best": get_mask_2d_best,
            "mask_2d_greedy": get_mask_2d_greedy}[mask_algo]
    pruned = {}
    for name, layer in _prunable(model, m):
        w = layer.weight
        arr = np.asarray(w._data, np.float32)
        if algo is not get_mask_1d and (arr.ndim < 2 or arr.shape[-2] % m):
            mask = get_mask_1d(arr, n, m)  # 2-D pattern needs both dims divisible
        else:
            mask = algo(arr, n, m)
        w._data = (jnp.asarray(arr * mask)).astype(w._data.dtype)
        if with_mask:
            _set_mask(w, mask)
        pruned[name] = mask
    return pruned


def decorate(optimizer):
    """Wrap an optimizer so masked weights stay pruned after updates
    (parity: OptimizerWithSparsityGuarantee — mask re-applied post-step)."""
    inner_step = optimizer.step

    def step_with_masks(*args, **kwargs):
        out = inner_step(*args, **kwargs)
        for p in optimizer._parameter_list:
            mask = _get_mask(p)
            if mask is not None:
                p._data = p._data * jnp.asarray(mask, p._data.dtype)
        return out

    optimizer.step = step_with_masks
    return optimizer
