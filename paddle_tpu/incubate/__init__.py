"""paddle.incubate equivalent — fused ops, MoE models, experimental API.

Parity: python/paddle/incubate/ (nn.functional fused ops,
distributed.models.moe, asp stubs).
"""

from . import asp
from . import nn
from . import distributed

__all__ = ["asp", "nn", "distributed"]
