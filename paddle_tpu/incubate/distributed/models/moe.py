"""paddle.incubate.distributed.models.moe — re-exported MoE stack.

Parity: python/paddle/incubate/distributed/models/moe/ (MoELayer +
gate zoo). The implementation lives in paddle_tpu.distributed.moe —
expert-parallel all-to-all dispatch expressed with mesh sharding instead
of global_scatter/global_gather collective ops.
"""

from ....distributed.moe import (
    BaseGate,
    ExpertMLP,
    GShardGate,
    MoELayer,
    NaiveGate,
    SwitchGate,
    gshard_routing,
)

__all__ = ["MoELayer", "BaseGate", "NaiveGate", "SwitchGate", "GShardGate",
           "ExpertMLP", "gshard_routing"]
