"""Fused transformer Layer classes (reference
python/paddle/incubate/nn/layer/fused_transformer.py:94,213,534,750).

On TPU "fused" means XLA-fused: the classes carry the REFERENCE weight
layouts (qkv_weight [3, h, d, e] etc., so fused checkpoints load
unchanged) and forward through incubate.nn.functional, whose jnp chains
XLA fuses the way the reference's hand-written CUDA kernels do.
"""

from __future__ import annotations

from typing import Optional

from ...nn.layer import Layer
from . import functional as FF

__all__ = ["FusedBiasDropoutResidualLayerNorm", "FusedMultiHeadAttention",
           "FusedFeedForward", "FusedTransformerEncoderLayer"]


class FusedBiasDropoutResidualLayerNorm(Layer):
    """out = layer_norm(residual + dropout(x + bias)); reference :94."""

    def __init__(self, embed_dim, dropout_rate: float = 0.5,
                 weight_attr=None, bias_attr=None, epsilon: float = 1e-5,
                 name=None):
        super().__init__()
        assert embed_dim > 0
        self.embed_dim = embed_dim
        self.dropout_rate = dropout_rate
        self._epsilon = epsilon
        self.linear_bias = self.create_parameter([embed_dim], attr=bias_attr,
                                                 is_bias=True)
        from ...nn.initializer import Constant

        self.ln_scale = self.create_parameter(
            [embed_dim], attr=weight_attr, default_initializer=Constant(1.0))
        self.ln_bias = self.create_parameter([embed_dim], attr=bias_attr,
                                             is_bias=True)

    def forward(self, x, residual):
        return FF.fused_bias_dropout_residual_layer_norm(
            x, residual, bias=self.linear_bias, ln_scale=self.ln_scale,
            ln_bias=self.ln_bias, dropout_rate=self.dropout_rate,
            ln_epsilon=self._epsilon, training=self.training)


class FusedMultiHeadAttention(Layer):
    """Fused self-attention block with residual + layer norm; reference
    :213. Weight layouts match the reference kernels: qkv_weight
    [3, num_heads, head_dim, embed_dim], qkv_bias [3, num_heads,
    head_dim], linear_weight [num_heads*head_dim, embed_dim]."""

    def __init__(self, embed_dim, num_heads, dropout_rate: float = 0.5,
                 attn_dropout_rate: float = 0.5, kdim=None, vdim=None,
                 normalize_before: bool = False, need_weights: bool = False,
                 qkv_weight_attr=None, qkv_bias_attr=None,
                 linear_weight_attr=None, linear_bias_attr=None,
                 pre_ln_scale_attr=None, pre_ln_bias_attr=None,
                 ln_scale_attr=None, ln_bias_attr=None,
                 epsilon: float = 1e-5, nranks: int = 1, ring_id: int = -1,
                 transpose_qkv_wb: bool = False, name=None):
        super().__init__()
        assert embed_dim > 0 and num_heads > 0
        assert embed_dim % num_heads == 0, "embed_dim must divide num_heads"
        assert need_weights is False, "Only need_weights=False is supported"
        if transpose_qkv_wb:
            raise NotImplementedError(
                "transpose_qkv_wb is a CUDA kernel-layout knob; use the "
                "default [3, h, d, e] layout")
        if kdim not in (None, embed_dim) or vdim not in (None, embed_dim):
            raise NotImplementedError(
                "the fused kernel is self-attention only (kdim/vdim must "
                "equal embed_dim)")
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        self.normalize_before = normalize_before
        self.dropout_rate = dropout_rate
        self.attn_dropout_rate = attn_dropout_rate
        self._epsilon = epsilon
        h, d = num_heads, self.head_dim
        self.qkv_weight = self.create_parameter([3, h, d, embed_dim],
                                                attr=qkv_weight_attr)
        self.qkv_bias = self.create_parameter([3, h, d], attr=qkv_bias_attr,
                                              is_bias=True)
        self.linear_weight = self.create_parameter([h * d, embed_dim],
                                                   attr=linear_weight_attr)
        self.linear_bias = self.create_parameter([embed_dim],
                                                 attr=linear_bias_attr,
                                                 is_bias=True)
        from ...nn.initializer import Constant

        if normalize_before:
            self.pre_ln_scale = self.create_parameter(
                [embed_dim], attr=pre_ln_scale_attr,
                default_initializer=Constant(1.0))
            self.pre_ln_bias = self.create_parameter(
                [embed_dim], attr=pre_ln_bias_attr, is_bias=True)
            self.ln_scale = self.ln_bias = None
        else:
            self.pre_ln_scale = self.pre_ln_bias = None
            self.ln_scale = self.create_parameter(
                [embed_dim], attr=ln_scale_attr,
                default_initializer=Constant(1.0))
            self.ln_bias = self.create_parameter([embed_dim],
                                                 attr=ln_bias_attr,
                                                 is_bias=True)

    def forward(self, query, key=None, value=None, attn_mask=None, cache=None):
        if (key is not None and key is not query) or \
                (value is not None and value is not query):
            raise NotImplementedError(
                "self-attention only (key/value must be None or the query)")
        return FF.fused_multi_head_attention(
            query, self.qkv_weight, self.linear_weight,
            pre_layer_norm=self.normalize_before,
            pre_ln_scale=self.pre_ln_scale, pre_ln_bias=self.pre_ln_bias,
            ln_scale=self.ln_scale, ln_bias=self.ln_bias,
            pre_ln_epsilon=self._epsilon, qkv_bias=self.qkv_bias,
            linear_bias=self.linear_bias, cache_kv=cache,
            attn_mask=attn_mask, dropout_rate=self.dropout_rate,
            attn_dropout_rate=self.attn_dropout_rate,
            ln_epsilon=self._epsilon, training=self.training)


class FusedFeedForward(Layer):
    """Fused FFN block with residual + layer norm; reference :534."""

    def __init__(self, d_model, dim_feedforward, dropout_rate: float = 0.1,
                 epsilon: float = 1e-5, activation: str = "relu",
                 act_dropout_rate=None, normalize_before: bool = False,
                 linear1_weight_attr=None, linear1_bias_attr=None,
                 linear2_weight_attr=None, linear2_bias_attr=None,
                 ln1_scale_attr=None, ln1_bias_attr=None,
                 ln2_scale_attr=None, ln2_bias_attr=None,
                 nranks: int = 1, ring_id: int = -1, name=None):
        super().__init__()
        assert d_model > 0 and dim_feedforward > 0
        self._d_model = d_model
        self._dropout_rate = dropout_rate
        self._act_dropout_rate = (dropout_rate if act_dropout_rate is None
                                  else act_dropout_rate)
        self._act_method = activation
        self._normalize_before = normalize_before
        self._epsilon = epsilon
        self.linear1_weight = self.create_parameter(
            [d_model, dim_feedforward], attr=linear1_weight_attr)
        self.linear1_bias = self.create_parameter(
            [dim_feedforward], attr=linear1_bias_attr, is_bias=True)
        self.linear2_weight = self.create_parameter(
            [dim_feedforward, d_model], attr=linear2_weight_attr)
        self.linear2_bias = self.create_parameter([d_model],
                                                  attr=linear2_bias_attr,
                                                  is_bias=True)
        from ...nn.initializer import Constant

        if normalize_before:
            self._ln1_scale = self.create_parameter(
                [d_model], attr=ln1_scale_attr,
                default_initializer=Constant(1.0))
            self._ln1_bias = self.create_parameter([d_model],
                                                   attr=ln1_bias_attr,
                                                   is_bias=True)
            self._ln2_scale = self._ln2_bias = None
        else:
            self._ln1_scale = self._ln1_bias = None
            self._ln2_scale = self.create_parameter(
                [d_model], attr=ln2_scale_attr,
                default_initializer=Constant(1.0))
            self._ln2_bias = self.create_parameter([d_model],
                                                   attr=ln2_bias_attr,
                                                   is_bias=True)

    def forward(self, src, cache=None):
        if cache is not None:
            raise NotImplementedError(
                "FusedFeedForward has no cache state; decode caches live in "
                "the attention layers")
        return FF.fused_feedforward(
            src, self.linear1_weight, self.linear2_weight,
            linear1_bias=self.linear1_bias, linear2_bias=self.linear2_bias,
            ln1_scale=self._ln1_scale, ln1_bias=self._ln1_bias,
            ln2_scale=self._ln2_scale, ln2_bias=self._ln2_bias,
            dropout1_rate=self._act_dropout_rate,
            dropout2_rate=self._dropout_rate,
            activation=self._act_method, ln1_epsilon=self._epsilon,
            ln2_epsilon=self._epsilon,
            pre_layer_norm=self._normalize_before, training=self.training)


class FusedTransformerEncoderLayer(Layer):
    """FusedMultiHeadAttention + FusedFeedForward; reference :750."""

    def __init__(self, d_model, nhead, dim_feedforward,
                 dropout_rate: float = 0.1, activation: str = "relu",
                 attn_dropout_rate=None, act_dropout_rate=None,
                 normalize_before: bool = False, weight_attr=None,
                 bias_attr=None):
        super().__init__()
        assert d_model > 0 and nhead > 0 and dim_feedforward > 0
        attn_dropout_rate = (dropout_rate if attn_dropout_rate is None
                             else attn_dropout_rate)
        act_dropout_rate = (dropout_rate if act_dropout_rate is None
                            else act_dropout_rate)
        self.normalize_before = normalize_before
        self.fused_attn = FusedMultiHeadAttention(
            d_model, nhead, dropout_rate=dropout_rate,
            attn_dropout_rate=attn_dropout_rate,
            normalize_before=normalize_before,
            qkv_weight_attr=weight_attr, qkv_bias_attr=bias_attr,
            linear_weight_attr=weight_attr, linear_bias_attr=bias_attr)
        self.ffn = FusedFeedForward(
            d_model, dim_feedforward, dropout_rate=dropout_rate,
            activation=activation, act_dropout_rate=act_dropout_rate,
            normalize_before=normalize_before,
            linear1_weight_attr=weight_attr, linear1_bias_attr=bias_attr,
            linear2_weight_attr=weight_attr, linear2_bias_attr=bias_attr)

    def forward(self, src, src_mask=None, cache=None):
        out = self.fused_attn(src, attn_mask=src_mask, cache=cache)
        return self.ffn(out)
