"""paddle.incubate.nn.functional — fused-op surface.

Parity: python/paddle/incubate/nn/functional/ (fused_rms_norm,
fused_rotary_position_embedding, fused_multi_head_attention,
fused_feedforward, fused_moe, fused_layer_norm, swiglu). TPU design: XLA
already fuses the elementwise pipelines these CUDA kernels hand-fuse, so
each "fused" op is the composite expressed as one jax function dispatched
as a single tape op (one grad node, one fusion boundary) — and attention
routes to the Pallas flash kernel on TPU.
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp

from ...core.tensor import Tensor
from ...nn import functional as F
from ...ops.dispatch import apply_op

__all__ = [
    "fused_rms_norm", "fused_layer_norm", "fused_rotary_position_embedding",
    "fused_multi_head_attention", "fused_feedforward", "swiglu",
    "fused_bias_act", "fused_linear", "fused_linear_activation",
    "fused_bias_dropout_residual_layer_norm",
]


def fused_rms_norm(x, norm_weight, norm_bias=None, epsilon: float = 1e-6,
                   begin_norm_axis: int = -1, **kwargs):
    nd = len(x.shape)
    if begin_norm_axis % nd != nd - 1:
        raise NotImplementedError(
            "fused_rms_norm normalizes the last axis; reshape for "
            f"begin_norm_axis={begin_norm_axis}")

    def fn(x, w, *rest):
        xf = x.astype(jnp.float32)
        var = jnp.mean(jnp.square(xf), axis=-1, keepdims=True)
        out = (xf * jax.lax.rsqrt(var + epsilon)).astype(x.dtype) * w
        if rest:
            out = out + rest[0]
        return out

    args = (x, norm_weight) + ((norm_bias,) if norm_bias is not None else ())
    return apply_op("fused_rms_norm", fn, *args)


def fused_layer_norm(x, norm_weight=None, norm_bias=None,
                     epsilon: float = 1e-5, **kwargs):
    """norm_weight/norm_bias None: identity scale / zero shift (the
    reference kernels treat them as optional)."""
    has_w, has_b = norm_weight is not None, norm_bias is not None

    def fn(x, *wb):
        xf = x.astype(jnp.float32)
        mu = jnp.mean(xf, axis=-1, keepdims=True)
        var = jnp.var(xf, axis=-1, keepdims=True)
        out = ((xf - mu) * jax.lax.rsqrt(var + epsilon)).astype(x.dtype)
        i = 0
        if has_w:
            out = out * wb[i]
            i += 1
        if has_b:
            out = out + wb[i]
        return out

    args = [x] + [a for a in (norm_weight, norm_bias) if a is not None]
    return apply_op("fused_layer_norm", fn, *args)


def fused_rotary_position_embedding(q, k=None, v=None, sin=None, cos=None,
                                    position_ids=None, use_neox_rotary_style: bool = True):
    """RoPE over [B, S, H, D] (parity: incubate fused_rope). neox style =
    half-split rotation; otherwise interleaved pairs."""

    def make_tables(seqlen, dim, dtype):
        inv = 1.0 / (10000.0 ** (jnp.arange(0, dim, 2, dtype=jnp.float32) / dim))
        t = jnp.arange(seqlen, dtype=jnp.float32)
        freqs = jnp.outer(t, inv)
        return jnp.cos(freqs).astype(dtype), jnp.sin(freqs).astype(dtype)

    pos = None
    if position_ids is not None:
        pos = position_ids._data if isinstance(position_ids, Tensor) else jnp.asarray(position_ids)

    def rope_one(x, cos_t, sin_t):
        # x: [B, S, H, D]; per-batch positions when position_ids given
        if pos is not None:
            c = cos_t[pos][:, :, None, :]   # [B, S, 1, half]
            s = sin_t[pos][:, :, None, :]
        else:
            c = cos_t[None, :, None, :]
            s = sin_t[None, :, None, :]
        if use_neox_rotary_style:
            half = x.shape[-1] // 2
            x1, x2 = x[..., :half], x[..., half:]
            return jnp.concatenate([x1 * c - x2 * s, x2 * c + x1 * s], axis=-1)
        x1 = x[..., 0::2]
        x2 = x[..., 1::2]
        o1 = x1 * c - x2 * s
        o2 = x2 * c + x1 * s
        return jnp.stack([o1, o2], axis=-1).reshape(x.shape)

    outs = []
    for t in (q, k, v):
        if t is None:
            outs.append(None)
            continue
        seqlen, dim = t.shape[1], t.shape[3]
        table_len = seqlen if pos is None else int(pos.max()) + 1
        if cos is not None and sin is not None:
            ca = cos._data if isinstance(cos, Tensor) else jnp.asarray(cos)
            sa = sin._data if isinstance(sin, Tensor) else jnp.asarray(sin)
            # tables arrive as [*, half] or duplicated to full dim; keep half
            ct = ca.reshape(-1, ca.shape[-1])[:, : dim // 2]
            st = sa.reshape(-1, sa.shape[-1])[:, : dim // 2]
        else:
            ct, st = make_tables(table_len, dim, t._data.dtype)
        outs.append(apply_op("fused_rope", lambda x, c=ct, s=st: rope_one(x, c, s), t))
    return tuple(outs)


def swiglu(x, y=None):
    """silu(x) * y; single-input form splits x in half (parity:
    paddle.incubate.nn.functional.swiglu)."""
    if y is None:
        def fn(x):
            a, b = jnp.split(x, 2, axis=-1)
            return jax.nn.silu(a) * b

        return apply_op("swiglu", fn, x)

    def fn(x, y):
        return jax.nn.silu(x) * y

    return apply_op("swiglu", fn, x, y)


def fused_bias_act(x, bias=None, act_method: str = "gelu", **kwargs):
    act = {"gelu": jax.nn.gelu, "relu": jax.nn.relu, "silu": jax.nn.silu,
           "swiglu": lambda v: jax.nn.silu(v[..., : v.shape[-1] // 2]) * v[..., v.shape[-1] // 2:]}[act_method]
    if bias is None:
        return apply_op("fused_bias_act", lambda x: act(x), x)
    return apply_op("fused_bias_act", lambda x, b: act(x + b), x, bias)


def fused_linear(x, weight, bias=None, transpose_weight: bool = False, **kwargs):
    if bias is None:
        return apply_op("fused_linear",
                        lambda x, w: x @ (w.T if transpose_weight else w), x, weight)
    return apply_op("fused_linear",
                    lambda x, w, b: x @ (w.T if transpose_weight else w) + b, x, weight, bias)


def fused_linear_activation(x, y, bias, trans_x: bool = False, trans_y: bool = False,
                            activation: str = "gelu"):
    act = {"gelu": jax.nn.gelu, "relu": jax.nn.relu, "none": lambda v: v}[activation]

    def fn(x, w, b):
        a = x.T if trans_x else x
        ww = w.T if trans_y else w
        return act(a @ ww + b)

    return apply_op("fused_linear_activation", fn, x, y, bias)


def _inverted_dropout(key, rate, x):
    """Shared inverted-dropout step for the fused blocks."""
    keep = jax.random.bernoulli(key, 1.0 - rate, x.shape)
    return jnp.where(keep, x / (1.0 - rate), 0.0).astype(x.dtype)


def fused_bias_dropout_residual_layer_norm(
        x, residual, bias=None, ln_scale=None, ln_bias=None,
        dropout_rate: float = 0.5, ln_epsilon: float = 1e-5,
        training: bool = True, **kwargs):
    """out = layer_norm(residual + dropout(x + bias)) — the epilogue the
    fused attention/ffn kernels share (reference incubate
    fused_bias_dropout_residual_layer_norm)."""
    drop = training and dropout_rate > 0
    if drop:
        from ...ops.random import split_key

        key = split_key()

    def fn(x, residual, *rest):
        b = rest[0] if bias is not None else None
        h = x if b is None else x + b
        if drop:
            h = _inverted_dropout(key, dropout_rate, h)
        return residual + h

    args = [x, residual] + ([bias] if bias is not None else [])
    out = apply_op("fused_bias_dropout_residual_ln", fn, *args)
    return fused_layer_norm(out, ln_scale, ln_bias, ln_epsilon)


def fused_multi_head_attention(x, qkv_weight, linear_weight, pre_layer_norm: bool = False,
                               pre_ln_scale=None, pre_ln_bias=None, ln_scale=None, ln_bias=None,
                               pre_ln_epsilon: float = 1e-5, qkv_bias=None, linear_bias=None,
                               cache_kv=None, attn_mask=None, dropout_rate: float = 0.0,
                               attn_dropout_rate: float = 0.0, ln_epsilon: float = 1e-5,
                               training: bool = True, num_heads: Optional[int] = None, **kwargs):
    """Fused transformer MHA block (parity: incubate
    fused_multi_head_attention; kernel phi/kernels/fusion/gpu/
    fused_attention_kernel)."""
    if cache_kv is not None:
        raise NotImplementedError(
            "fused_multi_head_attention cache_kv is not implemented; use "
            "the models' kv-cache decode path (models/llama.py)")
    h = x
    if pre_layer_norm:
        h = fused_layer_norm(h, pre_ln_scale, pre_ln_bias, pre_ln_epsilon)
    # qkv_weight: [3, num_heads, head_dim, embed_dim]
    n_heads = int(qkv_weight.shape[1])
    head_dim = int(qkv_weight.shape[2])
    if num_heads is not None and int(num_heads) != n_heads:
        raise ValueError(
            f"num_heads={num_heads} contradicts qkv_weight layout "
            f"({n_heads} heads)")
    drop = training and (dropout_rate > 0 or attn_dropout_rate > 0)
    if drop:
        from ...ops.random import split_key

        dk1, dk2 = jax.random.split(split_key())

    def attn_fn(h, qkvw, *rest):
        i = 0
        qkvb = None
        mask = None
        lw = rest[0]
        rest = rest[1:]
        if qkv_bias is not None:
            qkvb = rest[i]; i += 1
        if attn_mask is not None:
            mask = rest[i]; i += 1
        lb = rest[i] if linear_bias is not None else None
        B, S, E = h.shape
        w = qkvw.reshape(3, n_heads * head_dim, E)
        qkv = jnp.einsum("bse,tde->tbsd", h, w)
        if qkvb is not None:
            qkv = qkv + qkvb.reshape(3, 1, 1, -1)
        q, k, v = (qkv[t].reshape(B, S, n_heads, head_dim) for t in range(3))
        scale = 1.0 / math.sqrt(head_dim)
        logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
        if mask is not None:
            logits = logits + mask
        probs = jax.nn.softmax(logits, axis=-1)
        if drop and attn_dropout_rate > 0:
            probs = _inverted_dropout(dk1, attn_dropout_rate, probs)
        ctx = jnp.einsum("bhqk,bkhd->bqhd", probs, v).reshape(B, S, n_heads * head_dim)
        out = ctx @ lw
        if lb is not None:
            out = out + lb
        if drop and dropout_rate > 0:
            out = _inverted_dropout(dk2, dropout_rate, out)
        return out

    args = [h, qkv_weight, linear_weight]
    if qkv_bias is not None:
        args.append(qkv_bias)
    if attn_mask is not None:
        args.append(attn_mask)
    if linear_bias is not None:
        args.append(linear_bias)
    out = apply_op("fused_multi_head_attention", attn_fn, *args)
    out = out + x  # residual
    if not pre_layer_norm:
        out = fused_layer_norm(out, ln_scale, ln_bias, ln_epsilon)
    return out


def fused_feedforward(x, linear1_weight, linear2_weight, linear1_bias=None, linear2_bias=None,
                      ln1_scale=None, ln1_bias=None, ln2_scale=None, ln2_bias=None,
                      dropout1_rate: float = 0.5, dropout2_rate: float = 0.5,
                      activation: str = "relu", ln1_epsilon: float = 1e-5,
                      ln2_epsilon: float = 1e-5, pre_layer_norm: bool = False,
                      training: bool = True, **kwargs):
    h = x
    if pre_layer_norm:
        h = fused_layer_norm(h, ln1_scale, ln1_bias, ln1_epsilon)
    # paddle's gelu defaults to the exact erf form (reference
    # fused_feedforward passes act_method through to the phi kernel's
    # erf gelu); jax.nn.gelu defaults to tanh-approximate
    act = {"relu": jax.nn.relu,
           "gelu": lambda x: jax.nn.gelu(x, approximate=False),
           "silu": jax.nn.silu}[activation]
    drop = training and (dropout1_rate > 0 or dropout2_rate > 0)
    if drop:
        from ...ops.random import split_key

        k1, k2 = jax.random.split(split_key())

    def fn(h, w1, w2, *bs):
        i = 0
        b1 = bs[i] if linear1_bias is not None else None
        if linear1_bias is not None:
            i += 1
        b2 = bs[i] if linear2_bias is not None else None
        u = h @ w1
        if b1 is not None:
            u = u + b1
        u = act(u)
        if drop and dropout1_rate > 0:
            u = _inverted_dropout(k1, dropout1_rate, u)
        v = u @ w2
        if b2 is not None:
            v = v + b2
        if drop and dropout2_rate > 0:
            v = _inverted_dropout(k2, dropout2_rate, v)
        return v

    args = [h, linear1_weight, linear2_weight]
    if linear1_bias is not None:
        args.append(linear1_bias)
    if linear2_bias is not None:
        args.append(linear2_bias)
    out = apply_op("fused_feedforward", fn, *args)
    out = out + x
    if not pre_layer_norm:
        out = fused_layer_norm(out, ln2_scale, ln2_bias, ln2_epsilon)
    return out
