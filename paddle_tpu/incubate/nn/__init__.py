from . import functional
from .layers import (FusedBiasDropoutResidualLayerNorm, FusedFeedForward,
                     FusedMultiHeadAttention, FusedTransformerEncoderLayer)

__all__ = ["functional", "FusedBiasDropoutResidualLayerNorm",
           "FusedFeedForward", "FusedMultiHeadAttention",
           "FusedTransformerEncoderLayer"]
