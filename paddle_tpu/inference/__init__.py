"""paddle.inference equivalent — load a saved program and serve it.

Parity: paddle/fluid/inference/api/analysis_predictor.h:105
(AnalysisPredictor), python/paddle/inference/. TPU design: the "analysis +
IR passes + engine" pipeline collapses to deserializing the StableHLO
artifact written by ``jit.save``/``save_inference_model`` and jit-compiling
it with XLA on first run (XLA is the optimizing engine; there is no
separate TensorRT-style subgraph path to manage). Zero-copy run maps to
donating/holding device buffers on the PJRT client.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional

import numpy as np

from ..core.tensor import Tensor as _CoreTensor
from ..jit.save_load import TranslatedLayer
from ..jit.save_load import load as _jit_load

__all__ = ["Config", "Predictor", "Tensor", "create_predictor", "PrecisionType", "PlaceType"]


class PrecisionType:
    Float32 = "float32"
    Half = "float16"
    Bfloat16 = "bfloat16"
    Int8 = "int8"


class PlaceType:
    CPU = "cpu"
    GPU = "gpu"
    TPU = "tpu"
    XPU = "xpu"


class Config:
    """Parity: paddle_infer.Config — holds model paths + engine switches.
    Engine switches are accepted for API compatibility; XLA owns the
    optimization pipeline so most are informational."""

    def __init__(self, prog_file: Optional[str] = None, params_file: Optional[str] = None):
        if prog_file is not None and os.path.isdir(prog_file):
            # model-dir form: find the single prefix inside
            cands = [f[:-len(".pdmodel")] for f in os.listdir(prog_file) if f.endswith(".pdmodel")]
            if len(cands) != 1:
                raise ValueError(f"expected exactly one .pdmodel in {prog_file}, found {cands}")
            self._prefix = os.path.join(prog_file, cands[0])
        elif prog_file is not None and prog_file.endswith(".pdmodel"):
            self._prefix = prog_file[:-len(".pdmodel")]
        else:
            self._prefix = prog_file
        self._device = "tpu"
        self._precision = PrecisionType.Float32
        self._switches: Dict[str, object] = {}

    def set_prog_file(self, path: str):
        self._prefix = path[:-len(".pdmodel")] if path.endswith(".pdmodel") else path

    def set_params_file(self, path: str):
        pass  # params live beside the program artifact

    def prog_file(self) -> str:
        return self._prefix + ".pdmodel"

    def enable_use_gpu(self, memory_pool_init_size_mb: int = 100, device_id: int = 0,
                       precision=PrecisionType.Float32):
        self._device, self._precision = "gpu", precision

    def disable_gpu(self):
        self._device = "cpu"

    def enable_xpu(self, *a, **k):
        self._device = "xpu"

    def use_gpu(self) -> bool:
        return self._device == "gpu"

    def switch_ir_optim(self, flag: bool = True):
        self._switches["ir_optim"] = flag

    def enable_memory_optim(self, flag: bool = True):
        self._switches["memory_optim"] = flag

    def set_cpu_math_library_num_threads(self, n: int):
        self._switches["cpu_threads"] = n

    def enable_tensorrt_engine(self, *a, **k):
        self._switches["tensorrt"] = False  # no TRT on TPU; XLA compiles the whole graph

    def summary(self) -> str:
        return f"Config(prefix={self._prefix}, device={self._device}, precision={self._precision})"


class Tensor:
    """Input/output handle (parity: paddle_infer.Tensor zero-copy handles)."""

    def __init__(self, name: str, owner: "Predictor", is_input: bool):
        self.name = name
        self._owner = owner
        self._is_input = is_input

    def copy_from_cpu(self, data: np.ndarray):
        if not self._is_input:
            raise RuntimeError("copy_from_cpu on an output handle")
        self._owner._inputs[self.name] = np.ascontiguousarray(data)

    def copy_to_cpu(self) -> np.ndarray:
        if self._is_input:
            return np.asarray(self._owner._inputs[self.name])
        return np.asarray(self._owner._outputs[self.name])

    def shape(self):
        if self._is_input:
            return list(self._owner._inputs[self.name].shape)
        return list(self._owner._outputs[self.name].shape)

    def reshape(self, shape):
        pass  # shapes are taken from the copied-in array


class Predictor:
    """Parity: paddle_infer.Predictor over AnalysisPredictor."""

    def __init__(self, config: Config):
        self._config = config
        self._layer: TranslatedLayer = _jit_load(config._prefix)
        self._input_names = [s.name or f"x{i}" for i, s in enumerate(self._layer.input_specs)]
        fetch = self._layer._meta.get("fetch_names") or []
        self._output_names = list(fetch) if fetch else None  # filled after first run
        self._inputs: Dict[str, np.ndarray] = {}
        self._outputs: Dict[str, np.ndarray] = {}

    def get_input_names(self) -> List[str]:
        return list(self._input_names)

    def get_input_handle(self, name: str) -> Tensor:
        return Tensor(name, self, is_input=True)

    def get_output_names(self) -> List[str]:
        if self._output_names is None:
            return [f"fetch_{i}" for i in range(len(self._outputs) or 1)]
        return list(self._output_names)

    def get_output_handle(self, name: str) -> Tensor:
        return Tensor(name, self, is_input=False)

    def run(self, inputs: Optional[List[np.ndarray]] = None):
        if inputs is not None:
            for n, a in zip(self._input_names, inputs):
                self._inputs[n] = np.ascontiguousarray(a)
        args = [self._inputs[n] for n in self._input_names]
        out = self._layer(*args)
        outs = list(out) if isinstance(out, (tuple, list)) else [out]
        names = self._output_names or [f"fetch_{i}" for i in range(len(outs))]
        if self._output_names is None:
            self._output_names = names
        self._outputs = {n: np.asarray(o._data if isinstance(o, _CoreTensor) else o)
                         for n, o in zip(names, outs)}
        if inputs is not None:
            return [self._outputs[n] for n in names]
        return True

    def clear_intermediate_tensor(self):
        pass

    def try_shrink_memory(self):
        pass


def create_predictor(config: Config) -> Predictor:
    return Predictor(config)
