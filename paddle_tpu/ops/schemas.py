"""Op schema registry: the single source of truth tying op -> argument
sample spec -> supported dtypes -> differentiability -> numpy oracle.

Parity: the reference's YAML op registry (paddle/phi/ops/yaml/ops.yaml —
467 forward schemas; backward.yaml — 337 grad schemas) whose entries
drive test/legacy_test/op_test.py's per-op dtype and gradient checks.
Here the schema IS executable test metadata: tests/test_op_schema_sweep.py
enumerates SCHEMAS and runs every op through the dtype sweep
(fp32 oracle + bf16/fp16 tolerances) and finite-difference grad checks.

Each schema registers into ops.dispatch.OP_REGISTRY at import with the
light metadata (args/dtypes/has_grad); the heavyweight pieces (samplers,
numpy references) stay here.
"""

from __future__ import annotations

from typing import Callable, Dict, Optional, Sequence, Tuple

import numpy as np

from .dispatch import register_op

FLOAT_SWEEP = ("float32", "bfloat16", "float16")
INT_SWEEP = ("int32", "int64")

# ---------------------------------------------------------------------------
# input domains: FD grad checks perturb by ±1e-3, so every domain keeps the
# op smooth in that neighbourhood
# ---------------------------------------------------------------------------
_DOMAINS: Dict[str, Callable] = {
    "any":    lambda rng, sh: rng.uniform(-2.0, 2.0, sh).astype(np.float32),
    # fractional part pinned to [0.2, 0.8]: FD-safe for integer-step ops
    "offint": lambda rng, sh: (rng.randint(-2, 3, sh)
                               + rng.uniform(0.2, 0.8, sh)).astype(np.float32),
    # fractional part in [0.2, 0.4]: also away from round()'s .5 steps
    "offhalf": lambda rng, sh: (rng.randint(-2, 3, sh)
                                + rng.uniform(0.2, 0.4, sh)).astype(np.float32),
    "idx3":   lambda rng, sh: rng.randint(0, 3, sh).astype(np.int32),
    "pos":    lambda rng, sh: rng.uniform(0.5, 2.5, sh).astype(np.float32),
    "nonzero": lambda rng, sh: rng.uniform(0.5, 2.0, sh).astype(np.float32)
               * np.where(rng.rand(*sh) > 0.5, 1.0, -1.0).astype(np.float32),
    "unit":   lambda rng, sh: rng.uniform(-0.9, 0.9, sh).astype(np.float32),
    "gt1":    lambda rng, sh: rng.uniform(1.1, 3.0, sh).astype(np.float32),
    "prob":   lambda rng, sh: rng.uniform(0.05, 0.95, sh).astype(np.float32),
    "small":  lambda rng, sh: rng.uniform(-0.5, 0.5, sh).astype(np.float32),
    "int":    lambda rng, sh: rng.randint(0, 5, sh).astype(np.int32),
    "posint": lambda rng, sh: rng.randint(1, 9, sh).astype(np.int32),
    "bool":   lambda rng, sh: rng.rand(*sh) > 0.5,
}


class OpSchema:
    """One op's schema. ``inputs`` is a sequence of (shape, domain) pairs;
    ``api`` is a dotted path under the package root (resolved lazily)."""

    def __init__(self, name: str, api: str, np_ref: Callable,
                 inputs: Sequence[Tuple[tuple, str]], *,
                 kwargs: Optional[dict] = None,
                 dtypes: Tuple[str, ...] = FLOAT_SWEEP,
                 grad: bool = True,
                 grad_inputs: Optional[Sequence[int]] = None,
                 tol: Optional[dict] = None,
                 grad_tol: Optional[Tuple[float, float]] = None,
                 wrap: Optional[Callable] = None):
        self.name = name
        self.api = api
        self.np_ref = np_ref
        self.inputs = list(inputs)
        self.kwargs = kwargs or {}
        self.dtypes = dtypes
        self.grad = grad
        self.grad_inputs = grad_inputs
        self.tol = tol
        # (atol, rtol) override for the FD grad check: ops whose forward
        # accumulates many fp32 terms (convs, norms, attention) carry
        # honest FD noise ~1e-3 that the default tolerance rejects
        self.grad_tol = grad_tol
        # call adapter: wrap(api_fn) -> fn(*tensors, **kwargs); for ops
        # whose python signature isn't tensors-first (list inputs, einsum
        # equations, tuple-returning selections)
        self.wrap = wrap

    def sample(self, rng) -> list:
        return [_DOMAINS[dom](rng, sh) for sh, dom in self.inputs]

    def resolve(self):
        import importlib

        import paddle_tpu as root

        obj = root
        for part in self.api.split("."):
            try:
                obj = getattr(obj, part)
            except AttributeError:
                # lazily-loaded submodule (e.g. paddle_tpu.models)
                obj = importlib.import_module(f"{obj.__name__}.{part}")
        return obj


SCHEMAS: Dict[str, OpSchema] = {}


def _S(name, np_ref, inputs, api=None, **kw):
    s = OpSchema(name, api or name, np_ref, inputs, **kw)
    assert name not in SCHEMAS, f"duplicate schema {name}"
    SCHEMAS[name] = s
    register_op(name, args=[d for _, d in s.inputs], dtypes=list(s.dtypes),
                has_grad=s.grad, kwargs=sorted(s.kwargs))
    return s


# ---------------------------------------------------------------------------
# unary float ops (reference ops.yaml unary family)
# ---------------------------------------------------------------------------
# scipy is a TEST-oracle dependency only: resolve it lazily so importing
# the package (ops/__init__ imports this module for OP_REGISTRY metadata)
# never requires scipy — references below call _sp() at test time.


class _LazyScipySpecial:
    def __getattr__(self, item):
        from scipy import special

        return getattr(special, item)


sp = _LazyScipySpecial()

_SH = (3, 4)
_U = [(_SH, "any")]


def _unary(table, domain="any", **kw):
    for name, ref in table.items():
        _S(name, ref, [(_SH, domain)], **kw)


_unary({"tanh": np.tanh, "sin": np.sin, "cos": np.cos, "atan": np.arctan,
        "asinh": np.arcsinh, "sinh": np.sinh, "erf": lambda x: sp.erf(x),
        "neg": np.negative, "square": np.square, "sign": np.sign,
        "deg2rad": np.deg2rad, "rad2deg": np.rad2deg,
        "expm1": np.expm1, "sinc": np.sinc,
        "sigmoid": lambda x: 1 / (1 + np.exp(-x)),
        "abs": np.abs})
_unary({"exp": np.exp, "exp2": np.exp2}, domain="small")
_unary({"cosh": np.cosh}, domain="small")
_unary({"tan": np.tan}, domain="unit")
_unary({"asin": np.arcsin, "acos": np.arccos, "atanh": np.arctanh,
        "erfinv": lambda x: sp.erfinv(x)}, domain="unit",
       tol={"float16": (3e-2, 3e-2), "bfloat16": (8e-2, 8e-2)})
_unary({"acosh": np.arccosh}, domain="gt1")
_unary({"sqrt": np.sqrt, "rsqrt": lambda x: 1 / np.sqrt(x),
        "log": np.log, "log2": np.log2, "log10": np.log10,
        "log1p": np.log1p, "reciprocal": lambda x: 1 / x,
        "lgamma": lambda x: sp.gammaln(x), "digamma": lambda x: sp.digamma(x),
        "i0": lambda x: sp.i0(x), "i0e": lambda x: sp.i0e(x),
        "i1": lambda x: sp.i1(x), "i1e": lambda x: sp.i1e(x)},
       domain="pos")
_unary({"gammaln": lambda x: sp.gammaln(x)}, domain="pos")
_unary({"logit": lambda x: sp.logit(x)}, domain="prob",
       tol={"float16": (3e-2, 3e-2), "bfloat16": (8e-2, 8e-2)})
# integer-step functions: zero analytic grad == zero FD grad off the steps
_unary({"ceil": np.ceil, "floor": np.floor,
        "trunc": np.trunc, "frac": lambda x: x - np.trunc(x)},
       domain="offint")
_unary({"round": np.round}, domain="offhalf")  # steps at half-integers
_S("stanh", lambda x: 0.7159 * np.tanh(0.66667 * x), _U,
   kwargs={"scale_a": 0.66667, "scale_b": 0.7159})
_S("polygamma", lambda x: sp.polygamma(1, x), [(_SH, "pos")], kwargs={"n": 1})
_S("multigammaln", lambda x: sp.multigammaln(x, 2) if np.isscalar(x)
   else np.vectorize(lambda v: sp.multigammaln(v, 2))(x),
   [(_SH, "gt1")], kwargs={"p": 2})
_S("nan_to_num", np.nan_to_num, _U)
_S("scale", lambda x: 2.0 * x + 1.0, _U, kwargs={"scale": 2.0, "bias": 1.0})
_S("clip", lambda x: np.clip(x, -0.5, 0.5), _U, kwargs={"min": -0.5, "max": 0.5})

# ---------------------------------------------------------------------------
# binary float ops
# ---------------------------------------------------------------------------
_B = [(_SH, "any"), (_SH, "any")]
for name, ref in {"add": np.add, "subtract": np.subtract,
                  "multiply": np.multiply, "maximum": np.maximum,
                  "fmax": np.fmax, "fmin": np.fmin, "minimum": np.minimum,
                  "atan2": np.arctan2, "hypot": np.hypot,
                  "logaddexp": np.logaddexp,
                  "copysign": np.copysign}.items():
    _S(name, ref, _B)
_S("divide", np.divide, [(_SH, "any"), (_SH, "nonzero")])
_S("pow", np.power, [(_SH, "pos"), (_SH, "small")],
   tol={"float16": (3e-2, 3e-2), "bfloat16": (8e-2, 8e-2)})
_S("heaviside", np.heaviside, [(_SH, "nonzero"), (_SH, "any")])
_S("mod", np.mod, [(_SH, "any"), (_SH, "nonzero")], grad_inputs=[0])
_S("remainder", np.mod, [(_SH, "any"), (_SH, "nonzero")], grad_inputs=[0],
   api="remainder")
_S("floor_mod", np.mod, [(_SH, "any"), (_SH, "nonzero")], grad_inputs=[0])
_S("nextafter", np.nextafter, _B, grad=False,
   dtypes=("float32",))  # ULP-level op: low precision sweep meaningless
_S("ldexp", np.ldexp, [(_SH, "any"), (_SH, "int")], grad=False)
_S("lerp", lambda x, y: x + 0.3 * (y - x), _B, kwargs={"weight": 0.3})
_S("dist", lambda x, y: np.linalg.norm((x - y).ravel()), _B)
_S("cross", lambda a, b: np.cross(a, b, axis=-1),
   [((4, 3), "any"), ((4, 3), "any")], kwargs={"axis": -1})
_S("kron", np.kron, [((2, 3), "any"), ((3, 2), "any")])

# ---------------------------------------------------------------------------
# integer / bitwise / logical / comparison (no grad)
# ---------------------------------------------------------------------------
_I = [(_SH, "int"), (_SH, "int")]
for name, ref in {"bitwise_and": np.bitwise_and, "bitwise_or": np.bitwise_or,
                  "bitwise_xor": np.bitwise_xor}.items():
    _S(name, ref, _I, dtypes=INT_SWEEP, grad=False)
_S("bitwise_not", np.bitwise_not, [(_SH, "int")], dtypes=INT_SWEEP, grad=False)
_S("bitwise_left_shift", np.left_shift, [(_SH, "int"), (_SH, "int")],
   dtypes=INT_SWEEP, grad=False)
_S("bitwise_right_shift", np.right_shift, [(_SH, "int"), (_SH, "int")],
   dtypes=INT_SWEEP, grad=False)
_S("gcd", np.gcd, _I, dtypes=INT_SWEEP, grad=False)
_S("lcm", np.lcm, _I, dtypes=INT_SWEEP, grad=False)
for name, ref in {"logical_and": np.logical_and,
                  "logical_or": np.logical_or,
                  "logical_xor": np.logical_xor}.items():
    _S(name, ref, [(_SH, "bool"), (_SH, "bool")], dtypes=("bool",), grad=False)
_S("logical_not", np.logical_not, [(_SH, "bool")], dtypes=("bool",), grad=False)
for name, ref in {"equal": np.equal, "not_equal": np.not_equal,
                  "greater_than": np.greater, "greater_equal": np.greater_equal,
                  "less_than": np.less, "less_equal": np.less_equal}.items():
    # fp32 only: low-precision rounding can collide two distinct values,
    # flipping the comparison vs the fp32 oracle
    _S(name, ref, _B, grad=False, dtypes=("float32",))
for name, ref in {"isfinite": np.isfinite, "isinf": np.isinf,
                  "isnan": np.isnan, "signbit": np.signbit}.items():
    _S(name, ref, _U, grad=False)
_S("isclose", np.isclose, _B, grad=False, dtypes=("float32",))

# ---------------------------------------------------------------------------
# reductions
# ---------------------------------------------------------------------------
_S("sum", np.sum, _U)
_S("mean", np.mean, _U)
_S("prod", np.prod, [(_SH, "pos")],
   tol={"float16": (3e-2, 3e-2), "bfloat16": (2e-1, 2e-1)})
_S("max", np.max, _U)
_S("min", np.min, _U)
_S("amax", np.amax, _U)
_S("amin", np.amin, _U)
_S("std", lambda x: np.std(x, ddof=1), _U)
_S("var", lambda x: np.var(x, ddof=1), _U)
_S("logsumexp", lambda x: sp.logsumexp(x), _U)
_S("nansum", np.nansum, _U)
_S("nanmean", np.nanmean, _U)
_S("count_nonzero", np.count_nonzero, [(_SH, "int")], dtypes=INT_SWEEP,
   grad=False)
_S("all", np.all, [(_SH, "bool")], dtypes=("bool",), grad=False)
_S("any", np.any, [(_SH, "bool")], dtypes=("bool",), grad=False)
_S("trace", np.trace, [((4, 4), "any")])
_S("l1_norm", lambda x: np.abs(x).sum(), _U)
_S("squared_l2_norm", lambda x: (x ** 2).sum(), _U)
_S("p_norm", lambda x: np.linalg.norm(x.ravel(), 2), _U, kwargs={"p": 2})
_S("median", np.median, [((3, 5), "any")])  # subgradient at the pick
_S("nanmedian", np.nanmedian, [((3, 5), "any")], grad=False)
_S("cumsum", lambda x: np.cumsum(x, axis=0), _U, kwargs={"axis": 0})
_S("cumprod", lambda x: np.cumprod(x, axis=0), [(_SH, "pos")],
   kwargs={"dim": 0},
   tol={"float16": (3e-2, 3e-2), "bfloat16": (2e-1, 2e-1)})
_S("logcumsumexp", lambda x: np.log(np.cumsum(np.exp(x), axis=0)), _U,
   kwargs={"axis": 0})
_S("diff", lambda x: np.diff(x, axis=-1), _U)
_S("trapezoid", lambda x: np.trapezoid(x, axis=-1), _U)
_S("cumulative_trapezoid", lambda x: np.array(
    [np.cumsum((x[..., 1:] + x[..., :-1]) / 2, axis=-1)])[0], _U)

# ---------------------------------------------------------------------------
# manipulation (linear ops; grads exact)
# ---------------------------------------------------------------------------
_S("reshape", lambda x: x.reshape(4, 3), _U, kwargs={"shape": [4, 3]})
_S("transpose", lambda x: x.transpose(1, 0), _U, kwargs={"perm": [1, 0]})
_S("t", lambda x: x.T, _U)
_S("flatten", lambda x: x.reshape(-1), _U)
_S("squeeze", lambda x: np.squeeze(x, 0), [((1, 3, 4), "any")],
   kwargs={"axis": 0})
_S("unsqueeze", lambda x: x[:, None], _U, kwargs={"axis": 1})
_S("flip", lambda x: np.flip(x, 0), _U, kwargs={"axis": 0})
_S("roll", lambda x: np.roll(x, 1, 0), _U, kwargs={"shifts": 1, "axis": 0})
_S("tile", lambda x: np.tile(x, (2, 1)), _U, kwargs={"repeat_times": [2, 1]})
_S("broadcast_to", lambda x: np.broadcast_to(x, (2, 3, 4)), _U,
   kwargs={"shape": [2, 3, 4]})
_S("expand", lambda x: np.broadcast_to(x, (2, 3, 4)), _U,
   kwargs={"shape": [2, 3, 4]})
_S("tril", np.tril, [((4, 4), "any")])
_S("triu", np.triu, [((4, 4), "any")])
_S("diag", np.diag, [((4,), "any")])
_S("diagonal", lambda x: np.diagonal(x, 0, 0, 1), [((4, 4), "any")])
_S("rot90", lambda x: np.rot90(x, 1, (0, 1)), _U)
_S("moveaxis", lambda x: np.moveaxis(x, 0, 1), _U,
   kwargs={"source": 0, "destination": 1})
_S("swapaxes", lambda x: np.swapaxes(x, 0, 1), _U,
   kwargs={"axis0": 0, "axis1": 1})
_S("repeat_interleave", lambda x: np.repeat(x, 2, 0), _U,
   kwargs={"repeats": 2, "axis": 0})
_S("pad", lambda x: np.pad(x, ((1, 1), (2, 2))), _U,
   kwargs={"pad": [1, 1, 2, 2]})

# ---------------------------------------------------------------------------
# matmul family (MXU ops; the bf16 tolerance IS the TPU numerics contract)
# ---------------------------------------------------------------------------
_MM_TOL = {"float16": (2e-2, 2e-2), "bfloat16": (1e-1, 1e-1)}
_S("matmul", np.matmul, [((3, 4), "any"), ((4, 5), "any")], tol=_MM_TOL)
_S("mm", np.matmul, [((3, 4), "any"), ((4, 5), "any")], tol=_MM_TOL)
_S("bmm", np.matmul, [((2, 3, 4), "any"), ((2, 4, 5), "any")], tol=_MM_TOL)
_S("mv", np.matmul, [((3, 4), "any"), ((4,), "any")], tol=_MM_TOL)
_S("dot", lambda x, y: np.array((x * y).sum()), [((6,), "any"), ((6,), "any")],
   tol=_MM_TOL)
_S("inner", np.inner, [((3, 4), "any"), ((5, 4), "any")], tol=_MM_TOL)
_S("outer", np.outer, [((3,), "any"), ((4,), "any")], tol=_MM_TOL)
_S("addmm", lambda c, a, b: c + a @ b,
   [((3, 5), "any"), ((3, 4), "any"), ((4, 5), "any")], tol=_MM_TOL)
_S("cdist", lambda a, b: np.linalg.norm(a[:, None] - b[None], axis=-1),
   [((3, 4), "any"), ((5, 4), "any")], tol=_MM_TOL)
_S("tensordot", lambda a, b: np.tensordot(a, b, 1),
   [((3, 4), "any"), ((4, 5), "any")], kwargs={"axes": 1}, tol=_MM_TOL)

# ---------------------------------------------------------------------------
# indexed ops
# ---------------------------------------------------------------------------
_S("gather", lambda x, i: x[i], [(_SH, "any"), ((2,), "idx3")],
   grad_inputs=[0])
_S("index_select", lambda x, i: x[i], [(_SH, "any"), ((2,), "idx3")],
   kwargs={"axis": 0}, grad_inputs=[0])
_S("take_along_axis", lambda x, i: np.take_along_axis(x, i, 0),
   [(_SH, "any"), ((2, 4), "idx3")], kwargs={"axis": 0}, grad_inputs=[0])
_S("index_sample", lambda x, i: np.take_along_axis(x, i, 1),
   [(_SH, "any"), ((3, 2), "idx3")], grad_inputs=[0])

# activations under nn.functional (the hot fused-elementwise family)
_S("relu", lambda x: np.maximum(x, 0), _U, api="nn.functional.relu")
_S("gelu", lambda x: x * 0.5 * (1 + sp.erf(x / np.sqrt(2))), _U,
   api="nn.functional.gelu",
   tol={"float16": (3e-2, 3e-2), "bfloat16": (8e-2, 8e-2)})
_S("silu", lambda x: x / (1 + np.exp(-x)), _U, api="nn.functional.silu")
_S("softplus", lambda x: np.log1p(np.exp(x)), _U, api="nn.functional.softplus")
_S("softsign", lambda x: x / (1 + np.abs(x)), _U, api="nn.functional.softsign")
_S("elu", lambda x: np.where(x > 0, x, np.exp(x) - 1), _U,
   api="nn.functional.elu")
_S("selu", lambda x: 1.0507009873554805 * np.where(
    x > 0, x, 1.6732632423543772 * (np.exp(x) - 1)), _U,
   api="nn.functional.selu")
_S("leaky_relu", lambda x: np.where(x > 0, x, 0.01 * x), _U,
   api="nn.functional.leaky_relu")
_S("hardtanh", lambda x: np.clip(x, -1, 1), [(_SH, "offint")],
   api="nn.functional.hardtanh")
_S("hardsigmoid", lambda x: np.clip(x / 6 + 0.5, 0, 1), [(_SH, "small")],
   api="nn.functional.hardsigmoid")
_S("hardswish", lambda x: x * np.clip(x + 3, 0, 6) / 6, [(_SH, "small")],
   api="nn.functional.hardswish")
_S("mish", lambda x: x * np.tanh(np.log1p(np.exp(x))), _U,
   api="nn.functional.mish")
_S("tanhshrink", lambda x: x - np.tanh(x), _U, api="nn.functional.tanhshrink")
_S("softmax", lambda x: sp.softmax(x, axis=-1), _U,
   api="nn.functional.softmax")
_S("log_softmax", lambda x: sp.log_softmax(x, axis=-1), _U,
   api="nn.functional.log_softmax")

# ---------------------------------------------------------------------------
# axis-variant reductions (the reference sweeps axis/keepdim per op)
# ---------------------------------------------------------------------------
_AX = (2, 3, 4)
for base, npf in {"sum": np.sum, "mean": np.mean, "max": np.max,
                  "min": np.min}.items():
    _S(f"{base}_axis", lambda x, _f=npf: _f(x, axis=1), [(_AX, "any")],
       api=base, kwargs={"axis": 1})
    _S(f"{base}_keepdim", lambda x, _f=npf: _f(x, axis=-1, keepdims=True),
       [(_AX, "any")], api=base, kwargs={"axis": -1, "keepdim": True})
_S("logsumexp_axis", lambda x: sp.logsumexp(x, axis=0), [(_AX, "any")],
   api="logsumexp", kwargs={"axis": 0})
_S("std_axis", lambda x: np.std(x, axis=1, ddof=1), [(_AX, "any")],
   api="std", kwargs={"axis": 1})
_S("var_axis", lambda x: np.var(x, axis=1, ddof=1), [(_AX, "any")],
   api="var", kwargs={"axis": 1})
_S("prod_axis", lambda x: np.prod(x, axis=2), [(_AX, "pos")],
   api="prod", kwargs={"axis": 2},
   tol={"float16": (3e-2, 3e-2), "bfloat16": (2e-1, 2e-1)})

# ---------------------------------------------------------------------------
# linalg
# ---------------------------------------------------------------------------


def _posdef(rng, n=4):
    a = rng.randn(n, n).astype(np.float32)
    return a @ a.T + n * np.eye(n, dtype=np.float32)


_DOMAINS["posdef4"] = lambda rng, sh: _posdef(rng, sh[0])
_DOMAINS["wellcond4"] = lambda rng, sh: (
    rng.randn(*sh).astype(np.float32) + 3.0 * np.eye(sh[0], dtype=np.float32))

_LTOL = {"float16": (3e-2, 3e-2), "bfloat16": (1.5e-1, 1.5e-1)}
_S("cholesky", np.linalg.cholesky, [((4, 4), "posdef4")],
   api="linalg.cholesky", dtypes=("float32",))
_S("det", np.linalg.det, [((4, 4), "wellcond4")], api="linalg.det",
   dtypes=("float32",))
_S("slogdet", lambda a: np.stack(np.linalg.slogdet(a)),
   [((4, 4), "wellcond4")], api="linalg.slogdet", grad=False,
   wrap=lambda f: (lambda x, **k: _stack_pair(f(x))), dtypes=("float32",))
_S("inverse", np.linalg.inv, [((4, 4), "wellcond4")], dtypes=("float32",))
_S("matrix_power", lambda a: np.linalg.matrix_power(a, 3),
   [((4, 4), "small")], api="linalg.matrix_power", kwargs={"n": 3},
   tol=_LTOL)
_S("solve", lambda a, b: np.linalg.solve(a, b),
   [((4, 4), "wellcond4"), ((4, 2), "any")], api="linalg.solve",
   dtypes=("float32",))
_S("triangular_solve", lambda a, b: np.linalg.solve(np.tril(a) + 2 * np.eye(4,
   dtype=a.dtype), b),
   [((4, 4), "any"), ((4, 2), "any")], api="linalg.triangular_solve",
   kwargs={"upper": False},
   wrap=lambda f: (lambda a, b, **k: f(
       a.tril() + 2.0 * _eye_like(a), b, **k)), dtypes=("float32",))
_S("matrix_norm_fro", lambda a: np.linalg.norm(a),
   [((3, 4), "any")], api="linalg.norm", tol=_LTOL)
_S("vector_norm_1", lambda a: np.abs(a).sum(), [((6,), "any")],
   api="linalg.norm", kwargs={"p": 1})
_S("eigvalsh", lambda a: np.linalg.eigvalsh(a), [((4, 4), "posdef4")],
   api="linalg.eigvalsh", grad=False, dtypes=("float32",))
_S("matrix_rank", lambda a: np.array(np.linalg.matrix_rank(a)),
   [((4, 4), "wellcond4")], api="linalg.matrix_rank", grad=False,
   dtypes=("float32",))
_S("pinv", np.linalg.pinv, [((4, 3), "any")], api="linalg.pinv", grad=False,
   dtypes=("float32",))


def _stack_pair(out):
    import paddle_tpu as paddle

    return paddle.stack(list(out))


def _eye_like(a):
    import paddle_tpu as paddle

    return paddle.to_tensor(np.eye(a.shape[-1], dtype=np.float32))


# ---------------------------------------------------------------------------
# nn losses / similarity
# ---------------------------------------------------------------------------
_S("mse_loss", lambda x, y: ((x - y) ** 2).mean(), _B,
   api="nn.functional.mse_loss")
_S("l1_loss", lambda x, y: np.abs(x - y).mean(), _B,
   api="nn.functional.l1_loss")
_S("smooth_l1_loss", lambda x, y: np.where(
    np.abs(x - y) < 1.0, 0.5 * (x - y) ** 2, np.abs(x - y) - 0.5).mean(),
   _B, api="nn.functional.smooth_l1_loss")
_S("binary_cross_entropy", lambda p, t: -(t * np.log(p)
                                          + (1 - t) * np.log1p(-p)).mean(),
   [(_SH, "prob"), (_SH, "prob")], api="nn.functional.binary_cross_entropy",
   tol={"float16": (3e-2, 3e-2), "bfloat16": (8e-2, 8e-2)})
_S("kl_div", lambda lp, t: (t * (np.log(t) - lp)).mean(),
   [(_SH, "small"), (_SH, "prob")], api="nn.functional.kl_div",
   kwargs={"reduction": "mean"}, grad_inputs=[0])
_S("cosine_similarity", lambda a, b: (a * b).sum(-1)
   / (np.linalg.norm(a, axis=-1) * np.linalg.norm(b, axis=-1)),
   _B, api="nn.functional.cosine_similarity")
_S("log_sigmoid", lambda x: -np.log1p(np.exp(-x)), _U,
   api="nn.functional.log_sigmoid")
_S("softshrink", lambda x: np.where(x > 0.5, x - 0.5,
                                    np.where(x < -0.5, x + 0.5, 0.0)),
   _U, api="nn.functional.softshrink")
_S("hardshrink", lambda x: np.where(np.abs(x) > 0.5, x, 0.0), _U,
   api="nn.functional.hardshrink")
_S("celu", lambda x: np.where(x > 0, x, np.expm1(x)), _U,
   api="nn.functional.celu")
_S("thresholded_relu", lambda x: np.where(x > 1.0, x, 0.0),
   [(_SH, "offint")], api="nn.functional.thresholded_relu")
_S("relu6", lambda x: np.clip(x, 0, 6), _U, api="nn.functional.relu6")
_S("normalize", lambda x: x / np.linalg.norm(x, axis=-1, keepdims=True),
   [(_SH, "nonzero")], api="nn.functional.normalize")

# ---------------------------------------------------------------------------
# multi-input / tuple-output manipulation
# ---------------------------------------------------------------------------
_S("concat", lambda a, b: np.concatenate([a, b], axis=0), _B,
   wrap=lambda f: (lambda a, b, **k: f([a, b], **k)), kwargs={"axis": 0})
_S("stack", lambda a, b: np.stack([a, b], axis=0), _B,
   wrap=lambda f: (lambda a, b, **k: f([a, b], **k)), kwargs={"axis": 0})
_S("hstack", lambda a, b: np.hstack([a, b]), _B,
   wrap=lambda f: (lambda a, b, **k: f([a, b])))
_S("vstack", lambda a, b: np.vstack([a, b]), _B,
   wrap=lambda f: (lambda a, b, **k: f([a, b])))
_S("dstack", lambda a, b: np.dstack([a, b]), _B,
   wrap=lambda f: (lambda a, b, **k: f([a, b])))
_S("column_stack", lambda a, b: np.column_stack([a, b]), _B,
   wrap=lambda f: (lambda a, b, **k: f([a, b])))
_S("row_stack", lambda a, b: np.vstack([a, b]), _B,
   wrap=lambda f: (lambda a, b, **k: f([a, b])))
_S("block_diag", lambda a, b: np.block(
    [[a, np.zeros((a.shape[0], b.shape[1]), a.dtype)],
     [np.zeros((b.shape[0], a.shape[1]), a.dtype), b]]),
   [((2, 3), "any"), ((3, 2), "any")],
   wrap=lambda f: (lambda a, b, **k: f([a, b])))
_S("split", lambda x: tuple(np.split(x, 2, axis=1)), [((3, 4), "any")],
   kwargs={"num_or_sections": 2, "axis": 1})
_S("chunk", lambda x: tuple(np.split(x, 2, axis=0)), [((4, 3), "any")],
   kwargs={"chunks": 2, "axis": 0})
_S("unbind", lambda x: tuple(x), [((2, 4), "any")],
   wrap=lambda f: (lambda x, **k: tuple(f(x, **k))),
   kwargs={"axis": 0})
_S("unstack", lambda x: tuple(x), [((2, 4), "any")],
   wrap=lambda f: (lambda x, **k: tuple(f(x, **k))), kwargs={"axis": 0})
_S("where", np.where, [(_SH, "bool"), (_SH, "any"), (_SH, "any")],
   grad_inputs=[1, 2])
_S("einsum_matmul", lambda a, b: np.einsum("ij,jk->ik", a, b),
   [((3, 4), "any"), ((4, 5), "any")], api="einsum",
   wrap=lambda f: (lambda a, b, **k: f("ij,jk->ik", a, b)), tol=_MM_TOL)
_S("einsum_trace", lambda a: np.einsum("ii->", a), [((4, 4), "any")],
   api="einsum", wrap=lambda f: (lambda a, **k: f("ii->", a)))
_S("masked_fill", lambda x, m: np.where(m, 0.5, x),
   [(_SH, "any"), (_SH, "bool")], kwargs={"value": 0.5}, grad_inputs=[0])
_S("diagflat", np.diagflat, [((4,), "any")])
_S("diag_embed", lambda x: np.stack([np.diag(r) for r in x]),
   [((3, 4), "any")])
_S("flip_multi", lambda x: np.flip(x, (0, 1)), [(_SH, "any")], api="flip",
   kwargs={"axis": [0, 1]})
_DOMAINS["sorted"] = lambda rng, sh: np.sort(
    rng.uniform(-2, 2, sh).astype(np.float32))
_S("bucketize", lambda x, e: np.searchsorted(e, x, side="left")
   .astype(np.int64),
   [(_SH, "any"), ((5,), "sorted")], grad=False, dtypes=("float32",),
   wrap=lambda f: (lambda x, e, **k: f(x, e, right=False)))


# ---------------------------------------------------------------------------
# ordering / selection (tuple outputs exercise the harness's multi-out path)
# ---------------------------------------------------------------------------
_DOMAINS["distinct"] = lambda rng, sh: rng.permutation(
    np.linspace(-2, 2, int(np.prod(sh)))).astype(np.float32).reshape(sh)


def _modal(rng, sh):
    """Rows with one value repeated 3x (unambiguous mode), rest distinct."""
    rows = []
    n = sh[-1]
    for _ in range(int(np.prod(sh[:-1]))):
        row = rng.permutation(np.linspace(-2, 2, n)).astype(np.float32)
        rep = row[0]
        pos = rng.choice(np.arange(1, n), size=2, replace=False)
        row[pos] = rep
        rows.append(row)
    return np.stack(rows).reshape(sh)


_DOMAINS["modal"] = _modal


def _mode_ref(x):
    """Reference/torch semantics: modal value, LAST occurrence index."""
    flat = x.reshape(-1, x.shape[-1])
    vals, idxs = [], []
    for row in flat:
        uv, counts = np.unique(row, return_counts=True)
        m = uv[np.argmax(counts)]
        vals.append(m)
        idxs.append(np.where(row == m)[0][-1])
    return (np.array(vals, x.dtype).reshape(x.shape[:-1]),
            np.array(idxs, np.int64).reshape(x.shape[:-1]))


def _cum_argext(x, op):
    """Running arg-extreme with LAST-occurrence tie-break (impl + torch)."""
    ext = (np.maximum if op == "max" else np.minimum).accumulate(x, axis=-1)
    idx = np.zeros(x.shape, np.int64)
    flat_x = x.reshape(-1, x.shape[-1])
    flat_e = ext.reshape(-1, x.shape[-1])
    flat_i = idx.reshape(-1, x.shape[-1])
    for r in range(flat_x.shape[0]):
        for i in range(flat_x.shape[1]):
            pre = flat_x[r, :i + 1]
            flat_i[r, i] = i - np.argmax((pre == flat_e[r, i])[::-1])
    return ext, idx

_S("sort", lambda x: np.sort(x, axis=-1), [(_SH, "distinct")])
_S("argsort", lambda x: np.argsort(x, axis=-1, kind="stable"),
   [(_SH, "distinct")], grad=False, dtypes=("float32",))
_S("topk", lambda x: (np.sort(x, axis=-1)[..., ::-1][..., :3].copy(),
                      np.argsort(-x, axis=-1, kind="stable")[..., :3].copy()),
   [(_SH, "distinct")], kwargs={"k": 3}, dtypes=("float32",))
_S("kthvalue", lambda x: (np.sort(x, axis=-1)[..., 1],
                          np.argsort(x, axis=-1, kind="stable")[..., 1]),
   [(_SH, "distinct")], kwargs={"k": 2}, dtypes=("float32",))
_S("mode", _mode_ref, [((3, 6), "modal")], grad=False, dtypes=("float32",))
_S("cummax", lambda x: _cum_argext(x, "max"), [((3, 6), "modal")],
   kwargs={"axis": -1}, dtypes=("float32",))  # modal domain exercises ties
_S("cummin", lambda x: _cum_argext(x, "min"), [((3, 6), "modal")],
   kwargs={"axis": -1}, dtypes=("float32",))
_S("searchsorted", lambda seq, x: np.searchsorted(seq, x, side="left")
   .astype(np.int64),
   [((6,), "sorted"), ((3, 4), "any")], grad=False, dtypes=("float32",))
# ---------------------------------------------------------------------------
# white list: ops excluded from a specific check, with the reason recorded
# (parity: test/white_list/op_accuracy_white_list.py). Keep < 10% of SCHEMAS.
# ---------------------------------------------------------------------------
WHITE_LIST: Dict[str, Dict[str, str]] = {
    "erfinv": {"grad": "derivative ~ 1/erf'(x) explodes near ±1; FD unstable"},
    "nextafter": {"sweep": "ULP-level op; only exact fp32 comparison is meaningful"},
    "i1": {"grad": "scipy FD oracle noisy near 0"},
    "sinc": {"grad": "removable singularity at 0 makes FD noisy"},
    "logcumsumexp": {"sweep_low": "exp-space cumsum overflows fp16 quickly"},
    "multigammaln": {"grad": "vectorized scipy oracle too slow for FD"},
    "cummax": {"grad": "modal (tie) inputs make the FD subgradient non-unique"},
    "cummin": {"grad": "modal (tie) inputs make the FD subgradient non-unique"},
}


def registered_op_names():
    return sorted(SCHEMAS)


# long-tail schemas (manipulation/fft/nn/linalg/... ): populates SCHEMAS
# further; kept in separate modules for file size. Imported last so the
# registration helpers above exist.
from . import schemas_extended  # noqa: E402,F401
