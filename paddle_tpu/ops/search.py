"""Search / sort ops.

Parity: python/paddle/tensor/search.py (argmax, argsort, topk, sort,
searchsorted, kthvalue, mode) over XLA.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core import dtype as dtypes
from ..core.tensor import Tensor
from .dispatch import apply_op, ensure_tensor


def argmax(x, axis=None, keepdim=False, dtype="int64", name=None) -> Tensor:
    x = ensure_tensor(x)
    d = dtypes.convert_dtype(dtype)
    return Tensor(jnp.argmax(x._data, axis=axis, keepdims=keepdim).astype(d))


def argmin(x, axis=None, keepdim=False, dtype="int64", name=None) -> Tensor:
    x = ensure_tensor(x)
    d = dtypes.convert_dtype(dtype)
    return Tensor(jnp.argmin(x._data, axis=axis, keepdims=keepdim).astype(d))


def argsort(x, axis=-1, descending=False, stable=False, name=None) -> Tensor:
    x = ensure_tensor(x)
    idx = jnp.argsort(x._data, axis=axis, stable=stable, descending=descending)
    return Tensor(idx.astype(jnp.int64))


def sort(x, axis=-1, descending=False, stable=False, name=None) -> Tensor:
    x = ensure_tensor(x)

    def _f(a):
        out = jnp.sort(a, axis=axis, stable=stable, descending=descending)
        return out

    return apply_op("sort", _f, x)


def topk(x, k, axis=None, largest=True, sorted=True, name=None):
    x = ensure_tensor(x)
    if isinstance(k, Tensor):
        k = int(k._data.item())
    ax = -1 if axis is None else axis

    def _f(a):
        am = jnp.moveaxis(a, ax, -1)
        if largest:
            vals, idx = jax.lax.top_k(am, k)
        else:
            vals, idx = jax.lax.top_k(-am, k)
            vals = -vals
        return jnp.moveaxis(vals, -1, ax), jnp.moveaxis(idx, -1, ax)

    vals, idx = apply_op("topk", _f, x)
    return vals, Tensor(idx._data.astype(jnp.int64))


def searchsorted(sorted_sequence, values, out_int32=False, right=False, name=None) -> Tensor:
    ss, v = ensure_tensor(sorted_sequence), ensure_tensor(values)
    side = "right" if right else "left"

    def _f(s, val):
        if s.ndim == 1:
            out = jnp.searchsorted(s, val, side=side)
        else:
            flat_s = s.reshape(-1, s.shape[-1])
            flat_v = val.reshape(-1, val.shape[-1])
            out = jnp.stack([jnp.searchsorted(flat_s[i], flat_v[i], side=side) for i in range(flat_s.shape[0])])
            out = out.reshape(val.shape)
        return out.astype(jnp.int32 if out_int32 else jnp.int64)

    return Tensor(_f(ss._data, v._data))


def kthvalue(x, k, axis=-1, keepdim=False, name=None):
    x = ensure_tensor(x)

    def _f(a):
        am = jnp.moveaxis(a, axis, -1)
        vals, idx = jax.lax.top_k(-am, k)
        v = -vals[..., -1]
        i = idx[..., -1]
        if keepdim:
            v = jnp.expand_dims(v, axis)
            i = jnp.expand_dims(i, axis)
        return v, i

    vals, idx = apply_op("kthvalue", _f, x)
    return vals, Tensor(idx._data.astype(jnp.int64))


def mode(x, axis=-1, keepdim=False, name=None):
    x = ensure_tensor(x)
    arr = np.asarray(x._data)
    arr_m = np.moveaxis(arr, axis, -1)
    flat = arr_m.reshape(-1, arr_m.shape[-1])
    vals = np.empty(flat.shape[0], arr.dtype)
    idxs = np.empty(flat.shape[0], np.int64)
    for i, row in enumerate(flat):
        uniq, counts = np.unique(row, return_counts=True)
        best = uniq[np.argmax(counts)]
        vals[i] = best
        idxs[i] = np.where(row == best)[0][-1]
    shp = arr_m.shape[:-1]
    v = vals.reshape(shp)
    ix = idxs.reshape(shp)
    if keepdim:
        v = np.expand_dims(v, axis)
        ix = np.expand_dims(ix, axis)
    return Tensor(jnp.asarray(v)), Tensor(jnp.asarray(ix))


def bucketize(x, sorted_sequence, out_int32=False, right=False, name=None) -> Tensor:
    return searchsorted(sorted_sequence, x, out_int32=out_int32, right=right)


def histogram(input, bins=100, min=0, max=0, weight=None, density=False, name=None) -> Tensor:
    input = ensure_tensor(input)
    arr = np.asarray(input._data)
    lo, hi = (float(arr.min()), float(arr.max())) if min == 0 and max == 0 else (min, max)
    w = np.asarray(weight._data) if weight is not None else None
    hist, _ = np.histogram(arr, bins=bins, range=(lo, hi), weights=w, density=density)
    return Tensor(jnp.asarray(hist if density or w is not None else hist.astype(np.int64)))
