"""Eager op dispatch: the TPU analogue of the reference's generated ad_func path.

Reference call stack being replaced (SURVEY §3.1): generated
``matmul_ad_func`` (paddle/fluid/eager/auto_code_generator/generator/
eager_gen.py:316) → AMP autocast (fluid/eager/amp_auto_cast.h:23) →
phi API kernel dispatch (phi/api/lib/kernel_dispatch.h:216) → grad-node
creation (eager_gen.py:1096).

TPU design: each op is a pure jax function. Dispatch =
  1. AMP autocast hook (allow/block lists, like the reference's O1/O2),
  2. ``jax.vjp`` when any input requires grad — the pullback IS the grad
     node's kernel (XLA-traced, device-resident),
  3. tape recording (GradNode/Edge),
  4. optional NaN/Inf check (FLAGS_check_nan_inf parity).
XLA/PJRT executes ops asynchronously, so dispatch returns immediately —
the same async-enqueue property as the reference's stream model.
"""

from __future__ import annotations

import weakref
from typing import Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..core import dtype as dtypes
from ..core.autograd import Edge, GradNode, is_grad_enabled
from ..core.flags import flag
from ..core.tensor import Tensor
from ..profiler import _recording as _prof_recording  # shared mutable flag; zero-cost check
from ..observability.metrics import _ENABLED as _obs_on  # same zero-cost pattern
from ..observability.metrics import counter as _obs_counter

# NaN/Inf-check trips (FLAGS_check_nan_inf parity): every detection is a
# fleet-visible counter, not just a print/raise. Incremented only on the
# (rare) trip path — never on the per-op hot path.
_nan_trips = _obs_counter(
    "paddle_tpu_nan_check_trips_total",
    "ops whose output tripped the NaN/Inf finite check "
    "(FLAGS_check_nan_inf)", ("op",))

# Set by paddle_tpu.amp at import; signature: (op_name, [jax arrays]) -> [jax arrays]
_amp_cast_hook: Optional[Callable] = None

# Set by paddle_tpu.static while static-graph mode is enabled; signature:
# (op_name, fn, tensors, nouts) -> outputs | NotImplemented. Records the op
# into the current Program instead of executing (graph capture).
_static_hook: Optional[Callable] = None

# Set by paddle_tpu.amp.debugging while operator-stats collection is on:
# dict[(op_name, dtype_str)] -> count (parity: FLAGS low-precision op list,
# python/paddle/amp/debugging.py enable_operator_stats_collection).
_op_stats: Optional[dict] = None

# Op registry for introspection/testing (parity: phi/ops/yaml/ops.yaml registry role).
OP_REGISTRY: dict = {}

# Dispatch-name recorder (tests/test_schema_enforcement.py): while the
# list holds a set, every apply_op name is added to it. The enforcement
# test diffs recorded names against SCHEMAS ∪ NO_SCHEMA_WHITE_LIST ∪
# DYNAMIC_DISPATCH — the runtime cross-check of the static audit
# (parity role: ops.yaml's "no kernel without a schema" guarantee).
_dispatch_record = [None]


def record_dispatch(sink: Optional[set]):
    """Install (or clear, with None) the dispatch-name sink."""
    _dispatch_record[0] = sink


# Dataflow provenance mode (distributed/auto_shard.py): while enabled,
# every op output carries the union of its inputs' ``_prov`` sets — the
# TPU-form analogue of the reference's dist-attr propagation over a
# program (auto_parallel/static/completion.py).
_prov_enabled = [False]


def _propagate_prov(tensors, outs):
    # provenance sets are immutable and SHARED between tensors: the common
    # case (single provider chain) costs one attribute write, no copies
    acc = None
    for t in tensors:
        p = getattr(t, "_prov", None)
        if p:
            acc = p if acc is None or acc is p else (acc | p)
    if acc:
        for o in outs:
            o._prov = acc


def register_op(name: str, **meta):
    OP_REGISTRY[name] = meta


# ---------------------------------------------------------------------------
# Cached compiled programs for the dispatch hot path (SURVEY §7.1: "thin
# dispatch: (op, dtype) -> cached compiled executable"). Only STABLE op
# bodies qualify — module-level functions reused across calls, where the
# function object identity is a valid cache key. Per-call closures (ops
# closing over attributes) would compile fresh programs every call, so
# they take the plain eager path.
# ---------------------------------------------------------------------------

_fwd_jit_cache: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()
_pullback_cache: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()


def _is_diff_dtype(dt) -> bool:
    """float or complex — the dtypes that carry cotangents."""
    return (dtypes.is_floating_point(dt)
            or np.issubdtype(np.dtype(dt), np.complexfloating))


def _stable_fn(fn) -> bool:
    if getattr(fn, "_pt_stable", False):
        return True
    try:
        return (getattr(fn, "__closure__", True) is None
                and "<locals>" not in getattr(fn, "__qualname__", "<locals>"))
    except Exception:  # pragma: no cover
        return False


from collections import OrderedDict

_STABLE_CLOSURES: "OrderedDict" = OrderedDict()
_STABLE_CLOSURES_CAP = 1024  # LRU bound: evicting a closure also releases
# its weak-keyed jitted fwd/pullback executables (data-dependent shapes
# would otherwise pin compiled programs forever)


def stable_closure(fn, *attrs):
    """Memoized attr-binding: returns THE SAME function object for the same
    (fn, attrs), so attr-carrying ops (axis, perm, shape...) also qualify
    for the compiled fwd/pullback caches. attrs must be hashable."""
    key = (fn, attrs)
    f = _STABLE_CLOSURES.get(key)
    if f is None:
        def f(*arrays):
            return fn(*arrays, *attrs)

        f._pt_stable = True
        f.__name__ = getattr(fn, "__name__", "op") + str(attrs)
        _STABLE_CLOSURES[key] = f
        if len(_STABLE_CLOSURES) > _STABLE_CLOSURES_CAP:
            _STABLE_CLOSURES.popitem(last=False)
    else:
        _STABLE_CLOSURES.move_to_end(key)
    return f


def _cached_fwd(fn):
    """jit-compiled forward keyed on the (stable) fn object; jax.jit's own
    trace cache then keys on input avals — eager execution becomes a PJRT
    executable-cache lookup instead of per-primitive dispatch."""
    j = _fwd_jit_cache.get(fn)
    if j is None:
        try:
            j = jax.jit(fn)
            _fwd_jit_cache[fn] = j
        except TypeError:  # non-weakrefable
            return fn
    return j


def _cached_pullback(fn, diff_idx, sg_mask):
    """Compiled (inputs, float-cotangents) -> input-cotangents program.
    The forward is recomputed inside the program; XLA dead-code-eliminates
    everything the gradients don't need, leaving the pure grad kernel
    (the role of the reference's generated grad kernels)."""
    per_fn = _pullback_cache.get(fn)
    if per_fn is None:
        per_fn = _pullback_cache[fn] = {}
    key = (diff_idx, sg_mask)
    pb = per_fn.get(key)
    if pb is not None:
        return pb

    def pullback(datas, float_cots):
        def wrapped(*diff_xs):
            xs = list(datas)
            for i, x in zip(diff_idx, diff_xs):
                xs[i] = jax.lax.stop_gradient(x) if sg_mask[i] else x
            return fn(*xs)

        out, vjp = jax.vjp(wrapped, *[datas[i] for i in diff_idx])
        cots = _rebuild_cots(out, float_cots)
        return vjp(cots)

    pb = jax.jit(pullback)
    per_fn[key] = pb
    return pb


def _rebuild_cots(out, float_cots):
    """Interleave float cotangents with float0 zeros for int/bool outputs,
    matching ``out``'s structure (jax.vjp's cotangent contract)."""
    multi = isinstance(out, (tuple, list))
    outs = list(out) if multi else [out]
    fc = list(float_cots)
    cots = []
    for o in outs:
        if _is_diff_dtype(o.dtype):
            cots.append(fc.pop(0))
        else:
            cots.append(np.zeros(o.shape, jax.dtypes.float0))
    return tuple(cots) if multi else cots[0]


def set_amp_hook(hook):
    global _amp_cast_hook
    _amp_cast_hook = hook


def _check_finite(name: str, arrays):
    for a in arrays:
        if dtypes.is_floating_point(a.dtype):
            if not bool(jnp.isfinite(a).all()):
                if _obs_on[0]:
                    _nan_trips.labels(name).inc()
                if flag("check_nan_inf_level") >= 1:
                    print(f"[check_nan_inf] WARNING: op {name} produced NaN/Inf")
                else:
                    raise FloatingPointError(f"op {name} produced NaN/Inf output")


def _zeros_cotangent(shape, dtype):
    if np.dtype(dtype) in (np.dtype(np.bool_),) or np.issubdtype(np.dtype(dtype), np.integer):
        return np.zeros(shape, jax.dtypes.float0)
    return jnp.zeros(shape, dtype)


def apply_op(name: str, fn: Callable, *tensors: Tensor, nouts: Optional[int] = None):
    """Execute ``fn(*arrays)`` with tape recording.

    ``tensors`` are the Tensor inputs, positionally matching ``fn``'s args;
    static attributes must be closed over in ``fn``. ``fn`` may return a
    single array or a tuple of arrays.
    """
    if _prof_recording[0]:  # host tracer span per op (RecordEvent parity)
        from .. import profiler as _prof

        with _prof.RecordEvent(name, _prof.TracerEventType.Operator):
            return _apply_op_impl(name, fn, *tensors, nouts=nouts)
    return _apply_op_impl(name, fn, *tensors, nouts=nouts)


def _apply_op_impl(name: str, fn: Callable, *tensors: Tensor, nouts: Optional[int] = None):
    if _dispatch_record[0] is not None:
        _dispatch_record[0].add(name)
    if _static_hook is not None:
        res = _static_hook(name, fn, tensors, nouts)
        if res is not NotImplemented:
            return res
    datas = [t._data for t in tensors]

    if _amp_cast_hook is not None:
        datas = _amp_cast_hook(name, datas)

    record = is_grad_enabled() and any(not t.stop_gradient for t in tensors)

    if record:
        # Integer/bool inputs are closed over as constants rather than vjp
        # arguments (their cotangents would be float0; some tracer contexts
        # — e.g. shard_map — don't support differentiating through them).
        diff_mask = [_is_diff_dtype(d.dtype) for d in datas]
        sg_mask = [t.stop_gradient for t in tensors]
        diff_idx = [i for i, m in enumerate(diff_mask) if m]

        def wrapped(*diff_xs):
            xs = list(datas)
            for i, x in zip(diff_idx, diff_xs):
                xs[i] = jax.lax.stop_gradient(x) if sg_mask[i] else x
            return fn(*xs)

        diff_datas = [datas[i] for i in diff_idx]
        if not diff_datas:
            record = False
            out_data = fn(*datas)
        else:
            stable = _stable_fn(fn)
            if stable:
                # Lazy backward fast path: no vjp trace at dispatch. The
                # pullback is a cached jitted program derived at backward;
                # its recomputed forward is dead-code-eliminated by XLA, so
                # steady state is two executable-cache lookups per op
                # (reference: ad_func enqueues the forward kernel; the
                # grad node holds saved inputs only).
                out_data = _cached_fwd(fn)(*datas)
                datas_t = tuple(datas)
                didx = tuple(diff_idx)
                sg_t = tuple(sg_mask)

                def vjp_fn(cots):
                    cots_list = list(cots) if isinstance(cots, tuple) else [cots]
                    float_cots = tuple(c for c, spec in zip(cots_list, out_specs)
                                       if _is_diff_dtype(spec[1]))
                    diff_cots = _cached_pullback(fn, didx, sg_t)(datas_t, float_cots)
                    full = [None] * len(datas_t)
                    for i, g in zip(didx, diff_cots):
                        full[i] = g
                    return tuple(full)
            else:
                # per-call closure bodies: derive the pullback now (eager
                # vjp executes the forward exactly once through its trace —
                # deriving lazily at backward would re-run the forward)
                out_data, inner_vjp = jax.vjp(wrapped, *diff_datas)

                def vjp_fn(cots):
                    cots_list = list(cots) if isinstance(cots, tuple) else [cots]
                    filled = tuple(
                        c if _is_diff_dtype(spec[1])
                        else np.zeros(spec[0], jax.dtypes.float0)
                        for c, spec in zip(cots_list, out_specs))
                    diff_cots = inner_vjp(filled if len(filled) != 1 else filled[0])
                    full = [None] * len(datas)
                    for i, g in zip(diff_idx, diff_cots):
                        full[i] = g
                    return tuple(full)
    else:
        out_data = _cached_fwd(fn)(*datas) if _stable_fn(fn) else fn(*datas)

    multi = isinstance(out_data, (tuple, list))
    outs_data = list(out_data) if multi else [out_data]

    if _op_stats is not None:
        for d in outs_data:
            k = (name, str(np.dtype(d.dtype)))
            _op_stats[k] = _op_stats.get(k, 0) + 1

    if flag("check_nan_inf"):
        _check_finite(name, outs_data)

    if not record:
        outs = [Tensor(d, stop_gradient=True) for d in outs_data]
        if _prov_enabled[0]:
            _propagate_prov(tensors, outs)
        return outs if multi else outs[0]

    edges: List[Edge] = []
    for t in tensors:
        if t.stop_gradient:
            edges.append(Edge())
        elif t._grad_node is not None:
            edges.append(Edge(node=t._grad_node, slot=t._out_slot))
        else:
            edges.append(Edge(leaf=t))

    out_specs = [(tuple(d.shape), d.dtype) for d in outs_data]

    node = GradNode(name, vjp_fn, edges, out_specs)
    # re-derivation info for create_graph (double backward); fwd_datas
    # snapshots the input arrays so later in-place mutation of the input
    # Tensors cannot corrupt the re-derived vjp
    node.fwd_fn = wrapped
    node.fwd_inputs = [tensors[i] for i in diff_idx]
    node.fwd_datas = diff_datas
    node.diff_idx = diff_idx
    node.multi = multi

    outs = []
    for i, d in enumerate(outs_data):
        differentiable = _is_diff_dtype(d.dtype)
        t = Tensor(d, stop_gradient=not differentiable)
        if differentiable:
            t._grad_node = node
            t._out_slot = i
        outs.append(t)
    if _prov_enabled[0]:
        _propagate_prov(tensors, outs)
    return outs if multi else outs[0]


def as_tensor_or_scalar(x):
    """Normalize op operands: Tensors pass through; scalars/arrays stay raw
    (closed over as constants so they don't enter the tape)."""
    return x


def ensure_tensor(x, dtype=None) -> Tensor:
    if isinstance(x, Tensor):
        return x
    d = dtypes.convert_dtype(dtype) if dtype is not None else None
    arr = jnp.asarray(x, d)
    if d is None and arr.dtype == jnp.float64:
        arr = arr.astype(dtypes.get_default_dtype())
    return Tensor(arr, stop_gradient=True)
