"""Eager op dispatch: the TPU analogue of the reference's generated ad_func path.

Reference call stack being replaced (SURVEY §3.1): generated
``matmul_ad_func`` (paddle/fluid/eager/auto_code_generator/generator/
eager_gen.py:316) → AMP autocast (fluid/eager/amp_auto_cast.h:23) →
phi API kernel dispatch (phi/api/lib/kernel_dispatch.h:216) → grad-node
creation (eager_gen.py:1096).

TPU design: each op is a pure jax function. Dispatch =
  1. AMP autocast hook (allow/block lists, like the reference's O1/O2),
  2. ``jax.vjp`` when any input requires grad — the pullback IS the grad
     node's kernel (XLA-traced, device-resident),
  3. tape recording (GradNode/Edge),
  4. optional NaN/Inf check (FLAGS_check_nan_inf parity).
XLA/PJRT executes ops asynchronously, so dispatch returns immediately —
the same async-enqueue property as the reference's stream model.
"""

from __future__ import annotations

from typing import Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..core import dtype as dtypes
from ..core.autograd import Edge, GradNode, is_grad_enabled
from ..core.flags import flag
from ..core.tensor import Tensor
from ..profiler import _recording as _prof_recording  # shared mutable flag; zero-cost check

# Set by paddle_tpu.amp at import; signature: (op_name, [jax arrays]) -> [jax arrays]
_amp_cast_hook: Optional[Callable] = None

# Set by paddle_tpu.static while static-graph mode is enabled; signature:
# (op_name, fn, tensors, nouts) -> outputs | NotImplemented. Records the op
# into the current Program instead of executing (graph capture).
_static_hook: Optional[Callable] = None

# Set by paddle_tpu.amp.debugging while operator-stats collection is on:
# dict[(op_name, dtype_str)] -> count (parity: FLAGS low-precision op list,
# python/paddle/amp/debugging.py enable_operator_stats_collection).
_op_stats: Optional[dict] = None

# Op registry for introspection/testing (parity: phi/ops/yaml/ops.yaml registry role).
OP_REGISTRY: dict = {}


def register_op(name: str, **meta):
    OP_REGISTRY[name] = meta


def set_amp_hook(hook):
    global _amp_cast_hook
    _amp_cast_hook = hook


def _check_finite(name: str, arrays):
    for a in arrays:
        if dtypes.is_floating_point(a.dtype):
            if not bool(jnp.isfinite(a).all()):
                if flag("check_nan_inf_level") >= 1:
                    print(f"[check_nan_inf] WARNING: op {name} produced NaN/Inf")
                else:
                    raise FloatingPointError(f"op {name} produced NaN/Inf output")


def _zeros_cotangent(shape, dtype):
    if np.dtype(dtype) in (np.dtype(np.bool_),) or np.issubdtype(np.dtype(dtype), np.integer):
        return np.zeros(shape, jax.dtypes.float0)
    return jnp.zeros(shape, dtype)


def apply_op(name: str, fn: Callable, *tensors: Tensor, nouts: Optional[int] = None):
    """Execute ``fn(*arrays)`` with tape recording.

    ``tensors`` are the Tensor inputs, positionally matching ``fn``'s args;
    static attributes must be closed over in ``fn``. ``fn`` may return a
    single array or a tuple of arrays.
    """
    if _prof_recording[0]:  # host tracer span per op (RecordEvent parity)
        from .. import profiler as _prof

        with _prof.RecordEvent(name, _prof.TracerEventType.Operator):
            return _apply_op_impl(name, fn, *tensors, nouts=nouts)
    return _apply_op_impl(name, fn, *tensors, nouts=nouts)


def _apply_op_impl(name: str, fn: Callable, *tensors: Tensor, nouts: Optional[int] = None):
    if _static_hook is not None:
        res = _static_hook(name, fn, tensors, nouts)
        if res is not NotImplemented:
            return res
    datas = [t._data for t in tensors]

    if _amp_cast_hook is not None:
        datas = _amp_cast_hook(name, datas)

    record = is_grad_enabled() and any(not t.stop_gradient for t in tensors)

    if record:
        # Integer/bool inputs are closed over as constants rather than vjp
        # arguments (their cotangents would be float0; some tracer contexts
        # — e.g. shard_map — don't support differentiating through them).
        diff_mask = [
            dtypes.is_floating_point(d.dtype) or np.issubdtype(np.dtype(d.dtype), np.complexfloating)
            for d in datas
        ]
        sg_mask = [t.stop_gradient for t in tensors]
        diff_idx = [i for i, m in enumerate(diff_mask) if m]

        def wrapped(*diff_xs):
            xs = list(datas)
            for i, x in zip(diff_idx, diff_xs):
                xs[i] = jax.lax.stop_gradient(x) if sg_mask[i] else x
            return fn(*xs)

        diff_datas = [datas[i] for i in diff_idx]
        if not diff_datas:
            record = False
            out_data = fn(*datas)
        else:
            out_data, inner_vjp = jax.vjp(wrapped, *diff_datas)

            def vjp_fn(cots):
                diff_cots = inner_vjp(cots)
                full = [None] * len(datas)
                for i, g in zip(diff_idx, diff_cots):
                    full[i] = g
                return tuple(full)
    else:
        out_data = fn(*datas)

    multi = isinstance(out_data, (tuple, list))
    outs_data = list(out_data) if multi else [out_data]

    if _op_stats is not None:
        for d in outs_data:
            k = (name, str(np.dtype(d.dtype)))
            _op_stats[k] = _op_stats.get(k, 0) + 1

    if flag("check_nan_inf"):
        _check_finite(name, outs_data)

    if not record:
        outs = [Tensor(d, stop_gradient=True) for d in outs_data]
        return outs if multi else outs[0]

    edges: List[Edge] = []
    for t in tensors:
        if t.stop_gradient:
            edges.append(Edge())
        elif t._grad_node is not None:
            edges.append(Edge(node=t._grad_node, slot=t._out_slot))
        else:
            edges.append(Edge(leaf=t))

    out_specs = [(tuple(d.shape), d.dtype) for d in outs_data]

    def vjp_with_zero_fill(cots):
        # Replace int/bool-output cotangents with float0 zeros as jax.vjp requires.
        if isinstance(cots, tuple):
            cots = tuple(
                c if dtypes.is_floating_point(spec[1]) or np.issubdtype(np.dtype(spec[1]), np.complexfloating)
                else np.zeros(spec[0], jax.dtypes.float0)
                for c, spec in zip(cots, out_specs)
            )
        return vjp_fn(cots)

    node = GradNode(name, vjp_with_zero_fill, edges, out_specs)
    # re-derivation info for create_graph (double backward); fwd_datas
    # snapshots the input arrays so later in-place mutation of the input
    # Tensors cannot corrupt the re-derived vjp
    node.fwd_fn = wrapped
    node.fwd_inputs = [tensors[i] for i in diff_idx]
    node.fwd_datas = diff_datas
    node.diff_idx = diff_idx
    node.multi = multi

    outs = []
    for i, d in enumerate(outs_data):
        differentiable = dtypes.is_floating_point(d.dtype) or np.issubdtype(np.dtype(d.dtype), np.complexfloating)
        t = Tensor(d, stop_gradient=not differentiable)
        if differentiable:
            t._grad_node = node
            t._out_slot = i
        outs.append(t)
    return outs if multi else outs[0]


def as_tensor_or_scalar(x):
    """Normalize op operands: Tensors pass through; scalars/arrays stay raw
    (closed over as constants so they don't enter the tape)."""
    return x


def ensure_tensor(x, dtype=None) -> Tensor:
    if isinstance(x, Tensor):
        return x
    d = dtypes.convert_dtype(dtype) if dtype is not None else None
    arr = jnp.asarray(x, d)
    if d is None and arr.dtype == jnp.float64:
        arr = arr.astype(dtypes.get_default_dtype())
    return Tensor(arr, stop_gradient=True)
