"""Tensor creation ops.

Parity: python/paddle/tensor/creation.py (to_tensor:796, zeros, ones, full,
arange, linspace, eye, tril/triu, meshgrid, diag) over XLA arrays.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core import dtype as dtypes
from ..core.tensor import Tensor
from .dispatch import apply_op, ensure_tensor


def _shape(shape):
    if isinstance(shape, Tensor):
        return tuple(int(s) for s in np.asarray(shape._data))
    if isinstance(shape, (int, np.integer)):
        return (int(shape),)
    return tuple(int(s._data.item()) if isinstance(s, Tensor) else int(s) for s in shape)


def to_tensor(data, dtype=None, place=None, stop_gradient: bool = True) -> Tensor:
    d = dtypes.convert_dtype(dtype)
    if isinstance(data, Tensor):
        arr = data._data if d is None else data._data.astype(d)
        return Tensor(arr, stop_gradient=stop_gradient)
    arr = jnp.asarray(data, d)
    if d is None and arr.dtype == jnp.float64:
        arr = arr.astype(dtypes.get_default_dtype())
    return Tensor(arr, stop_gradient=stop_gradient)


def zeros(shape, dtype=None, name=None) -> Tensor:
    d = dtypes.convert_dtype(dtype) or dtypes.get_default_dtype()
    return Tensor(jnp.zeros(_shape(shape), d))


def ones(shape, dtype=None, name=None) -> Tensor:
    d = dtypes.convert_dtype(dtype) or dtypes.get_default_dtype()
    return Tensor(jnp.ones(_shape(shape), d))


def full(shape, fill_value, dtype=None, name=None) -> Tensor:
    d = dtypes.convert_dtype(dtype) or dtypes.get_default_dtype()
    if isinstance(fill_value, Tensor):
        fill_value = fill_value._data
    return Tensor(jnp.full(_shape(shape), fill_value, d))


def zeros_like(x, dtype=None, name=None) -> Tensor:
    x = ensure_tensor(x)
    d = dtypes.convert_dtype(dtype)
    return Tensor(jnp.zeros(x._data.shape, d or x._data.dtype))


def ones_like(x, dtype=None, name=None) -> Tensor:
    x = ensure_tensor(x)
    d = dtypes.convert_dtype(dtype)
    return Tensor(jnp.ones(x._data.shape, d or x._data.dtype))


def full_like(x, fill_value, dtype=None, name=None) -> Tensor:
    x = ensure_tensor(x)
    d = dtypes.convert_dtype(dtype)
    return Tensor(jnp.full(x._data.shape, fill_value, d or x._data.dtype))


def empty(shape, dtype=None, name=None) -> Tensor:
    return zeros(shape, dtype)


def empty_like(x, dtype=None, name=None) -> Tensor:
    return zeros_like(x, dtype)


def arange(start=0, end=None, step=1, dtype=None, name=None) -> Tensor:
    d = dtypes.convert_dtype(dtype)
    def _v(x):
        return x._data.item() if isinstance(x, Tensor) else x
    start, end, step = _v(start), _v(end), _v(step)
    if end is None:
        start, end = 0, start
    arr = jnp.arange(start, end, step, d)
    if d is None and arr.dtype == jnp.float64:
        arr = arr.astype(dtypes.get_default_dtype())
    return Tensor(arr)


def linspace(start, stop, num, dtype=None, name=None) -> Tensor:
    d = dtypes.convert_dtype(dtype) or dtypes.get_default_dtype()
    def _v(x):
        return x._data.item() if isinstance(x, Tensor) else x
    return Tensor(jnp.linspace(_v(start), _v(stop), int(_v(num)), dtype=d))


def eye(num_rows, num_columns=None, dtype=None, name=None) -> Tensor:
    d = dtypes.convert_dtype(dtype) or dtypes.get_default_dtype()
    return Tensor(jnp.eye(num_rows, num_columns, dtype=d))


def tril(x, diagonal=0, name=None) -> Tensor:
    return apply_op("tril", lambda a: jnp.tril(a, diagonal), ensure_tensor(x))


def triu(x, diagonal=0, name=None) -> Tensor:
    return apply_op("triu", lambda a: jnp.triu(a, diagonal), ensure_tensor(x))


def meshgrid(*args, **kwargs):
    ts = [ensure_tensor(a) for a in (args[0] if len(args) == 1 and isinstance(args[0], (list, tuple)) else args)]
    outs = apply_op("meshgrid", lambda *xs: tuple(jnp.meshgrid(*xs, indexing="ij")), *ts)
    return list(outs) if isinstance(outs, (list, tuple)) else [outs]


def diag(x, offset=0, padding_value=0, name=None) -> Tensor:
    x = ensure_tensor(x)

    def _diag(a):
        if a.ndim == 1:
            out = jnp.diag(a, k=offset)
            if padding_value != 0:
                mask = jnp.eye(out.shape[0], out.shape[1], k=offset, dtype=bool)
                out = jnp.where(mask, out, jnp.asarray(padding_value, a.dtype))
            return out
        return jnp.diagonal(a, offset=offset)

    return apply_op("diag", _diag, x)


def diagflat(x, offset=0, name=None) -> Tensor:
    return apply_op("diagflat", lambda a: jnp.diagflat(a, k=offset), ensure_tensor(x))


def clone(x, name=None) -> Tensor:
    return ensure_tensor(x).clone()


def assign(x, output=None) -> Tensor:
    x = ensure_tensor(x) if not isinstance(x, Tensor) else x
    out = apply_op("assign", lambda a: a, x)
    if output is not None:
        output._replace_(out)
        return output
    return out


def numel(x, name=None) -> Tensor:
    return Tensor(jnp.asarray(ensure_tensor(x).size, jnp.int64))


def tril_indices(row, col, offset=0, dtype="int64"):
    d = dtypes.convert_dtype(dtype)
    r, c = np.tril_indices(row, offset, col)
    return Tensor(jnp.asarray(np.stack([r, c]), d))


def triu_indices(row, col=None, offset=0, dtype="int64"):
    d = dtypes.convert_dtype(dtype)
    r, c = np.triu_indices(row, offset, col if col is not None else row)
    return Tensor(jnp.asarray(np.stack([r, c]), d))
