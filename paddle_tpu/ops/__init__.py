"""Op namespace + Tensor method registration.

Mirrors the reference's pattern of patching generated op methods onto the
Tensor pytype (reference: python/paddle/tensor/__init__.py method
registration; pybind eager_method.cc operator definitions).
"""

from __future__ import annotations

import builtins

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from .dispatch import apply_op, ensure_tensor, OP_REGISTRY, register_op, set_amp_hook
from .creation import *  # noqa: F401,F403
from .random import *  # noqa: F401,F403
from .math import *  # noqa: F401,F403
from .manipulation import *  # noqa: F401,F403
from .logic import *  # noqa: F401,F403
from .search import *  # noqa: F401,F403
from .math_extra import *  # noqa: F401,F403
from .long_tail import *  # noqa: F401,F403
from .api_parity import *  # noqa: F401,F403

from . import api_parity, creation, random, math, manipulation, logic, math_extra, search, long_tail


def _norm_index(idx):
    """Convert a Paddle-style index (Tensors allowed) to jnp-compatible index."""
    if isinstance(idx, Tensor):
        return idx._data
    if isinstance(idx, (list,)):
        return jnp.asarray(idx)
    if isinstance(idx, tuple):
        return tuple(_norm_index(i) for i in idx)
    return idx


def _getitem(self: Tensor, idx):
    nidx = _norm_index(idx)
    # Boolean-mask indexing yields dynamic shapes: eager host path.
    def _has_bool(i):
        if isinstance(i, tuple):
            return builtins.any(_has_bool(v) for v in i)
        return getattr(i, "dtype", None) == jnp.bool_ or isinstance(i, np.ndarray) and i.dtype == np.bool_

    if _has_bool(nidx):
        from .manipulation import masked_select

        if not isinstance(nidx, tuple) and nidx.shape == self._data.shape:
            return masked_select(self, Tensor(nidx))
        data = np.asarray(self._data)[np.asarray(idx) if not isinstance(idx, tuple) else idx]
        return Tensor(jnp.asarray(data))
    return apply_op("getitem", lambda a: a[nidx], self)


def _setitem(self: Tensor, idx, value):
    nidx = _norm_index(idx)
    if isinstance(value, Tensor):
        out = apply_op("setitem", lambda a, v: a.at[nidx].set(v.astype(a.dtype)), self, value)
    else:
        v = jnp.asarray(value)
        out = apply_op("setitem", lambda a: a.at[nidx].set(v.astype(a.dtype)), self)
    self._replace_(out)
    return self


def getitem(x, idx):
    """Functional ``x[idx]`` (the __getitem__ kernel; schema-swept)."""
    t = x if isinstance(x, Tensor) else Tensor(jnp.asarray(x))
    return _getitem(t, idx)


def setitem(x, idx, value):
    """Functional out-of-place ``x[idx] = value`` -> new Tensor (the
    __setitem__ kernel; schema-swept). ``x`` is left untouched."""
    t = x if isinstance(x, Tensor) else Tensor(jnp.asarray(x))
    y = Tensor(t._data, stop_gradient=t.stop_gradient)
    return _setitem(y, idx, value)


def _iter(self: Tensor):
    for i in range(len(self)):
        yield self[i]


_BINOPS = {
    "__add__": math.add,
    "__radd__": lambda x, y: math.add(y, x),
    "__sub__": math.subtract,
    "__rsub__": lambda x, y: math.subtract(y, x),
    "__mul__": math.multiply,
    "__rmul__": lambda x, y: math.multiply(y, x),
    "__truediv__": math.divide,
    "__rtruediv__": lambda x, y: math.divide(y, x),
    "__floordiv__": math.floor_divide,
    "__rfloordiv__": lambda x, y: math.floor_divide(y, x),
    "__mod__": math.mod,
    "__rmod__": lambda x, y: math.mod(y, x),
    "__pow__": math.pow,
    "__rpow__": lambda x, y: math.pow(y, x),
    "__matmul__": math.matmul,
    "__rmatmul__": lambda x, y: math.matmul(y, x),
    "__eq__": logic.equal,
    "__ne__": logic.not_equal,
    "__lt__": logic.less_than,
    "__le__": logic.less_equal,
    "__gt__": logic.greater_than,
    "__ge__": logic.greater_equal,
    "__and__": logic.bitwise_and,
    "__or__": logic.bitwise_or,
    "__xor__": logic.bitwise_xor,
    "__lshift__": logic.bitwise_left_shift,
    "__rshift__": logic.bitwise_right_shift,
}


def _patch_tensor():
    for name, fn in _BINOPS.items():
        setattr(Tensor, name, fn)
    Tensor.__neg__ = math.neg
    Tensor.__abs__ = math.abs
    Tensor.__invert__ = logic.bitwise_not
    Tensor.__getitem__ = _getitem
    Tensor.__setitem__ = _setitem
    Tensor.__iter__ = _iter
    Tensor.__hash__ = lambda self: id(self)

    _methods = {
        # math
        "add": math.add, "subtract": math.subtract, "multiply": math.multiply,
        "divide": math.divide, "floor_divide": math.floor_divide, "mod": math.mod,
        "remainder": math.mod, "pow": math.pow, "matmul": math.matmul, "mm": math.mm,
        "bmm": math.bmm, "dot": math.dot, "abs": math.abs, "neg": math.neg,
        "sqrt": math.sqrt, "rsqrt": math.rsqrt, "square": math.square,
        "reciprocal": math.reciprocal, "exp": math.exp, "log": math.log,
        "log2": math.log2, "log10": math.log10, "log1p": math.log1p,
        "sin": math.sin, "cos": math.cos, "tan": math.tan, "tanh": math.tanh,
        "sigmoid": math.sigmoid, "erf": math.erf, "sign": math.sign,
        "floor": math.floor, "ceil": math.ceil, "round": math.round, "trunc": math.trunc,
        "clip": math.clip, "scale": math.scale, "maximum": math.maximum, "minimum": math.minimum,
        "sum": math.sum, "mean": math.mean, "prod": math.prod, "max": math.max,
        "min": math.min, "amax": math.amax, "amin": math.amin, "all": math.all, "any": math.any,
        "std": math.std, "var": math.var, "median": math.median, "logsumexp": math.logsumexp,
        "cumsum": math.cumsum, "cumprod": math.cumprod, "trace": math.trace,
        "diagonal": math.diagonal, "inverse": math.inverse, "lerp": math.lerp,
        "kron": math.kron, "outer": math.outer, "inner": math.inner, "cross": math.cross,
        "atan2": math.atan2, "einsum": None,
        # manipulation
        "reshape": manipulation.reshape, "reshape_": manipulation.reshape_,
        "flatten": manipulation.flatten, "transpose": manipulation.transpose,
        "t": manipulation.t,
        "squeeze": manipulation.squeeze, "squeeze_": manipulation.squeeze_,
        "unsqueeze": manipulation.unsqueeze, "unsqueeze_": manipulation.unsqueeze_,
        "expand": manipulation.expand, "expand_as": manipulation.expand_as,
        "broadcast_to": manipulation.broadcast_to, "tile": manipulation.tile,
        "flip": manipulation.flip, "roll": manipulation.roll, "pad": manipulation.pad,
        "gather": manipulation.gather, "gather_nd": manipulation.gather_nd,
        "scatter": manipulation.scatter, "scatter_": manipulation.scatter_,
        "scatter_nd_add": manipulation.scatter_nd_add,
        "index_select": manipulation.index_select, "index_sample": manipulation.index_sample,
        "index_add": manipulation.index_add, "index_put": manipulation.index_put,
        "take_along_axis": manipulation.take_along_axis, "put_along_axis": manipulation.put_along_axis,
        "masked_select": manipulation.masked_select, "masked_fill": manipulation.masked_fill,
        "where": manipulation.where, "nonzero": manipulation.nonzero,
        "unique": manipulation.unique, "split": manipulation.split, "chunk": manipulation.chunk,
        "unstack": manipulation.unstack, "concat": None, "stack": None,
        "repeat_interleave": manipulation.repeat_interleave,
        "moveaxis": manipulation.moveaxis, "swapaxes": manipulation.swapaxes,
        "view": manipulation.view, "view_as": manipulation.view_as,
        "slice": manipulation.slice, "strided_slice": manipulation.strided_slice,
        "fill_diagonal_": manipulation.fill_diagonal_, "tensor_split": manipulation.tensor_split,
        # logic
        "equal": logic.equal, "not_equal": logic.not_equal,
        "greater_than": logic.greater_than, "greater_equal": logic.greater_equal,
        "less_than": logic.less_than, "less_equal": logic.less_equal,
        "logical_and": logic.logical_and, "logical_or": logic.logical_or,
        "logical_not": logic.logical_not, "logical_xor": logic.logical_xor,
        "bitwise_and": logic.bitwise_and, "bitwise_or": logic.bitwise_or,
        "bitwise_not": logic.bitwise_not, "bitwise_xor": logic.bitwise_xor,
        "equal_all": logic.equal_all, "allclose": logic.allclose, "isclose": logic.isclose,
        "isnan": logic.isnan, "isinf": logic.isinf, "isfinite": logic.isfinite,
        # search
        "argmax": search.argmax, "argmin": search.argmin, "argsort": search.argsort,
        "sort": search.sort, "topk": search.topk, "kthvalue": search.kthvalue,
        "mode": search.mode, "searchsorted": None, "bucketize": search.bucketize,
        # creation-ish
        "tril": creation.tril, "triu": creation.triu, "diag": creation.diag,
        "zero_": lambda self: self.set_value(jnp.zeros(self._data.shape, self._data.dtype)),
        "fill_": lambda self, v: self.set_value(jnp.full(self._data.shape, v, self._data.dtype)),
        # random inplace
        "uniform_": random.uniform_, "normal_": random.normal_, "exponential_": random.exponential_,
    }
    for name, fn in _methods.items():
        if fn is not None:
            setattr(Tensor, name, fn)

    # in-place arithmetic (rebind semantics)
    def _make_inplace(op):
        def f(self, y, name=None):
            return self._replace_(op(self, y))

        return f

    for nm, op in (("add_", math.add), ("subtract_", math.subtract), ("multiply_", math.multiply),
                   ("divide_", math.divide), ("remainder_", math.mod)):
        setattr(Tensor, nm, _make_inplace(op))

    Tensor.clip_ = lambda self, min=None, max=None, name=None: self._replace_(math.clip(self, min, max))
    Tensor.scale_ = lambda self, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None: self._replace_(
        math.scale(self, scale, bias, bias_after_scale))

    def cast_(self, dtype):
        from ..core.dtype import convert_dtype

        self._data = self._data.astype(convert_dtype(dtype))
        return self

    Tensor.cast_ = cast_

    # long-tail ops as Tensor methods (paddle method-call parity)
    for nm in ("bincount", "take", "quantile", "nanquantile", "nanmedian", "signbit",
               "sinc", "sgn", "isneginf", "isposinf", "isreal", "frexp", "unflatten",
               "masked_scatter", "renorm", "cov", "corrcoef", "vander", "trapezoid",
               "cumulative_trapezoid", "cdist"):
        setattr(Tensor, nm, getattr(math_extra, nm))

    # remaining reference Tensor-method surface
    import numpy as _np

    Tensor.numel = lambda self: self.size
    Tensor.dim = lambda self: self.ndim
    Tensor.ndimension = Tensor.dim
    Tensor.element_size = lambda self: _np.dtype(self._data.dtype).itemsize
    # reference API form: methods, not properties (paddle Tensor.real())
    Tensor.real = math.real
    Tensor.imag = math.imag
    def _mT(self):
        if len(self._data.shape) < 2:
            raise ValueError("Tensor.mT/H require at least 2 dimensions")
        return manipulation.swapaxes(self, -1, -2)

    Tensor.mT = property(_mT)
    Tensor.H = property(lambda self: math.conj(_mT(self)))
    Tensor.unbind = lambda self, axis=0: manipulation.unstack(self, axis)
    Tensor.cuda = lambda self, *a, **k: self  # device movement is a no-op handle copy
    Tensor.value = lambda self: self
    Tensor.get_tensor = lambda self: self
    for nm, op in (("exp_", math.exp), ("sqrt_", math.sqrt), ("rsqrt_", math.rsqrt),
                   ("floor_", math.floor), ("ceil_", math.ceil), ("round_", math.round),
                   ("reciprocal_", math.reciprocal), ("tanh_", math.tanh)):
        setattr(Tensor, nm, _make_inplace_unary(op))


def _make_inplace_unary(op):
    def f(self, name=None):
        return self._replace_(op(self))

    return f


_patch_tensor()


# ---------------------------------------------------------------------------
# Module-level in-place twins (reference python/paddle/__init__.py exports
# `op_` next to `op`). Each rebinds the tensor to the out-of-place result —
# XLA has no aliasing mutation, so rebind IS the in-place semantic here.
# ---------------------------------------------------------------------------

import sys as _sys

_THIS = _sys.modules[__name__]


def _make_module_inplace(base_fn):
    def f(x, *args, **kwargs):
        out = base_fn(x, *args, **kwargs)
        x._replace_(out if isinstance(out, Tensor) else Tensor(out))
        return x

    f.__name__ = base_fn.__name__ + "_"
    return f


_INPLACE_BASES = [
    "abs", "acos", "atan", "cos", "sin", "sinh", "tan", "tanh", "erf",
    "expm1", "log", "log2", "log10", "sqrt", "square", "floor", "ceil",
    "round", "trunc", "frac", "neg", "lgamma", "digamma", "logit", "pow",
    "divide", "multiply", "floor_divide", "mod", "remainder", "renorm",
    "bitwise_and", "bitwise_or", "bitwise_xor", "bitwise_not",
    "bitwise_left_shift", "bitwise_right_shift", "logical_and",
    "logical_or", "logical_not", "equal", "greater_equal", "greater_than",
    "less_equal", "less_than", "gcd", "lcm", "hypot", "ldexp", "copysign",
    "cumsum", "cumprod", "tril", "triu", "polygamma", "gammaln",
    "gammaincc", "gammainc", "multigammaln", "i0", "masked_fill",
    "masked_scatter", "t", "addmm", "sinc",
]

for _nm in _INPLACE_BASES:
    _base = getattr(_THIS, _nm, None)
    if _base is None:
        continue
    _inm = _nm + "_"
    if not hasattr(_THIS, _inm):
        setattr(_THIS, _inm, _make_module_inplace(_base))
    if not hasattr(Tensor, _inm):
        setattr(Tensor, _inm, getattr(_THIS, _inm))


def _overwrite_random(x, data):
    """Random fills REPLACE the tensor's history: the result does not
    depend on prior computation, so the stale grad node must go (same
    rule as eager collectives' _eager_result)."""
    x._data = data
    x._grad_node = None
    x._out_slot = None
    return x


def bernoulli_(x, p=0.5, name=None):
    """In-place bernoulli fill (reference: paddle.bernoulli_)."""
    from .random import split_key

    key = split_key()
    return _overwrite_random(
        x, (jax.random.uniform(key, x._data.shape) < p).astype(x._data.dtype))


def cauchy_(x, loc=0.0, scale=1.0, name=None):
    from .random import split_key

    u = jax.random.uniform(split_key(), x._data.shape, jnp.float32, 1e-6, 1 - 1e-6)
    return _overwrite_random(x, (loc + scale * jnp.tan(jnp.pi * (u - 0.5))).astype(x._data.dtype))


def geometric_(x, probs=0.5, name=None):
    from .random import split_key

    u = jax.random.uniform(split_key(), x._data.shape, jnp.float32, 1e-6, 1 - 1e-6)
    return _overwrite_random(x, jnp.ceil(jnp.log(u) / np.log1p(-probs)).astype(x._data.dtype))


def log_normal_(x, mean=1.0, std=2.0, name=None):
    from .random import split_key

    n = jax.random.normal(split_key(), x._data.shape, jnp.float32)
    return _overwrite_random(x, jnp.exp(mean + std * n).astype(x._data.dtype))


def exponential_(x, lam=1.0, name=None):
    from .random import split_key

    u = jax.random.uniform(split_key(), x._data.shape, jnp.float32, 1e-6, 1 - 1e-6)
    return _overwrite_random(x, (-jnp.log(u) / lam).astype(x._data.dtype))


def gaussian_(x, mean=0.0, std=1.0, name=None):
    from .random import split_key

    n = jax.random.normal(split_key(), x._data.shape, jnp.float32)
    return _overwrite_random(x, (mean + std * n).astype(x._data.dtype))


normal_ = gaussian_

for _nm in ("bernoulli_", "cauchy_", "geometric_", "log_normal_",
            "exponential_", "gaussian_", "normal_"):
    if not hasattr(Tensor, _nm):
        setattr(Tensor, _nm, getattr(_THIS, _nm))


# final __all__ stragglers
floor_mod_ = getattr(_THIS, "mod_", None) or getattr(_THIS, "remainder_")


def where_(condition, x=None, y=None, name=None):
    out = where(condition, x, y)
    x._replace_(out)
    return x


def batch(reader, batch_size, drop_last=False):
    """Deprecated reader-composition helper (reference paddle.batch)."""
    def wrapper():
        buf = []
        for item in reader():
            buf.append(item)
            if len(buf) == batch_size:
                yield buf
                buf = []
        if buf and not drop_last:
            yield buf

    return wrapper


def disable_signal_handler():
    return None


# paddle.cast_ module-level twin (Tensor.cast_ already exists)
def cast_(x, dtype):
    return x.cast_(dtype)


# Populate OP_REGISTRY with the executable schema table (ops.yaml parity).
# Import last: schemas resolve nothing at import time beyond scipy/numpy.
from . import schemas as _schemas  # noqa: E402,F401
