"""Math ops: elementwise, reductions, matmul.

Parity: python/paddle/tensor/math.py + phi kernels (phi/kernels/*.h elementwise
/ reduce / matmul families). Every op lowers to one-or-few XLA HLO ops so the
compiler can fuse; no hand scheduling.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core import dtype as dtypes
from ..core.tensor import Tensor
from .dispatch import apply_op, ensure_tensor


def _promote(x, y):
    """Tensor/scalar promotion: scalars keep tensor dtype (Paddle semantics);
    tensor-tensor promotes via result_type."""
    xt, yt = isinstance(x, Tensor), isinstance(y, Tensor)
    if xt and yt:
        if x._data.dtype != y._data.dtype:
            rd = jnp.promote_types(x._data.dtype, y._data.dtype)
            x = x.astype(rd) if x._data.dtype != rd else x
            y = y.astype(rd) if y._data.dtype != rd else y
        return x, y
    if xt:
        if isinstance(y, (bool, int, float, complex)) or np.isscalar(y):
            if isinstance(y, float) and not dtypes.is_floating_point(x._data.dtype):
                x = x.astype(dtypes.get_default_dtype())
            return x, ensure_tensor(jnp.asarray(y, x._data.dtype if not isinstance(y, complex) else None))
        return x, ensure_tensor(y)
    if yt:
        if isinstance(x, (bool, int, float, complex)) or np.isscalar(x):
            if isinstance(x, float) and not dtypes.is_floating_point(y._data.dtype):
                y = y.astype(dtypes.get_default_dtype())
            return ensure_tensor(jnp.asarray(x, y._data.dtype if not isinstance(x, complex) else None)), y
        return ensure_tensor(x), y
    return ensure_tensor(x), ensure_tensor(y)


def _binop(opname, jfn):
    def op(x, y, name=None):
        x, y = _promote(x, y)
        return apply_op(opname, jfn, x, y)

    op.__name__ = opname
    return op


def _unop(opname, jfn, float_only=False):
    def op(x, name=None):
        x = ensure_tensor(x)
        if float_only and not dtypes.is_floating_point(x._data.dtype):
            x = x.astype(dtypes.get_default_dtype())
        return apply_op(opname, jfn, x)

    op.__name__ = opname
    return op


# -- elementwise binary ------------------------------------------------------
add = _binop("add", jnp.add)
subtract = _binop("subtract", jnp.subtract)
multiply = _binop("multiply", jnp.multiply)
divide = _binop("divide", lambda a, b: jnp.divide(a, b) if dtypes.is_floating_point(jnp.result_type(a, b)) or jnp.issubdtype(jnp.result_type(a, b), jnp.complexfloating) else jnp.true_divide(a, b).astype(dtypes.get_default_dtype()))
floor_divide = _binop("floor_divide", jnp.floor_divide)
mod = _binop("mod", jnp.mod)
remainder = mod
floor_mod = mod
pow = _binop("pow", jnp.power)
maximum = _binop("maximum", jnp.maximum)
minimum = _binop("minimum", jnp.minimum)
fmax = _binop("fmax", jnp.fmax)
fmin = _binop("fmin", jnp.fmin)
atan2 = _binop("atan2", jnp.arctan2)
hypot = _binop("hypot", jnp.hypot)
logaddexp = _binop("logaddexp", jnp.logaddexp)
nextafter = _binop("nextafter", jnp.nextafter)
copysign = _binop("copysign", jnp.copysign)
heaviside = _binop("heaviside", jnp.heaviside)
gcd = _binop("gcd", jnp.gcd)
lcm = _binop("lcm", jnp.lcm)
ldexp = _binop("ldexp", lambda a, b: jnp.ldexp(a, b.astype(jnp.int32)))

# -- elementwise unary -------------------------------------------------------
abs = _unop("abs", jnp.abs)
neg = _unop("neg", jnp.negative)
negative = neg
sign = _unop("sign", jnp.sign)
sqrt = _unop("sqrt", jnp.sqrt, float_only=True)
rsqrt = _unop("rsqrt", jax.lax.rsqrt, float_only=True)
square = _unop("square", jnp.square)
reciprocal = _unop("reciprocal", jnp.reciprocal, float_only=True)
exp = _unop("exp", jnp.exp, float_only=True)
expm1 = _unop("expm1", jnp.expm1, float_only=True)
log = _unop("log", jnp.log, float_only=True)
log2 = _unop("log2", jnp.log2, float_only=True)
log10 = _unop("log10", jnp.log10, float_only=True)
log1p = _unop("log1p", jnp.log1p, float_only=True)
sin = _unop("sin", jnp.sin, float_only=True)
cos = _unop("cos", jnp.cos, float_only=True)
tan = _unop("tan", jnp.tan, float_only=True)
asin = _unop("asin", jnp.arcsin, float_only=True)
acos = _unop("acos", jnp.arccos, float_only=True)
atan = _unop("atan", jnp.arctan, float_only=True)
sinh = _unop("sinh", jnp.sinh, float_only=True)
cosh = _unop("cosh", jnp.cosh, float_only=True)
tanh = _unop("tanh", jnp.tanh, float_only=True)
asinh = _unop("asinh", jnp.arcsinh, float_only=True)
acosh = _unop("acosh", jnp.arccosh, float_only=True)
atanh = _unop("atanh", jnp.arctanh, float_only=True)
floor = _unop("floor", jnp.floor)
ceil = _unop("ceil", jnp.ceil)
round = _unop("round", jnp.round)
trunc = _unop("trunc", jnp.trunc)
frac = _unop("frac", lambda a: a - jnp.trunc(a))
erf = _unop("erf", jax.scipy.special.erf, float_only=True)
erfinv = _unop("erfinv", jax.scipy.special.erfinv, float_only=True)
sigmoid = _unop("sigmoid", jax.nn.sigmoid, float_only=True)
logit = _unop("logit", lambda a: jnp.log(a / (1 - a)), float_only=True)
digamma = _unop("digamma", jax.scipy.special.digamma, float_only=True)
lgamma = _unop("lgamma", jax.scipy.special.gammaln, float_only=True)
i0 = _unop("i0", lambda a: jax.scipy.special.i0(a), float_only=True)
i1 = _unop("i1", lambda a: jax.scipy.special.i1(a), float_only=True)
angle = _unop("angle", jnp.angle)
conj = _unop("conj", jnp.conj)
real = _unop("real", jnp.real)
imag = _unop("imag", jnp.imag)
deg2rad = _unop("deg2rad", jnp.deg2rad, float_only=True)
rad2deg = _unop("rad2deg", jnp.rad2deg, float_only=True)
exp2 = _unop("exp2", jnp.exp2, float_only=True)


def clip(x, min=None, max=None, name=None) -> Tensor:
    x = ensure_tensor(x)
    lo = min._data if isinstance(min, Tensor) else min
    hi = max._data if isinstance(max, Tensor) else max
    return apply_op("clip", lambda a: jnp.clip(a, lo, hi), x)


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None) -> Tensor:
    x = ensure_tensor(x)
    if act is not None:
        raise NotImplementedError(
            "scale(act=...) is the legacy fused-activation arg; apply the "
            "activation explicitly (XLA fuses it anyway)")
    s = scale._data if isinstance(scale, Tensor) else scale

    def _f(a):
        out = a * jnp.asarray(s, a.dtype) + jnp.asarray(bias, a.dtype) if bias_after_scale else (a + jnp.asarray(bias, a.dtype)) * jnp.asarray(s, a.dtype)
        return out

    return apply_op("scale", _f, x)


def lerp(x, y, weight, name=None) -> Tensor:
    x, y = _promote(x, y)
    w = weight._data if isinstance(weight, Tensor) else weight
    return apply_op("lerp", lambda a, b: a + w * (b - a), x, y)


def stanh(x, scale_a=0.67, scale_b=1.7159, name=None) -> Tensor:
    return apply_op("stanh", lambda a: scale_b * jnp.tanh(scale_a * a), ensure_tensor(x))


def multiplex(inputs, index, name=None) -> Tensor:
    ts = [ensure_tensor(t) for t in inputs]
    idx = ensure_tensor(index)

    def _f(ix, *xs):
        stacked = jnp.stack(xs, 0)
        return jnp.take_along_axis(stacked, ix.reshape(1, -1, *([1] * (xs[0].ndim - 1))), axis=0)[0]

    return apply_op("multiplex", _f, idx, *ts)


# -- reductions --------------------------------------------------------------
def _axis(axis):
    if axis is None:
        return None
    if isinstance(axis, Tensor):
        a = np.asarray(axis._data)
        return tuple(int(v) for v in np.atleast_1d(a))
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return int(axis)


def _reduce_body(a, jfn, ax, keepdim):
    return jfn(a, axis=ax, keepdims=keepdim)


def _reduce(opname, jfn, int_promote=False):
    from .dispatch import stable_closure

    def op(x, axis=None, keepdim=False, name=None):
        x = ensure_tensor(x)
        ax = _axis(axis)
        ax = tuple(ax) if isinstance(ax, list) else ax
        return apply_op(opname, stable_closure(_reduce_body, jfn, ax, keepdim), x)

    op.__name__ = opname
    return op


def _sum_body(a, ax, keepdim, d):
    if a.dtype == jnp.bool_:
        a = a.astype(jnp.int64)
    return jnp.sum(a, axis=ax, keepdims=keepdim, dtype=d)


def sum(x, axis=None, dtype=None, keepdim=False, name=None) -> Tensor:
    from .dispatch import stable_closure

    x = ensure_tensor(x)
    ax = _axis(axis)
    ax = tuple(ax) if isinstance(ax, list) else ax
    d = dtypes.convert_dtype(dtype)
    d = np.dtype(d) if d is not None else None
    return apply_op("sum", stable_closure(_sum_body, ax, keepdim, d), x)


mean = _reduce("mean", jnp.mean)
prod = _reduce("prod", jnp.prod)
max = _reduce("max", jnp.max)
min = _reduce("min", jnp.min)
amax = _reduce("amax", jnp.max)
amin = _reduce("amin", jnp.min)
all = _reduce("all", jnp.all)
any = _reduce("any", jnp.any)


def logsumexp(x, axis=None, keepdim=False, name=None) -> Tensor:
    x = ensure_tensor(x)
    ax = _axis(axis)
    return apply_op("logsumexp", lambda a: jax.scipy.special.logsumexp(a, axis=ax, keepdims=keepdim), x)


def std(x, axis=None, unbiased=True, keepdim=False, name=None) -> Tensor:
    x = ensure_tensor(x)
    ax = _axis(axis)
    return apply_op("std", lambda a: jnp.std(a, axis=ax, ddof=1 if unbiased else 0, keepdims=keepdim), x)


def var(x, axis=None, unbiased=True, keepdim=False, name=None) -> Tensor:
    x = ensure_tensor(x)
    ax = _axis(axis)
    return apply_op("var", lambda a: jnp.var(a, axis=ax, ddof=1 if unbiased else 0, keepdims=keepdim), x)


def median(x, axis=None, keepdim=False, mode="avg", name=None):
    """mode='avg': mean of the two middles (even length) -> Tensor.
    mode='min': the lower middle; with an axis this returns
    (values, int64 indices) like the reference median signature,
    axis=None returns the value only."""
    x = ensure_tensor(x)
    ax = _axis(axis)
    if mode == "min":
        # reference contract: mode='min' with an axis returns
        # (values, int64 indices of the lower middle); axis=None returns
        # the value only (paddle.median signature)
        if ax is None:
            def _f0(a):
                srt = jnp.sort(a.reshape(-1))
                out = srt[(srt.shape[0] - 1) // 2]
                return out.reshape((1,) * a.ndim) if keepdim else out

            return apply_op("median", _f0, x)

        def _f(a):
            n = a.shape[ax]
            order = jnp.argsort(a, axis=ax)
            mid = (n - 1) // 2
            idx = jnp.take(order, mid, axis=ax)
            val = jnp.take_along_axis(
                a, jnp.expand_dims(idx, ax), axis=ax).squeeze(ax)
            if keepdim:
                val = jnp.expand_dims(val, ax)
                idx = jnp.expand_dims(idx, ax)
            return val, idx.astype(jnp.int64)

        return apply_op("median", _f, x, nouts=2)
    return apply_op("median", lambda a: jnp.median(a, axis=ax, keepdims=keepdim), x)


def nanmean(x, axis=None, keepdim=False, name=None) -> Tensor:
    x = ensure_tensor(x)
    ax = _axis(axis)
    return apply_op("nanmean", lambda a: jnp.nanmean(a, axis=ax, keepdims=keepdim), x)


def nansum(x, axis=None, dtype=None, keepdim=False, name=None) -> Tensor:
    x = ensure_tensor(x)
    ax = _axis(axis)
    d = dtypes.convert_dtype(dtype)
    return apply_op("nansum", lambda a: jnp.nansum(a, axis=ax, keepdims=keepdim, dtype=d), x)


def cumsum(x, axis=None, dtype=None, name=None) -> Tensor:
    x = ensure_tensor(x)
    d = dtypes.convert_dtype(dtype)

    def _f(a):
        if axis is None:
            return jnp.cumsum(a.reshape(-1), dtype=d)
        return jnp.cumsum(a, axis=int(axis), dtype=d)

    return apply_op("cumsum", _f, x)


def cumprod(x, dim=None, dtype=None, name=None) -> Tensor:
    x = ensure_tensor(x)
    d = dtypes.convert_dtype(dtype)
    return apply_op("cumprod", lambda a: jnp.cumprod(a, axis=dim, dtype=d), x)


def cummax(x, axis=None, dtype="int64", name=None):
    x = ensure_tensor(x)
    ax = 0 if axis is None else int(axis)
    xd = x._data.reshape(-1) if axis is None else x._data

    def _f(a):
        vals = jax.lax.associative_scan(jnp.maximum, a, axis=ax)
        return vals

    vals = apply_op("cummax", _f, Tensor(xd, stop_gradient=x.stop_gradient) if axis is None else x)
    inds = _running_argext(xd, ax, jnp.greater_equal)
    return vals, Tensor(inds.astype(dtypes.convert_dtype(dtype)))


def cummin(x, axis=None, dtype="int64", name=None):
    x = ensure_tensor(x)
    ax = 0 if axis is None else int(axis)
    xd = x._data.reshape(-1) if axis is None else x._data
    vals = apply_op("cummin", lambda a: jax.lax.associative_scan(jnp.minimum, a, axis=ax),
                    Tensor(xd, stop_gradient=x.stop_gradient) if axis is None else x)
    inds = _running_argext(xd, ax, jnp.less_equal)
    return vals, Tensor(inds.astype(dtypes.convert_dtype(dtype)))


def _running_argext(a, ax, cmp):
    n = a.shape[ax]
    ar = jnp.moveaxis(a, ax, -1)
    best, besti = ar[..., 0], jnp.zeros(ar.shape[:-1], jnp.int64)
    outs = [besti]
    for i in range(1, n):
        x = ar[..., i]
        take = cmp(x, best)
        best = jnp.where(take, x, best)
        besti = jnp.where(take, jnp.asarray(i, jnp.int64), besti)
        outs.append(besti)
    return jnp.moveaxis(jnp.stack(outs, -1), -1, ax)


def count_nonzero(x, axis=None, keepdim=False, name=None) -> Tensor:
    x = ensure_tensor(x)
    ax = _axis(axis)
    return Tensor(jnp.count_nonzero(x._data, axis=ax, keepdims=keepdim).astype(jnp.int64))


# -- matmul family -----------------------------------------------------------
# Stable matmul bodies per transpose combo: module-level identity lets the
# dispatch layer cache compiled fwd/pullback programs (hot path).
def _mm_nn(a, b):
    return jnp.matmul(a, b)


def _mm_tn(a, b):
    return jnp.matmul(jnp.swapaxes(a, -1, -2) if a.ndim >= 2 else a, b)


def _mm_nt(a, b):
    return jnp.matmul(a, jnp.swapaxes(b, -1, -2) if b.ndim >= 2 else b)


def _mm_tt(a, b):
    return jnp.matmul(jnp.swapaxes(a, -1, -2) if a.ndim >= 2 else a,
                      jnp.swapaxes(b, -1, -2) if b.ndim >= 2 else b)


_MATMUL_FNS = {(False, False): _mm_nn, (True, False): _mm_tn,
               (False, True): _mm_nt, (True, True): _mm_tt}


def matmul(x, y, transpose_x=False, transpose_y=False, name=None) -> Tensor:
    x, y = ensure_tensor(x), ensure_tensor(y)
    return apply_op("matmul", _MATMUL_FNS[bool(transpose_x), bool(transpose_y)], x, y)


def mm(x, y, name=None) -> Tensor:
    return matmul(x, y)


def bmm(x, y, name=None) -> Tensor:
    return matmul(x, y)


def dot(x, y, name=None) -> Tensor:
    x, y = ensure_tensor(x), ensure_tensor(y)
    return apply_op("dot", lambda a, b: jnp.sum(a * b, axis=-1), x, y)


def inner(x, y, name=None) -> Tensor:
    x, y = ensure_tensor(x), ensure_tensor(y)
    return apply_op("inner", jnp.inner, x, y)


def outer(x, y, name=None) -> Tensor:
    x, y = ensure_tensor(x), ensure_tensor(y)
    return apply_op("outer", lambda a, b: jnp.outer(a.reshape(-1), b.reshape(-1)), x, y)


def mv(x, vec, name=None) -> Tensor:
    return matmul(x, vec)


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None) -> Tensor:
    input, x, y = ensure_tensor(input), ensure_tensor(x), ensure_tensor(y)
    return apply_op("addmm", lambda i, a, b: beta * i + alpha * jnp.matmul(a, b), input, x, y)


def einsum(equation, *operands) -> Tensor:
    ts = [ensure_tensor(o) for o in operands]
    return apply_op("einsum", lambda *xs: jnp.einsum(equation, *xs), *ts)


def kron(x, y, name=None) -> Tensor:
    x, y = ensure_tensor(x), ensure_tensor(y)
    return apply_op("kron", jnp.kron, x, y)


def cross(x, y, axis=9, name=None) -> Tensor:
    x, y = ensure_tensor(x), ensure_tensor(y)
    ax = axis if axis != 9 else None
    if ax is None:
        # find first dim of size 3 (Paddle semantics)
        for i, s in enumerate(x._data.shape):
            if s == 3:
                ax = i
                break
    return apply_op("cross", lambda a, b: jnp.cross(a, b, axis=ax), x, y)


def trace(x, offset=0, axis1=0, axis2=1, name=None) -> Tensor:
    x = ensure_tensor(x)
    return apply_op("trace", lambda a: jnp.trace(a, offset=offset, axis1=axis1, axis2=axis2), x)


def diagonal(x, offset=0, axis1=0, axis2=1, name=None) -> Tensor:
    x = ensure_tensor(x)
    return apply_op("diagonal", lambda a: jnp.diagonal(a, offset=offset, axis1=axis1, axis2=axis2), x)


def diff(x, n=1, axis=-1, prepend=None, append=None, name=None) -> Tensor:
    x = ensure_tensor(x)
    p = prepend._data if isinstance(prepend, Tensor) else prepend
    ap = append._data if isinstance(append, Tensor) else append
    return apply_op("diff", lambda a: jnp.diff(a, n=n, axis=axis, prepend=p, append=ap), x)


def inverse(x, name=None) -> Tensor:
    x = ensure_tensor(x)
    return apply_op("inverse", jnp.linalg.inv, x)


def rot90(x, k=1, axes=(0, 1), name=None) -> Tensor:
    x = ensure_tensor(x)
    return apply_op("rot90", lambda a: jnp.rot90(a, k=k, axes=tuple(axes)), x)


def broadcast_shape(x_shape, y_shape):
    return list(jnp.broadcast_shapes(tuple(x_shape), tuple(y_shape)))


def increment(x, value=1.0, name=None) -> Tensor:
    x._data = x._data + jnp.asarray(value, x._data.dtype)
    return x
