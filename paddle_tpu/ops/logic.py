"""Comparison / logical / bitwise ops.

Parity: python/paddle/tensor/logic.py over XLA.
"""

from __future__ import annotations

import jax.numpy as jnp

from ..core.tensor import Tensor
from .dispatch import apply_op, ensure_tensor
from .math import _promote


def _cmp(opname, jfn):
    def op(x, y, name=None):
        x, y = _promote(x, y)
        return apply_op(opname, jfn, x, y)

    op.__name__ = opname
    return op


equal = _cmp("equal", jnp.equal)
not_equal = _cmp("not_equal", jnp.not_equal)
greater_than = _cmp("greater_than", jnp.greater)
greater_equal = _cmp("greater_equal", jnp.greater_equal)
less_than = _cmp("less_than", jnp.less)
less_equal = _cmp("less_equal", jnp.less_equal)

logical_and = _cmp("logical_and", jnp.logical_and)
logical_or = _cmp("logical_or", jnp.logical_or)
logical_xor = _cmp("logical_xor", jnp.logical_xor)

bitwise_and = _cmp("bitwise_and", jnp.bitwise_and)
bitwise_or = _cmp("bitwise_or", jnp.bitwise_or)
bitwise_xor = _cmp("bitwise_xor", jnp.bitwise_xor)

bitwise_left_shift = _cmp("bitwise_left_shift", jnp.left_shift)
bitwise_right_shift = _cmp("bitwise_right_shift", jnp.right_shift)


def logical_not(x, out=None, name=None) -> Tensor:
    return apply_op("logical_not", jnp.logical_not, ensure_tensor(x))


def bitwise_not(x, out=None, name=None) -> Tensor:
    return apply_op("bitwise_not", jnp.bitwise_not, ensure_tensor(x))


def equal_all(x, y, name=None) -> Tensor:
    x, y = ensure_tensor(x), ensure_tensor(y)
    if x._data.shape != y._data.shape:
        return Tensor(jnp.asarray(False))
    return Tensor(jnp.array_equal(x._data, y._data))


def allclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None) -> Tensor:
    x, y = ensure_tensor(x), ensure_tensor(y)
    return Tensor(jnp.allclose(x._data, y._data, rtol=rtol, atol=atol, equal_nan=equal_nan))


def isclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None) -> Tensor:
    x, y = ensure_tensor(x), ensure_tensor(y)
    return apply_op("isclose", lambda a, b: jnp.isclose(a, b, rtol=rtol, atol=atol, equal_nan=equal_nan), x, y)


def isnan(x, name=None) -> Tensor:
    return apply_op("isnan", jnp.isnan, ensure_tensor(x))


def isinf(x, name=None) -> Tensor:
    return apply_op("isinf", jnp.isinf, ensure_tensor(x))


def isfinite(x, name=None) -> Tensor:
    return apply_op("isfinite", jnp.isfinite, ensure_tensor(x))


def is_tensor(x) -> bool:
    return isinstance(x, Tensor)


def is_empty(x, name=None) -> Tensor:
    return Tensor(jnp.asarray(ensure_tensor(x).size == 0))


def in_dynamic_mode() -> bool:
    from ..jit.api import in_to_static_mode

    return not in_to_static_mode()
