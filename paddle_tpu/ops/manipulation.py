"""Shape/layout manipulation + indexing ops.

Parity: python/paddle/tensor/manipulation.py (reshape, transpose, concat,
split, stack, squeeze, gather, scatter, …) over XLA. Static shapes
throughout — shape arguments are host ints so everything stays
jit-compilable (XLA semantics: no dynamic shapes).
"""

from __future__ import annotations

import builtins

import jax
import jax.numpy as jnp
import numpy as np

from ..core import dtype as dtypes
from ..core.tensor import Tensor
from .dispatch import apply_op, ensure_tensor


def _ints(seq):
    if isinstance(seq, Tensor):
        return tuple(int(v) for v in np.asarray(seq._data))
    if isinstance(seq, (int, np.integer)):
        return (int(seq),)
    return tuple(int(s._data.item()) if isinstance(s, Tensor) else int(s) for s in seq)


def cast(x, dtype) -> Tensor:
    return ensure_tensor(x).astype(dtype)


def _reshape_body(a, shp):
    return jnp.reshape(a, shp)


def reshape(x, shape, name=None) -> Tensor:
    from .dispatch import stable_closure

    x = ensure_tensor(x)
    shp = tuple(_ints(shape))
    return apply_op("reshape", stable_closure(_reshape_body, shp), x)


def reshape_(x, shape, name=None) -> Tensor:
    return x._replace_(reshape(x, shape))


def flatten(x, start_axis=0, stop_axis=-1, name=None) -> Tensor:
    x = ensure_tensor(x)
    nd = x.ndim
    s = start_axis % nd if nd else 0
    e = stop_axis % nd if nd else 0
    shp = list(x._data.shape)
    new = shp[:s] + [int(np.prod(shp[s : e + 1])) if shp else 1] + shp[e + 1 :]
    return apply_op("flatten", lambda a: jnp.reshape(a, new), x)


def _transpose_body(a, p):
    return jnp.transpose(a, p)


def transpose(x, perm, name=None) -> Tensor:
    from .dispatch import stable_closure

    x = ensure_tensor(x)
    p = tuple(_ints(perm))
    return apply_op("transpose", stable_closure(_transpose_body, p), x)


def t(x, name=None) -> Tensor:
    x = ensure_tensor(x)
    if x.ndim <= 1:
        return apply_op("t", lambda a: a, x)
    return apply_op("t", lambda a: jnp.swapaxes(a, -1, -2), x)


def moveaxis(x, source, destination, name=None) -> Tensor:
    x = ensure_tensor(x)
    return apply_op("moveaxis", lambda a: jnp.moveaxis(a, source, destination), x)


def swapaxes(x, axis0, axis1, name=None) -> Tensor:
    x = ensure_tensor(x)
    return apply_op("swapaxes", lambda a: jnp.swapaxes(a, axis0, axis1), x)


transpose_ = None  # assigned below if needed


def concat(x, axis=0, name=None) -> Tensor:
    ts = [ensure_tensor(t) for t in x]
    if isinstance(axis, Tensor):
        axis = int(axis._data.item())
    return apply_op("concat", lambda *xs: jnp.concatenate(xs, axis=axis), *ts)


def stack(x, axis=0, name=None) -> Tensor:
    ts = [ensure_tensor(t) for t in x]
    return apply_op("stack", lambda *xs: jnp.stack(xs, axis=axis), *ts)


def unstack(x, axis=0, num=None):
    x = ensure_tensor(x)
    n = num or x._data.shape[axis]
    outs = apply_op("unstack", lambda a: tuple(jnp.moveaxis(a, axis, 0)[i] for i in range(n)), x)
    return list(outs) if isinstance(outs, (list, tuple)) else [outs]


def split(x, num_or_sections, axis=0, name=None):
    x = ensure_tensor(x)
    if isinstance(axis, Tensor):
        axis = int(axis._data.item())
    dim = x._data.shape[axis]
    if isinstance(num_or_sections, int):
        sections = [dim // num_or_sections] * num_or_sections
    else:
        sections = [int(s._data.item()) if isinstance(s, Tensor) else int(s) for s in num_or_sections]
        neg = [i for i, s in enumerate(sections) if s < 0]
        if neg:
            known = builtins.sum(s for s in sections if s >= 0)
            sections[neg[0]] = dim - known
    offsets = np.cumsum([0] + sections)

    def _f(a):
        return tuple(jax.lax.slice_in_dim(a, int(offsets[i]), int(offsets[i + 1]), axis=axis) for i in range(len(sections)))

    outs = apply_op("split", _f, x)
    return list(outs) if isinstance(outs, (list, tuple)) else [outs]


def chunk(x, chunks, axis=0, name=None):
    return split(x, chunks, axis)


def tensor_split(x, num_or_indices, axis=0, name=None):
    x = ensure_tensor(x)
    outs = apply_op("tensor_split", lambda a: tuple(jnp.array_split(a, num_or_indices, axis=axis)), x)
    return list(outs) if isinstance(outs, (list, tuple)) else [outs]


def squeeze(x, axis=None, name=None) -> Tensor:
    x = ensure_tensor(x)
    if axis is None:
        ax = None
    else:
        ax = _ints(axis)
        ax = tuple(a % builtins.max(x.ndim, 1) for a in ax if x._data.shape[a] == 1)

    return apply_op("squeeze", lambda a: jnp.squeeze(a, axis=ax), x)


def squeeze_(x, axis=None, name=None) -> Tensor:
    return x._replace_(squeeze(x, axis))


def unsqueeze(x, axis, name=None) -> Tensor:
    x = ensure_tensor(x)
    ax = _ints(axis)
    return apply_op("unsqueeze", lambda a: jnp.expand_dims(a, ax), x)


def unsqueeze_(x, axis, name=None) -> Tensor:
    return x._replace_(unsqueeze(x, axis))


def expand(x, shape, name=None) -> Tensor:
    x = ensure_tensor(x)
    shp = list(_ints(shape))
    xshape = list(x._data.shape)
    # Paddle: -1 means keep dim
    pad = len(shp) - len(xshape)
    for i, s in enumerate(shp):
        if s == -1 and i >= pad:
            shp[i] = xshape[i - pad]
    return apply_op("expand", lambda a: jnp.broadcast_to(a, tuple(shp)), x)


def broadcast_to(x, shape, name=None) -> Tensor:
    return expand(x, shape)


def expand_as(x, y, name=None) -> Tensor:
    y = ensure_tensor(y)
    return expand(x, list(y._data.shape))


def broadcast_tensors(inputs, name=None):
    ts = [ensure_tensor(t) for t in inputs]
    outs = apply_op("broadcast_tensors", lambda *xs: tuple(jnp.broadcast_arrays(*xs)), *ts)
    return list(outs) if isinstance(outs, (list, tuple)) else [outs]


def tile(x, repeat_times, name=None) -> Tensor:
    x = ensure_tensor(x)
    reps = _ints(repeat_times)
    return apply_op("tile", lambda a: jnp.tile(a, reps), x)


def flip(x, axis, name=None) -> Tensor:
    x = ensure_tensor(x)
    ax = _ints(axis)
    return apply_op("flip", lambda a: jnp.flip(a, axis=ax), x)


def roll(x, shifts, axis=None, name=None) -> Tensor:
    x = ensure_tensor(x)
    sh = _ints(shifts) if not isinstance(shifts, int) else shifts
    ax = _ints(axis) if axis is not None and not isinstance(axis, int) else axis

    def _f(a):
        if ax is None:
            return jnp.roll(a.reshape(-1), sh).reshape(a.shape)
        return jnp.roll(a, sh, axis=ax)

    return apply_op("roll", _f, x)


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None) -> Tensor:
    x = ensure_tensor(x)
    p = _ints(pad)

    def _f(a):
        nd = a.ndim
        if len(p) == 2 * nd:
            width = [(p[2 * i], p[2 * i + 1]) for i in range(nd)]
        else:
            # Paddle NCHW-style: pad applies to last len(p)//2 spatial dims,
            # ordered (left, right, top, bottom, ...) from last dim backward.
            width = [(0, 0)] * nd
            nspatial = len(p) // 2
            if data_format.endswith("C") and nd >= 3:  # NHWC / NDHWC
                dims = list(range(1, 1 + nspatial))
            else:
                dims = list(range(nd - nspatial, nd))
            for i, d in enumerate(dims):
                width[d] = (p[2 * i], p[2 * i + 1])
        jmode = {"constant": "constant", "reflect": "reflect", "replicate": "edge", "circular": "wrap"}[mode]
        if jmode == "constant":
            return jnp.pad(a, width, mode="constant", constant_values=jnp.asarray(value, a.dtype))
        return jnp.pad(a, width, mode=jmode)

    return apply_op("pad", _f, x)


def gather(x, index, axis=0, name=None) -> Tensor:
    x, index = ensure_tensor(x), ensure_tensor(index)
    if isinstance(axis, Tensor):
        axis = int(axis._data.item())
    return apply_op("gather", lambda a, i: jnp.take(a, i.reshape(-1), axis=axis), x, index)


def gather_nd(x, index, name=None) -> Tensor:
    x, index = ensure_tensor(x), ensure_tensor(index)

    def _f(a, idx):
        k = idx.shape[-1]
        out = a[tuple(jnp.moveaxis(idx, -1, 0))]
        return out

    return apply_op("gather_nd", _f, x, index)


def scatter(x, index, updates, overwrite=True, name=None) -> Tensor:
    x, index, updates = ensure_tensor(x), ensure_tensor(index), ensure_tensor(updates)

    def _f(a, i, u):
        i = i.reshape(-1)
        if overwrite:
            return a.at[i].set(u)
        # Paddle overwrite=False: zero the rows then scatter-add
        zeroed = a.at[i].set(jnp.zeros_like(u))
        return zeroed.at[i].add(u)

    return apply_op("scatter", _f, x, index, updates)


def scatter_(x, index, updates, overwrite=True, name=None) -> Tensor:
    return x._replace_(scatter(x, index, updates, overwrite))


def scatter_nd_add(x, index, updates, name=None) -> Tensor:
    x, index, updates = ensure_tensor(x), ensure_tensor(index), ensure_tensor(updates)

    def _f(a, i, u):
        return a.at[tuple(jnp.moveaxis(i, -1, 0))].add(u)

    return apply_op("scatter_nd_add", _f, x, index, updates)


def scatter_nd(index, updates, shape, name=None) -> Tensor:
    index, updates = ensure_tensor(index), ensure_tensor(updates)
    shp = _ints(shape)

    def _f(i, u):
        return jnp.zeros(shp, u.dtype).at[tuple(jnp.moveaxis(i, -1, 0))].add(u)

    return apply_op("scatter_nd", _f, index, updates)


def index_select(x, index, axis=0, name=None) -> Tensor:
    x, index = ensure_tensor(x), ensure_tensor(index)
    return apply_op("index_select", lambda a, i: jnp.take(a, i.reshape(-1), axis=axis), x, index)


def index_sample(x, index) -> Tensor:
    x, index = ensure_tensor(x), ensure_tensor(index)
    return apply_op("index_sample", lambda a, i: jnp.take_along_axis(a, i, axis=1), x, index)


def index_add(x, index, axis, value, name=None) -> Tensor:
    x, index, value = ensure_tensor(x), ensure_tensor(index), ensure_tensor(value)

    def _f(a, i, v):
        am = jnp.moveaxis(a, axis, 0)
        vm = jnp.moveaxis(v, axis, 0)
        out = am.at[i.reshape(-1)].add(vm)
        return jnp.moveaxis(out, 0, axis)

    return apply_op("index_add", _f, x, index, value)


def index_put(x, indices, value, accumulate=False, name=None) -> Tensor:
    x = ensure_tensor(x)
    value = ensure_tensor(value)
    idx_ts = [ensure_tensor(i) for i in indices]

    def _f(a, v, *ix):
        key = tuple(ix)
        return a.at[key].add(v) if accumulate else a.at[key].set(v)

    return apply_op("index_put", _f, x, value, *idx_ts)


def _broadcast_indices(i, a_shape, axis):
    """paddle broadcast=True: indices broadcast against arr on every dim
    except ``axis`` (phi take_along_axis broadcast semantics)."""
    target = list(a_shape)
    target[axis] = i.shape[axis]
    return jnp.broadcast_to(i, tuple(target))


def take_along_axis(arr, indices, axis, broadcast=True) -> Tensor:
    arr, indices = ensure_tensor(arr), ensure_tensor(indices)

    def _f(a, i):
        if broadcast:
            i = _broadcast_indices(i, a.shape, axis)
        return jnp.take_along_axis(a, i, axis=axis)

    return apply_op("take_along_axis", _f, arr, indices)


def put_along_axis(arr, indices, values, axis, reduce="assign", include_self=True, broadcast=True) -> Tensor:
    arr, indices = ensure_tensor(arr), ensure_tensor(indices)
    values = ensure_tensor(values)

    def _f(a, i, v):
        if broadcast:
            i = _broadcast_indices(i, a.shape, axis)
        v = jnp.broadcast_to(v, i.shape) if v.ndim < i.ndim or v.shape != i.shape else v
        if reduce == "assign":
            return jnp.put_along_axis(a, i, v, axis=axis, inplace=False)
        idx = jnp.meshgrid(*[jnp.arange(s) for s in i.shape], indexing="ij")
        idx[axis] = i
        if not include_self:
            # reference include_self=False: touched positions start from
            # the reduction identity instead of a's original value
            ident = {"add": 0, "sum": 0, "mul": 1, "multiply": 1,
                     "amax": None, "amin": None}[reduce]
            if ident is None:
                ident = (jnp.finfo(a.dtype).min if reduce == "amax"
                         else jnp.finfo(a.dtype).max) \
                    if jnp.issubdtype(a.dtype, jnp.floating) else (
                        jnp.iinfo(a.dtype).min if reduce == "amax"
                        else jnp.iinfo(a.dtype).max)
            a = a.at[tuple(idx)].set(jnp.asarray(ident, a.dtype))
        if reduce in ("add", "sum"):
            return a.at[tuple(idx)].add(v)
        if reduce in ("mul", "multiply"):
            return a.at[tuple(idx)].multiply(v)
        if reduce == "amax":
            return a.at[tuple(idx)].max(v)
        if reduce == "amin":
            return a.at[tuple(idx)].min(v)
        raise ValueError(f"unknown reduce {reduce}")

    return apply_op("put_along_axis", _f, arr, indices, values)


def masked_select(x, mask, name=None) -> Tensor:
    x, mask = ensure_tensor(x), ensure_tensor(mask)
    # Dynamic output shape: host-side op (eager only), like reference CPU path.
    data = np.asarray(x._data)[np.asarray(mask._data)]
    return Tensor(jnp.asarray(data))


def masked_fill(x, mask, value, name=None) -> Tensor:
    x, mask = ensure_tensor(x), ensure_tensor(mask)
    v = value._data if isinstance(value, Tensor) else value
    return apply_op("masked_fill", lambda a, m: jnp.where(m, jnp.asarray(v, a.dtype), a), x, mask)


def where(condition, x=None, y=None, name=None):
    condition = ensure_tensor(condition)
    if x is None and y is None:
        return nonzero(condition, as_tuple=True)
    x, y = ensure_tensor(x), ensure_tensor(y)
    return apply_op("where", lambda c, a, b: jnp.where(c, a, b), condition, x, y)


# Index dtype note (deliberate): jax runs with x64 disabled, so index
# outputs are int32 — correct for any dimension < 2^31 (XLA itself caps
# per-dimension sizes near this). Requesting int64 would only emit a
# truncation warning and silently produce int32 anyway; int32 states the
# actual contract. Paddle-compat callers that need int64 can .astype
# after enabling jax_enable_x64.
_INDEX_DTYPE = jnp.int32


def nonzero(x, as_tuple=False):
    x = ensure_tensor(x)
    nz = np.nonzero(np.asarray(x._data))
    if as_tuple:
        return tuple(Tensor(jnp.asarray(v[:, None], _INDEX_DTYPE)) for v in nz)
    return Tensor(jnp.asarray(np.stack(nz, axis=1), _INDEX_DTYPE))


def unique(x, return_index=False, return_inverse=False, return_counts=False, axis=None, dtype="int64", name=None):
    x = ensure_tensor(x)
    res = np.unique(np.asarray(x._data), return_index=return_index, return_inverse=return_inverse,
                    return_counts=return_counts, axis=axis)
    if not isinstance(res, tuple):
        return Tensor(jnp.asarray(res))
    # dtype applies to the INDEX outputs (reference unique signature)
    idt = np.dtype(dtype) if dtype != "int64" else np.dtype(_INDEX_DTYPE)
    outs = [Tensor(jnp.asarray(res[0]))] + [
        Tensor(jnp.asarray(r.astype(idt))) for r in res[1:]]
    return tuple(outs)


def unique_consecutive(x, return_inverse=False, return_counts=False, axis=None, dtype="int64", name=None):
    x = ensure_tensor(x)
    arr = np.asarray(x._data)
    if axis is None:
        arr = arr.reshape(-1)
        keep = np.concatenate([[True], arr[1:] != arr[:-1]])
        out = arr[keep]
        outs = [Tensor(jnp.asarray(out))]
        idt = np.dtype(dtype) if dtype != "int64" else np.dtype(_INDEX_DTYPE)
        if return_inverse:
            inv = np.cumsum(keep) - 1
            outs.append(Tensor(jnp.asarray(inv.astype(idt))))
        if return_counts:
            idx = np.flatnonzero(keep)
            counts = np.diff(np.concatenate([idx, [len(arr)]]))
            outs.append(Tensor(jnp.asarray(counts.astype(idt))))
        return outs[0] if len(outs) == 1 else tuple(outs)
    raise NotImplementedError("unique_consecutive with axis")


def repeat_interleave(x, repeats, axis=None, name=None) -> Tensor:
    x = ensure_tensor(x)
    if isinstance(repeats, Tensor):
        r = np.asarray(repeats._data)
        data = np.repeat(np.asarray(x._data), r, axis=axis)
        return Tensor(jnp.asarray(data))
    return apply_op("repeat_interleave", lambda a: jnp.repeat(a, repeats, axis=axis), x)


def as_real(x, name=None) -> Tensor:
    x = ensure_tensor(x)
    return apply_op("as_real", lambda a: jnp.stack([jnp.real(a), jnp.imag(a)], axis=-1), x)


def as_complex(x, name=None) -> Tensor:
    x = ensure_tensor(x)
    return apply_op("as_complex", lambda a: jax.lax.complex(a[..., 0], a[..., 1]), x)


def view(x, shape_or_dtype, name=None) -> Tensor:
    if isinstance(shape_or_dtype, (list, tuple)):
        return reshape(x, shape_or_dtype)
    return ensure_tensor(x).astype(shape_or_dtype)


def view_as(x, other, name=None) -> Tensor:
    return reshape(x, list(ensure_tensor(other)._data.shape))


def slice(input, axes, starts, ends) -> Tensor:
    input = ensure_tensor(input)
    axes = _ints(axes)
    starts = _ints(starts)
    ends = _ints(ends)

    def _f(a):
        out = a
        for ax, s, e in zip(axes, starts, ends):
            dim = out.shape[ax]
            s2 = builtins.max(s + dim, 0) if s < 0 else builtins.min(s, dim)
            e2 = builtins.max(e + dim, 0) if e < 0 else builtins.min(e, dim)
            out = jax.lax.slice_in_dim(out, s2, e2, axis=ax)
        return out

    return apply_op("slice", _f, input)


def strided_slice(x, axes, starts, ends, strides, name=None) -> Tensor:
    x = ensure_tensor(x)
    axes = _ints(axes)
    starts, ends, strides = _ints(starts), _ints(ends), _ints(strides)

    def _f(a):
        idx = [builtins.slice(None)] * a.ndim
        for ax, s, e, st in zip(axes, starts, ends, strides):
            idx[ax] = builtins.slice(s, e, st)
        return a[tuple(idx)]

    return apply_op("strided_slice", _f, x)


def crop(x, shape=None, offsets=None, name=None) -> Tensor:
    x = ensure_tensor(x)
    shp = _ints(shape)
    offs = _ints(offsets) if offsets is not None else tuple(0 for _ in shp)

    def _f(a):
        return jax.lax.dynamic_slice(a, offs, shp)

    return apply_op("crop", _f, x)


def fill_diagonal_(x, value, offset=0, wrap=False, name=None) -> Tensor:
    x = ensure_tensor(x)
    rows, cols = x._data.shape[0], x._data.shape[1]
    if wrap and rows > cols:
        # np.fill_diagonal(wrap=True): the diagonal restarts after each
        # (cols+1)-row block of a tall matrix; offset shifts the start
        # (positive: right/col offset, negative: down/row offset)
        start = offset if offset >= 0 else -offset * cols
        flat = x._data.reshape(-1)
        pos = jnp.arange(start, rows * cols, cols + 1)
        x._data = flat.at[pos].set(
            jnp.asarray(value, x._data.dtype)).reshape(rows, cols)
        return x
    n = builtins.min(rows, cols)
    idx = jnp.arange(n - builtins.max(offset, 0))
    x._data = x._data.at[idx, idx + offset].set(jnp.asarray(value, x._data.dtype))
    return x


def flatten_(x, start_axis=0, stop_axis=-1, name=None) -> Tensor:
    return x._replace_(flatten(x, start_axis, stop_axis))


def atleast_1d(*inputs, name=None):
    outs = [apply_op("atleast_1d", jnp.atleast_1d, ensure_tensor(t)) for t in inputs]
    return outs[0] if len(outs) == 1 else outs


def atleast_2d(*inputs, name=None):
    outs = [apply_op("atleast_2d", jnp.atleast_2d, ensure_tensor(t)) for t in inputs]
    return outs[0] if len(outs) == 1 else outs


def atleast_3d(*inputs, name=None):
    outs = [apply_op("atleast_3d", jnp.atleast_3d, ensure_tensor(t)) for t in inputs]
    return outs[0] if len(outs) == 1 else outs
