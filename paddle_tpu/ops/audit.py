"""Dispatch-surface audit: enumerate every op name that can reach apply_op.

Parity: the reference's single YAML registry guarantees that every op that
dispatches has a schema (paddle/phi/ops/yaml/ops.yaml — an op cannot exist
without an entry; op_test.py then sweeps each entry per dtype). Our eager
ops are plain Python, so the equivalent guarantee is recovered by static
analysis: this module walks the package AST and collects

  1. direct literal calls         apply_op("name", ...)
  2. dispatcher forwarding        def _binop(opname, ...): apply_op(opname,)
     + literal call sites         _binop("add", jnp.add)
     (transitively: a function forwarding its parameter into another
     dispatcher's name slot is itself a dispatcher)
  3. dynamic name sites           apply_op(f"rnn_{mode}", ...) — returned
     separately; each must be covered by an explicit enumeration in
     ops.schemas.DYNAMIC_DISPATCH.

tests/test_schema_enforcement.py asserts: every collected name has a
schema in ops.schemas.SCHEMAS or an entry in ops.schemas.WHITE_LIST, and
every dynamic site matches a DYNAMIC_DISPATCH pattern.  A runtime
recorder in ops.dispatch cross-checks the same invariant over names that
actually dispatched during a test session (run_shards.py merges and
enforces per-process records).
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Set, Tuple

_PKG_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _iter_py_files(root: str = _PKG_ROOT):
    for dirpath, _dirnames, filenames in os.walk(root):
        for f in filenames:
            if f.endswith(".py"):
                yield os.path.join(dirpath, f)


def _func_name(node: ast.Call) -> str:
    f = node.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return ""


class _Visitor(ast.NodeVisitor):
    """One pass over one module: records apply_op call sites and, for each
    enclosing function, which of its parameters flow into a dispatcher's
    name slot."""

    def __init__(self, dispatchers: Dict[str, tuple]):
        # dispatcher function name -> (positional index, parameter name)
        # of its op-name slot; the parameter name resolves keyword calls
        # like apply_op(name="foo", ...)
        self.dispatchers = dispatchers
        self.literals: Set[str] = set()
        self.dynamic: List[Tuple[str, int, str]] = []  # (file, line, repr)
        self.new_dispatchers: Dict[str, int] = {}
        self._fn_stack: List[ast.FunctionDef] = []
        self._file = "?"

    def visit_FunctionDef(self, node):
        self._fn_stack.append(node)
        self.generic_visit(node)
        self._fn_stack.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    def _name_arg(self, call: ast.Call, idx: int, pname: str):
        if idx < len(call.args):
            return call.args[idx]
        for kw in call.keywords:
            if kw.arg == pname:
                return kw.value
        return None

    def visit_Call(self, node):
        fname = _func_name(node)
        slot = self.dispatchers.get(fname)
        if slot is not None:
            idx, pname = slot
            arg = self._name_arg(node, idx, pname)
            if arg is None:
                # name slot not found positionally or by keyword — flag
                # rather than silently skip (the guarantee depends on it)
                self.dynamic.append((self._file, node.lineno,
                                     f"{fname}(...): no name arg"))
            elif isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                self.literals.add(arg.value)
            elif isinstance(arg, ast.Name) and self._fn_stack:
                # parameter forwarding: the enclosing function owning the
                # parameter becomes a dispatcher (apply_op often sits in a
                # closure nested inside the factory that owns the name arg)
                for fn in reversed(self._fn_stack):
                    params = [a.arg for a in fn.args.args]
                    if arg.id in params:
                        self.new_dispatchers.setdefault(
                            fn.name, (params.index(arg.id), arg.id))
                        break
                else:
                    self.dynamic.append(
                        (self._file, node.lineno, ast.dump(arg)[:80]))
            else:
                self.dynamic.append(
                    (self._file, node.lineno, ast.dump(arg)[:80]))
        self.generic_visit(node)


def _resolve_module(path: str, level: int, module: str, root: str):
    """Resolve a relative/absolute intra-package import to a file path."""
    if level == 0:
        if not module or not module.startswith("paddle_tpu"):
            return None
        parts = module.split(".")[1:]
        base = root
    else:
        base = os.path.dirname(path)
        for _ in range(level - 1):
            base = os.path.dirname(base)
        parts = module.split(".") if module else []
    cand = os.path.join(base, *parts)
    if os.path.isfile(cand + ".py"):
        return cand + ".py"
    if os.path.isfile(os.path.join(cand, "__init__.py")):
        return os.path.join(cand, "__init__.py")
    return None


def _imported_names(path: str, tree: ast.AST, root: str) -> Dict[str, tuple]:
    """alias -> (defining_file, original_name) for intra-package imports."""
    out: Dict[str, tuple] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom):
            target = _resolve_module(path, node.level, node.module or "", root)
            if target is None:
                continue
            for alias in node.names:
                out[alias.asname or alias.name] = (target, alias.name)
    return out


def collect_dispatch_surface(root: str = _PKG_ROOT):
    """Returns (literal_names, dynamic_sites, dispatchers_per_module).

    Dispatcher resolution is module-scoped (a module's own defs plus names
    it explicitly imports) so same-named helpers in unrelated modules
    (e.g. a loss `_reduce(value, reduction)` vs math.py's `_reduce`
    dispatch factory) don't cross-contaminate.  Iterates to a fixed point
    so dispatchers-of-dispatchers and cross-module factory imports
    resolve."""
    sources = {}
    for path in _iter_py_files(root):
        try:
            with open(path, "r") as fh:
                sources[path] = ast.parse(fh.read())
        except SyntaxError:  # pragma: no cover
            continue

    imports = {p: _imported_names(p, t, root) for p, t in sources.items()}
    # pool of discovered dispatchers keyed by (defining_file, name); a
    # module sees a foreign dispatcher only by explicitly importing it
    pool: Dict[tuple, int] = {}
    literals: Set[str] = set()
    dynamic: List[Tuple[str, int, str]] = []
    for _round in range(10):
        literals = set()
        dynamic = []
        grown = False
        for path, tree in sources.items():
            scope = {"apply_op": (0, "name")}
            for alias, (target, orig) in imports[path].items():
                idx = pool.get((target, orig))
                if idx is not None:
                    scope[alias] = idx
            scope.update({n: i for (p, n), i in pool.items() if p == path})
            v = _Visitor(scope)
            v._file = os.path.relpath(path, root)
            v.visit(tree)
            literals |= v.literals
            dynamic.extend(v.dynamic)
            for k, i in v.new_dispatchers.items():
                if (path, k) not in pool:
                    pool[(path, k)] = i
                    grown = True
        if not grown:
            break
    return literals, dynamic, pool
