"""Long-tail math/manipulation ops.

Parity: the remaining python/paddle/tensor/{math,manipulation,linalg,
stat}.py surface — bincount, vander, trapezoid, cdist, quantile family,
stacking/splitting aliases, cov/corrcoef, take, renorm, polar/sgn/sinc,
masked_scatter. All pure jnp through the standard dispatch.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from .dispatch import apply_op, ensure_tensor

__all__ = [
    "bincount", "vander", "trapezoid", "cumulative_trapezoid", "cdist", "renorm",
    "frexp", "signbit", "take", "masked_scatter", "column_stack", "row_stack",
    "hstack", "vstack", "dstack", "hsplit", "vsplit", "dsplit", "unflatten",
    "block_diag", "cartesian_prod", "corrcoef", "cov", "nanmedian", "quantile",
    "nanquantile", "bitwise_invert", "polar", "sgn", "sinc", "isneginf",
    "isposinf", "isreal", "combinations",
]


def bincount(x, weights=None, minlength: int = 0, name=None) -> Tensor:
    xt = ensure_tensor(x)
    d = xt._data
    if isinstance(d, jax.Array) and not isinstance(d, jax.core.Tracer):
        n = int(max(int(np.asarray(d).max(initial=-1)) + 1, minlength))
    elif minlength > 0:  # traced/static values: the static length must be given
        n = minlength
    else:
        raise NotImplementedError(
            "bincount under tracing/static capture needs minlength (the output "
            "length is data-dependent)")

    if weights is None:
        return apply_op("bincount", lambda a: jnp.bincount(a, length=n), xt)
    return apply_op("bincount", lambda a, w: jnp.bincount(a, weights=w, length=n),
                    xt, ensure_tensor(weights))


def vander(x, n: Optional[int] = None, increasing: bool = False, name=None) -> Tensor:
    xt = ensure_tensor(x)
    cols = n if n is not None else int(xt.shape[0])
    return apply_op("vander", lambda a: jnp.vander(a, N=cols, increasing=increasing), xt)


def trapezoid(y, x=None, dx=None, axis: int = -1, name=None) -> Tensor:
    yt = ensure_tensor(y)
    if x is not None:
        return apply_op("trapezoid", lambda yy, xx: jnp.trapezoid(yy, xx, axis=axis),
                        yt, ensure_tensor(x))
    step = 1.0 if dx is None else dx
    return apply_op("trapezoid", lambda yy: jnp.trapezoid(yy, dx=step, axis=axis), yt)


def cumulative_trapezoid(y, x=None, dx=None, axis: int = -1, name=None) -> Tensor:
    yt = ensure_tensor(y)

    def fn(yy, *rest):
        yy_m = jnp.moveaxis(yy, axis, -1)
        if rest:
            xx = jnp.moveaxis(rest[0], axis, -1) if rest[0].ndim == yy.ndim else rest[0]
            d = jnp.diff(xx, axis=-1)
        else:
            d = jnp.full(yy_m.shape[-1] - 1, 1.0 if dx is None else dx, yy.dtype)
        avg = (yy_m[..., 1:] + yy_m[..., :-1]) * 0.5
        return jnp.moveaxis(jnp.cumsum(avg * d, axis=-1), -1, axis)

    if x is not None:
        return apply_op("cumulative_trapezoid", fn, yt, ensure_tensor(x))
    return apply_op("cumulative_trapezoid", fn, yt)


def cdist(x, y, p: float = 2.0, compute_mode="use_mm_for_euclid_dist_if_necessary",
          name=None) -> Tensor:
    def fn(a, b):
        d = a[..., :, None, :] - b[..., None, :, :]
        if p == 2.0:
            d2 = (d * d).sum(-1)
            # zero-distance pairs (self-distance) must not NaN the gradient
            safe = jnp.where(d2 > 0, d2, 1.0)
            return jnp.where(d2 > 0, jnp.sqrt(safe), 0.0)
        return jnp.power(jnp.power(jnp.abs(d), p).sum(-1), 1.0 / p)

    return apply_op("cdist", fn, ensure_tensor(x), ensure_tensor(y))


def renorm(x, p: float, axis: int, max_norm: float, name=None) -> Tensor:
    def fn(a):
        am = jnp.moveaxis(a, axis, 0)
        flat = am.reshape(am.shape[0], -1)
        norms = jnp.power(jnp.power(jnp.abs(flat), p).sum(-1), 1.0 / p)
        scale = jnp.where(norms > max_norm, max_norm / jnp.maximum(norms, 1e-12), 1.0)
        return jnp.moveaxis(am * scale.reshape((-1,) + (1,) * (am.ndim - 1)), 0, axis)

    return apply_op("renorm", fn, ensure_tensor(x))


def frexp(x, name=None):
    return apply_op("frexp", lambda a: jnp.frexp(a), ensure_tensor(x))


def signbit(x, name=None) -> Tensor:
    return apply_op("signbit", jnp.signbit, ensure_tensor(x))


def take(x, index, mode: str = "raise", name=None) -> Tensor:
    xt, it = ensure_tensor(x), ensure_tensor(index)
    if mode == "raise":
        # eager bounds check when values are concrete (tracers can't raise)
        idx_val = it._data
        if isinstance(idx_val, jax.Array) and not isinstance(idx_val, jax.core.Tracer):
            n = int(np.prod(xt.shape)) if xt.shape else 1
            arr = np.asarray(idx_val)
            if arr.size and (int(arr.max()) >= n or int(arr.min()) < -n):
                raise IndexError(f"take index out of range for {n} elements")

    def fn(a, i):
        flat = a.ravel()
        if mode == "clip":
            return jnp.take(flat, i, mode="clip")
        # raise/wrap: python-style negative indexing via modulo
        return jnp.take(flat, i % flat.shape[0], mode="clip")

    return apply_op("take", fn, xt, it)


def masked_scatter(x, mask, value, name=None) -> Tensor:
    def fn(a, m, v):
        flat_idx = jnp.cumsum(m.astype(jnp.int32).ravel()) - 1
        src = v.ravel()[jnp.clip(flat_idx, 0, v.size - 1)].reshape(a.shape)
        return jnp.where(m, src, a)

    return apply_op("masked_scatter", fn, ensure_tensor(x), ensure_tensor(mask),
                    ensure_tensor(value))


def _nary(name, jfn, tensors):
    ts = [ensure_tensor(t) for t in tensors]
    return apply_op(name, lambda *a: jfn(a), *ts)


def column_stack(x, name=None) -> Tensor:
    return _nary("column_stack", jnp.column_stack, x)


def hstack(x, name=None) -> Tensor:
    return _nary("hstack", jnp.hstack, x)


def vstack(x, name=None) -> Tensor:
    return _nary("vstack", jnp.vstack, x)


row_stack = vstack


def dstack(x, name=None) -> Tensor:
    return _nary("dstack", jnp.dstack, x)


def _nsplit(name, jfn, x, num_or_indices):
    xt = ensure_tensor(x)
    spec = num_or_indices if isinstance(num_or_indices, int) else [int(i) for i in num_or_indices]
    out = apply_op(name, lambda a: tuple(jfn(a, spec)), xt)
    return list(out) if isinstance(out, (tuple, list)) else [out]


def hsplit(x, num_or_indices, name=None):
    return _nsplit("hsplit", jnp.hsplit, x, num_or_indices)


def vsplit(x, num_or_indices, name=None):
    return _nsplit("vsplit", jnp.vsplit, x, num_or_indices)


def dsplit(x, num_or_indices, name=None):
    return _nsplit("dsplit", jnp.dsplit, x, num_or_indices)


def unflatten(x, axis: int, shape: Sequence[int], name=None) -> Tensor:
    def fn(a):
        ax = axis % a.ndim
        new_shape = a.shape[:ax] + tuple(shape) + a.shape[ax + 1:]
        return a.reshape(new_shape)

    return apply_op("unflatten", fn, ensure_tensor(x))


def block_diag(inputs, name=None) -> Tensor:
    ts = [ensure_tensor(t) for t in inputs]

    def fn(*mats):
        mats = [m if m.ndim == 2 else m.reshape(1, -1) for m in mats]
        R = sum(m.shape[0] for m in mats)
        C = sum(m.shape[1] for m in mats)
        out = jnp.zeros((R, C), mats[0].dtype)
        r = c = 0
        for m in mats:
            out = out.at[r:r + m.shape[0], c:c + m.shape[1]].set(m)
            r += m.shape[0]
            c += m.shape[1]
        return out

    return apply_op("block_diag", fn, *ts)


def cartesian_prod(x, name=None) -> Tensor:
    ts = [ensure_tensor(t) for t in x]

    def fn(*vs):
        grids = jnp.meshgrid(*vs, indexing="ij")
        return jnp.stack([g.ravel() for g in grids], axis=-1)

    out = apply_op("cartesian_prod", fn, *ts)
    return out


def combinations(x, r: int = 2, with_replacement: bool = False, name=None) -> Tensor:
    import itertools

    xt = ensure_tensor(x)
    n = int(xt.shape[0])
    comb = itertools.combinations_with_replacement if with_replacement else itertools.combinations
    idx = np.array(list(comb(range(n), r)), np.int32).reshape(-1, r)

    return apply_op("combinations", lambda a: a[jnp.asarray(idx)], xt)


def cov(x, rowvar: bool = True, ddof: bool = True, fweights=None, aweights=None,
        name=None) -> Tensor:
    # single implementation lives in linalg (handles fweights/aweights)
    from ..linalg import cov as _linalg_cov

    return _linalg_cov(x, rowvar=rowvar, ddof=ddof, fweights=fweights, aweights=aweights)


def corrcoef(x, rowvar: bool = True, name=None) -> Tensor:
    from ..linalg import corrcoef as _linalg_corrcoef

    return _linalg_corrcoef(x, rowvar=rowvar)


def quantile(x, q, axis=None, keepdim: bool = False, interpolation: str = "linear",
             name=None) -> Tensor:
    qa = jnp.asarray(q)
    return apply_op("quantile", lambda a: jnp.quantile(a, qa, axis=axis, keepdims=keepdim,
                                                       method=interpolation),
                    ensure_tensor(x))


def nanquantile(x, q, axis=None, keepdim: bool = False, interpolation: str = "linear",
                name=None) -> Tensor:
    qa = jnp.asarray(q)
    return apply_op("nanquantile", lambda a: jnp.nanquantile(a, qa, axis=axis, keepdims=keepdim,
                                                             method=interpolation),
                    ensure_tensor(x))


def nanmedian(x, axis=None, keepdim: bool = False, mode: str = "avg", name=None) -> Tensor:
    if mode == "min":  # lower of the two middle values on even counts
        return apply_op("nanmedian",
                        lambda a: jnp.nanquantile(a, 0.5, axis=axis, keepdims=keepdim,
                                                  method="lower"),
                        ensure_tensor(x))
    return apply_op("nanmedian", lambda a: jnp.nanmedian(a, axis=axis, keepdims=keepdim),
                    ensure_tensor(x))


def bitwise_invert(x, name=None) -> Tensor:
    return apply_op("bitwise_invert", jnp.invert, ensure_tensor(x))


def polar(abs, angle, name=None) -> Tensor:
    def fn(r, t):
        ctype = jnp.complex128 if r.dtype == jnp.float64 else jnp.complex64
        return (r * jnp.cos(t) + 1j * r * jnp.sin(t)).astype(ctype)

    return apply_op("polar", fn, ensure_tensor(abs), ensure_tensor(angle))


def sgn(x, name=None) -> Tensor:
    def fn(a):
        if jnp.issubdtype(a.dtype, jnp.complexfloating):
            mag = jnp.abs(a)
            return jnp.where(mag == 0, 0, a / jnp.maximum(mag, 1e-38)).astype(a.dtype)
        return jnp.sign(a)

    return apply_op("sgn", fn, ensure_tensor(x))


def sinc(x, name=None) -> Tensor:
    return apply_op("sinc", jnp.sinc, ensure_tensor(x))


def isneginf(x, name=None) -> Tensor:
    return apply_op("isneginf", jnp.isneginf, ensure_tensor(x))


def isposinf(x, name=None) -> Tensor:
    return apply_op("isposinf", jnp.isposinf, ensure_tensor(x))


def isreal(x, name=None) -> Tensor:
    return apply_op("isreal", jnp.isreal, ensure_tensor(x))
