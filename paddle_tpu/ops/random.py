"""Random ops with a global generator.

Parity: python/paddle/tensor/random.py + phi/core/generator.h (global RNG
state). TPU design: a single root ``jax.random`` key, split per call —
deterministic under ``seed()`` like the reference's Generator, and usable
inside jit via explicit key threading (``split_key``).
"""

from __future__ import annotations

import threading

import jax
import jax.numpy as jnp
import numpy as np

from ..core import dtype as dtypes
from ..core.tensor import Tensor
from .dispatch import ensure_tensor

_lock = threading.Lock()
# Lazily created: creating a key at import time would initialize the XLA
# backend and break jax.distributed.initialize() in multi-process jobs
# (init_parallel_env must run after `import paddle_tpu`, like the
# reference's init_parallel_env after `import paddle`).
_KEY = [None]


def _key():
    if _KEY[0] is None:
        _KEY[0] = jax.random.key(0)
    return _KEY[0]


def seed(s: int):
    with _lock:
        _KEY[0] = jax.random.key(int(s))
    return None


def split_key(seed: int = 0):
    """Pop a fresh subkey from the global generator (host-side state
    update). A nonzero ``seed`` bypasses the global stream entirely —
    reference semantics of per-call seed args (phi uniform/gaussian
    kernels: seed!=0 seeds a dedicated generator)."""
    if seed:
        return jax.random.PRNGKey(seed)
    with _lock:
        _KEY[0], sub = jax.random.split(_key())
    return sub


def get_rng_state():
    return [jax.random.key_data(_key())]


def set_rng_state(state):
    with _lock:
        _KEY[0] = jax.random.wrap_key_data(state[0] if isinstance(state, (list, tuple)) else state)


def rand(shape, dtype=None, name=None) -> Tensor:
    d = dtypes.convert_dtype(dtype) or dtypes.get_default_dtype()
    from .creation import _shape

    return Tensor(jax.random.uniform(split_key(), _shape(shape), d))


def randn(shape, dtype=None, name=None) -> Tensor:
    d = dtypes.convert_dtype(dtype) or dtypes.get_default_dtype()
    from .creation import _shape

    return Tensor(jax.random.normal(split_key(), _shape(shape), d))


def uniform(shape, dtype=None, min=-1.0, max=1.0, seed=0, name=None) -> Tensor:
    d = dtypes.convert_dtype(dtype) or dtypes.get_default_dtype()
    from .creation import _shape

    return Tensor(jax.random.uniform(split_key(seed), _shape(shape), d, minval=min, maxval=max))


def uniform_(x: Tensor, min=-1.0, max=1.0, seed=0, name=None) -> Tensor:
    x._data = jax.random.uniform(split_key(seed), x._data.shape, x._data.dtype, minval=min, maxval=max)
    return x


def normal(mean=0.0, std=1.0, shape=None, name=None) -> Tensor:
    from .creation import _shape

    if isinstance(mean, Tensor) or isinstance(std, Tensor):
        m = mean._data if isinstance(mean, Tensor) else mean
        s = std._data if isinstance(std, Tensor) else std
        shp = jnp.broadcast_shapes(jnp.shape(m), jnp.shape(s))
        return Tensor(jax.random.normal(split_key(), shp, dtypes.get_default_dtype()) * s + m)
    shp = _shape(shape) if shape is not None else ()
    return Tensor(jax.random.normal(split_key(), shp, dtypes.get_default_dtype()) * std + mean)


def normal_(x: Tensor, mean=0.0, std=1.0, name=None) -> Tensor:
    x._data = jax.random.normal(split_key(), x._data.shape, x._data.dtype) * std + mean
    return x


def gaussian(shape, mean=0.0, std=1.0, seed=0, dtype=None, name=None) -> Tensor:
    d = dtypes.convert_dtype(dtype) or dtypes.get_default_dtype()
    from .creation import _shape

    return Tensor(jax.random.normal(split_key(seed), _shape(shape), d) * std + mean)


def randint(low=0, high=None, shape=(1,), dtype="int64", name=None) -> Tensor:
    d = dtypes.convert_dtype(dtype)
    from .creation import _shape

    if high is None:
        low, high = 0, low
    return Tensor(jax.random.randint(split_key(), _shape(shape), low, high, d))


def randint_like(x, low=0, high=None, dtype=None, name=None) -> Tensor:
    x = ensure_tensor(x)
    d = dtypes.convert_dtype(dtype) or x._data.dtype
    if high is None:
        low, high = 0, low
    return Tensor(jax.random.randint(split_key(), x._data.shape, low, high, d))


def randperm(n, dtype="int64", name=None) -> Tensor:
    d = dtypes.convert_dtype(dtype)
    return Tensor(jax.random.permutation(split_key(), n).astype(d))


def bernoulli(x, name=None) -> Tensor:
    x = ensure_tensor(x)
    return Tensor(jax.random.bernoulli(split_key(), x._data).astype(x._data.dtype))


def multinomial(x, num_samples=1, replacement=False, name=None) -> Tensor:
    x = ensure_tensor(x)
    logits = jnp.log(jnp.maximum(x._data, 1e-30))
    if replacement:
        out = jax.random.categorical(split_key(), logits, axis=-1, shape=(*logits.shape[:-1], num_samples))
    else:
        k = split_key()
        z = jax.random.gumbel(k, logits.shape, logits.dtype) + logits
        _, out = jax.lax.top_k(z, num_samples)
    return Tensor(out.astype(jnp.int64))


def shuffle(x, name=None) -> Tensor:
    x = ensure_tensor(x)
    return Tensor(jax.random.permutation(split_key(), x._data, axis=0))


def poisson(x, name=None) -> Tensor:
    x = ensure_tensor(x)
    return Tensor(jax.random.poisson(split_key(), x._data).astype(x._data.dtype))


def exponential_(x: Tensor, lam=1.0, name=None) -> Tensor:
    x._data = jax.random.exponential(split_key(), x._data.shape, x._data.dtype) / lam
    return x
