"""Round-5 schema conversions: ops that previously sat in
NO_SCHEMA_WHITE_LIST but are deterministic and schemable.

Each entry gives the op a numpy oracle + sampled-input spec so the
dtype/grad sweep (tests/test_op_schema_sweep.py) covers it like any
other op — the white-list discipline's bound tightens from 10% to 5%
of the dispatch surface (reference: test/white_list shrinkage over
time; ops.yaml coverage is the norm, the white list the exception).

Grad notes: ops whose vjp requires *consistent* auxiliary index inputs
(moe permutation ops) or whose FD cost is quadratic in tensor size
(flash attention) register grad=False here; their gradients are pinned
by dedicated parity suites (tests/test_moe.py, test_flash_attention.py,
test_torch_oracle.py).
"""

from __future__ import annotations

import numpy as np

from .schemas import _S
from .schemas_extended import _GRAD_TOL_ACC, _NN_TOL

# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------


def _np_softmax(x, axis=-1):
    m = x.max(axis=axis, keepdims=True)
    e = np.exp(x - m)
    return e / e.sum(axis=axis, keepdims=True)


def _np_sigmoid(x):
    return 1.0 / (1.0 + np.exp(-x))


def _np_layer_norm(x, scale, bias, eps):
    m = x.mean(-1, keepdims=True)
    v = x.var(-1, keepdims=True)
    y = (x - m) / np.sqrt(v + eps)
    if scale is not None:
        y = y * scale
    if bias is not None:
        y = y + bias
    return y


# ---------------------------------------------------------------------------
# model-internal ops (models/llama.py, generation.py)
# ---------------------------------------------------------------------------

_ROPE_MAXPOS, _ROPE_OFF = 8, 1


def _np_rope_tables(head_dim, max_pos, theta=10000.0):
    inv = 1.0 / (theta ** (np.arange(0, head_dim, 2, dtype=np.float32)
                           / head_dim))
    freqs = np.outer(np.arange(max_pos, dtype=np.float32), inv)
    return np.cos(freqs), np.sin(freqs)


def _np_rope_one(x, off):
    cos, sin = _np_rope_tables(x.shape[-1], _ROPE_MAXPOS)
    s = x.shape[1]
    c = cos[off:off + s][None, :, None, :]
    si = sin[off:off + s][None, :, None, :]
    x1, x2 = np.split(x, 2, axis=-1)
    return np.concatenate([x1 * c - x2 * si, x2 * c + x1 * si], -1)


def _rope_ref(q, k):
    return _np_rope_one(q, _ROPE_OFF), _np_rope_one(k, _ROPE_OFF)


def _rope_wrap(api):
    def run(q, k):
        cos, sin = _np_rope_tables(int(q.shape[-1]), _ROPE_MAXPOS)
        return api(q, k, cos, sin, _ROPE_OFF)

    return run


_S("rope", _rope_ref, [((2, 4, 2, 8), "any"), ((2, 4, 2, 8), "any")],
   api="models.llama.apply_rotary_pos_emb", wrap=_rope_wrap,
   tol=_NN_TOL, grad_tol=_GRAD_TOL_ACC)

_S("repeat_kv", lambda x: np.repeat(x, 2, axis=2),
   [((2, 3, 2, 4), "any")], api="models.llama.repeat_kv",
   wrap=lambda api: lambda x: api(x, 2))


def _kv_write_ref(buf, new):
    out = buf.copy()
    out[:, 1:1 + new.shape[1]] = new
    return out


_S("kv_cache_update", _kv_write_ref,
   [((2, 6, 2, 3), "any"), ((2, 2, 2, 3), "any")],
   api="generation.kv_cache_write", kwargs={"position_offset": 1})

# paged KV pool (serving round 7): pool [num_blocks, block_size, h, d],
# per-row block tables route each token's write/read to a physical
# block. Fixed table [[1, 2], [3, 0]], offset 1, s=2: row 0 writes flat
# slots {3, 4}, row 1 {7, 0} — distinct, so the scatter ref is exact.
_PAGED_BT = np.array([[1, 2], [3, 0]], np.int32)
_PAGED_BS = 2


def _paged_kv_write_ref(pool, new):
    out = pool.copy()
    flat = out.reshape((-1,) + out.shape[2:])
    b, s = new.shape[0], new.shape[1]
    for r in range(b):
        for j in range(s):
            p = 1 + j
            blk = _PAGED_BT[r, p // _PAGED_BS]
            flat[blk * _PAGED_BS + p % _PAGED_BS] = new[r, j]
    return flat.reshape(out.shape)


_S("paged_kv_cache_update", _paged_kv_write_ref,
   [((4, 2, 2, 3), "any"), ((2, 2, 2, 3), "any")],
   api="generation.paged_kv_cache_write",
   wrap=lambda api: lambda pool, new: api(pool, new, _PAGED_BT, 1))


def _paged_gather_ref(pool):
    out = pool[_PAGED_BT.reshape(-1)]
    return out.reshape((2, 2 * _PAGED_BS) + pool.shape[2:])


_S("paged_kv_gather", _paged_gather_ref, [((4, 2, 2, 3), "any")],
   api="generation.gather_paged_kv",
   wrap=lambda api: lambda pool: api(pool, _PAGED_BT))

# ---------------------------------------------------------------------------
# RNN cells + fused RNN layers (nn/layers_rnn.py)
# ---------------------------------------------------------------------------


def _cell_wrap(n_weights):
    """wrap for cell classes: build the cell, substitute the sampled
    weights for its parameters, call it, return the step output."""

    def outer(cls):
        def run(x, h, *ws):
            gate_mult = {"LSTMCell": 4, "GRUCell": 3}.get(cls.__name__, 1)
            cell = cls(int(x.shape[-1]), int(ws[0].shape[0]) // gate_mult)
            names = ["weight_ih", "weight_hh", "bias_ih", "bias_hh"]
            for n, w in zip(names, ws):
                cell._parameters[n] = w
            if cls.__name__ == "LSTMCell":
                out = cell(x, (h, h * 0.5))
            else:
                out = cell(x, h)
            return out[0] if isinstance(out, (tuple, list)) else out

        return run

    return outer


def _simple_cell_ref(x, h, wi, wh, bi, bh):
    return np.tanh(x @ wi.T + h @ wh.T + bi + bh)


_S("simple_rnn_cell", _simple_cell_ref,
   [((2, 4), "any"), ((2, 5), "any"), ((5, 4), "small"), ((5, 5), "small"),
    ((5,), "small"), ((5,), "small")],
   api="nn.SimpleRNNCell", wrap=_cell_wrap(4), tol=_NN_TOL,
   grad_tol=_GRAD_TOL_ACC)


def _gru_cell_ref(x, h, wi, wh, bi, bh):
    xg = x @ wi.T + bi
    hg = h @ wh.T + bh
    xr, xz, xc = np.split(xg, 3, axis=-1)
    hr, hz, hc = np.split(hg, 3, axis=-1)
    r = _np_sigmoid(xr + hr)
    z = _np_sigmoid(xz + hz)
    c = np.tanh(xc + r * hc)
    return z * h + (1.0 - z) * c


_S("gru_cell", _gru_cell_ref,
   [((2, 4), "any"), ((2, 5), "any"), ((15, 4), "small"), ((15, 5), "small"),
    ((15,), "small"), ((15,), "small")],
   api="nn.GRUCell", wrap=_cell_wrap(4), tol=_NN_TOL, grad_tol=_GRAD_TOL_ACC)


def _lstm_cell_ref(x, h, wi, wh, bi, bh):
    c = h * 0.5
    gates = x @ wi.T + h @ wh.T + bi + bh
    i, f, g, o = np.split(gates, 4, axis=-1)
    c_new = _np_sigmoid(f) * c + _np_sigmoid(i) * np.tanh(g)
    return _np_sigmoid(o) * np.tanh(c_new)


_S("lstm_cell", _lstm_cell_ref,
   [((2, 4), "any"), ((2, 5), "any"), ((20, 4), "small"), ((20, 5), "small"),
    ((20,), "small"), ((20,), "small")],
   api="nn.LSTMCell", wrap=_cell_wrap(4), tol=_NN_TOL, grad_tol=_GRAD_TOL_ACC)


def _rnn_layer_wrap(cls_gates):
    def outer(cls):
        def run(x, wi, wh, bi, bh):
            H = int(wi.shape[0]) // cls_gates
            layer = cls(int(x.shape[-1]), H, 1)
            for n, w in zip(["weight_ih_l0", "weight_hh_l0",
                             "bias_ih_l0", "bias_hh_l0"],
                            [wi, wh, bi, bh]):
                layer._parameters[n] = w
            y, _ = layer(x)
            return y

        return run

    return outer


def _rnn_seq_ref(x, wi, wh, bi, bh):
    b, t, _ = x.shape
    h = np.zeros((b, wh.shape[1]), np.float32)
    outs = []
    for i in range(t):
        h = _simple_cell_ref(x[:, i], h, wi, wh, bi, bh)
        outs.append(h)
    return np.stack(outs, 1)


# grad_inputs=[0] on the fused layers: every FD evaluation re-traces the
# layer's lax.scan (~0.3 s), so sweeping all ~130 weight elements would
# cost minutes per schema; the cell schemas above FD-check the weight
# gradients of the same step math, the layer adds only the scan chaining
_S("rnn_rnn", _rnn_seq_ref,
   [((1, 2, 3), "any"), ((3, 3), "small"), ((3, 3), "small"),
    ((3,), "small"), ((3,), "small")],
   api="nn.SimpleRNN", wrap=_rnn_layer_wrap(1), tol=_NN_TOL,
   grad_inputs=[0], grad_tol=_GRAD_TOL_ACC)


def _gru_seq_ref(x, wi, wh, bi, bh):
    b, t, _ = x.shape
    h = np.zeros((b, wh.shape[1]), np.float32)
    outs = []
    for i in range(t):
        h = _gru_cell_ref(x[:, i], h, wi, wh, bi, bh)
        outs.append(h)
    return np.stack(outs, 1)


_S("rnn_gru", _gru_seq_ref,
   [((1, 2, 3), "any"), ((9, 3), "small"), ((9, 3), "small"),
    ((9,), "small"), ((9,), "small")],
   api="nn.GRU", wrap=_rnn_layer_wrap(3), tol=_NN_TOL,
   grad_inputs=[0], grad_tol=_GRAD_TOL_ACC)


def _lstm_seq_ref(x, wi, wh, bi, bh):
    b, t, _ = x.shape
    H = wh.shape[1]
    h = np.zeros((b, H), np.float32)
    c = np.zeros((b, H), np.float32)
    outs = []
    for i in range(t):
        gates = x[:, i] @ wi.T + h @ wh.T + bi + bh
        ii, f, g, o = np.split(gates, 4, axis=-1)
        c = _np_sigmoid(f) * c + _np_sigmoid(ii) * np.tanh(g)
        h = _np_sigmoid(o) * np.tanh(c)
        outs.append(h)
    return np.stack(outs, 1)


_S("rnn_lstm", _lstm_seq_ref,
   [((1, 2, 3), "any"), ((12, 3), "small"), ((12, 3), "small"),
    ((12,), "small"), ((12,), "small")],
   api="nn.LSTM", wrap=_rnn_layer_wrap(4), tol=_NN_TOL,
   grad_inputs=[0], grad_tol=_GRAD_TOL_ACC)

# ---------------------------------------------------------------------------
# pooling ceil-path, segment sub-op, sparse bias, indexing
# ---------------------------------------------------------------------------


def _ceil_pool_ref(x):
    n, c, hh, ww = x.shape
    oh = (hh + 1) // 2
    ow = (ww + 1) // 2
    out = np.full((n, c, oh, ow), -np.inf, x.dtype)
    for i in range(oh):
        for j in range(ow):
            out[:, :, i, j] = x[:, :, 2 * i:2 * i + 2,
                                2 * j:2 * j + 2].max(axis=(2, 3))
    return out


_S("ceil_pad", _ceil_pool_ref, [((1, 2, 5, 5), "any")],
   api="nn.functional.max_pool2d",
   kwargs={"kernel_size": 2, "stride": 2, "ceil_mode": True},
   tol=_NN_TOL, grad_tol=_GRAD_TOL_ACC)


def _segment_mean_ref(x, ids):
    n_seg = int(ids.max()) + 1
    out = np.zeros((n_seg,) + x.shape[1:], np.float32)
    cnt = np.zeros((n_seg,), np.float32)
    for i, s in enumerate(ids):
        out[int(s)] += x[i]
        cnt[int(s)] += 1
    return out / np.maximum(cnt, 1)[:, None]


_S("segment_mean_sum", _segment_mean_ref,
   [((6, 3), "any"), ((6,), "idx3")],
   api="ops.long_tail.segment_mean", grad_inputs=[0],
   tol=_NN_TOL, grad_tol=_GRAD_TOL_ACC)

_S("sparse_linear_bias", lambda x, b: x + b,
   [((3, 4), "any"), ((4,), "any")], api="sparse.linear_bias_add")

_S("getitem", lambda x, i: x[i],
   [((5, 4), "any"), ((3,), "idx3")], api="ops.getitem", grad_inputs=[0])


def _setitem_ref(x, v):
    y = x.copy()
    y[1:3] = v
    return y


_S("setitem", _setitem_ref, [((4, 5), "any"), ((2, 5), "any")],
   api="ops.setitem", wrap=lambda api: lambda x, v: api(x, slice(1, 3), v),
   grad_inputs=[1])

# ---------------------------------------------------------------------------
# audio feature stages (audio/functional.py)
# ---------------------------------------------------------------------------

_S("mel_projection", lambda s, fb: np.einsum("mf,bft->bmt", fb, s),
   [((2, 9, 6), "pos"), ((4, 9), "pos")],
   api="audio.functional.mel_projection", tol=_NN_TOL,
   grad_tol=_GRAD_TOL_ACC)


def _power_to_db_ref(m):
    log_spec = 10.0 * np.log10(np.maximum(m, 1e-10))
    return np.maximum(log_spec, log_spec.max() - 80.0)


# float32 tolerance 5e-4: TPU VPU log10 rounds a few ULP differently
# from the CPU libm oracle (measured 2.9e-4 max delta on chip) — the
# documented per-op TPU-tolerance delta, reference
# op_accuracy_white_list discipline
_S("power_to_db", _power_to_db_ref, [((2, 4, 6), "pos")],
   api="audio.functional.power_to_db",
   tol={"float32": (5e-4, 5e-4), **_NN_TOL}, grad_tol=_GRAD_TOL_ACC)

_S("mfcc_dct", lambda lm, dct: np.einsum("mk,bmt->bkt", dct, lm),
   [((2, 6, 5), "any"), ((6, 4), "any")],
   api="audio.functional.mfcc_dct", tol=_NN_TOL, grad_tol=_GRAD_TOL_ACC)

# ---------------------------------------------------------------------------
# flash attention (pallas kernels; forward numerics — grads quadratic in
# FD cost, pinned by tests/test_flash_attention.py parity)
# ---------------------------------------------------------------------------


def _dense_attn_ref(q, k, v, seg=None):
    b, s, h, d = q.shape
    qt = np.moveaxis(q, 2, 1).astype(np.float64)
    kt = np.moveaxis(k, 2, 1).astype(np.float64)
    vt = np.moveaxis(v, 2, 1).astype(np.float64)
    logits = np.einsum("bhqd,bhkd->bhqk", qt, kt) / np.sqrt(d)
    mask = np.tril(np.ones((s, s), bool))
    if seg is not None:
        same = seg[:, None, :, None] == seg[:, None, None, :]
        mask = mask[None, None] & same
    else:
        mask = mask[None, None]
    logits = np.where(mask, logits, -1e30)
    p = _np_softmax(logits, -1)
    out = np.einsum("bhqk,bhkd->bhqd", p, vt)
    return np.moveaxis(out, 1, 2).astype(np.float32)


_FLASH_TOL = {"float32": (5e-4, 5e-4), "bfloat16": (6e-2, 6e-2)}

_S("flash_attention", _dense_attn_ref,
   [((1, 128, 2, 64), "small"), ((1, 128, 2, 64), "small"),
    ((1, 128, 2, 64), "small")],
   api="pallas_kernels.flash_attention", grad=False,
   dtypes=("float32", "bfloat16"), tol=_FLASH_TOL)


def _varlen_attn_ref(q, k, v, seg):
    return _dense_attn_ref(q, k, v, seg)


_S("flash_attn_varlen", _varlen_attn_ref,
   [((1, 128, 2, 64), "small"), ((1, 128, 2, 64), "small"),
    ((1, 128, 2, 64), "small"), ((1, 128), "idx3")],
   api="pallas_kernels.flash_attention", grad=False,
   dtypes=("float32", "bfloat16"), tol=_FLASH_TOL,
   wrap=lambda api: lambda q, k, v, seg: api(q, k, v, segment_ids=seg))

# flash decode (pallas_kernels/decode_attention.py): single-query GQA
# attention over a static KV cache with per-row lengths. grad=False: the
# kernel is forward-only by design (decode is inference; the dispatch
# refuses grad mode). Fixed positions [3, 5]: row 0 mid-cache, row 1 at
# pos + q_len == max_len (the full-cache edge).
_FD_SWEEP_POS = np.array([3, 5], np.int32)


def _flash_decode_ref(q, kc, vc):
    B, qlen, H, d = q.shape
    KV = kc.shape[2]
    g = H // KV
    ke = np.repeat(kc.astype(np.float64), g, axis=2)
    ve = np.repeat(vc.astype(np.float64), g, axis=2)
    out = np.zeros(q.shape, np.float64)
    for b in range(B):
        for i in range(qlen):
            L = int(_FD_SWEEP_POS[b]) + i + 1
            for h in range(H):
                s = (ke[b, :L, h] @ q[b, i, h].astype(np.float64)) / np.sqrt(d)
                p = np.exp(s - s.max())
                out[b, i, h] = (p / p.sum()) @ ve[b, :L, h]
    return out.astype(np.float32)


_S("flash_decode_attention", _flash_decode_ref,
   [((2, 1, 4, 8), "any"), ((2, 6, 2, 8), "any"), ((2, 6, 2, 8), "any")],
   api="pallas_kernels.flash_decode_attention", grad=False,
   dtypes=("float32", "bfloat16"), tol=_FLASH_TOL,
   wrap=lambda api: lambda q, kc, vc: api(q, kc, vc, _FD_SWEEP_POS,
                                          block_k=4))


# paged variant: the same attention math, with the [2, 6, 2, 8] logical
# caches living as pool blocks [7, 2, 2, 8] addressed through a fixed
# [2, 3] block table (block 0 left as the dump block, like the engine).
_PFD_BT = np.array([[1, 2, 3], [4, 5, 6]], np.int32)


def _paged_flash_decode_ref(q, kp, vp):
    gather = lambda p: p[_PFD_BT.reshape(-1)].reshape(
        2, 6, p.shape[2], p.shape[3])
    return _flash_decode_ref(q, gather(kp), gather(vp))


_S("paged_flash_decode_attention", _paged_flash_decode_ref,
   [((2, 1, 4, 8), "any"), ((7, 2, 2, 8), "any"), ((7, 2, 2, 8), "any")],
   api="pallas_kernels.paged_flash_decode_attention", grad=False,
   dtypes=("float32", "bfloat16"), tol=_FLASH_TOL,
   wrap=lambda api: lambda q, kp, vp: api(q, kp, vp, _PFD_BT,
                                          _FD_SWEEP_POS))


# grouped-query SDPA (the flash-decode XLA fallback): per query head
# identical to sdpa over repeat_kv-expanded K/V — which is exactly how
# the oracle computes it.
def _gqa_sdpa_ref(q, k, v, mask):
    B, s, H, d = q.shape
    g = H // k.shape[2]
    ke = np.repeat(k.astype(np.float64), g, axis=2)
    ve = np.repeat(v.astype(np.float64), g, axis=2)
    qt = np.moveaxis(q.astype(np.float64), 2, 1)
    kt = np.moveaxis(ke, 2, 1)
    vt = np.moveaxis(ve, 2, 1)
    logits = np.einsum("bhqd,bhkd->bhqk", qt, kt) / np.sqrt(d) + mask
    p = _np_softmax(logits, -1)
    return np.moveaxis(np.einsum("bhqk,bhkd->bhqd", p, vt), 1, 2
                       ).astype(np.float32)


_S("gqa_sdpa", _gqa_sdpa_ref,
   [((2, 3, 4, 4), "any"), ((2, 5, 2, 4), "any"), ((2, 5, 2, 4), "any"),
    ((2, 1, 3, 5), "any")],
   api="nn.functional.grouped_query_sdpa", tol=_NN_TOL,
   grad_tol=_GRAD_TOL_ACC)

# ---------------------------------------------------------------------------
# fused MHA block (incubate.nn.functional) — pre-LN form
# ---------------------------------------------------------------------------


def _fused_mha_ref(x, qkvw, lw, lns, lnb, qkvb, lb):
    h = _np_layer_norm(x, lns, lnb, 1e-5)
    n_heads, head_dim = qkvw.shape[1], qkvw.shape[2]
    B, S, E = x.shape
    w = qkvw.reshape(3, n_heads * head_dim, E)
    qkv = np.einsum("bse,tde->tbsd", h, w) + qkvb.reshape(3, 1, 1, -1)
    q, k, v = (qkv[t].reshape(B, S, n_heads, head_dim) for t in range(3))
    logits = np.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(head_dim)
    p = _np_softmax(logits, -1)
    ctx = np.einsum("bhqk,bkhd->bqhd", p, v).reshape(B, S, -1)
    return ctx @ lw + lb + x


_S("fused_multi_head_attention", _fused_mha_ref,
   [((2, 3, 8), "any"), ((3, 2, 4, 8), "small"), ((8, 8), "small"),
    ((8,), "any"), ((8,), "any"), ((3, 2, 4), "any"), ((8,), "any")],
   api="incubate.nn.functional.fused_multi_head_attention",
   wrap=lambda api: lambda x, qkvw, lw, lns, lnb, qkvb, lb: api(
       x, qkvw, lw, pre_layer_norm=True, pre_ln_scale=lns, pre_ln_bias=lnb,
       qkv_bias=qkvb, linear_bias=lb, training=False),
   grad_inputs=[0], tol=_NN_TOL, grad_tol=_GRAD_TOL_ACC)

# ---------------------------------------------------------------------------
# MoE permutation dispatch/combine (distributed/moe.py). grad=False: the
# custom vjp is exact only for CONSISTENT (token_idx, inv_idx) pairs —
# randomly sampled index tensors are not inverse maps, so FD would
# disagree by construction; gradient parity lives in tests/test_moe.py.
# ---------------------------------------------------------------------------


def _moe_dispatch_ref(flat, ti, iv):
    t, m = flat.shape
    pad = np.concatenate([flat, np.zeros((1, m), flat.dtype)], 0)
    return pad[np.minimum(ti, t - 1)] * (ti < t)[..., None]


_S("moe_dispatch", _moe_dispatch_ref,
   [((6, 4), "any"), ((2, 3), "int"), ((6, 2), "int")],
   api="distributed.moe.dispatch_tokens", grad=False,
   dtypes=("float32",))


def _moe_combine_ref(eo, gate_t, ti, gw, iv):
    E, C, m = eo.shape
    flat = eo.reshape(E * C, m)
    sel = flat[np.minimum(iv, E * C - 1)] * (iv < E * C)[..., None]
    return (sel * gate_t[..., None]).sum(1).astype(np.float32)


_S("moe_combine", _moe_combine_ref,
   [((2, 3, 4), "any"), ((6, 2), "prob"), ((2, 3), "int"), ((2, 3), "prob"),
    ((6, 2), "int")],
   api="distributed.moe.combine_tokens", grad=False, dtypes=("float32",))


# ---------------------------------------------------------------------------
# fused conv+BN (pallas_kernels/fused_conv.py). grad=False: the custom
# VJPs reuse _bn_train_bwd + XLA conv vjps and are pinned exactly against
# the unfused composition in tests/test_fused_conv.py; FD through the
# interpret-mode Pallas conv is quadratic in tensor size.
# ---------------------------------------------------------------------------


def _np_conv_nhwc(x, w):
    k, c, kh, kw = w.shape
    pad = (kh - 1) // 2
    xp = np.pad(x.astype(np.float64), ((0, 0), (pad, pad), (pad, pad), (0, 0)))
    n, h, wd, _ = x.shape
    out = np.zeros((n, h, wd, k), np.float64)
    for di in range(kh):
        for dj in range(kw):
            out += xp[:, di:di + h, dj:dj + wd, :] @ w[:, :, di, dj].T.astype(np.float64)
    return out


def _fused_conv_bn_train_ref(x, wc, rm, rv, g, b):
    co = _np_conv_nhwc(x, wc)
    m = co.mean((0, 1, 2))
    v = co.var((0, 1, 2))
    y = (co - m) / np.sqrt(v + 1e-5) * g + b
    return y.astype(np.float32)


def _fused_conv_bn_eval_ref(x, wc, rm, rv, g, b):
    y = (_np_conv_nhwc(x, wc) - rm) / np.sqrt(rv + 1e-5) * g + b
    return y.astype(np.float32)


_FUSED_CONV_TOL = {"float32": (5e-4, 5e-4), "bfloat16": (8e-2, 8e-2)}

_S("fused_conv_bn_train", _fused_conv_bn_train_ref,
   [((2, 4, 4, 8), "any"), ((8, 8, 3, 3), "small"), ((8,), "any"),
    ((8,), "pos"), ((8,), "any"), ((8,), "any")],
   api="nn.functional.fused_conv_bn", grad=False,
   dtypes=("float32", "bfloat16"), tol=_FUSED_CONV_TOL,
   wrap=lambda api: lambda x, wc, rm, rv, g, b: api(
       x, wc, rm, rv, g, b, training=True))

_S("fused_conv_bn_eval", _fused_conv_bn_eval_ref,
   [((2, 4, 4, 8), "any"), ((8, 8, 1, 1), "small"), ((8,), "any"),
    ((8,), "pos"), ((8,), "any"), ((8,), "any")],
   api="nn.functional.fused_conv_bn", grad=False,
   dtypes=("float32", "bfloat16"), tol=_FUSED_CONV_TOL,
   wrap=lambda api: lambda x, wc, rm, rv, g, b: api(
       x, wc, rm, rv, g, b, training=False))

# ---------------------------------------------------------------------------
# quantized serving data path (round 11): int8 KV cache writes/reads and
# the weight-only dequant-fused matmul. All refs replicate the absmax
# convention of quantization/intx.py (q = clip(round(x/s*127)), dequant
# q*s/127) in numpy, so the comparisons pin the convention, not just the
# shapes. grad=False throughout: serving-only forward ops.
# ---------------------------------------------------------------------------

_QDOM_SCALE = "pos"   # absmax scales are positive by construction


def _np_absmax_pack(x):
    amax = np.abs(x).max(axis=-1)
    s = np.maximum(amax, 1e-9)[..., None]
    q = np.clip(np.round(x.astype(np.float32) / s * 127.0),
                -127.0, 127.0).astype(np.int8)
    return q, amax.astype(np.float32)


def _np_absmax_unpack(q, amax):
    s = np.maximum(amax, 1e-9)[..., None]
    return q.astype(np.float32) * s / 127.0


_KVQ_OFF = 3


def _kv_write_quant_ref(buf, sc, new):
    q, amax = _np_absmax_pack(new)
    b = buf.copy()
    s2 = sc.copy()
    b[:, _KVQ_OFF:_KVQ_OFF + new.shape[1]] = q
    s2[:, _KVQ_OFF:_KVQ_OFF + new.shape[1]] = amax
    return b, s2


_S("kv_cache_update_quant", _kv_write_quant_ref,
   [((2, 6, 2, 4), "int8w"), ((2, 6, 2), _QDOM_SCALE),
    ((2, 1, 2, 4), "any")],
   api="generation.kv_cache_write_quant", grad=False, dtypes=("float32",),
   wrap=lambda api: lambda b, s, n: api(b, s, n, _KVQ_OFF))

# paged twin: the [2, 6] logical caches live as pool blocks [7, 2, ...]
# through the same fixed block table the paged attention schemas use
_PKQ_BT = np.array([[1, 2, 3], [4, 5, 6]], np.int32)
_PKQ_POS = np.array([1, 3], np.int32)


def _paged_kv_write_quant_ref(pool, sc, new):
    q, amax = _np_absmax_pack(new)
    p = pool.copy()
    s2 = sc.copy()
    bs = pool.shape[1]
    for b in range(new.shape[0]):
        for j in range(new.shape[1]):
            t = _PKQ_POS[b] + j
            phys = _PKQ_BT[b, t // bs]
            p[phys, t % bs] = q[b, j]
            s2[phys, t % bs] = amax[b, j]
    return p, s2


_S("paged_kv_cache_update_quant", _paged_kv_write_quant_ref,
   [((7, 2, 2, 4), "int8w"), ((7, 2, 2), _QDOM_SCALE),
    ((2, 2, 2, 4), "any")],
   api="generation.paged_kv_cache_write_quant", grad=False,
   dtypes=("float32",),
   wrap=lambda api: lambda p, s, n: api(p, s, n, _PKQ_BT, _PKQ_POS))


def _kv_dequant_ref(buf, sc):
    return _np_absmax_unpack(buf, sc)


_S("kv_cache_dequant", _kv_dequant_ref,
   [((2, 6, 2, 4), "int8w"), ((2, 6, 2), _QDOM_SCALE)],
   api="generation.dequantize_kv_buffer", grad=False, dtypes=("float32",))


def _paged_gather_dequant_ref(pool, sc):
    g = pool[_PKQ_BT.reshape(-1)].reshape(2, 6, *pool.shape[2:])
    gs = sc[_PKQ_BT.reshape(-1)].reshape(2, 6, *sc.shape[2:])
    return _np_absmax_unpack(g, gs)


_S("paged_kv_gather_dequant", _paged_gather_dequant_ref,
   [((7, 2, 2, 4), "int8w"), ((7, 2, 2), _QDOM_SCALE)],
   api="generation.gather_paged_kv_dequant", grad=False,
   dtypes=("float32",),
   wrap=lambda api: lambda p, s: api(p, s, _PKQ_BT))


# weight-only matmul with the dequant fused into the Pallas prologue:
# x [m, k] @ dequant(q [n, k]).T, scale = per-out-channel dequant
# multiplier (nn.quant.weight_quantize convention: absmax/127, so the
# dequantized weight is O(1) — sampling the multiplier at O(1) instead
# would make outputs O(1e3) and void the bf16 tolerance)
from .schemas import _DOMAINS  # noqa: E402

_DOMAINS["qscale"] = lambda rng, sh: (
    rng.uniform(0.5, 2.5, sh) / 127.0).astype(np.float32)


def _quant_matmul_ref(x, q, s):
    return x.astype(np.float32) @ (q.astype(np.float32)
                                   * s[:, None].astype(np.float32)).T


_S("quant_matmul", _quant_matmul_ref,
   [((4, 32), "any"), ((16, 32), "int8w"), ((16,), "qscale")],
   api="pallas_kernels.quant_matmul", grad=False,
   dtypes=("float32", "bfloat16"), tol=_FLASH_TOL)


# quantized flash decode: the SAME attention oracle as the float
# schemas, fed the numpy-dequantized caches — pins that the kernel's
# fused dequant prologue computes exactly what unpack-then-attend does
def _flash_decode_int8_ref(q, kq, vq, ks, vs):
    return _flash_decode_ref(q, _np_absmax_unpack(kq, ks),
                             _np_absmax_unpack(vq, vs))


_S("flash_decode_attention_int8", _flash_decode_int8_ref,
   [((2, 1, 4, 8), "any"), ((2, 6, 2, 8), "int8w"),
    ((2, 6, 2, 8), "int8w"), ((2, 6, 2), _QDOM_SCALE),
    ((2, 6, 2), _QDOM_SCALE)],
   api="pallas_kernels.flash_decode_attention", grad=False,
   dtypes=("float32", "bfloat16"), tol=_FLASH_TOL,
   wrap=lambda api: lambda q, kq, vq, ks, vs: api(
       q, kq, vq, _FD_SWEEP_POS, block_k=4, k_scale=ks, v_scale=vs))


def _paged_flash_decode_int8_ref(q, kp, vp, ksp, vsp):
    gather = lambda p: p[_PFD_BT.reshape(-1)].reshape(
        2, 6, *p.shape[2:])
    return _flash_decode_ref(
        q, _np_absmax_unpack(gather(kp), gather(ksp)),
        _np_absmax_unpack(gather(vp), gather(vsp)))


_S("paged_flash_decode_attention_int8", _paged_flash_decode_int8_ref,
   [((2, 1, 4, 8), "any"), ((7, 2, 2, 8), "int8w"),
    ((7, 2, 2, 8), "int8w"), ((7, 2, 2), _QDOM_SCALE),
    ((7, 2, 2), _QDOM_SCALE)],
   api="pallas_kernels.paged_flash_decode_attention", grad=False,
   dtypes=("float32", "bfloat16"), tol=_FLASH_TOL,
   wrap=lambda api: lambda q, kp, vp, ks, vs: api(
       q, kp, vp, _PFD_BT, _FD_SWEEP_POS, k_scale=ks, v_scale=vs))
